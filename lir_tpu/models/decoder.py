"""Unified functional decoder-only transformer.

One forward covers the whole reference model zoo (SURVEY.md §2.6): GPT-2, the
GPT-NeoX family (pythia / dolly-v2 / stablelm-alpha / RedPajama / h2ogpt),
Llama-2 / Mistral / Qwen / Baichuan2, Falcon (MQA + shared-LN parallel block),
Bloom (ALiBi + embedding LayerNorm) and OPT — selected purely by
``registry.ModelConfig`` knobs. The reference reaches these architectures via
``transformers`` torch classes (analysis/compare_base_vs_instruct.py:423-455);
here they are a single JAX program so XLA can fuse and shard them.

Design (TPU-first):
- Layers are STACKED along a leading axis and iterated with ``lax.scan`` —
  one compiled block body regardless of depth, fast compiles, remat-friendly.
- Params/activations run in the param dtype (bf16 on TPU); softmax and the
  final logits are computed in fp32 (SURVEY.md §7 hard part 3).
- KV-cache prefill/decode split so scoring can capture per-step logits
  (the C13 measurement primitive, compare_base_vs_instruct.py:185-305).
- No data-dependent Python control flow below ``jit``; masks make padding a
  no-op so the whole scoring grid runs at fixed shapes.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .registry import ModelConfig
from .quant import (QuantTensor, dynamic_quant as _quant_kv, matmul as _mm,
                    shared_quant as _shared_quant)

Params = Dict[str, Any]

# Test hook: when True, the flash-attention route also engages on CPU with
# the Pallas interpreter, so the DECODER-LEVEL routing (mask plumbing, ALiBi
# slopes/positions wiring) is testable without a chip. Production leaves
# this False: CPU runs dense.
FLASH_INTERPRET_ON_CPU = False

# Same hook for the fused flash-decode kernel (ops/flash_decode): tier-1
# exercises the decode-step routing under the Pallas interpreter on CPU;
# production CPU runs dense, production TPU runs the kernel compiled
# (cfg.fused_decode, default on; RuntimeConfig.fused_decode opts out).
FUSED_DECODE_INTERPRET_ON_CPU = False

# Same hook for the shared-prefix cascade-prefill kernel
# (ops/cascade_prefill): tier-1 and the cascade smoke run the prefix-leg
# Pallas kernel under the interpreter on CPU; production CPU dispatches
# stay on the dense shared path (the engine's cascade routing checks this
# hook, runner.ScoringEngine.cascade_supported).
CASCADE_INTERPRET_ON_CPU = False


# ---------------------------------------------------------------------------
# Param init (random weights for tests; real weights come from models/loader.py)
# ---------------------------------------------------------------------------

def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.float32) -> Params:
    """Random-normal init with the exact tree layout the loader fills."""
    k = iter(jax.random.split(key, 64))
    D, H, K, hd, F, L = (cfg.hidden_size, cfg.n_heads, cfg.n_kv_heads,
                         cfg.head_dim, cfg.intermediate_size, cfg.n_layers)

    def w(*shape, scale=0.02):
        return (scale * jax.random.normal(next(k), shape)).astype(dtype)

    def norm_p(*lead) -> Params:
        p = {"scale": jnp.ones((*lead, D), dtype)}
        if cfg.norm == "layernorm":
            p["bias"] = jnp.zeros((*lead, D), dtype)
        return p

    layers: Params = {
        "ln1": norm_p(L),
        "wq": w(L, D, H * hd), "wk": w(L, D, K * hd), "wv": w(L, D, K * hd),
        "wo": w(L, H * hd, D),
        "w_up": w(L, D, F), "w_down": w(L, F, D),
    }
    if not cfg.shared_block_ln:
        layers["ln2"] = norm_p(L)
    if cfg.gated_mlp:
        layers["w_gate"] = w(L, D, F)
    if cfg.qkv_bias:
        layers["bq"] = jnp.zeros((L, H * hd), dtype)
        layers["bk"] = jnp.zeros((L, K * hd), dtype)
        layers["bv"] = jnp.zeros((L, K * hd), dtype)
    if cfg.attn_out_bias:
        layers["bo"] = jnp.zeros((L, D), dtype)
    if cfg.mlp_bias:
        layers["b_up"] = jnp.zeros((L, F), dtype)
        layers["b_down"] = jnp.zeros((L, D), dtype)

    params: Params = {"tok_embed": w(cfg.vocab_size, D, scale=0.02), "layers": layers}
    if cfg.pos_embedding == "learned":
        params["pos_embed"] = w(cfg.max_seq_len + cfg.learned_pos_offset, D)
    if cfg.embedding_norm:
        params["embed_ln"] = {"scale": jnp.ones((D,), dtype),
                              "bias": jnp.zeros((D,), dtype)}
    if cfg.final_norm:
        params["final_ln"] = norm_p()
    if not cfg.tie_embeddings:
        params["lm_head"] = w(D, cfg.vocab_size)
    return params


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------

def _norm(x: jax.Array, p: Params, cfg: ModelConfig) -> jax.Array:
    xf = x.astype(jnp.float32)
    if cfg.norm == "rmsnorm":
        xf = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + cfg.norm_eps)
        return (xf * p["scale"].astype(jnp.float32)).astype(x.dtype)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    xf = (xf - mu) * jax.lax.rsqrt(var + cfg.norm_eps)
    out = xf * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return out.astype(x.dtype)


def _act(x: jax.Array, kind: str) -> jax.Array:
    if kind == "silu":
        return jax.nn.silu(x)
    if kind == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if kind == "gelu_new":
        return jax.nn.gelu(x, approximate=True)
    return jax.nn.relu(x)


def _rope_sincos(positions: jax.Array, rotary_dim: int, theta: float) -> Tuple[jax.Array, jax.Array]:
    """sin/cos tables for rotate-half RoPE. positions: (..., S) int."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, rotary_dim, 2, dtype=jnp.float32) / rotary_dim))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # (..., S, rd/2)
    return jnp.sin(angles), jnp.cos(angles)


def _apply_rope(x: jax.Array, sin: jax.Array, cos: jax.Array, rotary_dim: int) -> jax.Array:
    """x: (B, S, nH, hd); rotate-half convention (HF llama/neox/falcon)."""
    rot, rest = x[..., :rotary_dim], x[..., rotary_dim:]
    x1, x2 = rot[..., : rotary_dim // 2], rot[..., rotary_dim // 2:]
    sin = sin[:, :, None, :].astype(x.dtype)   # (B, S, 1, rd/2)
    cos = cos[:, :, None, :].astype(x.dtype)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return jnp.concatenate([out, rest], axis=-1) if rest.shape[-1] else out


def alibi_slopes(n_heads: int) -> jax.Array:
    """ALiBi per-head slopes (bloom). Matches HF build_alibi_tensor."""
    closest = 2 ** math.floor(math.log2(n_heads))
    base = 2.0 ** (-(2.0 ** -(math.log2(closest) - 3)))
    slopes = [base ** (i + 1) for i in range(closest)]
    if closest != n_heads:
        extra_base = 2.0 ** (-(2.0 ** -(math.log2(2 * closest) - 3)))
        slopes += [extra_base ** (2 * i + 1) for i in range(n_heads - closest)]
    return jnp.asarray(slopes, dtype=jnp.float32)


def _attention(q: jax.Array, k: jax.Array, v: jax.Array, bias: jax.Array,
               cfg: ModelConfig,
               key_mask: Optional[jax.Array] = None) -> jax.Array:
    """q: (B,S,H,hd); k,v: (B,T,K,hd); bias: (B,H|1,S,T) additive fp32.

    With ``cfg.use_flash_attention``, full-sequence self-attention (the
    prefill) routes through the Pallas flash kernel, masking keys with the
    batch's actual attention mask (any padding pattern); ALiBi families
    (bloom) pass their per-head slopes + mask-aware key positions into the
    kernel. Decode steps keep the dense path ON PURPOSE: a decode query is
    one position, so its score row is (B, H, 1, T) — already O(T) memory
    with no (S, T) tile to avoid; a flash kernel would only add launch
    overhead per step. Non-block-divisible lengths also fall back dense."""
    B, S, H, hd = q.shape
    K = k.shape[2]
    G = H // K

    from ..ops.flash_attention import (
        DEFAULT_BLOCK_K, DEFAULT_BLOCK_Q, flash_attention,
    )

    block = max(DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
    flash_ok = (
        cfg.use_flash_attention
        and key_mask is not None
        and k.shape[1] == S
        # Blocks shrink to S when S <= block, so every power-of-two bucket
        # (64..1024) qualifies; only ragged lengths fall back dense.
        and (S % block == 0 or S <= block)
        # Pallas lowers on TPU only; CPU (tests, virtual meshes) runs dense
        # unless the interpreter test hook is on.
        and (jax.default_backend() == "tpu" or FLASH_INTERPRET_ON_CPU)
    )
    if flash_ok:
        if K != H:  # the Pallas kernel wants per-query-head k/v
            k = jnp.repeat(k, G, axis=2)
            v = jnp.repeat(v, G, axis=2)
        interpret = (FLASH_INTERPRET_ON_CPU
                     and jax.default_backend() != "tpu")
        if cfg.pos_embedding == "alibi":
            out = flash_attention(
                q, k, v, causal=True, key_mask=key_mask,
                alibi_slopes=alibi_slopes(cfg.n_heads),
                key_positions=mask_positions(key_mask),
                interpret=interpret)
        else:
            out = flash_attention(q, k, v, causal=True, key_mask=key_mask,
                                  interpret=interpret)
        return out.reshape(B, S, H * hd)

    # GQA/MQA contracts GROUPED query heads against the UN-REPEATED k/v
    # (same h = k*G + g convention as _attention_cached): repeating k/v to
    # H heads would materialize an H/K-times copy inside every layer of
    # the prefill scan — ~600 MB transient per layer for falcon's 71:1
    # MQA at batch 32 / seq 1024.
    T = k.shape[1]
    qg = q.reshape(B, S, K, G, hd)
    scores = jnp.einsum("bskgd,btkd->bkgst", qg, k).astype(jnp.float32)
    scores = scores.reshape(B, H, S, T) / math.sqrt(hd) + bias
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    pg = probs.reshape(B, K, G, S, T)
    out = jnp.einsum("bkgst,btkd->bskgd", pg, v)
    return out.reshape(B, S, H * hd)


def _attention_cached_int8(q: jax.Array, kq, ks, vq, vs,
                           bias: jax.Array, cfg: ModelConfig) -> jax.Array:
    """Decode-step attention over the int8 cache (payload (K, T, B, hd) +
    scales (K, T, B)). All dots run s8 x s8 -> s32 on the MXU: the query
    and the value-scale-folded probabilities are quantized dynamically
    per vector, so neither a bf16 copy of the cache nor one of the weights
    ever materializes. Softmax stays fp32.

    GQA/MQA contracts GROUPED query heads against the un-repeated cache
    (q reshaped to (B, S, K, G, hd)) — repeating the cache K -> H would
    materialize an H/K-times copy of the whole cache inside the decode
    loop, giving back the HBM the int8 cache exists to save.
    """
    B, S, H, hd = q.shape
    K = kq.shape[0]
    G = H // K
    qq, qs = _quant_kv(q)                                   # (B,S,H,hd),(B,S,H)
    qq = qq.reshape(B, S, K, G, hd)
    s32 = jnp.einsum("bskgd,ktbd->bkgst", qq, kq,
                     preferred_element_type=jnp.int32)
    scores = (s32.astype(jnp.float32).reshape(B, H, S, -1)
              * qs.transpose(0, 2, 1)[:, :, :, None]        # (B,H,S,1)
              * jnp.repeat(ks.transpose(2, 0, 1), G, axis=1)[:, :, None, :])
    scores = scores / math.sqrt(hd) + bias
    probs = jax.nn.softmax(scores, axis=-1)                 # fp32 (B,H,S,T)
    # Fold v scales in, then dynamically quantize the weighted probs.
    pw = probs * jnp.repeat(vs.transpose(2, 0, 1), G, axis=1)[:, :, None, :]
    pq, ps = _quant_kv(pw)                                  # (B,H,S,T),(B,H,S)
    pq = pq.reshape(B, K, G, S, -1)
    o32 = jnp.einsum("bkgst,ktbd->bskgd", pq, vq,
                     preferred_element_type=jnp.int32)
    out = (o32.astype(jnp.float32).reshape(B, S, H, hd)
           * ps.transpose(0, 2, 1)[..., None])
    return out.astype(q.dtype).reshape(B, S, H * hd)


def _fused_decode_ok(cfg: ModelConfig, S: int, fused_ctx) -> bool:
    """Static routing decision for the fused flash-decode kernel: a single-
    query decode step, a non-int8 cache, the flag on, and a backend that
    lowers Pallas (TPU; CPU only under the interpreter test hook)."""
    return (cfg.fused_decode
            and not cfg.kv_cache_int8
            and fused_ctx is not None
            and S == 1
            and (jax.default_backend() == "tpu"
                 or FUSED_DECODE_INTERPRET_ON_CPU))


def _attention_cached_flash(q: jax.Array, k: jax.Array, v: jax.Array,
                            cfg: ModelConfig, fused_ctx,
                            trunk_len: int = 0) -> jax.Array:
    """Decode-step attention through the fused Pallas flash-decode kernel
    (ops/flash_decode): the (B, H, 1, T) score row, fp32 softmax, and
    probability row stay in VMEM instead of round-tripping HBM between
    three XLA kernels. Same cache layout (K, T, B, hd), same GQA/MQA
    grouped contraction against the un-repeated cache, same masking
    semantics as :func:`_attention_cached` (pinned by tests/
    test_kernels.py); ALiBi rides per-head slopes + mask-aware key
    positions exactly like the prefill flash kernel.

    ``trunk_len`` > 0 (a shared-trunk dispatch with cascade decode on)
    routes through the trunk-aware variant: the cache's leading
    ``trunk_len`` slots are identical across rows, so the trunk splits
    read K/V from cache row 0 ONCE per kv head for all rows' queries —
    bitwise the flat kernel (the split ladder, per-split arithmetic and
    merge are unchanged; only the trunk tiles' HBM reads dedup)."""
    from ..ops.flash_decode import flash_decode, flash_decode_trunk

    B, S, H, hd = q.shape
    q_pos, key_mask, key_positions = fused_ctx
    interpret = (FUSED_DECODE_INTERPRET_ON_CPU
                 and jax.default_backend() != "tpu")
    slopes = (alibi_slopes(cfg.n_heads) if cfg.pos_embedding == "alibi"
              else None)
    if trunk_len > 0:
        out = flash_decode_trunk(q[:, 0], k, v, q_pos, key_mask,
                                 key_positions=key_positions,
                                 alibi_slopes=slopes, trunk_len=trunk_len,
                                 interpret=interpret)
    else:
        out = flash_decode(q[:, 0], k, v, q_pos, key_mask,
                           key_positions=key_positions, alibi_slopes=slopes,
                           interpret=interpret)
    return out.reshape(B, S, H * hd)


def _fused_decode_mq_ok(cfg: ModelConfig, S: int, fused_ctx) -> bool:
    """Static routing decision for the MULTI-QUERY fused decode kernel
    (the speculative verify window): same gates as :func:`_fused_decode_ok`
    but for a window of S > 1 teacher-forced queries carrying per-query
    positions (fused_ctx positions shaped (B, S))."""
    return (cfg.fused_decode
            and not cfg.kv_cache_int8
            and fused_ctx is not None
            and S > 1
            and getattr(fused_ctx[0], "ndim", 1) == 2
            and (jax.default_backend() == "tpu"
                 or FUSED_DECODE_INTERPRET_ON_CPU))


def _attention_cached_flash_mq(q: jax.Array, k: jax.Array, v: jax.Array,
                               cfg: ModelConfig, fused_ctx,
                               trunk_len: int = 0) -> jax.Array:
    """Verify-window attention through the multi-query fused kernel
    (ops/flash_decode.flash_decode_mq): S teacher-forced queries per row
    attend over the cache (the window's own k/v already written) in one
    launch, each query's reduction bitwise the single-query kernel's —
    the speculative verify path's decode-step parity contract.
    ``trunk_len`` > 0 routes the trunk-aware sibling so PR-13
    speculative verify windows ride the trunk-split dedup too (see
    :func:`_attention_cached_flash`)."""
    from ..ops.flash_decode import flash_decode_mq, flash_decode_mq_trunk

    B, S, H, hd = q.shape
    q_pos, key_mask, key_positions = fused_ctx
    interpret = (FUSED_DECODE_INTERPRET_ON_CPU
                 and jax.default_backend() != "tpu")
    slopes = (alibi_slopes(cfg.n_heads) if cfg.pos_embedding == "alibi"
              else None)
    if trunk_len > 0:
        out = flash_decode_mq_trunk(q, k, v, q_pos, key_mask,
                                    key_positions=key_positions,
                                    alibi_slopes=slopes,
                                    trunk_len=trunk_len,
                                    interpret=interpret)
    else:
        out = flash_decode_mq(q, k, v, q_pos, key_mask,
                              key_positions=key_positions,
                              alibi_slopes=slopes, interpret=interpret)
    return out.reshape(B, S, H * hd)


def _attention_cascade(q: jax.Array, k: jax.Array, v: jax.Array,
                       trunk_kv: Tuple[jax.Array, jax.Array],
                       suffix_mask: jax.Array, q_positions: jax.Array,
                       cfg: ModelConfig, int8_qk: bool) -> jax.Array:
    """Cascade-aware sibling of :func:`_attention_cached` for the
    shared-trunk PREFILL window (ops/cascade_prefill): the dispatch's
    remainder queries split into a prefix leg over the single-row shared
    trunk KV (one inter-query-batched dense matmul per kv head, int8
    QK^T optional) and a per-row causal suffix leg over the window's own
    k/v, merged by the flash split-K log-sum-exp rule (ops/lse). Same
    grouped GQA contraction against un-repeated k/v, same ALiBi
    key-position convention as every other attention route here. q:
    (B, R, H, hd); k/v: (B, R, K, hd) post-RoPE window k/v; trunk_kv:
    (K, Tt, hd) pair."""
    from ..ops.cascade_prefill import cascade_attention

    B, R, H, hd = q.shape
    interpret = jax.default_backend() != "tpu"
    slopes = (alibi_slopes(cfg.n_heads) if cfg.pos_embedding == "alibi"
              else None)
    tk, tv = trunk_kv
    out = cascade_attention(q, k, v, tk, tv, suffix_mask, q_positions,
                            alibi_slopes=slopes, int8_qk=int8_qk,
                            interpret=interpret,
                            fused_suffix=cfg.cascade_fused_suffix)
    return out.reshape(B, R, H * hd)


def _attention_cached(q: jax.Array, k: jax.Array, v: jax.Array,
                      bias: jax.Array, cfg: ModelConfig) -> jax.Array:
    """Decode-step attention over the CACHE layout (K, T, B, hd).

    The cache is stored head-major/batch-minor on purpose: it is the
    layout XLA's decode while-loop prefers for these dots, so the loop
    carry aliases the prefill output instead of inserting two full-cache
    layout copies (measured 2x 2.08 GiB at 7B batch 32 — the difference
    between fitting a chip and OOM; see SCALE.md). q: (B, S=1, H, hd).
    GQA/MQA contracts grouped query heads against the un-repeated cache
    (see _attention_cached_int8).
    """
    B, S, H, hd = q.shape
    K = k.shape[0]
    G = H // K
    qg = q.reshape(B, S, K, G, hd)
    scores = jnp.einsum("bskgd,ktbd->bkgst", qg, k).astype(jnp.float32)
    scores = scores.reshape(B, H, S, -1) / math.sqrt(hd) + bias
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    pg = probs.reshape(B, K, G, S, -1)
    out = jnp.einsum("bkgst,ktbd->bskgd", pg, v)
    return out.reshape(B, S, H * hd)


def _block(x: jax.Array, lp: Params, cfg: ModelConfig, sin, cos,
           bias: jax.Array, cache_kv: Optional[Tuple[jax.Array, jax.Array]],
           cache_index: Optional[jax.Array],
           key_mask: Optional[jax.Array] = None,
           attn_impl=None, fused_ctx=None, trunk_len: int = 0):
    """One transformer block. Returns (new_x, (k_full, v_full)).

    ``attn_impl(q, k, v, key_mask) -> (B, S, H*hd)`` replaces dense
    attention when given (the sequence-parallel path, parallel/seq_forward);
    it owns causality/ALiBi itself, so ``bias`` may be None then.
    ``fused_ctx`` — a (query positions (B,), cache mask (B, T), cache
    key positions (B, T)) triple — arms the fused flash-decode route for
    single-query cache steps (:func:`_fused_decode_ok`); the dense path
    and its ``bias`` remain the fallback on every other shape/backend.
    ``trunk_len`` (static) marks the cache's leading shared-trunk slots
    for the trunk-aware fused decode kernels (cascade decode) — 0 on
    every non-shared dispatch and whenever the fused route is off.
    """
    B, S, _ = x.shape
    H, K, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    h_attn_in = _norm(x, lp["ln1"], cfg)
    # Dynamic-int8 trees quantize the attention input ONCE for the whole
    # q/k/v triple (quant.shared_quant) — bit-identical to per-matrix
    # quantization, two fewer VPU amax/round passes per block.
    h_qkv = _shared_quant(h_attn_in, lp["wq"], lp["wk"], lp["wv"])
    q = _mm(h_qkv, lp["wq"])
    k = _mm(h_qkv, lp["wk"])
    v = _mm(h_qkv, lp["wv"])
    if cfg.qkv_bias:
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    q = q.reshape(B, S, H, hd)
    k = k.reshape(B, S, K, hd)
    v = v.reshape(B, S, K, hd)
    if cfg.pos_embedding == "rotary":
        rd = cfg.rotary_dim
        q = _apply_rope(q, sin, cos, rd)
        k = _apply_rope(k, sin, cos, rd)

    if cache_kv is not None:
        # Decode: insert this step's k/v at cache_index, attend over the
        # full cache. Cache layout is (K, T, B, hd) — see _attention_cached.
        ck, cv = cache_kv
        k_t = k.transpose(2, 1, 0, 3)  # (B, 1, K, hd) -> (K, 1, B, hd)
        v_t = v.transpose(2, 1, 0, 3)
        if cfg.kv_cache_int8:
            (ckq, cks), (cvq, cvs) = ck, cv
            k_q, k_s = _quant_kv(k_t)
            v_q, v_s = _quant_kv(v_t)
            ckq = lax.dynamic_update_slice(ckq, k_q, (0, cache_index, 0, 0))
            cks = lax.dynamic_update_slice(cks, k_s, (0, cache_index, 0))
            cvq = lax.dynamic_update_slice(cvq, v_q, (0, cache_index, 0, 0))
            cvs = lax.dynamic_update_slice(cvs, v_s, (0, cache_index, 0))
            ck, cv = (ckq, cks), (cvq, cvs)
            attn = _attention_cached_int8(q, ckq, cks, cvq, cvs, bias, cfg)
        else:
            ck = lax.dynamic_update_slice(ck, k_t.astype(ck.dtype),
                                          (0, cache_index, 0, 0))
            cv = lax.dynamic_update_slice(cv, v_t.astype(cv.dtype),
                                          (0, cache_index, 0, 0))
            if _fused_decode_ok(cfg, S, fused_ctx):
                attn = _attention_cached_flash(q, ck, cv, cfg, fused_ctx,
                                               trunk_len=trunk_len)
            elif _fused_decode_mq_ok(cfg, S, fused_ctx):
                attn = _attention_cached_flash_mq(q, ck, cv, cfg, fused_ctx,
                                                  trunk_len=trunk_len)
            else:
                attn = _attention_cached(q, ck, cv, bias, cfg)
    elif attn_impl is not None:
        # Prefill/forward: hand back this layer's (post-rope) k/v so prefill
        # can fill the cache without re-projecting them.
        ck, cv = k, v
        attn = attn_impl(q, k, v, key_mask)
    else:
        ck, cv = k, v
        attn = _attention(q, k, v, bias, cfg, key_mask=key_mask)
    attn = _mm(attn, lp["wo"])
    if cfg.attn_out_bias:
        attn = attn + lp["bo"]

    if cfg.parallel_block:
        mlp_in = h_attn_in if cfg.shared_block_ln else _norm(x, lp["ln2"], cfg)
    else:
        x = x + attn
        mlp_in = _norm(x, lp["ln2"], cfg)

    # Gated MLPs share one quantized copy of mlp_in across w_up/w_gate.
    mlp_q = (_shared_quant(mlp_in, lp["w_up"], lp["w_gate"])
             if cfg.gated_mlp else mlp_in)
    up = _mm(mlp_q, lp["w_up"])
    if cfg.mlp_bias:
        up = up + lp["b_up"]
    if cfg.gated_mlp:
        gate = _mm(mlp_q, lp["w_gate"])
        hidden = _act(gate, cfg.activation) * up
    else:
        hidden = _act(up, cfg.activation)
    mlp = _mm(hidden, lp["w_down"])
    if cfg.mlp_bias:
        mlp = mlp + lp["b_down"]

    out = x + attn + mlp if cfg.parallel_block else x + mlp
    return out, (ck, cv)


def _embed(params: Params, cfg: ModelConfig, tokens: jax.Array,
           positions: jax.Array) -> jax.Array:
    x = jnp.take(params["tok_embed"], tokens, axis=0)
    if cfg.pos_embedding == "learned":
        # mode="clip": an out-of-table position reuses the last row instead
        # of jnp.take's default NaN fill silently poisoning every logit.
        # The engine additionally refuses buckets that could overflow the
        # table (runner.ScoringEngine), so this is defense in depth.
        x = x + jnp.take(params["pos_embed"],
                         positions + cfg.learned_pos_offset, axis=0,
                         mode="clip")
    if cfg.embedding_norm:
        ln = {"scale": params["embed_ln"]["scale"], "bias": params["embed_ln"]["bias"]}
        x = _norm(x, ln, dataclasses.replace(cfg, norm="layernorm"))
    return x


def _unembed(params: Params, cfg: ModelConfig, x: jax.Array) -> jax.Array:
    if cfg.final_norm:
        x = _norm(x, params["final_ln"], cfg)
    head = params["tok_embed"].T if cfg.tie_embeddings else params["lm_head"]
    if isinstance(head, QuantTensor):
        logits = _mm(x.astype(jnp.float32), head)
    else:
        logits = jnp.einsum("bsd,dv->bsv", x.astype(jnp.float32),
                            head.astype(jnp.float32))
    if cfg.logit_softcap:
        logits = cfg.logit_softcap * jnp.tanh(logits / cfg.logit_softcap)
    return logits


def _causal_bias(attn_mask: jax.Array, positions: jax.Array, cfg: ModelConfig,
                 key_positions: Optional[jax.Array] = None,
                 key_mask: Optional[jax.Array] = None) -> jax.Array:
    """Additive fp32 attention bias (B, H|1, S, T).

    ``positions`` are mask-aware indices (pads get 0). Causality compares
    positions, so left-padded batches behave exactly like unpadded prompts.
    """
    if key_positions is None:
        key_positions, key_mask = positions, attn_mask
    neg = jnp.float32(-1e9)
    qp = positions[:, :, None]           # (B, S, 1)
    kp = key_positions[:, None, :]       # (B, 1, T)
    allowed = (kp <= qp) & (key_mask[:, None, :] > 0)
    bias = jnp.where(allowed, 0.0, neg)[:, None, :, :]  # (B, 1, S, T)
    if cfg.pos_embedding == "alibi":
        slopes = alibi_slopes(cfg.n_heads)  # (H,)
        alibi = slopes[None, :, None, None] * kp.astype(jnp.float32)[:, None, :, :]
        bias = bias + alibi
    return bias


def mask_positions(attn_mask: jax.Array) -> jax.Array:
    """Mask-aware position ids: pads -> 0, tokens -> 0..n-1 (left-pad safe)."""
    return jnp.maximum(jnp.cumsum(attn_mask, axis=-1) - 1, 0)


# ---------------------------------------------------------------------------
# Public forwards
# ---------------------------------------------------------------------------

def _scan_blocks(params: Params, cfg: ModelConfig, x, sin, cos, bias,
                 cache=None, cache_index=None, key_mask=None, attn_impl=None,
                 fused_ctx=None, trunk_len: int = 0):
    """lax.scan over the stacked layer params."""
    def body(carry, xs):
        h = carry
        if cache is None:
            lp = xs
            h, _ = _block(h, lp, cfg, sin, cos, bias, None, None,
                          key_mask=key_mask, attn_impl=attn_impl)
            return h, None
        lp, (ck, cv) = xs
        h, (nk, nv) = _block(h, lp, cfg, sin, cos, bias, (ck, cv),
                             cache_index, fused_ctx=fused_ctx,
                             trunk_len=trunk_len)
        return h, (nk, nv)

    xs = params["layers"] if cache is None else (params["layers"], cache)
    x, new_cache = lax.scan(body, x, xs)
    return x, new_cache


def forward(params: Params, cfg: ModelConfig, tokens: jax.Array,
            attn_mask: Optional[jax.Array] = None,
            attn_impl=None) -> jax.Array:
    """Full-sequence causal forward. tokens: (B, S) int32 -> fp32 logits (B,S,V).

    ``attn_impl`` (see _block) swaps in a sequence-parallel attention; the
    O(S*T) bias tensor is then never materialized — required for
    long-context prefill, where (S, T) would not fit.
    """
    if attn_mask is None:
        attn_mask = jnp.ones_like(tokens)
    positions = mask_positions(attn_mask)
    x = _embed(params, cfg, tokens, positions)
    sin = cos = None
    if cfg.pos_embedding == "rotary":
        sin, cos = _rope_sincos(positions, cfg.rotary_dim, cfg.rope_theta)
    bias = None if attn_impl is not None else _causal_bias(attn_mask, positions, cfg)
    x, _ = _scan_blocks(params, cfg, x, sin, cos, bias, key_mask=attn_mask,
                        attn_impl=attn_impl)
    return _unembed(params, cfg, x)


def init_cache(cfg: ModelConfig, batch: int, max_len: int, dtype=jnp.float32):
    """Per-layer KV cache stacked on the layer axis: (L, K, T, B, hd) pair.

    Head-major/batch-minor on purpose: this is the physical order XLA's
    decode while-loop assigns to the cache anyway; storing it logically
    row-major in that order lets the loop carry alias the prefill output
    instead of copying the whole cache (see _attention_cached).

    With ``cfg.kv_cache_int8`` each side becomes a (payload int8
    (L, K, T, B, hd), scale f32 (L, K, T, B)) pair — half the HBM.
    """
    shape = (cfg.n_layers, cfg.n_kv_heads, max_len, batch, cfg.head_dim)
    if cfg.kv_cache_int8:
        def side():
            return (jnp.zeros(shape, jnp.int8),
                    jnp.zeros(shape[:-1], jnp.float32))
        return (side(), side())
    return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


def prefill(params: Params, cfg: ModelConfig, tokens: jax.Array,
            attn_mask: jax.Array, max_len: int, attn_impl=None):
    """Run the prompt, fill the KV cache, return last-position logits.

    tokens/attn_mask: (B, S) with LEFT padding (so position S-1 is the prompt
    end for every row — mirrors the reference's unpadded single-prompt calls).
    Returns (logits_last (B, V) fp32, cache, next_positions (B,)).

    Masked padding is a positional no-op, so RIGHT-padded callers (the
    shared-prefix paths' canonical slot == position layout,
    engine/generate.py) are equally valid — they must simply ignore the
    returned logits/next_positions, which read slot S-1 (a pad there).

    ``attn_impl`` routes the prompt pass through sequence-parallel attention
    (parallel/seq_forward): the quadratic phase runs seq-sharded, and the
    returned cache holds the same per-layer k/v for ordinary decode.
    """
    B, S = tokens.shape
    positions = mask_positions(attn_mask)
    x = _embed(params, cfg, tokens, positions)
    sin = cos = None
    if cfg.pos_embedding == "rotary":
        sin, cos = _rope_sincos(positions, cfg.rotary_dim, cfg.rope_theta)
    bias = None if attn_impl is not None else _causal_bias(attn_mask, positions, cfg)

    # Scan layers, capturing each block's (post-rope) k/v — returned by
    # _block itself, no re-projection — into a (L, ...) stack. Each layer's
    # k/v is transposed to the cache layout (K, S, B, hd) and padded to
    # max_len INSIDE the body: the scan's output stacking then allocates
    # the cache at its final (L, K, T, B, hd) size directly, in the layout
    # the decode loop consumes. Stacking first and padding/transposing the
    # (L, ...) tensor afterwards would materialize the whole cache twice —
    # exactly what used to OOM a 7B at batch 32 / seq 1024 on one chip.
    pad = max_len - S
    pad_spec = ((0, 0), (0, pad), (0, 0), (0, 0))

    def body(h, lp):
        h_out, (k, v) = _block(h, lp, cfg, sin, cos, bias, None, None,
                               key_mask=attn_mask, attn_impl=attn_impl)
        k = k.transpose(2, 1, 0, 3)  # (B, S, K, hd) -> (K, S, B, hd)
        v = v.transpose(2, 1, 0, 3)
        if cfg.kv_cache_int8:
            def side(x):
                xq, xs = _quant_kv(x)
                return (jnp.pad(xq, pad_spec), jnp.pad(xs, pad_spec[:-1]))
            return h_out, (side(k), side(v))
        return h_out, (jnp.pad(k, pad_spec), jnp.pad(v, pad_spec))

    x, (ck, cv) = lax.scan(body, x, params["layers"])
    logits = _unembed(params, cfg, x[:, -1:, :])[:, 0, :]
    next_positions = positions[:, -1] + 1
    return logits, (ck, cv), next_positions


def extend(params: Params, cfg: ModelConfig, cache, suffix_tokens: jax.Array,
           suffix_mask: jax.Array, cache_mask: jax.Array, start_index: int):
    """Teacher-forced multi-token cache extension (chunked prefill).

    Runs ``suffix_tokens`` (B, S2), RIGHT-padded, through the layers in ONE
    forward pass, attending over the already-filled cache plus the suffix
    itself, and inserts the suffix k/v at cache slots
    [start_index, start_index + S2). This is how the perturbation sweep
    shares one prefill between the binary and confidence formats: the long
    rephrased text is prefilled once, then each short format suffix is
    extended here at ~S2/S of the prefill cost (the reference pays two full
    forward passes per cell, perturb_prompts.py:551-726).

    cache_mask: (B, T) validity over the FULL cache, already including the
    suffix slots (pads 0). Pad-slot k/v values are garbage but carry mask 0,
    so attention never sees them. Returns (last-valid-position logits
    (B, V) fp32, new_cache, next_positions (B,)).
    """
    B, S2 = suffix_tokens.shape
    key_positions = mask_positions(cache_mask)
    qpos = lax.dynamic_slice_in_dim(key_positions, start_index, S2, axis=1)
    x = _embed(params, cfg, suffix_tokens, qpos)
    sin = cos = None
    if cfg.pos_embedding == "rotary":
        sin, cos = _rope_sincos(qpos, cfg.rotary_dim, cfg.rope_theta)
    bias = _causal_bias(suffix_mask, qpos, cfg,
                        key_positions=key_positions, key_mask=cache_mask)
    x, new_cache = _scan_blocks(params, cfg, x, sin, cos, bias,
                                cache=cache, cache_index=start_index)
    # Per-row last REAL suffix position (right padding varies by row).
    last = jnp.maximum(jnp.sum(suffix_mask, axis=-1) - 1, 0)      # (B,)
    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)  # (B, 1, D)
    logits = _unembed(params, cfg, x_last)[:, 0, :]
    next_positions = jnp.take_along_axis(qpos, last[:, None], axis=1)[:, 0] + 1
    return logits, new_cache, next_positions


def cascade_extend(params: Params, cfg: ModelConfig, trunk_cache,
                   rem_tokens: jax.Array, rem_mask: jax.Array,
                   trunk_len: int, total_len: int, int8_qk: bool = False):
    """Shared-trunk cascade prefill: build a B-row cache from ONE trunk.

    The dense shared path (:func:`prefill` in generate.greedy_decode_
    fused_shared) recomputes the trunk's quadratic attention once per
    row even when every row shares it. Here the trunk KV is computed (or
    page-pool-gathered) ONCE at batch 1 — ``trunk_cache`` is a
    (L, K, Tt, 1, hd) pair, every slot real, slot == position — and only
    each row's remainder ``rem_tokens``/``rem_mask`` (B, R),
    RIGHT-padded (slot trunk_len + r == position, the canonical layout),
    runs through the layers, attending via the cascade split
    (:func:`_attention_cascade`): prefix leg against this layer's trunk
    KV + causal suffix leg over the window, merged exactly. The returned
    cache broadcasts the trunk KV across rows at slots [0, trunk_len),
    writes the remainder k/v at [trunk_len, trunk_len + R), and
    zero-pads to ``total_len`` — the drop-in analogue of ``prefill``'s
    cache output for a shared-trunk dispatch (no logits: the shared
    paths discard the prefill logits anyway and read branch logits from
    the suffix extensions). Requires a non-int8 KV cache (the engine
    gates routing, runner.cascade_supported).
    """
    assert not cfg.kv_cache_int8, "cascade prefill needs a float KV cache"
    B, R = rem_tokens.shape
    L, K, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
    qpos = trunk_len + mask_positions(rem_mask)                  # (B, R)
    x = _embed(params, cfg, rem_tokens, qpos)
    sin = cos = None
    if cfg.pos_embedding == "rotary":
        sin, cos = _rope_sincos(qpos, cfg.rotary_dim, cfg.rope_theta)
    tck, tcv = trunk_cache                                # (L, K, Tt, 1, hd)

    def body(h, xs):
        lp, (tk, tv) = xs

        def impl(q, k, v, key_mask):
            return _attention_cascade(q, k, v,
                                      (tk[:, :, 0, :], tv[:, :, 0, :]),
                                      rem_mask, qpos, cfg, int8_qk)

        h, (k, v) = _block(h, lp, cfg, sin, cos, None, None, None,
                           key_mask=rem_mask, attn_impl=impl)
        return h, (k, v)

    _, (rk, rv) = lax.scan(body, x, (params["layers"], (tck, tcv)))

    # Assemble the B-row cache in the (L, K, T, B, hd) layout: the trunk
    # side broadcasts across rows (identical KV by construction — the
    # dedup the cascade exists for), the remainder transposes in, the
    # tail zero-pads exactly as prefill pads.
    pad = total_len - trunk_len - R

    def side(trunk, win):
        t = jnp.broadcast_to(trunk, (L, K, trunk_len, B, hd))
        w = win.transpose(0, 3, 2, 1, 4).astype(trunk.dtype)  # (L,K,R,B,hd)
        z = jnp.zeros((L, K, pad, B, hd), trunk.dtype)
        return jnp.concatenate([t, w, z], axis=2)

    return side(tck, rk), side(tcv, rv)


def verify_extend(params: Params, cfg: ModelConfig, cache,
                  chunk_tokens: jax.Array, cache_mask: jax.Array,
                  start_index: jax.Array, trunk_len: int = 0):
    """Teacher-forced VERIFY window (speculative decode): run the S-token
    draft window [current emission, drafts...] through the layers in one
    forward, writing its k/v at cache slots [start_index, start_index+S)
    and returning the logits at EVERY window position — the multi-query
    sibling of :func:`decode_step` that checks S sequential-scan steps in
    one dispatch.

    Every window row is real (teacher forcing; acceptance is decided by
    the caller from the returned logits), so the query mask is all-ones;
    ``cache_mask`` is the FULL cache validity with the window's S slots
    already set (rejected slots of earlier windows stay 0 — masked
    garbage, exactly the early-stop discipline). Positions derive from
    the mask's cumsum, so each query sits at its row's next logical
    position: the attention reduction runs over the same valid
    (token, position) set in the same slot order as the sequential
    decode_step, masked slots contributing exact zeros (the paged-path
    argument), and the fused route goes through the multi-query flash
    kernel whose per-query ops are the single-query kernel's
    (ops/flash_decode.flash_decode_mq). Results are argmax/top-k
    identical to the sequential step and logits-equal within float
    tolerance (the window cache is longer — T*spec_k decode slots — so
    XLA may group the reduction's masked-zero lanes differently; the
    same bar PR-7's fused-vs-dense kernels cleared), which is what the
    speculative tail needs: every CONSUMED readout (position-0 floats,
    the emitted token stream) stays bitwise.

    ``trunk_len`` (static) routes the window through the trunk-aware
    multi-query kernel on shared-trunk dispatches (cascade decode, gated
    by ``cfg.cascade_decode``): the verify window's trunk splits compute
    once per kv head for every row's queries, bitwise the flat kernel.

    Returns (logits (B, S, V) fp32, new_cache)."""
    B, S2 = chunk_tokens.shape
    key_positions = mask_positions(cache_mask)
    qpos = lax.dynamic_slice_in_dim(key_positions, start_index, S2, axis=1)
    x = _embed(params, cfg, chunk_tokens, qpos)
    sin = cos = None
    if cfg.pos_embedding == "rotary":
        sin, cos = _rope_sincos(qpos, cfg.rotary_dim, cfg.rope_theta)
    ones = jnp.ones((B, S2), jnp.int32)
    bias = _causal_bias(ones, qpos, cfg,
                        key_positions=key_positions, key_mask=cache_mask)
    x, new_cache = _scan_blocks(params, cfg, x, sin, cos, bias,
                                cache=cache, cache_index=start_index,
                                fused_ctx=(qpos, cache_mask,
                                           key_positions),
                                trunk_len=(int(trunk_len)
                                           if cfg.cascade_decode else 0))
    logits = _unembed(params, cfg, x)
    return logits, new_cache


def decode_step(params: Params, cfg: ModelConfig, cache, token: jax.Array,
                position: jax.Array, step_index: jax.Array,
                prompt_mask: jax.Array, trunk_len: int = 0):
    """One greedy-decode step.

    token: (B,) int32 current input; position: (B,) its mask-aware position;
    step_index: scalar slot in the cache where this token's k/v land (= S + t);
    prompt_mask: (B, T) validity mask over the FULL cache length T (prompt pads
    0, prompt tokens and generated slots 1 once written).
    ``trunk_len`` (static): on a shared-trunk dispatch with cascade
    decode on (``cfg.cascade_decode``), the cache's leading trunk slots
    are row-identical and the fused kernel's trunk splits read them once
    per kv head for all rows — bitwise the flat kernel.
    Returns (logits (B, V) fp32, new_cache).
    """
    B = token.shape[0]
    x = _embed(params, cfg, token[:, None], position[:, None])
    sin = cos = None
    if cfg.pos_embedding == "rotary":
        sin, cos = _rope_sincos(position[:, None], cfg.rotary_dim, cfg.rope_theta)

    key_positions = mask_positions(prompt_mask)
    bias = _causal_bias(jnp.ones((B, 1), jnp.int32), position[:, None], cfg,
                        key_positions=key_positions, key_mask=prompt_mask)
    # The fused flash-decode route consumes the mask/positions directly
    # (the kernel owns causality + ALiBi); the bias tensor feeds only the
    # dense/int8 fallback and is dead code XLA drops when the kernel
    # engages.
    x, new_cache = _scan_blocks(params, cfg, x, sin, cos, bias,
                                cache=cache, cache_index=step_index,
                                fused_ctx=(position, prompt_mask,
                                           key_positions),
                                trunk_len=(int(trunk_len)
                                           if cfg.cascade_decode else 0))
    logits = _unembed(params, cfg, x)[:, 0, :]
    return logits, new_cache
