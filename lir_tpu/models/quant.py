"""Weight-only int8 quantization for the decoder's linear layers.

The reference's 8-bit mode is bitsandbytes
``BitsAndBytesConfig(load_in_8bit=True)`` (compare_base_vs_instruct.py:
431-435), used so a 7B model fits one GPU. The TPU-native equivalent:
symmetric per-output-channel int8 weights with fp32 scales, dequantized
inside the matmul (``(x @ q) * scale``) — HBM for the big matrices halves
versus bf16, so a 7B model (~7 GB int8) fits a single v5e chip without
tensor parallelism. Activations stay bf16/fp32; the readout's fp32 softmax
path is unchanged.

A ``QuantTensor`` is a registered pytree node, so quantized layer stacks
ride ``lax.scan`` (the leading L axis slices both payload and scales) and
``jax.tree`` utilities transparently.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QuantTensor:
    """Symmetric per-output-channel int8 weight: w ≈ q * scale.

    q: int8, original shape (..., D_in, D_out); scale: fp32 (..., D_out).

    ``dynamic`` (static pytree metadata): when True, ``matmul`` quantizes
    the ACTIVATIONS per token on the fly and runs the dot s8 x s8 -> s32 on
    the MXU (int8 peak = 2x bf16 on v5e; no bf16 dequant copy of the weight
    ever materializes). This is the TPU-native analogue of bitsandbytes
    LLM.int8() vector-wise quantization — the mode the reference actually
    runs (compare_base_vs_instruct.py:431-435) — without the fp16
    outlier-column decomposition, so it is opt-in (--int8-dynamic).
    """

    q: jax.Array
    scale: jax.Array
    dynamic: bool = dataclasses.field(
        default=False, metadata=dict(static=True))

    @property
    def shape(self):
        return self.q.shape

    def dequant(self, dtype=jnp.float32) -> jax.Array:
        return (self.q.astype(dtype) * self.scale[..., None, :].astype(dtype))


def quantize(w: jax.Array) -> QuantTensor:
    """Quantize a (..., D_in, D_out) weight to int8 with per-output-column
    scales (amax / 127, zero-safe)."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(w.astype(jnp.float32) / scale[..., None, :]), -127, 127
    ).astype(jnp.int8)
    return QuantTensor(q=q, scale=scale)


def dynamic_quant(x: jax.Array):
    """Symmetric per-vector int8 quantization over the LAST axis:
    x (..., D) -> (int8 payload (..., D), fp32 scale (...)), amax/127 with
    a zero-safe floor. The single source of the dynamic rule — used for
    activations (matmul), the int8 KV cache (models/decoder._quant_kv),
    and decode attention probabilities."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QuantActivation:
    """A pre-quantized activation: int8 payload + per-vector fp32 scale
    (the ``dynamic_quant`` pair) + the source dtype as static metadata.
    :func:`matmul` accepts it wherever a dynamic QuantTensor is the
    weight, so a call site multiplying ONE activation against SEVERAL
    dynamic int8 matrices (the decoder's wq/wk/wv triple, the gated
    MLP's w_up/w_gate pair) quantizes it once via :func:`shared_quant`
    instead of once per matrix — bit-identical results (the same
    amax/127 rule on the same tensor), N-1 fewer VPU quantization passes
    per site."""

    q: jax.Array      # int8 (..., D)
    scale: jax.Array  # fp32 (...)
    out_dtype: str = dataclasses.field(default="float32",
                                       metadata=dict(static=True))

    @classmethod
    def make(cls, x: jax.Array) -> "QuantActivation":
        xq, xs = dynamic_quant(x)
        return cls(q=xq, scale=xs, out_dtype=str(x.dtype))


def shared_quant(x: jax.Array, *weights):
    """Pre-quantize ``x`` once when EVERY weight it will multiply is a
    dynamic QuantTensor (the fused s8 x s8 path); pass it through
    untouched otherwise. The single entry point decoder.py/encdec.py use
    so no call site quantizes an activation it immediately re-quantizes."""
    if weights and all(isinstance(w, QuantTensor) and w.dynamic
                       for w in weights):
        return QuantActivation.make(x)
    return x


def _dot(x: jax.Array, w: jax.Array, accum_dtype) -> jax.Array:
    """(..., D_in) x (D_in, D_out) contraction as ONE lax.dot_general
    with an explicit accumulator dtype — the s8 x s8 -> s32 form the MXU
    runs at double rate (v5e/v5p/v6e) and the weight-only form XLA fuses
    the int8 -> activation-dtype convert into."""
    return jax.lax.dot_general(x, w, (((x.ndim - 1,), (0,)), ((), ())),
                               preferred_element_type=accum_dtype)


def matmul(x, w) -> jax.Array:
    """x @ w for dense or QuantTensor weights: (..., D_in) x (D_in, D_out).

    Every quantized branch issues a single ``lax.dot_general`` with int8
    inputs — no call site dequantizes a weight it immediately multiplies:

    - **dynamic** QuantTensors run the fused s8 x s8 -> s32 dot on the
      MXU (int8 peak = 2x bf16 on v5e); activations quantize per token
      (symmetric amax / 127, the LLM.int8() vector-wise rule) unless the
      caller already holds a :class:`QuantActivation` (shared_quant —
      the wq/wk/wv and w_up/w_gate call sites), whose payload feeds the
      dot directly. Scales apply on the narrow s32 output:
      y32 * x_scale * w_scale.
    - **static** (weight-only) QuantTensors contract the int8 payload
      with the convert fused INTO the dot — no bf16 copy of the weight
      ever materializes in HBM — and the per-output-column scale applies
      on the output side: (x @ q) * scale == x @ (q * scale).

    Measured on v5e: 1.5x prefill-shape matmul throughput vs the
    bf16-dequant path, and the per-step bf16 weight copy disappears from
    the decode loop's HBM traffic.
    """
    if isinstance(w, QuantTensor):
        if isinstance(x, QuantActivation):
            assert w.dynamic, "QuantActivation requires a dynamic weight"
            y = _dot(x.q, w.q, jnp.int32)
            return (y.astype(jnp.float32) * x.scale[..., None]
                    * w.scale).astype(x.out_dtype)
        if w.dynamic:
            xq, xs = dynamic_quant(x)
            y = _dot(xq, w.q, jnp.int32)
            return (y.astype(jnp.float32) * xs[..., None]
                    * w.scale).astype(x.dtype)
        y = _dot(x, w.q.astype(x.dtype), x.dtype)
        return y * w.scale.astype(x.dtype)
    if isinstance(x, QuantActivation):
        # A dense weight paired with a pre-quantized activation only
        # happens if a call site mis-grouped its weights; dequantize
        # rather than silently changing that weight's semantics.
        x = (x.q.astype(jnp.float32)
             * x.scale[..., None]).astype(x.out_dtype)
    return jnp.einsum("...d,de->...e", x, w)


# The per-layer matrices worth quantizing (biases/norms stay dense).
_LAYER_MATRICES = ("wq", "wk", "wv", "wo", "w_up", "w_gate", "w_down")


def _quantize_block(blk: Params, names, dynamic: bool) -> Params:
    """Shallow-copy a param subtree, int8-quantizing the named matrices
    (optionally tagged dynamic). The single quantize-a-stack rule shared by
    the decoder and T5 paths."""
    out = dict(blk)
    for name in names:
        if name in out:
            out[name] = dataclasses.replace(quantize(out[name]),
                                            dynamic=dynamic)
    return out


def quantize_decoder_params(params: Params, dynamic: bool = False) -> Params:
    """Quantize the big linear weights of a converted decoder param tree
    (stacked layer matrices + lm_head); everything else passes through.

    ``dynamic`` tags the LAYER matrices for on-the-fly activation
    quantization (see QuantTensor); the lm_head stays weight-only
    regardless — its fp32 activations feed the C13 logit readout directly,
    where activation-quantization noise would land on the measured
    probabilities."""
    out = dict(params)
    out["layers"] = _quantize_block(params["layers"], _LAYER_MATRICES,
                                    dynamic)
    if "lm_head" in params:
        out["lm_head"] = quantize(params["lm_head"])
    return out


# T5-family per-layer matrices (models/encdec.py stacks; biases/norms and
# the relative-position embeddings stay dense).
_ENCDEC_MATRICES = ("wq", "wk", "wv", "wo", "wi", "wi_0", "wi_1", "wo_mlp",
                    "cq", "ck", "cv", "co")


def quantize_encdec_params(params: Params, dynamic: bool = False) -> Params:
    """int8-quantize a converted T5 param tree (models/encdec.py layout) —
    the reference loads its t5/T0/tk-instruct models through the same 8-bit
    config as the decoders (compare_base_vs_instruct.py:431-435 via
    AutoModelForSeq2SeqLM :444-455). Same rules as the decoder path:
    per-output-channel scales, optional dynamic activation mode, lm_head
    weight-only (tied v1.0 embeddings stay dense entirely)."""
    out = dict(params)
    for side in ("encoder", "decoder"):
        out[side] = _quantize_block(params[side], _ENCDEC_MATRICES, dynamic)
    if "lm_head" in params:
        out["lm_head"] = quantize(params["lm_head"])
    return out


def random_quantized_params(cfg, key: jax.Array, dtype=jnp.bfloat16,
                            dynamic: bool = False) -> Params:
    """Random param tree at FULL size with the big matrices born int8.

    For real-size throughput/fit work (a 7B tree) the bf16 intermediate of
    init_params -> quantize would transiently double HBM; here each
    QuantTensor is generated directly (int8 payload + constant scale), so
    peak memory is the final int8 footprint. Layout matches
    decoder.init_params exactly (quantize_decoder_params of it would give
    the same tree structure)."""
    from . import decoder

    shapes = jax.eval_shape(lambda k: decoder.init_params(cfg, k, dtype=dtype),
                            key)
    flat, treedef = jax.tree_util.tree_flatten_with_path(shapes)
    quant_names = set(_LAYER_MATRICES) | {"lm_head"}

    leaves = []
    for i, (path, leaf) in enumerate(flat):
        leaf_key = jax.random.fold_in(key, i)
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in quant_names:
            q = jax.random.randint(leaf_key, leaf.shape, -127, 128, jnp.int8)
            scale = jnp.full(leaf.shape[:-2] + leaf.shape[-1:],
                             0.02 / 127.0, jnp.float32)
            leaves.append(QuantTensor(q=q, scale=scale,
                                      dynamic=dynamic and name != "lm_head"))
        else:
            leaves.append((0.02 * jax.random.normal(leaf_key, leaf.shape))
                          .astype(leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def param_bytes(params) -> int:
    """Total payload bytes of a param tree (QuantTensor-aware)."""
    total = 0
    for leaf in jax.tree.leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total
