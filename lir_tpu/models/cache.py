"""Converted-parameter cache: convert HF weights once, restore fast after.

SURVEY.md §5 (checkpoint/resume): the reference re-downloads and even
deletes each model's HF cache per sweep (compare_base_vs_instruct.py:79-86);
our design converts safetensors -> JAX pytree once and caches the result
with orbax, so a 12-model sweep pays the layout conversion once per model
ever, and restores go straight to (sharded) device buffers.

Layout per entry:
  <cache_root>/<name>/params/   orbax checkpoint (the pytree)
  <cache_root>/<name>/cfg.json  the ModelConfig/T5Config + kind marker
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Any, Optional, Tuple

import jax

from ..utils.logging import get_logger
from ..utils.manifest import atomic_write_text
from .registry import ModelConfig, T5Config

log = get_logger(__name__)

# Batch axis of every KV-cache leaf. decoder.init_cache lays the cache out
# (L, K, T, B, hd) — and int8 scale leaves (L, K, T, B) — so the batch is
# axis 3 in both flavors, which is what makes the row gather below one
# uniform tree_map.
KV_BATCH_AXIS = 3


def gather_rows(cache: Any, row_idx: jax.Array) -> Any:
    """Broadcast/reorder KV-cache rows: leaf[..., row_idx, ...] along the
    batch axis, for every leaf of either cache flavor (bf16 pair or int8
    payload+scale pairs).

    This is the cross-cell prefix-reuse primitive: the prefix-group decode
    prefills one cache row per *distinct* shared prefix (G rows), then
    gathers it out to one row per member prompt (M rows, ``row_idx`` maps
    member -> group) before the per-member suffix extension. The gather is
    a copy — the M-row cache is the same size the ungrouped path allocates
    anyway — but the quadratic prefill ran over G <= M rows.
    """
    import jax.numpy as jnp

    return jax.tree.map(
        lambda a: jnp.take(a, row_idx, axis=KV_BATCH_AXIS), cache)


def kv_cache_bytes(cfg, batch: int, max_len: int, dtype_bytes: int = 2) -> int:
    """HBM bytes of one decode KV cache at (batch, max_len) — the number
    the scheduler's batch-ladder sizing and DEPLOY.md's bucket-tuning
    notes reason about. int8 caches store a 1-byte payload plus an fp32
    scale per (head, position, row) vector."""
    per_side = cfg.n_layers * cfg.n_kv_heads * max_len * batch
    if getattr(cfg, "kv_cache_int8", False):
        return 2 * (per_side * cfg.head_dim + per_side * 4)
    return 2 * per_side * cfg.head_dim * dtype_bytes

_CFG_KINDS = {"decoder": ModelConfig, "t5": T5Config}


def _cfg_to_json(cfg) -> str:
    kind = "t5" if isinstance(cfg, T5Config) else "decoder"
    return json.dumps({"kind": kind, "fields": dataclasses.asdict(cfg)},
                      indent=2)


def _cfg_from_json(text: str):
    obj = json.loads(text)
    cls = _CFG_KINDS[obj["kind"]]
    fields = obj["fields"]
    # Tuples serialize as lists; dataclass fields that expect tuples accept
    # sequences at runtime, so pass through unchanged.
    return cls(**fields)


def cache_entry_dir(cache_root: Path, name: str) -> Path:
    return Path(cache_root) / name.replace("/", "__")


def has_cached(cache_root: Path, name: str) -> bool:
    entry = cache_entry_dir(cache_root, name)
    return (entry / "cfg.json").exists() and (entry / "params").exists()


def save_params(cache_root: Path, name: str, params: Any, cfg) -> Path:
    """Write the converted pytree + config. Overwrites an existing entry."""
    import orbax.checkpoint as ocp

    entry = cache_entry_dir(cache_root, name)
    entry.mkdir(parents=True, exist_ok=True)
    ckpt_dir = entry / "params"
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(ckpt_dir.resolve(), params, force=True)
    atomic_write_text(entry / "cfg.json", _cfg_to_json(cfg))
    log.info("cached converted params for %s at %s", name, entry)
    return entry


def load_params(
    cache_root: Path, name: str, shardings: Optional[Any] = None
) -> Tuple[Any, Any]:
    """Restore (params, cfg). With `shardings` (a pytree of NamedSharding
    matching the params tree), buffers restore directly into their sharded
    placement — no host-memory detour."""
    import orbax.checkpoint as ocp

    entry = cache_entry_dir(cache_root, name)
    cfg = _cfg_from_json((entry / "cfg.json").read_text())
    with ocp.StandardCheckpointer() as ckptr:
        if shardings is None:
            params = ckptr.restore((entry / "params").resolve())
        else:
            # Restore straight into the sharded placement: abstract targets
            # built from saved metadata + the caller's NamedShardings.
            metadata = ckptr.metadata((entry / "params").resolve())
            abstract = jax.tree.map(
                lambda meta, sh: jax.ShapeDtypeStruct(
                    meta.shape, meta.dtype, sharding=sh),
                metadata, shardings,
            )
            params = ckptr.restore((entry / "params").resolve(), abstract)
    log.info("restored cached params for %s", name)
    return params, cfg
