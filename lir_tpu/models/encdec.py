"""Functional T5-family encoder-decoder (t5-v1_1, flan-t5, T0, tk-instruct).

The reference routes "t5|t0|tk-instruct" repos through
``AutoModelForSeq2SeqLM`` (compare_instruct_models.py:471-475) and reads
yes/no probabilities from the decoder's first generated position
(compare_base_vs_instruct.py:203-241). This is the JAX equivalent: relative
position buckets, RMSNorm, gated-GeLU MLP (v1.1), no biases anywhere,
fp32 softmax/logits.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .registry import T5Config
from .quant import QuantTensor, matmul as _mm, shared_quant as _sq

Params = Dict[str, Any]


def _rmsnorm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    xf = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale.astype(jnp.float32)).astype(x.dtype)


def _relative_bucket(rel: jax.Array, bidirectional: bool, num_buckets: int,
                     max_distance: int) -> jax.Array:
    """HF T5 relative_position_bucket (modeling_t5 semantics re-derived)."""
    ret = jnp.zeros_like(rel)
    if bidirectional:
        num_buckets //= 2
        ret = ret + (rel > 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(rel)
    else:
        n = jnp.maximum(-rel, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / jnp.log(max_distance / max_exact) * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_large = jnp.minimum(val_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_large)


def _rel_bias(rel_embed: jax.Array, q_pos: jax.Array, k_pos: jax.Array,
              cfg: T5Config, bidirectional: bool) -> jax.Array:
    """(B,S),(B,T) mask-aware positions -> additive bias (B, H, S, T) fp32."""
    rel = k_pos[:, None, :] - q_pos[:, :, None]          # (B, S, T)
    buckets = _relative_bucket(rel, bidirectional,
                               cfg.relative_attention_num_buckets,
                               cfg.relative_attention_max_distance)
    bias = jnp.take(rel_embed, buckets, axis=0)          # (B, S, T, H)
    return jnp.transpose(bias, (0, 3, 1, 2)).astype(jnp.float32)


def _attn(q, k, v, bias):
    """q:(B,S,H,hd) k,v:(B,T,H,hd) bias fp32 (B,H,S,T). T5: NO 1/sqrt(d)."""
    B, S, H, hd = q.shape
    scores = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32) + bias
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhst,bthd->bshd", probs, v).reshape(B, S, H * hd)


def _proj(x, w):
    """Dense or int8 (QuantTensor) projection — quant.matmul handles both,
    including the dynamic s8 x s8 activation-quantization mode."""
    return _mm(x, w)


def _mlp(x, lp, cfg: T5Config):
    if cfg.gated_mlp:
        # One quantized activation feeds both gate matrices (dynamic int8
        # trees; quant.shared_quant is a no-op otherwise).
        xq = _sq(x, lp["wi_0"], lp["wi_1"])
        h = (jax.nn.gelu(_proj(xq, lp["wi_0"]), approximate=True)
             * _proj(xq, lp["wi_1"]))
    else:
        h = jax.nn.relu(_proj(x, lp["wi"]))
    return _proj(h, lp["wo_mlp"])


def init_params(cfg: T5Config, key: jax.Array, dtype=jnp.float32) -> Params:
    # 22 draws for a gated (wi_0/wi_1) untied config; headroom is free.
    k = iter(jax.random.split(key, 32))
    D, H, hd, F, L = (cfg.hidden_size, cfg.n_heads, cfg.head_dim,
                      cfg.intermediate_size, cfg.n_layers)

    def w(*shape, scale=0.02):
        return (scale * jax.random.normal(next(k), shape)).astype(dtype)

    def stack(cross: bool) -> Params:
        p = {
            "ln_attn": jnp.ones((L, D), dtype),
            "wq": w(L, D, H * hd), "wk": w(L, D, H * hd), "wv": w(L, D, H * hd),
            "wo": w(L, H * hd, D),
            "ln_mlp": jnp.ones((L, D), dtype),
        }
        if cfg.gated_mlp:
            p.update({"wi_0": w(L, D, F), "wi_1": w(L, D, F)})
        else:
            p["wi"] = w(L, D, F)
        p["wo_mlp"] = w(L, F, D)
        if cross:
            p.update({
                "ln_cross": jnp.ones((L, D), dtype),
                "cq": w(L, D, H * hd), "ck": w(L, D, H * hd),
                "cv": w(L, D, H * hd), "co": w(L, H * hd, D),
            })
        return p

    params: Params = {
        "shared_embed": w(cfg.vocab_size, D),
        "enc_rel_embed": w(cfg.relative_attention_num_buckets, H),
        "dec_rel_embed": w(cfg.relative_attention_num_buckets, H),
        "encoder": stack(cross=False),
        "enc_final_ln": jnp.ones((D,), dtype),
        "decoder": stack(cross=True),
        "dec_final_ln": jnp.ones((D,), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = w(D, cfg.vocab_size)
    return params


def encode(params: Params, cfg: T5Config, tokens: jax.Array,
           attn_mask: jax.Array) -> jax.Array:
    """Encoder stack: (B, S) -> (B, S, D)."""
    positions = jnp.maximum(jnp.cumsum(attn_mask, axis=-1) - 1, 0)
    x = jnp.take(params["shared_embed"], tokens, axis=0)
    pad_bias = jnp.where(attn_mask[:, None, None, :] > 0, 0.0, -1e9).astype(jnp.float32)
    rel = _rel_bias(params["enc_rel_embed"], positions, positions, cfg, True)
    bias = rel + pad_bias

    def body(h, lp):
        a_in = _rmsnorm(h, lp["ln_attn"], cfg.norm_eps)
        B, S, _ = a_in.shape
        aq = _sq(a_in, lp["wq"], lp["wk"], lp["wv"])
        q = _proj(aq, lp["wq"]).reshape(B, S, cfg.n_heads, cfg.head_dim)
        kk = _proj(aq, lp["wk"]).reshape(B, S, cfg.n_heads, cfg.head_dim)
        vv = _proj(aq, lp["wv"]).reshape(B, S, cfg.n_heads, cfg.head_dim)
        h = h + _proj(_attn(q, kk, vv, bias), lp["wo"])
        m_in = _rmsnorm(h, lp["ln_mlp"], cfg.norm_eps)
        h = h + _mlp(m_in, lp, cfg)
        return h, None

    x, _ = lax.scan(body, x, params["encoder"])
    return _rmsnorm(x, params["enc_final_ln"], cfg.norm_eps)


def decode(params: Params, cfg: T5Config, enc_out: jax.Array,
           enc_mask: jax.Array, dec_tokens: jax.Array,
           dec_mask: Optional[jax.Array] = None) -> jax.Array:
    """Full (teacher-forced) decoder pass -> fp32 logits (B, S_dec, V).

    For the yes/no readout only the first decoded position is needed:
    feed ``dec_tokens = [[decoder_start_token_id]]``.
    """
    B, S = dec_tokens.shape
    if dec_mask is None:
        dec_mask = jnp.ones_like(dec_tokens)
    positions = jnp.maximum(jnp.cumsum(dec_mask, axis=-1) - 1, 0)
    x = jnp.take(params["shared_embed"], dec_tokens, axis=0)

    causal = (positions[:, None, :] <= positions[:, :, None]) & (dec_mask[:, None, :] > 0)
    self_bias = _rel_bias(params["dec_rel_embed"], positions, positions, cfg, False)
    self_bias = self_bias + jnp.where(causal[:, None, :, :], 0.0, -1e9)
    cross_bias = jnp.where(enc_mask[:, None, None, :] > 0, 0.0, -1e9).astype(jnp.float32)

    def body(h, lp):
        a_in = _rmsnorm(h, lp["ln_attn"], cfg.norm_eps)
        aq = _sq(a_in, lp["wq"], lp["wk"], lp["wv"])
        q = _proj(aq, lp["wq"]).reshape(B, S, cfg.n_heads, cfg.head_dim)
        kk = _proj(aq, lp["wk"]).reshape(B, S, cfg.n_heads, cfg.head_dim)
        vv = _proj(aq, lp["wv"]).reshape(B, S, cfg.n_heads, cfg.head_dim)
        h = h + _proj(_attn(q, kk, vv, self_bias), lp["wo"])

        c_in = _rmsnorm(h, lp["ln_cross"], cfg.norm_eps)
        Te = enc_out.shape[1]
        eq = _sq(enc_out, lp["ck"], lp["cv"])
        cq = _proj(c_in, lp["cq"]).reshape(B, S, cfg.n_heads, cfg.head_dim)
        ck = _proj(eq, lp["ck"]).reshape(B, Te, cfg.n_heads, cfg.head_dim)
        cv = _proj(eq, lp["cv"]).reshape(B, Te, cfg.n_heads, cfg.head_dim)
        h = h + _proj(_attn(cq, ck, cv, cross_bias), lp["co"])

        m_in = _rmsnorm(h, lp["ln_mlp"], cfg.norm_eps)
        h = h + _mlp(m_in, lp, cfg)
        return h, None

    x, _ = lax.scan(body, x, params["decoder"])
    x = _rmsnorm(x, params["dec_final_ln"], cfg.norm_eps)
    if cfg.tie_embeddings:
        # T5 v1.0 ties + rescales by d_model^-0.5.
        head = params["shared_embed"].T
        x = x * (cfg.hidden_size ** -0.5)
    else:
        head = params["lm_head"]
    if isinstance(head, QuantTensor):
        return _mm(x.astype(jnp.float32), head)
    return jnp.einsum("bsd,dv->bsv", x.astype(jnp.float32), head.astype(jnp.float32))


def forward(params: Params, cfg: T5Config, enc_tokens: jax.Array,
            enc_mask: jax.Array, dec_tokens: jax.Array,
            dec_mask: Optional[jax.Array] = None) -> jax.Array:
    enc_out = encode(params, cfg, enc_tokens, enc_mask)
    return decode(params, cfg, enc_out, enc_mask, dec_tokens, dec_mask)
