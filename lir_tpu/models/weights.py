"""Async weight streaming + the HBM-budgeted LRU weight cache.

The inter-model agreement axis (the paper's axis 2) scores 10-18
open-weight models over one grid. Before the fleet layer, engine/multi.py
paid a full host->device weight load as DEAD MXU time per model: params
dropped between models, the next model's transfer serialized behind the
previous model's last dispatch. ServerlessLLM's observation transfers
directly — for a <=10-token scoring decode, checkpoint LOAD time (host
staging + host->device copy), not compute, dominates model-switch
latency — so this module makes the load overlappable and, where HBM
allows, makes it disappear entirely:

- **Pinned host staging** (:func:`host_stage`): the converted pytree
  (models/loader.py layout) held as host numpy buffers, QuantTensor
  payload/scale included. Staging is the slow, torch/safetensors-touching
  step; it runs ONCE per model and the staged tree is what the streamer
  re-ships on every (re)load — a reload costs one host->device copy, not
  a re-conversion.
- **Chunked, double-buffered streaming** (:func:`stream_params`): leaves
  ship through ``jax.device_put`` in bounded chunks with a small
  in-flight window, so a 7B tree never needs a second full host copy and
  transfers overlap. Per-model partition rules are honored via the
  ``parallel/sharding.py`` registry (``spec_tree_for``), QuantTensor
  scales taking the derived output-axis spec exactly like
  ``sharding.shard_params``. The streamed tree is BITWISE-identical to a
  monolithic ``device_put`` (pinned by tests/test_loader_streaming.py
  for every architecture family converter).
- **LRU weight cache** (:class:`WeightCache`): an HBM-budgeted pool of
  co-resident model param trees — the weight-side sibling of
  models/paged.py's KV page pool, with the same refcount discipline:
  every in-flight dispatch holds a reference, eviction (LRU) may only
  drop models nobody is dispatching, pinned models are unevictable, and
  a refcount can never go negative (a double release is a bug worth
  crashing on).
- **Async prefetch** (:class:`AsyncWeightStreamer`): a background worker
  streams the NEXT model's staged tree while the CURRENT model's
  dispatches run, so swap cost hides behind compute
  (``FleetStats.swap_s_hidden``) instead of serializing with it
  (``swap_s_exposed``). One worker on purpose: host->device bandwidth is
  one resource; two concurrent streams just halve each other.

engine/fleet.py composes these into the fleet scheduler; serve's
multiplexed fleet server and the rewritten engine/multi.py both ride it.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..utils.logging import get_logger

log = get_logger(__name__)

# Streaming chunk size. Large enough that per-chunk dispatch overhead is
# noise against the copy itself, small enough that the in-flight window
# (2 chunks) bounds transient host pinned memory well under one leaf of
# a 7B tree. DEPLOY.md §1k documents the tuning story.
DEFAULT_CHUNK_BYTES = 64 << 20
# Double buffering: chunk k+1 is issued while chunk k is still in
# flight; chunk k-1 must have landed before k+1 is issued.
INFLIGHT_CHUNKS = 2


class WeightCacheOOM(RuntimeError):
    """The weight cache cannot fit a model inside its HBM budget — every
    resident candidate for eviction is pinned or referenced by an
    in-flight dispatch. Deliberately loud: silently thrashing weights
    under a mis-sized budget is the failure DEPLOY.md §1k's arithmetic
    exists to prevent."""


def leaf_bytes(leaf) -> int:
    """Payload bytes of one tree leaf (QuantTensor-aware)."""
    from .quant import QuantTensor

    if isinstance(leaf, QuantTensor):
        return leaf_bytes(leaf.q) + leaf_bytes(leaf.scale)
    return int(leaf.size) * int(np.dtype(leaf.dtype).itemsize)


def tree_bytes(params: Any) -> int:
    """Total payload bytes of a param tree (the cache's accounting unit;
    equals models/quant.param_bytes on device trees)."""
    from .quant import QuantTensor

    return sum(leaf_bytes(l) for l in _leaves(params, QuantTensor))


def _leaves(tree: Any, quant_cls) -> List[Any]:
    import jax

    return jax.tree.leaves(tree,
                           is_leaf=lambda x: isinstance(x, quant_cls))


def host_stage(params: Any) -> Any:
    """Host staging copy of a converted param tree: every array leaf
    becomes a host numpy buffer (QuantTensor structure preserved —
    int8 payload + fp32 scale stay exactly as quantized). This is the
    tree the streamer ships; it never changes after staging, so a
    reload after eviction is bitwise-identical by construction."""
    import jax

    from .quant import QuantTensor

    def leaf(x):
        if isinstance(x, QuantTensor):
            return QuantTensor(q=np.asarray(jax.device_get(x.q)),
                               scale=np.asarray(jax.device_get(x.scale)),
                               dynamic=x.dynamic)
        return np.asarray(jax.device_get(x))

    return jax.tree.map(leaf, params,
                        is_leaf=lambda x: isinstance(x, QuantTensor))


class _InflightWindow:
    """Bounded device_put pipeline: admit a new transfer only after the
    one two slots back has landed (double buffering). ``drain`` blocks
    until everything landed."""

    def __init__(self, depth: int = INFLIGHT_CHUNKS):
        self.depth = depth
        self._pending: List[Any] = []

    def admit(self, arr) -> Any:
        self._pending.append(arr)
        if len(self._pending) > self.depth:
            head = self._pending.pop(0)
            if hasattr(head, "block_until_ready"):
                head.block_until_ready()
        return arr

    def drain(self) -> None:
        for arr in self._pending:
            if hasattr(arr, "block_until_ready"):
                arr.block_until_ready()
        self._pending.clear()


def _chunk_starts(n_rows: int, rows_per_chunk: int) -> List[int]:
    return list(range(0, n_rows, max(rows_per_chunk, 1)))


def _stream_array(arr: np.ndarray, sharding, chunk_bytes: int,
                  window: _InflightWindow):
    """One leaf host->device, split along axis 0 into <= chunk_bytes
    pieces re-joined on device. Axis 0 is the layer-stack (or vocab)
    axis — replicated in every partition rule this engine emits — so a
    chunk's sharding equals the full leaf's. Bitwise: concatenation of
    device_put chunks is the identical buffer a monolithic device_put
    produces."""
    import jax
    import jax.numpy as jnp

    nbytes = leaf_bytes(arr)
    put = (lambda a: jax.device_put(a, sharding)) if sharding is not None \
        else jax.device_put
    if arr.ndim == 0 or nbytes <= chunk_bytes or arr.shape[0] <= 1:
        return window.admit(put(arr))
    from ..observe import tracing

    rows = max(int(arr.shape[0] * chunk_bytes / nbytes), 1)
    starts = _chunk_starts(arr.shape[0], rows)
    with tracing.span("weights/stream_chunks", nbytes=nbytes,
                      chunks=len(starts)):
        parts = [window.admit(put(arr[s:s + rows])) for s in starts]
    if len(parts) == 1:
        return parts[0]
    joined = jnp.concatenate(parts, axis=0)
    if sharding is not None:
        # Re-pin the joined buffer: concatenate of same-sharded operands
        # already lands there, but make the placement explicit rather
        # than relying on XLA's default propagation.
        joined = jax.device_put(joined, sharding)
    return window.admit(joined)


def stream_params(staged: Any, cfg=None, mesh=None,
                  chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                  stats=None) -> Any:
    """Ship a host-staged tree to device in chunks with a double-buffered
    in-flight window; returns the fully-landed device tree.

    With ``cfg`` and ``mesh``, every leaf takes its NamedSharding from
    the per-model partition-rule registry
    (``parallel.sharding.spec_tree_for``); QuantTensor payloads take the
    dense weight's spec and scales the derived output-axis spec —
    exactly ``sharding.shard_params``'s placement, arrived at chunk by
    chunk. Without a mesh, leaves land on the default device.

    ``stats`` (profiling.FleetStats) counts ``weight_bytes_streamed``.
    """
    import jax

    from .quant import QuantTensor

    specs = None
    shard = None
    if mesh is not None and cfg is not None:
        from jax.sharding import NamedSharding

        from ..parallel import sharding as sharding_mod

        specs = sharding_mod.spec_tree_for(cfg, mesh, staged)
        shard = lambda spec: NamedSharding(mesh, spec)  # noqa: E731

    window = _InflightWindow()

    def leaf(x, spec=None):
        from ..parallel.sharding import quant_scale_spec

        if isinstance(x, QuantTensor):
            q = _stream_array(np.asarray(x.q),
                              shard(spec) if spec is not None else None,
                              chunk_bytes, window)
            scale = _stream_array(
                np.asarray(x.scale),
                shard(quant_scale_spec(spec)) if spec is not None else None,
                chunk_bytes, window)
            return QuantTensor(q=q, scale=scale, dynamic=x.dynamic)
        return _stream_array(np.asarray(x),
                             shard(spec) if spec is not None else None,
                             chunk_bytes, window)

    from ..observe import tracing

    is_qt = lambda x: isinstance(x, QuantTensor)  # noqa: E731
    with tracing.span("weights/stream", bytes=tree_bytes(staged)):
        if specs is not None:
            out = jax.tree.map(leaf, staged, specs, is_leaf=is_qt)
        else:
            out = jax.tree.map(leaf, staged, is_leaf=is_qt)
        window.drain()
    if stats is not None:
        stats.count("weight_bytes_streamed", tree_bytes(staged))
    return out


# ---------------------------------------------------------------------------
# LRU weight cache
# ---------------------------------------------------------------------------


class _Entry:
    __slots__ = ("params", "nbytes", "refcount", "pinned")

    def __init__(self, params: Any, nbytes: int):
        self.params = params
        self.nbytes = int(nbytes)
        self.refcount = 0
        self.pinned = False


class WeightCache:
    """HBM-budgeted LRU pool of co-resident model param trees.

    Bookkeeping only — loading/streaming is the fleet's job (the cache
    must never hold its lock across a multi-second host->device copy).
    Discipline mirrors the KV page pool (models/paged.py):

    - ``acquire`` marks a model in use by one dispatch stream
      (refcount += 1, MRU touch); ``release`` drops it. A refcount can
      never go negative.
    - ``insert`` evicts LRU models until the new tree fits the budget.
      Only models with refcount == 0 and not pinned are evictable; if
      nothing evictable frees enough, :class:`WeightCacheOOM`.
    - ``pin``/``unpin``: a pinned model is unevictable regardless of
      refcount (serving pins the models a fleet request is fanning
      across so no sub-request can evict another's weights mid-fan).

    ``budget_bytes=None`` means unbounded (CPU smoke / tests size by
    entry count instead via eviction pressure).
    """

    def __init__(self, budget_bytes: Optional[int] = None, stats=None,
                 on_evict: Optional[Callable[[str], None]] = None):
        self.budget_bytes = (None if budget_bytes is None
                             else int(budget_bytes))
        self.stats = stats
        # Eviction hook: the fleet clears the evicted engine's params
        # reference and donation-chain scratch so the HBM actually
        # reclaims (the cache's own reference is not the only one).
        self.on_evict = on_evict
        self._lock = threading.RLock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()  # guarded-by: _lock
        # Residency-change listeners (observe/sentinel.py re-scores its
        # sentinel grid when the resident set changes): called with
        # ("insert" | "evict", model_id), possibly under the cache
        # lock — listeners must be cheap and must NOT touch the cache.
        self._listeners: list = []  # guarded-by: _lock

    def add_listener(self, fn: Callable[[str, str], None]) -> None:
        with self._lock:
            self._listeners.append(fn)

    def _notify(self, event: str, model_id: str) -> None:
        for fn in list(self._listeners):
            try:
                fn(event, model_id)
            except Exception:  # noqa: BLE001 — telemetry must never
                # break residency bookkeeping
                log.exception("weight cache listener failed")

    # -- gauges --------------------------------------------------------------

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return sum(e.nbytes for e in self._entries.values())

    @property
    def resident_models(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def __contains__(self, model_id: str) -> bool:
        with self._lock:
            return model_id in self._entries

    def refcount(self, model_id: str) -> int:
        with self._lock:
            e = self._entries.get(model_id)
            return 0 if e is None else e.refcount

    def _gauge(self) -> None:
        if self.stats is not None:
            self.stats.gauge("resident_models", len(self._entries))
            self.stats.gauge("resident_bytes",
                             sum(e.nbytes for e in self._entries.values()))

    # -- resident set --------------------------------------------------------

    def insert(self, model_id: str, params: Any,
               nbytes: Optional[int] = None) -> None:
        """Make ``model_id`` resident (idempotent — re-inserting a
        resident model only touches MRU order). Evicts LRU models as
        needed; raises :class:`WeightCacheOOM` when the budget cannot be
        met by evicting unreferenced, unpinned models."""
        with self._lock:
            if model_id in self._entries:
                self._entries.move_to_end(model_id)
                return
            nbytes = tree_bytes(params) if nbytes is None else int(nbytes)
            if self.budget_bytes is not None:
                self._evict_until(self.budget_bytes - nbytes, model_id)
            self._entries[model_id] = _Entry(params, nbytes)
            self._gauge()
            self._notify("insert", model_id)

    def _evict_until(self, budget_left: int, incoming: str) -> None:  # guarded-by: _lock
        used = sum(e.nbytes for e in self._entries.values())
        if used <= budget_left:
            return
        for mid in list(self._entries):       # OrderedDict = LRU first
            e = self._entries[mid]
            if e.refcount > 0 or e.pinned:
                continue
            del self._entries[mid]
            used -= e.nbytes
            if self.stats is not None:
                self.stats.count("evictions")
            if self.on_evict is not None:
                self.on_evict(mid)
            self._notify("evict", mid)
            log.info("weight cache: evicted %s (%.2f GB) for %s",
                     mid, e.nbytes / 2**30, incoming)
            if used <= budget_left:
                self._gauge()
                return
        raise WeightCacheOOM(
            f"cannot fit {incoming} in the weight cache: "
            f"{used / 2**30:.2f} GB resident is pinned or in use, "
            f"budget leaves {max(budget_left, 0) / 2**30:.2f} GB")

    def evict_idle(self) -> Optional[str]:
        """Evict the LRU model that is idle (refcount 0, not pinned) —
        the HBM governor's ``evict_weights`` rung (engine/hbm.py).
        Returns the evicted model id, or None when every resident model
        is pinned or under an in-flight dispatch (nothing reclaimable
        without breaking the refcount contract)."""
        with self._lock:
            for mid in list(self._entries):   # OrderedDict = LRU first
                e = self._entries[mid]
                if e.refcount > 0 or e.pinned:
                    continue
                del self._entries[mid]
                if self.stats is not None:
                    self.stats.count("evictions")
                if self.on_evict is not None:
                    self.on_evict(mid)
                self._notify("evict", mid)
                self._gauge()
                log.info("weight cache: governor evicted idle %s "
                         "(%.2f GB)", mid, e.nbytes / 2**30)
                return mid
        return None

    def drop(self, model_id: str) -> None:
        """Explicitly evict one model (must be unreferenced/unpinned)."""
        with self._lock:
            e = self._entries.get(model_id)
            if e is None:
                return
            if e.refcount > 0 or e.pinned:
                raise WeightCacheOOM(
                    f"cannot drop {model_id}: refcount {e.refcount}, "
                    f"pinned {e.pinned}")
            del self._entries[model_id]
            if self.stats is not None:
                self.stats.count("evictions")
            if self.on_evict is not None:
                self.on_evict(model_id)
            self._notify("evict", model_id)
            self._gauge()

    # -- reference discipline ------------------------------------------------

    def acquire(self, model_id: str) -> Any:
        """Params of a RESIDENT model, refcounted for one dispatch
        stream. KeyError when not resident (the fleet loads first)."""
        with self._lock:
            e = self._entries[model_id]
            e.refcount += 1
            self._entries.move_to_end(model_id)
            return e.params

    def release(self, model_id: str) -> None:
        with self._lock:
            e = self._entries[model_id]
            e.refcount -= 1
            assert e.refcount >= 0, (
                f"weight cache refcount for {model_id} went negative — "
                "double release")

    def pin(self, model_id: str) -> None:
        with self._lock:
            self._entries[model_id].pinned = True

    def unpin(self, model_id: str) -> None:
        with self._lock:
            self._entries[model_id].pinned = False

    def peek(self, model_id: str) -> Optional[Any]:
        """Params without touching refcount or MRU order (tests)."""
        with self._lock:
            e = self._entries.get(model_id)
            return None if e is None else e.params


# ---------------------------------------------------------------------------
# Async prefetch
# ---------------------------------------------------------------------------


class AsyncWeightStreamer:
    """One background worker streaming staged trees to device ahead of
    need. ``prefetch`` enqueues a load; ``take`` blocks until that load
    lands and reports (params, load_seconds, waited_seconds) — the fleet
    books ``waited`` as exposed swap time and ``load - waited`` as
    hidden (overlapped with the previous model's compute).

    Single worker by design: host->device bandwidth is one shared
    resource, and the scheduler only ever needs the NEXT model early.
    """

    def __init__(self):
        import concurrent.futures

        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="weight-stream")
        self._lock = threading.Lock()
        self._futures: Dict[str, Any] = {}  # guarded-by: _lock

    def prefetch(self, model_id: str,
                 load_fn: Callable[[], Any]) -> None:
        """Start loading ``model_id`` in the background (idempotent while
        a load is already queued/running)."""
        with self._lock:
            if model_id in self._futures:
                return

            def timed():
                t0 = time.perf_counter()
                params = load_fn()
                return params, time.perf_counter() - t0

            self._futures[model_id] = self._pool.submit(timed)

    def pending(self, model_id: str) -> bool:
        with self._lock:
            return model_id in self._futures

    def take(self, model_id: str) -> Optional[Tuple[Any, float, float]]:
        """Claim a prefetched load: blocks until it lands, returns
        (params, load_s, waited_s), or None when nothing was prefetched
        for ``model_id``. A load that raised re-raises HERE, on the
        consumer thread — prefetch failures surface exactly where an
        inline load's would."""
        with self._lock:
            fut = self._futures.pop(model_id, None)
        if fut is None:
            return None
        t0 = time.perf_counter()
        params, load_s = fut.result()
        return params, load_s, time.perf_counter() - t0

    def cancel_all(self) -> None:
        with self._lock:
            futures = dict(self._futures)
            self._futures.clear()
        for fut in futures.values():
            fut.cancel()

    def shutdown(self) -> None:
        self.cancel_all()
        self._pool.shutdown(wait=True)
