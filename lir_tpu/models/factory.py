"""High-level model loading: HF checkpoint directory -> ScoringEngine.

The reference loads each model with ``AutoModelForCausalLM.from_pretrained
(device_map="auto", 8-bit)`` (compare_base_vs_instruct.py:423-455) and
routes t5/t0/tk-instruct through the Seq2Seq class
(compare_instruct_models.py:471-475). Here the flow is:

  local checkpoint dir -> AutoConfig/AutoTokenizer -> state dict
  (safetensors preferred, torch .bin fallback) -> loader.convert_* ->
  jax pytree (bf16 on TPU) -> optional Mesh sharding -> ScoringEngine

Zero-egress discipline: everything is ``local_files_only`` — weights must
already be on disk; nothing here talks to a hub.
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Any, Dict, Mapping, Optional

import jax.numpy as jnp
import numpy as np

from ..config import MeshConfig, RuntimeConfig
from ..engine.runner import ScoringEngine
from ..utils.logging import get_logger
from . import loader

log = get_logger(__name__)

# Routing rule "t5|t0|tk-instruct -> Seq2Seq" (compare_instruct_models.py:471-475).
_ENCDEC_PATTERN = re.compile(r"(^|/)(t5|flan-t5|t0|tk-instruct)", re.IGNORECASE)


def is_encoder_decoder(name_or_path: str, hf_cfg=None) -> bool:
    if hf_cfg is not None and getattr(hf_cfg, "is_encoder_decoder", False):
        return True
    return bool(_ENCDEC_PATTERN.search(str(name_or_path)))


class _LazyStateDict(Mapping[str, Any]):
    """Read tensors straight from safetensors shards on demand — one tensor
    resident at a time instead of a second full copy of a 7B checkpoint."""

    def __init__(self, model_dir: Path):
        from safetensors import safe_open

        self._open = safe_open
        self._index: Dict[str, Path] = {}
        index_file = model_dir / "model.safetensors.index.json"
        if index_file.exists():
            weight_map = json.loads(index_file.read_text())["weight_map"]
            for key, shard in weight_map.items():
                self._index[key] = model_dir / shard
        else:
            single = model_dir / "model.safetensors"
            if not single.exists():
                raise FileNotFoundError(f"no safetensors found in {model_dir}")
            with safe_open(single, framework="np") as f:
                for key in f.keys():
                    self._index[key] = single

    def __getitem__(self, key: str) -> np.ndarray:
        path = self._index[key]
        with self._open(path, framework="np") as f:
            return f.get_tensor(key)

    def __iter__(self):
        return iter(self._index)

    def __len__(self) -> int:
        return len(self._index)


def load_state_dict(model_dir: Path) -> Mapping[str, Any]:
    """safetensors (lazy) preferred; torch .bin fallback (full load)."""
    model_dir = Path(model_dir)
    try:
        return _LazyStateDict(model_dir)
    except FileNotFoundError:
        pass
    import torch

    bins = sorted(model_dir.glob("pytorch_model*.bin"))
    if not bins:
        raise FileNotFoundError(
            f"no safetensors or pytorch_model*.bin in {model_dir}"
        )
    state: Dict[str, Any] = {}
    for b in bins:
        state.update(torch.load(b, map_location="cpu", weights_only=True))
    return state


def load_engine(
    model_dir: Path,
    runtime: Optional[RuntimeConfig] = None,
    mesh_cfg: Optional[MeshConfig] = None,
    dtype=None,
    cache_root: Optional[Path] = None,
    quantize_int8: bool = False,
    int8_dynamic: bool = False,
    kv_cache_int8: bool = False,
    spec_config=None,
    governor_config=None,
    cascade_config=None,
) -> ScoringEngine:
    """Build a ready ScoringEngine from a local HF checkpoint directory.

    With `cache_root`, the converted pytree is cached via models.cache: the
    HF-layout conversion happens once per model ever, subsequent loads
    restore orbax buffers directly (sharded, when a mesh is given)."""
    import jax
    import transformers

    model_dir = Path(model_dir)
    hf_cfg = transformers.AutoConfig.from_pretrained(
        model_dir, local_files_only=True, trust_remote_code=False
    )
    tokenizer = transformers.AutoTokenizer.from_pretrained(
        model_dir, local_files_only=True, trust_remote_code=False
    )
    if dtype is None:
        dtype = (jnp.bfloat16 if jax.devices()[0].platform != "cpu"
                 else jnp.float32)

    encdec = is_encoder_decoder(model_dir.name, hf_cfg)

    from . import cache as cache_mod

    if cache_root is not None and cache_mod.has_cached(cache_root, model_dir.name):
        params, cfg = cache_mod.load_params(cache_root, model_dir.name)
    else:
        state = load_state_dict(model_dir)
        if encdec:
            cfg = loader.t5_config_from_hf(hf_cfg)
            params = loader.convert_t5(state, cfg, dtype=dtype)
        else:
            cfg, family = loader.config_from_hf(hf_cfg)
            params = loader.convert_decoder(state, cfg, family, dtype=dtype)
        if cache_root is not None:
            cache_mod.save_params(cache_root, model_dir.name, params, cfg)

    if kv_cache_int8:
        if encdec:
            # ≤50-token decodes re-run the tiny decoder stack instead of
            # keeping a cache (generate.t5_greedy_decode), so there is no
            # cache to quantize — say so instead of silently ignoring the
            # flag (ADVICE r2 #4).
            log.warning(
                "%s: --kv-cache-int8 has no effect on encoder-decoder "
                "models (no KV cache in the seq2seq decode path); "
                "proceeding without it", model_dir.name)
        else:
            import dataclasses

            cfg = dataclasses.replace(cfg, kv_cache_int8=True)
    if quantize_int8:
        from . import quant

        before = quant.param_bytes(params)
        qfn = (quant.quantize_encdec_params if encdec
               else quant.quantize_decoder_params)
        params = qfn(params, dynamic=int8_dynamic)
        log.info(
            "int8-quantized %s: %.2f GB -> %.2f GB", model_dir.name,
            before / 2**30, quant.param_bytes(params) / 2**30,
        )

    seq_mesh = None
    if mesh_cfg is not None and mesh_cfg.n_devices > 1:
        from ..parallel import sharding

        if encdec and mesh_cfg.seq > 1:
            # Ring/Ulysses prefill is a decoder-path feature; refuse the
            # seq axis loudly rather than silently serving a different
            # sharding than the user asked for (ADVICE r2 #4).
            raise ValueError(
                f"--mesh with seq={mesh_cfg.seq} > 1 is not supported for "
                f"encoder-decoder checkpoints ({model_dir.name}); use a "
                f"DATAxMODEL mesh (e.g. "
                f"{mesh_cfg.data}x{mesh_cfg.model * mesh_cfg.seq})")
        mesh = sharding.build_mesh(mesh_cfg)
        params = sharding.shard_params(params, cfg, mesh)
        if mesh_cfg.seq > 1:
            # Long-context: engine prefills seq-sharded (ring attention)
            # and decodes dense from the gathered cache.
            seq_mesh = mesh
        log.info(
            "sharded %s over mesh %s", model_dir.name,
            dict(zip(mesh.axis_names, mesh.devices.shape)),
        )

    log.info("loaded %s (%s, %s)", model_dir.name,
             "enc-dec" if encdec else "decoder", np.dtype(dtype).name)
    return ScoringEngine(
        params, cfg, tokenizer, runtime or RuntimeConfig(),
        encoder_decoder=encdec, seq_mesh=seq_mesh,
        spec_config=spec_config, governor_config=governor_config,
        cascade_config=cascade_config,
    )


def engine_factory(
    checkpoint_root: Path,
    runtime: Optional[RuntimeConfig] = None,
    mesh_cfg: Optional[MeshConfig] = None,
    cache_root: Optional[Path] = None,
    quantize_int8: bool = False,
    int8_dynamic: bool = False,
    kv_cache_int8: bool = False,
    spec_config=None,
    governor_config=None,
    cascade_config=None,
):
    """EngineFactory for engine.multi: maps an HF repo id to
    ``checkpoint_root/<org>__<name>`` or ``checkpoint_root/<name>``."""
    checkpoint_root = Path(checkpoint_root)

    def factory(model_name: str) -> ScoringEngine:
        candidates = [
            checkpoint_root / model_name.replace("/", "__"),
            checkpoint_root / model_name.split("/")[-1],
            checkpoint_root / model_name,
        ]
        for cand in candidates:
            if cand.is_dir():
                return load_engine(cand, runtime, mesh_cfg,
                                   cache_root=cache_root,
                                   quantize_int8=quantize_int8,
                                   int8_dynamic=int8_dynamic,
                                   kv_cache_int8=kv_cache_int8,
                                   spec_config=spec_config,
                                   governor_config=governor_config,
                                   cascade_config=cascade_config)
        raise FileNotFoundError(
            f"no local checkpoint for {model_name} under {checkpoint_root} "
            f"(tried {[str(c) for c in candidates]})"
        )

    return factory
