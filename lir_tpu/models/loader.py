"""HF checkpoint -> JAX pytree conversion.

The reference loads every model with ``AutoModelForCausalLM.from_pretrained``
+ bitsandbytes int8 (compare_base_vs_instruct.py:423-455). Here weights are
converted ONCE from the HF torch state_dict into the stacked-layer pytree that
``models/decoder.py`` / ``models/encdec.py`` consume (bf16 on TPU), then cached;
no torch on the hot path.

Conventions:
- All our projection matrices are (in_features, out_features); torch
  ``nn.Linear`` stores (out, in) and is transposed; GPT-2 ``Conv1D`` is
  already (in, out).
- Fused QKV layouts are de-interleaved per family (gpt-neox/bloom use
  head-major [q k v] interleave; falcon MQA appends single k/v rows).
- Layer params are stacked on a leading L axis for ``lax.scan``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Mapping, Tuple

import numpy as np
import jax.numpy as jnp

from .registry import ModelConfig, T5Config

Params = Dict[str, Any]


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().to("cpu")
        if t.dtype.__str__() == "torch.bfloat16":
            t = t.float()
        return t.numpy()
    return np.asarray(t)


class _SD:
    """State-dict view with transparent prefix stripping + numpy conversion."""

    def __init__(self, sd: Mapping[str, Any]):
        self.sd = dict(sd)

    def __call__(self, key: str) -> np.ndarray:
        if key in self.sd:
            return _np(self.sd[key])
        for pref in ("transformer.", "model.", "gpt_neox."):
            if pref + key in self.sd:
                return _np(self.sd[pref + key])
        raise KeyError(key)

    def has(self, key: str) -> bool:
        try:
            self(key)
            return True
        except KeyError:
            return False


def _lin(w: np.ndarray) -> np.ndarray:
    """torch Linear (out, in) -> ours (in, out)."""
    return np.ascontiguousarray(w.T)


def _stack(rows, dtype) -> jnp.ndarray:
    return jnp.asarray(np.stack(rows), dtype=dtype)


# ---------------------------------------------------------------------------
# Per-family layer extractors: (sd, cfg, i) -> dict of per-layer numpy arrays
# ---------------------------------------------------------------------------

def _split_qkv_headmajor(w: np.ndarray, b, H: int, hd: int):
    """gpt-neox / bloom fusion: rows are [h0:(q k v), h1:(q k v), ...].

    w: (3*H*hd, D) torch layout -> three (D, H*hd)."""
    D = w.shape[1]
    w3 = w.reshape(H, 3, hd, D)
    outs = []
    for j in range(3):
        outs.append(np.ascontiguousarray(w3[:, j].reshape(H * hd, D).T))
    if b is None:
        return outs, (None, None, None)
    b3 = b.reshape(H, 3, hd)
    bs = [np.ascontiguousarray(b3[:, j].reshape(H * hd)) for j in range(3)]
    return outs, bs


def _layer_gpt2(sd: _SD, cfg: ModelConfig, i: int) -> Dict[str, np.ndarray]:
    p = f"h.{i}."
    D = cfg.hidden_size
    ca_w = sd(p + "attn.c_attn.weight")          # Conv1D: (D, 3D) = (in, out)
    ca_b = sd(p + "attn.c_attn.bias")
    return {
        "ln1.scale": sd(p + "ln_1.weight"), "ln1.bias": sd(p + "ln_1.bias"),
        "wq": ca_w[:, :D], "wk": ca_w[:, D:2 * D], "wv": ca_w[:, 2 * D:],
        "bq": ca_b[:D], "bk": ca_b[D:2 * D], "bv": ca_b[2 * D:],
        "wo": sd(p + "attn.c_proj.weight"), "bo": sd(p + "attn.c_proj.bias"),
        "ln2.scale": sd(p + "ln_2.weight"), "ln2.bias": sd(p + "ln_2.bias"),
        "w_up": sd(p + "mlp.c_fc.weight"), "b_up": sd(p + "mlp.c_fc.bias"),
        "w_down": sd(p + "mlp.c_proj.weight"), "b_down": sd(p + "mlp.c_proj.bias"),
    }


def _layer_gptneox(sd: _SD, cfg: ModelConfig, i: int) -> Dict[str, np.ndarray]:
    p = f"layers.{i}."
    (wq, wk, wv), (bq, bk, bv) = _split_qkv_headmajor(
        sd(p + "attention.query_key_value.weight"),
        sd(p + "attention.query_key_value.bias"), cfg.n_heads, cfg.head_dim)
    return {
        "ln1.scale": sd(p + "input_layernorm.weight"),
        "ln1.bias": sd(p + "input_layernorm.bias"),
        "wq": wq, "wk": wk, "wv": wv, "bq": bq, "bk": bk, "bv": bv,
        "wo": _lin(sd(p + "attention.dense.weight")),
        "bo": sd(p + "attention.dense.bias"),
        "ln2.scale": sd(p + "post_attention_layernorm.weight"),
        "ln2.bias": sd(p + "post_attention_layernorm.bias"),
        "w_up": _lin(sd(p + "mlp.dense_h_to_4h.weight")),
        "b_up": sd(p + "mlp.dense_h_to_4h.bias"),
        "w_down": _lin(sd(p + "mlp.dense_4h_to_h.weight")),
        "b_down": sd(p + "mlp.dense_4h_to_h.bias"),
    }


def _layer_llama(sd: _SD, cfg: ModelConfig, i: int) -> Dict[str, np.ndarray]:
    p = f"layers.{i}."
    out = {
        "ln1.scale": sd(p + "input_layernorm.weight"),
        "wq": _lin(sd(p + "self_attn.q_proj.weight")),
        "wk": _lin(sd(p + "self_attn.k_proj.weight")),
        "wv": _lin(sd(p + "self_attn.v_proj.weight")),
        "wo": _lin(sd(p + "self_attn.o_proj.weight")),
        "ln2.scale": sd(p + "post_attention_layernorm.weight"),
        "w_gate": _lin(sd(p + "mlp.gate_proj.weight")),
        "w_up": _lin(sd(p + "mlp.up_proj.weight")),
        "w_down": _lin(sd(p + "mlp.down_proj.weight")),
    }
    if cfg.qkv_bias:  # qwen-style
        out.update({"bq": sd(p + "self_attn.q_proj.bias"),
                    "bk": sd(p + "self_attn.k_proj.bias"),
                    "bv": sd(p + "self_attn.v_proj.bias")})
    return out


def _layer_qwen1(sd: _SD, cfg: ModelConfig, i: int) -> Dict[str, np.ndarray]:
    """Qwen-v1 NATIVE tensor names (model_type "qwen", trust_remote_code
    family — reference loads it via remote code, compare_base_vs_instruct.py:
    421; we re-implement the mapping from the public modeling_qwen.py):

    - ``h.{i}.attn.c_attn``: fused qkv, torch Linear (3D, D), q|k|v blocks
      (NOT head-interleaved), WITH bias even though every other projection
      is bias-free (``no_bias`` exempts c_attn).
    - ``h.{i}.mlp.{w1,w2,c_proj}``: Qwen's MLP is ``c_proj(w1(x) *
      silu(w2(x)))`` — w2 is the GATE, w1 the up-projection; each is
      config.intermediate_size // 2 wide.
    - RMSNorm ``ln_1``/``ln_2``/``ln_f`` (scale only).

    Checkpoints already converted to llama-format names keep loading via
    the _layer_llama fallback in convert_decoder.
    """
    p = f"h.{i}."
    D = cfg.hidden_size
    ca = sd(p + "attn.c_attn.weight")  # (3D, D)
    cb = sd(p + "attn.c_attn.bias")
    return {
        "ln1.scale": sd(p + "ln_1.weight"),
        "wq": _lin(ca[:D]), "wk": _lin(ca[D:2 * D]), "wv": _lin(ca[2 * D:]),
        "bq": cb[:D], "bk": cb[D:2 * D], "bv": cb[2 * D:],
        "wo": _lin(sd(p + "attn.c_proj.weight")),
        "ln2.scale": sd(p + "ln_2.weight"),
        "w_gate": _lin(sd(p + "mlp.w2.weight")),
        "w_up": _lin(sd(p + "mlp.w1.weight")),
        "w_down": _lin(sd(p + "mlp.c_proj.weight")),
    }


def _layer_baichuan(sd: _SD, cfg: ModelConfig, i: int) -> Dict[str, np.ndarray]:
    """Baichuan2 packs qkv as W_pack (3D, D), q|k|v blocks (not interleaved)."""
    p = f"layers.{i}."
    D = cfg.hidden_size
    wp = sd(p + "self_attn.W_pack.weight")  # (3D, D)
    return {
        "ln1.scale": sd(p + "input_layernorm.weight"),
        "wq": _lin(wp[:D]), "wk": _lin(wp[D:2 * D]), "wv": _lin(wp[2 * D:]),
        "wo": _lin(sd(p + "self_attn.o_proj.weight")),
        "ln2.scale": sd(p + "post_attention_layernorm.weight"),
        "w_gate": _lin(sd(p + "mlp.gate_proj.weight")),
        "w_up": _lin(sd(p + "mlp.up_proj.weight")),
        "w_down": _lin(sd(p + "mlp.down_proj.weight")),
    }


def _layer_falcon(sd: _SD, cfg: ModelConfig, i: int) -> Dict[str, np.ndarray]:
    """falcon-7b MQA fusion: rows = [H query heads | 1 key head | 1 value head]."""
    p = f"h.{i}."
    H, hd = cfg.n_heads, cfg.head_dim
    w = sd(p + "self_attention.query_key_value.weight")  # ((H+2)*hd, D)
    wv3 = w.reshape(H + 2, hd, -1)
    wq = np.ascontiguousarray(wv3[:H].reshape(H * hd, -1).T)
    wk = np.ascontiguousarray(wv3[H].T)
    wv = np.ascontiguousarray(wv3[H + 1].T)
    return {
        "ln1.scale": sd(p + "input_layernorm.weight"),
        "ln1.bias": sd(p + "input_layernorm.bias"),
        "wq": wq, "wk": wk, "wv": wv,
        "wo": _lin(sd(p + "self_attention.dense.weight")),
        "w_up": _lin(sd(p + "mlp.dense_h_to_4h.weight")),
        "w_down": _lin(sd(p + "mlp.dense_4h_to_h.weight")),
    }


def _layer_bloom(sd: _SD, cfg: ModelConfig, i: int) -> Dict[str, np.ndarray]:
    p = f"h.{i}."
    (wq, wk, wv), (bq, bk, bv) = _split_qkv_headmajor(
        sd(p + "self_attention.query_key_value.weight"),
        sd(p + "self_attention.query_key_value.bias"), cfg.n_heads, cfg.head_dim)
    return {
        "ln1.scale": sd(p + "input_layernorm.weight"),
        "ln1.bias": sd(p + "input_layernorm.bias"),
        "wq": wq, "wk": wk, "wv": wv, "bq": bq, "bk": bk, "bv": bv,
        "wo": _lin(sd(p + "self_attention.dense.weight")),
        "bo": sd(p + "self_attention.dense.bias"),
        "ln2.scale": sd(p + "post_attention_layernorm.weight"),
        "ln2.bias": sd(p + "post_attention_layernorm.bias"),
        "w_up": _lin(sd(p + "mlp.dense_h_to_4h.weight")),
        "b_up": sd(p + "mlp.dense_h_to_4h.bias"),
        "w_down": _lin(sd(p + "mlp.dense_4h_to_h.weight")),
        "b_down": sd(p + "mlp.dense_4h_to_h.bias"),
    }


def _layer_opt(sd: _SD, cfg: ModelConfig, i: int) -> Dict[str, np.ndarray]:
    p = f"decoder.layers.{i}."
    return {
        "ln1.scale": sd(p + "self_attn_layer_norm.weight"),
        "ln1.bias": sd(p + "self_attn_layer_norm.bias"),
        "wq": _lin(sd(p + "self_attn.q_proj.weight")),
        "bq": sd(p + "self_attn.q_proj.bias"),
        "wk": _lin(sd(p + "self_attn.k_proj.weight")),
        "bk": sd(p + "self_attn.k_proj.bias"),
        "wv": _lin(sd(p + "self_attn.v_proj.weight")),
        "bv": sd(p + "self_attn.v_proj.bias"),
        "wo": _lin(sd(p + "self_attn.out_proj.weight")),
        "bo": sd(p + "self_attn.out_proj.bias"),
        "ln2.scale": sd(p + "final_layer_norm.weight"),
        "ln2.bias": sd(p + "final_layer_norm.bias"),
        "w_up": _lin(sd(p + "fc1.weight")), "b_up": sd(p + "fc1.bias"),
        "w_down": _lin(sd(p + "fc2.weight")), "b_down": sd(p + "fc2.bias"),
    }


_LAYER_FNS: Dict[str, Callable[[_SD, ModelConfig, int], Dict[str, np.ndarray]]] = {
    "gpt2": _layer_gpt2, "gpt_neox": _layer_gptneox, "llama": _layer_llama,
    "mistral": _layer_llama, "qwen2": _layer_llama, "qwen": _layer_qwen1,
    "qwen_llama": _layer_llama,
    "baichuan": _layer_baichuan, "falcon": _layer_falcon,
    "RefinedWebModel": _layer_falcon, "bloom": _layer_bloom, "opt": _layer_opt,
}

_EMBED_KEYS = {
    "gpt2": "wte.weight", "gpt_neox": "embed_in.weight",
    "llama": "embed_tokens.weight", "mistral": "embed_tokens.weight",
    "qwen2": "embed_tokens.weight", "qwen": "wte.weight",
    "qwen_llama": "embed_tokens.weight",
    "baichuan": "embed_tokens.weight",
    "falcon": "word_embeddings.weight", "RefinedWebModel": "word_embeddings.weight",
    "bloom": "word_embeddings.weight", "opt": "decoder.embed_tokens.weight",
}

_FINAL_LN = {
    "gpt2": ("ln_f.weight", "ln_f.bias"),
    "gpt_neox": ("final_layer_norm.weight", "final_layer_norm.bias"),
    "llama": ("norm.weight", None), "mistral": ("norm.weight", None),
    "qwen2": ("norm.weight", None), "qwen": ("ln_f.weight", None),
    "qwen_llama": ("norm.weight", None),
    "baichuan": ("norm.weight", None),
    "falcon": ("ln_f.weight", "ln_f.bias"),
    "RefinedWebModel": ("ln_f.weight", "ln_f.bias"),
    "bloom": ("ln_f.weight", "ln_f.bias"),
    "opt": ("decoder.final_layer_norm.weight", "decoder.final_layer_norm.bias"),
}


def convert_decoder(state_dict: Mapping[str, Any], cfg: ModelConfig,
                    family: str, dtype=jnp.float32) -> Params:
    """Build the stacked-layer pytree `models/decoder.py` expects."""
    sd = _SD(state_dict)
    if family == "qwen" and not sd.has("h.0.attn.c_attn.weight"):
        # A Qwen-v1 checkpoint pre-converted to llama-format names.
        family = "qwen_llama"
    layer_fn = _LAYER_FNS[family]
    rows = [layer_fn(sd, cfg, i) for i in range(cfg.n_layers)]

    layers: Params = {}
    for key in rows[0]:
        stacked = _stack([r[key] for r in rows], dtype)
        if "." in key:  # "ln1.scale" -> layers["ln1"]["scale"]
            a, b = key.split(".")
            layers.setdefault(a, {})[b] = stacked
        else:
            layers[key] = stacked

    params: Params = {"tok_embed": jnp.asarray(sd(_EMBED_KEYS[family]), dtype),
                      "layers": layers}

    if cfg.pos_embedding == "learned":
        pk = {"gpt2": "wpe.weight", "opt": "decoder.embed_positions.weight"}[family]
        params["pos_embed"] = jnp.asarray(sd(pk), dtype)
    if cfg.embedding_norm:
        params["embed_ln"] = {
            "scale": jnp.asarray(sd("word_embeddings_layernorm.weight"), dtype),
            "bias": jnp.asarray(sd("word_embeddings_layernorm.bias"), dtype)}
    if cfg.final_norm:
        wkey, bkey = _FINAL_LN[family]
        fl = {"scale": jnp.asarray(sd(wkey), dtype)}
        if bkey is not None:
            fl["bias"] = jnp.asarray(sd(bkey), dtype)
        params["final_ln"] = fl
    if not cfg.tie_embeddings:
        for head_key in ("embed_out.weight", "lm_head.weight"):
            if sd.has(head_key):
                params["lm_head"] = jnp.asarray(_lin(sd(head_key)), dtype)
                break
        else:
            raise KeyError("untied lm head not found in state dict")
    return params


def convert_t5(state_dict: Mapping[str, Any], cfg: T5Config,
               dtype=jnp.float32) -> Params:
    sd = _SD(state_dict)

    def stack_block(side: str, cross: bool) -> Params:
        rows = []
        for i in range(cfg.n_layers):
            p = f"{side}.block.{i}."
            row = {
                "ln_attn": sd(p + "layer.0.layer_norm.weight"),
                "wq": _lin(sd(p + "layer.0.SelfAttention.q.weight")),
                "wk": _lin(sd(p + "layer.0.SelfAttention.k.weight")),
                "wv": _lin(sd(p + "layer.0.SelfAttention.v.weight")),
                "wo": _lin(sd(p + "layer.0.SelfAttention.o.weight")),
            }
            mlp_idx = 2 if cross else 1
            if cross:
                row.update({
                    "ln_cross": sd(p + "layer.1.layer_norm.weight"),
                    "cq": _lin(sd(p + "layer.1.EncDecAttention.q.weight")),
                    "ck": _lin(sd(p + "layer.1.EncDecAttention.k.weight")),
                    "cv": _lin(sd(p + "layer.1.EncDecAttention.v.weight")),
                    "co": _lin(sd(p + "layer.1.EncDecAttention.o.weight")),
                })
            m = f"{p}layer.{mlp_idx}."
            row["ln_mlp"] = sd(m + "layer_norm.weight")
            if cfg.gated_mlp:
                row["wi_0"] = _lin(sd(m + "DenseReluDense.wi_0.weight"))
                row["wi_1"] = _lin(sd(m + "DenseReluDense.wi_1.weight"))
            else:
                row["wi"] = _lin(sd(m + "DenseReluDense.wi.weight"))
            row["wo_mlp"] = _lin(sd(m + "DenseReluDense.wo.weight"))
            rows.append(row)
        return {k: _stack([r[k] for r in rows], dtype) for k in rows[0]}

    params: Params = {
        "shared_embed": jnp.asarray(sd("shared.weight"), dtype),
        "enc_rel_embed": jnp.asarray(
            sd("encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"),
            dtype),
        "dec_rel_embed": jnp.asarray(
            sd("decoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"),
            dtype),
        "encoder": stack_block("encoder", cross=False),
        "enc_final_ln": jnp.asarray(sd("encoder.final_layer_norm.weight"), dtype),
        "decoder": stack_block("decoder", cross=True),
        "dec_final_ln": jnp.asarray(sd("decoder.final_layer_norm.weight"), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = jnp.asarray(_lin(sd("lm_head.weight")), dtype)
    return params


# ---------------------------------------------------------------------------
# HF-config adapters
# ---------------------------------------------------------------------------

def config_from_hf(hf_cfg) -> Tuple[ModelConfig, str]:
    """Map a transformers PretrainedConfig to (ModelConfig, family)."""
    mt = hf_cfg.model_type
    g = lambda *names, d=None: next(
        (getattr(hf_cfg, n) for n in names if getattr(hf_cfg, n, None) is not None), d)
    common = dict(
        name=getattr(hf_cfg, "name_or_path", mt) or mt,
        vocab_size=hf_cfg.vocab_size,
        hidden_size=g("hidden_size", "n_embd", "d_model"),
        n_layers=g("num_hidden_layers", "n_layer", "num_layers"),
        n_heads=g("num_attention_heads", "n_head"),
        max_seq_len=g("max_position_embeddings", "n_positions", "seq_length", d=2048),
    )
    if mt == "gpt2":
        return ModelConfig(**common, intermediate_size=4 * common["hidden_size"],
                           pos_embedding="learned", norm="layernorm",
                           norm_eps=hf_cfg.layer_norm_epsilon, activation="gelu_new",
                           gated_mlp=False, qkv_bias=True, attn_out_bias=True,
                           mlp_bias=True,
                           # HF GPT-2 defaults to tied embeddings, but the
                           # config is authoritative: an untied checkpoint
                           # carries a real lm_head.weight that MUST be
                           # used (scoring through wte^T instead silently
                           # rewrites every logit).
                           tie_embeddings=bool(getattr(
                               hf_cfg, "tie_word_embeddings", True))), "gpt2"
    if mt == "gpt_neox":
        return ModelConfig(**common, intermediate_size=hf_cfg.intermediate_size,
                           pos_embedding="rotary", rotary_pct=hf_cfg.rotary_pct,
                           rope_theta=getattr(hf_cfg, "rotary_emb_base", 10000.0),
                           norm="layernorm", norm_eps=hf_cfg.layer_norm_eps,
                           activation="gelu", gated_mlp=False,
                           parallel_block=hf_cfg.use_parallel_residual,
                           qkv_bias=True, attn_out_bias=True, mlp_bias=True), "gpt_neox"
    if mt in ("llama", "mistral", "qwen2", "baichuan"):
        return ModelConfig(**common, intermediate_size=hf_cfg.intermediate_size,
                           n_kv_heads=g("num_key_value_heads"),
                           rope_theta=g("rope_theta", d=10000.0),
                           norm_eps=hf_cfg.rms_norm_eps,
                           qkv_bias=(mt == "qwen2" and getattr(
                               hf_cfg, "attention_bias", False)) or bool(
                               getattr(hf_cfg, "use_bias", False)),
                           tie_embeddings=bool(getattr(hf_cfg, "tie_word_embeddings",
                                                       False))), mt
    if mt == "qwen":
        # Qwen-v1 (trust_remote_code upstream): RMSNorm, rotary, fused-qkv
        # bias; config.intermediate_size counts BOTH mlp halves — the public
        # modeling_qwen.py sets ff_dim = intermediate_size // 2 per
        # projection (see _layer_qwen1). no_bias=False checkpoints would
        # carry c_proj/mlp biases _layer_qwen1 does not read — refuse them
        # loudly rather than load silently-wrong weights.
        if not getattr(hf_cfg, "no_bias", True):
            raise ValueError(
                "Qwen-v1 with no_bias=False (biased c_proj/mlp) is not "
                "supported by the native mapping")
        return ModelConfig(**common,
                           intermediate_size=hf_cfg.intermediate_size // 2,
                           rope_theta=g("rotary_emb_base", d=10000.0),
                           norm_eps=g("layer_norm_epsilon", d=1e-6),
                           qkv_bias=True,
                           tie_embeddings=bool(getattr(
                               hf_cfg, "tie_word_embeddings", False))), "qwen"
    if mt in ("falcon", "RefinedWebModel"):
        return ModelConfig(**common, intermediate_size=4 * common["hidden_size"],
                           n_kv_heads=1 if g("multi_query", d=True) else common["n_heads"],
                           pos_embedding="rotary", norm="layernorm",
                           norm_eps=hf_cfg.layer_norm_epsilon,
                           activation="gelu", gated_mlp=False, parallel_block=True,
                           shared_block_ln=True, tie_embeddings=True), "falcon"
    if mt == "bloom":
        return ModelConfig(**common, intermediate_size=4 * common["hidden_size"],
                           pos_embedding="alibi", norm="layernorm",
                           norm_eps=hf_cfg.layer_norm_epsilon, activation="gelu_new",
                           gated_mlp=False, embedding_norm=True, qkv_bias=True,
                           attn_out_bias=True, mlp_bias=True,
                           tie_embeddings=True), "bloom"
    if mt == "opt":
        return ModelConfig(**common, intermediate_size=hf_cfg.ffn_dim,
                           pos_embedding="learned", learned_pos_offset=2,
                           norm="layernorm", activation="relu", gated_mlp=False,
                           qkv_bias=True, attn_out_bias=True, mlp_bias=True,
                           tie_embeddings=True), "opt"
    raise ValueError(f"unsupported model_type {mt!r}")


def t5_config_from_hf(hf_cfg) -> T5Config:
    return T5Config(
        name=getattr(hf_cfg, "name_or_path", "t5") or "t5",
        vocab_size=hf_cfg.vocab_size, hidden_size=hf_cfg.d_model,
        n_layers=hf_cfg.num_layers, n_heads=hf_cfg.num_heads,
        head_dim=hf_cfg.d_kv, intermediate_size=hf_cfg.d_ff,
        norm_eps=hf_cfg.layer_norm_epsilon,
        relative_attention_num_buckets=hf_cfg.relative_attention_num_buckets,
        relative_attention_max_distance=getattr(
            hf_cfg, "relative_attention_max_distance", 128),
        gated_mlp="gated" in hf_cfg.feed_forward_proj,
        activation="gelu_new" if "gelu" in hf_cfg.feed_forward_proj else "relu",
        tie_embeddings=bool(hf_cfg.tie_word_embeddings),
        decoder_start_token_id=hf_cfg.decoder_start_token_id,
    )
