"""Paged KV allocator: one block-granular page pool under every KV path.

vLLM's PagedAttention observation (Kwon et al., 2023) applied to this
engine's cache layout: KV for a token prefix is stored in fixed-size
PAGES of ``page_size`` positions, device-resident in one pool tensor per
cache leaf, so a prefix computed once can back any later dispatch that
shares it — across requests, batches, and (offline) bucket queues. The
radix index over which token sequence owns which pages lives in
engine/prefix_tree.py; this module is the allocator itself:

- **Pool layout.** The decode cache is a pytree of (L, K, T, B, hd)
  leaves (int8 flavor adds (L, K, T, B) scales) — models/cache.py. The
  pool stores the same leaves with the (T, B) plane replaced by
  (n_pages, page_size): page p holds ``page_size`` consecutive token
  POSITIONS of one cached prefix, in canonical position space (position
  0 = the prefix's first token), so reuse is independent of which
  dispatch happened to produce the KV.
- **Gather/scatter.** :func:`gather_slots` assembles a dense dispatch
  cache from a per-(row, slot) source table — SLOT granular, so cached
  pages land at exactly the slots the unpaged left-padded prefill would
  have written them to (that exact-layout discipline is what makes paged
  results BITWISE-identical to the contiguous-cache path; see
  generate._paged_prefix). :func:`scatter_pages` extracts full pages out
  of a dispatch's final cache into the pool, with the pool DONATED so
  the update aliases in place — one persistent HBM block for the whole
  session, the same donation discipline the dispatch cache chain uses.
- **Refcounts.** Host-side per-page refcounts (never negative — pinned
  by tests): the radix tree holds one reference per cached page, every
  in-flight dispatch holds one more per page it gathered, and eviction
  (LRU, driven by the tree) may only free pages whose sole reference is
  the tree's — a page under an in-flight dispatch is unevictable by
  construction.
- **Handoff.** :class:`CacheHandoff` (moved here from engine/runner.py)
  is the cross-dispatch donation chain for the dense dispatch caches —
  the third KV ownership scheme, now co-owned by the one allocator
  module so pool pages and dispatch scratch follow the same rules.

Page 0 is reserved as a trash page: slot-table entries that carry no
cached KV point at its (all-zero) positions — the gathered slots are
masked, and masked attention contributions are exact zeros, the same
exact zeros the left-padded prefill's masked pad slots contribute — and
scatter padding writes land there too.
"""

from __future__ import annotations

import functools
from typing import Any, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..utils.logging import get_logger

log = get_logger(__name__)

# Cache-leaf axis convention (models/cache.py): (L, K, T, B, hd) payloads,
# (L, K, T, B) int8 scales — time axis 2, batch axis 3 in both flavors.
TIME_AXIS = 2
DEFAULT_PAGE_SIZE = 16


def kv_page_bytes(cfg, page_size: int = DEFAULT_PAGE_SIZE,
                  dtype_bytes: int = 2) -> int:
    """HBM bytes of ONE pool page — the unit DEPLOY.md §1g's pool-sizing
    arithmetic multiplies by ``n_pages``. Mirrors models/cache.
    kv_cache_bytes at (batch=1, max_len=page_size)."""
    per_side = cfg.n_layers * cfg.n_kv_heads * page_size
    if getattr(cfg, "kv_cache_int8", False):
        return 2 * (per_side * cfg.head_dim + per_side * 4)
    return 2 * per_side * cfg.head_dim * dtype_bytes


def window_edges(bucket: int, page_size: int = DEFAULT_PAGE_SIZE
                 ) -> Tuple[int, ...]:
    """Remainder-window shapes a paged dispatch at ``bucket`` may run:
    powers of two from one page up to (exclusive) the bucket itself.
    Every warm dispatch recomputes a ``window``-wide slice of its rows'
    prefixes, anchored at the dispatch's LONGEST REAL ROW (the uncached
    tails, plus however much of the cached prefix the window overlaps;
    the anchor is a traced scalar, so it costs no extra executables),
    and gathers everything before the window from the pool; a needed
    window >= bucket means nothing useful is cached and the dispatch
    runs the plain unpaged prefill instead."""
    out = []
    w = max(int(page_size), 8)
    while w < bucket:
        out.append(w)
        w *= 2
    return tuple(out)


def pick_window(needed: int, bucket: int,
                page_size: int = DEFAULT_PAGE_SIZE) -> Optional[int]:
    """Smallest window edge covering ``needed`` recompute tokens, or None
    when only the full-bucket (unpaged) prefill covers it."""
    for w in window_edges(bucket, page_size):
        if w >= needed:
            return w
    return None


def _pool_leaf_shape(leaf_shape: Tuple[int, ...], n_pages: int,
                     page_size: int) -> Tuple[int, ...]:
    """Cache leaf (L, K, T, B[, hd]) -> pool leaf (L, K, P, ps[, hd])."""
    return leaf_shape[:2] + (n_pages, page_size) + leaf_shape[4:]


def gather_slots(pool: Any, slot_src) -> Any:
    """Assemble a dense decode cache from the pool at SLOT granularity:
    ``slot_src`` (B, S) int32 indexes the pool's flattened
    (n_pages * page_size) position axis — entry (r, s) says which pool
    position fills cache slot ``s`` of row ``r``. Unfilled slots point
    at the reserved trash page 0 (exact zeros; they are masked anyway).
    Returns (L, K, S, B[, hd]) leaves — the dense cache layout at
    ``S`` slots. Traced inline by the paged decode entry points
    (engine/generate.py), so XLA fuses the gather with the first
    consumer."""
    import jax.numpy as jnp

    def leaf(p):
        ps = p.shape[3]
        flat = p.reshape(p.shape[:2] + (p.shape[2] * ps,) + p.shape[4:])
        x = flat[:, :, slot_src]                    # (L, K, B, S[, hd])
        return jnp.moveaxis(x, 2, 3)                # (L, K, S, B[, hd])

    return jax.tree.map(leaf, pool)


@functools.partial(jax.jit, donate_argnames=("pool",))
def scatter_pages(pool: Any, cache: Any, page_ids, rows, slot_idx) -> Any:
    """Write full pages extracted from a dispatch's final cache into the
    pool: page ``page_ids[j]`` receives cache slots ``slot_idx[j]`` of
    batch row ``rows[j]``, for every leaf. The pool is DONATED so XLA
    updates the one resident buffer in place. Padding entries (the
    caller pads the write list to a stable power-of-two shape) all
    target the reserved trash page 0."""
    def leaf(p, c):
        blocks = c[:, :, slot_idx, rows[:, None]]   # (L, K, N, ps[, hd])
        return p.at[:, :, page_ids].set(blocks)

    return jax.tree.map(leaf, pool, cache)


@jax.jit
def extract_pages(pool: Any, page_ids) -> Any:
    """Gather whole pages out of the pool for migration (serve/migrate):
    returns (L, K, N, ps[, hd]) blocks per leaf, ``page_ids`` (N,) int32.
    Padding entries (callers pad to a stable chunk shape) target the
    reserved trash page 0 — their blocks are dead bytes the import side
    drops. Read-only on the pool: a migration export can never disturb
    the donation discipline of the scatter path."""
    return jax.tree.map(lambda p: p[:, :, page_ids], pool)


@functools.partial(jax.jit, donate_argnames=("pool",))
def insert_pages(pool: Any, blocks: Any, page_ids) -> Any:
    """Write migrated page blocks into the pool: page ``page_ids[j]``
    receives ``blocks[..., j, ...]`` for every leaf — the import-side
    sibling of :func:`scatter_pages`, taking blocks that arrived over
    the wire instead of slots of a local dispatch cache. The pool is
    DONATED (in-place update of the one resident buffer); padding
    entries target the trash page 0, whose contents are masked out of
    every gather anyway."""
    def leaf(p, b):
        return p.at[:, :, page_ids].set(b)

    return jax.tree.map(leaf, pool, blocks)


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


class KVPagePool:
    """Device-resident page pool + host-side free list and refcounts.

    The device pytree (``leaves``) materializes lazily from the first
    cache tree (or aval tree) it sees — that is the one place the leaf
    structure/dtypes (bf16 vs int8 payload+scale) are authoritative, so
    the pool can never disagree with the engine's actual cache flavor.
    """

    def __init__(self, n_pages: int, page_size: int = DEFAULT_PAGE_SIZE,
                 stats=None):
        if n_pages < 2:
            raise ValueError("KVPagePool needs >= 2 pages (page 0 is the "
                             "reserved trash page)")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self.stats = stats
        self.leaves: Optional[Any] = None
        self.refcount = np.zeros(self.n_pages, np.int64)
        self.refcount[0] = 1            # trash page: never allocated/freed
        self._free: List[int] = list(range(self.n_pages - 1, 0, -1))

    # -- device side ---------------------------------------------------------

    def ensure(self, cache_like: Any) -> None:
        """Materialize the pool leaves to match ``cache_like`` (a cache
        pytree OR a ShapeDtypeStruct tree of one). Idempotent."""
        if self.leaves is not None:
            return
        import jax.numpy as jnp

        self.leaves = jax.tree.map(
            lambda a: jnp.zeros(
                _pool_leaf_shape(tuple(a.shape), self.n_pages,
                                 self.page_size), a.dtype),
            cache_like)
        log.info("KV page pool materialized: %d pages x %d tokens",
                 self.n_pages, self.page_size)

    def scatter(self, cache: Any, writes: Sequence[Tuple[int, int, int]]
                ) -> None:
        """Apply ``writes`` = [(page_id, batch_row, start_slot), ...]:
        page_id <- cache[:, :, start_slot : start_slot + page_size, row].
        Pads the list to a power of two (trash-page writes) so the jitted
        scatter keeps a small, stable set of shapes."""
        if not writes:
            return
        self.ensure(cache)
        n = _pow2(len(writes))
        pages = np.zeros((n,), np.int32)
        rows = np.zeros((n,), np.int32)
        starts = np.zeros((n,), np.int32)
        for j, (pg, row, start) in enumerate(writes):
            pages[j], rows[j], starts[j] = pg, row, start
        slot_idx = starts[:, None] + np.arange(self.page_size,
                                               dtype=np.int32)[None, :]
        import jax.numpy as jnp

        self.leaves = scatter_pages(self.leaves, cache, jnp.asarray(pages),
                                    jnp.asarray(rows), jnp.asarray(slot_idx))

    def extract(self, page_ids: Sequence[int], pad_to: int = 0) -> Any:
        """Device blocks for ``page_ids`` (migration export leg). The id
        list is padded to ``pad_to`` (or the next power of two) with
        trash-page entries so chunked exports keep one executable per
        chunk shape. Returns the (L, K, N, ps[, hd]) block tree; the
        call is async — the caller overlaps the device->host fetch."""
        assert self.leaves is not None, "extract before ensure()"
        import jax.numpy as jnp

        n = max(pad_to, _pow2(len(page_ids)))
        ids = np.zeros((n,), np.int32)
        ids[:len(page_ids)] = np.asarray(page_ids, np.int32)
        return extract_pages(self.leaves, jnp.asarray(ids))

    def insert(self, blocks: Any, page_ids: Sequence[int]) -> None:
        """Land migrated blocks at ``page_ids`` (import leg). ``blocks``
        may be padded wider than the id list (the export side's stable
        chunk shape); extra entries are steered to the trash page."""
        assert self.leaves is not None, "insert before ensure()"
        import jax.numpy as jnp

        n = jax.tree.leaves(blocks)[0].shape[2]
        assert n >= len(page_ids), "blocks narrower than the id list"
        ids = np.zeros((n,), np.int32)
        ids[:len(page_ids)] = np.asarray(page_ids, np.int32)
        self.leaves = insert_pages(self.leaves, blocks, jnp.asarray(ids))

    def page_nbytes(self) -> int:
        """HBM bytes of ONE page across every leaf (0 before ensure) —
        the per-page unit MigrationStats.bytes_streamed counts."""
        if self.leaves is None:
            return 0
        return self.nbytes // self.n_pages

    # -- host-side allocator -------------------------------------------------

    @property
    def nbytes(self) -> int:
        """HBM bytes of the materialized pool leaves (0 before
        :meth:`ensure`) — the figure the HBM governor's ledger carries
        for the whole page reservation (engine/hbm.py)."""
        if self.leaves is None:
            return 0
        import numpy as np

        return sum(int(leaf.size) * int(np.dtype(leaf.dtype).itemsize)
                   for leaf in jax.tree.leaves(self.leaves))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.n_pages - 1 - len(self._free)

    def alloc(self) -> Optional[int]:
        """One free page id, or None when exhausted (the caller evicts
        through the radix tree and retries — the pool itself has no idea
        which pages are coldest)."""
        if not self._free:
            return None
        page = self._free.pop()
        assert self.refcount[page] == 0, "allocated a referenced page"
        return page

    def incref(self, pages: Sequence[int]) -> None:
        for p in pages:
            self.refcount[p] += 1

    def decref(self, pages: Sequence[int]) -> None:
        """Drop one reference per page; a page reaching zero returns to
        the free list. The count can never go negative — that would mean
        a double free, which is a bug worth crashing on."""
        for p in pages:
            self.refcount[p] -= 1
            assert self.refcount[p] >= 0, f"page {p} refcount went negative"
            if self.refcount[p] == 0:
                self._free.append(int(p))


class CacheHandoff:
    """Cross-dispatch KV-cache buffer reuse via donation (the dense
    dispatch caches, as opposed to the pool's cached-prefix pages).

    The fused decode entry points can return their final cache and accept
    the previous dispatch's cache as a DONATED scratch argument
    (generate: ``return_cache``/``scratch_cache``); XLA then writes the
    new dispatch's cache into the donated buffer, so one HBM block serves
    every same-shape dispatch of a bucket queue instead of an alloc/free
    per dispatch. A key change drops the old buffer (freed once its last
    dispatch completes) and the next shape bootstraps fresh. ``take()``
    removes the cache BEFORE the call so a dispatch that raises (OOM
    fallback) can never re-donate a consumed buffer.

    ``key`` must determine every cache-shape input (kind, bucket, batch,
    suffix buckets, decode budget) — the scheduler plans those per bucket
    precisely so consecutive dispatches share a key. Paged and unpaged
    dispatches of one (bucket, batch) share a key ON PURPOSE: the
    exact-layout paged path returns a cache of the identical shape, so
    the donation chain runs unbroken across cold (unpaged) and warm
    (paged) dispatches of a bucket queue.
    """

    def __init__(self) -> None:
        self._key = None
        self._cache = None

    @property
    def pending(self) -> bool:
        """True while a parked cache buffer is held (the HBM governor's
        reclaim path frees it under OOM — engine/hbm.py)."""
        return self._cache is not None

    def take(self, key: Tuple):
        cache, k = self._cache, self._key
        self._cache = self._key = None
        return cache if k == key else None

    def put(self, key: Tuple, cache) -> None:
        self._key = key
        self._cache = cache
