"""Architecture registry: one ModelConfig dataclass covers every decoder-only
family the reference sweeps (reference: analysis/compare_base_vs_instruct.py:136-180,
analysis/compare_instruct_models.py:145-166) plus the T5 encoder-decoder branch
(routing rule "t5|t0|tk-instruct -> Seq2Seq", compare_instruct_models.py:471-475).

Instead of one torch class per HF repo (the reference relies on
``AutoModelForCausalLM`` + ``trust_remote_code``), we describe each family by a
small set of orthogonal architectural knobs and run them all through a single
functional JAX forward (models/decoder.py). trust_remote_code families (Qwen,
Baichuan) are re-implemented via these knobs, not remote code.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Unified decoder-only transformer description.

    Defaults are Llama-style; presets below override per family.
    """

    name: str = "unnamed"
    vocab_size: int = 32000
    hidden_size: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: Optional[int] = None      # None -> MHA (= n_heads); 1 -> MQA (falcon)
    head_dim: Optional[int] = None        # None -> hidden_size // n_heads
    intermediate_size: int = 11008
    max_seq_len: int = 2048

    # Position encoding
    pos_embedding: str = "rotary"         # "rotary" | "learned" | "alibi"
    rotary_pct: float = 1.0               # gpt-neox/pythia: 0.25
    rope_theta: float = 10000.0
    learned_pos_offset: int = 0           # OPT: positions start at 2

    # Normalization
    norm: str = "rmsnorm"                 # "rmsnorm" | "layernorm"
    norm_eps: float = 1e-5
    embedding_norm: bool = False          # bloom: LayerNorm right after embedding
    final_norm: bool = True

    # Block structure
    parallel_block: bool = False          # gpt-neox/falcon: h = x + attn(ln1 x) + mlp(ln2 x)
    shared_block_ln: bool = False         # falcon-7b: one LN feeds both attn and mlp

    # MLP
    activation: str = "silu"              # "silu" | "gelu" | "gelu_new" | "relu"
    gated_mlp: bool = True                # llama/mistral/qwen: silu(gate) * up

    # Biases
    qkv_bias: bool = False
    attn_out_bias: bool = False
    mlp_bias: bool = False

    # Output head
    tie_embeddings: bool = False
    logit_softcap: Optional[float] = None

    # Attention backend: route full-sequence self-attention through the
    # Pallas flash kernel (O(S*hd) memory) instead of the dense score
    # matrix. ALiBi (bloom) rides the kernel via per-head slopes; decode
    # steps and non-block-divisible sequences fall back dense.
    use_flash_attention: bool = False

    # Decode attention backend: route single-query KV-cached decode steps
    # through the fused Pallas flash-decode kernel (ops/flash_decode.py —
    # K-split online softmax + log-sum-exp combine, scores never leave
    # VMEM) instead of the dense score-row lowering. Default ON; engages
    # only where Pallas lowers (TPU; CPU keeps the dense path unless the
    # interpreter test hook is set) and only for the non-int8 cache (the
    # int8 cache has its own fused s8-dot path). RuntimeConfig.
    # fused_decode / --no-fused-decode opt out, restoring the dense
    # decode path exactly.
    fused_decode: bool = True

    # Cascade decode (ops/flash_decode.flash_decode_trunk): shared-trunk
    # dispatches compute the trunk's split-K decode partials ONCE per kv
    # head for ALL rows' queries (the trunk K/V tiles stream from HBM
    # once per step instead of once per row), per-row suffix splits run
    # the flat kernel's path over only the tail, merged by ops/lse —
    # bitwise the flat kernel by construction. Static so the decode
    # executables specialize on it; mirrored from RuntimeConfig.
    # cascade_decode / --no-cascade-decode, which restores the flat
    # kernel exactly (the trunk extent is then pinned to 0).
    cascade_decode: bool = True

    # Fused cascade-prefill suffix leg (ops/cascade_prefill): prefix +
    # suffix + log-sum-exp merge in ONE Pallas launch, no HBM round-trip
    # for the partial (o, m, l) triples. Bitwise the two-leg path on the
    # cascade matrix; RuntimeConfig.cascade_fused_suffix /
    # --no-cascade-fused-suffix restores the two-leg lowering exactly.
    cascade_fused_suffix: bool = True

    # KV-cache storage: int8 with per-(head, position, row) scales halves
    # cache HBM (the single-chip long-context limiter — a 7B's bf16 cache
    # plus XLA's while-loop copy OOMs v5e at seq 1024, SCALE.md) and
    # halves decode-phase cache reads. Decode attention then runs s8 x s8
    # dots with dynamic query/probability quantization, mirroring the
    # dynamic int8 weight mode. Prefill attention is unaffected (it reads
    # the pre-quantization k/v). Opt-in; measured accuracy in tests.
    kv_cache_int8: bool = False

    def __post_init__(self) -> None:
        if self.head_dim is None:
            object.__setattr__(self, "head_dim", self.hidden_size // self.n_heads)
        if self.n_kv_heads is None:
            object.__setattr__(self, "n_kv_heads", self.n_heads)
        assert self.pos_embedding in ("rotary", "learned", "alibi"), self.pos_embedding
        assert self.norm in ("rmsnorm", "layernorm"), self.norm
        assert self.activation in ("silu", "gelu", "gelu_new", "relu"), self.activation
        # shared_block_ln reuses the attention LN for the MLP, which only
        # exists in falcon-style PARALLEL blocks; a sequential block with
        # it set would KeyError('ln2') deep inside the first forward trace.
        assert not (self.shared_block_ln and not self.parallel_block), (
            "shared_block_ln=True requires parallel_block=True "
            f"({self.name})")

    @property
    def rotary_dim(self) -> int:
        return int(self.head_dim * self.rotary_pct)


@dataclasses.dataclass(frozen=True)
class T5Config:
    """Encoder-decoder (T5 v1.1 / flan-t5 / T0 / tk-instruct) description."""

    name: str = "t5"
    vocab_size: int = 32128
    hidden_size: int = 512                # d_model
    n_layers: int = 8                     # per stack
    n_heads: int = 6
    head_dim: int = 64                    # d_kv (NOT hidden/heads for t5 v1.1)
    intermediate_size: int = 1024         # d_ff
    norm_eps: float = 1e-6
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    gated_mlp: bool = True                # v1.1: gelu-gated; v1.0: relu non-gated
    activation: str = "gelu_new"
    tie_embeddings: bool = False          # v1.1: untied lm_head
    decoder_start_token_id: int = 0


# ---------------------------------------------------------------------------
# Family presets — shapes are the real HF configs for the reference model zoo.
# ---------------------------------------------------------------------------

def gpt2(size: str = "small") -> ModelConfig:
    dims = {"small": (768, 12, 12), "medium": (1024, 24, 16), "large": (1280, 36, 20),
            "xl": (1600, 48, 25)}[size]
    d, l, h = dims
    return ModelConfig(
        name=f"gpt2-{size}", vocab_size=50257, hidden_size=d, n_layers=l, n_heads=h,
        intermediate_size=4 * d, max_seq_len=1024, pos_embedding="learned",
        norm="layernorm", activation="gelu_new", gated_mlp=False,
        qkv_bias=True, attn_out_bias=True, mlp_bias=True, tie_embeddings=True,
    )


def gptneox(name: str = "pythia-6.9b", *, hidden: int = 4096, layers: int = 32,
            heads: int = 32, vocab: int = 50432, rotary_pct: float = 0.25,
            inter: Optional[int] = None, max_seq: int = 2048) -> ModelConfig:
    """Pythia / dolly-v2 / stablelm-alpha / RedPajama-INCITE / h2ogpt family."""
    return ModelConfig(
        name=name, vocab_size=vocab, hidden_size=hidden, n_layers=layers, n_heads=heads,
        intermediate_size=inter if inter is not None else 4 * hidden, max_seq_len=max_seq,
        pos_embedding="rotary", rotary_pct=rotary_pct, norm="layernorm",
        activation="gelu", gated_mlp=False, parallel_block=True,
        qkv_bias=True, attn_out_bias=True, mlp_bias=True,
    )


# 7B-class presets run DENSE prefill attention by default: measured on a
# v5e chip (SCALE.md "flash vs dense"), dense beats the Pallas flash
# kernel by ~8% at every batch/seq that fits a single chip (S<=512 —
# XLA's fused softmax never materializes the full (B, H, S, S) f32
# tensor), and past that the KV-cache while-loop layout copies OOM first
# either way. Flip use_flash_attention=True for long-S workloads on
# larger-HBM chips; ALiBi (bloom) is supported in-kernel.

def llama2_7b() -> ModelConfig:
    return ModelConfig(name="llama-2-7b", vocab_size=32000, hidden_size=4096,
                       n_layers=32, n_heads=32, intermediate_size=11008,
                       max_seq_len=4096, use_flash_attention=False)


def mistral_7b() -> ModelConfig:
    return ModelConfig(name="mistral-7b", vocab_size=32000, hidden_size=4096,
                       n_layers=32, n_heads=32, n_kv_heads=8, intermediate_size=14336,
                       max_seq_len=4096, use_flash_attention=False)


def qwen_7b() -> ModelConfig:
    # Qwen-7B (v1): llama-like but qkv bias and vocab 151936 (trust_remote_code
    # upstream; re-implemented here).
    return ModelConfig(name="qwen-7b", vocab_size=151936, hidden_size=4096,
                       n_layers=32, n_heads=32, intermediate_size=11008,
                       max_seq_len=2048, qkv_bias=True, norm_eps=1e-6,
                       use_flash_attention=False)


def pythia_69b() -> ModelConfig:
    """EleutherAI/pythia-6.9b at real size (gptneox: partial rotary 0.25,
    parallel block, LayerNorm) — the base half of the dolly-v2 pair
    (compare_base_vs_instruct.py:136-180)."""
    return gptneox(name="pythia-6.9b")


def h2ogpt_12b() -> ModelConfig:
    """h2oai/h2ogpt-oasst1-512-12b — the reference zoo's largest model
    (compare_instruct_models.py:145-166). Pythia-12b architecture:
    gptneox with hidden 5120 / 36 layers / 40 heads / vocab 50688."""
    return gptneox(name="h2ogpt-oasst1-512-12b", hidden=5120, layers=36,
                   heads=40, vocab=50688)


def baichuan2_7b() -> ModelConfig:
    return ModelConfig(name="baichuan2-7b", vocab_size=125696, hidden_size=4096,
                       n_layers=32, n_heads=32, intermediate_size=11008,
                       max_seq_len=4096, use_flash_attention=False)


def falcon_7b() -> ModelConfig:
    return ModelConfig(
        name="falcon-7b", vocab_size=65024, hidden_size=4544, n_layers=32,
        n_heads=71, n_kv_heads=1, intermediate_size=4 * 4544, max_seq_len=2048,
        pos_embedding="rotary", norm="layernorm", activation="gelu", gated_mlp=False,
        parallel_block=True, shared_block_ln=True, tie_embeddings=True,
        use_flash_attention=False,
    )


def bloom_7b1() -> ModelConfig:
    return ModelConfig(
        name="bloom-7b1", vocab_size=250880, hidden_size=4096, n_layers=30,
        n_heads=32, intermediate_size=4 * 4096, max_seq_len=2048,
        pos_embedding="alibi", norm="layernorm", activation="gelu_new", gated_mlp=False,
        embedding_norm=True, qkv_bias=True, attn_out_bias=True, mlp_bias=True,
        tie_embeddings=True, use_flash_attention=False,
    )


def opt(name: str = "opt-iml-1.3b") -> ModelConfig:
    return ModelConfig(
        name=name, vocab_size=50272, hidden_size=2048, n_layers=24, n_heads=32,
        intermediate_size=8192, max_seq_len=2048, pos_embedding="learned",
        learned_pos_offset=2, norm="layernorm", activation="relu", gated_mlp=False,
        qkv_bias=True, attn_out_bias=True, mlp_bias=True, tie_embeddings=True,
    )


def t5_v1_1(size: str = "base") -> T5Config:
    dims = {"small": (512, 8, 6, 1024), "base": (768, 12, 12, 2048),
            "large": (1024, 24, 16, 2816), "xl": (2048, 24, 32, 5120)}[size]
    d, l, h, ff = dims
    return T5Config(name=f"t5-v1_1-{size}", hidden_size=d, n_layers=l, n_heads=h,
                    intermediate_size=ff)


def flan_t5(size: str = "base") -> T5Config:
    cfg = t5_v1_1(size)
    return dataclasses.replace(cfg, name=f"flan-t5-{size}")


def t0_3b() -> T5Config:
    return T5Config(name="T0_3B", hidden_size=2048, n_layers=24, n_heads=32,
                    intermediate_size=5120)


# Tiny configs for tests (parity vs transformers CPU on random weights).
def tiny(family: str) -> ModelConfig:
    base = dict(vocab_size=256, hidden_size=64, n_layers=2, n_heads=4,
                intermediate_size=128, max_seq_len=128)
    if family == "gpt2":
        return ModelConfig(name="tiny-gpt2", pos_embedding="learned", norm="layernorm",
                           activation="gelu_new", gated_mlp=False, qkv_bias=True,
                           attn_out_bias=True, mlp_bias=True, tie_embeddings=True, **base)
    if family == "gptneox":
        return ModelConfig(name="tiny-gptneox", pos_embedding="rotary", rotary_pct=0.25,
                           norm="layernorm", activation="gelu", gated_mlp=False,
                           parallel_block=True, qkv_bias=True, attn_out_bias=True,
                           mlp_bias=True, **base)
    if family == "llama":
        return ModelConfig(name="tiny-llama", **base)
    if family == "mistral":
        return ModelConfig(name="tiny-mistral", n_kv_heads=2, **base)
    if family == "falcon":
        return ModelConfig(name="tiny-falcon", pos_embedding="rotary", norm="layernorm",
                           activation="gelu", gated_mlp=False, parallel_block=True,
                           shared_block_ln=True, n_kv_heads=1, tie_embeddings=True, **base)
    if family == "bloom":
        return ModelConfig(name="tiny-bloom", pos_embedding="alibi", norm="layernorm",
                           activation="gelu_new", gated_mlp=False, embedding_norm=True,
                           qkv_bias=True, attn_out_bias=True, mlp_bias=True,
                           tie_embeddings=True, **base)
    if family == "opt":
        return ModelConfig(name="tiny-opt", pos_embedding="learned", learned_pos_offset=2,
                           norm="layernorm", activation="relu", gated_mlp=False,
                           qkv_bias=True, attn_out_bias=True, mlp_bias=True,
                           tie_embeddings=True, **base)
    raise KeyError(family)


REGISTRY = {
    "gpt2": gpt2, "gptneox": gptneox, "llama2-7b": llama2_7b,
    "mistral-7b": mistral_7b, "qwen-7b": qwen_7b, "baichuan2-7b": baichuan2_7b,
    "falcon-7b": falcon_7b, "bloom-7b1": bloom_7b1, "opt": opt,
    "t5-v1_1": t5_v1_1, "flan-t5": flan_t5, "t0-3b": t0_3b,
}
