"""Perturbation-grid construction, subset sampling, and resume keys.

C4/C5/C6 parity (SURVEY.md §2.1): the reference expands
(prompt x rephrasing x format) into OpenAI batch requests with custom_id
metadata (perturb_prompts.py:190-269), skips (Model, Original Main Part,
Rephrased Main Part) triples already present in the output Excel (:161-188),
and supports a seeded random subset for cost estimation (:109-159). Here the
grid is a deterministic list of cells; "requests" are just batched local
forward passes, and resume runs through utils/manifest.SweepManifest with the
same key triple.
"""

from __future__ import annotations

import dataclasses
import functools
import random
from typing import Dict, List, Optional, Sequence, Tuple

from ..data.prompts import LegalPrompt

RESUME_KEY_FIELDS = ("model", "original_main", "rephrased_main")

# Manifest key field -> D6 results-workbook column. Seeding the resume
# done-set from the results artifact (SweepManifest.from_existing_results)
# needs this mapping: the workbook keeps the reference's column names
# while the manifest keys stay snake_case.
RESUME_COLUMN_MAP = {
    "model": "Model",
    "original_main": "Original Main Part",
    "rephrased_main": "Rephrased Main Part",
}


@dataclasses.dataclass(frozen=True)
class GridCell:
    """One (model, prompt, rephrasing) measurement unit.

    Each cell scores TWO prompts (binary + confidence format) — the
    reference's two request dicts per rephrasing (perturb_prompts.py:208-252).
    """

    model: str
    prompt_idx: int
    rephrase_idx: int
    original_main: str
    rephrased_main: str
    response_format: str
    confidence_format: str
    target_tokens: Tuple[str, str]

    # cached: the ragged scheduler touches each prompt string several
    # times per sweep (tokenize at plan time, dispatch, row build) — a
    # 20k-cell grid re-concatenating ~1 KB strings per access is pure
    # waste. cached_property writes instance __dict__ directly, which a
    # frozen dataclass permits.
    @functools.cached_property
    def binary_prompt(self) -> str:
        return f"{self.rephrased_main} {self.response_format}"

    @functools.cached_property
    def confidence_prompt(self) -> str:
        return f"{self.rephrased_main} {self.confidence_format}"

    def resume_record(self) -> Dict[str, str]:
        return {"model": self.model, "original_main": self.original_main,
                "rephrased_main": self.rephrased_main}


def build_grid(model: str, prompts: Sequence[LegalPrompt],
               perturbations: Sequence[Sequence[str]],
               include_original: bool = True) -> List[GridCell]:
    """Expand the full grid for one model.

    ``perturbations[i]`` is the rephrasing list for ``prompts[i]``. The
    EXECUTED reference grid contains only the rephrasings
    (create_batch_requests iterates the rephrasing lists alone,
    perturb_prompts.py:200-213 — pinned by tools/reference_perturb_oracle.py);
    ``include_original=True`` (the local-pipeline default) additionally
    scores the unperturbed original as rephrase_idx 0, a lir_tpu
    extension that anchors each prompt's perturbation distribution. Pass
    ``include_original=False`` for reference-exact grids (the API-backend
    oracle differential does)."""
    cells: List[GridCell] = []
    for pi, (prompt, rephrasings) in enumerate(zip(prompts, perturbations)):
        variants = ([prompt.main, *rephrasings] if include_original
                    else list(rephrasings))
        for ri, rephrased in enumerate(variants):
            cells.append(GridCell(
                model=model, prompt_idx=pi, rephrase_idx=ri,
                original_main=prompt.main, rephrased_main=rephrased,
                response_format=prompt.response_format,
                confidence_format=prompt.confidence_format,
                target_tokens=prompt.target_tokens))
    return cells


def random_subset(cells: Sequence[GridCell], size: Optional[int],
                  seed: int = 42) -> List[GridCell]:
    """Seeded subset sampling, regrouped by prompt (perturb_prompts.py:109-159)."""
    if size is None or size >= len(cells):
        return list(cells)
    rng = random.Random(seed)
    picked = rng.sample(list(cells), size)
    picked.sort(key=lambda c: (c.prompt_idx, c.rephrase_idx))
    return picked


def pending_cells(cells: Sequence[GridCell], manifest) -> List[GridCell]:
    """Drop cells whose resume key is already in the manifest (C5 dedup)."""
    return [c for c in cells if not manifest.is_done(c.resume_record())]
