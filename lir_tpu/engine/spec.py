"""Speculative scoring decode — host-side drafting orchestration.

The paper's workload (PAPER.md axis 1: thousands of rephrasings of ~5
legal prompts, all ending in near-identical ``"confidence: NN"`` tails
and yes/no preambles) is uniquely speculation-friendly: the remaining
decode cost after the PR-7 kernels is the ≤10-token SEQUENTIAL scan
itself, and speculative decoding (Leviathan et al. 2023) collapses it —
draft k tokens cheaply, verify them in ONE multi-query forward
(generate._spec_tail over decoder.verify_extend), accept greedily so
every emitted token is bitwise what the sequential scan would have
produced.

This module owns the HOST half: building one dispatch's
:class:`SpecPlan` —

- **radix-tree continuation drafts** (prompt-lookup, Saxena-style, with
  the lookup table being the engine's own radix prefix tree token
  history): ``prefix_tree.continuation(bucket, ids, k)`` predicts each
  row's whole continuation from previously cached longer prompts and
  recorded completion tails — no draft model, no extra HBM;
- **compacted context buffers** for the in-scan n-gram fallback drafter
  (the dispatch's own prompt tokens + accepted emissions);
- **fleet draft-model arming** (the engine holds the small model's
  params/cfg, acquired through the PR-10 WeightCache by the fleet
  layer so drafting can never evict the verifier mid-dispatch);

plus the readout side: folding the dispatch's device-side SpecOut
counters into profiling.SpecStats without forcing a host sync on the
dispatch thread (``flush_pending``), and recording observed completions
back into the tree (``record_tails``) so repeat visits draft the whole
reply. Draft quality is strictly a SPEED knob — a corrupted draft
(faults/plan.py ``draft_corrupt``) only costs re-verification.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.logging import get_logger

log = get_logger(__name__)


@dataclasses.dataclass
class SpecPlan:
    """One shared dispatch's drafting inputs (engine-internal). Arrays
    are host numpy; the runner lifts them to device with the dispatch.
    ``ctx_*`` are each branch's compacted full prompts right-padded to
    bucket + suffix-bucket + decode-budget; ``draft_*`` the tree-probed
    continuations padded to the decode budget. ``fleet`` is True when a
    draft model (engine._spec_draft) drafts instead of the self-lookup
    pair."""

    k: int
    ngram: int
    ctx_a: np.ndarray
    ctx_a_len: np.ndarray
    draft_a: np.ndarray
    draft_a_len: np.ndarray
    ctx_b: np.ndarray
    ctx_b_len: np.ndarray
    draft_b: np.ndarray
    draft_b_len: np.ndarray
    fleet: bool = False
    tree_rows: int = 0

    def dyn_args(self) -> Tuple[np.ndarray, ...]:
        """The eight drafting arrays in generate.*_spec argument order."""
        return (self.ctx_a, self.ctx_a_len, self.draft_a, self.draft_a_len,
                self.ctx_b, self.ctx_b_len, self.draft_b, self.draft_b_len)


def _ctx_arrays(ids_rows: Sequence[Sequence[int]], width: int,
                pad_id: int) -> Tuple[np.ndarray, np.ndarray]:
    B = len(ids_rows)
    ctx = np.full((B, width), pad_id, np.int32)
    lens = np.zeros((B,), np.int32)
    for r, ids in enumerate(ids_rows):
        n = min(len(ids), width)
        ctx[r, :n] = np.asarray(ids[:n], np.int32)
        lens[r] = n
    return ctx, lens


def _tree_drafts(tree, bucket: int, ids_rows: Sequence[Sequence[int]],
                 budget: int) -> Tuple[np.ndarray, np.ndarray, int]:
    B = len(ids_rows)
    toks = np.zeros((B, budget), np.int32)
    lens = np.zeros((B,), np.int32)
    hit_rows = 0
    for r, ids in enumerate(ids_rows):
        cont = tree.continuation(bucket, ids, budget)
        if cont:
            n = min(len(cont), budget)
            toks[r, :n] = np.asarray(cont[:n], np.int32)
            lens[r] = n
            hit_rows += 1
    return toks, lens, hit_rows


def build_plan(engine, bin_ids: Sequence[Sequence[int]],
               conf_ids: Sequence[Sequence[int]], bucket: int,
               ba: int, bb: int, new_tokens: int,
               conf_tokens: int) -> Optional[SpecPlan]:
    """Build one shared dispatch's SpecPlan, or None when speculation is
    off / unsupported for this engine (the runner then dispatches the
    sequential executable and counts a fallback only for spec-eligible
    engines)."""
    rt = engine.rt
    if not engine.spec_supported():
        return None
    spec_cfg = engine.spec_cfg
    k = int(rt.spec_k)
    from ..engine import tokens as tok

    pad_id = tok.pad_token_id(engine.tokenizer)
    ctx_a, len_a = _ctx_arrays(bin_ids, bucket + ba + new_tokens, pad_id)
    ctx_b, len_b = _ctx_arrays(conf_ids, bucket + bb + conf_tokens, pad_id)
    B = len(bin_ids)
    draft_a = np.zeros((B, new_tokens), np.int32)
    dlen_a = np.zeros((B,), np.int32)
    draft_b = np.zeros((B, conf_tokens), np.int32)
    dlen_b = np.zeros((B,), np.int32)
    fleet = engine._spec_draft is not None
    tree_rows = 0
    if (not fleet and spec_cfg.tree_probe
            and engine.prefix_cache is not None):
        draft_a, dlen_a, hits_a = _tree_drafts(
            engine.prefix_cache, bucket, bin_ids, new_tokens)
        draft_b, dlen_b, hits_b = _tree_drafts(
            engine.prefix_cache, bucket, conf_ids, conf_tokens)
        tree_rows = hits_a + hits_b
    plan = SpecPlan(k=k, ngram=int(spec_cfg.ngram),
                    ctx_a=ctx_a, ctx_a_len=len_a,
                    draft_a=draft_a, draft_a_len=dlen_a,
                    ctx_b=ctx_b, ctx_b_len=len_b,
                    draft_b=draft_b, draft_b_len=dlen_b,
                    fleet=fleet, tree_rows=tree_rows)
    fault = getattr(engine, "spec_fault_plan", None)
    if fault is not None:
        vocab = int(engine.cfg.vocab_size)
        fault.corrupt_draft([(plan.draft_a, plan.draft_a_len),
                             (plan.draft_b, plan.draft_b_len)], vocab)
    return plan


def record_tails(engine, bucket: int,
                 prompt_ids: Sequence[Sequence[int]],
                 gen_rows: Any, n_real: int,
                 max_tails: int = 32) -> int:
    """Record each real row's observed continuation (its raw generated
    ids) into the radix tree's token history, so a repeat visit of the
    same prompt drafts the whole reply. No-op without a tree. Returns
    rows recorded."""
    tree = engine.prefix_cache
    if tree is None or not engine.spec_supported():
        return 0
    if not engine.spec_cfg.tree_probe:
        return 0
    gen = np.asarray(gen_rows)
    done = 0
    for r in range(min(n_real, gen.shape[0], len(prompt_ids))):
        if tree.record_tail(bucket, prompt_ids[r], gen[r].tolist(),
                            max_tails=max_tails):
            done += 1
    return done


def flush_pending(engine) -> None:
    """Fold every pending device-side SpecOut pair into
    profiling.SpecStats. Deferred off the dispatch path on purpose — a
    device_get at dispatch time would serialize the host against the
    in-flight computation; callers flush at readout boundaries (the
    serve batcher after its payload device_get, the sweep at stats
    time)."""
    import jax

    pending = engine._spec_pending
    if not pending:
        return
    engine._spec_pending = []
    host = jax.device_get(pending)
    for spec_a, spec_b in host:
        for so in (spec_a, spec_b):
            engine.spec_stats.add_branch(so.drafted, so.accepted,
                                         int(so.chunks),
                                         int(so.seq_steps))
