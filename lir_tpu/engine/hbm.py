"""Unified HBM governor: one memory ledger, a pressure-driven
degradation ladder, and reclaim-and-retry OOM routing.

Before this module, HBM was governed by four uncoordinated mechanisms:
the WeightCache budget (models/weights.py — loud terminal
``WeightCacheOOM``), the page-pool size flag (models/paged.py), the
piggyback two-cache headroom gate (engine/runner.py), and spec-draft
pins (engine/fleet.py) — and a real device OOM mid-sweep simply
re-raised ("the batch ladder owns OOM" only in bench/tools). vLLM-class
servers treat this as table stakes: a single ledger of who holds HBM
and a reversible degradation order when it runs out. DistServe/
Mooncake-style disaggregation (ROADMAP item 2) additionally makes
per-replica memory a *placement* input, so the governor's pressure
gauge is exported to the router (serve/router.py) beside weight
residency.

Three pieces:

- **Ledger.** Every HBM consumer registers projected bytes under a
  stable name (``register``/``update``/``unregister``): engine params,
  the KV page pool, the dispatch/handoff donation caches, spec-draft
  pins, fleet weight-cache residency, the streaming accumulator
  lattice. ``admit`` checks a projected allocation against the budget
  BEFORE the bytes exist (counters ``admits``/``denials``), and the
  ledger total / budget ratio is the **pressure** gauge, published
  into :class:`~lir_tpu.utils.profiling.MemStats` (the ``mem`` source
  of the unified metrics snapshot, next to ``device_memory_stats()``).
- **Degradation ladder.** Sustained pressure above
  ``GovernorConfig.engage_pressure`` walks one rung per
  ``sustain_ticks`` dispatches, in reclaim order:

  1. ``evict_weights`` — drop one idle (unreferenced, unpinned) LRU
     model from the fleet weight cache;
  2. ``evict_pages``   — evict cold radix pages from the KV page pool;
  3. ``no_piggyback``  — stop opening piggyback chains (a chain keeps
     TWO dispatch caches live);
  4. ``no_spec``       — disable speculative drafting (the sequential
     path is already bitwise-identical);
  5. ``batch_down``    — halve the serve batcher's dispatch rows;
  6. ``shed``          — backpressure: refuse new submits.

  Every rung is REVERSIBLE: pressure sustained below
  ``engage - hysteresis`` releases the most recent rung (counters
  ``rung_downs``/``rung_ups`` record both directions), so a cleared
  squeeze restores full throughput without a restart. None of the
  rungs can change results — eviction re-loads/re-prefills bitwise,
  piggyback/spec OFF are pinned bitwise-identical, and batch
  composition is masked out of every readout.
- **OOM routing.** ``handle_oom(site)`` is called by the sweep's
  dispatch recovery and the serve supervisor when
  ``is_oom_error(err)``: the governor force-engages the reclaim rungs
  (weights, pages, piggyback) immediately — no sustain wait — and
  returns True when anything was freed, telling the caller to retry
  the dispatch ONCE. A second OOM is the irreducible dispatch: the
  caller quarantines it (serve resolves its rows as errors WITHOUT
  feeding the circuit breaker — capacity is not device death; sweep
  raises :class:`HbmExhausted` with the full ledger arithmetic for the
  bench/tools batch ladder).

The seeded ``hbm_squeeze`` fault kind (faults/plan.py,
``wrap_governor``) shrinks the ledger budget mid-run and auto-restores
it, proving the whole walk down AND back up under chaos
(tools/chaos_smoke.py scenario 10, ``make mem-smoke``, bench.py's
"memory" headline key).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..config import GovernorConfig
from ..utils.logging import get_logger
from ..utils.profiling import MemStats

log = get_logger(__name__)

# Reclaim order — the ladder walks DOWN this list under pressure and
# back UP it (reverse order) when pressure clears. Indexes are the
# MemStats.rung gauge. With a tier store attached (serve/tiers.py),
# the first two rungs become reversible DEMOTIONS: evict_weights
# records the victim's staged tree to the disk tier before eviction
# (engine/fleet.py) and evict_pages exports the coldest radix leaves
# to the host/disk ladder before their pages leave HBM
# (engine/runner._evict_cold_pages) — same bytes freed, nothing
# deleted.
RUNGS: Tuple[str, ...] = ("evict_weights", "evict_pages", "no_piggyback",
                          "no_spec", "batch_down", "shed")
# Rungs that free bytes NOW — the set handle_oom force-engages.
RECLAIM_RUNGS: Tuple[str, ...] = ("evict_weights", "evict_pages",
                                  "no_piggyback")


class HbmExhausted(RuntimeError):
    """A dispatch OOMed even after the governor reclaimed everything
    reclaimable — the irreducible dispatch. Carries the full ledger
    arithmetic so the operator (or the bench's batch ladder) can size
    the fix instead of guessing."""


class OomSignal(BaseException):
    """Control-flow marker lifting a device OOM OUT of a generic
    ``except Exception`` retry boundary. BaseException on purpose,
    mirroring faults.InjectedPreemption's rationale: an exponential-
    backoff loop re-attempting the SAME allocation can only re-OOM —
    capacity is not transience — so the serve supervisor must see the
    OOM immediately and route it through the governor's
    reclaim-and-retry instead of burning its retry budget and feeding
    the circuit breaker. Always caught explicitly one frame up; never
    escapes the dispatch path."""

    def __init__(self, err: BaseException):
        super().__init__(str(err))
        self.err = err


def device_budget_bytes(reserve_frac: float = 0.08) -> Optional[int]:
    """The device's reported HBM limit minus the reserve slack, or None
    when the backend exposes no memory stats (CPU smoke — host RAM
    governs and the ladder never engages)."""
    try:
        import jax

        stats = jax.devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit")
    except Exception:  # noqa: BLE001 — no stats, no derived budget
        return None
    if not limit:
        return None
    return int(limit * (1.0 - reserve_frac))


class HbmGovernor:
    """One memory ledger + the pressure-driven degradation ladder.

    Host-side bookkeeping only (never holds device buffers, never
    blocks on device work). Thread-safe throughout: the sweep dispatch
    loop, the serve supervisor, fleet weight-cache listeners, and the
    router's pressure reads all touch it concurrently.
    """

    def __init__(self, config: Optional[GovernorConfig] = None,
                 stats: Optional[MemStats] = None,
                 budget_bytes: Optional[int] = None):
        self.cfg = config if config is not None else GovernorConfig()
        self.stats = stats if stats is not None else MemStats()
        if budget_bytes is None:
            budget_bytes = self.cfg.budget_bytes
            if budget_bytes is None and self.cfg.enabled:
                budget_bytes = device_budget_bytes(
                    self.cfg.hbm_reserve_frac)
        self._lock = threading.RLock()
        self._base_budget = budget_bytes       # guarded-by: _lock
        self._adopted_base = False             # guarded-by: _lock
        self._squeeze_frac = 1.0               # guarded-by: _lock
        self._squeeze_left = 0                 # guarded-by: _lock
        self._entries: Dict[str, int] = {}     # guarded-by: _lock
        self._level = 0                        # guarded-by: _lock
        self._over_ticks = 0                   # guarded-by: _lock
        self._under_ticks = 0                  # guarded-by: _lock
        # rung name -> (engage_fn() -> freed anything, release_fn)
        self._actions: Dict[str, Tuple[Optional[Callable[[], bool]],
                                       Optional[Callable[[], None]]]] \
            = {}                               # guarded-by: _lock
        self._publish_locked()

    # -- the ledger ----------------------------------------------------------

    def register(self, name: str, nbytes: int) -> None:
        """Make one consumer's projected bytes visible to the ledger
        (idempotent — re-registering replaces)."""
        with self._lock:
            self._entries[str(name)] = max(int(nbytes), 0)
            self._publish_locked()

    update = register

    def unregister(self, name: str) -> None:
        with self._lock:
            self._entries.pop(str(name), None)
            self._publish_locked()

    def ledger(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._entries)

    @property
    def ledger_bytes(self) -> int:
        with self._lock:
            return sum(self._entries.values())

    @property
    def budget_bytes(self) -> Optional[int]:
        """The CURRENT governed budget (squeeze applied), or None when
        unbounded."""
        with self._lock:
            return self._budget_locked()

    def _budget_locked(self) -> Optional[int]:  # guarded-by: _lock
        if self._base_budget is None:
            return None
        return int(self._base_budget * self._squeeze_frac)

    def headroom(self) -> Optional[int]:
        """Budget minus ledger (None when unbounded; floor 0)."""
        with self._lock:
            budget = self._budget_locked()
            if budget is None:
                return None
            return max(budget - sum(self._entries.values()), 0)

    def pressure(self) -> float:
        """ledger / budget (0.0 when unbounded — nothing to press
        against)."""
        with self._lock:
            budget = self._budget_locked()
            if not budget:
                return 0.0
            return sum(self._entries.values()) / budget

    def admit(self, name: str, nbytes: int) -> bool:
        """Admission check: would ``nbytes`` more for ``name`` fit the
        budget? Counts ``admits``/``denials``; advisory — the caller
        decides whether a denial is fatal (fleet boot validation) or a
        reclaim trigger (WeightCache insert)."""
        with self._lock:
            budget = self._budget_locked()
            if budget is None:
                self.stats.count("admits")
                return True
            projected = (sum(self._entries.values())
                         - self._entries.get(str(name), 0) + int(nbytes))
            if projected <= budget:
                self.stats.count("admits")
                return True
            self.stats.count("denials")
            return False

    def _publish_locked(self) -> None:  # guarded-by: _lock
        total = sum(self._entries.values())
        budget = self._budget_locked()
        self.stats.gauge("ledger_bytes", int(total))
        self.stats.gauge("budget_bytes", int(budget or 0))
        self.stats.gauge("pressure",
                         float(total / budget) if budget else 0.0)
        self.stats.gauge("rung", int(self._level))

    # -- rung actions --------------------------------------------------------

    def set_action(self, rung: str,
                   engage: Optional[Callable[[], bool]] = None,
                   release: Optional[Callable[[], None]] = None) -> None:
        """Attach reclaim callbacks to a rung (fleet: evict one idle LRU
        model; engine: evict cold radix pages). Flag rungs
        (no_piggyback/no_spec/batch_down/shed) need no callbacks —
        consumers poll :meth:`allows`/:meth:`batch_cap`/
        :meth:`should_shed` instead. ``engage`` returns True when it
        actually freed something (drives handle_oom's retry decision)."""
        assert rung in RUNGS, f"unknown governor rung {rung!r}"
        with self._lock:
            self._actions[rung] = (engage, release)

    def allows(self, feature: str) -> bool:
        """False while the named flag rung is engaged. ``feature`` is
        "piggyback" or "spec"."""
        rung = {"piggyback": "no_piggyback", "spec": "no_spec"}[feature]
        with self._lock:
            return self._level <= RUNGS.index(rung)

    def batch_cap(self, full: int) -> int:
        """The serve batcher's dispatch-row cap: halved while the
        batch_down rung is engaged (power-of-two preserved so the
        capped shape is one the precompile grid already covers)."""
        with self._lock:
            engaged = self._level > RUNGS.index("batch_down")
        return max(full // 2, 1) if engaged else full

    def should_shed(self) -> bool:
        """True while the terminal backpressure rung is engaged —
        submits then resolve shed instead of queueing behind memory
        that is not coming back this tick."""
        with self._lock:
            engaged = self._level > RUNGS.index("shed")
        if engaged:
            self.stats.count("sheds")
        return engaged

    @property
    def level(self) -> int:
        with self._lock:
            return self._level

    def engaged_rungs(self) -> List[str]:
        with self._lock:
            return list(RUNGS[: self._level])

    # -- the ladder ----------------------------------------------------------

    def _engage_locked(self, reason: str) -> bool:  # guarded-by: _lock
        """Walk one rung down; returns True when the rung's action
        freed bytes (flag rungs count as engaged-but-nothing-freed)."""
        if self._level >= len(RUNGS):
            return False
        rung = RUNGS[self._level]
        self._level += 1
        self.stats.site("rung_downs", rung)
        engage, _ = self._actions.get(rung, (None, None))
        freed = False
        if engage is not None:
            try:
                freed = bool(engage())
            except Exception:  # noqa: BLE001 — a broken reclaim hook
                # must not take the dispatch path down with it
                log.exception("governor rung %s engage action failed",
                              rung)
        log.warning("hbm governor: engaged rung %s (%s; pressure %.2f, "
                    "level %d/%d)", rung, reason, self.pressure(),
                    self._level, len(RUNGS))
        self._publish_locked()
        return freed

    def _release_locked(self) -> None:  # guarded-by: _lock
        if self._level <= 0:
            return
        self._level -= 1
        rung = RUNGS[self._level]
        self.stats.site("rung_ups", rung)
        _, release = self._actions.get(rung, (None, None))
        if release is not None:
            try:
                release()
            except Exception:  # noqa: BLE001
                log.exception("governor rung %s release action failed",
                              rung)
        log.info("hbm governor: released rung %s (pressure %.2f, level "
                 "%d/%d)", rung, self.pressure(), self._level,
                 len(RUNGS))
        self._publish_locked()

    def tick(self) -> None:
        """One dispatch boundary: re-read pressure, walk the ladder.
        Sustained over-pressure (``sustain_ticks`` consecutive ticks
        above ``engage_pressure``) engages one rung; sustained
        under-pressure (below ``engage - hysteresis``) releases one —
        the hysteresis band between the two is quiet, so a rung can
        never flap on the threshold itself. An active squeeze counts
        down here and restores the budget when it expires."""
        if not self.cfg.enabled:
            return
        with self._lock:
            if self._squeeze_left > 0:
                self._squeeze_left -= 1
                if self._squeeze_left == 0:
                    self._squeeze_frac = 1.0
                    if self._adopted_base:
                        # The base was adopted from the ledger for the
                        # squeeze's sake (unbounded governor): give the
                        # unboundedness back, or pressure would sit at
                        # exactly 1.0 forever.
                        self._base_budget = None
                        self._adopted_base = False
                    log.info("hbm governor: squeeze expired — budget "
                             "restored")
            p = (0.0 if not self._budget_locked()
                 else sum(self._entries.values()) / self._budget_locked())
            sustain = max(int(self.cfg.sustain_ticks), 1)
            if p >= self.cfg.engage_pressure:
                self._over_ticks += 1
                self._under_ticks = 0
                if self._over_ticks >= sustain:
                    self._over_ticks = 0
                    self._engage_locked(f"pressure {p:.2f}")
            elif p <= self.cfg.engage_pressure - self.cfg.hysteresis:
                self._under_ticks += 1
                self._over_ticks = 0
                if self._under_ticks >= sustain and self._level > 0:
                    self._under_ticks = 0
                    self._release_locked()
            else:
                self._over_ticks = 0
                self._under_ticks = 0
            self._publish_locked()

    # -- OOM routing ---------------------------------------------------------

    def handle_oom(self, site: str) -> bool:
        """A real device OOM reached the dispatch path: force-engage
        the reclaim rungs immediately (no sustain wait — the device
        already told us the ledger lies) and report whether anything
        was actually freed, i.e. whether a single retry is worth the
        caller's time. The engaged rungs release through the ordinary
        hysteresis walk once pressure clears."""
        self.stats.site("oom_events", site)
        if not self.cfg.enabled:
            return False
        freed = False
        with self._lock:
            target = RUNGS.index(RECLAIM_RUNGS[-1]) + 1
            while self._level < target:
                freed = self._engage_locked(f"device OOM at {site}") \
                    or freed
        if freed:
            self.stats.count("oom_reclaims")
        else:
            self.stats.count("oom_exhausted")
        return freed

    def oom_message(self, site: str, err: BaseException) -> str:
        """The HbmExhausted arithmetic: who holds what against which
        budget, so the irreducible dispatch is sized, not guessed."""
        with self._lock:
            entries = dict(self._entries)
            budget = self._budget_locked()
        held = ", ".join(f"{k}={v / 2**30:.2f} GiB"
                         for k, v in sorted(entries.items())) or "nothing"
        total = sum(entries.values())
        return (f"device OOM at {site} survived governor reclaim "
                f"(ledger {total / 2**30:.2f} GiB"
                f"{'' if budget is None else f' / budget {budget / 2**30:.2f} GiB'}; "
                f"holders: {held}; engaged rungs: "
                f"{','.join(self.engaged_rungs()) or 'none'}): {err!r}")

    # -- chaos ---------------------------------------------------------------

    def squeeze(self, frac: float, calls: int = 8) -> None:
        """Shrink the governed budget to ``frac`` of its base for the
        next ``calls`` ticks (the seeded ``hbm_squeeze`` fault kind's
        entry point — faults/plan.wrap_governor). Auto-restores, so
        the ladder's walk back up is part of the same proof. A governor
        with no base budget adopts the current ledger total as one
        (the CPU-smoke path: squeezing 'unbounded' must still bite)."""
        with self._lock:
            if self._base_budget is None:
                self._base_budget = max(sum(self._entries.values()), 1)
                self._adopted_base = True
            self._squeeze_frac = max(float(frac), 0.01)
            self._squeeze_left = max(int(calls), 1)
            self.stats.count("squeezes")
            self._publish_locked()
        log.warning("hbm governor: budget squeezed to %.0f%% for %d "
                    "ticks (pressure now %.2f)", frac * 100, calls,
                    self.pressure())

    def summary(self) -> Dict[str, object]:
        out = self.stats.summary()
        out["ledger"] = {k: int(v) for k, v in self.ledger().items()}
        out["engaged"] = self.engaged_rungs()
        return out


def validate_fleet_budget(model_id: str, nbytes: int,
                          budget_bytes: Optional[int],
                          governor: Optional[HbmGovernor] = None) -> None:
    """Fleet-boot budget validation: a weight-cache budget smaller than
    one configured model can NEVER hold it — every sweep would die
    mid-run as a WeightCacheOOM. Fail construction instead, with the
    full HBM arithmetic (per-model bytes, what else the ledger holds —
    page-pool reservation included — and the remaining headroom)."""
    if budget_bytes is None or nbytes <= budget_bytes:
        if governor is not None:
            governor.admit(f"weights:{model_id}", nbytes)
        return
    held = ""
    headroom = budget_bytes - nbytes
    if governor is not None:
        governor.stats.count("denials")
        entries = {k: v for k, v in governor.ledger().items()
                   if not k.startswith("weights")}
        if entries:
            held = ("; other HBM holders: "
                    + ", ".join(f"{k}={v / 2**30:.2f} GiB"
                                for k, v in sorted(entries.items())))
            headroom -= sum(entries.values())
    raise ValueError(
        f"weight-cache budget {budget_bytes / 2**30:.2f} GiB cannot hold "
        f"model {model_id!r} ({nbytes / 2**30:.2f} GiB) even empty — "
        f"headroom after the model would be {headroom / 2**30:.2f} GiB"
        f"{held}. Raise --weight-cache-gb above the largest configured "
        f"model (DEPLOY.md §1o sizing arithmetic) or drop the model "
        f"from the fleet.")
