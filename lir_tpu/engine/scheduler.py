"""Length-aware ragged sweep scheduler: bucket ladder + slot refill +
cross-cell prefix reuse.

The perturbation grid is a *ragged* workload: real rephrasings of a legal
prompt vary ~2-4x in tokenized length, while the engine's decode programs
are fixed-shape. The legacy path batched cells in todo order and padded
every batch to the longest row's bucket — on a mixed-length grid nearly
every batch contains one long prompt, so nearly every batch pays the
largest bucket and short prompts burn their FLOPs on left-padding. This
module sits between the grid and the engine and plans the whole sweep's
dispatches up front (the grid is fully known — there is no online arrival
process):

1. **Bucket ladder** (tokens.bucket_ladder): cells are sorted into
   ~sqrt(2)-spaced prompt-length buckets by their real tokenized prefix
   length, so a 90-token rephrasing prefills 128 slots, not 1024. Each
   bucket's shape compiles once and serves every dispatch in the bucket.
2. **Slot refill**: batches are drained per bucket queue, so batch slots
   that the todo-order path would have wasted as ragged-tail padding are
   refilled with the next same-bucket cells; when a bucket's queue can no
   longer fill a batch, its tail is promoted into the next bucket's queue
   whenever the cost model says the promoted rows are cheaper than a
   padded tail dispatch — the sweep then pays for at most one ragged tail
   instead of one per bucket. (In-scan retirement is already handled by
   the early stop's all-done ``lax.cond`` skip; the retire positions feed
   the decode-occupancy counter, profiling.OccupancyStats.)
3. **Cross-cell prefix reuse**: cells whose tokenized prompts agree on a
   long prefix (the sweep formats x rephrasings of one base prompt, when
   rephrasings preserve the opening tokens) are grouped; each group's
   prefix is prefilled ONCE and every member row extends from a
   row-gathered copy of that cache (generate.greedy_decode_fused_grouped)
   — generalizing decode_fused_shared's pairwise binary/confidence
   sharing to arbitrary fan-out.

The scheduler is pure host-side planning — deterministic, total (every
cell lands in exactly one dispatch), and engine-agnostic (items carry an
opaque payload). Shapes it plans are stable per bucket, which is what
lets the runner's cache handoff keep one donated KV buffer per bucket
(see generate: ``scratch_cache``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..utils.profiling import OccupancyStats
from . import tokens as tok

SUFFIX_BUCKETS = (8, 16, 32, 64, 128, 256)

# Decode-floor price constants: how many prefill row-tokens one
# decode-scan token is worth. Recalibrated against the PR-7 fused kernel
# timings (flash-decode + int8 matmul fusion): with the fused kernels a
# decode step's device time tracks one prefill row-token closely (the
# score row, softmax, and probability row stay in VMEM), so the fused
# price is 1.0 — which also keeps every pre-existing plan byte-identical.
# The UNFUSED dense lowering pays ~3x that in HBM round-trips per step;
# engines running --no-fused-decode price their decode floor (and hence
# their watchdog deadlines) with the slower constant so the planner
# doesn't over-promote tails and the watchdog doesn't shoot legitimate
# dense decodes timed against a fused-kernel calibration.
DECODE_TOKEN_COST_FUSED = 1.0
DECODE_TOKEN_COST_UNFUSED = 3.0
# Speculative decode (engine/spec.py): a verify forward checks spec_k
# positions at once, so with healthy accept rates a decode token costs a
# fraction of a sequential step. 0.5 prices the conservative ≥2x
# dispatch-reduction target rather than the full-accept best case; a
# zero-accept dispatch legitimately falls back to ~sequential cost,
# which is why watchdog_seed_headroom() covers the UNFUSED/SPEC spread.
DECODE_TOKEN_COST_SPEC = 0.5


def decode_token_cost(fused_decode: bool = True,
                      spec_decode: bool = False) -> float:
    """The decode-floor constant for a kernel mode (see above).
    ``spec_decode`` prices a speculating dispatch; the default keeps
    every pre-existing (non-spec) plan byte-identical."""
    if spec_decode:
        return DECODE_TOKEN_COST_SPEC
    return (DECODE_TOKEN_COST_FUSED if fused_decode
            else DECODE_TOKEN_COST_UNFUSED)


# Cascade DECODE discount (ops/flash_decode trunk variants): the share
# of a fused decode step's cost that is KV-cache HBM streaming — the
# only term the trunk dedup removes (weights/activations stream either
# way). The discount scales by the trunk's fraction of the cache extent
# and by the deduped-row fraction (slots - 1) / slots, so a batch-1 or
# trunkless dispatch prices byte-identically to the flat kernel.
CASCADE_DECODE_KV_SHARE = 0.3

# Cascade-prefill watchdog spread (watchdog_seed_headroom): a cascade
# engine's deadlines calibrate on cascade-discounted dispatches, but an
# ineligible dispatch (short LCP, too few rows) legitimately runs the
# FULL dense prefill — up to the whole trunk re-paid per row. 2.0 covers
# the worst eligible-vs-fallback prefill ratio the eligibility gates
# admit (trunk < bucket, so the dense prefill is at most ~2x the
# cascade-discounted price the deadline was calibrated on).
CASCADE_PREFILL_SPREAD = 2.0


def watchdog_seed_headroom(spec_decode: bool = False,
                           cascade: bool = False) -> float:
    """EWMA seed headroom for the dispatch watchdog (guard/watchdog.py):
    the spread between the decode pricing a deadline is calibrated on
    and the most expensive mode a dispatch may legitimately fall back
    to (the unfused dense path). The watchdog's first calibration
    sample is inflated by this ratio so a deadline calibrated on
    fused-kernel dispatches never fires spuriously on a dense
    fallback. A SPECULATING engine (``spec_decode``) widens the seed
    to the UNFUSED/SPEC spread: its dispatches are priced at the
    speculative decode floor, and a zero-accept dispatch that
    degenerates to the sequential scan — possibly on the dense
    fallback path — must never trip a spec-calibrated deadline.
    Non-spec engines keep the original fused/unfused spread (their
    deadlines owe speculation nothing). A CASCADE engine
    (``cascade``) additionally multiplies in the cascade/dense
    PREFILL spread (CASCADE_PREFILL_SPREAD): its deadlines calibrate
    on trunk-discounted dispatches, and an ineligible dispatch that
    falls back to the full dense prefill must never trip a
    cascade-calibrated deadline. The spreads compose — a spec+cascade
    engine can hit both fallbacks on one dispatch."""
    seed = (DECODE_TOKEN_COST_UNFUSED / DECODE_TOKEN_COST_SPEC
            if spec_decode
            else DECODE_TOKEN_COST_UNFUSED / DECODE_TOKEN_COST_FUSED)
    if cascade:
        seed *= CASCADE_PREFILL_SPREAD
    return seed


def _tail_batch(n: int, cap: int) -> int:
    """Smallest power of two >= n, capped (mirrors runner._tail_batch)."""
    b = 1
    while b < n:
        b *= 2
    return min(b, cap)


def decode_floor(n_rows: int, batch_size: int, decode_cost: int,
                 fused_decode: bool = True,
                 spec_decode: bool = False,
                 decode_trunk_frac: float = 0.0) -> float:
    """The decode-scan floor of a dispatch's price: every padded slot runs
    the full decode budget whether it carries work or padding, priced at
    the kernel mode's decode-floor constant. Cached prefill can never
    push a dispatch below this (bucket_cost); the piggyback path prices
    a parked dispatch's pending scans with exactly this term.
    ``spec_decode`` prices a speculating dispatch's verify forwards.

    ``decode_trunk_frac`` (trunk tokens / cache extent, 0..1) prices the
    cascade-DECODE dedup: a trunk-aware dispatch streams its trunk K/V
    tiles once per step instead of once per row, shaving
    CASCADE_DECODE_KV_SHARE x trunk-fraction x deduped-row-fraction off
    the floor. The default keeps every pre-existing plan
    byte-identical."""
    slots = _tail_batch(n_rows, batch_size)
    floor = (slots * decode_cost
             * decode_token_cost(fused_decode, spec_decode))
    if decode_trunk_frac > 0.0 and slots > 1:
        frac = min(max(float(decode_trunk_frac), 0.0), 1.0)
        floor *= 1.0 - CASCADE_DECODE_KV_SHARE * frac * (slots - 1) / slots
    return floor


def bucket_cost(n_rows: int, bucket_edge: int, batch_size: int,
                decode_cost: int, cached_tokens: int = 0,
                fused_decode: bool = True,
                spec_decode: bool = False,
                cascade: bool = False,
                trunk_tokens: int = 0,
                decode_trunk_frac: float = 0.0) -> float:
    """Row-token cost of dispatching ``n_rows`` cells at ``bucket_edge``:
    a padded power-of-two batch prefilled at the edge, plus the fixed
    decode floor (:func:`decode_floor` — the steps run whether the slots
    carry work or padding, priced per kernel mode).

    This is THE decode-cost price model (linear param term dominates at
    7B scale: prefill ~ bucket edge per row, each decode step ~ 1 token
    per slot under the fused kernels). The offline planner's slot-refill
    rule (:meth:`RaggedScheduler._plan_shared`), the online continuous
    batcher's bucket-selection policy (serve/batcher.py), AND the
    dispatch watchdog's deadline predictions (guard/watchdog.py) price
    dispatches through this one helper so the three can't drift apart.

    ``cached_tokens`` are prefix tokens the cross-request radix cache
    (engine/prefix_tree.py) already holds for the candidate rows —
    FREE prefill: a paged dispatch gathers them from the page pool
    instead of recomputing, so they come off the prefill term. The
    decode scan is the floor: cached prefill can never make a dispatch
    cheaper than its decode steps.

    ``cascade``/``trunk_tokens`` price the shared-trunk cascade
    discount (ops/cascade_prefill): a cascade dispatch prefills its
    ``trunk_tokens``-token trunk ONCE instead of once per slot, so
    ``(slots - 1) * trunk_tokens`` comes off the prefill term — on top
    of any radix-cached tokens (a warm trunk discounts through
    ``cached_tokens`` too; the max(0) clamp keeps double-counting from
    going negative). ``decode_trunk_frac`` prices the cascade-DECODE
    dedup through :func:`decode_floor`. Defaults price the dense path
    byte-identically."""
    slots = _tail_batch(n_rows, batch_size)
    prefill = slots * bucket_edge - int(cached_tokens)
    if cascade and trunk_tokens > 0:
        prefill -= (slots - 1) * int(trunk_tokens)
    prefill = max(prefill, 0)
    return prefill + decode_floor(n_rows, batch_size, decode_cost,
                                  fused_decode, spec_decode,
                                  decode_trunk_frac=decode_trunk_frac)


@dataclasses.dataclass(frozen=True)
class SweepItem:
    """One grid cell, tokenized. ``lcp`` is the binary/confidence shared
    token prefix (tokens.shared_prefix_len) — the row's prefill length."""

    cell: Any
    bin_ids: Tuple[int, ...]
    conf_ids: Tuple[int, ...]
    lcp: int

    @property
    def prefix_len(self) -> int:
        return max(self.lcp, 1)


@dataclasses.dataclass(frozen=True)
class PrefixGroup:
    """Cells sharing ``plen`` leading tokens; prefilled once as one row."""

    items: Tuple[SweepItem, ...]
    plen: int


@dataclasses.dataclass
class Dispatch:
    """One engine call. ``kind`` is "shared" (pairwise prefix sharing,
    decode_fused_shared) or "grouped" (cross-cell prefix reuse,
    decode_fused_grouped). ``refilled`` counts cells promoted here from a
    smaller bucket's ragged tail. Suffix-bucket edges are planned per
    PREFIX bucket (not per dispatch) so every dispatch in a bucket shares
    one compiled shape and one handoff cache buffer."""

    kind: str
    bucket: int
    items: List[SweepItem]
    refilled: int = 0
    groups: Optional[List[PrefixGroup]] = None
    sfx_bucket_a: int = 0
    sfx_bucket_b: int = 0

    @property
    def cells(self) -> List[Any]:
        return [it.cell for it in self.items]

    def padded_rows(self, batch_size: int) -> Tuple[int, int]:
        """(prefill rows, member rows) after the runner's power-of-two
        tail padding — the EXACT shapes the engine will dispatch, so the
        compile plan (engine/compile_plan.py) can lower every executable
        before the first dispatch. Shared dispatches prefill and decode
        the same padded batch; grouped dispatches prefill one row per
        group and decode two member rows ([bin, conf]) per cell."""
        n = len(self.items)
        if self.kind == "shared":
            b = batch_size if n == batch_size else _tail_batch(n, batch_size)
            return b, b
        return (_tail_batch(len(self.groups), batch_size),
                _tail_batch(2 * n, 2 * batch_size))


def build_items(bin_ids: Sequence[Sequence[int]],
                conf_ids: Sequence[Sequence[int]],
                cells: Sequence[Any]) -> List[SweepItem]:
    """Pair pre-tokenized prompt ids with their cells (total: one item per
    cell, in input order)."""
    items = []
    for c, b, f in zip(cells, bin_ids, conf_ids):
        b, f = tuple(int(i) for i in b), tuple(int(i) for i in f)
        items.append(SweepItem(cell=c, bin_ids=b, conf_ids=f,
                               lcp=tok.shared_prefix_len(b, f)))
    return items


def _lcp(a: Sequence[int], b: Sequence[int]) -> int:
    n, cap = 0, min(len(a), len(b))
    while n < cap and a[n] == b[n]:
        n += 1
    return n


class RaggedScheduler:
    """Plans a sweep's dispatches from tokenized items.

    Parameters
    ----------
    buckets: prefix bucket ladder (tokens.bucket_ladder edges).
    batch_size: cells per dispatch (member rows are 2x this in grouped
        dispatches — one binary + one confidence row per cell).
    new_budget: max decode tokens any row runs (bounds the cache extent
        the learned-position check reasons about).
    decode_cost: per-slot decode tokens a dispatch pays regardless of
        prompt length (both branches' budgets; the sweep passes
        new_tokens + conf_tokens). Defaults to new_budget. The slot
        refill cost model charges a kept tail dispatch this on top of
        its prefill — decode steps are the fixed price of dispatching
        at all, which is what promotion avoids.
    suffix_buckets: right-pad edges for format suffixes.
    max_extent: position ceiling (learned-position tables); None = no cap.
    min_group_prefix / min_group_cells: cross-cell grouping engages only
        for >= min_group_cells cells agreeing on >= min_group_prefix
        tokens AND on at least half of each member's prefill — shorter
        shared prefixes don't amortize the extra suffix-extension FLOPs.
    group_cells: 0 disables cross-cell grouping entirely.
    cached_probe: optional ``(item, bucket_edge) -> cached tokens`` hook
        into the cross-request radix prefix cache (engine/prefix_tree.
        match_len). The slot-refill rule then prices cached-prefix
        tokens as FREE prefill — and since the radix namespaces are
        per-bucket, promoting a tail into the next bucket honestly
        loses this bucket's cached pages, which the probe reflects.
    """

    def __init__(self, buckets: Sequence[int], batch_size: int, *,
                 new_budget: int = 8, decode_cost: Optional[int] = None,
                 suffix_buckets: Sequence[int] = SUFFIX_BUCKETS,
                 max_extent: Optional[int] = None,
                 min_group_prefix: int = 16, min_group_cells: int = 4,
                 group_cells: bool = True,
                 cached_probe=None,
                 fused_decode: bool = True,
                 stats: Optional[OccupancyStats] = None):
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self.batch = int(batch_size)
        self.new_budget = int(new_budget)
        self.decode_cost = int(new_budget if decode_cost is None
                               else decode_cost)
        self.fused_decode = bool(fused_decode)
        self.suffix_buckets = tuple(sorted(suffix_buckets))
        self.max_extent = max_extent
        self.min_group_prefix = int(min_group_prefix)
        self.min_group_cells = int(min_group_cells)
        self.group_cells = group_cells
        self.cached_probe = cached_probe
        self.stats = stats if stats is not None else OccupancyStats()

    def _cached_tokens(self, items: Sequence[Tuple[SweepItem, bool]],
                       edge: int) -> int:
        """Radix-cached prefix tokens across ``items`` at ``edge``'s
        namespace (0 without a probe — the legacy price)."""
        if self.cached_probe is None:
            return 0
        return sum(self.cached_probe(it, edge) for it, _ in items)

    # -- cross-cell prefix grouping -----------------------------------------

    def _fits_grouped(self, plen: int, items: Sequence[SweepItem]) -> bool:
        """A candidate group must keep every member's suffix inside the
        suffix ladder, leave >= 1 real suffix token per member row, and
        (learned positions) keep bucket + suffix + decode inside the
        table."""
        max_sfx = max(max(len(it.bin_ids), len(it.conf_ids)) - plen
                      for it in items)
        min_sfx = min(min(len(it.bin_ids), len(it.conf_ids)) - plen
                      for it in items)
        if min_sfx < 1 or max_sfx > self.suffix_buckets[-1]:
            return False
        bucket = tok.assign_bucket(plen, self.buckets)
        if bucket < plen:           # prefix exceeds the largest bucket
            return False
        if self.max_extent is not None:
            sfx_bucket = tok.pick_bucket([max_sfx], self.suffix_buckets)
            if bucket + sfx_bucket + self.new_budget > self.max_extent:
                return False
        return True

    def _form_groups(self, items: List[SweepItem]
                     ) -> Tuple[List[PrefixGroup], List[SweepItem]]:
        """Greedy grouping over sort order: sorting by token sequence puts
        shared-prefix cells adjacent, so one linear merge pass finds every
        maximal run agreeing on a long-enough prefix. Deterministic (sort
        key is the token tuple; ties broken by input order via stable
        sort) and total (non-grouped items pass through untouched)."""
        order = sorted(range(len(items)), key=lambda i: items[i].bin_ids)
        groups: List[PrefixGroup] = []
        rest: List[SweepItem] = []
        run: List[SweepItem] = []
        run_plen = 0

        def flush():
            nonlocal run, run_plen
            if len(run) >= self.min_group_cells:
                groups.append(PrefixGroup(items=tuple(run), plen=run_plen))
            else:
                rest.extend(run)
            run, run_plen = [], 0

        for i in order:
            it = items[i]
            if not run:
                run, run_plen = [it], it.prefix_len
                continue
            # Joint prefix if `it` joins: common tokens with the run,
            # capped by each side's own binary/confidence split point.
            p = min(run_plen, _lcp(run[-1].bin_ids, it.bin_ids), it.lcp)
            ok = (p >= self.min_group_prefix
                  and len(run) < self.batch
                  # the shared prefix must carry at least half of every
                  # member's prefill or grouping re-pays it in suffixes
                  and all(2 * p >= m.prefix_len for m in run + [it])
                  and self._fits_grouped(p, run + [it]))
            if ok:
                run.append(it)
                run_plen = p
            else:
                flush()
                run, run_plen = [it], it.prefix_len
        flush()
        # Restore input order among non-grouped items (stable downstream
        # bucket queues).
        pos = {id(it): i for i, it in enumerate(items)}
        rest.sort(key=lambda it: pos[id(it)])
        return groups, rest

    # -- bucket queues + slot refill ----------------------------------------

    def _plan_shared(self, items: List[SweepItem]) -> List[Dispatch]:
        queues: Dict[int, List[Tuple[SweepItem, bool]]] = {
            b: [] for b in self.buckets}
        for it in items:
            queues[tok.assign_bucket(it.prefix_len, self.buckets)].append(
                (it, False))

        out: List[Dispatch] = []
        B = self.batch
        for bi, edge in enumerate(self.buckets):
            q = queues[edge]
            while len(q) >= B:
                chunk, q = q[:B], q[B:]
                out.append(Dispatch(
                    kind="shared", bucket=edge,
                    items=[it for it, _ in chunk],
                    refilled=sum(1 for _, r in chunk if r)))
            if not q:
                continue
            nxt = self.buckets[bi + 1] if bi + 1 < len(self.buckets) else None
            # Slot refill under the shared price model (bucket_cost).
            # Keeping the tail pays a WHOLE extra dispatch: a padded
            # power-of-two batch prefilled at this edge plus its fixed
            # decode scan. Promoting pays len(tail) rows at the next
            # edge, where they fill slots of dispatches that run anyway
            # (and cascade upward the same way). With a prefix-cache
            # probe, cached tokens discount each side: a tail whose
            # prefixes are warm in THIS bucket's radix namespace is
            # cheap to keep and expensive to promote (the next bucket's
            # namespace holds different pages).
            if (nxt is not None
                    and len(q) * nxt - self._cached_tokens(q, nxt)
                    < bucket_cost(len(q), edge, B, self.decode_cost,
                                  cached_tokens=self._cached_tokens(q, edge),
                                  fused_decode=self.fused_decode)):
                queues[nxt] = [(it, True) for it, _ in q] + queues[nxt]
            else:
                out.append(Dispatch(
                    kind="shared", bucket=edge,
                    items=[it for it, _ in q],
                    refilled=sum(1 for _, r in q if r)))
        return out

    def _plan_grouped(self, groups: List[PrefixGroup]) -> List[Dispatch]:
        """Pack prefix groups into dispatches: groups sharing a prefix
        bucket ride together until the member-row capacity (2 rows per
        cell, capped at 2*batch) fills."""
        by_bucket: Dict[int, List[PrefixGroup]] = {}
        for g in groups:
            by_bucket.setdefault(
                tok.assign_bucket(g.plen, self.buckets), []).append(g)
        out: List[Dispatch] = []
        cap = 2 * self.batch
        for edge in sorted(by_bucket):
            cur: List[PrefixGroup] = []
            rows = 0
            for g in by_bucket[edge]:
                if cur and rows + 2 * len(g.items) > cap:
                    out.append(self._grouped_dispatch(edge, cur))
                    cur, rows = [], 0
                cur.append(g)
                rows += 2 * len(g.items)
            if cur:
                out.append(self._grouped_dispatch(edge, cur))
        return out

    def _grouped_dispatch(self, edge: int,
                          groups: List[PrefixGroup]) -> Dispatch:
        return Dispatch(
            kind="grouped", bucket=edge,
            items=[it for g in groups for it in g.items], groups=groups)

    # -- public entry --------------------------------------------------------

    def schedule(self, items: Sequence[SweepItem]) -> List[Dispatch]:
        """Plan every dispatch for ``items``. Total and deterministic:
        each item appears in exactly one dispatch; identical inputs plan
        identical schedules."""
        items = list(items)
        if self.group_cells and self.min_group_cells > 1:
            groups, rest = self._form_groups(items)
        else:
            groups, rest = [], items
        dispatches = self._plan_shared(rest) + self._plan_grouped(groups)

        # Plan suffix buckets PER PREFIX BUCKET (shape/handoff stability).
        sfx_a: Dict[Tuple[str, int], int] = {}
        sfx_b: Dict[Tuple[str, int], int] = {}
        for d in dispatches:
            key = (d.kind, d.bucket)
            if d.kind == "shared":
                la = max(len(it.bin_ids) - it.lcp for it in d.items)
                lb = max(len(it.conf_ids) - it.lcp for it in d.items)
            else:
                la = lb = max(
                    max(len(it.bin_ids), len(it.conf_ids)) - g.plen
                    for g in d.groups for it in g.items)
            sfx_a[key] = max(sfx_a.get(key, 1), la)
            sfx_b[key] = max(sfx_b.get(key, 1), lb)
        for d in dispatches:
            key = (d.kind, d.bucket)
            d.sfx_bucket_a = tok.pick_bucket([sfx_a[key]], self.suffix_buckets)
            d.sfx_bucket_b = tok.pick_bucket([sfx_b[key]], self.suffix_buckets)

        self._account(dispatches)
        return dispatches

    def _account(self, dispatches: List[Dispatch]) -> None:
        for d in dispatches:
            n = len(d.items)
            if d.kind == "shared":
                slots = _tail_batch(n, self.batch)
                real = sum(it.prefix_len for it in d.items)
                self.stats.add_dispatch(d.bucket, n, slots, real,
                                        refilled=d.refilled)
            else:
                g_pad = _tail_batch(len(d.groups), self.batch)
                m_pad = _tail_batch(2 * n, 2 * self.batch)
                real = sum(grp.plen for grp in d.groups)
                self.stats.add_dispatch(d.bucket, n, m_pad, real,
                                        used_slots=2 * n,
                                        prefill_slots=g_pad)
                self.stats.grouped_cells += n
                self.stats.grouped_prefill_rows += g_pad
