"""The core measurement primitive: yes/no token-probability readout.

C13 parity (SURVEY.md §2.1): the reference generates up to 50 tokens with
scores, scans the first MAX_LOOK_AHEAD=10 generated positions, and at the
FIRST position where the Yes or No token id appears in the top-2 reads
P(yes)/P(no) from that position's softmax, falling back to position 0
(compare_base_vs_instruct.py:185-305). The two reference scripts drifted on
the readout (odds_ratio = yes/no vs relative_prob = yes/(yes+no), SURVEY.md
§1); here ONE primitive returns both.

Everything is vectorized over the batch: (B,) results from one jitted call,
replacing the reference's one-prompt-at-a-time loop.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

MAX_LOOK_AHEAD = 10   # compare_base_vs_instruct.py:187
TOPK_MATCH = 2        # top-2 rule, :270-273


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class YesNoScores:
    """Batched scorer output (all arrays shaped (B,) unless noted)."""

    yes_prob: jax.Array
    no_prob: jax.Array
    yes_logprob: jax.Array
    no_logprob: jax.Array
    odds_ratio: jax.Array        # yes/no  (compare_base_vs_instruct.py:293)
    relative_prob: jax.Array     # yes/(yes+no) (compare_instruct_models.py:281)
    position_found: jax.Array    # int32; first top-2 match, else 0
    yes_no_found: jax.Array      # bool
    generated: jax.Array         # (B, max_new) int32 token ids (completion text)


def readout_from_step_logits(step_logits: jax.Array, generated: jax.Array,
                             yes_id: jax.Array, no_id: jax.Array,
                             scan_positions: int = MAX_LOOK_AHEAD) -> YesNoScores:
    """Apply the scan-position rule to captured per-step logits.

    step_logits: (B, T_new, V) fp32; generated: (B, T_new) int32;
    yes_id/no_id: scalar int32 target token ids (first sub-token of " Yes" /
    " No" or "Yes"/"No" per tokenizer adapter — SURVEY.md §7 hard part 1).
    """
    B, T, V = step_logits.shape
    yes_id = jnp.broadcast_to(jnp.asarray(yes_id, jnp.int32), (B,))  # per-row ok
    no_id = jnp.broadcast_to(jnp.asarray(no_id, jnp.int32), (B,))
    window = step_logits[:, :scan_positions, :]          # (B, P, V)
    probs = jax.nn.softmax(window, axis=-1)

    _, top_idx = jax.lax.top_k(window, TOPK_MATCH)        # (B, P, k)
    is_target = ((top_idx == yes_id[:, None, None])
                 | (top_idx == no_id[:, None, None]))
    found_at = jnp.any(is_target, axis=-1)                # (B, P)

    any_found = jnp.any(found_at, axis=-1)                # (B,)
    first_pos = jnp.argmax(found_at, axis=-1)             # first True, else 0
    position = jnp.where(any_found, first_pos, 0).astype(jnp.int32)

    sel = jnp.take_along_axis(probs, position[:, None, None], axis=1)[:, 0, :]
    yes_prob = jnp.take_along_axis(sel, yes_id[:, None], axis=1)[:, 0]
    no_prob = jnp.take_along_axis(sel, no_id[:, None], axis=1)[:, 0]
    eps = 1e-10
    denom = yes_prob + no_prob
    return YesNoScores(
        yes_prob=yes_prob,
        no_prob=no_prob,
        yes_logprob=jnp.log(yes_prob + eps),
        no_logprob=jnp.log(no_prob + eps),
        odds_ratio=yes_prob / (no_prob + eps),
        relative_prob=jnp.where(denom > 0, yes_prob / (denom + eps), jnp.nan),
        position_found=position,
        yes_no_found=any_found,
        generated=generated,
    )


def readout_from_fused(fused, yes_ids: jax.Array, no_ids: jax.Array,
                       scan_positions: int = MAX_LOOK_AHEAD) -> YesNoScores:
    """The same C13 scan-position rule applied to a FusedDecodeOut (per-step
    p_yes/p_no/top-2 captured in-scan instead of full logit stacks).

    yes_ids/no_ids: (B,) per-row target ids — must match the ids the fused
    decode ran with."""
    top2 = fused.top2_ids[:, :scan_positions, :]              # (B, P, 2)
    is_target = ((top2 == yes_ids[:, None, None])
                 | (top2 == no_ids[:, None, None]))
    found_at = jnp.any(is_target, axis=-1)                    # (B, P)
    any_found = jnp.any(found_at, axis=-1)
    first_pos = jnp.argmax(found_at, axis=-1)
    position = jnp.where(any_found, first_pos, 0).astype(jnp.int32)

    yes_prob = jnp.take_along_axis(fused.p_yes, position[:, None], axis=1)[:, 0]
    no_prob = jnp.take_along_axis(fused.p_no, position[:, None], axis=1)[:, 0]
    eps = 1e-10
    denom = yes_prob + no_prob
    return YesNoScores(
        yes_prob=yes_prob,
        no_prob=no_prob,
        yes_logprob=jnp.log(yes_prob + eps),
        no_logprob=jnp.log(no_prob + eps),
        odds_ratio=yes_prob / (no_prob + eps),
        relative_prob=jnp.where(denom > 0, yes_prob / (denom + eps), jnp.nan),
        position_found=position,
        yes_no_found=any_found,
        generated=fused.generated,
    )


def count_averaged_responses(runs, target_1: str, target_2: str):
    """Reasoning-model answer-count averaging (perturb_prompts.py:412-446),
    shared by the local sampled scorer and the API batch decoder so the two
    paths cannot drift.

    if/elif order preserved from the reference (:423-426): a response
    containing BOTH targets (e.g. "Not Covered" contains "Covered") counts
    toward target 1 only. Returns (p1, p2, most_common_response) where the
    most-common pick is deterministic (first-seen wins ties — max(set(...))
    would depend on string hashing).
    """
    from collections import Counter

    n = len(runs)
    c1 = c2 = 0
    for r in runs:
        if target_1 in r:
            c1 += 1
        elif target_2 in r:
            c2 += 1
    most_common = Counter(runs).most_common(1)[0][0] if runs else ""
    return (c1 / n if n else 0.0, c2 / n if n else 0.0, most_common)


def topk_logprobs(step_logits: jax.Array, k: int = 20, position: int = 0):
    """Top-k (logprob, token_id) at one generated position — fills the D6
    'Log Probabilities' column the API backend got from OpenAI's
    ``top_logprobs=20`` (perturb_prompts.py:249-252,474-488).

    Returns (logprobs (B, k), ids (B, k))."""
    logp = jax.nn.log_softmax(step_logits[:, position, :], axis=-1)
    vals, ids = jax.lax.top_k(logp, k)
    return vals, ids


def weighted_confidence(step_logits: jax.Array, digit_token_ids: jax.Array,
                        digit_values: jax.Array, position: int = 0) -> jax.Array:
    """E[v] over integer-token probabilities 0..100 — the API-backend
    "Weighted Confidence" readout (perturb_prompts.py:504-526) recomputed
    from local logits.

    digit_token_ids: (K,) token ids whose decoded text is an integer in
    [0, 100]; digit_values: (K,) the integers. Probabilities are renormalized
    over the digit set, matching the reference's sum-over-top-logprobs.
    Returns (B,) expected confidence.
    """
    probs = jax.nn.softmax(step_logits[:, position, :], axis=-1)  # (B, V)
    p = probs[:, digit_token_ids]                                 # (B, K)
    mass = jnp.sum(p, axis=-1, keepdims=True)
    return jnp.sum(p * digit_values[None, :], axis=-1) / jnp.maximum(mass[:, 0], 1e-10)
