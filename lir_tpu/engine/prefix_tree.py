"""Radix tree over tokenized prefixes -> KV page runs (cross-request
prefix cache).

SGLang's RadixAttention observation (Zheng et al., 2023) applied to this
engine: the production workload — millions of users scoring variations
of the same ~5 legal prompts — re-asks prompts whose tokenized prefixes
agree for hundreds of tokens, so the KV of a prefix computed once should
back every later dispatch that shares it, across requests and across
batches. The page pool (models/paged.KVPagePool) owns the device
memory; this module owns the INDEX: which token sequence's KV lives in
which pages, in LRU order, with hit/miss/eviction accounting
(utils/profiling.PrefixCacheStats).

Design notes:

- **Page-granular edges.** Every tree edge covers exactly ``page_size``
  consecutive token ids (one pool page of KV positions). That is a
  radix tree specialized to fixed-length chunks: node splitting — the
  fiddly half of a general radix tree — can never be needed, because
  two sequences that diverge mid-page simply share all full pages
  before the divergent one and recompute the partial page inside the
  dispatch's remainder window.
- **Per-bucket namespaces.** The tree is partitioned by the producing
  dispatch's prefix-bucket edge. KV values are bitwise-reproducible
  only across dispatches of the SAME bucket shape (the attention
  reductions that compute them run at the bucket extent), so pages
  produced at bucket 128 must never back a bucket-64 dispatch — the
  partition makes the bitwise-parity guarantee hold by construction.
  Sharing loss is small: rows sharing a tokenized prefix have
  near-equal prefix lengths and land in the same bucket.
- **Reference discipline.** The tree holds ONE pool reference per
  cached page for as long as its node exists; :meth:`lookup` takes an
  additional reference per matched page (the in-flight dispatch's pin),
  dropped by :meth:`release` after the dispatch returns. Eviction frees
  only leaf nodes whose page refcount is exactly the tree's own — a
  page under an in-flight dispatch is unevictable by construction
  (pinned by tests/test_prefix_cache.py).
- **LRU by lookup clock.** Every lookup/insert stamps the touched path
  with a monotonic clock; eviction removes the stalest evictable
  leaves first, cascading into parents as they become leaves. The LRU
  order is global across bucket namespaces (one pool, one clock).
- **Single-threaded by contract.** Lookups, inserts, and evictions run
  on the dispatch thread (the serve supervisor / the sweep's main
  thread). Admission-time pricing uses :meth:`match_len`, a read-only
  probe that takes no references and mutates nothing.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..utils.logging import get_logger
from ..utils.profiling import PrefixCacheStats

log = get_logger(__name__)

# Page-pool index events (the cluster-wide prefix index rides these the
# same way the router's residency map rides WeightCache listener
# events): fn(event, bucket, ids) with event "insert" (ids = the full
# page-aligned token prefix now cached) or "evict" (ids = the removed
# node's full token path — that page and everything under it is gone).
PageListener = Callable[[str, int, Tuple[int, ...]], None]


class _Node:
    """One cached page: ``key`` is the page's token-id chunk (within the
    parent's context), ``page`` its pool page id. ``tails`` is the
    node's TOKEN HISTORY for speculative drafting: observed
    continuations of sequences ending at this node, keyed by the
    sub-page remainder between the node's depth and the recording
    sequence's end ({remainder tuple -> continuation tuple}, insertion-
    ordered for LRU capping). Host memory only — no pool pages, no
    HBM."""

    __slots__ = ("key", "page", "children", "parent", "clock", "tails",
                 "bucket")

    def __init__(self, key: Tuple[int, ...], page: int,
                 parent: Optional["_Node"]):
        self.key = key
        self.page = page
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.parent = parent
        self.clock = 0
        self.tails: Dict[Tuple[int, ...], Tuple[int, ...]] = {}
        self.bucket: Optional[int] = None   # set on namespace roots only


@dataclasses.dataclass
class PrefixMatch:
    """Result of one pinned lookup: ``pages`` cover the first ``tokens``
    ids of the probed sequence (tokens == len(pages) * page_size). Hand
    back to :meth:`RadixPrefixCache.release` once the dispatch that
    gathered these pages has returned."""

    pages: Tuple[int, ...]
    tokens: int


class RadixPrefixCache:
    """The radix index over one :class:`~lir_tpu.models.paged.KVPagePool`,
    partitioned into per-bucket namespaces (module docstring)."""

    def __init__(self, pool, stats: Optional[PrefixCacheStats] = None):
        self.pool = pool
        self.stats = stats if stats is not None else PrefixCacheStats()
        self.page_size = pool.page_size
        self._roots: Dict[int, _Node] = {}
        self._clock = 0
        self._nodes = 0
        self._listeners: List[PageListener] = []
        self.stats.gauge_pages(pool.pages_in_use, pool.n_pages - 1)

    def __len__(self) -> int:
        return self._nodes

    def add_listener(self, fn: PageListener) -> None:
        """Subscribe to page insert/evict events (module-level
        ``PageListener`` contract). Fired on the tree's owning dispatch
        thread — listeners do cheap index bookkeeping only (the
        router's ClusterPrefixIndex takes its own lock)."""
        self._listeners.append(fn)

    def _notify(self, event: str, bucket: int,
                ids: Tuple[int, ...]) -> None:
        for fn in list(self._listeners):
            try:
                fn(event, int(bucket), ids)
            except Exception:  # noqa: BLE001 — an index listener must
                # never take the serving tree down with it.
                log.exception("prefix-tree listener failed (%s)", event)

    def _node_ids(self, node: _Node) -> Tuple[int, ...]:
        """Full token path of ``node`` (root-exclusive), for evict
        events."""
        keys: List[Tuple[int, ...]] = []
        n: Optional[_Node] = node
        while n is not None and n.key != ():
            keys.append(n.key)
            n = n.parent
        return tuple(t for k in reversed(keys) for t in k)

    def _node_bucket(self, node: _Node) -> int:
        n = node
        while n.parent is not None:
            n = n.parent
        return int(n.bucket if n.bucket is not None else 0)

    def _root(self, bucket: int) -> _Node:
        root = self._roots.get(int(bucket))
        if root is None:
            root = self._roots[int(bucket)] = _Node((), 0, None)
            root.bucket = int(bucket)
        return root

    # -- walking -------------------------------------------------------------

    def _chunks(self, ids: Sequence[int]) -> List[Tuple[int, ...]]:
        ps = self.page_size
        n_full = len(ids) // ps
        return [tuple(int(t) for t in ids[k * ps:(k + 1) * ps])
                for k in range(n_full)]

    def _walk(self, bucket: int, ids: Sequence[int],
              touch: bool) -> List[_Node]:
        path: List[_Node] = []
        node = self._root(bucket)
        for key in self._chunks(ids):
            child = node.children.get(key)
            if child is None:
                break
            path.append(child)
            node = child
        if touch and path:
            self._clock += 1
            for n in path:
                n.clock = self._clock
        return path

    # -- read side -----------------------------------------------------------

    def match_len(self, bucket: int, ids: Sequence[int]) -> int:
        """Cached leading tokens of ``ids`` in the ``bucket`` namespace
        right now — the admission-time pricing probe
        (scheduler.bucket_cost's ``cached_tokens``). Takes no
        references; the answer is advisory (eviction between probe and
        dispatch can only shrink it, and the dispatch re-looks up with a
        pin)."""
        return len(self._walk(bucket, ids, touch=False)) * self.page_size

    def lookup(self, bucket: int, ids: Sequence[int],
               record: bool = True) -> PrefixMatch:
        """Deepest cached prefix of ``ids``, PINNED: every matched page
        gains one pool reference so eviction cannot free it while the
        dispatch that gathers it is in flight. Callers MUST
        :meth:`release` the match after the dispatch returns.
        ``record=False`` skips the hit/miss counters (batch-padding rows
        duplicate a real row; their pins are needed, their stats are
        noise)."""
        path = self._walk(bucket, ids, touch=True)
        pages = tuple(n.page for n in path)
        self.pool.incref(pages)
        if record:
            self.stats.count("lookups")
            if pages:
                self.stats.count("hits")
        return PrefixMatch(pages=pages, tokens=len(pages) * self.page_size)

    def release(self, match: PrefixMatch) -> None:
        """Drop a lookup's dispatch pin. The tree's own reference keeps
        the pages cached; they merely become evictable again (a pinned
        node can never leave the tree — :meth:`_evictable_leaves`)."""
        self.pool.decref(match.pages)

    # -- token history (speculative drafting) --------------------------------

    def continuation(self, bucket: int, ids: Sequence[int],
                     k: int) -> Tuple[int, ...]:
        """READ-ONLY probe: up to ``k`` tokens the tree's own token
        history predicts will follow ``ids`` in the ``bucket``
        namespace — the prompt-lookup self-drafting source
        (engine/spec.py). Two histories compose, page-key descent
        first:

        - deeper PAGE KEYS: another sequence cached with ``ids`` as a
          proper prefix contributes its next chunks (most-recently-
          touched child wins — the workload's rephrasings make the
          hottest continuation the likeliest);
        - recorded TAILS (:meth:`record_tail`): a previously completed
          dispatch of this exact prompt contributes its observed
          continuation (suffix + emissions) beyond the paged prefix.

        Takes no references, touches no clocks, and is advisory by
        construction: a wrong continuation is merely a draft the
        verifier rejects (bitwise results regardless —
        tests/test_spec_decode.py)."""
        ids = [int(t) for t in ids]
        path = self._walk(bucket, ids, touch=False)
        depth = len(path)
        node = path[-1] if path else self._roots.get(int(bucket))
        if node is None:
            return ()
        rem = tuple(ids[depth * self.page_size:])
        out: List[int] = []
        while len(out) < k:
            cands = [c for key, c in node.children.items()
                     if key[:len(rem)] == rem]
            if not cands:
                break
            child = max(cands, key=lambda n: n.clock)
            out.extend(child.key[len(rem):])
            node, rem = child, ()
        if len(out) < k:
            tail = node.tails.get(rem)
            if tail:
                out.extend(tail)
        return tuple(out[:k])

    def record_tail(self, bucket: int, ids: Sequence[int],
                    tail: Sequence[int], max_tails: int = 32,
                    max_tokens: int = 512) -> bool:
        """Record that ``ids`` was observed continuing with ``tail``
        (the dispatch's format suffix + emitted tokens): the token-
        history side of the tree, host memory only. The record lands on
        the deepest node whose pages cover ``ids`` (or the namespace
        root), keyed by the sub-page remainder; per-node entries are
        LRU-capped at ``max_tails`` and a remainder+tail longer than
        ``max_tokens`` is refused (a sequence that shares no pages
        with anything cached is not worth remembering whole)."""
        ids = [int(t) for t in ids]
        tail = tuple(int(t) for t in tail)
        if not tail:
            return False
        path = self._walk(bucket, ids, touch=False)
        depth = len(path)
        node = path[-1] if path else self._root(bucket)
        rem = tuple(ids[depth * self.page_size:])
        if len(rem) + len(tail) > max_tokens:
            return False
        node.tails.pop(rem, None)           # re-insert = most recent
        node.tails[rem] = tail
        while len(node.tails) > max_tails:
            node.tails.pop(next(iter(node.tails)))
        return True

    # -- write side ----------------------------------------------------------

    def plan_insert(self, bucket: int,
                    ids: Sequence[int]) -> Tuple[int, List[int]]:
        """Allocate tree nodes + pool pages for every full-page chunk of
        ``ids`` not yet cached under ``bucket``. Returns (first uncached
        token index, page ids in chunk order) — the caller scatters the
        dispatch's freshly-computed KV into those pages
        (models/paged.scatter_pages via KVPagePool.scatter) and the
        pages are live for the NEXT lookup immediately (the scatter is
        ordered before any later gather on the host side).

        Allocation failure mid-run (pool exhausted, everything else
        pinned) stops the insert early: the tree caches a shorter
        prefix, never a torn one — a radix path is valid by
        construction since nodes are added parent-first."""
        chunks = self._chunks(ids)
        path = self._walk(bucket, ids, touch=True)
        node = path[-1] if path else self._root(bucket)
        start = len(path)
        new_pages: List[int] = []
        self._clock += 1
        for key in chunks[start:]:
            page = self._alloc_with_evict()
            if page is None:
                break
            child = _Node(key, page, node)
            child.clock = self._clock
            node.children[key] = child
            self.pool.incref((page,))          # the tree's own reference
            self._nodes += 1
            new_pages.append(page)
            node = child
        if new_pages:
            self.stats.count("inserted_pages", len(new_pages))
            covered = (start + len(new_pages)) * self.page_size
            self._notify("insert", bucket,
                         tuple(int(t) for t in ids[:covered]))
        self.stats.gauge_pages(self.pool.pages_in_use,
                               self.pool.n_pages - 1)
        return start * self.page_size, new_pages

    def forget_tail(self, bucket: int, ids: Sequence[int],
                    n_pages: int) -> int:
        """Remove the deepest ``n_pages`` nodes along ``ids``' cached
        path and drop the tree's page references — the ROLLBACK of a
        cancelled/corrupt page import (serve/migrate.py): the nodes a
        failed transfer created must leave the tree before any dispatch
        can gather their never-filled pages. Only tail nodes with no
        children are removable (exactly what a fresh plan_insert
        created); returns how many were removed."""
        path = self._walk(bucket, ids, touch=False)
        removed = 0
        for node in reversed(path[-n_pages:] if n_pages else []):
            if node.children:
                break           # someone extended past us: keep the path
            self._notify("evict", int(bucket), self._node_ids(node))
            del node.parent.children[node.key]
            self._nodes -= 1
            self.pool.decref((node.page,))
            removed += 1
        if removed:
            self.stats.gauge_pages(self.pool.pages_in_use,
                                   self.pool.n_pages - 1)
        return removed

    def evict_tail(self, bucket: int, ids: Sequence[int],
                   n_pages: int) -> int:
        """Like :meth:`forget_tail`, but for tier DEMOTION
        (serve/tiers.py): additionally refuses any page whose refcount
        is not exactly the tree's own 1 — a dispatch-pinned page must
        never leave HBM, demoted or otherwise. Removal walks from the
        deepest node up and stops at the first shared (has-children) or
        pinned node, so a partial demotion still leaves a valid radix
        path; returns how many pages actually left the tree (the
        caller counts the shortfall as pin refusals)."""
        path = self._walk(bucket, ids, touch=False)
        removed = 0
        for node in reversed(path[-n_pages:] if n_pages else []):
            if node.children or self.pool.refcount[node.page] != 1:
                break           # shared or pinned: the tail stops here
            self._notify("evict", int(bucket), self._node_ids(node))
            del node.parent.children[node.key]
            self._nodes -= 1
            self.pool.decref((node.page,))
            removed += 1
        if removed:
            self.stats.count("evicted_pages", removed)
            self.stats.gauge_pages(self.pool.pages_in_use,
                                   self.pool.n_pages - 1)
        return removed

    def _alloc_with_evict(self) -> Optional[int]:
        page = self.pool.alloc()
        if page is None and self.evict(1):
            page = self.pool.alloc()
        return page

    # -- eviction ------------------------------------------------------------

    def _evictable_leaves(self) -> List[_Node]:
        """Leaf nodes (across every bucket namespace) whose page holds
        exactly ONE reference (the tree's): no children depend on them
        and no dispatch has them pinned."""
        out: List[_Node] = []
        stack = [n for root in self._roots.values()
                 for n in root.children.values()]
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif self.pool.refcount[n.page] == 1:
                out.append(n)
        return out

    def coldest_leaves(self, limit: int = 8
                       ) -> List[Tuple[int, Tuple[int, ...]]]:
        """The stalest evictable leaves as (bucket, full token path)
        pairs, LRU-first — the tier-demotion candidate probe
        (serve/tiers.py): each pair names a whole cached prefix whose
        tail :meth:`evict_tail` can demote without touching pinned or
        shared pages. Read-only; takes no references."""
        leaves = sorted(self._evictable_leaves(), key=lambda n: n.clock)
        return [(self._node_bucket(n), self._node_ids(n))
                for n in leaves[:max(0, int(limit))]]

    def evict(self, n_pages: int) -> int:
        """Free >= ``n_pages`` pool pages by removing the least-recently
        -used evictable leaves, cascading into parents as they become
        leaves. Returns how many pages were actually freed (less than
        asked when everything else is pinned or interior)."""
        freed = 0
        candidates = sorted(self._evictable_leaves(), key=lambda n: n.clock)
        while freed < n_pages and candidates:
            node = candidates.pop(0)
            parent = node.parent
            self._notify("evict", self._node_bucket(node),
                         self._node_ids(node))
            del parent.children[node.key]
            self._nodes -= 1
            self.pool.decref((node.page,))
            freed += 1
            # The parent may have just become an evictable leaf that is
            # staler than remaining candidates — keep LRU order exact.
            # (Namespace roots carry key == () and are never evicted.)
            if (parent is not None and parent.key != ()
                    and not parent.children
                    and self.pool.refcount[parent.page] == 1):
                candidates.append(parent)
                candidates.sort(key=lambda n: n.clock)
        if freed:
            self.stats.count("evicted_pages", freed)
            self.stats.gauge_pages(self.pool.pages_in_use,
                                   self.pool.n_pages - 1)
        return freed


# ---------------------------------------------------------------------------
# Cluster-wide prefix index (router-side; ROADMAP item 2)
# ---------------------------------------------------------------------------


class ClusterPrefixIndex:
    """The radix prefix tree made CLUSTER-WIDE: a router-side index of
    which REPLICA holds which prefix pages, fed by every replica tree's
    :meth:`RadixPrefixCache.add_listener` insert/evict events — the
    same event-driven discipline the PR-12 weight-residency map rides.
    A prefix prefilled anywhere is then warm everywhere: placement
    reads :meth:`match_pages` (page residency beside weight residency
    and ``hbm_pressure`` in ``ReplicaRouter._pick``), and a migration
    (serve/migrate.py) pulls matching pages from the best holder
    instead of re-prefilling.

    The index stores token CHUNKS only (one dict node per page, no pool
    references, no HBM) and is ADVISORY by construction: the exporting
    replica re-looks its pages up with a pin, so a stale entry costs a
    shorter match or a fallback re-prefill, never a wrong answer.
    Thread-safe: listener events arrive on each replica's supervisor
    thread while the router thread matches.
    """

    def __init__(self, page_size: int = 16):
        self.page_size = int(page_size)
        self._lock = threading.Lock()
        # (replica_id, bucket, tier) -> nested {chunk-tuple: child dict}
        # — tier is a residency DIMENSION ("hbm" from the replica trees,
        # "host"/"disk" from each replica's TieredPageStore), so
        # placement can price "warm on host at replica 2" against "cold
        # everywhere" (serve/tiers.py; DEPLOY.md §1s).
        self._tries: Dict[Tuple[str, int, str], Dict] = {}  # guarded-by: _lock

    def _chunks(self, ids: Sequence[int]) -> List[Tuple[int, ...]]:
        ps = self.page_size
        return [tuple(int(t) for t in ids[k * ps:(k + 1) * ps])
                for k in range(len(ids) // ps)]

    def on_event(self, replica_id: str, event: str, bucket: int,
                 ids: Sequence[int], tier: str = "hbm") -> None:
        """One replica tree's page event (wire with
        ``tree.add_listener(functools.partial(index.on_event, rid))``);
        tier stores fire the same events with ``tier="host"``/``"disk"``
        via :meth:`on_tier_event`."""
        chunks = self._chunks(ids)
        if not chunks:
            return
        with self._lock:
            trie = self._tries.setdefault(
                (str(replica_id), int(bucket), str(tier)), {})
            if event == "insert":
                node = trie
                for ck in chunks:
                    node = node.setdefault(ck, {})
            elif event == "evict":
                node, hops = trie, []
                for ck in chunks:
                    child = node.get(ck)
                    if child is None:
                        return          # already pruned (advisory index)
                    hops.append((node, ck))
                    node = child
                parent, key = hops[-1]
                del parent[key]         # the page and its whole subtree

    def on_tier_event(self, replica_id: str, event: str, tier: str,
                      bucket: int, ids: Sequence[int]) -> None:
        """A TieredPageStore's movement event (serve/tiers.py
        ``TierListener`` contract — wire with ``store.add_listener(
        functools.partial(index.on_tier_event, rid))``). A tier entry
        ALWAYS covers a whole prefix, so ``event="evict"`` prunes the
        full path."""
        self.on_event(replica_id, event, bucket, ids, tier=tier)

    def drop_replica(self, replica_id: str) -> None:
        """Forget a replica's HBM pages wholesale (its pool died with
        it). Host/disk tier entries survive — they live outside the
        process's device memory and are exactly what a restart-warm
        rejoin re-serves."""
        with self._lock:
            for key in [k for k in self._tries
                        if k[0] == replica_id and k[2] == "hbm"]:
                del self._tries[key]

    def match_pages(self, bucket: int, ids: Sequence[int],
                    tier: str = "hbm") -> Dict[str, int]:
        """Pages of ``ids``' leading prefix each replica holds in the
        ``bucket`` namespace at ``tier`` right now — the placement/
        migration probe (tokens covered = pages * page_size)."""
        chunks = self._chunks(ids)
        out: Dict[str, int] = {}
        with self._lock:
            for (rid, b, t), trie in self._tries.items():
                if b != int(bucket) or t != str(tier):
                    continue
                node, n = trie, 0
                for ck in chunks:
                    node = node.get(ck)
                    if node is None:
                        break
                    n += 1
                if n:
                    out[rid] = max(out.get(rid, 0), n)
        return out

    def match_tiers(self, bucket: int, ids: Sequence[int]
                    ) -> Dict[str, Dict[str, int]]:
        """Every tier's match depth per replica: {replica_id: {tier:
        pages}} — ``ReplicaRouter._pick`` prices each tier's pages with
        its own bonus (HBM full, host/disk discounted)."""
        with self._lock:
            tiers = sorted({k[2] for k in self._tries})
        out: Dict[str, Dict[str, int]] = {}
        for t in tiers:
            for rid, pages in self.match_pages(bucket, ids,
                                               tier=t).items():
                out.setdefault(rid, {})[t] = pages
        return out

    def best_holder(self, bucket: int, ids: Sequence[int],
                    exclude: Optional[Sequence[str]] = None
                    ) -> Tuple[Optional[str], int]:
        """(replica with the deepest match, pages) — the migration
        source probe; (None, 0) when nothing matches."""
        matches = self.match_pages(bucket, ids)
        for rid in (exclude or ()):
            matches.pop(rid, None)
        if not matches:
            return None, 0
        rid = max(matches, key=lambda r: matches[r])
        return rid, matches[rid]
