"""Tokenizer adapters: target-token-id resolution + ragged batch packing.

SURVEY.md §7 ranks tokenizer semantics parity as hard part #1: the decoder
branch of the reference uses the first sub-token of the LEADING-SPACE
variants '" Yes"/" No"' (compare_base_vs_instruct.py:244-247, fallback to
bare "Yes"/"No" at compare_instruct_models.py:232-233), while the
encoder-decoder branch uses bare ``tokenizer("Yes").input_ids[0]``
(compare_base_vs_instruct.py:208-209). Mis-resolving these ids silently
corrupts every downstream statistic, so this module is the one place that
rule lives, and tests pin it per family.
"""

from __future__ import annotations

import re
from typing import List, Optional, Sequence, Tuple

import numpy as np


def first_token_id(tokenizer, text: str) -> int:
    ids = tokenizer(text, add_special_tokens=False).input_ids
    if len(ids) == 0:
        raise ValueError(f"tokenizer produced no ids for {text!r}")
    return int(ids[0])


def yes_no_ids(tokenizer, *, encoder_decoder: bool = False,
               yes_text: str = "Yes", no_text: str = "No") -> Tuple[int, int]:
    """Resolve the two target token ids under the reference's rules."""
    if encoder_decoder:
        return first_token_id(tokenizer, yes_text), first_token_id(tokenizer, no_text)
    try:
        return (first_token_id(tokenizer, " " + yes_text),
                first_token_id(tokenizer, " " + no_text))
    except ValueError:
        return first_token_id(tokenizer, yes_text), first_token_id(tokenizer, no_text)


def target_token_ids(tokenizer, targets: Sequence[str],
                     *, encoder_decoder: bool = False) -> List[int]:
    """First-token ids for arbitrary target strings (legal prompts use e.g.
    'Covered'/'Not' — perturb_prompts.py target_tokens)."""
    out = []
    for t in targets:
        if encoder_decoder:
            out.append(first_token_id(tokenizer, t))
        else:
            try:
                out.append(first_token_id(tokenizer, " " + t))
            except ValueError:
                out.append(first_token_id(tokenizer, t))
    return out


def integer_token_table(tokenizer, lo: int = 0, hi: int = 100
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """(token_ids, values) for every single-token rendering of an integer in
    [lo, hi] — with and without leading space. Feeds
    engine.score.weighted_confidence (reference E[v] readout,
    perturb_prompts.py:504-526, which scans top_logprobs for integer-parsable
    token strings)."""
    ids, vals = [], []
    seen = set()
    for v in range(lo, hi + 1):
        for text in (str(v), " " + str(v)):
            toks = tokenizer(text, add_special_tokens=False).input_ids
            if len(toks) == 1 and toks[0] not in seen:
                seen.add(toks[0])
                ids.append(int(toks[0]))
                vals.append(float(v))
    return np.asarray(ids, np.int32), np.asarray(vals, np.float32)


# Bit flags of digit_stop_classes (the confidence early stop's per-token
# surface classification; consumed by generate._fused_tail).
STOP_PURE = 1         # surface (after any space prefix) is digits only
STOP_PREFIX = 2       # surface begins with a word-boundary prefix (▁/Ġ/ws)
STOP_STARTS_WORD = 4  # glues onto the previous token (first char is a word
                      # char with NO space prefix — "st" after "1" = "1st")
STOP_ENDS_WORD = 8    # last decoded char is a word char
STOP_TRANSPARENT = 16  # decodes to nothing (bracketed specials): invisible
                       # to the text, so it must not start/stop anything

def eos_only_stop_classes(vocab_size: int) -> np.ndarray:
    """(vocab_size,) all-STOP_TRANSPARENT class table: under
    generate._fused_tail's rule a transparent token freezes every piece
    of text state (no digit run ever opens), so the only remaining done
    condition is ``emit == eos_id`` — a pure all-rows-emitted-EOS stop
    with exactly the trim-at-EOS semantics the host applies to response
    text anyway (runner.decode_completion / HF generate parity). Used for
    the sweep's BINARY branch, whose numeric readout consumes position 0
    only (perturb_prompts.py:474-526): skipped trailing steps can never
    change a recorded value, they are pure EOS fill."""
    return np.full((vocab_size,), STOP_TRANSPARENT, np.int32)


_SPACE_PREFIX = ("▁", "Ġ", "Ċ", " ", "\t", "\n", "\r")
_BYTE_FORM = re.compile(r"<0[xX]([0-9A-Fa-f]{2})>")
_SPECIAL_FORM = re.compile(r"<[^<>]*>")


def _is_word(c: str) -> bool:
    """Unicode word character, matching the ``\\b`` semantics of the
    confidence parse's ``\\b\\d+\\b`` ('è' is a word char: '2ème' has no
    boundary after the 2, so it must read as glue here too)."""
    return c.isalnum() or c == "_"


def digit_stop_classes(tokenizer, vocab_size: int) -> Optional[np.ndarray]:
    """(vocab_size,) int32 bitmask classifying every token's DECODED
    surface for the confidence early stop (generate._fused_tail): the scan
    may halt a row only once its text provably contains a complete
    standalone integer — the exact ``\\b(\\d+)\\b`` ``_parse_confidence``
    reads (perturb_prompts.py:500-502). "contains a digit" alone is wrong
    both ways: '<0x0A>' has a surface digit but decodes to a newline, and
    '1'+'st' shows a digit the parse can never match ("1st" has no word
    boundary after the 1).

    Needs real per-token strings (``convert_ids_to_tokens``); returns None
    otherwise (e.g. the test FakeTokenizer) and callers disable the stop.
    """
    convert = getattr(tokenizer, "convert_ids_to_tokens", None)
    if convert is None:
        return None
    # Model vocab may be padded past the tokenizer's (multiple-of-128
    # embedding tables): padding rows class 0 (never argmax in a trained
    # model anyway).
    try:
        n = min(vocab_size, len(tokenizer))
    except TypeError:
        n = vocab_size
    try:
        toks = convert(list(range(n)))
    except Exception:  # noqa: BLE001 — added-token gaps
        return None

    # Transparency comes from the tokenizer's own metadata, not surface
    # form: ordinary vocab pieces can fullmatch <...> yet decode to literal
    # text (<div>, <br> in code-trained vocabs) — those must be classified
    # by their surface like any other token (ADVICE r4).
    special_ids: set = set()
    for i in (getattr(tokenizer, "all_special_ids", None) or ()):
        special_ids.add(int(i))
    added = getattr(tokenizer, "added_tokens_decoder", None)
    if added:
        try:
            for tid, tok in added.items():
                if getattr(tok, "special", False):
                    special_ids.add(int(tid))
        except Exception:  # noqa: BLE001 — non-dict implementations
            pass
    to_string = getattr(tokenizer, "convert_tokens_to_string", None)

    def _classify(i: int, t) -> int:
        if t is None:
            return 0
        m = _BYTE_FORM.fullmatch(t)
        if m:
            t = chr(int(m.group(1), 16))   # the byte's actual character
        elif i in special_ids:
            return STOP_TRANSPARENT
        elif _SPECIAL_FORM.fullmatch(t):
            # Looks special but isn't registered: either a raw-tokenizer
            # special invisible to metadata (decodes to "") or a literal
            # vocab piece like <div> — ask the tokenizer which.
            if to_string is not None:
                try:
                    surface = to_string([t])
                except Exception:  # noqa: BLE001
                    surface = t
                if surface == "":
                    return STOP_TRANSPARENT
                t = surface
        stripped = t.lstrip("".join(_SPACE_PREFIX))
        prefix = len(stripped) < len(t)
        cls = STOP_PREFIX if prefix else 0
        if stripped and all(c in "0123456789" for c in stripped):
            cls |= STOP_PURE
        if stripped and not prefix and _is_word(stripped[0]):
            cls |= STOP_STARTS_WORD
        # ENDS_WORD reads the DECODED tail: a prefix-only token ('Ġ' is a
        # letter codepoint but decodes to a space) ends at a boundary.
        if stripped and _is_word(stripped[-1]):
            cls |= STOP_ENDS_WORD
        return cls

    mask = np.zeros((vocab_size,), dtype=np.int32)
    mask[:n] = [_classify(i, t) for i, t in enumerate(toks)]
    return mask


def pad_token_id(tokenizer) -> int:
    pid = getattr(tokenizer, "pad_token_id", None)
    if pid is None:
        pid = getattr(tokenizer, "eos_token_id", 0) or 0
    return int(pid)


def left_pad_ids(ids_list: Sequence[Sequence[int]], max_len: int,
                 pad_id: int) -> Tuple[np.ndarray, np.ndarray]:
    """LEFT-pad pre-tokenized prompts to (B, max_len) int32 (tokens, mask).

    Left padding keeps the prompt end at position max_len-1 for every row, so
    one jitted prefill serves ragged prompts (decoder.mask_positions gives
    pads position 0 and the bias masks them out). Truncates from the left if
    a prompt exceeds max_len (reference prompts are ≲700 tokens, SURVEY §5).
    """
    B = len(ids_list)
    tokens = np.full((B, max_len), pad_id, np.int32)
    mask = np.zeros((B, max_len), np.int32)
    for i, ids in enumerate(ids_list):
        ids = list(ids)[-max_len:]
        tokens[i, max_len - len(ids):] = ids
        mask[i, max_len - len(ids):] = 1
    return tokens, mask


def left_pad_batch(tokenizer, prompts: Sequence[str], max_len: int,
                   *, add_special_tokens: bool = True
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Tokenize + LEFT-pad to (B, max_len) int32 (tokens, mask)."""
    ids_list = [tokenizer(p, add_special_tokens=add_special_tokens).input_ids
                for p in prompts]
    return left_pad_ids(ids_list, max_len, pad_token_id(tokenizer))


def trim_at_eos(ids: Sequence[int], eos_id: Optional[int]) -> List[int]:
    """Drop the first EOS and everything after it — parity with HF
    ``generate`` stopping at EOS (the jitted decode runs a fixed number of
    steps, so post-EOS garbage must not leak into decoded completions or the
    confidence-integer parse)."""
    ids = [int(i) for i in ids]
    if eos_id is None:
        return ids
    try:
        return ids[: ids.index(int(eos_id))]
    except ValueError:
        return ids


def right_pad_ids(ids_list: Sequence[Sequence[int]], max_len: int,
                  pad_id: int) -> Tuple[np.ndarray, np.ndarray]:
    """RIGHT-pad pre-tokenized suffixes to (B, max_len) int32 (tokens, mask).

    Format suffixes in the shared-prefix sweep path sit AFTER a left-padded
    prefix in the KV cache, so their real tokens must start at the first
    suffix slot; the decoder reads per-row validity from the mask
    (decoder.extend). Truncates from the right if a suffix exceeds max_len.
    """
    B = len(ids_list)
    tokens = np.full((B, max_len), pad_id, np.int32)
    mask = np.zeros((B, max_len), np.int32)
    for i, ids in enumerate(ids_list):
        ids = list(ids)[:max_len]
        tokens[i, :len(ids)] = ids
        mask[i, :len(ids)] = 1
    return tokens, mask


def shared_prefix_len(a: Sequence[int], b: Sequence[int]) -> int:
    """Longest common token prefix of two prompts, capped so BOTH suffixes
    keep at least one real token (decoder.extend reads its branch logits
    from the last real suffix position — an empty suffix has none).

    Splitting at the common-token boundary (instead of at a string
    boundary) is tokenizer-agnostic: BPE merges that cross the text split
    point simply shorten the shared prefix by a token or two."""
    cap = min(len(a), len(b)) - 1
    n = 0
    while n < cap and a[n] == b[n]:
        n += 1
    return max(n, 0)


def common_prefix_len(rows: Sequence[Sequence[int]]) -> int:
    """Longest common token prefix across ALL rows — the shared-trunk
    extent of a dispatch (runner.cascade_trunk_for snaps it to the
    trunk-quantum grid). Unlike :func:`shared_prefix_len` there is no
    keep-a-suffix cap: a row whose whole prefix IS the trunk simply
    contributes zero remainder tokens to the cascade extension (its
    remainder slots are masked, the standard pad-slot discipline)."""
    if not rows:
        return 0
    n = min(len(r) for r in rows)
    first = rows[0]
    for i in range(n):
        t = first[i]
        for r in rows[1:]:
            if r[i] != t:
                return i
    return n


def pick_bucket(lengths: Sequence[int], buckets: Sequence[int]) -> int:
    """Smallest bucket that fits the longest prompt (static-shape discipline:
    one compile per bucket instead of one per length)."""
    m = max(lengths)
    for b in sorted(buckets):
        if b >= m:
            return b
    return max(buckets)


# Flash-attention block edge (ops/flash_attention DEFAULT_BLOCK_Q/K): a
# prefill length qualifies for the Pallas kernel when S <= block or
# S % block == 0, so bucket edges above one block must be multiples of it
# or every dispatch in that bucket silently falls back to dense attention.
FLASH_BLOCK = 128


def bucket_ladder(max_len: int, min_bucket: int = 64,
                  align: int = FLASH_BLOCK) -> Tuple[int, ...]:
    """Prompt-length bucket edges for the ragged sweep scheduler.

    A geometric ~sqrt(2) ladder instead of the old powers-of-two set: each
    step pays at most ~41% padding waste in the worst case (vs 100% for
    x2 steps), and every edge stays flash-eligible — edges <= ``align``
    are free-form (the kernel shrinks its block to S), edges above it are
    rounded UP to a multiple of ``align``. Rounding collapses near-equal
    steps, so the ladder is strictly increasing and ends exactly at a
    cap >= ``max_len``'s covering edge, clipped to max_len when max_len
    itself is not on the grid (the engine's truncation semantics need a
    bucket that equals the configured ceiling).

    One XLA compile per (bucket, batch) pair is the cost of each extra
    edge; ~9 edges at 1024 keeps that bounded while cutting the padded
    FLOPs the single-bucket path burns on short prompts.
    """
    if max_len < min_bucket:
        return (max_len,)
    edges: List[int] = []
    x = float(min_bucket)
    while True:
        e = int(round(x))
        # Edges at or under one flash block stay lane-friendly (x16);
        # above it they must be whole blocks (see FLASH_BLOCK).
        step = 16 if e <= align else align
        e = ((e + step - 1) // step) * step
        if e >= max_len:
            break
        if not edges or e > edges[-1]:
            edges.append(e)
        x *= 2 ** 0.5
    edges.append(max_len)
    return tuple(edges)


def assign_bucket(length: int, buckets: Sequence[int]) -> int:
    """Smallest bucket edge >= ``length``; over-long prompts land in the
    largest bucket (left-truncation semantics, same as pick_bucket). Total
    and deterministic: every length maps to exactly one edge."""
    for b in sorted(buckets):
        if b >= length:
            return b
    return max(buckets)
