"""Sweep drivers: word-meaning model comparison (D1/D2) and the perturbation
grid (D6), with manifest resume and periodic checkpoints.

These replace the reference's two L2 orchestration bodies:
- compare_base_vs_instruct.py:386-550 / compare_instruct_models.py:376-566
  (sequential per-prompt GPU loops -> one batched TPU call per bucket), and
- perturb_prompts.py:551-726,917-1066 (OpenAI Batch upload/poll/decode ->
  local batched scoring; checkpoint-every-100-rows and done-set resume
  semantics preserved, perturb_prompts.py:975-984,161-188).
"""

from __future__ import annotations

import json
import queue
import re
import threading
from pathlib import Path
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import RetryConfig
from ..data import schemas
from ..guard import numerics
from ..observe import registry as metrics_mod
from ..observe import tracing
from ..data.prompts import LegalPrompt
from ..utils.logging import get_logger
from ..utils.manifest import SweepManifest
from ..utils.profiling import OccupancyStats, StreamStats
from ..utils.retry import retry_with_exponential_backoff
from . import compile_plan
from . import generate
from . import grid as grid_mod
from . import scheduler as sched_mod
from . import score as score_mod
from . import stream_stats as stream_mod
from . import tokens as tok
from .runner import PiggybackIneligible, ScoringEngine, _tail_batch

log = get_logger(__name__)

CHECKPOINT_EVERY = 100  # rows, perturb_prompts.py:975-984

# Device-dispatch recovery policy for the offline sweep: a transient
# XLA/runtime fault (or an injected chaos fault — lir_tpu/faults) costs
# a short full-jitter retry window, not the sweep. Deliberately brief:
# the sweep resumes from its manifest anyway, so a persistent outage
# should fail fast into the operator's restart loop rather than sleep
# through it.
DISPATCH_RETRY = RetryConfig(max_retries=3, initial_delay=0.05,
                             max_delay=1.0, backoff_factor=2.0,
                             full_jitter=True, max_elapsed=30.0)


def _dispatch_with_recovery(engine, call, cost=None):
    """Run one device dispatch with the sweep's self-healing ladder: on
    failure, degrade the AOT registry to lazy jit (a corrupt precompiled
    executable is the first suspect — runner.degrade_to_lazy also resets
    the donation chain the failed dispatch may have consumed) and retry
    under DISPATCH_RETRY. KeyboardInterrupt/SystemExit and simulated
    preemptions (BaseException) always propagate — recovery outlives
    faults, not kills.

    The call runs under the engine's dispatch WATCHDOG (guard/watchdog):
    ``cost`` is the dispatch's scheduler.bucket_cost() price, and a call
    that outlives floor + multiple * predicted seconds is abandoned with
    a thread-stack dump and surfaces DispatchStalled — an ordinary
    Exception, so a HANG flows through exactly this recovery path (one
    deadline lost, then degrade + retry) instead of parking the sweep
    forever."""
    from ..utils.profiling import is_oom_error

    wd = getattr(engine, "watchdog", None)
    if wd is not None and wd.enabled:
        inner = call
        call = lambda: wd.watch(inner, cost=cost, site="sweep")  # noqa: E731

    gov = getattr(engine, "governor", None)
    try:
        out = call()
        if gov is not None:
            gov.tick()      # one ladder tick per dispatch boundary
        return out
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception as err:  # noqa: BLE001 — retried below
        if is_oom_error(err):
            # Capacity, not transience — the retry/backoff ladder would
            # only re-OOM. Route through the governor: force-engage the
            # reclaim rungs (idle weights, cold pages, the piggyback
            # carry) and retry ONCE against the freed headroom. A
            # second OOM is the irreducible dispatch: raise with the
            # full ledger arithmetic (the bench/tools batch ladder
            # still owns the final fallback).
            from . import hbm

            if gov is not None and gov.handle_oom("sweep"):
                log.warning("sweep dispatch OOMed (%r); governor "
                            "reclaimed — retrying once", err)
                try:
                    return call()
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as err2:  # noqa: BLE001
                    if is_oom_error(err2):
                        gov.stats.count("oom_exhausted")
                        raise hbm.HbmExhausted(
                            gov.oom_message("sweep", err2)) from err2
                    raise
            raise
        log.warning("sweep dispatch failed (%r); degrading AOT registry "
                    "-> lazy jit and retrying", err)
        engine.degrade_to_lazy()
        out = retry_with_exponential_backoff(
            call, retry_on=(Exception,), config=DISPATCH_RETRY,
            log=lambda m: log.warning("sweep dispatch retry: %s", m))
        engine.fault_stats.count("recovered_dispatches")
        return out


def run_word_meaning_sweep(
    engine: ScoringEngine, model_name: str, base_or_instruct: str,
    questions: Sequence[str], format_prompt: Callable[[str], str],
) -> List[schemas.ScoreRow]:
    """Score the 50 word-meaning questions for one model -> D1/D2 rows.

    ``format_prompt`` is the C14 formatter (few-shot for base models, direct
    for instruct — compare_base_vs_instruct.py:462-463)."""
    prompts = [format_prompt(q) for q in questions]
    results = engine.score_prompts(prompts)
    rows = []
    for q, r in zip(questions, results):
        rows.append(schemas.ScoreRow(
            prompt=q, model=model_name, base_or_instruct=base_or_instruct,
            model_output=r.completion, yes_prob=r.yes_prob, no_prob=r.no_prob,
            position_found=r.position_found, yes_no_found=r.yes_no_found))
    return rows


def _parse_confidence(text: str, complete: bool = True) -> Optional[int]:
    """First integer in the response (perturb_prompts.py:500-502).

    ``complete=False`` marks a decode that hit its token budget without
    emitting EOS: an integer whose digits touch the end of such text may be
    cut mid-number ("...about 85" truncated to "...about 8"), so it is
    rejected (None) rather than silently recorded wrong. An integer followed
    by more text is always safe.

    The prompt asks for a confidence in [0, 100]; an integer outside that
    range ("confidence: 250", a year, a policy number) is model noise, not
    a confidence, and recording it verbatim poisons every downstream
    confidence statistic — rejected (None), same as no integer at all.
    """
    m = re.search(r"\b(\d+)\b", text)
    if m is None:
        return None
    if not complete and m.end() == len(text.rstrip()):
        return None
    try:
        val = int(m.group(1))
    except ValueError:
        return None
    if not 0 <= val <= 100:
        return None
    return val


def _decode_complete(generated_row: np.ndarray, eos_id) -> bool:
    """True when the fixed-length decode emitted EOS (the reply finished
    inside the budget). Tokenizers without EOS can't signal completion;
    treat their output as complete (legacy behavior)."""
    if eos_id is None:
        return True
    return bool(np.any(np.asarray(generated_row) == eos_id))


def run_perturbation_sweep(
    engine: ScoringEngine, model_name: str,
    prompts: Sequence[LegalPrompt], perturbations: Sequence[Sequence[str]],
    results_path: Path, manifest: Optional[SweepManifest] = None,
    subset_size: Optional[int] = None, seed: int = 42,
    checkpoint_every: int = CHECKPOINT_EVERY,
    reasoning: bool = False, reasoning_runs: int = 10,
) -> List[schemas.PerturbationRow]:
    """Run (or resume) the perturbation grid for one model, writing D6 rows.

    Readout parity with the API backend (perturb_prompts.py:474-526):
    - Token_1/2_Prob come from the FIRST generated position (scan_positions=1,
      not the local backend's 10-position rule). The reference zeroes a
      target's probability when it falls outside the top-20 logprobs; we
      compute the exact softmax probability instead (strict improvement,
      noted for the judge diff).
    - 'Log Probabilities' stores the top-20 (token_id -> logprob) map.
    - Confidence value = first integer in the decoded confidence response;
      Weighted Confidence = E[v] over integer tokens in [0,100] at the first
      confidence position.

    ``reasoning=True`` is the local reasoning-model mode (REASONING_MODEL_
    RUNS, perturb_prompts.py:47,412-446): each binary prompt is sampled
    ``reasoning_runs`` times and Token_i_Prob becomes the answer-count
    fraction (runner.score_prompts_sampled); Weighted Confidence equals the
    parsed confidence integer (:459-464) and no logprob map is stored.
    """
    results_path = schemas.resolve_results_path(results_path)
    # Multi-host pods: each host owns a deterministic shard of the grid and
    # its OWN results/manifest files (suffix .hostN) — disjoint writes, and
    # a preempted host resumes exactly its shard. Single-process runs leave
    # paths untouched.
    from ..parallel import multihost

    if manifest is not None and multihost.is_multiprocess():
        # An explicit manifest + multi-process execution would make every
        # host sweep the FULL grid and race on one results file. Refuse
        # loudly instead of silently duplicating work (ADVICE r2 #1).
        raise ValueError(
            "explicit manifest is incompatible with multi-process execution: "
            "each host must own its .hostN results/manifest shard — pass "
            "manifest=None and let the sweep derive per-host paths")
    shard_grid = manifest is None and multihost.is_multiprocess()
    base_results_path = results_path
    if shard_grid:
        i = __import__("jax").process_index()
        results_path = results_path.with_name(
            f"{results_path.stem}.host{i}{results_path.suffix}")
        log.info("multihost: process %d writes %s", i, results_path)
    # Leased shards (engine/lease.py): work distribution by lease
    # records in a SHARED <results>.leases.jsonl log instead of the
    # static host_shard split — every host sees the full grid, claims
    # shards, and steals expired ones, so a slow or dead host
    # rebalances instead of strangling the shard fence. Re-scored rows
    # fold into the streaming lattice as bitwise no-ops (slot
    # idempotence); pair with --no-row-artifact on pods, where a
    # stolen shard's rows would otherwise appear in two hosts' row
    # files (DEPLOY.md §1m).
    lease_mode = (engine.rt.lease_shards and not reasoning
                  and not engine.encoder_decoder)
    # Crash-consistent resume: the done-set is the UNION of the manifest
    # and the rows already in the results artifact. The flush order is
    # results-append THEN manifest-mark, so a kill between the two leaves
    # rows only the results file knows about — a manifest-only resume
    # would re-score and duplicate them (pinned by tools/chaos_smoke.py).
    # (`manifest or ...` would silently replace an EMPTY explicit
    # manifest — len() == 0 is falsy — discarding any wrapping/faking a
    # caller attached to it; test None explicitly.)
    if manifest is None:
        manifest = SweepManifest.from_existing_results(
            results_path.with_suffix(".manifest.jsonl"), results_path,
            grid_mod.RESUME_KEY_FIELDS,
            column_map=grid_mod.RESUME_COLUMN_MAP)
    engine.occupancy = None  # set by _run_pipelined's ragged planner
    cells = grid_mod.build_grid(model_name, prompts, perturbations)
    cells = grid_mod.random_subset(cells, subset_size, seed)
    if shard_grid and not lease_mode:
        cells = multihost.host_shard(cells)
    todo = grid_mod.pending_cells(cells, manifest)
    log.info("%s: %d/%d grid cells pending", model_name, len(todo), len(cells))

    # Streaming statistics (engine/stream_stats.py): a device-resident
    # accumulator lattice every scoring dispatch updates with ONE fused
    # XLA call — grid -> percentile/kappa/bootstrap-CI estimates without
    # round-tripping rows through the host. The bootstrap key is
    # RECORDED in the manifest on first run and read back on resume, so
    # streaming CIs are reproducible across resume and across
    # --no-streaming-stats re-runs over the row artifact; the
    # accumulator itself checkpoints at every flush boundary (atomic
    # write) and re-seeds from that checkpoint, with re-folds of
    # already-dispatched rows idempotent by slot layout.
    sink = None
    accum_path = None
    write_rows = True
    if (engine.rt.streaming_stats and not reasoning
            and not engine.encoder_decoder and cells):
        n_reph = 1 + max(c.rephrase_idx for c in cells)
        stream_seed = manifest.meta.get("stream_seed")
        if stream_seed is None:
            stream_seed = int(seed)
            manifest.set_meta("stream_seed", stream_seed)
        sink = stream_mod.StreamSink(
            len(prompts), n_reph, int(stream_seed),
            guard=engine.rt.numerics_guard, stats=StreamStats())
        accum_path = results_path.with_suffix(stream_mod.ACCUM_SUFFIX)
        if len(manifest) and accum_path.exists():
            if sink.load(accum_path):
                log.info("streaming stats: resumed accumulator from %s "
                         "(%d rows already folded)", accum_path,
                         sink.snapshot().rows_folded)
        write_rows = bool(engine.rt.row_artifact)
    engine.stream_sink = sink
    if sink is not None and getattr(engine, "governor", None) is not None:
        # Accumulator lattice: a small but real device-resident
        # consumer — the ledger carries it so pressure math is honest.
        engine.governor.register("stream_accum", sink.accum_bytes)

    # Pre-resolve per-prompt target token ids once (SURVEY §7 hard part 1).
    target_ids = {
        pi: tok.target_token_ids(engine.tokenizer, p.target_tokens,
                                 encoder_decoder=engine.encoder_decoder)
        for pi, p in enumerate(prompts)
    }

    rows: List[schemas.PerturbationRow] = []
    pending_rows: List[schemas.PerturbationRow] = []
    B = engine.rt.batch_size
    checkpoint_every = max(1, checkpoint_every)
    # Only position 0 feeds the D6 readouts; decode just enough tokens for
    # the confidence integer / leading response text unless full-completion
    # parity is requested (config.RuntimeConfig.sweep_decode_tokens).
    # Reasoning mode ignores these budgets on purpose: its models emit
    # chain-of-thought BEFORE the answer, so every sampled run gets the full
    # max_new_tokens (the reference gives them max_completion_tokens=2000,
    # perturb_prompts.py:249-252).
    new_tokens = (engine.rt.max_new_tokens if engine.rt.sweep_full_completions
                  else min(engine.rt.sweep_decode_tokens,
                           engine.rt.max_new_tokens))
    conf_tokens = (engine.rt.max_new_tokens
                   if engine.rt.sweep_full_completions
                   else min(engine.rt.sweep_confidence_tokens,
                            engine.rt.max_new_tokens))
    lease_mgr = None
    lease_shards_list = None
    score_shard = None
    if reasoning:
        for start in range(0, len(todo), B):
            batch = todo[start:start + B]
            n = len(batch)
            bsz = B if n == B else _tail_batch(n, B)
            full = list(batch) + [batch[-1]] * (bsz - n)
            pending_rows, rows = _reasoning_batch(
                engine, model_name, prompts, batch, full, seed,
                reasoning_runs, pending_rows, rows)
            if len(pending_rows) >= checkpoint_every:
                _flush(pending_rows, results_path, manifest)
                pending_rows = []
    else:
        engine.compile_stats.snapshot_persistent()
        if lease_mode and todo:
            from . import lease as lease_mod

            jx = __import__("jax")
            lease_path = schemas.resolve_results_path(
                base_results_path).with_suffix(lease_mod.LEASE_SUFFIX)
            lease_mgr = lease_mod.LeaseManager(
                lease_path, holder=f"host{jx.process_index()}",
                ttl_s=engine.rt.lease_ttl_s)
            # Renew-on-flush: every durable manifest flush extends the
            # held leases — progress is the heartbeat.
            lease_mgr.attach_manifest(manifest)
            # Shards partition the FULL grid (not the pending subset):
            # shard ids must be stable across resumes and across hosts,
            # or a resumed holder's lease records would name different
            # cells than the ones it scored. Per-shard scoring filters
            # to pending cells, so a fully-done shard just closes out.
            lease_shards_list = lease_mod.partition_shards(
                cells, engine.rt.lease_cells_per_shard,
                n_holders=jx.process_count())
            log.info("lease mode: %d pending cells over %d shards "
                     "(ttl %.0fs, log %s)", len(todo),
                     len(lease_shards_list), lease_mgr.ttl_s,
                     lease_path)

            def score_shard(shard_cells):
                pend = grid_mod.pending_cells(shard_cells, manifest)
                if pend:
                    _run_pipelined(
                        engine, model_name, pend, target_ids,
                        results_path, manifest, checkpoint_every,
                        new_tokens, conf_tokens, rows, pending_rows,
                        sink=sink, accum_path=accum_path,
                        write_rows=write_rows)
                if pending_rows:
                    # Flush BEFORE the done-record: a shard is only
                    # "done" once its rows/marks are durable.
                    _flush(pending_rows, results_path, manifest,
                           sink=sink, accum_path=accum_path)
                    del pending_rows[:]
        try:
            if lease_mgr is None:
                _run_pipelined(engine, model_name, todo, target_ids,
                               results_path, manifest, checkpoint_every,
                               new_tokens, conf_tokens, rows,
                               pending_rows, sink=sink,
                               accum_path=accum_path,
                               write_rows=write_rows)
            else:
                for sid, shard_cells in lease_mgr.claim_loop(
                        lease_shards_list):
                    with tracing.span("lease/shard", shard=int(sid),
                                      cells=len(shard_cells)):
                        score_shard(shard_cells)
                    lease_mgr.mark_done(sid)
        finally:
            # Flush the PARTIAL accumulator on every exit path —
            # including a preemption kill (BaseException) and the chaos
            # harness's injected faults — so a resumed sweep seeds from
            # the latest folds. Safe against the manifest done-set:
            # folds are idempotent per cell, so rows dispatched-but-not-
            # marked re-fold to bitwise-identical values, never double-
            # count (pinned by make chaos-smoke scenario 7).
            if sink is not None and accum_path is not None:
                sink.checkpoint(accum_path)
        engine.compile_stats.finish_persistent()
        log.info("compile plan: %s",
                 json.dumps(engine.compile_stats.summary()))
        if engine.prefix_cache is not None:
            log.info("prefix cache: %s",
                     json.dumps(engine.prefix_stats.summary()))
        if engine.fault_stats.recovered_dispatches:
            log.info("fault recovery: %s",
                     json.dumps(engine.fault_stats.summary()))
        if lease_mgr is not None:
            log.info("shard leases: %s",
                     json.dumps(lease_mgr.stats.summary()))
        if getattr(engine, "kernel_stats", None) is not None \
                and engine.kernel_stats.counters:
            log.info("piggyback chains: %s",
                     json.dumps(engine.kernel_stats.counters))
        if getattr(engine, "spec_stats", None) is not None:
            engine.spec_flush()
            if engine.spec_stats.spec_dispatches:
                log.info("speculative decode: %s",
                         json.dumps(engine.spec_stats.summary()))
        if sink is not None:
            # Cheap finalize (counts + kappa; CIs on demand via
            # sink.finalize(n_boot=...)) — the live-estimate readout.
            final = sink.finalize(n_boot=0)
            log.info("streaming stats: %d rows folded on device, "
                     "kappa=%.4f; counters: %s",
                     final["rows_folded"], final["kappa"]["kappa"],
                     json.dumps(sink.stats.summary()))
        # Per-sweep unified metrics dump (observe/registry): the SAME
        # canonical snapshot schema the serve {"op": "metrics"}
        # endpoint answers live, with the per-device HBM gauges.
        log.info("metrics: %s", json.dumps(
            metrics_mod.engine_registry(engine, sink=sink).snapshot()))

    if pending_rows:
        _flush(pending_rows, results_path, manifest, sink=sink,
               accum_path=accum_path)
    if shard_grid:
        # A host whose shard had zero pending cells (grid smaller than the
        # pod, or a fully-resumed shard) still writes a header-only shard
        # file: the post-barrier merge distinguishes "host had nothing to
        # do" from "shard invisible — no shared filesystem" by existence.
        if write_rows and not results_path.exists():
            schemas.write_perturbation_results([], results_path)
        # Fence so no host's caller reads partial peers; per-host workbooks
        # concatenate row-wise (the D6 schema has no cross-row state).
        # LIVENESS-GUARDED (parallel/multihost.py): a heartbeat allgather
        # + timeout-bounded barrier, so a dead peer host raises
        # HostDesyncError on the survivors — whose shard artifacts and
        # manifests are already flushed, hence resumable — instead of
        # parking every live host inside the collective forever.
        if lease_mgr is not None:
            # LEASE-AWARE fence: drain the lease log before barriering —
            # steal and score shards whose holder's lease expired (dead
            # or straggling peer), so the fence closes after at most
            # one TTL of straggle instead of waiting out the slowest
            # static shard. Stolen re-scores fold bitwise-idempotently.
            def _steal_and_score() -> bool:
                got = lease_mgr.steal_expired(lease_shards_list)
                if got is None:
                    return False
                sid, shard_cells = got
                with tracing.span("lease/shard", shard=int(sid),
                                  cells=len(shard_cells), stolen=True):
                    score_shard(shard_cells)
                lease_mgr.mark_done(sid)
                return True

            multihost.lease_fence(
                "perturbation-lease-drain", lease_mgr.all_done,
                _steal_and_score,
                timeout_s=engine.rt.barrier_timeout_s,
                payload=len(rows), stats=engine.guard_stats)
        else:
            multihost.liveness_barrier(
                "perturbation-sweep-done",
                timeout_s=engine.rt.barrier_timeout_s,
                payload=len(rows), stats=engine.guard_stats)
        if sink is not None:
            # Streaming-statistics fence merge: allgather every host's
            # (disjoint) shard accumulator and union slot-wise — ONE
            # small collective per sweep, so a pod-wide run produces
            # one global accumulator without any host touching rows.
            # Runs between the liveness barriers: peers are known alive
            # and their folds flushed. Every host computes the merged
            # lattice (the collective is symmetric); host 0 persists it
            # next to the merged row artifact.
            # Leased sweeps tolerate IDENTICAL overlap: a stolen
            # shard's re-scored rows appear in two hosts' lattices,
            # bitwise-equal by slot idempotence (asserted by the
            # merge). Static shards stay disjoint-or-error.
            merged_acc = sink.merge_across_hosts(
                allow_identical_overlap=lease_mgr is not None)
            if __import__("jax").process_index() == 0:
                merged_path = schemas.resolve_results_path(
                    base_results_path).with_suffix(
                        stream_mod.ACCUM_SUFFIX)
                stream_mod.save_accum(merged_acc, merged_path)
                log.info("multihost: merged stream accumulator -> %s "
                         "(%d rows folded)", merged_path,
                         merged_acc.rows_folded)
        if __import__("jax").process_index() == 0 and write_rows:
            # Gather step on a shared filesystem: merge every visible
            # .hostN shard (+ manifests) into the final artifact — the
            # reference's "download each batch output and append"
            # (perturb_prompts.py:161-188). Hosts without a shared fs see
            # only their own shard; gather_rows covers that topology.
            merged = schemas.concat_host_shards(
                base_results_path,
                n_hosts=__import__("jax").process_count())
            if merged is not None:
                log.info("multihost: merged host shards -> %s (%d rows)",
                         schemas.resolve_results_path(base_results_path),
                         len(merged))
            else:
                log.warning(
                    "multihost: peer shards not visible from host 0 (no "
                    "shared filesystem?) — final artifact NOT merged; "
                    "gather rows over the network (multihost.gather_rows) "
                    "or concatenate the per-host %s.hostN files manually",
                    base_results_path.stem)
        # Second fence: peers must not return (and possibly let their
        # launcher read the final artifact) while host 0 is still
        # mid-merge. Same liveness bound — host 0 dying mid-merge must
        # not hang its peers.
        multihost.liveness_barrier(
            "perturbation-merge-done",
            timeout_s=engine.rt.barrier_timeout_s,
            payload=len(rows), stats=engine.guard_stats)
    return rows


def _steps_used(gen_row: np.ndarray, eos_id) -> int:
    """Decode steps a row actually used: up to and including its first
    EOS (stopped rows emit EOS fill afterwards), else the full budget."""
    hits = np.flatnonzero(np.asarray(gen_row) == eos_id)
    return int(hits[0]) + 1 if hits.size else int(len(gen_row))


def _plan_ragged(engine, todo, new_tokens, conf_tokens):
    """Tokenize the pending grid ONCE and plan every dispatch through the
    ragged scheduler (bucket ladder + slot refill + prefix groups). The
    plan and its occupancy counters hang off ``engine.occupancy`` for the
    bench/operators."""
    with engine._tok_lock:
        bin_ids = [engine.tokenizer(c.binary_prompt).input_ids
                   for c in todo]
        conf_ids = [engine.tokenizer(c.confidence_prompt).input_ids
                    for c in todo]
    items = sched_mod.build_items(bin_ids, conf_ids, todo)
    stats = OccupancyStats()
    max_extent = (engine.cfg.max_seq_len
                  if getattr(engine.cfg, "pos_embedding", None) == "learned"
                  else None)
    # Prefix-aware slot-refill pricing: with the cross-request radix
    # cache enabled, cached-prefix tokens are free prefill and the
    # promotion rule accounts for the per-bucket namespaces (a promoted
    # tail abandons this bucket's cached pages).
    cached_probe = None
    if engine.prefix_cache is not None:
        cached_probe = (lambda it, b: engine.prefix_cache.match_len(
            b, it.bin_ids[:it.lcp]))
    planner = sched_mod.RaggedScheduler(
        engine.buckets, engine.rt.batch_size,
        new_budget=max(new_tokens, conf_tokens),
        decode_cost=new_tokens + conf_tokens, max_extent=max_extent,
        min_group_prefix=engine.rt.sweep_group_min_prefix,
        min_group_cells=engine.rt.sweep_group_min_cells,
        group_cells=engine.rt.sweep_group_min_cells > 0,
        cached_probe=cached_probe,
        fused_decode=engine.rt.fused_decode,
        stats=stats)
    dispatches = planner.schedule(items)
    engine.occupancy = stats
    log.info(
        "ragged schedule: %d cells -> %d dispatches over buckets %s "
        "(occupancy %.1f%%, padding waste %.1f%%, refilled %d, "
        "grouped %d)", len(todo), len(dispatches),
        sorted({d.bucket for d in dispatches}), stats.occupancy_pct,
        stats.padding_waste_pct,
        sum(b.refilled for b in stats.buckets.values()),
        stats.grouped_cells)
    return dispatches, stats


def _run_pipelined(engine, model_name, todo, target_ids, results_path,
                   manifest, checkpoint_every, new_tokens, conf_tokens,
                   rows, pending_rows, sink=None, accum_path=None,
                   write_rows=True) -> None:
    """Greedy (non-reasoning) sweep loop, pipelined over a writer thread.

    The device is the scarce resource; everything host-side rides shotgun:

    - MAIN thread: tokenize + left-pad bucket N, dispatch its binary and
      confidence fused decodes (jax dispatch is async — the device queue
      serializes them), enqueue the result handles, move on to bucket N+1.
      It never blocks on device results.
    - WRITER thread: ``device_get`` bucket N's outputs (releases the GIL
      while the device works), decode completion text, build D6 rows, and
      run the Excel/manifest checkpoint flushes. All of this used to sit on
      the critical path between dispatches (VERDICT r2 weak #1: the end-to-
      end sweep ran at 49% of the isolated scoring rate).

    With ``engine.rt.ragged_scheduler`` the batches come from the ragged
    scheduler's plan (engine/scheduler.py) instead of todo order: cells
    are bucketed by real tokenized prefix length, ragged bucket tails are
    refilled into the next bucket (slot refill), and long-shared-prefix
    cells score through one grouped prefill. Per-cell results are
    IDENTICAL either way (padding is masked out of every readout; pinned
    by tests/test_scheduler.py) — only dispatch composition and row order
    change, and the manifest keys rows by cell identity so resume is
    unaffected.

    The queue is bounded (depth 2) so at most ~3 buckets of decode outputs
    are live on device — outputs are small (generated ids + top-20 maps),
    but unbounded dispatch-ahead would also tokenize the whole grid up
    front for no benefit. Row order is preserved: one writer drains buckets
    in dispatch order. A writer failure stops the producer at the next
    bucket boundary and re-raises on the caller's thread; rows scored but
    not yet flushed when an earlier flush failed are NOT marked done, so a
    resumed sweep re-scores at most ``checkpoint_every`` cells (the same
    write-ahead guarantee as the synchronous loop).
    """
    B = engine.rt.batch_size
    work_q: "queue.Queue" = queue.Queue(maxsize=2)
    failed = threading.Event()
    writer_err: List[BaseException] = []
    early_stop = (engine.rt.sweep_early_stop
                  and not engine.rt.sweep_full_completions)
    ragged = bool(engine.rt.ragged_scheduler and todo
                  and not engine.encoder_decoder)
    occupancy = None
    stop_armed = False
    if ragged:
        dispatches, occupancy = _plan_ragged(engine, todo, new_tokens,
                                             conf_tokens)
        stop_armed = early_stop and engine.digit_stop_mask is not None
        engine.fresh_handoff()  # fresh donation chain per sweep
        # Compile plan: the schedule fixes every dispatch shape, so lower
        # + compile ALL bucket executables in background threads while
        # the first bucket streams — the dispatch loop then consumes
        # precompiled executables (runner.exec_registry) instead of
        # paying trace-on-first-call serially inside the timed loop.
        engine.exec_registry = None
        if engine.rt.aot_precompile:
            specs = compile_plan.plan_specs(
                dispatches, B, new_tokens, conf_tokens, stop_armed,
                prefix_page_size=(engine.prefix_cache.page_size
                                  if engine.prefix_cache is not None
                                  else 0),
                piggyback=engine.piggyback_supported(),
                stream_shape=(None if sink is None else
                              (sink.n_prompts, sink.n_rephrase,
                               sink.guard)),
                spec_k=(engine.rt.spec_k
                        if engine.spec_supported() else 0),
                spec_draft=getattr(engine, "_spec_draft", None)
                is not None,
                cascade_trunk=(
                    (lambda d: engine.cascade_trunk_for(
                        [it.bin_ids[:it.lcp] for it in d.items],
                        len(d.items), d.bucket))
                    if getattr(engine, "cascade_supported",
                               lambda: False)() else None),
                cascade_int8=bool(
                    getattr(engine, "cascade_cfg", None) is not None
                    and engine.cascade_cfg.int8_qk),
                decode_trunk=(
                    (lambda d: engine.decode_trunk_for(
                        [it.bin_ids[:it.lcp] for it in d.items],
                        len(d.items), d.bucket))
                    if getattr(engine, "cascade_decode_supported",
                               lambda: False)() else None))
            engine.exec_registry = compile_plan.precompile_async(
                engine, specs, max_workers=engine.rt.precompile_workers)
            log.info("compile plan: precompiling %d executable shapes "
                     "in the background (manifest %s)", len(specs),
                     engine.exec_registry.manifest_key)
        if sink is not None and engine.exec_registry is not None:
            # The sink consumes its planned accumulator-update
            # executables through the same registry (lazy-jit fallback
            # on any miss, as everywhere else).
            registry = engine.exec_registry

            def _stream_exec(width, _topk, _registry=registry):
                return _registry.get(compile_plan.stream_fold_spec(
                    sink.n_prompts, sink.n_rephrase, width, sink.guard))

            sink.registry_get = _stream_exec

    def _drain(batch, fused, res, cfused, spec_rec=None):
        with tracing.span("sweep/drain", rows=len(batch)):
            _drain_inner(batch, fused, res, cfused, spec_rec)

    def _drain_inner(batch, fused, res, cfused, spec_rec=None):
        if sink is not None:
            # THE tentpole hot-loop step: fold this dispatch's device
            # readouts into the donated accumulator with one fused XLA
            # call. Everything it consumes stays on device; padding
            # rows scatter out-of-range and drop.
            sink.fold(res.yes_prob, res.no_prob,
                      cfused.weighted_confidence, fused.topk_logprobs,
                      batch, topk=int(fused.topk_logprobs.shape[-1]))
        if not write_rows:
            # Streaming-only mode: the row artifact is skipped, so NO
            # per-row payload is ever device_get — the bytes the csv
            # path would have transferred are accounted as avoided.
            sink.note_bytes_avoided(
                (fused.generated, fused.topk_logprobs, fused.topk_ids,
                 cfused.generated, cfused.weighted_confidence,
                 res.yes_prob, res.no_prob))
            pending_marks.extend(c.resume_record() for c in batch)
            if len(pending_marks) >= checkpoint_every:
                _flush_marks()
            return
        res_h, lp_vals, lp_ids, gen_host = jax.device_get(
            (res, fused.topk_logprobs, fused.topk_ids, fused.generated))
        wconf, cgen_host = jax.device_get(
            (cfused.weighted_confidence, cfused.generated))
        if spec_rec is not None:
            # Prompt-lookup self-drafting warms itself: record each real
            # row's observed continuation into the radix tree's token
            # history, so a repeat visit (re-run grid, sentinel sweep)
            # drafts the whole reply (engine/spec.py).
            b_ids, c_ids, rec_bucket, rec_n = spec_rec
            engine.spec_record(rec_bucket, b_ids, gen_host, rec_n)
            engine.spec_record(rec_bucket, c_ids, cgen_host, rec_n)
        if occupancy is not None and stop_armed:
            # Decode-step occupancy: rows retired by the early stop idle
            # until the batch's slowest row (profiling.OccupancyStats).
            for j in range(len(batch)):
                occupancy.add_decode(
                    _steps_used(gen_host[j], engine.eos_id),
                    int(gen_host.shape[1]))
                occupancy.add_decode(
                    _steps_used(cgen_host[j], engine.eos_id),
                    int(cgen_host.shape[1]))
        for j, cell in enumerate(batch):
            t1p = float(res_h.yes_prob[j])
            t2p = float(res_h.no_prob[j])
            wc = float(wconf[j])
            # Numerics guard (lir_tpu/guard): validate the device-derived
            # readouts BEFORE they become a row. Corrupt rows (NaN/Inf
            # logits, insane renormalization) are quarantined with their
            # cell identity and every measurement field nulled — the same
            # row-local isolation the degradation ladder gives poison
            # rows — instead of landing in results.csv as plausible-
            # looking confidences. Neighbors are untouched.
            reason = None
            if engine.rt.numerics_guard:
                engine.guard_stats.site("checked", "sweep")
                reason = numerics.check_values(t1p, t2p, wc, lp_vals[j])
            if reason is not None:
                engine.guard_stats.quarantine("sweep", reason)
                log.warning("numerics guard: quarantined cell %r (%s)",
                            cell.rephrased_main[:40], reason)
                row = schemas.PerturbationRow(
                    model=model_name,
                    original_main=cell.original_main,
                    response_format=cell.response_format,
                    confidence_format=cell.confidence_format,
                    rephrased_main=cell.rephrased_main,
                    full_rephrased_prompt=cell.binary_prompt,
                    full_confidence_prompt=cell.confidence_prompt,
                    model_response=numerics.NUMERICS_ERROR,
                    model_confidence_response=(
                        f"{numerics.NUMERICS_ERROR} — {reason} "
                        f"(row quarantined by the numerics guard)"),
                    log_probabilities="",
                    token_1_prob=None,
                    token_2_prob=None,
                    confidence_value=None,
                    weighted_confidence=None,
                )
                rows.append(row)
                pending_rows.append(row)
                continue
            completion = engine.decode_completion(gen_host[j])
            conf_text = engine.decode_completion(cgen_host[j])
            # A short confidence decode that never reached EOS may have cut
            # an integer mid-number; don't trust an end-of-text match then.
            conf_complete = (engine.rt.sweep_full_completions
                             or _decode_complete(cgen_host[j], engine.eos_id))
            logprob_map = {
                int(i): round(float(v), 6)
                for i, v in zip(lp_ids[j], lp_vals[j])
            }
            row = schemas.PerturbationRow(
                model=model_name,
                original_main=cell.original_main,
                response_format=cell.response_format,
                confidence_format=cell.confidence_format,
                rephrased_main=cell.rephrased_main,
                full_rephrased_prompt=cell.binary_prompt,
                full_confidence_prompt=cell.confidence_prompt,
                model_response=completion,
                model_confidence_response=conf_text,
                log_probabilities=json.dumps(logprob_map),
                token_1_prob=t1p,
                token_2_prob=t2p,
                confidence_value=_parse_confidence(conf_text, conf_complete),
                weighted_confidence=wc,
            )
            rows.append(row)
            pending_rows.append(row)
        if len(pending_rows) >= checkpoint_every:
            _flush(pending_rows, results_path, manifest, sink=sink,
                   accum_path=accum_path)
            del pending_rows[:]

    # Streaming-only manifest marks (no rows to key them off). Flush
    # order mirrors _flush's write-ahead rule with the accumulator
    # playing the results artifact: checkpoint the accum FIRST, then
    # mark done — a crash between the two re-dispatches rows whose
    # folds are already (idempotently) in the checkpoint, and can never
    # mark a row done that the accumulator lost.
    pending_marks: List[dict] = []

    def _flush_marks():
        if sink is not None and accum_path is not None:
            sink.checkpoint(accum_path)
        manifest.mark_done_many(pending_marks)
        log.info("checkpoint: +%d rows (streaming-only) -> %s",
                 len(pending_marks), accum_path)
        del pending_marks[:]

    def _writer():
        while True:
            item = work_q.get()
            if item is None:
                return
            if failed.is_set():
                continue        # drain remaining items to unblock the producer
            try:
                _drain(*item)
            except BaseException as e:      # noqa: BLE001 — re-raised below
                writer_err.append(e)
                failed.set()

    def _dispatch_legacy():
        for start in range(0, len(todo), B):
            if failed.is_set():
                return
            batch = todo[start:start + B]
            n = len(batch)
            # Tail bucket: pad to the next power of two instead of the full
            # B — at most one extra compile per sweep, and the final bucket
            # stops re-scoring batch[-1] up to B-1 times (VERDICT r1 #6).
            bsz = B if n == B else _tail_batch(n, B)
            full = list(batch) + [batch[-1]] * (bsz - n)

            # Both formats in ONE call: the binary and confidence prompts
            # share the rephrased legal text, so the engine prefills that
            # prefix once and runs each short format suffix as a chunked
            # extension — per-cell device work drops from two full prefills
            # to ~one (the fused scan still captures per-step target probs,
            # top-2, and the position-0 top-20/E[v] readouts in-scan).
            t1 = np.asarray(
                [target_ids[c.prompt_idx][0] for c in full], np.int32)
            t2 = np.asarray(
                [target_ids[c.prompt_idx][1] for c in full], np.int32)
            fused, cfused = _dispatch_with_recovery(
                engine, lambda: engine.decode_fused_shared(
                    [c.binary_prompt for c in full],
                    [c.confidence_prompt for c in full],
                    t1, t2, new_tokens=new_tokens, conf_tokens=conf_tokens,
                    early_stop=early_stop),
                # Legacy batches pick their bucket inside the engine;
                # price at the ladder's widest edge (a generous deadline
                # beats a hair-trigger one).
                cost=sched_mod.bucket_cost(bsz, max(engine.buckets), B,
                                           new_tokens + conf_tokens,
                                           fused_decode=engine.rt.fused_decode))
            res = score_mod.readout_from_fused(
                fused, jnp.asarray(t1), jnp.asarray(t2), scan_positions=1)
            work_q.put((batch, fused, res, cfused))

    # Chunked prefill/decode piggybacking: runs of CONSECUTIVE shared
    # dispatches with one compiled shape (the common case — bucket queues
    # drain same-shape batches back to back) chain through the engine's
    # piggyback path: each dispatch's prefill call carries the PARKED
    # decode scans of the previous dispatch (generate.shared_piggyback_
    # step), so the stream pays one device round-trip per dispatch and
    # decode never waits on a host gap behind a full prefill. Results are
    # identical per row (tests/test_kernels.py); any failure falls back
    # to the plain recovered path, which recomputes both dispatches.
    use_piggy = (ragged
                 and getattr(engine, "piggyback_supported",
                             lambda: False)())
    fused_dec = engine.rt.fused_decode
    # Speculative dispatches price their decode floor at the verify-
    # window constant (scheduler.DECODE_TOKEN_COST_SPEC); the watchdog's
    # widened seed headroom covers a zero-accept dispatch degenerating
    # to sequential cost.
    spec_on = getattr(engine, "spec_supported", lambda: False)()
    # Cascade-eligible dispatches take the shared-prefix path inside
    # decode_fused_shared (runner._dispatch_shared_cascade) — they never
    # ride the piggyback chain (the cascade prefill has no parked-decode
    # carry slot), mirroring compile_plan's `piggyback and not trunk`
    # spec planning. Their trunk length also discounts the watchdog
    # prefill price below.
    cascade_on = getattr(engine, "cascade_supported", lambda: False)()
    cascade_trunks = []
    piggy_keys = []
    if ragged:
        for d in dispatches:
            if d.kind == "shared":
                n = len(d.items)
                trunk = (engine.cascade_trunk_for(
                    [it.bin_ids[:it.lcp] for it in d.items], n, d.bucket)
                    if cascade_on else 0)
                cascade_trunks.append(trunk)
                piggy_keys.append(
                    None if trunk else
                    (d.bucket, B if n == B else _tail_batch(n, B),
                     d.sfx_bucket_a, d.sfx_bucket_b))
            else:
                cascade_trunks.append(0)
                piggy_keys.append(None)
    pending: List[Optional[dict]] = [None]   # the parked dispatch's meta

    def _watched(call, cost):
        wd = getattr(engine, "watchdog", None)
        if wd is not None and wd.enabled:
            out = wd.watch(call, cost=cost, site="sweep")
        else:
            out = call()
        if getattr(engine, "governor", None) is not None:
            engine.governor.tick()   # piggyback chain dispatch boundary
        return out

    def _emit(meta, fused, cfused):
        res = score_mod.readout_from_fused(
            fused, jnp.asarray(meta["t1"]), jnp.asarray(meta["t2"]),
            scan_positions=1)
        spec_rec = None
        if engine.spec_supported() and engine.prefix_cache is not None:
            spec_rec = ([it.bin_ids for it in meta["full_items"]],
                        [it.conf_ids for it in meta["full_items"]],
                        meta["bucket"], meta["n"])
        work_q.put((meta["batch"], fused, res, cfused, spec_rec))

    def _plain_shared(meta):
        full_items, t1, t2 = meta["full_items"], meta["t1"], meta["t2"]
        with tracing.span("sweep/dispatch", bucket=int(meta["bucket"]),
                          rows=int(meta["n"])):
            fused, cfused = _dispatch_with_recovery(
                engine, lambda: engine.decode_fused_shared(
                    [it.cell.binary_prompt for it in full_items],
                    [it.cell.confidence_prompt for it in full_items],
                    t1, t2, new_tokens=new_tokens,
                    conf_tokens=conf_tokens, early_stop=early_stop,
                    pretokenized_a=[it.bin_ids for it in full_items],
                    pretokenized_b=[it.conf_ids for it in full_items],
                    bucket=meta["bucket"], sfx_buckets_ab=meta["sfx_ab"],
                    reuse_cache=True, n_real=meta["n"]),
                cost=sched_mod.bucket_cost(
                    meta["n"], meta["bucket"], B,
                    new_tokens + conf_tokens, fused_decode=fused_dec,
                    spec_decode=spec_on,
                    cascade=meta.get("trunk", 0) > 0,
                    trunk_tokens=meta.get("trunk", 0)))
        _emit(meta, fused, cfused)

    def _redispatch_pending():
        """Broken chain: the parked dispatch's carry is gone (possibly
        consumed by donation) — recompute it through the plain recovered
        path, which owes nothing to the chain."""
        meta, pending[0] = pending[0], None
        engine.piggy_abort()
        _plain_shared(meta)

    def _drain_pending():
        if pending[0] is None:
            return
        meta = pending[0]
        try:
            fused, cfused = _watched(
                lambda: engine.piggy_drain(meta["t1"], meta["t2"]),
                cost=sched_mod.decode_floor(
                    meta["n"], B, new_tokens + conf_tokens,
                    fused_decode=fused_dec))
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as err:  # noqa: BLE001 — plain-path fallback
            log.warning("piggyback drain failed (%r); re-dispatching the "
                        "parked batch through the plain path", err)
            _redispatch_pending()
            return
        pending[0] = None
        _emit(meta, fused, cfused)

    def _dispatch_ragged():
        for i, d in enumerate(dispatches):
            if failed.is_set():
                return
            batch = d.cells
            n = len(d.items)
            if d.kind == "shared":
                bsz = B if n == B else _tail_batch(n, B)
                full_items = list(d.items) + [d.items[-1]] * (bsz - n)
                t1 = np.asarray(
                    [target_ids[it.cell.prompt_idx][0]
                     for it in full_items], np.int32)
                t2 = np.asarray(
                    [target_ids[it.cell.prompt_idx][1]
                     for it in full_items], np.int32)
                meta = dict(batch=batch, full_items=full_items, t1=t1,
                            t2=t2, bucket=d.bucket, n=n, key=piggy_keys[i],
                            sfx_ab=(d.sfx_bucket_a, d.sfx_bucket_b),
                            trunk=cascade_trunks[i])
                # Chain iff the parked dispatch shares this shape, or this
                # dispatch opens a run the NEXT dispatch will ride.
                # Cascade-eligible dispatches carry a None key — two of
                # them must not chain through the None == None trap.
                chainable = use_piggy and piggy_keys[i] is not None and (
                    (pending[0] is not None
                     and pending[0]["key"] == piggy_keys[i])
                    or (pending[0] is None and i + 1 < len(dispatches)
                        and piggy_keys[i + 1] == piggy_keys[i]))
                if chainable:
                    prev = pending[0]
                    cost = sched_mod.bucket_cost(
                        n, d.bucket, B, new_tokens + conf_tokens,
                        fused_decode=fused_dec)
                    if prev is not None:
                        cost += sched_mod.decode_floor(
                            prev["n"], B, new_tokens + conf_tokens,
                            fused_decode=fused_dec)
                    try:
                        out = _watched(
                            lambda: engine.decode_fused_shared_piggy(
                                [it.bin_ids for it in full_items],
                                [it.conf_ids for it in full_items],
                                new_tokens, conf_tokens, early_stop,
                                d.bucket,
                                (d.sfx_bucket_a, d.sfx_bucket_b),
                                prev_yes=(prev["t1"] if prev else None),
                                prev_no=(prev["t2"] if prev else None)),
                            cost)
                    except PiggybackIneligible as err:
                        log.info("piggyback ineligible (%s); dispatching "
                                 "plainly", err)
                        _drain_pending()
                        _plain_shared(meta)
                        continue
                    except (KeyboardInterrupt, SystemExit):
                        raise
                    except BaseException as err:  # noqa: BLE001
                        log.warning(
                            "piggyback step failed (%r); falling back to "
                            "the plain path for both dispatches", err)
                        if pending[0] is not None:
                            _redispatch_pending()
                        else:
                            engine.piggy_abort()
                        _plain_shared(meta)
                        continue
                    if out is not None:
                        _emit(prev, *out)
                    pending[0] = meta
                    continue
                _drain_pending()
                _plain_shared(meta)
                continue
            else:
                _drain_pending()   # grouped shapes never ride the chain
                t1 = np.asarray(
                    [target_ids[it.cell.prompt_idx][0]
                     for it in d.items], np.int32)
                t2 = np.asarray(
                    [target_ids[it.cell.prompt_idx][1]
                     for it in d.items], np.int32)
                with tracing.span("sweep/dispatch", kind="grouped",
                                  bucket=int(d.bucket), rows=n):
                    out, m = _dispatch_with_recovery(
                        engine, lambda: engine.decode_fused_grouped(
                            d.groups, t1, t2, new_tokens, conf_tokens,
                            early_stop, d.bucket,
                            max(d.sfx_bucket_a, d.sfx_bucket_b),
                            reuse_cache=True),
                        # Grouped dispatches run [bin, conf] member rows
                        # per cell — price the doubled row count.
                        cost=sched_mod.bucket_cost(
                            2 * n, d.bucket, B, new_tokens + conf_tokens,
                            fused_decode=fused_dec))
                # Member rows are [bin, conf] per cell: even rows carry
                # the binary readout, odd rows the confidence one. Both
                # ran the shared max(new, conf) budget, so each branch
                # view trims its per-step fields back to ITS budget —
                # greedy decoding is prefix-stable, so the trimmed tokens
                # equal what a budget-exact decode would have produced
                # (and the extra steps retire via the EOS stop when
                # armed).
                def _branch(start, budget):
                    idx = slice(start, m, 2)
                    return generate.FusedDecodeOut(
                        generated=out.generated[idx, :budget],
                        p_yes=out.p_yes[idx, :budget],
                        p_no=out.p_no[idx, :budget],
                        top2_ids=out.top2_ids[idx, :budget],
                        topk_logprobs=out.topk_logprobs[idx],
                        topk_ids=out.topk_ids[idx],
                        weighted_confidence=out.weighted_confidence[idx])

                fused = _branch(0, new_tokens)
                cfused = _branch(1, conf_tokens)
                res = score_mod.readout_from_fused(
                    fused, jnp.asarray(t1), jnp.asarray(t2),
                    scan_positions=1)
            work_q.put((batch, fused, res, cfused))
        _drain_pending()   # close the piggyback chain's last dispatch

    wt = threading.Thread(target=_writer, name="sweep-writer", daemon=True)
    wt.start()
    try:
        if ragged:
            _dispatch_ragged()
        else:
            _dispatch_legacy()
    finally:
        work_q.put(None)
        wt.join()
    if writer_err:
        raise writer_err[0]
    if pending_marks:
        _flush_marks()


def _reasoning_batch(engine, model_name, prompts, batch, full, seed,
                     reasoning_runs, pending_rows, rows):
    """Score one padded bucket in reasoning mode: n sampled binary runs with
    count averaging + one sampled confidence response per cell.

    Rows are keyed by GRID-CELL IDENTITY (prompt_idx, rephrase_idx), not by
    position in the todo list or the batch — a resumed or subset sweep
    samples exactly what an uninterrupted run would for every cell."""
    base = jax.random.PRNGKey(seed)
    cell_keys = jnp.stack([
        jax.random.fold_in(jax.random.fold_in(base, c.prompt_idx),
                           c.rephrase_idx)
        for c in full])
    targets = [prompts[c.prompt_idx].target_tokens for c in full]
    sampled = engine.score_prompts_sampled(
        [c.binary_prompt for c in full], targets, n_runs=reasoning_runs,
        key=cell_keys)
    conf_keys = jax.vmap(
        lambda k: jax.random.fold_in(k, 10_000))(cell_keys)
    conf_texts, conf_ids = engine.sample_completions_with_ids(
        [c.confidence_prompt for c in full], conf_keys)

    for j, cell in enumerate(batch):
        s = sampled[j]
        conf_text = conf_texts[j].strip()
        # Same mid-number truncation guard as the greedy path: a reply that
        # never reached EOS may have been cut inside its integer.
        conf_val = _parse_confidence(
            conf_text, _decode_complete(conf_ids[j], engine.eos_id))
        row = schemas.PerturbationRow(
            model=model_name,
            original_main=cell.original_main,
            response_format=cell.response_format,
            confidence_format=cell.confidence_format,
            rephrased_main=cell.rephrased_main,
            full_rephrased_prompt=cell.binary_prompt,
            full_confidence_prompt=cell.confidence_prompt,
            model_response=s.response,
            model_confidence_response=conf_text,
            log_probabilities="",       # reasoning models expose no logprobs
            token_1_prob=s.token_1_prob,
            token_2_prob=s.token_2_prob,
            # weighted confidence equals the raw parsed integer in reasoning
            # mode (perturb_prompts.py:459-464)
            confidence_value=conf_val,
            weighted_confidence=None if conf_val is None else float(conf_val),
        )
        rows.append(row)
        pending_rows.append(row)
    return pending_rows, rows


def _flush(rows: List[schemas.PerturbationRow], results_path: Path,
           manifest: SweepManifest, sink=None, accum_path=None) -> None:
    """Atomic-append rows then mark them done (write-ahead order: a crash
    between the two re-scores at most one checkpoint, never loses rows).

    The streaming accumulator checkpoints FIRST: the resume done-set is
    the union of manifest and results artifact, so an accumulator
    written after the rows could miss rows the union declares done — a
    permanent lattice hole. Checkpoint-then-append means the accum is
    always a superset of the done-set, and superset folds are
    idempotent re-scores, never losses."""
    if sink is not None and accum_path is not None:
        sink.checkpoint(accum_path)
    schemas.write_perturbation_results(rows, results_path, append=True)
    manifest.mark_done_many([
        {"model": r.model, "original_main": r.original_main,
         "rephrased_main": r.rephrased_main} for r in rows])
    log.info("checkpoint: +%d rows -> %s", len(rows), results_path)
