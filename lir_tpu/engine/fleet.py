"""Fleet scheduler: model-id-aware engine orchestration for the
inter-model agreement axis.

The paper's axis 2 (κ over 10-18 open-weight models) is a MODEL-major
workload on a chip that holds one model comfortably and several tiny
ones easily. AlpaServe's statistical-multiplexing result and
ServerlessLLM's load-dominates-switching observation both land here:

- a :class:`ModelFleet` owns one :class:`~lir_tpu.engine.runner.
  ScoringEngine` per model plus the HBM-budgeted LRU
  :class:`~lir_tpu.models.weights.WeightCache` and the single-worker
  :class:`~lir_tpu.models.weights.AsyncWeightStreamer`;
- ``acquire(model_id)`` makes a model's weights device-resident
  (cache hit -> free; prefetched -> pay only the un-overlapped tail;
  cold -> inline load, fully exposed) and refcounts them against the
  caller's dispatch stream, so LRU eviction can never pull weights out
  from under an in-flight dispatch;
- ``sweep(model_ids, fn)`` is the prefetch pipeline engine/multi.py now
  drives sweeps through: while model i scores, model i+1 streams —
  swap cost hides behind compute (FleetStats.swap_s_hidden) instead of
  serializing with it, replacing the old drop-params-and-reload loop
  whose every switch was dead MXU time.

Engines are constructed once (tokenizer, buckets, manifest key, stats
all persist); only the param tree moves. ``compile_plan`` executables
re-key on model config, so a model whose weights were evicted and
re-streamed warm-starts: same avals, same executables, zero recompiles
— and re-streamed weights are BITWISE the staged originals, so results
cannot depend on eviction history (pinned by tests/test_fleet.py).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..models import weights
from ..utils.logging import get_logger
from ..utils.profiling import FleetStats
from . import hbm

log = get_logger(__name__)

# An engine factory maps model id -> ready ScoringEngine (models/
# factory.engine_factory is the checkpoint-backed one; tests inject
# closures over tiny params).
EngineFactory = Callable[[str], Any]


class _Slot:
    """One model's fleet state. ``engine`` is built lazily (on the
    prefetch worker when possible — tokenizer load and weight
    conversion overlap the previous model's compute); ``staged`` is the
    pinned host staging copy reloads stream from."""

    __slots__ = ("model_id", "make_engine", "engine", "staged", "nbytes")

    def __init__(self, model_id: str,
                 make_engine: Optional[EngineFactory] = None,
                 engine: Any = None):
        self.model_id = model_id
        self.make_engine = make_engine
        self.engine = engine
        self.staged: Any = None
        self.nbytes: int = 0


class ModelFleet:
    """Co-resident model pool + async weight streaming + swap
    accounting. Thread discipline: ``acquire``/``release``/``sweep``
    run on ONE consumer thread (the sweep loop or the serve fleet
    supervisor); the streamer's single worker is the only other thread
    that touches slots, and every slot it writes is handed over through
    a future (happens-before at ``take``)."""

    def __init__(self, cache_budget_bytes: Optional[int] = None,
                 prefetch: bool = True, mesh=None,
                 stage_reloads: bool = True,
                 stats: Optional[FleetStats] = None,
                 governor: Optional[hbm.HbmGovernor] = None):
        self.stats = stats if stats is not None else FleetStats()
        self.mesh = mesh
        self.prefetch_enabled = bool(prefetch)
        # Keep a host staging copy at first load so an evicted model
        # reloads via the chunked streamer (one host->device copy)
        # instead of a full checkpoint re-conversion. Costs host RAM =
        # fleet weight bytes; single-pass sweeps that never revisit a
        # model can turn it off.
        self.stage_reloads = bool(stage_reloads)
        self.cache = weights.WeightCache(cache_budget_bytes,
                                         stats=self.stats,
                                         on_evict=self._on_evict)
        self.streamer = weights.AsyncWeightStreamer()
        self._slots: Dict[str, _Slot] = {}
        self._order: List[str] = []
        self._active: Optional[str] = None
        self._lock = threading.RLock()
        # Unified HBM governor (engine/hbm.py): the weight cache's
        # residency rides the ledger via the same listener events the
        # router's residency map uses, and the ladder's evict_weights
        # rung drops one idle LRU model through the cache's own
        # refcount discipline (in-flight/pinned models unevictable).
        self.governor = governor
        if governor is not None:
            governor.register("weights", 0)
            governor.set_action("evict_weights",
                                engage=self.evict_idle)
            self.cache.add_listener(self._on_residency_event)

    # -- construction --------------------------------------------------------

    def attach_governor(self, governor: hbm.HbmGovernor) -> None:
        """Adopt an HBM governor after construction (the fleet server
        shares its first engine's governor so weights, pages, pins and
        dispatch caches land in ONE ledger). Re-validates every sized
        slot against the budget and seeds the weights ledger entry."""
        if self.governor is governor:
            return
        self.governor = governor
        governor.set_action("evict_weights", engage=self.evict_idle)
        self.cache.add_listener(self._on_residency_event)
        governor.update("weights", self.cache.resident_bytes)
        with self._lock:
            for slot in self._slots.values():
                if slot.nbytes:
                    hbm.validate_fleet_budget(
                        slot.model_id, slot.nbytes,
                        self.cache.budget_bytes, governor=governor)

    def add_model(self, model_id: str, engine: Any = None,
                  make_engine: Optional[EngineFactory] = None) -> None:
        """Register a model. With ``engine`` (already loaded), its
        params move under cache ownership immediately — the engine
        keeps everything BUT the weights. With ``make_engine``, the
        first acquire/prefetch builds the engine (checkpoint load on
        the worker thread)."""
        assert (engine is None) != (make_engine is None), (
            "pass exactly one of engine / make_engine")
        with self._lock:
            assert model_id not in self._slots, f"duplicate model {model_id}"
            slot = _Slot(model_id, make_engine=make_engine, engine=engine)
            if engine is not None:
                params = engine.params
                slot.nbytes = weights.tree_bytes(params)
                # Boot-time budget validation: a budget smaller than
                # this model can NEVER hold it — fail construction
                # with the full HBM arithmetic instead of surfacing as
                # a WeightCacheOOM mid-sweep (engine/hbm.py).
                hbm.validate_fleet_budget(model_id, slot.nbytes,
                                          self.cache.budget_bytes,
                                          governor=self.governor)
                if self.stage_reloads:
                    slot.staged = weights.host_stage(params)
                self.cache.insert(model_id, params, slot.nbytes)
                # The cache now owns these bytes: drop the engine-level
                # params ledger entry so a shared governor counts them
                # once, under "weights".
                release = getattr(engine, "release_params_ledger", None)
                if release is not None:
                    release()
            self._slots[model_id] = slot
            self._order.append(model_id)

    @classmethod
    def from_factory(cls, factory: EngineFactory,
                     model_ids: Sequence[str], **kwargs) -> "ModelFleet":
        fleet = cls(**kwargs)
        for mid in model_ids:
            fleet.add_model(mid, make_engine=factory)
        return fleet

    @classmethod
    def from_engines(cls, engines: Sequence[tuple], **kwargs
                     ) -> "ModelFleet":
        """[(model_id, ScoringEngine), ...] — tests and the serve boot
        path, where engines are already built."""
        fleet = cls(**kwargs)
        for mid, engine in engines:
            fleet.add_model(mid, engine=engine)
        return fleet

    @property
    def model_ids(self) -> List[str]:
        return list(self._order)

    def engine(self, model_id: str) -> Any:
        """The model's engine, WITHOUT making weights resident (host
        metadata only: tokenizer, buckets, rt). None until first
        load for make_engine slots."""
        return self._slots[model_id].engine

    def resident(self, model_id: str) -> bool:
        return model_id in self.cache

    # -- load path -----------------------------------------------------------

    def _on_residency_event(self, event: str, model_id: str) -> None:
        """WeightCache listener: mirror resident bytes into the HBM
        governor's ledger. Fired possibly under the cache lock — cheap
        gauge write only, never touches the cache."""
        if self.governor is not None:
            self.governor.update("weights", self.cache.resident_bytes)

    def evict_idle(self) -> bool:
        """Governor evict_weights rung: drop ONE idle LRU model (its
        staged host copy survives, so a re-acquire streams it back
        bitwise). With a weight tier store attached
        (:meth:`attach_tiers`) the rung DEMOTES: the victim's staged
        tree is recorded to the disk tier first, so the weights
        survive even process death (restart-warm re-stages them).
        True when a model was actually evicted."""
        evicted = self.cache.evict_idle()
        if evicted is not None:
            # The staged tree never changes after staging, so recording
            # AFTER eviction is the same bytes recording before would
            # have been (and a no-op when attach_tiers already
            # mirrored it).
            slot = self._slots.get(evicted)
            if slot is not None:
                self._record_staged(slot)
        return evicted is not None

    def attach_tiers(self, store) -> None:
        """Adopt a serve/tiers.TieredWeightStore: every staged host
        tree is MIRRORED to the disk tier (staged trees are immutable,
        so one record per model covers every later eviction — the
        cache's own insert-time LRU evictions included, not just the
        governor's evict_idle rung), and :meth:`reseed_weights`
        re-stages recorded models on a restart-warm boot. Models
        already staged when the store attaches record here; models
        staged later record at staging time (:meth:`_load`)."""
        self._tier_store = store
        with self._lock:
            slots = [s for s in self._slots.values()
                     if s.staged is not None]
        for slot in slots:
            self._record_staged(slot)

    def _record_staged(self, slot: _Slot) -> None:
        """Best-effort disk-tier record of one staged tree (no-op
        without a store or when already recorded; a full or broken
        disk degrades to pre-tier behavior, never fails the caller)."""
        store = getattr(self, "_tier_store", None)
        if store is None or slot.staged is None:
            return
        try:
            store.put(slot.model_id, slot.staged)
        except Exception:  # noqa: BLE001 — see docstring.
            log.exception("weight tier record failed for %s — "
                          "continuing untiered", slot.model_id)

    def reseed_weights(self, store=None) -> int:
        """Restart-warm the fleet's HOST tier from the disk tier: any
        slot without a staged tree whose model the store has recorded
        gets it back (CRC-verified — a corrupt record is refused and
        the model cold-loads). The DEVICE copy still streams on first
        acquire through the ordinary bitwise ``stream_params`` path.
        Returns models re-staged."""
        store = store if store is not None else getattr(
            self, "_tier_store", None)
        if store is None:
            return 0
        n = 0
        with self._lock:
            for slot in self._slots.values():
                if slot.staged is not None or not store.has(slot.model_id):
                    continue
                staged = store.get(slot.model_id)
                if staged is None:
                    continue        # refused (checksum) or vanished
                slot.staged = staged
                n += 1
        if n:
            store.stats.count("restart_weights_reseeded", n)
        return n

    def _on_evict(self, model_id: str) -> None:
        slot = self._slots.get(model_id)
        if slot is None or slot.engine is None:
            return
        # Drop every engine-held reference to device weight/scratch HBM:
        # the cache's entry was the canonical reference, the engine's
        # param pointer and its donation-chain scratch cache are the
        # stragglers that would keep the buffers alive.
        slot.engine.params = None
        slot.engine.fresh_handoff()

    def _load(self, slot: _Slot) -> Any:
        """Runs on the streamer worker (prefetch) or inline (cold
        acquire): produce the model's device param tree."""
        if slot.staged is not None:
            eng = slot.engine
            cfg = None if eng is None else eng.cfg
            return weights.stream_params(
                slot.staged, cfg=cfg if self.mesh is not None else None,
                mesh=self.mesh, stats=self.stats)
        engine = slot.make_engine(slot.model_id)
        params = engine.params
        slot.engine = engine
        slot.nbytes = weights.tree_bytes(params)
        # Factory slots learn their size at first load — run the same
        # budget arithmetic add_model runs for pre-built engines, so a
        # mis-sized fleet fails its FIRST load loudly instead of
        # thrashing into WeightCacheOOM mid-sweep.
        hbm.validate_fleet_budget(slot.model_id, slot.nbytes,
                                  self.cache.budget_bytes,
                                  governor=self.governor)
        release = getattr(engine, "release_params_ledger", None)
        if release is not None:
            release()    # the cache owns the bytes from here
        if self.stage_reloads:
            slot.staged = weights.host_stage(params)
            self._record_staged(slot)
        return params

    def prefetch(self, model_id: str) -> None:
        """Start streaming ``model_id``'s weights in the background (a
        no-op when already resident, prefetch disabled, or a prefetch
        is already in flight)."""
        if not self.prefetch_enabled:
            return
        slot = self._slots[model_id]
        if model_id in self.cache:
            return
        self.streamer.prefetch(model_id, lambda: self._load(slot))

    def acquire(self, model_id: str):
        """Engine with weights device-resident + refcounted. Swap
        accounting: a cache hit costs nothing; a prefetched load books
        only the un-overlapped wait as exposed; a cold inline load is
        fully exposed (exactly what the sequential drop-and-reload
        baseline pays for EVERY switch)."""
        from ..observe import tracing

        slot = self._slots[model_id]
        if model_id in self.cache:
            params = self.cache.acquire(model_id)
            self.stats.count("cache_hits")
        else:
            # The weight-swap span covers exactly the EXPOSED wait —
            # what the scoring loop actually stalls on (a prefetched
            # load's hidden portion already overlapped compute).
            with tracing.span("fleet/weight_swap", model=model_id):
                taken = self.streamer.take(model_id)
                if taken is not None:
                    params, load_s, waited = taken
                    self.stats.count("prefetch_hits")
                    self.stats.count("loads")
                    self.stats.count("load_s", load_s)
                    self.stats.count("swap_s_exposed", waited)
                    self.stats.count("swap_s_hidden",
                                     max(load_s - waited, 0.0))
                else:
                    t0 = time.perf_counter()
                    params = self._load(slot)
                    load_s = time.perf_counter() - t0
                    self.stats.count("prefetch_misses")
                    self.stats.count("loads")
                    self.stats.count("load_s", load_s)
                    self.stats.count("swap_s_exposed", load_s)
                self.cache.insert(model_id, params, slot.nbytes or None)
            params = self.cache.acquire(model_id)
        if self._active != model_id:
            self.stats.count("model_swaps")
            self._active = model_id
        slot.engine.params = params
        return slot.engine

    def release(self, model_id: str) -> None:
        self.cache.release(model_id)

    # -- speculative drafting (engine/spec.py) -------------------------------

    def acquire_spec_draft(self, engine, model_id: str) -> Optional[str]:
        """When ``engine.rt.spec_draft_model`` names ANOTHER fleet
        model, make that model's weights resident and REFCOUNTED
        (WeightCache.acquire — unevictable for the dispatch window, so
        drafting can never evict the verifier mid-dispatch, nor the
        verifier the drafter) and arm the verifier's fleet drafting
        (ScoringEngine.set_spec_draft). Returns the draft model id to
        hand back to :meth:`release_spec_draft`, or None when fleet
        drafting doesn't apply (self-draft mode, unknown draft id,
        drafting for itself)."""
        draft_id = getattr(engine.rt, "spec_draft_model", "")
        if (not draft_id or draft_id == model_id
                or draft_id not in self._slots
                or not getattr(engine, "spec_supported", lambda: False)()):
            return None
        dengine = self.acquire(draft_id)
        try:
            engine.set_spec_draft(dengine.params, dengine.cfg, draft_id)
        except BaseException:
            self.release(draft_id)
            raise
        return draft_id

    def release_spec_draft(self, engine, draft_id: Optional[str]) -> None:
        """Disarm fleet drafting and drop the draft weights' dispatch
        reference (the LRU cache decides residency from here)."""
        if draft_id is None:
            return
        engine.clear_spec_draft()
        self.release(draft_id)

    def pin(self, model_id: str) -> None:
        self.cache.pin(model_id)

    def unpin(self, model_id: str) -> None:
        self.cache.unpin(model_id)

    # -- the prefetch pipeline -----------------------------------------------

    def sweep(self, model_ids: Sequence[str],
              fn: Callable[[str, Any], Any]) -> Dict[str, Any]:
        """Model-major sweep with next-model prefetch overlap: while
        ``fn(model_id, engine)`` computes on model i, model i+1's
        weights stream in the background. The engine handed to ``fn``
        is resident and refcounted for the duration of the call."""
        ids = list(model_ids)
        out: Dict[str, Any] = {}
        for i, mid in enumerate(ids):
            engine = self.acquire(mid)
            if i + 1 < len(ids):
                self.prefetch(ids[i + 1])
            try:
                out[mid] = fn(mid, engine)
            finally:
                self.release(mid)
        return out

    def shutdown(self) -> None:
        self.streamer.shutdown()
