"""Multi-model sweep orchestration (C10-C12 drivers, C15/C16 aux).

Parity targets:
  - the (base, instruct) pair loop of compare_base_vs_instruct.py:386-550
    and the instruct-only loop of compare_instruct_models.py:376-566,
    including the per-model try/except that emits NaN rows instead of
    killing a 12-hour sweep (:482-492 / :512-522);
  - the ThreadPoolExecutor model fan-out of perturb_prompts.py:917-962 —
    on TPU the models share the chips, so the sweep is sequential per model
    (SURVEY.md §2.5) with the same results-merging semantics;
  - C15 memory management, now fleet-owned: instead of dropping params
    and reloading cold per model (dead MXU time per switch), the sweep
    drives the :class:`~lir_tpu.engine.fleet.ModelFleet` prefetch
    pipeline — model i+1's weights stream host->device WHILE model i
    scores, co-resident models stay cached up to the HBM budget, and the
    LRU weight cache reclaims exactly when pressure demands (replacing
    gc/empty_cache/HF-cache-delete, compare_base_vs_instruct.py:68-88);
  - C16 session capture: the whole sweep log is written next to the CSVs.

Failure semantics (guard-layer parity with the single-model sweep): a
model that fails to load or dispatch still emits its NaN rows, but the
failure is now CLASSIFIED — ``error:model`` (load/dispatch exception)
vs ``error:numerics`` (rows whose readouts fail guard/numerics.
check_values are quarantined: cell identity kept, measurement fields
nulled) — with the same GuardStats counters the single-model sweep and
serve paths produce, so a fleet sweep's corruption is countable, not
silent.

Cost accounting becomes throughput accounting: every scored prompt feeds a
ThroughputMeter and the sweep summary reports prompts/sec/chip
(BASELINE.json metric) instead of dollars.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..data import schemas
from ..data.prompts import (
    WORD_MEANING_QUESTIONS,
    format_baichuan_prompt,
    format_base_prompt,
    format_instruct_direct,
    format_instruct_prompt,
)
from ..guard import numerics
from ..observe import tracing
from ..utils.logging import get_logger, save_captured_output, start_capture
from ..utils.profiling import (GuardStats, ThroughputMeter,
                               device_memory_stats)
from .fleet import ModelFleet
from .runner import ScoringEngine
from .sweep import run_word_meaning_sweep

log = get_logger(__name__)

# Model-level failure status prefix (vs error:numerics for row-level
# quarantine) — guard/numerics owns the numerics spelling.
MODEL_ERROR = "error:model"

# An engine factory returns a ready ScoringEngine for a model name; the
# fleet layer owns its weights afterwards (LRU cache + async streaming).
EngineFactory = Callable[[str], ScoringEngine]


@dataclasses.dataclass
class ModelSpec:
    """One model in a sweep."""

    name: str
    base_or_instruct: str  # "base" | "instruct"

    @property
    def is_base(self) -> bool:
        return self.base_or_instruct == "base"


def nan_rows_for_model(
    spec: ModelSpec, questions: Sequence[str]
) -> List[schemas.ScoreRow]:
    """NaN fallback rows — one bad model must not abort the sweep
    (compare_base_vs_instruct.py:482-492)."""
    return [
        schemas.ScoreRow(
            prompt=q, model=spec.name, base_or_instruct=spec.base_or_instruct,
            model_output="ERROR", yes_prob=float("nan"),
            no_prob=float("nan"), yes_no_found=False,
        )
        for q in questions
    ]


def format_for(spec: ModelSpec, sweep_kind: str = "base_vs_instruct"
               ) -> Callable[[str], str]:
    """C14 prompt-formatter routing.

    ``base_vs_instruct`` (D1 semantics, compare_base_vs_instruct.py:462-463):
    base models (plus bloom-7b1) get the few-shot 'Question:/Answer:'
    scaffold; instruct models get the few-shot prefix + bare question.
    ``instruct_only`` (D2 semantics, compare_instruct_models.py:488-492):
    bare question, with the Baichuan chat template special case.
    """
    if sweep_kind == "instruct_only":
        if "baichuan" in spec.name.lower():
            return format_baichuan_prompt
        return format_instruct_direct
    if spec.is_base or spec.name.lower() == "bigscience/bloom-7b1":
        return format_base_prompt
    return format_instruct_prompt


def _host_path(path: Path) -> Path:
    """Per-host artifact suffix on pods (.hostN); identity single-process."""
    from ..parallel import multihost

    if not multihost.is_multiprocess():
        return path
    import jax

    return path.with_name(
        f"{path.stem}.host{jax.process_index()}{path.suffix}")


def _quarantine_rows(rows: List[schemas.ScoreRow],
                     guard: GuardStats) -> Tuple[List[schemas.ScoreRow], int]:
    """Row-level numerics boundary (guard/numerics parity with the
    perturbation sweep): every scored row's readouts are validated;
    offenders keep their cell identity with measurement fields nulled
    and count as ``error:numerics`` quarantines."""
    out: List[schemas.ScoreRow] = []
    n_bad = 0
    for r in rows:
        guard.site("checked", "multi")
        reason = numerics.check_values(r.yes_prob, r.no_prob)
        if reason is None:
            out.append(r)
            continue
        n_bad += 1
        guard.quarantine("multi", reason)
        log.warning("numerics guard: quarantined %s row %r (%s)",
                    r.model, r.prompt[:60], reason)
        out.append(dataclasses.replace(
            r, model_output="ERROR", yes_prob=float("nan"),
            no_prob=float("nan"), yes_no_found=False))
    return out, n_bad


def run_model_comparison_sweep(
    specs: Sequence[ModelSpec],
    engine_factory: EngineFactory,
    out_dir: Path,
    questions: Sequence[str] = WORD_MEANING_QUESTIONS,
    write_base_csv: bool = True,
    write_instruct_csv: bool = True,
    sweep_kind: str = "base_vs_instruct",
    fleet: Optional[ModelFleet] = None,
    weight_prefetch: bool = True,
    weight_cache_bytes: Optional[int] = None,
) -> Dict[str, object]:
    """Sweep every model over the 50 word-meaning questions, producing the
    D1 and/or D2 CSVs plus throughput metrics and a session log.

    The sweep runs through the fleet scheduler (engine/fleet.py): model
    i+1's weights stream host->device while model i scores, co-resident
    models stay cached inside ``weight_cache_bytes`` (None = unbounded),
    and per-model results are BITWISE what a standalone engine produces
    (weights are moved, never transformed). Pass an existing ``fleet``
    to reuse residency/staging across sweeps (the serve fleet does);
    otherwise one is built from ``engine_factory`` for this call."""
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    capture = start_capture()
    from ..parallel import multihost

    if multihost.is_multiprocess():
        # Pods parallelize across MODELS (the reference's ThreadPoolExecutor
        # axis, perturb_prompts.py:917-946): host i loads specs[i::N]; CSVs
        # get a .hostN suffix and concatenate row-wise.
        specs = multihost.host_shard(list(specs))
        log.info("multihost: process %d sweeps %d model(s)",
                 __import__("jax").process_index(), len(specs))
    meter = ThroughputMeter()
    guard = GuardStats()
    all_rows: List[schemas.ScoreRow] = []
    per_model: Dict[str, Dict[str, object]] = {}

    own_fleet = fleet is None
    if own_fleet:
        fleet = ModelFleet.from_factory(
            engine_factory, [], prefetch=weight_prefetch,
            cache_budget_bytes=weight_cache_bytes,
            # A comparison sweep visits each model once; staging a host
            # copy for reloads that never happen would only burn RAM.
            stage_reloads=False)
    known = set(fleet.model_ids)
    for spec in specs:
        if spec.name not in known:
            fleet.add_model(spec.name, make_engine=engine_factory)
            known.add(spec.name)

    for i, spec in enumerate(specs):
        log.info("=== %s (%s) ===", spec.name, spec.base_or_instruct)
        acquired = False
        draft_id = None
        try:
            engine = fleet.acquire(spec.name)
            acquired = True
            # Fleet drafting (engine/spec.py): a co-resident small
            # model drafts for this verifier, both weight refcounts
            # held for the model's whole dispatch stream.
            draft_id = fleet.acquire_spec_draft(engine, spec.name)
            if i + 1 < len(specs):
                # The prefetch pipeline: the next model's weights stream
                # on the background worker while this model's dispatches
                # run — the swap cost the old drop-and-reload loop paid
                # as dead device time per switch.
                fleet.prefetch(specs[i + 1].name)
            fmt = format_for(spec, sweep_kind)
            with meter.measure(), tracing.span(
                    "sweep/model", model=spec.name.split("/")[-1]):
                rows = run_word_meaning_sweep(
                    engine, spec.name, spec.base_or_instruct, questions, fmt,
                )
            rows, n_quarantined = _quarantine_rows(rows, guard)
            # Token accounting — the counters the reference priced into
            # dollars (perturb_prompts.py:1021-1066) feed throughput here.
            tokens_in = sum(
                len(engine.tokenizer(fmt(q)).input_ids) for q in questions
            )
            # Implied-TFLOPS/MFU sanity figure: per-MODEL matmul FLOPs at
            # this model's mean prompt length (mixed-size sweeps stay
            # correctly weighted; enc-dec models contribute no flops and
            # only dilute MFU downward — never a false "impossible" alarm).
            flops = 0.0
            if not engine.encoder_decoder:
                import jax

                from ..models.quant import QuantTensor
                from ..utils.profiling import scoring_step_flops

                flops = len(rows) * scoring_step_flops(
                    engine.cfg, 1, max(tokens_in // max(len(rows), 1), 1),
                    engine.rt.max_new_tokens)
                meter.int8_dots = meter.int8_dots or any(
                    getattr(l, "dynamic", False)
                    for l in jax.tree.leaves(
                        engine.params,
                        is_leaf=lambda x: isinstance(x, QuantTensor)))
            meter.add(len(rows), tokens_in=tokens_in,
                      tokens_out=len(rows) * engine.rt.max_new_tokens,
                      flops=flops)
            n_found = sum(r.yes_no_found for r in rows)
            per_model[spec.name] = {
                "rows": len(rows),
                "yes_no_found": n_found,
                "status": ("ok" if n_quarantined == 0 else
                           f"{numerics.NUMERICS_ERROR} — {n_quarantined} "
                           f"row(s) quarantined"),
                "rows_quarantined": n_quarantined,
            }
            log.info(
                "%s: %d rows, yes/no found in %d", spec.name, len(rows), n_found
            )
        except Exception as exc:
            log.error("Model %s failed: %s — emitting NaN rows", spec.name, exc)
            guard.quarantine("multi", MODEL_ERROR)
            rows = nan_rows_for_model(spec, questions)
            per_model[spec.name] = {"rows": len(rows),
                                    "status": f"{MODEL_ERROR}: {exc}"}
        finally:
            # C15, fleet edition: drop THIS dispatch stream's reference;
            # the LRU weight cache decides whether the model stays
            # co-resident (budget headroom -> free re-acquire later) or
            # reclaims its HBM under pressure.
            if acquired:
                fleet.release_spec_draft(engine, draft_id)
                fleet.release(spec.name)
        all_rows.extend(rows)
        mem = device_memory_stats()
        if mem:
            log.info("device memory: %s", mem)
    if own_fleet:
        fleet.shutdown()

    artifacts: Dict[str, object] = {"per_model": per_model,
                                    "throughput": meter.summary(),
                                    "fleet": fleet.stats.summary(),
                                    "guard": guard.summary()}
    log.info("fleet: %s", artifacts["fleet"])
    if write_base_csv:
        # D1 holds every swept model, base and instruct alike.
        df = schemas.write_model_comparison_csv(
            all_rows, _host_path(out_dir / "model_comparison_results.csv")
        )
        artifacts["model_comparison_csv"] = df
    if write_instruct_csv:
        instruct_rows = [r for r in all_rows if r.base_or_instruct == "instruct"]
        if instruct_rows:
            df = schemas.write_instruct_comparison_csv(
                instruct_rows,
                _host_path(out_dir / "instruct_model_comparison_results.csv")
            )
            artifacts["instruct_comparison_csv"] = df

    log.info("Sweep throughput: %s", meter.summary())
    save_captured_output(capture, _host_path(out_dir / "sweep_session_log.txt"))
    return artifacts


def base_instruct_pairs(
    pairs: Sequence[Tuple[str, str]]
) -> List[ModelSpec]:
    """Expand (base, instruct) repo-id pairs into a sweep order matching the
    reference's pair loop (compare_base_vs_instruct.py:136-180)."""
    specs: List[ModelSpec] = []
    for base, instruct in pairs:
        specs.append(ModelSpec(base, "base"))
        specs.append(ModelSpec(instruct, "instruct"))
    return specs
