"""On-pod perturbation generation (C3) — zero external API calls.

Parity target: analysis/perturb_prompts.py:727-870. The reference asks
Claude (temperature 0.9) for 100 sessions x 20 numbered rephrasings per
legal prompt, parses the numbered list (including continuation lines),
caches everything to perturbations.json, and validates the cache against
the in-code prompt list on reload. Here the generator is any local
instruct model run through the sampling decoder; the parser, cache format,
and validation rule are byte-compatible, and a cached reference
perturbations.json can be dropped in directly (BASELINE north star:
"reuse cached perturbations.json or run an instruct model on-pod as the
rephraser").
"""

from __future__ import annotations

from pathlib import Path
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..data import schemas
from ..data.prompts import LegalPrompt, rephrase_request
from ..utils.logging import get_logger

log = get_logger(__name__)

PromptParts = Tuple[str, str, Tuple[str, str], str]


def parse_numbered_rephrasings(text: str) -> List[str]:
    """Parse a numbered-list response into rephrasings.

    Rule parity (perturb_prompts.py:812-835): skip blanks and "here are"
    preambles; "N. text" splits at the first dot; "N text" strips leading
    digits and ' .-\\t'; unnumbered lines continue the previous rephrasing.
    """
    out: List[str] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.lower().startswith("here are"):
            continue
        if line[0].isdigit():
            parts = line.split(".", 1)
            if len(parts) > 1:
                out.append(parts[1].strip())
            else:
                out.append(line.lstrip("0123456789").strip(" .-\t"))
        elif out:
            out[-1] += " " + line
        else:
            out.append(line)
    return out


def prompt_parts(prompt: LegalPrompt) -> PromptParts:
    return (
        prompt.main,
        prompt.response_format,
        tuple(prompt.target_tokens),
        prompt.confidence_format,
    )


def generate_rephrasings(
    generate_text: Callable[[Sequence[str], jax.Array], List[str]],
    prompts: Sequence[LegalPrompt],
    key: jax.Array,
    sessions_per_prompt: int = 100,
    rephrasings_per_session: int = 20,
    sessions_per_batch: int = 8,
) -> List[Tuple[PromptParts, List[str]]]:
    """Generate the full perturbation set with a local model.

    `generate_text` maps (prompt texts, PRNG key) -> decoded texts; the
    sweep drivers pass a sampling-decode closure over the loaded rephraser
    model. Sessions are batched — the reference's 100 sequential API calls
    per prompt become ceil(100/B) batched TPU sampling calls.
    """
    # Two-phase closures (rephraser_from_engine) pipeline the loop: batch
    # N+1 is DISPATCHED before batch N's ids are fetched, so the host-side
    # device_get + text decode of batch N overlaps the device's sampling
    # of batch N+1 instead of serializing with it (jax dispatch is async;
    # the old loop blocked on np.asarray(jax.device_get(gen)) each batch).
    # Plain callables keep the synchronous path.
    dispatch = getattr(generate_text, "dispatch", None)
    fetch = getattr(generate_text, "fetch", None)
    pipelined = dispatch is not None and fetch is not None

    results: List[Tuple[PromptParts, List[str]]] = []
    for prompt in prompts:
        request = rephrase_request(prompt.main, n=rephrasings_per_session)
        all_rephrasings: List[str] = []
        remaining = sessions_per_prompt
        pending = None  # in-flight device handle (pipelined mode)

        def drain(handle) -> None:
            try:
                for text in fetch(handle):
                    all_rephrasings.extend(parse_numbered_rephrasings(text))
            except Exception as exc:  # session-skip parity (:841-843)
                log.warning("rephrase batch failed (%s); skipping", exc)

        while remaining > 0:
            n = min(sessions_per_batch, remaining)
            remaining -= n
            key, sub = jax.random.split(key)
            if pipelined:
                try:
                    handle = dispatch([request] * n, sub)
                except Exception as exc:
                    log.warning("rephrase batch failed (%s); skipping", exc)
                    handle = None
                if pending is not None:
                    drain(pending)
                pending = handle
                continue
            try:
                texts = generate_text([request] * n, sub)
            except Exception as exc:  # session-skip parity (:841-843)
                log.warning("rephrase batch failed (%s); skipping", exc)
                continue
            for text in texts:
                all_rephrasings.extend(parse_numbered_rephrasings(text))
        if pending is not None:
            drain(pending)
        log.info(
            "Generated %d rephrasings for prompt %r",
            len(all_rephrasings), prompt.main[:50],
        )
        results.append((prompt_parts(prompt), all_rephrasings))
    return results


def load_or_generate_perturbations(
    cache_path: Path,
    prompts: Sequence[LegalPrompt],
    generate_text: Optional[Callable[[Sequence[str], jax.Array], List[str]]],
    key: Optional[jax.Array] = None,
    sessions_per_prompt: int = 100,
    rephrasings_per_session: int = 20,
) -> List[Tuple[PromptParts, List[str]]]:
    """Cache-or-generate flow with the reference's validation rule
    (perturb_prompts.py:739-777): a reloaded cache must match the in-code
    prompt list element-by-element or it is regenerated.
    """
    cache_path = Path(cache_path)
    if cache_path.exists():
        try:
            entries = schemas.load_perturbations(cache_path)
        except Exception as exc:
            log.warning("Perturbation cache unreadable (%s); regenerating", exc)
            entries = []
        if entries and schemas.validate_perturbation_cache(entries, prompts):
            log.info(
                "Loaded %d cached perturbation sets from %s",
                len(entries), cache_path,
            )
            return entries
        if entries:
            log.warning(
                "Perturbation cache at %s does not match the prompt list; "
                "regenerating", cache_path,
            )

    if generate_text is None:
        raise RuntimeError(
            f"No valid perturbation cache at {cache_path} and no rephraser "
            "model supplied. Provide generate_text (a local sampling model) "
            "or a cached perturbations.json."
        )
    key = key if key is not None else jax.random.PRNGKey(42)
    results = generate_rephrasings(
        generate_text, prompts, key,
        sessions_per_prompt=sessions_per_prompt,
        rephrasings_per_session=rephrasings_per_session,
    )
    schemas.save_perturbations(cache_path, results)
    log.info("Saved perturbations to %s", cache_path)
    return results


def rephraser_from_engine(engine, temperature: float = 0.9,
                          max_new_tokens: int = 512):
    """Build a `generate_text` closure from a ScoringEngine's model.

    Uses the sampling decoder (temperature 0.9 parity with
    perturb_prompts.py:802) over the engine's params/config/tokenizer.

    The closure carries ``dispatch``/``fetch`` attributes splitting the
    call at its sync point: ``dispatch`` tokenizes and launches the
    sampling decode (jax dispatch is async — it returns a device handle
    immediately), ``fetch`` blocks on ``device_get`` and decodes the
    texts. generate_rephrasings uses the pair to overlap batch N's host
    decode with batch N+1's device sampling; calling ``generate_text``
    directly remains the synchronous compose of the two.
    """
    from . import generate as gen_mod
    from . import tokens as tok
    import jax.numpy as jnp

    def dispatch(texts: Sequence[str], key: jax.Array) -> jax.Array:
        ids_list = [engine.tokenizer(t).input_ids for t in texts]
        bucket = tok.pick_bucket([len(i) for i in ids_list], engine.buckets)
        toks_arr, mask = tok.left_pad_ids(
            ids_list, bucket, tok.pad_token_id(engine.tokenizer))
        return gen_mod.sample_decode(
            engine.params, engine.cfg, jnp.asarray(toks_arr),
            jnp.asarray(mask), key, temperature=temperature,
            max_new_tokens=max_new_tokens,
            # HF/API-parity EOS stop: post-EOS tokens are trimmed from the
            # text either way (decode_completion), so the only effect is
            # refunding post-completion decode steps.
            eos_id=(None if engine.eos_id is None
                    else jnp.int32(engine.eos_id)))

    def fetch(gen: jax.Array) -> List[str]:
        gen_host = np.asarray(jax.device_get(gen))
        return [engine.decode_completion(row) for row in gen_host]

    def generate_text(texts: Sequence[str], key: jax.Array) -> List[str]:
        return fetch(dispatch(texts, key))

    generate_text.dispatch = dispatch
    generate_text.fetch = fetch
    return generate_text
