"""Device-resident streaming statistics: grid -> CIs with no host row
round-trip (ROADMAP item 4).

The paper's deliverables are distributions, not rows — percentile CIs
over ~2,000 rephrasings per prompt, within-prompt kappa/agreement
contingency counts, bootstrap CIs on the per-prompt means. Before this
module the sweep materialized every row to results.csv and the
``stats``/``survey`` layers re-loaded it host-side; at the ROADMAP's
1M-rephrasing scale that host round-trip (generated ids + top-20 maps +
text per row) dominates the post-sweep cost and is the only reason live
reliability estimates don't exist mid-run.

The sink is a donated accumulator pytree held on device:

- ``filled`` (P, R) int32 — which grid cells have folded;
- ``rel``    (P, R) f32  — per-cell relative probability P(yes)/(P(yes)+
  P(no)), NaN for zero-mass or guard-quarantined cells;
- ``conf``   (P, R) f32  — per-cell weighted confidence, NaN likewise;
- ``dec``    (P, R) int32 — binarized decision (1 = yes > no, 0 = no,
  -1 = invalid). Computed as ``yes > no`` on device, which is EXACTLY
  equivalent to the host pipeline's float64 ``Relative_Prob > 0.5``
  rule (y > n in float32 implies y/(y+n) >= 0.5 + 2.5e-8 in float64 —
  far outside division rounding), so contingency counts match the
  csv-reload path bitwise.

Every scoring dispatch updates it with ONE fused XLA call
(:func:`fold_update`, accumulator donated, padding rows dropped via an
out-of-range scatter index) — no per-row device->host transfer in the
dispatch hot loop. The per-cell slot layout is the design's crux:
scatter writes are idempotent and commutative, so

- a resumed sweep re-folding rows that were dispatched but not yet
  checkpointed lands bitwise on the same accumulator (greedy decode is
  deterministic per backend) — `make chaos-smoke` proves resume-merged
  accumulators identical to an uninterrupted run;
- multihost shards fold disjoint slots and merge at the shard fence by
  elementwise union (stats/streaming.merge_accums) — order-free, no
  float reassociation;
- moments/percentiles/kappa/bootstrap reduce from the lattice in ONE
  canonical order at finalize (stats/streaming), so Welford/Chan-style
  running sums never accumulate in a resume-dependent order.

Memory: 16 bytes per grid cell (DEPLOY.md §1j arithmetic) — a
1M-rephrasing sweep holds a 16 MB accumulator where the row artifact
would stream ~2 KB per row through the host.

The device-side validity predicate mirrors guard/numerics.check_values
exactly (probs finite in [0,1], sum <= 1 + eps, weighted confidence in
[0,100], top-20 logprob map NaN-free and non-positive) so a row the
host pipeline quarantines as ``error:numerics`` is NaN'd here too —
counts agree bitwise with the csv-reload path whether or not rows were
ever materialized.

:class:`ServeStreamSink` is the online variant: serving answers clients
host-side anyway, so it folds resolved payloads into a bounded ring
(grouped by target pair) keyed by content address — idempotent across
SIGTERM checkpoint/resume, which is what keeps ``inflight_cancelled``
rows from double-counting.
"""

from __future__ import annotations

import collections
import functools
import os
import tempfile
import threading
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import get_logger
from ..utils.profiling import StreamStats

log = get_logger(__name__)

# Validation slop mirrored from guard/numerics.py — rounding, not
# tolerance for corruption. Kept numerically identical so the device
# predicate and the host quarantine can never disagree about a row.
_P_EPS = 1e-4
_SUM_EPS = 1e-3
_CONF_EPS = 1e-3

ACCUM_SUFFIX = ".accum.npz"


def new_accum(n_prompts: int, n_rephrase: int) -> Dict[str, jax.Array]:
    """Fresh device accumulator lattice for a (P, R) grid."""
    P, R = int(n_prompts), int(n_rephrase)
    return {
        "filled": jnp.zeros((P, R), jnp.int32),
        "rel": jnp.full((P, R), jnp.nan, jnp.float32),
        "conf": jnp.full((P, R), jnp.nan, jnp.float32),
        "dec": jnp.full((P, R), -1, jnp.int32),
    }


def _row_ok(yes, no, wconf, lp):
    """Device mirror of guard/numerics.check_values over the fields the
    statistics consume."""
    ok = jnp.isfinite(yes) & (yes >= -_P_EPS) & (yes <= 1.0 + _P_EPS)
    ok &= jnp.isfinite(no) & (no >= -_P_EPS) & (no <= 1.0 + _P_EPS)
    ok &= (yes + no) <= 1.0 + _SUM_EPS
    ok &= (jnp.isfinite(wconf) & (wconf >= -_CONF_EPS)
           & (wconf <= 100.0 + _CONF_EPS))
    ok &= ~jnp.any(jnp.isnan(lp), axis=-1)
    ok &= ~jnp.any(lp > _P_EPS, axis=-1)
    return ok


@functools.partial(jax.jit, donate_argnums=(0,),
                   static_argnames=("guard",))
def fold_update(acc, yes, no, wconf, lp, pidx, ridx, *,
                guard: bool = True):
    """One fused accumulator update per dispatch (the tentpole kernel).

    ``yes``/``no``/``wconf`` are the dispatch's (B,) position-0 readouts,
    ``lp`` its (B, K) top-K logprob values, ``pidx``/``ridx`` the (B,)
    grid coordinates of each row — padding rows carry ``ridx == R``
    (out of range) and are dropped by the scatter, so a dispatch's pad
    rows can never overwrite a real cell regardless of what values the
    engine happened to pad with. The accumulator is DONATED: the update
    is an in-place scatter on device, not a copy. ``guard`` is STATIC
    (baked into the executable): False — the numerics guard disabled —
    accepts every row verbatim, matching the host pipeline."""
    ok = (_row_ok(yes, no, wconf, lp) if guard
          else jnp.ones(yes.shape, bool))
    total = yes + no
    has_mass = total > 0
    rel = jnp.where(ok & has_mass, yes / total, jnp.nan)
    conf = jnp.where(ok, wconf, jnp.nan)
    dec = jnp.where(ok & has_mass, (yes > no).astype(jnp.int32), -1)
    at = lambda leaf: leaf.at[pidx, ridx]  # noqa: E731
    return {
        "filled": at(acc["filled"]).set(1, mode="drop"),
        "rel": at(acc["rel"]).set(rel.astype(jnp.float32), mode="drop"),
        "conf": at(acc["conf"]).set(conf.astype(jnp.float32),
                                    mode="drop"),
        "dec": at(acc["dec"]).set(dec, mode="drop"),
    }


def lower_fold(n_prompts: int, n_rephrase: int, batch: int, topk: int,
               guard: bool):
    """AOT lowering of :func:`fold_update` for one dispatch batch shape
    (engine/compile_plan plans one per distinct fold width, so the sweep
    loop never pays trace-on-first-call for the sink either)."""
    P, R, B = int(n_prompts), int(n_rephrase), int(batch)
    i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)  # noqa: E731
    f32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.float32)  # noqa: E731
    acc = {"filled": i32(P, R), "rel": f32(P, R), "conf": f32(P, R),
           "dec": i32(P, R)}
    return fold_update.lower(acc, f32(B), f32(B), f32(B), f32(B, topk),
                             i32(B), i32(B), guard=guard)


class StreamSink:
    """Per-sweep streaming sink: owns the device accumulator, the fold
    entry point, checkpoint save/load, and the multihost fence merge.

    The accumulator is only ever touched from the sweep writer thread
    (folds are serialized in dispatch order there), so no lock guards
    it; the StreamStats counters are thread-safe on their own.
    """

    def __init__(self, n_prompts: int, n_rephrase: int, seed: int,
                 guard: bool = True,
                 stats: Optional[StreamStats] = None,
                 registry_get: Optional[Callable] = None):
        self.n_prompts = int(n_prompts)
        self.n_rephrase = int(n_rephrase)
        self.seed = int(seed)
        self.guard = bool(guard)
        self.stats = stats if stats is not None else StreamStats()
        # Optional AOT registry hook (engine/compile_plan): called with
        # the fold batch width; returns a compiled executable or None
        # (lazy jit fallback — always correct).
        self.registry_get = registry_get
        self._acc = new_accum(self.n_prompts, self.n_rephrase)
        # Mesh placement: on a sharded engine the dispatch outputs carry
        # a NamedSharding, so the accumulator must live REPLICATED on
        # that same mesh (set on first fold; see _ensure_placement).
        # Registry executables are lowered single-device and are
        # bypassed then — the jit path compiles for the mesh shardings.
        self._mesh_placed = False
        self.stats.gauge("accum_bytes", self.accum_bytes)

    @property
    def accum_bytes(self) -> int:
        return sum(leaf.nbytes for leaf in self._acc.values())

    # -- fold (dispatch hot loop: device-side only) --------------------------

    def _ensure_placement(self, ref) -> None:
        """Colocate the accumulator with the dispatch outputs. A mesh
        engine's readouts are committed to the mesh; folding them
        against a single-device accumulator would be an incompatible-
        devices error, so the lattice is replicated onto that mesh once
        (PartitionSpec() — every device holds the identical copy; the
        scatter update then runs replicated and deterministic). Static
        metadata only: no device sync."""
        if self._mesh_placed:
            return
        sh = getattr(ref, "sharding", None)
        mesh = getattr(sh, "mesh", None)
        if mesh is None or len(getattr(sh, "device_set", ())) <= 1:
            self._mesh_placed = True   # single-device: nothing to do
            return
        from jax.sharding import NamedSharding, PartitionSpec

        target = NamedSharding(mesh, PartitionSpec())
        self._acc = jax.device_put(self._acc, target)
        # AOT fold executables were lowered without shardings — a mesh
        # sink takes the lazily-jitted path, which compiles for the
        # actual input shardings.
        self.registry_get = None
        self._mesh_placed = True

    def fold(self, yes, no, wconf, lp, cells: Sequence,
             topk: int) -> None:
        """Fold one dispatch's device readouts. ``cells`` are the REAL
        grid cells of the dispatch in row order; rows beyond them are
        padding and fold with an out-of-range slot (dropped). The update
        is ONE fused device call; nothing here reads a device value."""
        from ..observe import tracing

        with tracing.span("stream/fold", rows=len(cells)):
            self._fold(yes, no, wconf, lp, cells, topk)

    def _fold(self, yes, no, wconf, lp, cells: Sequence,
              topk: int) -> None:
        self._ensure_placement(yes)
        bsz = int(yes.shape[0])
        n = len(cells)
        pidx = np.zeros(bsz, np.int32)
        ridx = np.full(bsz, self.n_rephrase, np.int32)  # pad -> dropped
        for j, c in enumerate(cells):
            pidx[j] = c.prompt_idx
            ridx[j] = c.rephrase_idx
        compiled = (self.registry_get(bsz, topk)
                    if self.registry_get is not None else None)
        if compiled is not None:
            self._acc = compiled(self._acc, yes, no, wconf, lp,
                                 jnp.asarray(pidx), jnp.asarray(ridx))
        else:
            self._acc = fold_update(self._acc, yes, no, wconf, lp,
                                    jnp.asarray(pidx),
                                    jnp.asarray(ridx), guard=self.guard)
        self.stats.count("rows_folded", n)
        self.stats.count("dispatch_folds")

    def note_bytes_avoided(self, arrays: Sequence) -> None:
        """Account the per-row payload bytes the csv path would have
        device_get for this dispatch (shape metadata only — no sync)."""
        self.stats.count("host_bytes_avoided",
                         sum(int(a.nbytes) for a in arrays))

    # -- readout boundary (checkpoints, fences, finalize) --------------------

    def snapshot(self):
        """Explicit device->host readout of the accumulator (the ONE
        sanctioned transfer: a few bytes per grid cell, at checkpoint /
        fence / finalize cadence, never per row)."""
        from ..stats import streaming

        host = jax.device_get(self._acc)
        return streaming.HostAccum(
            filled=np.asarray(host["filled"]),
            rel=np.asarray(host["rel"]),
            conf=np.asarray(host["conf"]),
            dec=np.asarray(host["dec"]),
            seed=self.seed)

    def checkpoint(self, path: Path) -> None:
        """Atomic accumulator snapshot next to the results artifact
        (PR-4 manifest machinery: tmp + fsync + rename, so a kill
        mid-checkpoint leaves the previous snapshot, never a torn one).
        Called at every flush boundary and from the preemption exit
        path — a resumed sweep seeds from it and re-folds only what the
        manifest says is pending (idempotent by slot layout)."""
        acc = self.snapshot()
        save_accum(acc, path)
        self.stats.count("checkpoints")

    def load(self, path: Path) -> bool:
        """Seed the device accumulator from a prior checkpoint. Shape
        mismatch (a different grid) starts fresh instead of corrupting."""
        acc = load_accum(path)
        if acc is None:
            return False
        if acc.filled.shape != (self.n_prompts, self.n_rephrase):
            log.warning("stream accum %s has shape %s != grid (%d, %d); "
                        "starting fresh", path, acc.filled.shape,
                        self.n_prompts, self.n_rephrase)
            return False
        self.seed = int(acc.seed)
        # jnp.array (copy=True), NOT jnp.asarray: on CPU, asarray may
        # ZERO-COPY-alias the checkpoint's host numpy buffers, and
        # fold_update donates the lattice — XLA is then free to reuse
        # any donated same-size buffer for any output (int32 `filled`
        # and f32 `conf` are both 4 B/cell), which intermittently
        # cross-wires the leaves after a resume (filled's bit pattern
        # showing up as 1e-45 denormals in conf). A donated buffer must
        # be one the device exclusively owns.
        self._acc = {
            "filled": jnp.array(acc.filled),
            "rel": jnp.array(acc.rel),
            "conf": jnp.array(acc.conf),
            "dec": jnp.array(acc.dec),
        }
        self._mesh_placed = False   # re-colocate on the next fold
        return True

    def merge_across_hosts(self, allow_identical_overlap: bool = False):
        """Multihost fence merge: allgather every host's shard
        accumulator and union them slot-wise. A COLLECTIVE — every host
        must call it at the same fence. Returns the merged HostAccum
        (identical on every host). Static shards are disjoint (overlap
        is a hard error); LEASED sweeps pass
        ``allow_identical_overlap=True`` because a stolen shard's
        re-scored rows legitimately appear in two hosts' lattices —
        bitwise-identical by slot idempotence, asserted by the merge."""
        from ..parallel import multihost
        from ..stats import streaming

        mine = self.snapshot()
        gathered = [
            streaming.HostAccum(filled=f, rel=r, conf=c, dec=d,
                                seed=self.seed)
            for f, r, c, d in zip(
                multihost.gather_stacked(mine.filled),
                multihost.gather_stacked(mine.rel),
                multihost.gather_stacked(mine.conf),
                multihost.gather_stacked(mine.dec))
        ]
        merged = streaming.merge_accums(
            gathered, allow_identical_overlap=allow_identical_overlap)
        self.stats.count("merges")
        return merged

    def finalize(self, n_boot: int = 1000, confidence: float = 0.95):
        """Grid -> CIs directly from the accumulator (no csv reload).
        Also the live mid-run estimate: callable at any point of a
        running sweep for in-progress percentile/kappa estimates."""
        import time as _time

        from ..stats import streaming

        t0 = _time.perf_counter()
        out = streaming.summarize(self.snapshot(), n_boot=n_boot,
                                  confidence=confidence)
        self.stats.count("finalize_s", _time.perf_counter() - t0)
        return out


def save_accum(acc, path: Path) -> None:
    """Crash-safe accumulator write (tmp + fsync + rename)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=str(path.parent),
                               prefix=path.name + ".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, filled=acc.filled, rel=acc.rel, conf=acc.conf,
                     dec=acc.dec, seed=np.int64(acc.seed))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_accum(path: Path):
    """Read a checkpointed accumulator; None when missing/unreadable
    (resume then re-folds from the manifest's pending set alone)."""
    from ..stats import streaming

    path = Path(path)
    if not path.exists():
        return None
    try:
        with np.load(path) as z:
            return streaming.HostAccum(
                filled=z["filled"], rel=z["rel"], conf=z["conf"],
                dec=z["dec"], seed=int(z["seed"]))
    except Exception as err:  # noqa: BLE001 — a torn/foreign file only
        # costs re-folding; never fails the resume.
        log.warning("stream accum %s unreadable (%r); starting fresh",
                    path, err)
        return None


class WindowedStreamSink:
    """The accumulator lattice with a TIME axis (ROADMAP item 5): one
    donated device lattice per window id, managed as an ordered pool.

    Each window is a full :class:`StreamSink` over the same (rows,
    cols) grid, so EVERY property PR 9 proved carries over per window
    unchanged: folds are one fused donated scatter, idempotent and
    commutative within a window (a re-scored slot lands bitwise on the
    same cell), per-window checkpoints are atomic, and resume/merge is
    the same slot-wise union (``stats/streaming.merge_accums``) —
    order-free, overlap a hard error. The time axis only chooses WHICH
    lattice a fold targets; it never changes fold semantics.

    The observatory uses rows = fleet models and cols = sentinel slots
    (``sweep_slot * n_sentinels + sentinel_idx``), but the class is
    grid-agnostic — an offline windowed re-scoring sweep can use
    (prompt, rephrase) exactly like the single-window sink.

    Window lifecycle: windows materialize on first fold; beyond
    ``max_windows`` the OLDEST window's device lattice is dropped
    (after an optional checkpoint via the eviction hook) so a
    long-running observatory holds bounded HBM. Thread discipline
    mirrors StreamSink: one folding thread (the sentinel scheduler /
    sweep writer); checkpoints and drift readers consume host
    snapshots.
    """

    def __init__(self, n_rows: int, n_cols: int, seed: int = 0,
                 guard: bool = True, max_windows: int = 64,
                 stats: Optional[StreamStats] = None,
                 on_evict: Optional[Callable[[int], None]] = None):
        self.n_rows = int(n_rows)
        self.n_cols = int(n_cols)
        self.seed = int(seed)
        self.guard = bool(guard)
        self.max_windows = max(int(max_windows), 1)
        self.stats = stats if stats is not None else StreamStats()
        self.on_evict = on_evict
        self._sinks: Dict[int, StreamSink] = {}
        self._order: List[int] = []      # insertion order = age

    def window_ids(self) -> List[int]:
        return sorted(self._sinks)

    def sink(self, window_id: int) -> StreamSink:
        """The window's sink, created on first touch. The per-window
        bootstrap seed is fold_in-style derived (seed + window id) so
        CIs stay reproducible per window across resume."""
        wid = int(window_id)
        s = self._sinks.get(wid)
        if s is None:
            s = StreamSink(self.n_rows, self.n_cols,
                           seed=self.seed + wid, guard=self.guard,
                           stats=self.stats)
            self._sinks[wid] = s
            self._order.append(wid)
            while len(self._order) > self.max_windows:
                old = self._order.pop(0)
                if self.on_evict is not None:
                    self.on_evict(old)
                del self._sinks[old]
                log.info("windowed sink: dropped window %d "
                         "(max_windows=%d)", old, self.max_windows)
        return s

    def fold(self, window_id: int, yes, no, wconf, lp,
             cells: Sequence, topk: int) -> None:
        """One fused fold into the window's lattice (StreamSink.fold
        semantics exactly — padding rows scatter out of range)."""
        self.sink(window_id).fold(yes, no, wconf, lp, cells, topk)

    def snapshot(self, window_id: int):
        return self._sinks[int(window_id)].snapshot()

    def device_acc(self, window_id: int) -> Dict[str, jax.Array]:
        """The window's live device lattice (observe/drift.py reduces
        it on device without a host round-trip)."""
        return self._sinks[int(window_id)]._acc

    # -- checkpoint / resume -------------------------------------------------

    def _window_path(self, directory: Path, wid: int) -> Path:
        return Path(directory) / f"w{int(wid)}{ACCUM_SUFFIX}"

    def checkpoint(self, directory: Path) -> int:
        """Atomic per-window accumulator snapshots (``w<id>.accum.npz``
        — the single-window save_accum format, one file per window so a
        kill mid-checkpoint tears at most one window back to its
        previous snapshot). Returns windows written."""
        directory = Path(directory)
        directory.mkdir(parents=True, exist_ok=True)
        for wid, s in self._sinks.items():
            s.checkpoint(self._window_path(directory, wid))
        return len(self._sinks)

    def load(self, directory: Path) -> List[int]:
        """Seed window lattices from a checkpoint directory; returns
        the window ids restored. Re-folds after a resume are bitwise
        no-ops per window (slot idempotence), so kill → load → re-score
        converges on the uninterrupted run's lattices exactly."""
        directory = Path(directory)
        restored: List[int] = []
        if not directory.is_dir():
            return restored
        for path in sorted(directory.glob(f"w*{ACCUM_SUFFIX}")):
            stem = path.name[:-len(ACCUM_SUFFIX)]
            try:
                wid = int(stem[1:])
            except ValueError:
                continue
            if self.sink(wid).load(path):
                restored.append(wid)
        return restored

    def merge_window(self, window_id: int, other) -> None:
        """Slot-wise union of a disjoint shard's HostAccum into one
        window (streaming.merge_accums discipline: overlap on a filled
        slot raises — two folders scored one sentinel cell)."""
        from ..stats import streaming

        wid = int(window_id)
        mine = self.snapshot(wid) if wid in self._sinks else None
        if mine is None:
            merged = other
        else:
            merged = streaming.merge_accums([mine, other])
        s = self.sink(wid)
        # jnp.array (copy), not asarray: the lattice is donated on the
        # next fold — see StreamSink.load.
        s._acc = {
            "filled": jnp.array(merged.filled),
            "rel": jnp.array(merged.rel),
            "conf": jnp.array(merged.conf),
            "dec": jnp.array(merged.dec),
        }
        s._mesh_placed = False
        self.stats.count("merges")


class ServeStreamSink:
    """Online streaming sink: live percentile/kappa estimates over the
    last ``window`` served rows, grouped by target pair.

    Serving transfers every payload host-side anyway (clients need
    answers),
    so folds consume resolved payloads — which is also what makes the
    accounting idempotent across a SIGTERM checkpoint: a row folds iff
    its future resolved ok, exactly once, keyed by content address. An
    ``inflight_cancelled`` row never folds (its future resolved expired
    before the payload landed); if it is re-submitted after a resume it
    folds on its fresh score — once.
    """

    def __init__(self, window: int = 4096, max_groups: int = 64,
                 stats: Optional[StreamStats] = None):
        self.window = max(int(window), 1)
        self.max_groups = int(max_groups)
        self.stats = stats if stats is not None else StreamStats()
        self._lock = threading.Lock()
        # Ring lattice + idempotence set; all guarded by _lock (the
        # supervisor folds while stats endpoints read).
        self._group_ids: Dict[Tuple[str, str], int] = {}  # guarded-by: _lock
        self._group: np.ndarray = np.full(self.window, -1, np.int32)  # guarded-by: _lock
        self._rel: np.ndarray = np.full(self.window, np.nan, np.float64)  # guarded-by: _lock
        self._conf: np.ndarray = np.full(self.window, np.nan, np.float64)  # guarded-by: _lock
        self._dec: np.ndarray = np.full(self.window, -1, np.int32)  # guarded-by: _lock
        self._head: int = 0  # guarded-by: _lock
        self._folded: "collections.OrderedDict[str, None]" = (  # guarded-by: _lock
            collections.OrderedDict())
        self._folded_cap = max(8 * self.window, 65536)

    def _group_id(self, targets: Tuple[str, str]) -> int:  # guarded-by: _lock
        gid = self._group_ids.get(targets)
        if gid is None:
            if len(self._group_ids) >= self.max_groups:
                return self.max_groups - 1  # overflow bucket
            gid = len(self._group_ids)
            self._group_ids[targets] = gid
        return gid

    def fold_payload(self, key, targets: Tuple[str, str],
                     payload: Dict) -> bool:
        """Fold one resolved measurement payload; returns False when the
        content address already folded (dedup hit, checkpoint resume,
        re-submitted cancelled row) — the double-count guard."""
        key = str(key)
        t1p = payload.get("token_1_prob")
        t2p = payload.get("token_2_prob")
        wc = payload.get("weighted_confidence")
        with self._lock:
            if key in self._folded:
                return False
            self._folded[key] = None
            while len(self._folded) > self._folded_cap:
                self._folded.popitem(last=False)
            i = self._head % self.window
            self._head += 1
            self._group[i] = self._group_id(tuple(targets))
            if (t1p is not None and t2p is not None
                    and np.isfinite(t1p) and np.isfinite(t2p)
                    and t1p + t2p > 0):
                self._rel[i] = t1p / (t1p + t2p)
                self._dec[i] = 1 if t1p > t2p else 0
            else:
                self._rel[i] = np.nan
                self._dec[i] = -1
            self._conf[i] = (float(wc) if wc is not None
                             and np.isfinite(wc) else np.nan)
        self.stats.count("rows_folded")
        return True

    def summary(self) -> Dict[str, object]:
        """Live estimates over the ring: per-group n/mean/percentiles of
        the relative probability, confidence mean, and the within-group
        kappa over binarized decisions (stats/streaming closed form)."""
        from ..stats import streaming

        self.stats.count("live_queries")
        with self._lock:
            used = self._group >= 0
            group = self._group[used].copy()
            rel = self._rel[used].copy()
            conf = self._conf[used].copy()
            dec = self._dec[used].copy()
            names = {gid: list(t) for t, gid in self._group_ids.items()}
        per_group: Dict[str, object] = {}
        for gid in sorted(set(group.tolist())):
            m = group == gid
            r = rel[m]
            r = r[np.isfinite(r)]
            entry: Dict[str, object] = {
                "targets": names.get(gid, ["?", "?"]),
                "rows": int(m.sum()), "n_valid": int(r.size),
            }
            if r.size:
                entry.update({
                    "mean_relative_prob": float(r.mean()),
                    "p2_5": float(np.percentile(r, 2.5)),
                    "p97_5": float(np.percentile(r, 97.5)),
                })
            c = conf[m]
            c = c[np.isfinite(c)]
            if c.size:
                entry["mean_weighted_confidence"] = float(c.mean())
            per_group[str(gid)] = entry
        valid = dec >= 0
        kap = streaming.kappa_from_counts(
            *streaming.group_counts(group[valid], dec[valid]))
        return {"rows_folded": int(self._head),
                "window": int(min(self._head, self.window)),
                "per_group": per_group, "kappa": kap}

    # -- SIGTERM checkpoint / resume -----------------------------------------

    def state(self) -> Dict[str, object]:
        """JSON-serializable snapshot for the serve shutdown checkpoint:
        the ring lattice AND the folded-key set, so a resumed server
        never re-folds a row the previous incarnation counted."""
        with self._lock:
            return {
                "window": self.window,
                "head": self._head,
                "groups": [[list(t), gid]
                           for t, gid in self._group_ids.items()],
                "group": self._group.tolist(),
                "rel": [None if not np.isfinite(v) else float(v)
                        for v in self._rel],
                "conf": [None if not np.isfinite(v) else float(v)
                         for v in self._conf],
                "dec": self._dec.tolist(),
                "folded": list(self._folded.keys()),
            }

    def restore(self, state: Optional[Dict[str, object]]) -> None:
        if not state or int(state.get("window", 0)) != self.window:
            return
        with self._lock:
            self._head = int(state["head"])
            self._group_ids = {tuple(t): int(g)
                               for t, g in state["groups"]}
            self._group = np.asarray(state["group"], np.int32)
            self._rel = np.asarray(
                [np.nan if v is None else v for v in state["rel"]],
                np.float64)
            self._conf = np.asarray(
                [np.nan if v is None else v for v in state["conf"]],
                np.float64)
            self._dec = np.asarray(state["dec"], np.int32)
            self._folded = collections.OrderedDict(
                (k, None) for k in state.get("folded", ()))
