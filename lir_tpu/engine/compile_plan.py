"""Compile plan: parallel AOT precompilation of the sweep's executables.

The ragged scheduler plans every dispatch shape up front, so nothing about
compilation needs to be lazy: this module turns a dispatch plan into the
exact set of (bucket, batch, suffix, variant) executables the sweep will
call, lowers and compiles them CONCURRENTLY in background threads (XLA
compilation releases the GIL) while the first bucket streams, and hands
the engine an :class:`ExecutableRegistry` the dispatch path consults
instead of triggering trace-on-first-call inside the timed loop.

Three layers cooperate:

1. **Persistent cache** (utils/compile_cache.py): every AOT compile goes
   through JAX's disk cache, so a restarted worker deserializes instead
   of recompiling — and because the lazy jit path hashes to the SAME HLO,
   precompiled-vs-lazy results are not merely numerically equal but the
   same executable.
2. **This registry**: keyed by (engine manifest key, shape spec). The
   manifest key covers model config, quant mode, mesh, and bucket ladder
   (utils/compile_cache.manifest_key), so an executable compiled for one
   configuration can never be looked up by another.
3. **Observability** (utils/profiling.CompileStats): per-shape compile
   seconds, registry hit / lazy-miss counts, persistent-cache hit/miss
   deltas — logged per sweep and surfaced in bench.py's headline.

The registry is an OPTIMIZATION: every lookup miss (unplanned shape, the
runner's shared-prefix fallback path, a failed compile) falls through to
the ordinary jitted call, which is always correct.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..utils.logging import get_logger
from ..utils.profiling import CompileStats

log = get_logger(__name__)

TOPK = 20  # the D6 top-20 logprob map — fixed across every sweep caller


@dataclasses.dataclass(frozen=True)
class ShapeSpec:
    """Everything that selects one compiled executable, shape-wise.

    ``kind`` is "shared" (decode_fused_shared), "grouped"
    (decode_fused_grouped), or their prefix-cache-resume variants
    "shared_paged"/"grouped_paged" (generate.*_paged — the block-table
    executables, selected additionally by ``window``, the remainder-
    window edge each row recomputes while the rest of its prefix
    gathers from the page pool). ``batch`` is the PADDED member-row
    count the runner will dispatch (shared: the padded batch; grouped:
    m_pad); ``groups`` the padded prefill-row count (grouped only, else
    0). ``sfx_a``/``sfx_b`` are the right-pad suffix bucket edges
    (grouped uses a single merged edge in ``sfx_a``). ``stops_armed``
    records whether the stop-mask arguments are arrays or None — that
    changes the traced pytree, hence the executable. ``scratch``
    selects the donated-KV-cache variant (every dispatch after the
    first of a bucket queue donates the previous cache —
    runner._CacheHandoff; paged and unpaged variants of one shape
    return the same cache aval, so the chain crosses them freely).
    ``spec_k`` > 0 selects the SPECULATIVE-decode executable for that
    verify-window size (generate.greedy_decode_fused_shared_spec /
    _paged_spec — the verify executables are planned per (bucket,
    batch, k)); ``spec_draft`` its fleet-draft-model variant (the
    draft model's params ride the traced pytree). ``trunk`` > 0 selects
    a CASCADE-prefill executable (kinds "shared_cascade"/
    "shared_cascade_paged" — generate.greedy_decode_fused_shared_cascade
    and its paged-trunk sibling) at that static shared-trunk extent, and
    ``cascade_int8`` its in-kernel int8-QK^T variant; both change the
    lowered program, so keying them here is what guarantees an
    executable can never serve the wrong mode (a dense lookup can't
    return a cascade program or vice versa). For the paged cascade kind,
    ``window`` is the TRUNK's recompute-window edge (the (1, W) chunk
    the radix resume teacher-forces), not a per-row window.
    ``decode_trunk`` > 0 selects the CASCADE-DECODE variant of the
    plain "shared"/"shared_paged" kinds (and their spec siblings): the
    decode scans' trunk splits run trunk-aware
    (ops/flash_decode.flash_decode_trunk — bitwise the flat kernels)
    at that static trunk extent. The cascade kinds don't carry it:
    their decode trunk IS ``trunk`` (generate._cascade_branches), so
    ``trunk`` already keys the lowering."""

    kind: str
    bucket: int
    batch: int
    groups: int
    sfx_a: int
    sfx_b: int
    new_tokens: int
    conf_tokens: int
    stops_armed: bool
    scratch: bool
    window: int = 0
    spec_k: int = 0
    spec_draft: bool = False
    trunk: int = 0
    cascade_int8: bool = False
    decode_trunk: int = 0

    @property
    def label(self) -> str:
        sfx = (f"{self.sfx_a}+{self.sfx_b}"
               if self.kind.startswith(("shared", "piggy"))
               else str(self.sfx_a))
        var = "donated" if self.scratch else "fresh"
        win = f"/win{self.window}" if self.window else ""
        spec = ""
        if self.spec_k:
            spec = f"/spec{self.spec_k}" + ("+draft" if self.spec_draft
                                            else "")
        casc = ""
        if self.trunk:
            casc = f"/trunk{self.trunk}" + ("+i8" if self.cascade_int8
                                            else "")
        if self.decode_trunk:
            casc += f"/dtrunk{self.decode_trunk}"
        return (f"{self.kind}/b{self.bucket}x{self.batch}/sfx{sfx}"
                f"/new{self.new_tokens}-{self.conf_tokens}{win}{spec}"
                f"{casc}/{var}")


def shared_spec(bucket: int, batch: int, sfx_a: int, sfx_b: int,
                new_tokens: int, conf_tokens: int, stops_armed: bool,
                scratch: bool, spec_k: int = 0,
                spec_draft: bool = False,
                decode_trunk: int = 0) -> ShapeSpec:
    return ShapeSpec("shared", int(bucket), int(batch), 0, int(sfx_a),
                     int(sfx_b), int(new_tokens), int(conf_tokens),
                     bool(stops_armed), bool(scratch),
                     spec_k=int(spec_k), spec_draft=bool(spec_draft),
                     decode_trunk=int(decode_trunk))


def grouped_spec(bucket: int, groups: int, batch: int, sfx: int,
                 max_new: int, stops_armed: bool,
                 scratch: bool) -> ShapeSpec:
    return ShapeSpec("grouped", int(bucket), int(batch), int(groups),
                     int(sfx), 0, int(max_new), 0, bool(stops_armed),
                     bool(scratch))


def shared_paged_spec(bucket: int, batch: int, window: int, sfx_a: int,
                      sfx_b: int, new_tokens: int, conf_tokens: int,
                      stops_armed: bool, scratch: bool,
                      spec_k: int = 0,
                      decode_trunk: int = 0) -> ShapeSpec:
    return ShapeSpec("shared_paged", int(bucket), int(batch), 0,
                     int(sfx_a), int(sfx_b), int(new_tokens),
                     int(conf_tokens), bool(stops_armed), bool(scratch),
                     int(window), spec_k=int(spec_k),
                     decode_trunk=int(decode_trunk))


def shared_cascade_spec(bucket: int, batch: int, trunk: int, sfx_a: int,
                        sfx_b: int, new_tokens: int, conf_tokens: int,
                        stops_armed: bool, scratch: bool,
                        int8_qk: bool = False) -> ShapeSpec:
    """Cold cascade-prefill executable (generate.greedy_decode_fused_
    shared_cascade): batch-1 trunk prefill at the static ``trunk``
    extent + per-row cascade remainder extension."""
    return ShapeSpec("shared_cascade", int(bucket), int(batch), 0,
                     int(sfx_a), int(sfx_b), int(new_tokens),
                     int(conf_tokens), bool(stops_armed), bool(scratch),
                     trunk=int(trunk), cascade_int8=bool(int8_qk))


def shared_cascade_paged_spec(bucket: int, batch: int, trunk: int,
                              window: int, sfx_a: int, sfx_b: int,
                              new_tokens: int, conf_tokens: int,
                              stops_armed: bool, scratch: bool,
                              int8_qk: bool = False) -> ShapeSpec:
    """Warm cascade executable (generate.greedy_decode_fused_shared_
    cascade_paged): the trunk resumes from the radix page pool through a
    (1, ``window``) recompute chunk instead of prefilling."""
    return ShapeSpec("shared_cascade_paged", int(bucket), int(batch), 0,
                     int(sfx_a), int(sfx_b), int(new_tokens),
                     int(conf_tokens), bool(stops_armed), bool(scratch),
                     int(window), trunk=int(trunk),
                     cascade_int8=bool(int8_qk))


def grouped_paged_spec(bucket: int, groups: int, batch: int, window: int,
                       sfx: int, max_new: int, stops_armed: bool,
                       scratch: bool) -> ShapeSpec:
    return ShapeSpec("grouped_paged", int(bucket), int(batch), int(groups),
                     int(sfx), 0, int(max_new), 0, bool(stops_armed),
                     bool(scratch), int(window))


def stream_fold_spec(n_prompts: int, n_rephrase: int, batch: int,
                     guard: bool) -> ShapeSpec:
    """Streaming-statistics accumulator update (engine/stream_stats.
    fold_update) for one fold width: ``bucket`` carries the prompt
    count, ``groups`` the rephrase-slot count, ``batch`` the dispatch's
    fold width (shared: padded member rows; grouped: one branch's row
    count), and ``stops_armed`` the numerics-guard bit — the guard is a
    STATIC of the fold program (it changes the lowered predicate), so
    guarded and unguarded sinks can never share an executable."""
    return ShapeSpec("stream_fold", int(n_prompts), int(batch),
                     int(n_rephrase), 0, 0, 0, 0, bool(guard), False)


def piggy_prefill_spec(bucket: int, batch: int, sfx_a: int, sfx_b: int,
                       new_tokens: int, conf_tokens: int) -> ShapeSpec:
    """Chain opener (generate.shared_piggyback_prefill): prefill + suffix
    extensions into the disjoint-region carry, decode scans parked. Stop
    tables don't appear until the scans run, so stops_armed is always
    False here."""
    return ShapeSpec("piggy_prefill", int(bucket), int(batch), 0,
                     int(sfx_a), int(sfx_b), int(new_tokens),
                     int(conf_tokens), False, False)


def piggy_step_spec(bucket: int, batch: int, sfx_a: int, sfx_b: int,
                    new_tokens: int, conf_tokens: int,
                    stops_armed: bool) -> ShapeSpec:
    """One piggybacked call: parked decode scans + the next dispatch's
    prefill in one program (generate.shared_piggyback_step)."""
    return ShapeSpec("piggy_step", int(bucket), int(batch), 0, int(sfx_a),
                     int(sfx_b), int(new_tokens), int(conf_tokens),
                     bool(stops_armed), False)


def piggy_drain_spec(bucket: int, batch: int, sfx_a: int, sfx_b: int,
                     new_tokens: int, conf_tokens: int,
                     stops_armed: bool) -> ShapeSpec:
    """Chain closer: the last parked dispatch's decode scans alone
    (generate.shared_piggyback_drain)."""
    return ShapeSpec("piggy_drain", int(bucket), int(batch), 0, int(sfx_a),
                     int(sfx_b), int(new_tokens), int(conf_tokens),
                     bool(stops_armed), False)


def plan_specs(dispatches: Sequence[Any], batch_size: int, new_tokens: int,
               conf_tokens: int, stops_armed: bool,
               prefix_page_size: int = 0,
               piggyback: bool = False,
               stream_shape: Optional[Tuple[int, int, bool]] = None,
               spec_k: int = 0, spec_draft: bool = False,
               cascade_trunk=None, cascade_int8: bool = False,
               decode_trunk=None,
               ) -> List[ShapeSpec]:
    """Distinct executables a dispatch plan will call, in first-use order
    (the precompile pool works the list front-to-back, so the first
    bucket's executable compiles first and the dispatch loop rarely
    waits). Mirrors the runner's padding/handoff behavior exactly:
    the first dispatch of each handoff key runs the scratchless variant,
    every consecutive same-key dispatch the donated one.

    ``prefix_page_size`` > 0 (an engine whose cross-request prefix cache
    is enabled) additionally plans the block-table executables: for each
    dispatch shape, one paged variant per remainder-window edge the
    runner may pick (models/paged.window_edges) — which window a warm
    dispatch runs depends on what the radix tree holds at dispatch
    time, so the plan covers them all.

    ``piggyback`` (an engine whose chunked prefill/decode piggybacking is
    on) plans the chain executables for every run of CONSECUTIVE
    same-shape shared dispatches — the exact chains the sweep forms:
    opener (prefill-only), step (parked decode + next prefill), and
    drain. Plain specs stay planned regardless (the runtime memory gate
    may refuse a chain, and the recovery path re-dispatches plainly).

    ``stream_shape`` = (n_prompts, n_rephrase, numerics_guard) plans the
    streaming-statistics accumulator-update executable for every
    distinct fold width the plan's dispatches will use (shared: the
    padded member-row count; grouped: one branch's row count), so the
    sink's per-dispatch fold never pays trace-on-first-call inside the
    timed loop either. Planned FIRST — the very first dispatch folds.

    ``cascade_trunk`` (a cascade-prefill engine) maps a shared dispatch
    to its snapped shared-trunk extent (0 = ineligible — the runner's
    own eligibility rule, so the plan covers exactly the cascade
    executables the loop will call); eligible dispatches plan the
    cascade executable (plus its paged-trunk variants when the prefix
    cache is on — the trunk's recompute window depends on what the
    radix tree holds at dispatch time, so every trunk window edge is
    covered). The plain shared spec stays planned regardless: a dense
    fallback re-dispatches through it.

    ``decode_trunk`` (a cascade-DECODE engine) maps a shared dispatch to
    the static trunk extent its decode scans dedup at (0 = flat
    kernels); eligible dispatches plan the trunk-aware variant of every
    plain shared/paged/spec executable ALONGSIDE the flat one — which
    variant the runner calls depends on the same per-dispatch rule, and
    the flat specs cover the --no-cascade-decode engine and the dense
    fallback."""
    from ..models import paged as paged_mod

    specs: List[ShapeSpec] = []
    seen = set()
    prev_key: Optional[Tuple] = None

    def add(spec: ShapeSpec) -> None:
        if spec not in seen:
            seen.add(spec)
            specs.append(spec)

    if stream_shape is not None:
        n_prompts, n_rephrase, guard = stream_shape
        for d in dispatches:
            _, m_pad = d.padded_rows(batch_size)
            width = m_pad if d.kind == "shared" else len(d.items)
            add(stream_fold_spec(n_prompts, n_rephrase, width, guard))
    for d in dispatches:
        g_pad, m_pad = d.padded_rows(batch_size)
        if d.kind == "shared":
            key = ("shared", d.bucket, m_pad, d.sfx_bucket_a,
                   d.sfx_bucket_b, new_tokens, conf_tokens)
            scratch = key == prev_key
            trunk = int(cascade_trunk(d)) if cascade_trunk else 0
            # Cascade-decode extent for the PLAIN kinds: a cascade-
            # prefill-eligible dispatch never reaches them (the cascade
            # path takes precedence), so its dtrunk variants would be
            # dead compiles.
            dt = (int(decode_trunk(d))
                  if (decode_trunk is not None and not trunk) else 0)
            add(shared_spec(d.bucket, m_pad, d.sfx_bucket_a,
                            d.sfx_bucket_b, new_tokens, conf_tokens,
                            stops_armed, scratch=scratch,
                            decode_trunk=dt))
            if spec_k:
                # Speculative verify executables, planned per
                # (bucket, batch, k) alongside the sequential shape
                # (the runner falls back to it on a spec-ineligible
                # dispatch).
                add(shared_spec(d.bucket, m_pad, d.sfx_bucket_a,
                                d.sfx_bucket_b, new_tokens, conf_tokens,
                                stops_armed, scratch=scratch,
                                spec_k=spec_k, spec_draft=spec_draft,
                                decode_trunk=dt))
            if trunk:
                add(shared_cascade_spec(d.bucket, m_pad, trunk,
                                        d.sfx_bucket_a, d.sfx_bucket_b,
                                        new_tokens, conf_tokens,
                                        stops_armed, scratch=scratch,
                                        int8_qk=cascade_int8))
                if prefix_page_size:
                    for w in paged_mod.window_edges(trunk,
                                                    prefix_page_size):
                        add(shared_cascade_paged_spec(
                            d.bucket, m_pad, trunk, w, d.sfx_bucket_a,
                            d.sfx_bucket_b, new_tokens, conf_tokens,
                            stops_armed, scratch=scratch,
                            int8_qk=cascade_int8))
            if piggyback and scratch and not trunk:
                # A repeat of the previous shared shape — the sweep will
                # chain these dispatches: plan all three chain stages.
                add(piggy_prefill_spec(d.bucket, m_pad, d.sfx_bucket_a,
                                       d.sfx_bucket_b, new_tokens,
                                       conf_tokens))
                add(piggy_step_spec(d.bucket, m_pad, d.sfx_bucket_a,
                                    d.sfx_bucket_b, new_tokens,
                                    conf_tokens, stops_armed))
                add(piggy_drain_spec(d.bucket, m_pad, d.sfx_bucket_a,
                                     d.sfx_bucket_b, new_tokens,
                                     conf_tokens, stops_armed))
            if prefix_page_size:
                for w in paged_mod.window_edges(d.bucket, prefix_page_size):
                    add(shared_paged_spec(
                        d.bucket, m_pad, w, d.sfx_bucket_a, d.sfx_bucket_b,
                        new_tokens, conf_tokens, stops_armed,
                        scratch=scratch, decode_trunk=dt))
                    if spec_k and not spec_draft:
                        # Paged + speculative composes for self-drafting
                        # only (the paged front binds slot tables, not
                        # prefix tokens — nothing for a draft model to
                        # prefill from).
                        add(shared_paged_spec(
                            d.bucket, m_pad, w, d.sfx_bucket_a,
                            d.sfx_bucket_b, new_tokens, conf_tokens,
                            stops_armed, scratch=scratch, spec_k=spec_k,
                            decode_trunk=dt))
        else:
            sfx = max(d.sfx_bucket_a, d.sfx_bucket_b)
            max_new = max(new_tokens, conf_tokens)
            key = ("grouped", d.bucket, g_pad, m_pad, sfx, max_new)
            scratch = key == prev_key
            add(grouped_spec(d.bucket, g_pad, m_pad, sfx, max_new,
                             stops_armed, scratch=scratch))
            if prefix_page_size:
                for w in paged_mod.window_edges(d.bucket, prefix_page_size):
                    add(grouped_paged_spec(
                        d.bucket, g_pad, m_pad, w, sfx, max_new,
                        stops_armed, scratch=scratch))
        prev_key = key
    return specs


# ---------------------------------------------------------------------------
# Lowering: exact aval reconstruction of the runner's call sites
# ---------------------------------------------------------------------------

def _spec_avals(engine, spec: ShapeSpec):
    """The eight drafting-array avals (SpecPlan.dyn_args order) appended
    to a speculative executable's argument list."""
    import jax
    import jax.numpy as jnp

    i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)  # noqa: E731
    B = spec.batch
    return (i32(B, spec.bucket + spec.sfx_a + spec.new_tokens), i32(B),
            i32(B, spec.new_tokens), i32(B),
            i32(B, spec.bucket + spec.sfx_b + spec.conf_tokens), i32(B),
            i32(B, spec.conf_tokens), i32(B))


def _spec_statics(engine, spec: ShapeSpec) -> dict:
    out = dict(spec_k=spec.spec_k, ngram=int(engine.spec_cfg.ngram))
    return out


def _spec_draft_kwargs(engine, spec: ShapeSpec):
    """(dynamic kwargs, statics) arming the fleet draft model in a
    speculative executable's signature."""
    if not spec.spec_draft:
        return {"draft_params": None}, {"draft_cfg": None}
    draft_params, draft_cfg, _ = engine._spec_draft
    import jax

    avals = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype),
        draft_params)
    return {"draft_params": avals}, {"draft_cfg": draft_cfg}


def _avals_shared(engine, spec: ShapeSpec):
    """(args, kwargs) ShapeDtypeStructs matching runner.decode_fused_shared's
    call into generate.greedy_decode_fused_shared (or its speculative
    sibling when ``spec.spec_k``) — one canonical layout shared with
    :func:`_registry_call` so lowering and dispatch can never drift
    apart."""
    import jax
    import jax.numpy as jnp

    i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)  # noqa: E731
    f32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.float32)  # noqa: E731
    B = spec.batch
    digit_ids, digit_vals = engine.digit_table
    args = (engine.params, i32(B, spec.bucket), i32(B, spec.bucket),
            i32(B, spec.sfx_a), i32(B, spec.sfx_a),
            i32(B, spec.sfx_b), i32(B, spec.sfx_b),
            i32(B), i32(B), i32(len(digit_ids)), f32(len(digit_vals)))
    V = engine.cfg.vocab_size
    kwargs = dict(
        stop_mask_a=(i32(V) if spec.stops_armed else None),
        stop_mask_b=(i32(V) if spec.stops_armed else None),
        eos_id=(i32() if spec.stops_armed else None),
    )
    statics = dict(max_new_a=spec.new_tokens, max_new_b=spec.conf_tokens,
                   topk=TOPK, prefill_fn=engine._prefill_fn,
                   return_cache=True, decode_trunk=spec.decode_trunk)
    if spec.spec_k:
        args = args + _spec_avals(engine, spec)
        dk, ds = _spec_draft_kwargs(engine, spec)
        kwargs.update(dk)
        statics.update(_spec_statics(engine, spec), **ds)
    return args, kwargs, statics


def _avals_grouped(engine, spec: ShapeSpec):
    import jax
    import jax.numpy as jnp

    i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)  # noqa: E731
    f32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.float32)  # noqa: E731
    G, M = spec.groups, spec.batch
    digit_ids, digit_vals = engine.digit_table
    args = (engine.params, i32(G, spec.bucket), i32(G, spec.bucket),
            i32(M, spec.sfx_a), i32(M, spec.sfx_a), i32(M),
            i32(M), i32(M), i32(len(digit_ids)), f32(len(digit_vals)))
    V = engine.cfg.vocab_size
    armed = spec.stops_armed
    kwargs = dict(
        stop_mask=(i32(V) if armed else None),
        stop_mask2=(i32(V) if armed else None),
        stop_sel=(jax.ShapeDtypeStruct((M,), jnp.bool_) if armed else None),
        eos_id=(i32() if armed else None),
    )
    statics = dict(max_new=spec.new_tokens, topk=TOPK,
                   prefill_fn=engine._prefill_fn, return_cache=True)
    return args, kwargs, statics


def _pool_avals(engine):
    """ShapeDtypeStruct tree of the engine's page-pool leaves (the paged
    executables bind the pool as an ordinary pytree argument)."""
    import jax

    pool = engine.prefix_cache.pool
    return jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype),
        pool.leaves)


def _avals_shared_paged(engine, spec: ShapeSpec):
    """Avals for runner.decode_fused_shared's PAGED call into
    generate.greedy_decode_fused_shared_paged (prefix-cache resume):
    (params, pool, slot_src, win_start, prefix_mask, rem, rem_mask,
    sfx..x4, yes, no, digit_ids, digit_vals)."""
    import jax
    import jax.numpy as jnp

    i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)  # noqa: E731
    f32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.float32)  # noqa: E731
    B, W = spec.batch, spec.window
    digit_ids, digit_vals = engine.digit_table
    args = (engine.params, _pool_avals(engine),
            i32(B, spec.bucket), i32(), i32(B, spec.bucket),
            i32(B, W), i32(B, W),
            i32(B, spec.sfx_a), i32(B, spec.sfx_a),
            i32(B, spec.sfx_b), i32(B, spec.sfx_b),
            i32(B), i32(B), i32(len(digit_ids)), f32(len(digit_vals)))
    V = engine.cfg.vocab_size
    kwargs = dict(
        stop_mask_a=(i32(V) if spec.stops_armed else None),
        stop_mask_b=(i32(V) if spec.stops_armed else None),
        eos_id=(i32() if spec.stops_armed else None),
    )
    statics = dict(max_new_a=spec.new_tokens, max_new_b=spec.conf_tokens,
                   topk=TOPK, return_cache=True,
                   decode_trunk=spec.decode_trunk)
    if spec.spec_k:
        args = args + _spec_avals(engine, spec)
        statics.update(_spec_statics(engine, spec))
    return args, kwargs, statics


def _avals_shared_cascade(engine, spec: ShapeSpec):
    """Avals for runner.decode_fused_shared's cascade call into
    generate.greedy_decode_fused_shared_cascade: the dense shared
    layout with the trunk extent baked static (``spec.trunk``)."""
    import jax
    import jax.numpy as jnp

    i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)  # noqa: E731
    f32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.float32)  # noqa: E731
    B = spec.batch
    digit_ids, digit_vals = engine.digit_table
    args = (engine.params, i32(B, spec.bucket), i32(B, spec.bucket),
            i32(B, spec.sfx_a), i32(B, spec.sfx_a),
            i32(B, spec.sfx_b), i32(B, spec.sfx_b),
            i32(B), i32(B), i32(len(digit_ids)), f32(len(digit_vals)))
    V = engine.cfg.vocab_size
    kwargs = dict(
        stop_mask_a=(i32(V) if spec.stops_armed else None),
        stop_mask_b=(i32(V) if spec.stops_armed else None),
        eos_id=(i32() if spec.stops_armed else None),
    )
    statics = dict(max_new_a=spec.new_tokens, max_new_b=spec.conf_tokens,
                   trunk_len=spec.trunk, topk=TOPK,
                   int8_qk=spec.cascade_int8, return_cache=True)
    return args, kwargs, statics


def _avals_shared_cascade_paged(engine, spec: ShapeSpec):
    """Avals for the warm-trunk cascade call into
    generate.greedy_decode_fused_shared_cascade_paged: a batch-1 paged
    front (slot table + recompute window over the TRUNK extent) ahead
    of the dense shared layout."""
    import jax
    import jax.numpy as jnp

    i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)  # noqa: E731
    f32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.float32)  # noqa: E731
    B, W, Tt = spec.batch, spec.window, spec.trunk
    digit_ids, digit_vals = engine.digit_table
    args = (engine.params, _pool_avals(engine),
            i32(1, Tt), i32(), i32(1, Tt),
            i32(1, W), i32(1, W),
            i32(B, spec.bucket), i32(B, spec.bucket),
            i32(B, spec.sfx_a), i32(B, spec.sfx_a),
            i32(B, spec.sfx_b), i32(B, spec.sfx_b),
            i32(B), i32(B), i32(len(digit_ids)), f32(len(digit_vals)))
    V = engine.cfg.vocab_size
    kwargs = dict(
        stop_mask_a=(i32(V) if spec.stops_armed else None),
        stop_mask_b=(i32(V) if spec.stops_armed else None),
        eos_id=(i32() if spec.stops_armed else None),
    )
    statics = dict(max_new_a=spec.new_tokens, max_new_b=spec.conf_tokens,
                   trunk_len=spec.trunk, topk=TOPK,
                   int8_qk=spec.cascade_int8, return_cache=True)
    return args, kwargs, statics


def _avals_grouped_paged(engine, spec: ShapeSpec):
    import jax
    import jax.numpy as jnp

    i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)  # noqa: E731
    f32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.float32)  # noqa: E731
    G, M, W = spec.groups, spec.batch, spec.window
    digit_ids, digit_vals = engine.digit_table
    args = (engine.params, _pool_avals(engine),
            i32(G, spec.bucket), i32(), i32(G, spec.bucket),
            i32(G, W), i32(G, W),
            i32(M, spec.sfx_a), i32(M, spec.sfx_a), i32(M),
            i32(M), i32(M), i32(len(digit_ids)), f32(len(digit_vals)))
    V = engine.cfg.vocab_size
    armed = spec.stops_armed
    kwargs = dict(
        stop_mask=(i32(V) if armed else None),
        stop_mask2=(i32(V) if armed else None),
        stop_sel=(jax.ShapeDtypeStruct((M,), jnp.bool_) if armed else None),
        eos_id=(i32() if armed else None),
    )
    statics = dict(max_new=spec.new_tokens, topk=TOPK, return_cache=True)
    return args, kwargs, statics


def _avals_piggy(engine, spec: ShapeSpec):
    """Avals for the three piggyback-chain entry points. The step and
    drain bind the CARRY aval — recovered from the opener via eval_shape
    (tracing only, no device work)."""
    import jax
    import jax.numpy as jnp

    from . import generate

    i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)  # noqa: E731
    f32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.float32)  # noqa: E731
    B = spec.batch
    dispatch_args = (i32(B, spec.bucket), i32(B, spec.bucket),
                     i32(B, spec.sfx_a), i32(B, spec.sfx_a),
                     i32(B, spec.sfx_b), i32(B, spec.sfx_b))
    budgets = dict(max_new_a=spec.new_tokens, max_new_b=spec.conf_tokens)
    if spec.kind == "piggy_prefill":
        return dispatch_args, {}, dict(**budgets, prefill_fn=None)
    carry = generate.shared_piggyback_prefill.eval_shape(
        engine.params, engine.cfg, *dispatch_args, **budgets,
        prefill_fn=None)
    digit_ids, digit_vals = engine.digit_table
    readout = (i32(B), i32(B), i32(len(digit_ids)), f32(len(digit_vals)))
    V = engine.cfg.vocab_size
    kwargs = dict(
        stop_mask_a=(i32(V) if spec.stops_armed else None),
        stop_mask_b=(i32(V) if spec.stops_armed else None),
        eos_id=(i32() if spec.stops_armed else None),
    )
    if spec.kind == "piggy_step":
        return ((carry,) + dispatch_args + readout, kwargs,
                dict(**budgets, topk=TOPK, prefill_fn=None))
    # piggy_drain: carry + readout args, slot offsets derived from the
    # spec exactly as the runner derives them.
    statics = dict(slot0_a=spec.bucket + spec.sfx_a,
                   slot0_b=(spec.bucket + spec.sfx_a + spec.new_tokens
                            + spec.sfx_b),
                   **budgets, topk=TOPK)
    return (carry,) + readout, kwargs, statics


def _lower_compile(engine, spec: ShapeSpec):
    """Lower + compile one spec; returns the jax Compiled executable.

    The donated variant needs the KV-cache aval, which is exactly the
    scratchless variant's returned cache — recovered via eval_shape
    (tracing only, no device work)."""
    from . import generate

    if spec.kind == "stream_fold":
        from . import stream_stats

        return stream_stats.lower_fold(
            spec.bucket, spec.groups, spec.batch, TOPK,
            spec.stops_armed).compile()
    if spec.kind.startswith("piggy"):
        fn = {"piggy_prefill": generate.shared_piggyback_prefill,
              "piggy_step": generate.shared_piggyback_step,
              "piggy_drain": generate.shared_piggyback_drain}[spec.kind]
        args, kwargs, statics = _avals_piggy(engine, spec)
        return fn.lower(engine.params, engine.cfg, *args, **kwargs,
                        **statics).compile()
    if spec.kind == "shared":
        fn = (generate.greedy_decode_fused_shared_spec if spec.spec_k
              else generate.greedy_decode_fused_shared)
        args, kwargs, statics = _avals_shared(engine, spec)
    elif spec.kind == "shared_cascade":
        fn = generate.greedy_decode_fused_shared_cascade
        args, kwargs, statics = _avals_shared_cascade(engine, spec)
    elif spec.kind == "shared_cascade_paged":
        fn = generate.greedy_decode_fused_shared_cascade_paged
        args, kwargs, statics = _avals_shared_cascade_paged(engine, spec)
    elif spec.kind == "shared_paged":
        fn = (generate.greedy_decode_fused_shared_paged_spec
              if spec.spec_k else generate.greedy_decode_fused_shared_paged)
        args, kwargs, statics = _avals_shared_paged(engine, spec)
    elif spec.kind == "grouped_paged":
        fn = generate.greedy_decode_fused_grouped_paged
        args, kwargs, statics = _avals_grouped_paged(engine, spec)
    else:
        fn = generate.greedy_decode_fused_grouped
        args, kwargs, statics = _avals_grouped(engine, spec)
    scratch = None
    if spec.scratch:
        out_shape = fn.eval_shape(args[0], engine.cfg, *args[1:],
                                  scratch_cache=None, **kwargs, **statics)
        scratch = out_shape[-1]  # the returned final cache's aval tree
    lowered = fn.lower(args[0], engine.cfg, *args[1:],
                       scratch_cache=scratch, **kwargs, **statics)
    return lowered.compile()


# Process-wide executable cache: the AOT analogue of jit's in-memory
# executable cache. `.lower().compile()` bypasses the pjit cache, so
# without this every sweep (bench warmup -> timed, back-to-back grids on
# one engine, repeated tests) would re-pay its AOT compiles; with it, a
# (manifest key, spec) pair compiles at most once per process. Safe by
# keying: the manifest key covers model config, runtime knobs, quant
# mode, mesh, ladder AND a params-aval fingerprint (runner), and the
# compiled program binds only shapes/dtypes — params values are runtime
# arguments, so engines sharing a key may share executables.
_EXEC_CACHE: Dict[Tuple[str, ShapeSpec], Any] = {}
_EXEC_CACHE_LOCK = threading.Lock()


def exec_cache_clear() -> None:
    """Drop the process-wide executable cache (tests; pairs with
    jax.clear_caches() when simulating a cold restart in-process)."""
    with _EXEC_CACHE_LOCK:
        _EXEC_CACHE.clear()


class ExecutableRegistry:
    """Futures of compiled executables, keyed by ShapeSpec under one
    engine manifest key.

    ``get`` blocks only when the wanted shape is still compiling (the
    pool works specs in dispatch order, so in the steady state the
    executable is ready before its first dispatch); a missing or failed
    spec returns None and the caller falls back to the lazily-jitted
    path. Thread-safe: the sweep's dispatch thread reads while pool
    threads write results."""

    def __init__(self, manifest_key: str,
                 stats: Optional[CompileStats] = None,
                 compile_timeout_s: Optional[float] = None,
                 guard_stats=None):
        self.manifest_key = manifest_key
        self.stats = stats if stats is not None else CompileStats()
        # Watchdog bound on how long a dispatch may wait for a still-
        # compiling executable (guard layer): a wedged compile thread
        # then costs one lazy-jit fallback, not the sweep. None = wait
        # unbounded (legacy).
        self.compile_timeout_s = compile_timeout_s
        self.guard_stats = guard_stats
        self._futures: Dict[ShapeSpec, "Future"] = {}
        self._lock = threading.Lock()
        self._warned = False

    def __len__(self) -> int:
        return len(self._futures)

    def submit(self, spec: ShapeSpec, engine, executor) -> None:
        with self._lock:
            if spec in self._futures:
                return
            cache_key = (self.manifest_key, spec)
            with _EXEC_CACHE_LOCK:
                cached = _EXEC_CACHE.get(cache_key)
            if cached is not None:
                fut: "Future" = Future()
                fut.set_result(cached)
                self._futures[spec] = fut
                return

            def task():
                t0 = time.perf_counter()
                compiled = _lower_compile(engine, spec)
                self.stats.record_shape(spec.label,
                                        time.perf_counter() - t0)
                with _EXEC_CACHE_LOCK:
                    _EXEC_CACHE[cache_key] = compiled
                return compiled

            self._futures[spec] = executor.submit(task)

    def get(self, spec: ShapeSpec):
        with self._lock:
            fut = self._futures.get(spec)
        if fut is None:
            self.stats.lazy_misses += 1
            return None
        try:
            compiled = fut.result(timeout=self.compile_timeout_s)
        except FuturesTimeout:
            # Stalled compile: abandon the wait (the pool thread keeps
            # the future; a late success still lands in _EXEC_CACHE for
            # the next sweep) and dispatch lazily.
            if self.guard_stats is not None:
                self.guard_stats.site("stalls", "compile")
            log.warning("AOT compile for %s exceeded its %.1fs watchdog "
                        "deadline; falling back to lazy jit for this "
                        "dispatch", spec.label, self.compile_timeout_s)
            self.stats.lazy_misses += 1
            return None
        except Exception as err:  # noqa: BLE001 — fall back to lazy jit
            if not self._warned:
                self._warned = True
                log.warning("AOT compile failed for %s (%r); falling back "
                            "to lazy jit for unserved shapes", spec.label,
                            err)
            self.stats.lazy_misses += 1
            return None
        self.stats.aot_hits += 1
        return compiled

    def wait(self) -> int:
        """Block until every submitted compile finishes; returns the count
        of successful executables (the precompile CLI's synchronous exit)."""
        ok = 0
        with self._lock:
            futures = list(self._futures.items())
        for spec, fut in futures:
            try:
                fut.result()
                ok += 1
            except Exception as err:  # noqa: BLE001
                log.warning("precompile failed for %s: %r", spec.label, err)
        return ok


def precompile_async(engine, specs: Sequence[ShapeSpec],
                     max_workers: int = 0) -> ExecutableRegistry:
    """Kick off background compilation of every spec (dispatch order) and
    return the registry immediately — the sweep's first dispatches stream
    while later buckets' executables compile concurrently. The pool's
    threads outlive this call; registry futures own the results."""
    stats = getattr(engine, "compile_stats", None) or CompileStats()
    rt = getattr(engine, "rt", None)
    timeout = None
    if rt is not None and getattr(rt, "watchdog_multiple", 0) > 0:
        # The compile deadline mirrors the dispatch watchdog's shape:
        # floor * multiple — generous enough for a real 7B executable,
        # bounded enough that a wedged compiler thread costs one lazy
        # fallback instead of parking the dispatch loop forever.
        timeout = rt.watchdog_floor_s * max(rt.watchdog_multiple, 1.0)
    registry = ExecutableRegistry(engine.cache_manifest_key, stats,
                                  compile_timeout_s=timeout,
                                  guard_stats=getattr(engine,
                                                      "guard_stats", None))
    if not specs:
        return registry
    from ..utils import compile_cache

    compile_cache.write_manifest(engine.cache_manifest_key, {
        "model": engine.cfg, "runtime": engine.rt,
        "buckets": engine.buckets,
        "quant": compile_cache.quant_mode(engine.params),
        "shapes": [s.label for s in specs]})
    import os

    workers = max_workers or min(len(specs), max(2, (os.cpu_count() or 4)))
    executor = ThreadPoolExecutor(max_workers=workers,
                                  thread_name_prefix="compile-plan")
    for spec in specs:
        registry.submit(spec, engine, executor)
    executor.shutdown(wait=False)
    return registry


def registry_call(compiled, args: Tuple, kwargs: Dict[str, Any],
                  scratch_cache):
    """Invoke a registry executable with the canonical argument layout.

    AOT-compiled functions take only the DYNAMIC arguments (static
    cfg/budgets/flags were baked in at lower time), with the same
    positional/keyword split the lowering used — args positional minus
    cfg, stop args + scratch_cache by keyword."""
    return compiled(*args, scratch_cache=scratch_cache, **kwargs)


def sweep_specs_for_ladder(engine, sfx_buckets: Sequence[int] = (8, 16),
                           batches: Optional[Sequence[int]] = None,
                           ) -> List[ShapeSpec]:
    """The warm-ahead-of-serving spec set (`lir_tpu precompile` and the
    serving layer's boot precompile): for every bucket-ladder edge x
    candidate suffix edge x batch size, both handoff variants of the
    shared-prefix executable at the engine's sweep budgets.

    ``batches`` defaults to the engine's configured batch alone (the
    offline sweep dispatches full batches except one tail); the online
    server additionally warms the power-of-two TAIL batches
    (serve_batches) because continuous batching dispatches partial
    batches whenever the queue runs shallow. Grouped-dispatch shapes
    depend on the realized prefix groups, so those still compile lazily
    (into the persistent cache) the first time a grid forms them."""
    rt = engine.rt
    new_tokens = (rt.max_new_tokens if rt.sweep_full_completions
                  else min(rt.sweep_decode_tokens, rt.max_new_tokens))
    conf_tokens = (rt.max_new_tokens if rt.sweep_full_completions
                   else min(rt.sweep_confidence_tokens, rt.max_new_tokens))
    stops_armed = (rt.sweep_early_stop and not rt.sweep_full_completions
                   and engine.digit_stop_mask is not None)
    windows = ()
    if getattr(engine, "prefix_cache", None) is not None:
        from ..models import paged as paged_mod

        windows = lambda b: paged_mod.window_edges(  # noqa: E731
            b, engine.prefix_cache.page_size)
    sk = 0
    sdraft = False
    if getattr(engine, "spec_supported", lambda: False)():
        sk = rt.spec_k
        sdraft = getattr(engine, "_spec_draft", None) is not None
    specs = []
    for bucket in engine.buckets:
        for sfx in sfx_buckets:
            for batch in (batches if batches is not None
                          else (rt.batch_size,)):
                for scratch in (False, True):
                    specs.append(shared_spec(
                        bucket, batch, sfx, sfx, new_tokens,
                        conf_tokens, stops_armed, scratch))
                    if sk:
                        specs.append(shared_spec(
                            bucket, batch, sfx, sfx, new_tokens,
                            conf_tokens, stops_armed, scratch,
                            spec_k=sk, spec_draft=sdraft))
                    if windows:
                        # Block-table variants: one per remainder-window
                        # edge, so a warm serve dispatch resuming from
                        # the radix cache never pays a trace either.
                        for w in windows(bucket):
                            specs.append(shared_paged_spec(
                                bucket, batch, w, sfx, sfx, new_tokens,
                                conf_tokens, stops_armed, scratch))
                            if sk and not sdraft:
                                specs.append(shared_paged_spec(
                                    bucket, batch, w, sfx, sfx,
                                    new_tokens, conf_tokens, stops_armed,
                                    scratch, spec_k=sk))
    return specs


def serve_batches(batch_size: int) -> Tuple[int, ...]:
    """Every padded batch shape the continuous batcher can dispatch at a
    configured batch size: the full batch plus each power-of-two tail
    below it (runner._tail_batch pads partial batches onto this grid)."""
    out = []
    b = 1
    while b < batch_size:
        out.append(b)
        b *= 2
    out.append(batch_size)
    return tuple(out)
