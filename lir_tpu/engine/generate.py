"""Greedy decoding with per-step logit capture.

The reference's measurement path is ``model.generate(max_new_tokens=50,
output_scores=True, return_dict_in_generate=True)`` followed by a scan of the
first 10 score tensors (compare_base_vs_instruct.py:251-278). Here that is one
jitted program: prefill the KV cache, then ``lax.scan`` 50 greedy steps,
stacking each step's fp32 logits. Fixed shapes throughout — the grid engine
batches ragged prompts by left-padding (decoder.mask_positions makes padding
a no-op).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..models import decoder
from ..models.registry import ModelConfig, T5Config
from ..models import encdec
from . import tokens as _tok


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class FusedDecodeOut:
    """Per-step readout captured inside the decode scan — everything the
    sweeps consume, WITHOUT materializing the (B, T_new, V) logit stack.

    At seq 256 / vocab 32k / 10 steps the full stack is ~50 MB of HBM
    traffic per batch; this struct is ~100 floats per row. The fused path is
    the production scorer; `greedy_decode` (full capture) remains for
    debugging and parity tests.
    """

    generated: jax.Array      # (B, T_new) int32
    p_yes: jax.Array          # (B, T_new) fp32 softmax prob of the yes id
    p_no: jax.Array           # (B, T_new) fp32
    top2_ids: jax.Array       # (B, T_new, 2) int32 — the top-2 match rule
    topk_logprobs: jax.Array  # (B, K) fp32 at position 0 (D6 log-prob map)
    topk_ids: jax.Array       # (B, K) int32
    weighted_confidence: jax.Array  # (B,) fp32 E[v] over digit ids at pos 0


def is_per_row_keys(key: jax.Array) -> bool:
    """True when ``key`` is a BATCH of PRNG keys (one stream per prompt
    row), under either key flavor: typed keys (jax.random.key — a key
    batch is shape (B,), scalar key shape ()) or legacy uint32 keys (a
    batch is (B, 2), a single key (2,))."""
    try:
        if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
            return key.ndim >= 1
    except TypeError:
        pass
    return getattr(key, "ndim", 1) == 2


def _small_readout(logits: jax.Array, yes_ids: jax.Array, no_ids: jax.Array):
    """(B, V) fp32 logits -> (p_yes, p_no, top2_ids): O(B*V) compute, O(B)
    output."""
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    l_yes = jnp.take_along_axis(logits, yes_ids[:, None], axis=1)[:, 0]
    l_no = jnp.take_along_axis(logits, no_ids[:, None], axis=1)[:, 0]
    p_yes = jnp.exp(l_yes - lse)
    p_no = jnp.exp(l_no - lse)
    _, top2 = lax.top_k(logits, 2)
    return p_yes, p_no, top2.astype(jnp.int32)


def _fused_tail(params, cfg: ModelConfig, logits0: jax.Array, cache,
                cache_mask0: jax.Array, pos0: jax.Array, slot0: int,
                yes_ids: jax.Array, no_ids: jax.Array, digit_ids: jax.Array,
                digit_vals: jax.Array, max_new_tokens: int, topk: int,
                stop_mask: jax.Array = None, eos_id: jax.Array = None,
                stop_mask2: jax.Array = None, stop_sel: jax.Array = None,
                decode_trunk: int = 0,
                ) -> Tuple[FusedDecodeOut, Tuple]:
    """The fused greedy scan shared by the full-prompt and shared-prefix
    paths: start from ``logits0`` (the first generated position), write
    generated k/v at cache slots ``slot0 + t``, capture the C13/D6 readouts
    in-scan. Returns (FusedDecodeOut, final cache).

    ``decode_trunk`` (static) marks the cache's leading shared-trunk
    slots on a shared-prefix dispatch: every decode step's trunk splits
    then run trunk-aware (cascade decode — decoder.decode_step), the
    trunk K/V streaming from HBM once per step instead of once per row.
    Gated by ``cfg.cascade_decode``; 0 keeps the flat kernel exactly.

    ``stop_mask`` ((V,) int32 surface-class bitmask from
    tokens.digit_stop_classes) + ``eos_id`` enable the confidence early
    stop: a row is DONE once it emits EOS, or once a standalone digit run
    (pure digit tokens opened at a word boundary) is followed by a
    non-gluing token — at that point the decoded text provably contains a
    complete ``\\b\\d+\\b`` integer, the only thing the confidence parse
    reads. Letter-glued digits ('1'+'st') neither open nor terminate a
    run, and transparent specials (empty decode) change nothing, so the
    stop NEVER nulls an answer the full budget would have parsed. Done
    rows emit EOS from the next step (so host-side EOS trimming ends their
    text at the stop point), and once EVERY row is done the remaining scan
    steps skip the model forward via a scalar ``lax.cond`` — a generous
    token budget then costs actual-response-length decode steps, not the
    worst case. Per-step p_yes/p_no/top2 after a row's stop point reflect
    the EOS-fed model and must not be consumed (the sweep's confidence
    readout uses position 0 only).

    ``stop_mask2`` + ``stop_sel`` ((B,) bool) select a SECOND class table
    per row: rows where ``stop_sel`` is True read their emitted token's
    class from ``stop_mask2`` instead of ``stop_mask``. The prefix-group
    decode mixes both sweep formats in one batch and needs the binary
    rows on the EOS-only table while confidence rows run the digit stop.
    """
    early_stop = stop_mask is not None and eos_id is not None
    # Position-0 extras (first generated position): top-k logprob map +
    # weighted confidence.
    logp0 = logits0 - jax.scipy.special.logsumexp(
        logits0, axis=-1, keepdims=True)
    tk_vals, tk_ids = lax.top_k(logp0, topk)
    p_digits = jnp.exp(logp0[:, digit_ids])                    # (B, K)
    mass = jnp.maximum(p_digits.sum(axis=-1), 1e-10)
    wconf = (p_digits * digit_vals[None, :]).sum(axis=-1) / mass

    B = logits0.shape[0]

    def step(carry, t):
        logits, cache, cache_mask, done, digit_run, prev_ew = carry
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        p_yes, p_no, top2 = _small_readout(logits, yes_ids, no_ids)
        if early_stop:
            emit = jnp.where(done, eos_id, nxt)
            cls = stop_mask[emit]
            if stop_mask2 is not None:
                cls = jnp.where(stop_sel, stop_mask2[emit], cls)
            pure = (cls & _tok.STOP_PURE) != 0
            prefix = (cls & _tok.STOP_PREFIX) != 0
            glue = (cls & _tok.STOP_STARTS_WORD) != 0
            ends_w = (cls & _tok.STOP_ENDS_WORD) != 0
            transp = (cls & _tok.STOP_TRANSPARENT) != 0
            done = done | (emit == eos_id) | (digit_run & ~glue & ~transp)
            # A standalone digit run opens on a pure-digit token at a word
            # boundary (space prefix, or previous token ended non-word —
            # position 0 starts at a boundary: prev_ew init False), extends
            # through unprefixed pure-digit tokens, and is spoiled by
            # anything else. Transparent tokens freeze all text state.
            digit_run = jnp.where(
                transp, digit_run,
                (pure & (prefix | ~prev_ew)) | (digit_run & pure & ~prefix))
            prev_ew = jnp.where(transp, prev_ew, ends_w)

            # Defensive (ADVICE r4): the slot write happens only when the
            # step actually runs, so an early-stopped tail's final cache
            # never marks unwritten KV slots as valid. No current caller
            # reads that mask (both fused callers discard it) — this
            # removes the latent hazard for future cache reuse, nothing
            # more.
            all_done = jnp.all(done)
            step_mask = cache_mask.at[:, slot0 + t].set(1)

            def run(args):
                lg, c = args
                return decoder.decode_step(
                    params, cfg, c, emit, pos0 + t, slot0 + t, step_mask,
                    trunk_len=decode_trunk)

            new_logits, cache = lax.cond(
                all_done, lambda args: args, run, (logits, cache))
            cache_mask = jnp.where(all_done, cache_mask, step_mask)
        else:
            emit = nxt
            cache_mask = cache_mask.at[:, slot0 + t].set(1)
            new_logits, cache = decoder.decode_step(
                params, cfg, cache, emit, pos0 + t, slot0 + t, cache_mask,
                trunk_len=decode_trunk)
        return ((new_logits, cache, cache_mask, done, digit_run, prev_ew),
                (emit, p_yes, p_no, top2))

    zeros_b = jnp.zeros((B,), bool)
    (_, cache_f, _, _, _, _), (gen, p_yes, p_no, top2) = lax.scan(
        step, (logits0, cache, cache_mask0, zeros_b, zeros_b, zeros_b),
        jnp.arange(max_new_tokens))

    return FusedDecodeOut(
        generated=jnp.swapaxes(gen, 0, 1),
        p_yes=jnp.swapaxes(p_yes, 0, 1),
        p_no=jnp.swapaxes(p_no, 0, 1),
        top2_ids=jnp.swapaxes(top2, 0, 1),
        topk_logprobs=tk_vals,
        topk_ids=tk_ids,
        weighted_confidence=wconf,
    ), cache_f


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new_tokens", "topk",
                                    "prefill_fn"))
def greedy_decode_fused(params, cfg: ModelConfig, tokens: jax.Array,
                        attn_mask: jax.Array, yes_ids: jax.Array,
                        no_ids: jax.Array, digit_ids: jax.Array,
                        digit_vals: jax.Array, max_new_tokens: int = 50,
                        topk: int = 20,
                        prefill_fn=None, stop_mask: jax.Array = None,
                        eos_id: jax.Array = None) -> FusedDecodeOut:
    """Greedy decode with the C13/D6 readouts fused into the scan.

    yes_ids/no_ids: (B,) per-row target token ids (rows of one batch may
    score different prompts with different target tokens). digit_ids/vals:
    the integer-token table for the weighted-confidence readout (pass empty
    arrays to skip: the gather on an empty axis is free). stop_mask/eos_id
    enable the confidence early stop (see _fused_tail).
    """
    B, S = tokens.shape
    T = S + max_new_tokens
    pf = prefill_fn or decoder.prefill
    logits0, cache, pos0 = pf(params, cfg, tokens, attn_mask, T)
    cache_mask0 = jnp.pad(attn_mask, ((0, 0), (0, max_new_tokens)))
    out, _ = _fused_tail(params, cfg, logits0, cache, cache_mask0, pos0, S,
                         yes_ids, no_ids, digit_ids, digit_vals,
                         max_new_tokens, topk, stop_mask=stop_mask,
                         eos_id=eos_id)
    return out


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new", "topk", "prefill_fn",
                                    "return_cache"),
                   donate_argnames=("scratch_cache",))
def greedy_decode_fused_grouped(params, cfg: ModelConfig, prefix: jax.Array,
                                prefix_mask: jax.Array, sfx: jax.Array,
                                sfx_mask: jax.Array, group_idx: jax.Array,
                                yes_ids: jax.Array, no_ids: jax.Array,
                                digit_ids: jax.Array, digit_vals: jax.Array,
                                max_new: int, topk: int = 20,
                                prefill_fn=None, stop_mask: jax.Array = None,
                                stop_mask2: jax.Array = None,
                                stop_sel: jax.Array = None,
                                eos_id: jax.Array = None,
                                return_cache: bool = False,
                                scratch_cache=None):
    """M fused greedy decodes sharing G <= M prefix prefills (cross-cell
    prefix reuse).

    Generalizes :func:`greedy_decode_fused_shared` from "two formats of one
    row share that row's prefill" to "any member rows whose prompts share a
    token prefix share ONE prefill": the ragged scheduler groups grid cells
    whose tokenized prompts agree on a long prefix (all the sweep formats x
    rephrasings of one base prompt, when the rephrasings preserve the
    opening tokens), prefills each distinct prefix once as a (G, S)
    RIGHT-padded batch (the canonical slot == position layout — see
    greedy_decode_fused_shared), and ``group_idx`` (M,) maps each member
    row to its prefix. The member suffixes (M, S2) RIGHT-padded then run one chunked
    teacher-forced extension over the row-gathered cache, followed by the
    fused scan. Prefill FLOPs drop by the group fan-out M/G; the gathered
    M-row cache is the same size the ungrouped path allocates.

    ``stop_mask``/``stop_mask2``/``stop_sel`` give per-row stop tables (the
    mixed-format batch runs EOS-only stops on binary rows and the digit
    stop on confidence rows — see _fused_tail). The pairwise special case
    (G rows, 2 members each, ``group_idx = [0, 0, 1, 1, ...]``) scores
    identically to greedy_decode_fused_shared (pinned by
    tests/test_scheduler.py).

    ``return_cache=True`` additionally returns the scan's final KV cache;
    ``scratch_cache`` (DONATED) accepts the previous same-shape dispatch's
    returned cache so XLA writes this dispatch's cache into the same HBM
    block — one cache buffer then serves an entire bucket queue instead of
    an alloc/free per dispatch (see runner._CacheHandoff). Results never
    depend on the scratch contents: prefill overwrites every slot and
    attention is masked by ``cache_mask`` regardless.
    """
    del scratch_cache  # donated scratch: memory reuse only, never read
    G, S = prefix.shape
    M, S2 = sfx.shape
    T0 = S + S2 + max_new
    pf = prefill_fn or decoder.prefill
    _, gcache, _ = pf(params, cfg, prefix, prefix_mask, T0)

    from ..models import cache as cache_mod

    cache = cache_mod.gather_rows(gcache, group_idx)
    pm = jnp.take(prefix_mask, group_idx, axis=0)              # (M, S)
    cm = jnp.concatenate(
        [pm, sfx_mask, jnp.zeros((M, max_new), pm.dtype)], axis=1)
    logits_l, cache2, pos = decoder.extend(
        params, cfg, cache, sfx, sfx_mask, cm, S)
    out, cache_f = _fused_tail(params, cfg, logits_l, cache2, cm, pos, S + S2,
                               yes_ids, no_ids, digit_ids, digit_vals,
                               max_new, topk, stop_mask=stop_mask,
                               eos_id=eos_id, stop_mask2=stop_mask2,
                               stop_sel=stop_sel)
    if return_cache:
        return out, cache_f
    return out


@functools.partial(jax.jit, static_argnames=("cfg", "prefill_fn"))
def prefill_cache(params, cfg: ModelConfig, tokens: jax.Array,
                  attn_mask: jax.Array, prefill_fn=None):
    """PREFILL-ONLY pass: run the prompt, return the KV cache, decode
    nothing — the prefill-role dispatch of disaggregated serving
    (serve/migrate.py). ``tokens``/``attn_mask`` are (B, S)
    RIGHT-padded at the bucket extent, exactly the canonical
    slot == position layout the shared-prefix paths prefill with, and
    the cache is allocated at S slots: ``decoder.prefill`` computes
    every slot's k/v at the S-wide attention extent and pads the cache
    afterwards, so the page values extracted from this cache are
    BITWISE the values a full scoring dispatch of the same bucket would
    have inserted (pinned by tests/test_migrate.py) — which is what
    lets a decode replica resume from migrated pages identically to a
    colocated run."""
    pf = prefill_fn or decoder.prefill
    _, cache, _ = pf(params, cfg, tokens, attn_mask, tokens.shape[1])
    return cache


def _paged_prefix(params, cfg: ModelConfig, pool, slot_src: jax.Array,
                  win_start: jax.Array, prefix_mask: jax.Array,
                  rem: jax.Array, rem_mask: jax.Array, total_len: int):
    """The paged replacement for the shared-prefill step, EXACT-LAYOUT:
    assemble the cached prefix KV from the page pool (models/paged.
    gather_slots over ``slot_src`` (B, S)) and teacher-force the
    recompute WINDOW — slots [w0, w0 + R), each row's prefix tokens in
    that range RIGHT-padded into ``rem``/``rem_mask`` (B, R) — via one
    chunked extension over the S-slot cache view (decoder.extend at
    start_index = ``win_start``, a TRACED scalar: the window is anchored
    at the dispatch's longest real row, not the bucket edge, so rows
    shorter than the bucket never pay recompute FLOPs for pad slots —
    and the anchor varies per dispatch without retracing). A dispatch
    then pays prefill FLOPs for R tokens per row instead of the whole
    bucket.

    The layout discipline is what buys bitwise parity with the unpaged
    path (pinned by tests/test_prefix_cache.py):

    - the shared-prefix paths RIGHT-pad their prefixes (slot == token
      position, runner.decode_fused_shared), so a token's slot — and
      hence the reduction layout that computes its KV — is independent
      of its row's length: pages produced under any row back any later
      row sharing the prefix bitwise;
    - the window extension runs over an S-slot cache view — the exact
      attention extent the prefill's quadratic pass reduces over — and
      only afterwards is the cache padded out to ``total_len`` with
      zeros, exactly as prefill pads;
    - unfilled slots (a short row's tail, slots a cold row has no pages
      for) read the trash page's exact zeros; the unpaged prefill holds
      garbage pad-token k/v there instead, but both contribute exact
      0.0 through the masked softmax, so the difference is invisible.

    ``prefix_mask`` is the standard right-pad mask (B, S) — the SAME
    tensor the unpaged path computes. Returns the cache with
    [0, total_len) allocated and [0, S) populated — the drop-in analogue
    of ``prefill``'s cache output.
    """
    from ..models import paged as paged_mod

    S = prefix_mask.shape[1]
    cache = paged_mod.gather_slots(pool, slot_src)          # S-slot view
    _, cache, _ = decoder.extend(params, cfg, cache, rem, rem_mask,
                                 prefix_mask, win_start)

    def pad_leaf(a):
        pad = [(0, 0)] * a.ndim
        pad[2] = (0, total_len - S)                         # time axis
        return jnp.pad(a, pad)

    return jax.tree.map(pad_leaf, cache)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new_a", "max_new_b", "topk",
                                    "return_cache", "decode_trunk"),
                   donate_argnames=("scratch_cache",))
def greedy_decode_fused_shared_paged(params, cfg: ModelConfig, pool,
                                     slot_src: jax.Array,
                                     win_start: jax.Array,
                                     prefix_mask: jax.Array, rem: jax.Array,
                                     rem_mask: jax.Array, sfx_a: jax.Array,
                                     sfx_a_mask: jax.Array, sfx_b: jax.Array,
                                     sfx_b_mask: jax.Array,
                                     yes_ids: jax.Array, no_ids: jax.Array,
                                     digit_ids: jax.Array,
                                     digit_vals: jax.Array, max_new_a: int,
                                     max_new_b: int, topk: int = 20,
                                     stop_mask_b: jax.Array = None,
                                     stop_mask_a: jax.Array = None,
                                     eos_id: jax.Array = None,
                                     return_cache: bool = False,
                                     decode_trunk: int = 0,
                                     scratch_cache=None):
    """:func:`greedy_decode_fused_shared` resuming from the cross-request
    radix prefix cache: the quadratic prefill over each row's shared
    binary/confidence prefix is replaced by a page-pool slot gather plus
    one chunked extension over the per-row remainder window
    (:func:`_paged_prefix`); the two format-suffix branches and the
    fused scans are the unpaged path's own code at the unpaged path's
    own shapes, which is what makes paged results BITWISE-identical to
    the contiguous-cache path per request (pinned by
    tests/test_prefix_cache.py). ``return_cache`` also returns the final
    cache — callers feed it back into the pool (page insertion) and the
    donation chain (its shape equals the unpaged path's, so cold and
    warm dispatches share one donated buffer)."""
    del scratch_cache  # donated scratch: memory reuse only, never read
    B, S = prefix_mask.shape
    S2a, S2b = sfx_a.shape[1], sfx_b.shape[1]
    T0 = S + max(S2a + max_new_a, S2b + max_new_b)
    cache = _paged_prefix(params, cfg, pool, slot_src, win_start,
                          prefix_mask, rem, rem_mask, T0)

    empty_ids = jnp.zeros((0,), jnp.int32)
    empty_vals = jnp.zeros((0,), jnp.float32)

    def branch(cache_in, sfx, sfx_mask, new_tokens, d_ids, d_vals,
               stop_mask=None):
        S2 = sfx.shape[1]
        cm = jnp.concatenate(
            [prefix_mask, sfx_mask,
             jnp.zeros((B, T0 - S - S2), prefix_mask.dtype)], axis=1)
        logits_l, cache2, pos = decoder.extend(
            params, cfg, cache_in, sfx, sfx_mask, cm, S)
        return _fused_tail(params, cfg, logits_l, cache2, cm, pos, S + S2,
                           yes_ids, no_ids, d_ids, d_vals, new_tokens, topk,
                           stop_mask=stop_mask, eos_id=eos_id,
                           decode_trunk=decode_trunk)

    out_a, cache_a = branch(cache, sfx_a, sfx_a_mask, max_new_a,
                            empty_ids, empty_vals, stop_mask=stop_mask_a)
    out_b, cache_b = branch(cache_a, sfx_b, sfx_b_mask, max_new_b,
                            digit_ids, digit_vals, stop_mask=stop_mask_b)
    if return_cache:
        return out_a, out_b, cache_b
    return out_a, out_b


def _cascade_branches(params, cfg: ModelConfig, tcache, trunk_len: int,
                      prefix, prefix_mask, sfx_a, sfx_a_mask, sfx_b,
                      sfx_b_mask, yes_ids, no_ids, digit_ids, digit_vals,
                      max_new_a: int, max_new_b: int, topk: int,
                      int8_qk: bool, stop_mask_b, stop_mask_a, eos_id,
                      return_cache: bool):
    """Shared tail of the cold/paged cascade variants: cascade-extend the
    per-row remainders over the (L, K, trunk_len, 1, hd) trunk cache,
    then run the two format branches as the dense shared path's OWN code
    at its own shapes — which is what makes the cascade argmax-identical
    to :func:`greedy_decode_fused_shared` (the PR-7 parity bar, pinned
    by tests/test_cascade.py) and lets cold/warm cascade dispatches share
    the dense path's donated cache buffer (same cache aval)."""
    B, S = prefix.shape
    S2a, S2b = sfx_a.shape[1], sfx_b.shape[1]
    T0 = S + max(S2a + max_new_a, S2b + max_new_b)
    # Static trunk split: slots [0, trunk_len) are the shared trunk
    # (right-padded canonical layout — slot == position), the remainder
    # is everything after, per row.
    rem = prefix[:, trunk_len:]
    rem_mask = prefix_mask[:, trunk_len:]
    cache = decoder.cascade_extend(params, cfg, tcache, rem, rem_mask,
                                   trunk_len, T0, int8_qk=int8_qk)

    empty_ids = jnp.zeros((0,), jnp.int32)
    empty_vals = jnp.zeros((0,), jnp.float32)

    def branch(cache_in, sfx, sfx_mask, new_tokens, d_ids, d_vals,
               stop_mask=None):
        S2 = sfx.shape[1]
        cm = jnp.concatenate(
            [prefix_mask, sfx_mask,
             jnp.zeros((B, T0 - S - S2), prefix_mask.dtype)], axis=1)
        logits_l, cache2, pos = decoder.extend(
            params, cfg, cache_in, sfx, sfx_mask, cm, S)
        return _fused_tail(params, cfg, logits_l, cache2, cm, pos, S + S2,
                           yes_ids, no_ids, d_ids, d_vals, new_tokens, topk,
                           stop_mask=stop_mask, eos_id=eos_id,
                           decode_trunk=trunk_len)

    out_a, cache_a = branch(cache, sfx_a, sfx_a_mask, max_new_a,
                            empty_ids, empty_vals, stop_mask=stop_mask_a)
    out_b, cache_b = branch(cache_a, sfx_b, sfx_b_mask, max_new_b,
                            digit_ids, digit_vals, stop_mask=stop_mask_b)
    if return_cache:
        return out_a, out_b, cache_b
    return out_a, out_b


@functools.partial(jax.jit,
                   static_argnames=("cfg", "trunk_len", "max_new_a",
                                    "max_new_b", "topk", "int8_qk",
                                    "return_cache"),
                   donate_argnames=("scratch_cache",))
def greedy_decode_fused_shared_cascade(params, cfg: ModelConfig,
                                       prefix: jax.Array,
                                       prefix_mask: jax.Array,
                                       sfx_a: jax.Array, sfx_a_mask: jax.Array,
                                       sfx_b: jax.Array, sfx_b_mask: jax.Array,
                                       yes_ids: jax.Array, no_ids: jax.Array,
                                       digit_ids: jax.Array,
                                       digit_vals: jax.Array,
                                       max_new_a: int, max_new_b: int,
                                       trunk_len: int, topk: int = 20,
                                       int8_qk: bool = False,
                                       stop_mask_b: jax.Array = None,
                                       stop_mask_a: jax.Array = None,
                                       eos_id: jax.Array = None,
                                       return_cache: bool = False,
                                       scratch_cache=None):
    """:func:`greedy_decode_fused_shared` with the SHARED-TRUNK prefill
    decomposed (ROADMAP item 1 / ops/cascade_prefill): every row of the
    dispatch shares its first ``trunk_len`` tokens verbatim (the engine's
    LCP gate, runner.decode_fused_shared), so the quadratic trunk prefill
    runs ONCE at batch 1 instead of once per row, the per-row remainders
    extend over it via cascade attention (prefix leg = one dense GEMM per
    kv head against the shared trunk KV, suffix leg = causal window,
    log-sum-exp merge), and the two format branches are the dense path's
    own code. The dense path recomputes B x trunk_len^2 trunk attention;
    this pays 1 x — the whole point of the cascade."""
    del scratch_cache  # donated scratch: memory reuse only, never read
    # Trunk prefill at batch 1, EXACT trunk extent: row 0's first
    # trunk_len tokens are byte-identical to every other row's (LCP), all
    # real (trunk <= every row's real length), so mask is all-ones and
    # slot t is position t — the layout cascade_extend assumes and the
    # same layout the radix page pool stores, which is what makes the
    # paged-warm trunk bitwise-identical to this cold one.
    ones = jnp.ones((1, trunk_len), prefix_mask.dtype)
    _, tcache, _ = decoder.prefill(params, cfg, prefix[:1, :trunk_len],
                                   ones, trunk_len)
    return _cascade_branches(params, cfg, tcache, trunk_len, prefix,
                             prefix_mask, sfx_a, sfx_a_mask, sfx_b,
                             sfx_b_mask, yes_ids, no_ids, digit_ids,
                             digit_vals, max_new_a, max_new_b, topk, int8_qk,
                             stop_mask_b, stop_mask_a, eos_id, return_cache)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "trunk_len", "max_new_a",
                                    "max_new_b", "topk", "int8_qk",
                                    "return_cache"),
                   donate_argnames=("scratch_cache",))
def greedy_decode_fused_shared_cascade_paged(params, cfg: ModelConfig, pool,
                                             slot_src: jax.Array,
                                             win_start: jax.Array,
                                             trunk_mask: jax.Array,
                                             trunk_rem: jax.Array,
                                             trunk_rem_mask: jax.Array,
                                             prefix: jax.Array,
                                             prefix_mask: jax.Array,
                                             sfx_a: jax.Array,
                                             sfx_a_mask: jax.Array,
                                             sfx_b: jax.Array,
                                             sfx_b_mask: jax.Array,
                                             yes_ids: jax.Array,
                                             no_ids: jax.Array,
                                             digit_ids: jax.Array,
                                             digit_vals: jax.Array,
                                             max_new_a: int, max_new_b: int,
                                             trunk_len: int, topk: int = 20,
                                             int8_qk: bool = False,
                                             stop_mask_b: jax.Array = None,
                                             stop_mask_a: jax.Array = None,
                                             eos_id: jax.Array = None,
                                             return_cache: bool = False,
                                             scratch_cache=None):
    """:func:`greedy_decode_fused_shared_cascade` with the TRUNK resumed
    from the cross-request radix prefix cache: the batch-1 trunk prefill
    becomes a page-pool slot gather plus one recompute-window extension
    (:func:`_paged_prefix` at one row, ``total_len == trunk_len`` so no
    tail pad) — a warm trunk costs ZERO quadratic recompute, the cascade's
    headline win. The paged trunk cache is BITWISE the cold trunk prefill
    (same exact-layout discipline tests/test_prefix_cache.py pins for the
    shared path), so everything from cascade_extend on — and therefore
    every output — is bitwise the cold cascade's."""
    del scratch_cache  # donated scratch: memory reuse only, never read
    tcache = _paged_prefix(params, cfg, pool, slot_src, win_start,
                           trunk_mask, trunk_rem, trunk_rem_mask, trunk_len)
    return _cascade_branches(params, cfg, tcache, trunk_len, prefix,
                             prefix_mask, sfx_a, sfx_a_mask, sfx_b,
                             sfx_b_mask, yes_ids, no_ids, digit_ids,
                             digit_vals, max_new_a, max_new_b, topk, int8_qk,
                             stop_mask_b, stop_mask_a, eos_id, return_cache)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new", "topk", "return_cache"),
                   donate_argnames=("scratch_cache",))
def greedy_decode_fused_grouped_paged(params, cfg: ModelConfig, pool,
                                      slot_src: jax.Array,
                                      win_start: jax.Array,
                                      prefix_mask: jax.Array,
                                      rem: jax.Array, rem_mask: jax.Array,
                                      sfx: jax.Array, sfx_mask: jax.Array,
                                      group_idx: jax.Array,
                                      yes_ids: jax.Array, no_ids: jax.Array,
                                      digit_ids: jax.Array,
                                      digit_vals: jax.Array, max_new: int,
                                      topk: int = 20,
                                      stop_mask: jax.Array = None,
                                      stop_mask2: jax.Array = None,
                                      stop_sel: jax.Array = None,
                                      eos_id: jax.Array = None,
                                      return_cache: bool = False,
                                      scratch_cache=None):
    """:func:`greedy_decode_fused_grouped` resuming group prefixes from
    the radix prefix cache: the (G, S) group prefill becomes a page-pool
    slot gather plus one remainder-window extension
    (:func:`_paged_prefix` at G rows, same exact-layout discipline as
    the shared variant), then the member-row gather
    (models/cache.gather_rows), suffix extension, and fused scan run as
    the unpaged grouped path's own code at its own shapes. A sweep whose
    prefix groups recur across dispatches (one base prompt's rephrasings
    split across bucket queues, or a re-run grid on a warm engine) then
    prefills each group prefix ONCE, not once per dispatch."""
    del scratch_cache  # donated scratch: memory reuse only, never read
    G, S = prefix_mask.shape
    M, S2 = sfx.shape
    T0 = S + S2 + max_new
    gcache = _paged_prefix(params, cfg, pool, slot_src, win_start,
                           prefix_mask, rem, rem_mask, T0)

    from ..models import cache as cache_mod

    cache = cache_mod.gather_rows(gcache, group_idx)
    pm = jnp.take(prefix_mask, group_idx, axis=0)              # (M, S)
    cm = jnp.concatenate(
        [pm, sfx_mask, jnp.zeros((M, max_new), pm.dtype)], axis=1)
    logits_l, cache2, pos = decoder.extend(
        params, cfg, cache, sfx, sfx_mask, cm, S)
    out, cache_f = _fused_tail(params, cfg, logits_l, cache2, cm, pos, S + S2,
                               yes_ids, no_ids, digit_ids, digit_vals,
                               max_new, topk, stop_mask=stop_mask,
                               eos_id=eos_id, stop_mask2=stop_mask2,
                               stop_sel=stop_sel)
    if return_cache:
        return out, cache_f
    return out


# ---------------------------------------------------------------------------
# Speculative scoring decode (prompt-lookup / fleet drafting, fused verify)
# ---------------------------------------------------------------------------

@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SpecOut:
    """Per-branch speculative-decode accounting, read out host-side into
    profiling.SpecStats. ``drafted``/``accepted`` are (3,) int32 token
    counts by draft source — 0 = radix-tree continuation, 1 = n-gram
    prompt-lookup (including fallback filler), 2 = fleet draft model.
    ``chunks`` counts the verify forwards actually run; ``seq_steps``
    the forwards the sequential scan would have run on the same rows
    (its all-done early exit included), so chunks vs seq_steps IS the
    dispatch-reduction headline."""

    drafted: jax.Array    # (3,) int32
    accepted: jax.Array   # (3,) int32
    chunks: jax.Array     # () int32
    seq_steps: jax.Array  # () int32


def _stop_transition(emit, done, digit_run, prev_ew, stop_mask, eos_id):
    """One emission's stop-state transition — EXACTLY _fused_tail's rules
    (shared so the speculative scan's done/digit-run evolution can never
    drift from the sequential scan's)."""
    cls = stop_mask[emit]
    pure = (cls & _tok.STOP_PURE) != 0
    prefix = (cls & _tok.STOP_PREFIX) != 0
    glue = (cls & _tok.STOP_STARTS_WORD) != 0
    ends_w = (cls & _tok.STOP_ENDS_WORD) != 0
    transp = (cls & _tok.STOP_TRANSPARENT) != 0
    new_done = done | (emit == eos_id) | (digit_run & ~glue & ~transp)
    new_run = jnp.where(
        transp, digit_run,
        (pure & (prefix | ~prev_ew)) | (digit_run & pure & ~prefix))
    new_ew = jnp.where(transp, prev_ew, ends_w)
    return new_done, new_run, new_ew


def _spec_tail(params, cfg: ModelConfig, logits0: jax.Array, cache,
               cache_mask0: jax.Array, pos0: jax.Array, slot0: int,
               yes_ids: jax.Array, no_ids: jax.Array, digit_ids: jax.Array,
               digit_vals: jax.Array, max_new_tokens: int, topk: int,
               spec_k: int, ctx0: jax.Array, ctx0_len: jax.Array,
               draft_tokens: jax.Array, draft_len: jax.Array,
               stop_mask: jax.Array = None, eos_id: jax.Array = None,
               ngram: int = 2, draft_params=None, draft_cfg=None,
               dcache=None, decode_trunk: int = 0):
    """The speculative counterpart of :func:`_fused_tail`: instead of T
    sequential decode steps, scan up to T verify WINDOWS of ``spec_k``
    teacher-forced positions each — [pending emission, draft, draft, ...]
    — through ONE multi-query forward (decoder.verify_extend), then
    greedily accept the draft prefix the verifier's own argmax confirms.
    A window emits between 1 and spec_k tokens and consumes exactly
    spec_k cache slots (rejected tails stay mask-0 garbage, the
    early-stop discipline), so the cache is sized slot0 + T*spec_k.

    Parity contract (pinned by tests/test_spec_decode.py): every
    CONSUMED result is bitwise the sequential scan's, and the per-step
    float rows match within float tolerance —

    - an accepted draft is accepted BECAUSE it equals the verifier's
      argmax at that position, so the emitted token stream, the top-2
      stream, and every position-0 readout (target probabilities,
      top-20 logprob map, weighted confidence — the whole shared-path
      readout surface, sweep rows and serve payloads alike) are
      bitwise-identical; interior per-step probabilities come from the
      verify forward, whose logits are argmax-identical and
      tolerance-equal to decode_step's (decoder.verify_extend — the
      window cache's extra masked slots can regroup reduction lanes,
      the same bar PR-7's fused-vs-dense kernels cleared);
    - done rows advance on forced-EOS "drafts", reproducing the
      sequential scan's EOS-fed evolution, and once every row is done
      the positions past the global stop step are rewritten with the
      stop-step values — the sequential scan's all-done freeze,
      recovered exactly.

    Draft sources, per position (quality-only — a bad draft is simply
    rejected): the host-probed radix-tree continuation ``draft_tokens``
    (B, T) valid below ``draft_len``; an in-scan ``ngram``-gram lookup
    over ``ctx0`` (the row's compacted prompt, right-padded to
    ctx-width >= prompt + T) extended with accepted emissions; or, when
    ``draft_params`` is given, a fleet draft model running spec_k
    sequential small steps per window over its own ``dcache`` (same
    slot layout, same masks). Returns (FusedDecodeOut, final cache,
    final draft cache, SpecOut)."""
    assert spec_k >= 2, "speculation needs a draft window of >= 2"
    early = stop_mask is not None and eos_id is not None
    fleet = draft_params is not None
    T = max_new_tokens
    B = logits0.shape[0]
    W = ctx0.shape[1]

    # Position-0 extras — identical to _fused_tail.
    logp0 = logits0 - jax.scipy.special.logsumexp(
        logits0, axis=-1, keepdims=True)
    tk_vals, tk_ids = lax.top_k(logp0, topk)
    p_digits = jnp.exp(logp0[:, digit_ids])
    mass = jnp.maximum(p_digits.sum(axis=-1), 1e-10)
    wconf = (p_digits * digit_vals[None, :]).sum(axis=-1) / mass

    rows = jnp.arange(B)
    i32 = jnp.int32
    zeros_b = jnp.zeros((B,), bool)

    carry0 = dict(
        logits=logits0, cache=cache, cache_mask=cache_mask0,
        done=zeros_b, digit_run=zeros_b, prev_ew=zeros_b,
        filled=jnp.zeros((B,), i32), done_step=jnp.full((B,), T, i32),
        ctx=ctx0, ctx_n=ctx0_len.astype(i32),
        gen=jnp.zeros((B, T), i32),
        p_yes=jnp.zeros((B, T), jnp.float32),
        p_no=jnp.zeros((B, T), jnp.float32),
        top2=jnp.zeros((B, T, 2), i32),
        drafted=jnp.zeros((3,), i32), accepted=jnp.zeros((3,), i32),
        chunks=jnp.zeros((), i32),
    )
    if fleet:
        carry0["dcache"] = dcache

    def _scatter_row(buf, idx, val, ok):
        """Per-row scatter at (row, idx) where ``ok`` (dropped rows index
        out of range)."""
        eff = jnp.where(ok, idx, T)
        return buf.at[rows, eff].set(val, mode="drop")

    def _gather_ctx(ctx, idx):
        return jnp.take_along_axis(
            ctx, jnp.clip(idx, 0, W - 1)[:, None], axis=1)[:, 0]

    def _window(carry, c):
        all_done = jnp.all(carry["done"])
        tstar = jnp.max(carry["done_step"])
        needed = jnp.where(all_done, jnp.minimum(T, tstar + 1), T)
        go = jnp.min(carry["filled"]) < needed

        def run(carry):
            logits = carry["logits"]
            cache_mask = carry["cache_mask"]
            done = carry["done"]
            digit_run = carry["digit_run"]
            prev_ew = carry["prev_ew"]
            filled = carry["filled"]
            done_step = carry["done_step"]
            ctx, ctx_n = carry["ctx"], carry["ctx_n"]
            gen_b, py_b = carry["gen"], carry["p_yes"]
            pn_b, t2_b = carry["p_no"], carry["top2"]
            drafted, accepted = carry["drafted"], carry["accepted"]
            base = slot0 + c * spec_k
            live0 = filled < T
            done0 = done

            # -- emission 0: the pending token, from the carried logits.
            nxt = jnp.argmax(logits, axis=-1).astype(i32)
            e0 = jnp.where(done, eos_id, nxt) if early else nxt
            py0, pn0, t20 = _small_readout(logits, yes_ids, no_ids)
            gen_b = _scatter_row(gen_b, filled, e0, live0)
            py_b = _scatter_row(py_b, filled, py0, live0)
            pn_b = _scatter_row(pn_b, filled, pn0, live0)
            t2_b = _scatter_row(t2_b, filled, t20, live0)
            if early:
                nd, nr, ne = _stop_transition(e0, done, digit_run, prev_ew,
                                              stop_mask, eos_id)
                done_step = jnp.where(live0 & nd & ~done, filled, done_step)
                done = jnp.where(live0, nd, done)
                digit_run = jnp.where(live0, nr, digit_run)
                prev_ew = jnp.where(live0, ne, prev_ew)
            eff = jnp.where(live0, jnp.clip(ctx_n, 0, W - 1),
                            jnp.full((B,), W, i32))
            ctx = ctx.at[rows, eff].set(e0, mode="drop")
            ctx_n = ctx_n + live0.astype(i32)

            # -- drafts for window positions 1..spec_k-1 ------------------
            drafts, src_tree = [], []
            if fleet:
                dc = carry["dcache"]
                dm = cache_mask
                tok = e0
                for j in range(spec_k):
                    dm = lax.dynamic_update_slice(
                        dm, jnp.ones((B, 1), dm.dtype), (0, base + j))
                    dl, dc = decoder.decode_step(
                        draft_params, draft_cfg, dc, tok,
                        pos0 + filled + j, base + j, dm)
                    if j < spec_k - 1:
                        d = jnp.argmax(dl, axis=-1).astype(i32)
                        if early:
                            d = jnp.where(done, eos_id, d)
                        drafts.append(d)
                        src_tree.append(jnp.zeros((B,), bool))
                        tok = d
                new_dcache = dc
            else:
                # n-gram pattern: the last `ngram` context tokens
                # (prompt + emissions, e0 included).
                n_pos = W - ngram + 1
                pidx = jnp.arange(n_pos)
                eq = jnp.ones((B, n_pos), bool)
                for m in range(ngram):
                    pat_m = _gather_ctx(ctx, ctx_n - ngram + m)
                    eq = eq & (ctx[:, m:m + n_pos] == pat_m[:, None])
                ok_pos = (pidx[None, :] + ngram <= ctx_n[:, None] - 1)
                ok_pos = ok_pos & (ctx_n >= ngram)[:, None]
                best = jnp.where(eq & ok_pos, pidx[None, :], -1).max(axis=1)
                for j in range(1, spec_k):
                    t_idx = filled + j
                    tval = jnp.take_along_axis(
                        draft_tokens, jnp.clip(t_idx, 0, T - 1)[:, None],
                        axis=1)[:, 0]
                    t_ok = t_idx < draft_len
                    ng_idx = best + ngram + (j - 1)
                    ngval = _gather_ctx(ctx, ng_idx)
                    ng_ok = (best >= 0) & (ng_idx < ctx_n)
                    d = jnp.where(t_ok, tval,
                                  jnp.where(ng_ok, ngval, jnp.zeros((), i32)))
                    if early:
                        d = jnp.where(done, eos_id, d)
                    drafts.append(d)
                    src_tree.append(t_ok & ~done)

            # -- ONE fused verify over [e0, drafts...] --------------------
            X = jnp.stack([e0] + drafts, axis=1)           # (B, spec_k)
            cm_run = lax.dynamic_update_slice(
                cache_mask, jnp.ones((B, spec_k), cache_mask.dtype),
                (0, base))
            V, new_cache = decoder.verify_extend(
                params, cfg, carry["cache"], X, cm_run, base,
                trunk_len=decode_trunk)

            # -- greedy acceptance + per-position emissions ---------------
            acc = live0
            n_new = live0.astype(i32)
            d_state, r_state, e_state = done, digit_run, prev_ew
            for j in range(1, spec_k):
                Vj = V[:, j - 1]
                rj = jnp.argmax(Vj, axis=-1).astype(i32)
                if early:
                    rj = jnp.where(d_state, eos_id, rj)
                can = acc & (filled + j < T)
                ok = can & (X[:, j] == rj)
                pyj, pnj, t2j = _small_readout(Vj, yes_ids, no_ids)
                gen_b = _scatter_row(gen_b, filled + j, rj, ok)
                py_b = _scatter_row(py_b, filled + j, pyj, ok)
                pn_b = _scatter_row(pn_b, filled + j, pnj, ok)
                t2_b = _scatter_row(t2_b, filled + j, t2j, ok)
                eff = jnp.where(ok, jnp.clip(ctx_n - 1 + j, 0, W - 1),
                                jnp.full((B,), W, i32))
                ctx = ctx.at[rows, eff].set(rj, mode="drop")
                if early:
                    nd, nr, ne = _stop_transition(rj, d_state, r_state,
                                                  e_state, stop_mask, eos_id)
                    done_step = jnp.where(ok & nd & ~d_state, filled + j,
                                          done_step)
                    d_state = jnp.where(ok, nd, d_state)
                    r_state = jnp.where(ok, nr, r_state)
                    e_state = jnp.where(ok, ne, e_state)
                counted = can & ~done0
                if fleet:
                    drafted = drafted.at[2].add(jnp.sum(counted, dtype=i32))
                    accepted = accepted.at[2].add(
                        jnp.sum(ok & ~done0, dtype=i32))
                else:
                    tr = src_tree[j - 1]
                    drafted = drafted.at[0].add(
                        jnp.sum(counted & tr, dtype=i32))
                    drafted = drafted.at[1].add(
                        jnp.sum(counted & ~tr, dtype=i32))
                    accepted = accepted.at[0].add(
                        jnp.sum(ok & ~done0 & tr, dtype=i32))
                    accepted = accepted.at[1].add(
                        jnp.sum(ok & ~done0 & ~tr, dtype=i32))
                n_new = n_new + ok.astype(i32)
                acc = ok

            # Next pending logits = after the LAST emitted token.
            last = jnp.clip(n_new - 1, 0, spec_k - 1)
            nl = jnp.take_along_axis(V, last[:, None, None], axis=1)[:, 0]
            new_logits = jnp.where(live0[:, None], nl, logits)
            # Shrink the window's validity to the emitted prefix.
            cols = (jnp.arange(spec_k)[None, :]
                    < n_new[:, None]).astype(cache_mask.dtype)
            new_mask = lax.dynamic_update_slice(cm_run, cols, (0, base))
            ctx_n = ctx_n + (n_new - live0.astype(i32))

            out = dict(carry)
            out.update(logits=new_logits, cache=new_cache,
                       cache_mask=new_mask, done=d_state,
                       digit_run=r_state, prev_ew=e_state,
                       filled=filled + n_new, done_step=done_step,
                       ctx=ctx, ctx_n=ctx_n, gen=gen_b, p_yes=py_b,
                       p_no=pn_b, top2=t2_b, drafted=drafted,
                       accepted=accepted,
                       chunks=carry["chunks"] + jnp.ones((), i32))
            if fleet:
                out["dcache"] = new_dcache
            return out

        return lax.cond(go, run, lambda car: car, carry), None

    carry, _ = lax.scan(_window, carry0, jnp.arange(T))

    gen_b, py_b = carry["gen"], carry["p_yes"]
    pn_b, t2_b = carry["p_no"], carry["top2"]
    if early:
        # The sequential scan's all-done freeze: once EVERY row is done
        # (global stop step t*), it skips the model forward and repeats
        # the t*-step values to the end of the budget. Recover exactly
        # that tail from the evolved buffers.
        all_done = jnp.all(carry["done"])
        tstar = jnp.max(carry["done_step"])
        fr = jnp.clip(tstar, 0, T - 1)
        pos = jnp.arange(T)[None, :]
        tail = all_done & (pos > tstar)
        gen_b = jnp.where(tail, eos_id, gen_b)
        py_b = jnp.where(tail, py_b[:, fr][:, None], py_b)
        pn_b = jnp.where(tail, pn_b[:, fr][:, None], pn_b)
        t2_b = jnp.where(tail[..., None], t2_b[:, fr][:, None, :], t2_b)
        seq_steps = jnp.where(all_done, jnp.minimum(tstar, T),
                              jnp.full((), T, i32)).astype(i32)
    else:
        seq_steps = jnp.full((), T, i32)

    out = FusedDecodeOut(
        generated=gen_b, p_yes=py_b, p_no=pn_b, top2_ids=t2_b,
        topk_logprobs=tk_vals, topk_ids=tk_ids, weighted_confidence=wconf)
    spec = SpecOut(drafted=carry["drafted"], accepted=carry["accepted"],
                   chunks=carry["chunks"], seq_steps=seq_steps)
    return out, carry["cache"], carry.get("dcache"), spec


def _shared_spec_branches(params, cfg: ModelConfig, cache, dcache,
                          prefix_mask, sfx_a, sfx_a_mask, sfx_b, sfx_b_mask,
                          yes_ids, no_ids, digit_ids, digit_vals,
                          ctx_a, ctx_a_len, draft_a, draft_a_len,
                          ctx_b, ctx_b_len, draft_b, draft_b_len,
                          T0: int, max_new_a: int, max_new_b: int,
                          spec_k: int, ngram: int, topk: int,
                          stop_mask_a, stop_mask_b, eos_id,
                          draft_params, draft_cfg, return_cache: bool,
                          decode_trunk: int = 0):
    """Both format branches of a shared-prefix dispatch through the
    speculative tail — branch B consumes branch A's cache buffer exactly
    as the sequential path does (masks keep the branches disjoint).

    The suffix extension that produces each branch's position-0 logits
    runs over a cache VIEW truncated to the SEQUENTIAL path's extent
    (``T0_seq``), its suffix k/v written back into the full speculative
    cache afterward: reduction lane grouping follows the attention
    extent, so extending at the inflated spec extent would wobble the
    position-0 readouts' low bits — truncation keeps the whole CONSUMED
    readout surface bitwise the sequential path's, and only the verify
    windows (whose interior floats are tolerance-bound anyway) reduce
    at the longer extent."""
    B, S = prefix_mask.shape
    empty_ids = jnp.zeros((0,), jnp.int32)
    empty_vals = jnp.zeros((0,), jnp.float32)
    T0_seq = S + max(sfx_a.shape[1] + max_new_a,
                     sfx_b.shape[1] + max_new_b)

    def _extend_seq_extent(ext_params, ext_cfg, cache_in, sfx, sfx_mask):
        S2 = sfx.shape[1]
        cm_seq = jnp.concatenate(
            [prefix_mask, sfx_mask,
             jnp.zeros((B, T0_seq - S - S2), prefix_mask.dtype)], axis=1)
        view = jax.tree.map(
            lambda a: lax.slice_in_dim(a, 0, T0_seq, axis=2), cache_in)
        logits_l, view2, pos = decoder.extend(
            ext_params, ext_cfg, view, sfx, sfx_mask, cm_seq, S)
        # Write only the suffix slots back — the extension touched
        # nothing else.
        cache2 = jax.tree.map(
            lambda full, v: lax.dynamic_update_slice_in_dim(
                full, lax.slice_in_dim(v, S, S + S2, axis=2), S, axis=2),
            cache_in, view2)
        return logits_l, cache2, pos

    def branch(cache_in, dcache_in, sfx, sfx_mask, new_tokens, d_ids,
               d_vals, ctx, ctx_len, dr, dr_len, stop_mask):
        S2 = sfx.shape[1]
        cm = jnp.concatenate(
            [prefix_mask, sfx_mask,
             jnp.zeros((B, T0 - S - S2), prefix_mask.dtype)], axis=1)
        logits_l, cache2, pos = _extend_seq_extent(
            params, cfg, cache_in, sfx, sfx_mask)
        dcache2 = None
        if dcache_in is not None:
            _, dcache2, _ = _extend_seq_extent(
                draft_params, draft_cfg, dcache_in, sfx, sfx_mask)
        return _spec_tail(
            params, cfg, logits_l, cache2, cm, pos, S + S2, yes_ids,
            no_ids, d_ids, d_vals, new_tokens, topk, spec_k, ctx, ctx_len,
            dr, dr_len, stop_mask=stop_mask, eos_id=eos_id, ngram=ngram,
            draft_params=draft_params, draft_cfg=draft_cfg, dcache=dcache2,
            decode_trunk=decode_trunk)

    out_a, cache_a, dcache_a, spec_a = branch(
        cache, dcache, sfx_a, sfx_a_mask, max_new_a, empty_ids, empty_vals,
        ctx_a, ctx_a_len, draft_a, draft_a_len, stop_mask_a)
    out_b, cache_b, _, spec_b = branch(
        cache_a, dcache_a, sfx_b, sfx_b_mask, max_new_b, digit_ids,
        digit_vals, ctx_b, ctx_b_len, draft_b, draft_b_len, stop_mask_b)
    if return_cache:
        return out_a, out_b, spec_a, spec_b, cache_b
    return out_a, out_b, spec_a, spec_b


def spec_total_len(bucket: int, sfx_a: int, sfx_b: int, max_new_a: int,
                   max_new_b: int, spec_k: int) -> int:
    """Cache length a speculative shared dispatch allocates: each of the
    T decode windows owns spec_k slots (rejected tails stay masked), so
    the decode region is budget * spec_k instead of budget."""
    return bucket + max(sfx_a + max_new_a * spec_k,
                        sfx_b + max_new_b * spec_k)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new_a", "max_new_b", "topk",
                                    "spec_k", "ngram", "draft_cfg",
                                    "prefill_fn", "return_cache",
                                    "decode_trunk"),
                   donate_argnames=("scratch_cache",))
def greedy_decode_fused_shared_spec(
        params, cfg: ModelConfig, prefix: jax.Array, prefix_mask: jax.Array,
        sfx_a: jax.Array, sfx_a_mask: jax.Array, sfx_b: jax.Array,
        sfx_b_mask: jax.Array, yes_ids: jax.Array, no_ids: jax.Array,
        digit_ids: jax.Array, digit_vals: jax.Array,
        ctx_a: jax.Array, ctx_a_len: jax.Array, draft_a: jax.Array,
        draft_a_len: jax.Array, ctx_b: jax.Array, ctx_b_len: jax.Array,
        draft_b: jax.Array, draft_b_len: jax.Array,
        max_new_a: int, max_new_b: int, spec_k: int, ngram: int = 2,
        topk: int = 20, prefill_fn=None, stop_mask_b: jax.Array = None,
        stop_mask_a: jax.Array = None, eos_id: jax.Array = None,
        draft_params=None, draft_cfg: ModelConfig = None,
        return_cache: bool = False, decode_trunk: int = 0,
        scratch_cache=None):
    """:func:`greedy_decode_fused_shared` with SPECULATIVE decode tails:
    one shared-prefix prefill, two suffix extensions, then each branch's
    sequential greedy scan is replaced by the draft-and-verify window
    scan (:func:`_spec_tail` — per-row accept lengths, per-row stop
    conditions, consumed results bitwise the sequential path's,
    per-step float rows to tolerance). ``ctx_*`` carry
    each branch's compacted prompt tokens for the in-scan n-gram
    drafter; ``draft_*`` the host-probed radix-tree continuations;
    ``draft_params``/``draft_cfg`` arm fleet-model drafting instead
    (same tokenizer/vocab as the verifier — the engine enforces it).
    Returns (binary out, confidence out, binary SpecOut, confidence
    SpecOut[, final cache])."""
    del scratch_cache  # donated scratch: memory reuse only, never read
    B, S = prefix.shape
    S2a, S2b = sfx_a.shape[1], sfx_b.shape[1]
    T0 = spec_total_len(S, S2a, S2b, max_new_a, max_new_b, spec_k)
    pf = prefill_fn or decoder.prefill
    _, cache, _ = pf(params, cfg, prefix, prefix_mask, T0)
    dcache = None
    if draft_params is not None:
        _, dcache, _ = decoder.prefill(draft_params, draft_cfg, prefix,
                                       prefix_mask, T0)
    return _shared_spec_branches(
        params, cfg, cache, dcache, prefix_mask, sfx_a, sfx_a_mask, sfx_b,
        sfx_b_mask, yes_ids, no_ids, digit_ids, digit_vals,
        ctx_a, ctx_a_len, draft_a, draft_a_len, ctx_b, ctx_b_len, draft_b,
        draft_b_len, T0, max_new_a, max_new_b, spec_k, ngram, topk,
        stop_mask_a, stop_mask_b, eos_id, draft_params, draft_cfg,
        return_cache, decode_trunk=decode_trunk)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new_a", "max_new_b", "topk",
                                    "spec_k", "ngram", "return_cache",
                                    "decode_trunk"),
                   donate_argnames=("scratch_cache",))
def greedy_decode_fused_shared_paged_spec(
        params, cfg: ModelConfig, pool, slot_src: jax.Array,
        win_start: jax.Array, prefix_mask: jax.Array, rem: jax.Array,
        rem_mask: jax.Array, sfx_a: jax.Array, sfx_a_mask: jax.Array,
        sfx_b: jax.Array, sfx_b_mask: jax.Array, yes_ids: jax.Array,
        no_ids: jax.Array, digit_ids: jax.Array, digit_vals: jax.Array,
        ctx_a: jax.Array, ctx_a_len: jax.Array, draft_a: jax.Array,
        draft_a_len: jax.Array, ctx_b: jax.Array, ctx_b_len: jax.Array,
        draft_b: jax.Array, draft_b_len: jax.Array,
        max_new_a: int, max_new_b: int, spec_k: int, ngram: int = 2,
        topk: int = 20, stop_mask_b: jax.Array = None,
        stop_mask_a: jax.Array = None, eos_id: jax.Array = None,
        return_cache: bool = False, decode_trunk: int = 0,
        scratch_cache=None):
    """Speculative decode over the radix-paged prefill front: cached
    prefix pages gather from the pool and only the remainder window
    recomputes (:func:`_paged_prefix`), then both branches run the
    speculative tail — prefill savings AND decode savings on one warm
    dispatch (self-drafting only: the paged executable binds slot
    tables, not prefix tokens, so there is nothing for a draft model to
    prefill from)."""
    del scratch_cache  # donated scratch: memory reuse only, never read
    B, S = prefix_mask.shape
    S2a, S2b = sfx_a.shape[1], sfx_b.shape[1]
    T0 = spec_total_len(S, S2a, S2b, max_new_a, max_new_b, spec_k)
    cache = _paged_prefix(params, cfg, pool, slot_src, win_start,
                          prefix_mask, rem, rem_mask, T0)
    return _shared_spec_branches(
        params, cfg, cache, None, prefix_mask, sfx_a, sfx_a_mask, sfx_b,
        sfx_b_mask, yes_ids, no_ids, digit_ids, digit_vals,
        ctx_a, ctx_a_len, draft_a, draft_a_len, ctx_b, ctx_b_len, draft_b,
        draft_b_len, T0, max_new_a, max_new_b, spec_k, ngram, topk,
        stop_mask_a, stop_mask_b, eos_id, None, None, return_cache,
        decode_trunk=decode_trunk)


# ---------------------------------------------------------------------------
# Chunked prefill/decode piggybacking (Sarathi-Serve-style)
# ---------------------------------------------------------------------------

@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PiggybackCarry:
    """One in-flight shared dispatch, parked between engine calls with its
    decode scans still pending: the prefill + both suffix extensions have
    run, and the NEXT piggybacked call fuses this dispatch's decode scans
    into the same XLA program as its own prefill
    (:func:`shared_piggyback_step`) — the dispatch stream then pays one
    device round-trip per dispatch instead of a prefill call AND a decode
    drain, and the host gap between a decode scan and the next prefill
    disappears.

    Unlike the sequential path (branch B's suffix overwrites branch A's
    suffix slots after A's scan retires), a parked cache must keep BOTH
    branches alive, so the piggyback layout gives each branch a disjoint
    slot region: [S, S+S2a+max_new_a) for A, then B's suffix + decode
    region after it. Slots are physical only — positions, masks, and
    causality are all mask-aware — so per-row results are identical to
    the sequential dispatch (pinned by tests/test_kernels.py).
    """

    logits_a: jax.Array   # (B, V) fp32 — branch A first-position logits
    logits_b: jax.Array
    cache: Any            # KV cache pytree, branch regions disjoint
    cm_a: jax.Array       # (B, T) branch A cache mask (B region zeroed)
    cm_b: jax.Array
    pos_a: jax.Array      # (B,) next mask-aware decode positions
    pos_b: jax.Array


def _piggyback_extend(params, cfg: ModelConfig, prefix, prefix_mask,
                      sfx_a, sfx_a_mask, sfx_b, sfx_b_mask,
                      max_new_a: int, max_new_b: int,
                      prefill_fn=None) -> PiggybackCarry:
    """Prefill + both suffix extensions WITHOUT the decode scans, into the
    disjoint-region piggyback cache layout (see PiggybackCarry)."""
    B, S = prefix.shape
    S2a, S2b = sfx_a.shape[1], sfx_b.shape[1]
    T = S + S2a + max_new_a + S2b + max_new_b
    pf = prefill_fn or decoder.prefill
    _, cache, _ = pf(params, cfg, prefix, prefix_mask, T)
    zeros = functools.partial(jnp.zeros, dtype=prefix_mask.dtype)
    cm_a = jnp.concatenate(
        [prefix_mask, sfx_a_mask, zeros((B, T - S - S2a))], axis=1)
    logits_a, cache, pos_a = decoder.extend(
        params, cfg, cache, sfx_a, sfx_a_mask, cm_a, S)
    off_b = S + S2a + max_new_a
    cm_b = jnp.concatenate(
        [prefix_mask, zeros((B, S2a + max_new_a)), sfx_b_mask,
         zeros((B, max_new_b))], axis=1)
    logits_b, cache, pos_b = decoder.extend(
        params, cfg, cache, sfx_b, sfx_b_mask, cm_b, off_b)
    return PiggybackCarry(logits_a=logits_a, logits_b=logits_b, cache=cache,
                          cm_a=cm_a, cm_b=cm_b, pos_a=pos_a, pos_b=pos_b)


def _piggyback_scan(params, cfg: ModelConfig, carry: PiggybackCarry,
                    yes_ids, no_ids, digit_ids, digit_vals,
                    slot0_a: int, slot0_b: int, max_new_a: int,
                    max_new_b: int, topk: int, stop_mask_a, stop_mask_b,
                    eos_id) -> Tuple[FusedDecodeOut, FusedDecodeOut]:
    """Run the parked dispatch's two fused decode scans (branch A then B
    over the one carried cache buffer; B's mask excludes A's region, so
    per-row results equal the sequential dispatch's)."""
    empty_ids = jnp.zeros((0,), jnp.int32)
    empty_vals = jnp.zeros((0,), jnp.float32)
    out_a, cache_a = _fused_tail(params, cfg, carry.logits_a, carry.cache,
                                 carry.cm_a, carry.pos_a, slot0_a,
                                 yes_ids, no_ids, empty_ids, empty_vals,
                                 max_new_a, topk, stop_mask=stop_mask_a,
                                 eos_id=eos_id)
    out_b, _ = _fused_tail(params, cfg, carry.logits_b, cache_a,
                           carry.cm_b, carry.pos_b, slot0_b,
                           yes_ids, no_ids, digit_ids, digit_vals,
                           max_new_b, topk, stop_mask=stop_mask_b,
                           eos_id=eos_id)
    return out_a, out_b


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new_a", "max_new_b",
                                    "prefill_fn"))
def shared_piggyback_prefill(params, cfg: ModelConfig, prefix, prefix_mask,
                             sfx_a, sfx_a_mask, sfx_b, sfx_b_mask,
                             max_new_a: int, max_new_b: int,
                             prefill_fn=None) -> PiggybackCarry:
    """Open a piggyback chain: dispatch the first shared batch's prefill +
    suffix extensions and park its decode scans in the returned carry."""
    return _piggyback_extend(params, cfg, prefix, prefix_mask, sfx_a,
                             sfx_a_mask, sfx_b, sfx_b_mask, max_new_a,
                             max_new_b, prefill_fn)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new_a", "max_new_b", "topk",
                                    "prefill_fn"),
                   donate_argnames=("carry",))
def shared_piggyback_step(params, cfg: ModelConfig, carry: PiggybackCarry,
                          prefix, prefix_mask, sfx_a, sfx_a_mask, sfx_b,
                          sfx_b_mask, yes_ids, no_ids, digit_ids,
                          digit_vals, max_new_a: int, max_new_b: int,
                          topk: int = 20, stop_mask_a=None,
                          stop_mask_b=None, eos_id=None, prefill_fn=None):
    """One piggybacked call: the PARKED dispatch's pending decode scans and
    the NEXT dispatch's prefill + suffix extensions run in ONE XLA
    program. ``yes_ids``/``no_ids`` (and the stop tables) belong to the
    parked dispatch; the chain's shapes/budgets are identical by
    construction (the scheduler only chains same-shape dispatches), so
    the new carry reuses the donated old one's buffers. Returns
    (parked binary out, parked confidence out, new carry)."""
    B, S = prefix.shape
    S2a, S2b = sfx_a.shape[1], sfx_b.shape[1]
    out_a, out_b = _piggyback_scan(
        params, cfg, carry, yes_ids, no_ids, digit_ids, digit_vals,
        S + S2a, S + S2a + max_new_a + S2b, max_new_a, max_new_b, topk,
        stop_mask_a, stop_mask_b, eos_id)
    new_carry = _piggyback_extend(params, cfg, prefix, prefix_mask, sfx_a,
                                  sfx_a_mask, sfx_b, sfx_b_mask, max_new_a,
                                  max_new_b, prefill_fn)
    return out_a, out_b, new_carry


@functools.partial(jax.jit,
                   static_argnames=("cfg", "slot0_a", "slot0_b", "max_new_a",
                                    "max_new_b", "topk"),
                   donate_argnames=("carry",))
def shared_piggyback_drain(params, cfg: ModelConfig, carry: PiggybackCarry,
                           yes_ids, no_ids, digit_ids, digit_vals,
                           slot0_a: int, slot0_b: int, max_new_a: int,
                           max_new_b: int, topk: int = 20,
                           stop_mask_a=None, stop_mask_b=None, eos_id=None):
    """Close a piggyback chain: run the last parked dispatch's decode scans
    alone (no prefill rides along — the chain is over)."""
    return _piggyback_scan(params, cfg, carry, yes_ids, no_ids, digit_ids,
                           digit_vals, slot0_a, slot0_b, max_new_a,
                           max_new_b, topk, stop_mask_a, stop_mask_b,
                           eos_id)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new_a", "max_new_b", "topk",
                                    "prefill_fn", "return_cache",
                                    "decode_trunk"),
                   donate_argnames=("scratch_cache",))
def greedy_decode_fused_shared(params, cfg: ModelConfig, prefix: jax.Array,
                               prefix_mask: jax.Array, sfx_a: jax.Array,
                               sfx_a_mask: jax.Array, sfx_b: jax.Array,
                               sfx_b_mask: jax.Array, yes_ids: jax.Array,
                               no_ids: jax.Array, digit_ids: jax.Array,
                               digit_vals: jax.Array, max_new_a: int,
                               max_new_b: int, topk: int = 20,
                               prefill_fn=None, stop_mask_b: jax.Array = None,
                               stop_mask_a: jax.Array = None,
                               eos_id: jax.Array = None,
                               return_cache: bool = False,
                               decode_trunk: int = 0,
                               scratch_cache=None):
    """TWO fused greedy decodes sharing ONE prefill over a common prefix.

    The perturbation sweep scores every grid cell under two formats whose
    prompts differ only in a short trailing instruction (the rephrased legal
    text is shared — perturb_prompts.py:728-734). The reference pays two
    full forward passes per cell; here the shared prefix (B, S) RIGHT-padded
    (slot == token position — the canonical layout that lets the
    cross-request prefix cache reuse this prefill's KV pages bitwise
    across rows of different lengths; pads are masked no-ops either way)
    is prefilled once, then each format's suffix (B, S2*) RIGHT-padded is
    run through a teacher-forced chunked-prefill extension
    (decoder.extend) at ~S2/S of the prefill cost, followed by the fused
    greedy scan. Device work per cell drops from 2 prefills to ~1.

    Branch B consumes branch A's final cache buffer on purpose: A's suffix
    and generated slots are overwritten/masked (branch B's cache_mask shows
    only prefix + its own suffix), so XLA can alias the cache update
    in place instead of holding two full KV caches live.

    Returns (binary FusedDecodeOut, confidence FusedDecodeOut); the
    confidence branch gets the digit table, the binary branch skips it.
    ``return_cache=True`` appends the final KV cache to the return value;
    ``scratch_cache`` (DONATED) accepts the previous same-shape dispatch's
    cache so XLA writes this one into the same HBM block — one buffer per
    (bucket, batch) shape for a whole sweep instead of an alloc/free per
    dispatch (runner._CacheHandoff). Results never depend on the scratch
    contents: prefill overwrites every slot and attention is masked by
    the cache masks regardless.
    """
    del scratch_cache  # donated scratch: memory reuse only, never read
    B, S = prefix.shape
    S2a, S2b = sfx_a.shape[1], sfx_b.shape[1]
    T0 = S + max(S2a + max_new_a, S2b + max_new_b)
    pf = prefill_fn or decoder.prefill
    _, cache, _ = pf(params, cfg, prefix, prefix_mask, T0)

    empty_ids = jnp.zeros((0,), jnp.int32)
    empty_vals = jnp.zeros((0,), jnp.float32)

    def branch(cache_in, sfx, sfx_mask, new_tokens, d_ids, d_vals,
               stop_mask=None):
        S2 = sfx.shape[1]
        cm = jnp.concatenate(
            [prefix_mask, sfx_mask,
             jnp.zeros((B, T0 - S - S2), prefix_mask.dtype)], axis=1)
        logits_l, cache2, pos = decoder.extend(
            params, cfg, cache_in, sfx, sfx_mask, cm, S)
        return _fused_tail(params, cfg, logits_l, cache2, cm, pos, S + S2,
                           yes_ids, no_ids, d_ids, d_vals, new_tokens, topk,
                           stop_mask=stop_mask, eos_id=eos_id,
                           decode_trunk=decode_trunk)

    # The binary branch (A) takes, when provided, the EOS-only stop
    # (tokens.eos_only_stop_classes: all-transparent classes reduce the
    # done rule to emit == eos) — its numeric readout is position 0 and
    # its response text is EOS-trimmed downstream, so skipped trailing
    # steps are pure EOS fill.
    out_a, cache_a = branch(cache, sfx_a, sfx_a_mask, max_new_a,
                            empty_ids, empty_vals, stop_mask=stop_mask_a)
    # The confidence branch (B) takes the digit table and, when provided,
    # the digit early stop — only its first complete integer is read.
    out_b, cache_b = branch(cache_a, sfx_b, sfx_b_mask, max_new_b,
                            digit_ids, digit_vals, stop_mask=stop_mask_b)
    if return_cache:
        return out_a, out_b, cache_b
    return out_a, out_b


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new_tokens", "prefill_fn"))
def greedy_decode(params, cfg: ModelConfig, tokens: jax.Array,
                  attn_mask: jax.Array, max_new_tokens: int = 50,
                  prefill_fn=None) -> Tuple[jax.Array, jax.Array]:
    """tokens/attn_mask: (B, S) LEFT-padded int32.

    Returns (generated (B, max_new_tokens) int32,
             step_logits (B, max_new_tokens, V) fp32)."""
    B, S = tokens.shape
    T = S + max_new_tokens
    pf = prefill_fn or decoder.prefill
    logits0, cache, pos0 = pf(params, cfg, tokens, attn_mask, T)

    cache_mask0 = jnp.pad(attn_mask, ((0, 0), (0, max_new_tokens)))

    def step(carry, t):
        logits, cache, cache_mask = carry
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        cache_mask = cache_mask.at[:, S + t].set(1)
        new_logits, cache = decoder.decode_step(
            params, cfg, cache, nxt, pos0 + t, S + t, cache_mask)
        return (new_logits, cache, cache_mask), (nxt, logits)

    (_, _, _), (gen, step_logits) = lax.scan(
        step, (logits0, cache, cache_mask0), jnp.arange(max_new_tokens))
    # scan stacks on axis 0 -> (T_new, B, ...); put batch first.
    return jnp.swapaxes(gen, 0, 1), jnp.swapaxes(step_logits, 0, 1)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new_tokens", "prefill_fn"))
def sample_decode(params, cfg: ModelConfig, tokens: jax.Array,
                  attn_mask: jax.Array, key: jax.Array,
                  temperature: float = 0.9, max_new_tokens: int = 50,
                  prefill_fn=None, eos_id: jax.Array = None) -> jax.Array:
    """Temperature sampling with the same prefill + lax.scan structure as
    greedy_decode, for the on-pod perturbation generator (the reference
    rephrases with temperature 0.9 via the Anthropic API,
    perturb_prompts.py:799-809; here the sampler runs on the local model).

    ``key`` is either one PRNG key (a fresh subkey per step; a row's draws
    then depend on its batch position) or per-row keys shaped (B, 2) — each
    row gets its own stream folded per step, so a row's sample depends ONLY
    on its key, not on which batch it rides in (resume-deterministic
    reasoning sweeps key rows by grid-cell identity).

    ``eos_id`` arms the HF-generate-parity stop: a row emits EOS fill
    after its first EOS (no post-EOS samples leak into text, matching the
    API/HF semantics the reference relies on), and once EVERY row is done
    the remaining scan steps skip the model forward via a scalar
    lax.cond — a generous session budget then costs actual response
    length. Non-done rows' draws are bit-identical to the unstopped
    sampler (the per-step keys never depend on doneness).

    Returns generated (B, max_new_tokens) int32. Per-step logits are not
    captured — rephrasings need text only, and dropping the (B, T, V) stack
    keeps HBM free for long sample runs."""
    B, S = tokens.shape
    T = S + max_new_tokens
    per_row = is_per_row_keys(key)
    early = eos_id is not None
    pf = prefill_fn or decoder.prefill
    logits0, cache, pos0 = pf(params, cfg, tokens, attn_mask, T)
    cache_mask0 = jnp.pad(attn_mask, ((0, 0), (0, max_new_tokens)))

    def step(carry, xs):
        logits, cache, cache_mask, done = carry
        t, step_key = xs
        scaled = logits / jnp.maximum(temperature, 1e-6)
        if per_row:
            nxt = jax.vmap(jax.random.categorical)(step_key, scaled)
        else:
            nxt = jax.random.categorical(step_key, scaled, axis=-1)
        nxt = nxt.astype(jnp.int32)
        if early:
            emit = jnp.where(done, eos_id, nxt)
            done = done | (emit == eos_id)
            all_done = jnp.all(done)
            step_mask = cache_mask.at[:, S + t].set(1)

            def run(args):
                lg, c = args
                return decoder.decode_step(
                    params, cfg, c, emit, pos0 + t, S + t, step_mask)

            new_logits, cache = lax.cond(
                all_done, lambda args: args, run, (logits, cache))
            cache_mask = jnp.where(all_done, cache_mask, step_mask)
        else:
            emit = nxt
            cache_mask = cache_mask.at[:, S + t].set(1)
            new_logits, cache = decoder.decode_step(
                params, cfg, cache, emit, pos0 + t, S + t, cache_mask)
        return (new_logits, cache, cache_mask, done), emit

    if per_row:
        # (T, B, 2): row b's stream at step t = fold_in(keys[b], t).
        keys = jax.vmap(
            lambda t: jax.vmap(lambda k: jax.random.fold_in(k, t))(key)
        )(jnp.arange(max_new_tokens))
    else:
        keys = jax.random.split(key, max_new_tokens)
    (_, _, _, _), gen = lax.scan(
        step, (logits0, cache, cache_mask0, jnp.zeros((B,), bool)),
        (jnp.arange(max_new_tokens), keys))
    return jnp.swapaxes(gen, 0, 1)


@functools.partial(jax.jit, static_argnames=("cfg", "max_new_tokens"))
def t5_greedy_decode(params, cfg: T5Config, enc_tokens: jax.Array,
                     enc_mask: jax.Array, max_new_tokens: int = 50
                     ) -> Tuple[jax.Array, jax.Array]:
    """Encoder-decoder greedy decode (reference Seq2Seq branch,
    compare_base_vs_instruct.py:203-241).

    Re-runs the (tiny) decoder stack over a fixed (B, max_new) buffer each
    step — sequences here are ≤50 tokens so a KV cache buys nothing.
    Returns (generated (B, max_new), step_logits (B, max_new, V) fp32)."""
    B = enc_tokens.shape[0]
    enc_out = encdec.encode(params, cfg, enc_tokens, enc_mask)

    dec_buf0 = jnp.full((B, max_new_tokens + 1), cfg.decoder_start_token_id,
                        dtype=jnp.int32)
    mask0 = jnp.zeros((B, max_new_tokens + 1), jnp.int32).at[:, 0].set(1)

    def step(carry, t):
        dec_buf, mask = carry
        logits = encdec.decode(params, cfg, enc_out, enc_mask, dec_buf, mask)
        # Logits at the last valid position (= t).
        step_logits = jnp.take_along_axis(
            logits, t[None, None, None].repeat(B, 0), axis=1)[:, 0, :]
        nxt = jnp.argmax(step_logits, axis=-1).astype(jnp.int32)
        dec_buf = dec_buf.at[:, t + 1].set(nxt)
        mask = mask.at[:, t + 1].set(1)
        return (dec_buf, mask), (nxt, step_logits)

    (_, _), (gen, step_logits) = lax.scan(
        step, (dec_buf0, mask0), jnp.arange(max_new_tokens))
    return jnp.swapaxes(gen, 0, 1), jnp.swapaxes(step_logits, 0, 1)
