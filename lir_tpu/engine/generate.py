"""Greedy decoding with per-step logit capture.

The reference's measurement path is ``model.generate(max_new_tokens=50,
output_scores=True, return_dict_in_generate=True)`` followed by a scan of the
first 10 score tensors (compare_base_vs_instruct.py:251-278). Here that is one
jitted program: prefill the KV cache, then ``lax.scan`` 50 greedy steps,
stacking each step's fp32 logits. Fixed shapes throughout — the grid engine
batches ragged prompts by left-padding (decoder.mask_positions makes padding
a no-op).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..models import decoder
from ..models.registry import ModelConfig, T5Config
from ..models import encdec
from . import tokens as _tok


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class FusedDecodeOut:
    """Per-step readout captured inside the decode scan — everything the
    sweeps consume, WITHOUT materializing the (B, T_new, V) logit stack.

    At seq 256 / vocab 32k / 10 steps the full stack is ~50 MB of HBM
    traffic per batch; this struct is ~100 floats per row. The fused path is
    the production scorer; `greedy_decode` (full capture) remains for
    debugging and parity tests.
    """

    generated: jax.Array      # (B, T_new) int32
    p_yes: jax.Array          # (B, T_new) fp32 softmax prob of the yes id
    p_no: jax.Array           # (B, T_new) fp32
    top2_ids: jax.Array       # (B, T_new, 2) int32 — the top-2 match rule
    topk_logprobs: jax.Array  # (B, K) fp32 at position 0 (D6 log-prob map)
    topk_ids: jax.Array       # (B, K) int32
    weighted_confidence: jax.Array  # (B,) fp32 E[v] over digit ids at pos 0


def is_per_row_keys(key: jax.Array) -> bool:
    """True when ``key`` is a BATCH of PRNG keys (one stream per prompt
    row), under either key flavor: typed keys (jax.random.key — a key
    batch is shape (B,), scalar key shape ()) or legacy uint32 keys (a
    batch is (B, 2), a single key (2,))."""
    try:
        if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
            return key.ndim >= 1
    except TypeError:
        pass
    return getattr(key, "ndim", 1) == 2


def _small_readout(logits: jax.Array, yes_ids: jax.Array, no_ids: jax.Array):
    """(B, V) fp32 logits -> (p_yes, p_no, top2_ids): O(B*V) compute, O(B)
    output."""
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    l_yes = jnp.take_along_axis(logits, yes_ids[:, None], axis=1)[:, 0]
    l_no = jnp.take_along_axis(logits, no_ids[:, None], axis=1)[:, 0]
    p_yes = jnp.exp(l_yes - lse)
    p_no = jnp.exp(l_no - lse)
    _, top2 = lax.top_k(logits, 2)
    return p_yes, p_no, top2.astype(jnp.int32)


def _fused_tail(params, cfg: ModelConfig, logits0: jax.Array, cache,
                cache_mask0: jax.Array, pos0: jax.Array, slot0: int,
                yes_ids: jax.Array, no_ids: jax.Array, digit_ids: jax.Array,
                digit_vals: jax.Array, max_new_tokens: int, topk: int,
                stop_mask: jax.Array = None, eos_id: jax.Array = None,
                stop_mask2: jax.Array = None, stop_sel: jax.Array = None,
                ) -> Tuple[FusedDecodeOut, Tuple]:
    """The fused greedy scan shared by the full-prompt and shared-prefix
    paths: start from ``logits0`` (the first generated position), write
    generated k/v at cache slots ``slot0 + t``, capture the C13/D6 readouts
    in-scan. Returns (FusedDecodeOut, final cache).

    ``stop_mask`` ((V,) int32 surface-class bitmask from
    tokens.digit_stop_classes) + ``eos_id`` enable the confidence early
    stop: a row is DONE once it emits EOS, or once a standalone digit run
    (pure digit tokens opened at a word boundary) is followed by a
    non-gluing token — at that point the decoded text provably contains a
    complete ``\\b\\d+\\b`` integer, the only thing the confidence parse
    reads. Letter-glued digits ('1'+'st') neither open nor terminate a
    run, and transparent specials (empty decode) change nothing, so the
    stop NEVER nulls an answer the full budget would have parsed. Done
    rows emit EOS from the next step (so host-side EOS trimming ends their
    text at the stop point), and once EVERY row is done the remaining scan
    steps skip the model forward via a scalar ``lax.cond`` — a generous
    token budget then costs actual-response-length decode steps, not the
    worst case. Per-step p_yes/p_no/top2 after a row's stop point reflect
    the EOS-fed model and must not be consumed (the sweep's confidence
    readout uses position 0 only).

    ``stop_mask2`` + ``stop_sel`` ((B,) bool) select a SECOND class table
    per row: rows where ``stop_sel`` is True read their emitted token's
    class from ``stop_mask2`` instead of ``stop_mask``. The prefix-group
    decode mixes both sweep formats in one batch and needs the binary
    rows on the EOS-only table while confidence rows run the digit stop.
    """
    early_stop = stop_mask is not None and eos_id is not None
    # Position-0 extras (first generated position): top-k logprob map +
    # weighted confidence.
    logp0 = logits0 - jax.scipy.special.logsumexp(
        logits0, axis=-1, keepdims=True)
    tk_vals, tk_ids = lax.top_k(logp0, topk)
    p_digits = jnp.exp(logp0[:, digit_ids])                    # (B, K)
    mass = jnp.maximum(p_digits.sum(axis=-1), 1e-10)
    wconf = (p_digits * digit_vals[None, :]).sum(axis=-1) / mass

    B = logits0.shape[0]

    def step(carry, t):
        logits, cache, cache_mask, done, digit_run, prev_ew = carry
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        p_yes, p_no, top2 = _small_readout(logits, yes_ids, no_ids)
        if early_stop:
            emit = jnp.where(done, eos_id, nxt)
            cls = stop_mask[emit]
            if stop_mask2 is not None:
                cls = jnp.where(stop_sel, stop_mask2[emit], cls)
            pure = (cls & _tok.STOP_PURE) != 0
            prefix = (cls & _tok.STOP_PREFIX) != 0
            glue = (cls & _tok.STOP_STARTS_WORD) != 0
            ends_w = (cls & _tok.STOP_ENDS_WORD) != 0
            transp = (cls & _tok.STOP_TRANSPARENT) != 0
            done = done | (emit == eos_id) | (digit_run & ~glue & ~transp)
            # A standalone digit run opens on a pure-digit token at a word
            # boundary (space prefix, or previous token ended non-word —
            # position 0 starts at a boundary: prev_ew init False), extends
            # through unprefixed pure-digit tokens, and is spoiled by
            # anything else. Transparent tokens freeze all text state.
            digit_run = jnp.where(
                transp, digit_run,
                (pure & (prefix | ~prev_ew)) | (digit_run & pure & ~prefix))
            prev_ew = jnp.where(transp, prev_ew, ends_w)

            # Defensive (ADVICE r4): the slot write happens only when the
            # step actually runs, so an early-stopped tail's final cache
            # never marks unwritten KV slots as valid. No current caller
            # reads that mask (both fused callers discard it) — this
            # removes the latent hazard for future cache reuse, nothing
            # more.
            all_done = jnp.all(done)
            step_mask = cache_mask.at[:, slot0 + t].set(1)

            def run(args):
                lg, c = args
                return decoder.decode_step(
                    params, cfg, c, emit, pos0 + t, slot0 + t, step_mask)

            new_logits, cache = lax.cond(
                all_done, lambda args: args, run, (logits, cache))
            cache_mask = jnp.where(all_done, cache_mask, step_mask)
        else:
            emit = nxt
            cache_mask = cache_mask.at[:, slot0 + t].set(1)
            new_logits, cache = decoder.decode_step(
                params, cfg, cache, emit, pos0 + t, slot0 + t, cache_mask)
        return ((new_logits, cache, cache_mask, done, digit_run, prev_ew),
                (emit, p_yes, p_no, top2))

    zeros_b = jnp.zeros((B,), bool)
    (_, cache_f, _, _, _, _), (gen, p_yes, p_no, top2) = lax.scan(
        step, (logits0, cache, cache_mask0, zeros_b, zeros_b, zeros_b),
        jnp.arange(max_new_tokens))

    return FusedDecodeOut(
        generated=jnp.swapaxes(gen, 0, 1),
        p_yes=jnp.swapaxes(p_yes, 0, 1),
        p_no=jnp.swapaxes(p_no, 0, 1),
        top2_ids=jnp.swapaxes(top2, 0, 1),
        topk_logprobs=tk_vals,
        topk_ids=tk_ids,
        weighted_confidence=wconf,
    ), cache_f


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new_tokens", "topk",
                                    "prefill_fn"))
def greedy_decode_fused(params, cfg: ModelConfig, tokens: jax.Array,
                        attn_mask: jax.Array, yes_ids: jax.Array,
                        no_ids: jax.Array, digit_ids: jax.Array,
                        digit_vals: jax.Array, max_new_tokens: int = 50,
                        topk: int = 20,
                        prefill_fn=None, stop_mask: jax.Array = None,
                        eos_id: jax.Array = None) -> FusedDecodeOut:
    """Greedy decode with the C13/D6 readouts fused into the scan.

    yes_ids/no_ids: (B,) per-row target token ids (rows of one batch may
    score different prompts with different target tokens). digit_ids/vals:
    the integer-token table for the weighted-confidence readout (pass empty
    arrays to skip: the gather on an empty axis is free). stop_mask/eos_id
    enable the confidence early stop (see _fused_tail).
    """
    B, S = tokens.shape
    T = S + max_new_tokens
    pf = prefill_fn or decoder.prefill
    logits0, cache, pos0 = pf(params, cfg, tokens, attn_mask, T)
    cache_mask0 = jnp.pad(attn_mask, ((0, 0), (0, max_new_tokens)))
    out, _ = _fused_tail(params, cfg, logits0, cache, cache_mask0, pos0, S,
                         yes_ids, no_ids, digit_ids, digit_vals,
                         max_new_tokens, topk, stop_mask=stop_mask,
                         eos_id=eos_id)
    return out


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new", "topk", "prefill_fn",
                                    "return_cache"),
                   donate_argnames=("scratch_cache",))
def greedy_decode_fused_grouped(params, cfg: ModelConfig, prefix: jax.Array,
                                prefix_mask: jax.Array, sfx: jax.Array,
                                sfx_mask: jax.Array, group_idx: jax.Array,
                                yes_ids: jax.Array, no_ids: jax.Array,
                                digit_ids: jax.Array, digit_vals: jax.Array,
                                max_new: int, topk: int = 20,
                                prefill_fn=None, stop_mask: jax.Array = None,
                                stop_mask2: jax.Array = None,
                                stop_sel: jax.Array = None,
                                eos_id: jax.Array = None,
                                return_cache: bool = False,
                                scratch_cache=None):
    """M fused greedy decodes sharing G <= M prefix prefills (cross-cell
    prefix reuse).

    Generalizes :func:`greedy_decode_fused_shared` from "two formats of one
    row share that row's prefill" to "any member rows whose prompts share a
    token prefix share ONE prefill": the ragged scheduler groups grid cells
    whose tokenized prompts agree on a long prefix (all the sweep formats x
    rephrasings of one base prompt, when the rephrasings preserve the
    opening tokens), prefills each distinct prefix once as a (G, S)
    RIGHT-padded batch (the canonical slot == position layout — see
    greedy_decode_fused_shared), and ``group_idx`` (M,) maps each member
    row to its prefix. The member suffixes (M, S2) RIGHT-padded then run one chunked
    teacher-forced extension over the row-gathered cache, followed by the
    fused scan. Prefill FLOPs drop by the group fan-out M/G; the gathered
    M-row cache is the same size the ungrouped path allocates.

    ``stop_mask``/``stop_mask2``/``stop_sel`` give per-row stop tables (the
    mixed-format batch runs EOS-only stops on binary rows and the digit
    stop on confidence rows — see _fused_tail). The pairwise special case
    (G rows, 2 members each, ``group_idx = [0, 0, 1, 1, ...]``) scores
    identically to greedy_decode_fused_shared (pinned by
    tests/test_scheduler.py).

    ``return_cache=True`` additionally returns the scan's final KV cache;
    ``scratch_cache`` (DONATED) accepts the previous same-shape dispatch's
    returned cache so XLA writes this dispatch's cache into the same HBM
    block — one cache buffer then serves an entire bucket queue instead of
    an alloc/free per dispatch (see runner._CacheHandoff). Results never
    depend on the scratch contents: prefill overwrites every slot and
    attention is masked by ``cache_mask`` regardless.
    """
    del scratch_cache  # donated scratch: memory reuse only, never read
    G, S = prefix.shape
    M, S2 = sfx.shape
    T0 = S + S2 + max_new
    pf = prefill_fn or decoder.prefill
    _, gcache, _ = pf(params, cfg, prefix, prefix_mask, T0)

    from ..models import cache as cache_mod

    cache = cache_mod.gather_rows(gcache, group_idx)
    pm = jnp.take(prefix_mask, group_idx, axis=0)              # (M, S)
    cm = jnp.concatenate(
        [pm, sfx_mask, jnp.zeros((M, max_new), pm.dtype)], axis=1)
    logits_l, cache2, pos = decoder.extend(
        params, cfg, cache, sfx, sfx_mask, cm, S)
    out, cache_f = _fused_tail(params, cfg, logits_l, cache2, cm, pos, S + S2,
                               yes_ids, no_ids, digit_ids, digit_vals,
                               max_new, topk, stop_mask=stop_mask,
                               eos_id=eos_id, stop_mask2=stop_mask2,
                               stop_sel=stop_sel)
    if return_cache:
        return out, cache_f
    return out


def _paged_prefix(params, cfg: ModelConfig, pool, slot_src: jax.Array,
                  win_start: jax.Array, prefix_mask: jax.Array,
                  rem: jax.Array, rem_mask: jax.Array, total_len: int):
    """The paged replacement for the shared-prefill step, EXACT-LAYOUT:
    assemble the cached prefix KV from the page pool (models/paged.
    gather_slots over ``slot_src`` (B, S)) and teacher-force the
    recompute WINDOW — slots [w0, w0 + R), each row's prefix tokens in
    that range RIGHT-padded into ``rem``/``rem_mask`` (B, R) — via one
    chunked extension over the S-slot cache view (decoder.extend at
    start_index = ``win_start``, a TRACED scalar: the window is anchored
    at the dispatch's longest real row, not the bucket edge, so rows
    shorter than the bucket never pay recompute FLOPs for pad slots —
    and the anchor varies per dispatch without retracing). A dispatch
    then pays prefill FLOPs for R tokens per row instead of the whole
    bucket.

    The layout discipline is what buys bitwise parity with the unpaged
    path (pinned by tests/test_prefix_cache.py):

    - the shared-prefix paths RIGHT-pad their prefixes (slot == token
      position, runner.decode_fused_shared), so a token's slot — and
      hence the reduction layout that computes its KV — is independent
      of its row's length: pages produced under any row back any later
      row sharing the prefix bitwise;
    - the window extension runs over an S-slot cache view — the exact
      attention extent the prefill's quadratic pass reduces over — and
      only afterwards is the cache padded out to ``total_len`` with
      zeros, exactly as prefill pads;
    - unfilled slots (a short row's tail, slots a cold row has no pages
      for) read the trash page's exact zeros; the unpaged prefill holds
      garbage pad-token k/v there instead, but both contribute exact
      0.0 through the masked softmax, so the difference is invisible.

    ``prefix_mask`` is the standard right-pad mask (B, S) — the SAME
    tensor the unpaged path computes. Returns the cache with
    [0, total_len) allocated and [0, S) populated — the drop-in analogue
    of ``prefill``'s cache output.
    """
    from ..models import paged as paged_mod

    S = prefix_mask.shape[1]
    cache = paged_mod.gather_slots(pool, slot_src)          # S-slot view
    _, cache, _ = decoder.extend(params, cfg, cache, rem, rem_mask,
                                 prefix_mask, win_start)

    def pad_leaf(a):
        pad = [(0, 0)] * a.ndim
        pad[2] = (0, total_len - S)                         # time axis
        return jnp.pad(a, pad)

    return jax.tree.map(pad_leaf, cache)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new_a", "max_new_b", "topk",
                                    "return_cache"),
                   donate_argnames=("scratch_cache",))
def greedy_decode_fused_shared_paged(params, cfg: ModelConfig, pool,
                                     slot_src: jax.Array,
                                     win_start: jax.Array,
                                     prefix_mask: jax.Array, rem: jax.Array,
                                     rem_mask: jax.Array, sfx_a: jax.Array,
                                     sfx_a_mask: jax.Array, sfx_b: jax.Array,
                                     sfx_b_mask: jax.Array,
                                     yes_ids: jax.Array, no_ids: jax.Array,
                                     digit_ids: jax.Array,
                                     digit_vals: jax.Array, max_new_a: int,
                                     max_new_b: int, topk: int = 20,
                                     stop_mask_b: jax.Array = None,
                                     stop_mask_a: jax.Array = None,
                                     eos_id: jax.Array = None,
                                     return_cache: bool = False,
                                     scratch_cache=None):
    """:func:`greedy_decode_fused_shared` resuming from the cross-request
    radix prefix cache: the quadratic prefill over each row's shared
    binary/confidence prefix is replaced by a page-pool slot gather plus
    one chunked extension over the per-row remainder window
    (:func:`_paged_prefix`); the two format-suffix branches and the
    fused scans are the unpaged path's own code at the unpaged path's
    own shapes, which is what makes paged results BITWISE-identical to
    the contiguous-cache path per request (pinned by
    tests/test_prefix_cache.py). ``return_cache`` also returns the final
    cache — callers feed it back into the pool (page insertion) and the
    donation chain (its shape equals the unpaged path's, so cold and
    warm dispatches share one donated buffer)."""
    del scratch_cache  # donated scratch: memory reuse only, never read
    B, S = prefix_mask.shape
    S2a, S2b = sfx_a.shape[1], sfx_b.shape[1]
    T0 = S + max(S2a + max_new_a, S2b + max_new_b)
    cache = _paged_prefix(params, cfg, pool, slot_src, win_start,
                          prefix_mask, rem, rem_mask, T0)

    empty_ids = jnp.zeros((0,), jnp.int32)
    empty_vals = jnp.zeros((0,), jnp.float32)

    def branch(cache_in, sfx, sfx_mask, new_tokens, d_ids, d_vals,
               stop_mask=None):
        S2 = sfx.shape[1]
        cm = jnp.concatenate(
            [prefix_mask, sfx_mask,
             jnp.zeros((B, T0 - S - S2), prefix_mask.dtype)], axis=1)
        logits_l, cache2, pos = decoder.extend(
            params, cfg, cache_in, sfx, sfx_mask, cm, S)
        return _fused_tail(params, cfg, logits_l, cache2, cm, pos, S + S2,
                           yes_ids, no_ids, d_ids, d_vals, new_tokens, topk,
                           stop_mask=stop_mask, eos_id=eos_id)

    out_a, cache_a = branch(cache, sfx_a, sfx_a_mask, max_new_a,
                            empty_ids, empty_vals, stop_mask=stop_mask_a)
    out_b, cache_b = branch(cache_a, sfx_b, sfx_b_mask, max_new_b,
                            digit_ids, digit_vals, stop_mask=stop_mask_b)
    if return_cache:
        return out_a, out_b, cache_b
    return out_a, out_b


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new", "topk", "return_cache"),
                   donate_argnames=("scratch_cache",))
def greedy_decode_fused_grouped_paged(params, cfg: ModelConfig, pool,
                                      slot_src: jax.Array,
                                      win_start: jax.Array,
                                      prefix_mask: jax.Array,
                                      rem: jax.Array, rem_mask: jax.Array,
                                      sfx: jax.Array, sfx_mask: jax.Array,
                                      group_idx: jax.Array,
                                      yes_ids: jax.Array, no_ids: jax.Array,
                                      digit_ids: jax.Array,
                                      digit_vals: jax.Array, max_new: int,
                                      topk: int = 20,
                                      stop_mask: jax.Array = None,
                                      stop_mask2: jax.Array = None,
                                      stop_sel: jax.Array = None,
                                      eos_id: jax.Array = None,
                                      return_cache: bool = False,
                                      scratch_cache=None):
    """:func:`greedy_decode_fused_grouped` resuming group prefixes from
    the radix prefix cache: the (G, S) group prefill becomes a page-pool
    slot gather plus one remainder-window extension
    (:func:`_paged_prefix` at G rows, same exact-layout discipline as
    the shared variant), then the member-row gather
    (models/cache.gather_rows), suffix extension, and fused scan run as
    the unpaged grouped path's own code at its own shapes. A sweep whose
    prefix groups recur across dispatches (one base prompt's rephrasings
    split across bucket queues, or a re-run grid on a warm engine) then
    prefills each group prefix ONCE, not once per dispatch."""
    del scratch_cache  # donated scratch: memory reuse only, never read
    G, S = prefix_mask.shape
    M, S2 = sfx.shape
    T0 = S + S2 + max_new
    gcache = _paged_prefix(params, cfg, pool, slot_src, win_start,
                           prefix_mask, rem, rem_mask, T0)

    from ..models import cache as cache_mod

    cache = cache_mod.gather_rows(gcache, group_idx)
    pm = jnp.take(prefix_mask, group_idx, axis=0)              # (M, S)
    cm = jnp.concatenate(
        [pm, sfx_mask, jnp.zeros((M, max_new), pm.dtype)], axis=1)
    logits_l, cache2, pos = decoder.extend(
        params, cfg, cache, sfx, sfx_mask, cm, S)
    out, cache_f = _fused_tail(params, cfg, logits_l, cache2, cm, pos, S + S2,
                               yes_ids, no_ids, digit_ids, digit_vals,
                               max_new, topk, stop_mask=stop_mask,
                               eos_id=eos_id, stop_mask2=stop_mask2,
                               stop_sel=stop_sel)
    if return_cache:
        return out, cache_f
    return out


# ---------------------------------------------------------------------------
# Chunked prefill/decode piggybacking (Sarathi-Serve-style)
# ---------------------------------------------------------------------------

@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PiggybackCarry:
    """One in-flight shared dispatch, parked between engine calls with its
    decode scans still pending: the prefill + both suffix extensions have
    run, and the NEXT piggybacked call fuses this dispatch's decode scans
    into the same XLA program as its own prefill
    (:func:`shared_piggyback_step`) — the dispatch stream then pays one
    device round-trip per dispatch instead of a prefill call AND a decode
    drain, and the host gap between a decode scan and the next prefill
    disappears.

    Unlike the sequential path (branch B's suffix overwrites branch A's
    suffix slots after A's scan retires), a parked cache must keep BOTH
    branches alive, so the piggyback layout gives each branch a disjoint
    slot region: [S, S+S2a+max_new_a) for A, then B's suffix + decode
    region after it. Slots are physical only — positions, masks, and
    causality are all mask-aware — so per-row results are identical to
    the sequential dispatch (pinned by tests/test_kernels.py).
    """

    logits_a: jax.Array   # (B, V) fp32 — branch A first-position logits
    logits_b: jax.Array
    cache: Any            # KV cache pytree, branch regions disjoint
    cm_a: jax.Array       # (B, T) branch A cache mask (B region zeroed)
    cm_b: jax.Array
    pos_a: jax.Array      # (B,) next mask-aware decode positions
    pos_b: jax.Array


def _piggyback_extend(params, cfg: ModelConfig, prefix, prefix_mask,
                      sfx_a, sfx_a_mask, sfx_b, sfx_b_mask,
                      max_new_a: int, max_new_b: int,
                      prefill_fn=None) -> PiggybackCarry:
    """Prefill + both suffix extensions WITHOUT the decode scans, into the
    disjoint-region piggyback cache layout (see PiggybackCarry)."""
    B, S = prefix.shape
    S2a, S2b = sfx_a.shape[1], sfx_b.shape[1]
    T = S + S2a + max_new_a + S2b + max_new_b
    pf = prefill_fn or decoder.prefill
    _, cache, _ = pf(params, cfg, prefix, prefix_mask, T)
    zeros = functools.partial(jnp.zeros, dtype=prefix_mask.dtype)
    cm_a = jnp.concatenate(
        [prefix_mask, sfx_a_mask, zeros((B, T - S - S2a))], axis=1)
    logits_a, cache, pos_a = decoder.extend(
        params, cfg, cache, sfx_a, sfx_a_mask, cm_a, S)
    off_b = S + S2a + max_new_a
    cm_b = jnp.concatenate(
        [prefix_mask, zeros((B, S2a + max_new_a)), sfx_b_mask,
         zeros((B, max_new_b))], axis=1)
    logits_b, cache, pos_b = decoder.extend(
        params, cfg, cache, sfx_b, sfx_b_mask, cm_b, off_b)
    return PiggybackCarry(logits_a=logits_a, logits_b=logits_b, cache=cache,
                          cm_a=cm_a, cm_b=cm_b, pos_a=pos_a, pos_b=pos_b)


def _piggyback_scan(params, cfg: ModelConfig, carry: PiggybackCarry,
                    yes_ids, no_ids, digit_ids, digit_vals,
                    slot0_a: int, slot0_b: int, max_new_a: int,
                    max_new_b: int, topk: int, stop_mask_a, stop_mask_b,
                    eos_id) -> Tuple[FusedDecodeOut, FusedDecodeOut]:
    """Run the parked dispatch's two fused decode scans (branch A then B
    over the one carried cache buffer; B's mask excludes A's region, so
    per-row results equal the sequential dispatch's)."""
    empty_ids = jnp.zeros((0,), jnp.int32)
    empty_vals = jnp.zeros((0,), jnp.float32)
    out_a, cache_a = _fused_tail(params, cfg, carry.logits_a, carry.cache,
                                 carry.cm_a, carry.pos_a, slot0_a,
                                 yes_ids, no_ids, empty_ids, empty_vals,
                                 max_new_a, topk, stop_mask=stop_mask_a,
                                 eos_id=eos_id)
    out_b, _ = _fused_tail(params, cfg, carry.logits_b, cache_a,
                           carry.cm_b, carry.pos_b, slot0_b,
                           yes_ids, no_ids, digit_ids, digit_vals,
                           max_new_b, topk, stop_mask=stop_mask_b,
                           eos_id=eos_id)
    return out_a, out_b


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new_a", "max_new_b",
                                    "prefill_fn"))
def shared_piggyback_prefill(params, cfg: ModelConfig, prefix, prefix_mask,
                             sfx_a, sfx_a_mask, sfx_b, sfx_b_mask,
                             max_new_a: int, max_new_b: int,
                             prefill_fn=None) -> PiggybackCarry:
    """Open a piggyback chain: dispatch the first shared batch's prefill +
    suffix extensions and park its decode scans in the returned carry."""
    return _piggyback_extend(params, cfg, prefix, prefix_mask, sfx_a,
                             sfx_a_mask, sfx_b, sfx_b_mask, max_new_a,
                             max_new_b, prefill_fn)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new_a", "max_new_b", "topk",
                                    "prefill_fn"),
                   donate_argnames=("carry",))
def shared_piggyback_step(params, cfg: ModelConfig, carry: PiggybackCarry,
                          prefix, prefix_mask, sfx_a, sfx_a_mask, sfx_b,
                          sfx_b_mask, yes_ids, no_ids, digit_ids,
                          digit_vals, max_new_a: int, max_new_b: int,
                          topk: int = 20, stop_mask_a=None,
                          stop_mask_b=None, eos_id=None, prefill_fn=None):
    """One piggybacked call: the PARKED dispatch's pending decode scans and
    the NEXT dispatch's prefill + suffix extensions run in ONE XLA
    program. ``yes_ids``/``no_ids`` (and the stop tables) belong to the
    parked dispatch; the chain's shapes/budgets are identical by
    construction (the scheduler only chains same-shape dispatches), so
    the new carry reuses the donated old one's buffers. Returns
    (parked binary out, parked confidence out, new carry)."""
    B, S = prefix.shape
    S2a, S2b = sfx_a.shape[1], sfx_b.shape[1]
    out_a, out_b = _piggyback_scan(
        params, cfg, carry, yes_ids, no_ids, digit_ids, digit_vals,
        S + S2a, S + S2a + max_new_a + S2b, max_new_a, max_new_b, topk,
        stop_mask_a, stop_mask_b, eos_id)
    new_carry = _piggyback_extend(params, cfg, prefix, prefix_mask, sfx_a,
                                  sfx_a_mask, sfx_b, sfx_b_mask, max_new_a,
                                  max_new_b, prefill_fn)
    return out_a, out_b, new_carry


@functools.partial(jax.jit,
                   static_argnames=("cfg", "slot0_a", "slot0_b", "max_new_a",
                                    "max_new_b", "topk"),
                   donate_argnames=("carry",))
def shared_piggyback_drain(params, cfg: ModelConfig, carry: PiggybackCarry,
                           yes_ids, no_ids, digit_ids, digit_vals,
                           slot0_a: int, slot0_b: int, max_new_a: int,
                           max_new_b: int, topk: int = 20,
                           stop_mask_a=None, stop_mask_b=None, eos_id=None):
    """Close a piggyback chain: run the last parked dispatch's decode scans
    alone (no prefill rides along — the chain is over)."""
    return _piggyback_scan(params, cfg, carry, yes_ids, no_ids, digit_ids,
                           digit_vals, slot0_a, slot0_b, max_new_a,
                           max_new_b, topk, stop_mask_a, stop_mask_b,
                           eos_id)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new_a", "max_new_b", "topk",
                                    "prefill_fn", "return_cache"),
                   donate_argnames=("scratch_cache",))
def greedy_decode_fused_shared(params, cfg: ModelConfig, prefix: jax.Array,
                               prefix_mask: jax.Array, sfx_a: jax.Array,
                               sfx_a_mask: jax.Array, sfx_b: jax.Array,
                               sfx_b_mask: jax.Array, yes_ids: jax.Array,
                               no_ids: jax.Array, digit_ids: jax.Array,
                               digit_vals: jax.Array, max_new_a: int,
                               max_new_b: int, topk: int = 20,
                               prefill_fn=None, stop_mask_b: jax.Array = None,
                               stop_mask_a: jax.Array = None,
                               eos_id: jax.Array = None,
                               return_cache: bool = False,
                               scratch_cache=None):
    """TWO fused greedy decodes sharing ONE prefill over a common prefix.

    The perturbation sweep scores every grid cell under two formats whose
    prompts differ only in a short trailing instruction (the rephrased legal
    text is shared — perturb_prompts.py:728-734). The reference pays two
    full forward passes per cell; here the shared prefix (B, S) RIGHT-padded
    (slot == token position — the canonical layout that lets the
    cross-request prefix cache reuse this prefill's KV pages bitwise
    across rows of different lengths; pads are masked no-ops either way)
    is prefilled once, then each format's suffix (B, S2*) RIGHT-padded is
    run through a teacher-forced chunked-prefill extension
    (decoder.extend) at ~S2/S of the prefill cost, followed by the fused
    greedy scan. Device work per cell drops from 2 prefills to ~1.

    Branch B consumes branch A's final cache buffer on purpose: A's suffix
    and generated slots are overwritten/masked (branch B's cache_mask shows
    only prefix + its own suffix), so XLA can alias the cache update
    in place instead of holding two full KV caches live.

    Returns (binary FusedDecodeOut, confidence FusedDecodeOut); the
    confidence branch gets the digit table, the binary branch skips it.
    ``return_cache=True`` appends the final KV cache to the return value;
    ``scratch_cache`` (DONATED) accepts the previous same-shape dispatch's
    cache so XLA writes this one into the same HBM block — one buffer per
    (bucket, batch) shape for a whole sweep instead of an alloc/free per
    dispatch (runner._CacheHandoff). Results never depend on the scratch
    contents: prefill overwrites every slot and attention is masked by
    the cache masks regardless.
    """
    del scratch_cache  # donated scratch: memory reuse only, never read
    B, S = prefix.shape
    S2a, S2b = sfx_a.shape[1], sfx_b.shape[1]
    T0 = S + max(S2a + max_new_a, S2b + max_new_b)
    pf = prefill_fn or decoder.prefill
    _, cache, _ = pf(params, cfg, prefix, prefix_mask, T0)

    empty_ids = jnp.zeros((0,), jnp.int32)
    empty_vals = jnp.zeros((0,), jnp.float32)

    def branch(cache_in, sfx, sfx_mask, new_tokens, d_ids, d_vals,
               stop_mask=None):
        S2 = sfx.shape[1]
        cm = jnp.concatenate(
            [prefix_mask, sfx_mask,
             jnp.zeros((B, T0 - S - S2), prefix_mask.dtype)], axis=1)
        logits_l, cache2, pos = decoder.extend(
            params, cfg, cache_in, sfx, sfx_mask, cm, S)
        return _fused_tail(params, cfg, logits_l, cache2, cm, pos, S + S2,
                           yes_ids, no_ids, d_ids, d_vals, new_tokens, topk,
                           stop_mask=stop_mask, eos_id=eos_id)

    # The binary branch (A) takes, when provided, the EOS-only stop
    # (tokens.eos_only_stop_classes: all-transparent classes reduce the
    # done rule to emit == eos) — its numeric readout is position 0 and
    # its response text is EOS-trimmed downstream, so skipped trailing
    # steps are pure EOS fill.
    out_a, cache_a = branch(cache, sfx_a, sfx_a_mask, max_new_a,
                            empty_ids, empty_vals, stop_mask=stop_mask_a)
    # The confidence branch (B) takes the digit table and, when provided,
    # the digit early stop — only its first complete integer is read.
    out_b, cache_b = branch(cache_a, sfx_b, sfx_b_mask, max_new_b,
                            digit_ids, digit_vals, stop_mask=stop_mask_b)
    if return_cache:
        return out_a, out_b, cache_b
    return out_a, out_b


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new_tokens", "prefill_fn"))
def greedy_decode(params, cfg: ModelConfig, tokens: jax.Array,
                  attn_mask: jax.Array, max_new_tokens: int = 50,
                  prefill_fn=None) -> Tuple[jax.Array, jax.Array]:
    """tokens/attn_mask: (B, S) LEFT-padded int32.

    Returns (generated (B, max_new_tokens) int32,
             step_logits (B, max_new_tokens, V) fp32)."""
    B, S = tokens.shape
    T = S + max_new_tokens
    pf = prefill_fn or decoder.prefill
    logits0, cache, pos0 = pf(params, cfg, tokens, attn_mask, T)

    cache_mask0 = jnp.pad(attn_mask, ((0, 0), (0, max_new_tokens)))

    def step(carry, t):
        logits, cache, cache_mask = carry
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        cache_mask = cache_mask.at[:, S + t].set(1)
        new_logits, cache = decoder.decode_step(
            params, cfg, cache, nxt, pos0 + t, S + t, cache_mask)
        return (new_logits, cache, cache_mask), (nxt, logits)

    (_, _, _), (gen, step_logits) = lax.scan(
        step, (logits0, cache, cache_mask0), jnp.arange(max_new_tokens))
    # scan stacks on axis 0 -> (T_new, B, ...); put batch first.
    return jnp.swapaxes(gen, 0, 1), jnp.swapaxes(step_logits, 0, 1)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "max_new_tokens", "prefill_fn"))
def sample_decode(params, cfg: ModelConfig, tokens: jax.Array,
                  attn_mask: jax.Array, key: jax.Array,
                  temperature: float = 0.9, max_new_tokens: int = 50,
                  prefill_fn=None, eos_id: jax.Array = None) -> jax.Array:
    """Temperature sampling with the same prefill + lax.scan structure as
    greedy_decode, for the on-pod perturbation generator (the reference
    rephrases with temperature 0.9 via the Anthropic API,
    perturb_prompts.py:799-809; here the sampler runs on the local model).

    ``key`` is either one PRNG key (a fresh subkey per step; a row's draws
    then depend on its batch position) or per-row keys shaped (B, 2) — each
    row gets its own stream folded per step, so a row's sample depends ONLY
    on its key, not on which batch it rides in (resume-deterministic
    reasoning sweeps key rows by grid-cell identity).

    ``eos_id`` arms the HF-generate-parity stop: a row emits EOS fill
    after its first EOS (no post-EOS samples leak into text, matching the
    API/HF semantics the reference relies on), and once EVERY row is done
    the remaining scan steps skip the model forward via a scalar
    lax.cond — a generous session budget then costs actual response
    length. Non-done rows' draws are bit-identical to the unstopped
    sampler (the per-step keys never depend on doneness).

    Returns generated (B, max_new_tokens) int32. Per-step logits are not
    captured — rephrasings need text only, and dropping the (B, T, V) stack
    keeps HBM free for long sample runs."""
    B, S = tokens.shape
    T = S + max_new_tokens
    per_row = is_per_row_keys(key)
    early = eos_id is not None
    pf = prefill_fn or decoder.prefill
    logits0, cache, pos0 = pf(params, cfg, tokens, attn_mask, T)
    cache_mask0 = jnp.pad(attn_mask, ((0, 0), (0, max_new_tokens)))

    def step(carry, xs):
        logits, cache, cache_mask, done = carry
        t, step_key = xs
        scaled = logits / jnp.maximum(temperature, 1e-6)
        if per_row:
            nxt = jax.vmap(jax.random.categorical)(step_key, scaled)
        else:
            nxt = jax.random.categorical(step_key, scaled, axis=-1)
        nxt = nxt.astype(jnp.int32)
        if early:
            emit = jnp.where(done, eos_id, nxt)
            done = done | (emit == eos_id)
            all_done = jnp.all(done)
            step_mask = cache_mask.at[:, S + t].set(1)

            def run(args):
                lg, c = args
                return decoder.decode_step(
                    params, cfg, c, emit, pos0 + t, S + t, step_mask)

            new_logits, cache = lax.cond(
                all_done, lambda args: args, run, (logits, cache))
            cache_mask = jnp.where(all_done, cache_mask, step_mask)
        else:
            emit = nxt
            cache_mask = cache_mask.at[:, S + t].set(1)
            new_logits, cache = decoder.decode_step(
                params, cfg, cache, emit, pos0 + t, S + t, cache_mask)
        return (new_logits, cache, cache_mask, done), emit

    if per_row:
        # (T, B, 2): row b's stream at step t = fold_in(keys[b], t).
        keys = jax.vmap(
            lambda t: jax.vmap(lambda k: jax.random.fold_in(k, t))(key)
        )(jnp.arange(max_new_tokens))
    else:
        keys = jax.random.split(key, max_new_tokens)
    (_, _, _, _), gen = lax.scan(
        step, (logits0, cache, cache_mask0, jnp.zeros((B,), bool)),
        (jnp.arange(max_new_tokens), keys))
    return jnp.swapaxes(gen, 0, 1)


@functools.partial(jax.jit, static_argnames=("cfg", "max_new_tokens"))
def t5_greedy_decode(params, cfg: T5Config, enc_tokens: jax.Array,
                     enc_mask: jax.Array, max_new_tokens: int = 50
                     ) -> Tuple[jax.Array, jax.Array]:
    """Encoder-decoder greedy decode (reference Seq2Seq branch,
    compare_base_vs_instruct.py:203-241).

    Re-runs the (tiny) decoder stack over a fixed (B, max_new) buffer each
    step — sequences here are ≤50 tokens so a KV cache buys nothing.
    Returns (generated (B, max_new), step_logits (B, max_new, V) fp32)."""
    B = enc_tokens.shape[0]
    enc_out = encdec.encode(params, cfg, enc_tokens, enc_mask)

    dec_buf0 = jnp.full((B, max_new_tokens + 1), cfg.decoder_start_token_id,
                        dtype=jnp.int32)
    mask0 = jnp.zeros((B, max_new_tokens + 1), jnp.int32).at[:, 0].set(1)

    def step(carry, t):
        dec_buf, mask = carry
        logits = encdec.decode(params, cfg, enc_out, enc_mask, dec_buf, mask)
        # Logits at the last valid position (= t).
        step_logits = jnp.take_along_axis(
            logits, t[None, None, None].repeat(B, 0), axis=1)[:, 0, :]
        nxt = jnp.argmax(step_logits, axis=-1).astype(jnp.int32)
        dec_buf = dec_buf.at[:, t + 1].set(nxt)
        mask = mask.at[:, t + 1].set(1)
        return (dec_buf, mask), (nxt, step_logits)

    (_, _), (gen, step_logits) = lax.scan(
        step, (dec_buf0, mask0), jnp.arange(max_new_tokens))
    return jnp.swapaxes(gen, 0, 1), jnp.swapaxes(step_logits, 0, 1)
