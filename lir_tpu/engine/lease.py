"""Lease-based work-stealing sweep shards (ROADMAP item 1, offline
half).

Static ``multihost.host_shard`` partitioning has a failure mode the
PR-5 liveness machinery only *detects*: a slow host strangles the shard
fence (every fast host idles at the barrier), and a dead host's shard
is simply lost until an operator relaunches. This module converts
statically partitioned shards into LEASED shards:

- the pending grid is split into small shards
  (:func:`partition_shards`);
- shard ownership is a lease record — ``{holder, expiry, seq, done}``
  — riding the PR-9 manifest machinery's ``{"__meta__": ...}`` lines
  in a SHARED ``<results>.leases.jsonl`` log (one file all hosts
  append; the SweepManifest append discipline — single fsync'd write,
  torn trailing line tolerated and truncated on the next append —
  carries over verbatim, so a kill mid-claim leaves a resumable log);
- a holder RENEWS its lease at every manifest flush
  (:meth:`LeaseManager.attach_manifest` — renew-on-flush), so "alive"
  means "making durable progress", not merely "process exists";
- expiry is WALL-CLOCK (``time.time``): leases compare across hosts,
  and wall time is the only clock hosts share. (The serve-side
  breakers are the opposite case — per-process cooldowns on
  ``time.monotonic``; see faults/breaker.py.)
- a live host that runs out of unclaimed shards STEALS shards whose
  lease expired (holder dead or straggling) — and because PR 9's
  slot-scatter folds are idempotent, the stolen shard's re-scored rows
  land bitwise on the same accumulator cells, so the fence merge
  (``stats/streaming.merge_accums(..., allow_identical_overlap=True)``)
  still produces a lattice bitwise-identical to an uninterrupted
  static run (pinned by tests/test_lease.py and bench.py's "elastic"
  key).

Single-process runs degrade cleanly: one holder claims every shard in
order, and the lease log doubles as a shard-progress record.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from ..utils.logging import get_logger
from ..utils.manifest import SweepManifest
from ..utils.profiling import LeaseStats

log = get_logger(__name__)

LEASE_SUFFIX = ".leases.jsonl"
LEASE_PREFIX = "lease:"

# The lease log is a SweepManifest used for its __meta__ machinery
# only; ordinary done-lines never appear, but the class needs key
# fields to construct.
_LEASE_KEY_FIELDS = ("shard",)


def partition_shards(cells: Sequence, cells_per_shard: int,
                     n_holders: int = 1) -> List[List]:
    """Split the pending cell list into contiguous shards of
    ``cells_per_shard`` cells (the stealing granularity). ``<= 0``
    derives ~4 shards per holder so every host has steal targets
    without the lease log dominating."""
    cells = list(cells)
    if not cells:
        return []
    if cells_per_shard <= 0:
        cells_per_shard = max(1, len(cells) // max(4 * n_holders, 1))
    return [cells[i:i + cells_per_shard]
            for i in range(0, len(cells), cells_per_shard)]


class LeaseManager:
    """One holder's view of the shared shard-lease log.

    Thread discipline: one sweep thread per holder drives it (claims,
    renews, steals); the only cross-thread caller is the manifest-flush
    wrapper installed by :meth:`attach_manifest`, which runs on the
    sweep writer thread — renews are therefore internally idempotent
    and cheap. Cross-HOST concurrency is resolved by the log itself:
    every decision re-reads the log first (:meth:`refresh`), and the
    append order on a shared filesystem arbitrates near-simultaneous
    claims (last write wins, seq strictly increases — the loser's next
    renew sees a foreign live lease and reports the lease LOST rather
    than continuing blind).
    """

    def __init__(self, path, holder: str, ttl_s: float = 300.0,
                 clock=time.time, stats: Optional[LeaseStats] = None):
        self.path = Path(path)
        self.holder = str(holder)
        self.ttl_s = float(ttl_s)
        self.clock = clock
        self.stats = stats if stats is not None else LeaseStats()
        self.held: set = set()       # shard ids this holder believes it owns
        self.n_shards: Optional[int] = None
        self._man = SweepManifest(self.path, _LEASE_KEY_FIELDS)

    # -- the log -------------------------------------------------------------

    def refresh(self) -> None:
        """Re-read the shared log (another host may have appended).
        A fresh SweepManifest parse keeps the torn-tail tolerance: a
        kill mid-append leaves a fragment the next parse skips and the
        next append truncates."""
        self._man = SweepManifest(self.path, _LEASE_KEY_FIELDS)
        self.stats.count("refreshes")

    def record(self, shard_id: int) -> Optional[Dict]:
        rec = self._man.meta.get(f"{LEASE_PREFIX}{int(shard_id)}")
        return dict(rec) if isinstance(rec, dict) else None

    def _write(self, shard_id: int, expiry: float, seq: int,
               done: bool = False) -> None:
        self._man.set_meta(f"{LEASE_PREFIX}{int(shard_id)}", {
            "holder": self.holder, "expiry": float(expiry),
            "seq": int(seq), "done": bool(done)})

    def expired(self, rec: Dict) -> bool:
        return float(rec.get("expiry", 0.0)) <= self.clock()

    def register_shards(self, n: int) -> None:
        self.n_shards = int(n)

    # -- claim / renew / steal / release -------------------------------------

    def claim(self, shard_id: int, steal: bool = False) -> bool:
        """Take the shard's lease. Refused (False) when the shard is
        done, or another holder's lease is still LIVE (double-claim
        refusal). An expired foreign lease needs ``steal=True`` — the
        explicit work-stealing event, counted separately."""
        self.refresh()
        rec = self.record(shard_id)
        now = self.clock()
        if rec is None:
            self._write(shard_id, now + self.ttl_s, 0)
            self.held.add(int(shard_id))
            self.stats.count("claims")
            return True
        if rec.get("done"):
            return False
        foreign = rec.get("holder") != self.holder
        if foreign and not self.expired(rec):
            self.stats.count("refused")
            return False
        if foreign:
            self.stats.count("expired_seen")
            if not steal:
                self.stats.count("refused")
                return False
            from ..observe import tracing

            tracing.add_span("lease/steal", now, self.clock(),
                             shard=int(shard_id),
                             frm=str(rec.get("holder")))
            self.stats.count("steals")
            log.warning("lease: stealing shard %d from %s (lease "
                        "expired %.1fs ago)", shard_id, rec.get("holder"),
                        now - float(rec.get("expiry", 0.0)))
        else:
            self.stats.count("claims")
        self._write(shard_id, now + self.ttl_s,
                    int(rec.get("seq", 0)) + 1)
        self.held.add(int(shard_id))
        return True

    def renew(self, shard_id: int) -> bool:
        """Extend a held lease (called at flush boundaries). Returns
        False — and drops the shard from ``held`` — when the lease was
        stolen out from under this holder (it expired and a live host
        took it): the holder should stop spending device time on a
        shard it no longer owns (its folds so far are harmless —
        bitwise no-ops under the idempotent lattice)."""
        self.refresh()
        rec = self.record(shard_id)
        now = self.clock()
        if rec is not None and rec.get("holder") != self.holder \
                and not self.expired(rec):
            self.stats.count("lost")
            self.held.discard(int(shard_id))
            log.warning("lease: shard %d lost to %s (stolen after "
                        "expiry); abandoning it", shard_id,
                        rec.get("holder"))
            return False
        self._write(shard_id, now + self.ttl_s,
                    int((rec or {}).get("seq", 0)) + 1)
        self.held.add(int(shard_id))
        self.stats.count("renews")
        return True

    def renew_held(self) -> None:
        """Renew every held lease — the renew-on-flush hook."""
        for sid in sorted(self.held):
            self.renew(sid)

    def mark_done(self, shard_id: int) -> None:
        """Shard completed and durably flushed: the done record is the
        cross-host skip signal (a done shard is never claimable or
        stealable again)."""
        self._write(shard_id, self.clock(),
                    int((self.record(shard_id) or {}).get("seq", 0)) + 1,
                    done=True)
        self.held.discard(int(shard_id))
        self.stats.count("releases")
        self.stats.count("shards_done")

    def is_done(self, shard_id: int) -> bool:
        rec = self.record(shard_id)
        return bool(rec and rec.get("done"))

    def all_done(self, n_shards: Optional[int] = None) -> bool:
        n = self.n_shards if n_shards is None else int(n_shards)
        assert n is not None, "register_shards first"
        self.refresh()
        return all(self.is_done(s) for s in range(n))

    # -- iteration (the sweep driver's loop) ---------------------------------

    def claim_loop(self, shards: Sequence[Sequence]
                   ) -> Iterator[Tuple[int, Sequence]]:
        """Yield ``(shard_id, cells)`` for every shard this holder can
        take — unclaimed/own shards plus steals of expired foreign
        leases — repeated until nothing is claimable (remaining shards
        are done or held live elsewhere; the lease-aware fence owns
        waiting on those). Each holder scans from its own stable offset
        so simultaneously-starting hosts spread over the shard list
        instead of racing the same first claim."""
        import hashlib

        self.register_shards(len(shards))
        n = len(shards)
        if n == 0:
            return
        start = int(hashlib.md5(self.holder.encode()).hexdigest(),
                    16) % n
        order = list(range(start, n)) + list(range(0, start))
        while True:
            progressed = False
            for sid in order:
                if sid in self.held or self.is_done(sid):
                    continue
                if self.claim(sid, steal=True):
                    progressed = True
                    yield sid, shards[sid]
            if not progressed:
                return

    def steal_expired(self, shards: Sequence[Sequence]
                      ) -> Optional[Tuple[int, Sequence]]:
        """One steal attempt (the lease-aware fence's work unit):
        claim the first not-done shard whose lease is expired (or was
        never claimed). None when every remaining shard is held live."""
        self.refresh()
        for sid, cells in enumerate(shards):
            if self.is_done(sid) or sid in self.held:
                continue
            if self.claim(sid, steal=True):
                return sid, cells
        return None

    # -- renew-on-flush ------------------------------------------------------

    def attach_manifest(self, manifest) -> None:
        """Wrap the sweep manifest's ``mark_done_many`` so every flush
        (rows durably appended + marked) renews the held leases —
        progress IS the heartbeat."""
        inner = manifest.mark_done_many

        def marked(records):
            inner(records)
            self.renew_held()

        manifest.mark_done_many = marked
