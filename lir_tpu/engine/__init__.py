"""Inference engine: batched scoring, greedy decode, grid + sweep drivers."""

from .fleet import ModelFleet  # noqa: F401
from .runner import PromptScore, ScoringEngine  # noqa: F401
from .score import YesNoScores, readout_from_step_logits, weighted_confidence  # noqa: F401
from .sweep import run_perturbation_sweep, run_word_meaning_sweep  # noqa: F401
from .multi import (  # noqa: F401
    ModelSpec,
    base_instruct_pairs,
    run_model_comparison_sweep,
)
from .rephrase import (  # noqa: F401
    load_or_generate_perturbations,
    parse_numbered_rephrasings,
    rephraser_from_engine,
)
