"""Batched scoring engine: the TPU replacement for both reference backends.

Where the reference loops prompts one at a time through
``model.generate(output_scores=True)`` (compare_base_vs_instruct.py:458-492)
or ships them to the OpenAI Batch API (perturb_prompts.py:551-726), this
engine packs ragged prompts into fixed-shape left-padded batches, runs ONE
jitted greedy-decode-with-capture per batch (sharded over the device mesh),
and applies the C13 readout vectorized over the batch.

Static-shape discipline: prompts are bucketed by token length and the batch
axis padded to ``batch_size``, so XLA compiles once per (bucket, batch_size)
pair and every subsequent batch reuses the cache.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import RuntimeConfig
from . import generate, score, tokens as tok


@dataclasses.dataclass
class PromptScore:
    """One prompt's raw measurement. Sweep drivers wrap this into
    data/schemas.py records (which add model identity and D1/D2 semantics)."""

    prompt: str
    completion: str
    yes_prob: float
    no_prob: float
    yes_logprob: float
    no_logprob: float
    odds_ratio: float
    relative_prob: float
    position_found: int
    yes_no_found: bool


class ScoringEngine:
    """Holds (params, cfg, tokenizer) and the jitted decode path.

    ``encoder_decoder=True`` routes through the T5 branch (reference routing
    rule compare_instruct_models.py:471-475).
    """

    def __init__(self, params: Any, cfg: Any, tokenizer: Any,
                 runtime: Optional[RuntimeConfig] = None,
                 encoder_decoder: bool = False,
                 yes_text: str = "Yes", no_text: str = "No"):
        self.params = params
        self.cfg = cfg
        self.tokenizer = tokenizer
        self.rt = runtime or RuntimeConfig()
        self.encoder_decoder = encoder_decoder
        self.yes_id, self.no_id = tok.yes_no_ids(
            tokenizer, encoder_decoder=encoder_decoder,
            yes_text=yes_text, no_text=no_text)
        self.eos_id = getattr(tokenizer, "eos_token_id", None)
        # Length buckets: powers of two up to max_seq_len (≲700-token prompts).
        self.buckets = [b for b in (64, 128, 256, 512, 1024)
                        if b <= self.rt.max_seq_len] or [self.rt.max_seq_len]
        self._digit_table: Optional[Tuple[np.ndarray, np.ndarray]] = None

    @property
    def digit_table(self) -> Tuple[np.ndarray, np.ndarray]:
        """(token ids, values) of single-token integers 0..100, resolved
        once per tokenizer (feeds the weighted-confidence readout)."""
        if self._digit_table is None:
            self._digit_table = tok.integer_token_table(self.tokenizer)
        return self._digit_table

    # -- building blocks ----------------------------------------------------

    def decode_prompts(self, prompts: Sequence[str]
                       ) -> Tuple[jax.Array, jax.Array]:
        """Tokenize once, left-pad into the smallest fitting bucket, run one
        jitted greedy decode. Returns (generated (B, T_new) int32,
        step_logits (B, T_new, V) fp32)."""
        ids_list = [self.tokenizer(p).input_ids for p in prompts]
        bucket = tok.pick_bucket([len(i) for i in ids_list], self.buckets)
        toks_arr, mask = tok.left_pad_ids(ids_list, bucket,
                                          tok.pad_token_id(self.tokenizer))
        if self.encoder_decoder:
            return generate.t5_greedy_decode(
                self.params, self.cfg, jnp.asarray(toks_arr), jnp.asarray(mask),
                max_new_tokens=self.rt.max_new_tokens)
        return generate.greedy_decode(
            self.params, self.cfg, jnp.asarray(toks_arr), jnp.asarray(mask),
            max_new_tokens=self.rt.max_new_tokens)

    def decode_fused(self, prompts: Sequence[str], yes_ids: np.ndarray,
                     no_ids: np.ndarray, with_digits: bool = False):
        """The production scoring path: one jitted decode with the C13/D6
        readouts fused into the scan (no (B, T, V) logit stack). Decoder-only
        models only; T5 keeps the capture path (tiny vocab stacks)."""
        assert not self.encoder_decoder
        ids_list = [self.tokenizer(p).input_ids for p in prompts]
        bucket = tok.pick_bucket([len(i) for i in ids_list], self.buckets)
        toks_arr, mask = tok.left_pad_ids(ids_list, bucket,
                                          tok.pad_token_id(self.tokenizer))
        if with_digits:
            digit_ids, digit_vals = self.digit_table
        else:
            digit_ids = np.zeros((0,), np.int32)
            digit_vals = np.zeros((0,), np.float32)
        return generate.greedy_decode_fused(
            self.params, self.cfg, jnp.asarray(toks_arr), jnp.asarray(mask),
            jnp.asarray(yes_ids, jnp.int32), jnp.asarray(no_ids, jnp.int32),
            jnp.asarray(digit_ids), jnp.asarray(digit_vals),
            max_new_tokens=self.rt.max_new_tokens)

    def decode_completion(self, generated_ids: np.ndarray) -> str:
        """Token ids -> text, stopping at the first EOS (HF generate parity —
        the fixed-length jitted decode keeps emitting after EOS; those tokens
        must not leak into response text or the confidence-integer parse)."""
        trimmed = tok.trim_at_eos(np.asarray(generated_ids).tolist(), self.eos_id)
        return self.tokenizer.decode(trimmed, skip_special_tokens=True).strip()

    # -- public API ---------------------------------------------------------

    def score_prompts(self, prompts: Sequence[str]) -> List[PromptScore]:
        """Score every prompt; one jitted call per full batch."""
        order = np.argsort([len(p) for p in prompts], kind="stable")
        rows: List[Optional[PromptScore]] = [None] * len(prompts)
        B = self.rt.batch_size
        for start in range(0, len(order), B):
            idx = order[start:start + B]
            batch_prompts = [prompts[i] for i in idx]
            rows_out = self._score_batch(batch_prompts)
            for i, r in zip(idx, rows_out):
                rows[i] = r
        return rows  # type: ignore[return-value]

    def _score_batch(self, batch_prompts: List[str]) -> List[PromptScore]:
        n = len(batch_prompts)
        B = self.rt.batch_size
        padded_prompts = batch_prompts + [batch_prompts[-1]] * (B - n)

        if self.encoder_decoder:
            gen, step_logits = self.decode_prompts(padded_prompts)
            res = score.readout_from_step_logits(
                step_logits, gen, jnp.int32(self.yes_id),
                jnp.int32(self.no_id), scan_positions=self.rt.scan_positions)
        else:
            yes_ids = np.full((B,), self.yes_id, np.int32)
            no_ids = np.full((B,), self.no_id, np.int32)
            fused = self.decode_fused(padded_prompts, yes_ids, no_ids)
            res = score.readout_from_fused(
                fused, jnp.asarray(yes_ids), jnp.asarray(no_ids),
                scan_positions=self.rt.scan_positions)

        res = jax.device_get(res)
        out = []
        for j in range(n):
            out.append(PromptScore(
                prompt=batch_prompts[j],
                completion=self.decode_completion(res.generated[j]),
                yes_prob=float(res.yes_prob[j]),
                no_prob=float(res.no_prob[j]),
                yes_logprob=float(res.yes_logprob[j]),
                no_logprob=float(res.no_logprob[j]),
                odds_ratio=float(res.odds_ratio[j]),
                relative_prob=float(res.relative_prob[j]),
                position_found=int(res.position_found[j]),
                yes_no_found=bool(res.yes_no_found[j]),
            ))
        return out
