"""Batched scoring engine: the TPU replacement for both reference backends.

Where the reference loops prompts one at a time through
``model.generate(output_scores=True)`` (compare_base_vs_instruct.py:458-492)
or ships them to the OpenAI Batch API (perturb_prompts.py:551-726), this
engine packs ragged prompts into fixed-shape left-padded batches, runs ONE
jitted greedy-decode-with-capture per batch (sharded over the device mesh),
and applies the C13 readout vectorized over the batch.

Static-shape discipline: prompts are bucketed by token length and the batch
axis padded to ``batch_size``, so XLA compiles once per (bucket, batch_size)
pair and every subsequent batch reuses the cache.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import RuntimeConfig
from ..guard.watchdog import DispatchWatchdog
from ..utils.profiling import CompileStats, FaultStats, GuardStats
from . import compile_plan, generate, score, tokens as tok


def _tail_batch(n: int, cap: int) -> int:
    """Smallest power of two >= n, capped at the configured batch size."""
    b = 1
    while b < n:
        b *= 2
    return min(b, cap)


class _CacheHandoff:
    """Cross-dispatch KV-cache buffer reuse via donation.

    The fused decode entry points can return their final cache and accept
    the previous dispatch's cache as a DONATED scratch argument
    (generate: ``return_cache``/``scratch_cache``); XLA then writes the
    new dispatch's cache into the donated buffer, so one HBM block serves
    every same-shape dispatch of a bucket queue instead of an alloc/free
    per dispatch. A key change drops the old buffer (freed once its last
    dispatch completes) and the next shape bootstraps fresh. ``take()``
    removes the cache BEFORE the call so a dispatch that raises (OOM
    fallback) can never re-donate a consumed buffer.

    ``key`` must determine every cache-shape input (kind, bucket, batch,
    suffix buckets, decode budget) — the scheduler plans those per bucket
    precisely so consecutive dispatches share a key.
    """

    def __init__(self) -> None:
        self._key = None
        self._cache = None

    def take(self, key: Tuple):
        cache, k = self._cache, self._key
        self._cache = self._key = None
        return cache if k == key else None

    def put(self, key: Tuple, cache) -> None:
        self._key = key
        self._cache = cache


@dataclasses.dataclass
class PromptScore:
    """One prompt's raw measurement. Sweep drivers wrap this into
    data/schemas.py records (which add model identity and D1/D2 semantics)."""

    prompt: str
    completion: str
    yes_prob: float
    no_prob: float
    yes_logprob: float
    no_logprob: float
    odds_ratio: float
    relative_prob: float
    position_found: int
    yes_no_found: bool


@dataclasses.dataclass
class SampledScore:
    """n-run count-averaged measurement (reasoning-model mode,
    perturb_prompts.py:412-446): probabilities are answer-count fractions,
    not logit softmaxes."""

    prompt: str
    response: str               # most common run text
    all_responses: List[str]
    token_1_prob: float
    token_2_prob: float
    odds_ratio: float


class ScoringEngine:
    """Holds (params, cfg, tokenizer) and the jitted decode path.

    ``encoder_decoder=True`` routes through the T5 branch (reference routing
    rule compare_instruct_models.py:471-475).
    """

    def __init__(self, params: Any, cfg: Any, tokenizer: Any,
                 runtime: Optional[RuntimeConfig] = None,
                 encoder_decoder: bool = False,
                 yes_text: str = "Yes", no_text: str = "No",
                 seq_mesh: Any = None, seq_impl: str = "ring"):
        self.params = params
        self.cfg = cfg
        self.tokenizer = tokenizer
        self.rt = runtime or RuntimeConfig()
        self.encoder_decoder = encoder_decoder
        # Sequence-parallel prefill (long-context path): with a mesh whose
        # `seq` axis > 1, the quadratic prompt phase runs seq-sharded
        # through ring/Ulysses attention (parallel/seq_forward) and hands
        # the KV cache back unsharded for ordinary dense decode. Built ONCE
        # here so the jitted decode fns cache on a stable static callable.
        self._prefill_fn = None
        if seq_mesh is not None and not encoder_decoder:
            from ..parallel.seq_forward import prefill_seq_parallel

            def _seq_prefill(p, c, t, m, T, *, _mesh=seq_mesh,
                             _impl=seq_impl):
                return prefill_seq_parallel(p, c, t, m, T, mesh=_mesh,
                                            impl=_impl)

            self._prefill_fn = _seq_prefill
        self.yes_id, self.no_id = tok.yes_no_ids(
            tokenizer, encoder_decoder=encoder_decoder,
            yes_text=yes_text, no_text=no_text)
        self.eos_id = getattr(tokenizer, "eos_token_id", None)
        # The pipelined sweep tokenizes bucket N+1 on the main thread while
        # its writer thread decodes bucket N's completions. HF fast (Rust)
        # tokenizers are NOT safe under concurrent encode/decode (encode
        # takes a write borrow for truncation/padding state -> intermittent
        # "Already borrowed" RuntimeError), so every tokenizer touch goes
        # through this lock. Contention is negligible: encode/decode are
        # each ~ms per bucket vs ~1.5 s of device work.
        self._tok_lock = threading.Lock()
        # Length buckets. With the ragged scheduler: a ~sqrt(2) ladder
        # (tokens.bucket_ladder) so short prompts prefill short shapes —
        # each edge compiles once and the scheduler keeps dispatches
        # bucket-pure. Legacy mode keeps the powers-of-two set whose
        # per-batch pick_bucket pads every mixed-length batch to its
        # longest row (the bench's single-bucket baseline).
        if self.rt.ragged_scheduler:
            self.buckets = list(tok.bucket_ladder(self.rt.max_seq_len))
        else:
            self.buckets = [b for b in (64, 128, 256, 512, 1024)
                            if b <= self.rt.max_seq_len] or [self.rt.max_seq_len]
        if getattr(cfg, "pos_embedding", None) == "learned":
            # A bucket + generation budget past the learned-position table
            # would read beyond pos_embed (gpt2/opt tables are exactly
            # max_seq_len rows): trim such buckets so a ~1000-token prompt
            # fails loudly into a smaller bucket's truncation semantics
            # instead of decoding at clipped positions.
            limit = cfg.max_seq_len - self.rt.max_new_tokens
            fitting = [b for b in self.buckets if b <= limit]
            if not fitting:
                raise ValueError(
                    f"{cfg.name}: no length bucket fits the learned-"
                    f"position table ({cfg.max_seq_len} rows) minus the "
                    f"generation budget ({self.rt.max_new_tokens}) — "
                    f"reduce max_new_tokens or max_seq_len")
            self.buckets = fitting
        self._digit_table: Optional[Tuple[np.ndarray, np.ndarray]] = None
        self._digit_stop_mask: Any = False  # False = not resolved yet
        self._eos_stop_mask: Optional[jax.Array] = None
        # Cross-dispatch KV-cache buffer reuse (donation) + the last
        # sweep's scheduler counters (profiling.OccupancyStats) — set by
        # sweep.run_perturbation_sweep, read by bench.py.
        self._handoff = _CacheHandoff()
        self.occupancy = None
        # Compile plan (engine/compile_plan.py): the sweep precompiles its
        # planned shapes into this registry; the decode entry points below
        # consult it and fall back to lazy jit on any miss. Stats record
        # per-shape compile seconds + registry/persistent-cache hit rates.
        self.compile_stats = CompileStats()
        self.exec_registry = None
        # Failure-path accounting (lir_tpu/faults): the sweep's dispatch
        # recovery and any wrapping FaultPlan count into this.
        self.fault_stats = FaultStats()
        # Guard layer (lir_tpu/guard): the dispatch watchdog (stall
        # detection priced by scheduler.bucket_cost, calibrated against
        # this engine's own dispatch rate) and the counters it shares
        # with the numerics guard and the multihost liveness barrier.
        self.guard_stats = GuardStats()
        self.watchdog = DispatchWatchdog(
            multiple=self.rt.watchdog_multiple,
            floor_s=self.rt.watchdog_floor_s, stats=self.guard_stats)
        self._seq_mesh_note = (
            None if seq_mesh is None
            else (repr(getattr(seq_mesh, "shape", seq_mesh)), seq_impl))
        self._manifest_key: Optional[str] = None

    def fresh_handoff(self) -> None:
        """Reset the cross-dispatch KV-cache donation chain. Call at the
        start of every dispatch stream (a sweep, a serving session): the
        first dispatch of each bucket then always runs the scratchless
        jit signature and later ones the donated-cache signature — the
        same two executables a warmup over the same shapes compiles, so
        steady-state timing never hits a fresh compile mid-stream."""
        self._handoff = _CacheHandoff()

    def degrade_to_lazy(self) -> None:
        """Degradation-ladder step one (lir_tpu/faults): drop the AOT
        registry so subsequent dispatches fall back to lazy jit — a
        fresh trace excludes a corrupt precompiled executable from the
        fault hypothesis — and reset the donation chain, whose scratch
        buffer a failed dispatch may have consumed or left in an
        undefined state. Both rebuild themselves on demand; the cost is
        one re-trace per shape, paid only after a real failure."""
        self.exec_registry = None
        self.fresh_handoff()

    @property
    def cache_manifest_key(self) -> str:
        """Cache key covering model config, runtime knobs, quant mode,
        mesh, and the bucket ladder (utils/compile_cache.manifest_key) —
        the namespace under which this engine's executables are planned,
        registered, and recorded in the on-disk manifest. Two engines
        differing in ANY of those inputs get different keys, so a
        registry or warmed cache can never serve a stale configuration."""
        if self._manifest_key is None:
            import jax as _jax

            from ..utils import compile_cache

            # Params fingerprint: shapes/dtypes/shardings (never values —
            # executables bind avals only, so same-shape engines with
            # different weights may share executables; differently
            # sharded or dtyped params may not).
            leaves = _jax.tree.leaves(self.params)
            params_fp = [(tuple(getattr(l, "shape", ())),
                          str(getattr(l, "dtype", type(l).__name__)),
                          str(getattr(l, "sharding", None)))
                         for l in leaves]
            self._manifest_key = compile_cache.manifest_key(
                self.cfg, self.rt, buckets=self.buckets,
                quant=compile_cache.quant_mode(self.params),
                mesh={"devices": _jax.device_count(),
                      "platform": _jax.default_backend(),
                      "seq_mesh": self._seq_mesh_note,
                      "params": params_fp})
        return self._manifest_key

    @property
    def digit_stop_mask(self) -> Optional[jax.Array]:
        """(V,) int32 surface-class device array for the confidence early
        stop (tokens.digit_stop_classes), or None when this tokenizer can't
        provide per-token strings (or has no EOS to signal the stop with) —
        callers then decode the full budget."""
        if self._digit_stop_mask is False:
            mask = None
            if self.eos_id is not None:
                with self._tok_lock:
                    m = tok.digit_stop_classes(self.tokenizer,
                                               self.cfg.vocab_size)
                if m is not None:
                    mask = jnp.asarray(m)
            self._digit_stop_mask = mask
        return self._digit_stop_mask

    @property
    def eos_stop_mask(self) -> Optional[jax.Array]:
        """(V,) all-transparent class table (tokens.eos_only_stop_classes)
        arming a pure all-rows-emitted-EOS stop on the sweep's binary
        branch. Gated on :attr:`digit_stop_mask` being available — the
        same real-tokenizer-with-EOS condition — so content-free
        tokenizers (FakeTokenizer) stay fully stop-free on BOTH branches
        and the bench's stop-OFF comparison keeps its meaning."""
        if self.digit_stop_mask is None:
            return None
        if self._eos_stop_mask is None:
            self._eos_stop_mask = jnp.asarray(
                tok.eos_only_stop_classes(self.cfg.vocab_size))
        return self._eos_stop_mask

    @property
    def digit_table(self) -> Tuple[np.ndarray, np.ndarray]:
        """(token ids, values) of single-token integers 0..100, resolved
        once per tokenizer (feeds the weighted-confidence readout)."""
        if self._digit_table is None:
            with self._tok_lock:
                if self._digit_table is None:
                    self._digit_table = tok.integer_token_table(self.tokenizer)
        return self._digit_table

    # -- building blocks ----------------------------------------------------

    def decode_prompts(self, prompts: Sequence[str]
                       ) -> Tuple[jax.Array, jax.Array]:
        """Tokenize once, left-pad into the smallest fitting bucket, run one
        jitted greedy decode. Returns (generated (B, T_new) int32,
        step_logits (B, T_new, V) fp32)."""
        toks, mask = self._pad_batch(prompts)
        if self.encoder_decoder:
            return generate.t5_greedy_decode(
                self.params, self.cfg, toks, mask,
                max_new_tokens=self.rt.max_new_tokens)
        return generate.greedy_decode(
            self.params, self.cfg, toks, mask,
            max_new_tokens=self.rt.max_new_tokens,
            prefill_fn=self._prefill_fn)

    def decode_fused(self, prompts: Sequence[str], yes_ids: np.ndarray,
                     no_ids: np.ndarray, with_digits: bool = False,
                     max_new_tokens: Optional[int] = None,
                     pretokenized: Optional[Sequence[Sequence[int]]] = None,
                     early_stop: bool = False, eos_stop: bool = False):
        """The production scoring path: one jitted decode with the C13/D6
        readouts fused into the scan (no (B, T, V) logit stack). Decoder-only
        models only; T5 keeps the capture path (tiny vocab stacks).

        ``max_new_tokens`` overrides the runtime default (the perturbation
        sweep passes its short per-cell budget, config.RuntimeConfig).
        ``pretokenized`` skips tokenization when the caller already holds
        the token ids (the shared-prefix fallback path). ``early_stop``
        enables the confidence digit early stop (generate._fused_tail);
        ``eos_stop`` the pure all-rows-emitted-EOS stop instead
        (:attr:`eos_stop_mask` — the sweep's binary branch). Both are
        gated on tokenizer support and only valid for calls whose
        downstream readout is position-0 (+ first-integer parse for the
        digit variant)."""
        assert not self.encoder_decoder
        assert not (early_stop and eos_stop), "pick one stop rule"
        toks, mask = self._pad_batch(prompts, pretokenized)
        if with_digits:
            digit_ids, digit_vals = self.digit_table
        else:
            digit_ids = np.zeros((0,), np.int32)
            digit_vals = np.zeros((0,), np.float32)
        stop_mask = (self.digit_stop_mask if early_stop
                     else self.eos_stop_mask if eos_stop else None)
        return generate.greedy_decode_fused(
            self.params, self.cfg, toks, mask,
            jnp.asarray(yes_ids, jnp.int32), jnp.asarray(no_ids, jnp.int32),
            jnp.asarray(digit_ids), jnp.asarray(digit_vals),
            max_new_tokens=(self.rt.max_new_tokens if max_new_tokens is None
                            else max_new_tokens),
            prefill_fn=self._prefill_fn, stop_mask=stop_mask,
            eos_id=(None if stop_mask is None
                    else jnp.int32(self.eos_id)))

    def decode_fused_shared(self, binary_prompts: Sequence[str],
                            confidence_prompts: Sequence[str],
                            yes_ids: np.ndarray, no_ids: np.ndarray,
                            new_tokens: int, conf_tokens: int,
                            early_stop: bool = False,
                            pretokenized_a: Optional[Sequence[Sequence[int]]] = None,
                            pretokenized_b: Optional[Sequence[Sequence[int]]] = None,
                            bucket: Optional[int] = None,
                            sfx_buckets_ab: Optional[Tuple[int, int]] = None,
                            reuse_cache: bool = False):
        """Score BOTH sweep formats with ONE shared-prefix prefill.

        Each grid cell's binary and confidence prompts share the long
        rephrased legal text and differ only in the short trailing format
        instruction. Tokenize both, split every row at the longest common
        TOKEN prefix (tokenizer-agnostic — see tokens.shared_prefix_len),
        left-pad the prefixes into the standard bucket and right-pad each
        format's suffix into a small power-of-two bucket, then run
        generate.greedy_decode_fused_shared: one prefill + two chunked
        suffix extensions instead of two full prefills. Returns
        (binary FusedDecodeOut, confidence FusedDecodeOut).

        The ragged scheduler passes ``pretokenized_a/b`` (cells were
        tokenized once at planning time), an explicit prefix ``bucket``
        and per-bucket ``sfx_buckets_ab`` (shape stability across a
        bucket queue), and ``reuse_cache=True`` to thread the KV cache
        buffer through the dispatch chain via donation (_CacheHandoff).
        The fallback guards below still apply and win over the overrides.
        """
        assert not self.encoder_decoder
        if pretokenized_a is not None:
            bin_ids = [list(i) for i in pretokenized_a]
            conf_ids = [list(i) for i in pretokenized_b]
        else:
            with self._tok_lock:
                bin_ids = [self.tokenizer(p).input_ids
                           for p in binary_prompts]
                conf_ids = [self.tokenizer(p).input_ids
                            for p in confidence_prompts]
        lcp = [tok.shared_prefix_len(a, b)
               for a, b in zip(bin_ids, conf_ids)]
        pad_id = tok.pad_token_id(self.tokenizer)
        sfx_buckets = (8, 16, 32, 64, 128, 256)
        sfx_a_ids = [a[n:] for a, n in zip(bin_ids, lcp)]
        sfx_b_ids = [b[n:] for b, n in zip(conf_ids, lcp)]
        max_sfx = max(len(s) for s in sfx_a_ids + sfx_b_ids)
        max_total = max(len(r) for r in bin_ids + conf_ids)
        if bucket is None or bucket < max(max(n, 1) for n in lcp):
            bucket = tok.pick_bucket([max(n, 1) for n in lcp], self.buckets)
        if sfx_buckets_ab is not None:
            ba, bb = sfx_buckets_ab
            ba = max(ba, tok.pick_bucket(
                [len(s) for s in sfx_a_ids], sfx_buckets))
            bb = max(bb, tok.pick_bucket(
                [len(s) for s in sfx_b_ids], sfx_buckets))
        else:
            ba = tok.pick_bucket([len(s) for s in sfx_a_ids], sfx_buckets)
            bb = tok.pick_bucket([len(s) for s in sfx_b_ids], sfx_buckets)
        fallback_reason = None
        if max_sfx > max(sfx_buckets):
            # A suffix longer than the largest bucket would be silently
            # right-truncated — dropping the very instruction the readout
            # depends on. Prompt pairs that diverge this early share too
            # little to be worth a shared prefill anyway.
            fallback_reason = (
                f"a prompt pair diverges {max_sfx} tokens before its end "
                f"(> {max(sfx_buckets)} suffix bucket)")
        elif max_total > max(self.buckets):
            # An over-long TOTAL prompt: the plain path left-truncates the
            # whole prompt into the largest bucket, while the shared path
            # would retain prefix-bucket + suffix-bucket tokens — more
            # context, an unpinned scoring divergence between the two paths
            # (ADVICE r3 #2). The plain path owns over-long semantics.
            fallback_reason = (
                f"a prompt ({max_total} tokens) exceeds the largest "
                f"bucket ({max(self.buckets)})")
        elif (getattr(self.cfg, "pos_embedding", None) == "learned"
              and bucket + max(ba + new_tokens, bb + conf_tokens)
              > self.cfg.max_seq_len):
            # The suffix extension appends past the prefix bucket, so decode
            # positions can reach the shared-decode cache length
            # bucket + max(ba+new, bb+conf) (generate.py T0) — beyond the
            # plain-path limit the constructor's bucket trim enforces. A
            # learned-position table would be read out of range (ADVICE r3
            # #1); the plain path's trimmed buckets stay in range.
            fallback_reason = (
                f"prefix bucket {bucket} + suffix/new-token budget "
                f"{max(ba + new_tokens, bb + conf_tokens)} would overrun "
                f"the {self.cfg.max_seq_len}-row learned-position table")
        if fallback_reason is not None:
            from ..utils.logging import get_logger

            get_logger(__name__).info(
                "shared-prefix fallback: %s — scoring this whole bucket "
                "with two full prefills", fallback_reason)
            fused = self.decode_fused(binary_prompts, yes_ids, no_ids,
                                      max_new_tokens=new_tokens,
                                      pretokenized=bin_ids,
                                      eos_stop=early_stop)
            cfused = self.decode_fused(confidence_prompts, yes_ids, no_ids,
                                       with_digits=True,
                                       max_new_tokens=conf_tokens,
                                       pretokenized=conf_ids,
                                       early_stop=early_stop)
            return fused, cfused
        prefix, prefix_mask = tok.left_pad_ids(
            [a[:n] for a, n in zip(bin_ids, lcp)], bucket, pad_id)
        sfx_a, sfx_a_mask = tok.right_pad_ids(sfx_a_ids, ba, pad_id)
        sfx_b, sfx_b_mask = tok.right_pad_ids(sfx_b_ids, bb, pad_id)
        digit_ids, digit_vals = self.digit_table
        stop_mask = self.digit_stop_mask if early_stop else None
        kwargs = dict(
            max_new_a=new_tokens, max_new_b=conf_tokens,
            prefill_fn=self._prefill_fn, stop_mask_b=stop_mask,
            stop_mask_a=(None if stop_mask is None else self.eos_stop_mask),
            eos_id=(None if stop_mask is None
                    else jnp.int32(self.eos_id)))
        if reuse_cache:
            key = ("shared", bucket, len(bin_ids), ba, bb, new_tokens,
                   conf_tokens, early_stop)
            scratch = self._handoff.take(key)
            dyn_args = (self.params, jnp.asarray(prefix),
                        jnp.asarray(prefix_mask), jnp.asarray(sfx_a),
                        jnp.asarray(sfx_a_mask), jnp.asarray(sfx_b),
                        jnp.asarray(sfx_b_mask),
                        jnp.asarray(yes_ids, jnp.int32),
                        jnp.asarray(no_ids, jnp.int32),
                        jnp.asarray(digit_ids), jnp.asarray(digit_vals))
            exe = None
            if self.exec_registry is not None:
                exe = self.exec_registry.get(compile_plan.shared_spec(
                    bucket, len(bin_ids), ba, bb, new_tokens, conf_tokens,
                    stops_armed=stop_mask is not None,
                    scratch=scratch is not None))
            if exe is not None:
                stop_kwargs = {k: kwargs[k] for k in
                               ("stop_mask_a", "stop_mask_b", "eos_id")}
                fused, cfused, cache = compile_plan.registry_call(
                    exe, dyn_args, stop_kwargs, scratch)
            else:
                fused, cfused, cache = generate.greedy_decode_fused_shared(
                    dyn_args[0], self.cfg, *dyn_args[1:],
                    return_cache=True, scratch_cache=scratch, **kwargs)
            self._handoff.put(key, cache)
            return fused, cfused
        return generate.greedy_decode_fused_shared(
            self.params, self.cfg, jnp.asarray(prefix),
            jnp.asarray(prefix_mask), jnp.asarray(sfx_a),
            jnp.asarray(sfx_a_mask), jnp.asarray(sfx_b),
            jnp.asarray(sfx_b_mask),
            jnp.asarray(yes_ids, jnp.int32), jnp.asarray(no_ids, jnp.int32),
            jnp.asarray(digit_ids), jnp.asarray(digit_vals), **kwargs)

    def decode_fused_grouped(self, groups, yes_ids: np.ndarray,
                             no_ids: np.ndarray, new_tokens: int,
                             conf_tokens: int, early_stop: bool,
                             bucket: int, sfx_bucket: int,
                             reuse_cache: bool = False):
        """Cross-cell prefix reuse: score every member prompt of
        ``groups`` (scheduler.PrefixGroup-shaped: ``.items`` with
        ``.bin_ids``/``.conf_ids``, shared ``.plen``) with ONE prefill per
        group. Member rows are laid out [bin, conf] per cell, cells in
        group order; ``yes_ids``/``no_ids`` are per-CELL in that order.

        Returns (FusedDecodeOut over the padded member batch, real member
        row count) — callers slice even rows for the binary readout and
        odd rows for the confidence readout. Both formats run one shared
        decode budget max(new_tokens, conf_tokens); with ``early_stop``
        the binary rows take the EOS-only stop table and the confidence
        rows the digit stop (per-row selection, generate._fused_tail), so
        the extra binary steps retire the moment the row answers.
        """
        assert not self.encoder_decoder
        pad_id = tok.pad_token_id(self.tokenizer)
        prefix_ids, sfx_ids, group_idx, cell_rows = [], [], [], 0
        for g in groups:
            gi = len(prefix_ids)
            prefix_ids.append(list(g.items[0].bin_ids[:g.plen]))
            for it in g.items:
                sfx_ids.append(list(it.bin_ids[g.plen:]))
                sfx_ids.append(list(it.conf_ids[g.plen:]))
                group_idx += [gi, gi]
                cell_rows += 1
        m = len(sfx_ids)
        g_pad = _tail_batch(len(prefix_ids), self.rt.batch_size)
        m_pad = _tail_batch(m, 2 * self.rt.batch_size)
        prefix_ids += [prefix_ids[-1]] * (g_pad - len(prefix_ids))
        sfx_ids += [sfx_ids[-1]] * (m_pad - m)
        group_idx += [group_idx[-1]] * (m_pad - m)
        if max(len(p) for p in prefix_ids) > bucket:
            raise ValueError("scheduler planned a group prefix longer than "
                             "its bucket")  # planning bug, never truncate
        if (getattr(self.cfg, "pos_embedding", None) == "learned"
                and bucket + sfx_bucket + max(new_tokens, conf_tokens)
                > self.cfg.max_seq_len):
            raise ValueError("scheduler planned a grouped dispatch past the "
                             "learned-position table")

        prefix, prefix_mask = tok.left_pad_ids(prefix_ids, bucket, pad_id)
        sfx, sfx_mask = tok.right_pad_ids(sfx_ids, sfx_bucket, pad_id)
        yes2 = np.repeat(np.asarray(yes_ids, np.int32), 2)
        no2 = np.repeat(np.asarray(no_ids, np.int32), 2)
        yes2 = np.concatenate([yes2, np.repeat(yes2[-1:], m_pad - m)])
        no2 = np.concatenate([no2, np.repeat(no2[-1:], m_pad - m)])
        digit_ids, digit_vals = self.digit_table
        stop_mask = self.digit_stop_mask if early_stop else None
        kwargs = dict(
            max_new=max(new_tokens, conf_tokens),
            prefill_fn=self._prefill_fn,
            stop_mask=(None if stop_mask is None else self.eos_stop_mask),
            stop_mask2=stop_mask,
            stop_sel=(None if stop_mask is None else
                      jnp.asarray(np.arange(m_pad) % 2 == 1)),
            eos_id=(None if stop_mask is None else jnp.int32(self.eos_id)))
        args = (self.params, self.cfg, jnp.asarray(prefix),
                jnp.asarray(prefix_mask), jnp.asarray(sfx),
                jnp.asarray(sfx_mask),
                jnp.asarray(np.asarray(group_idx, np.int32)),
                jnp.asarray(yes2), jnp.asarray(no2),
                jnp.asarray(digit_ids), jnp.asarray(digit_vals))
        if reuse_cache:
            key = ("grouped", bucket, g_pad, m_pad, sfx_bucket,
                   kwargs["max_new"], early_stop)
            scratch = self._handoff.take(key)
            exe = None
            if self.exec_registry is not None:
                exe = self.exec_registry.get(compile_plan.grouped_spec(
                    bucket, g_pad, m_pad, sfx_bucket, kwargs["max_new"],
                    stops_armed=stop_mask is not None,
                    scratch=scratch is not None))
            if exe is not None:
                stop_kwargs = {k: kwargs[k] for k in
                               ("stop_mask", "stop_mask2", "stop_sel",
                                "eos_id")}
                out, cache = compile_plan.registry_call(
                    exe, (args[0],) + args[2:], stop_kwargs, scratch)
            else:
                out, cache = generate.greedy_decode_fused_grouped(
                    *args, return_cache=True, scratch_cache=scratch,
                    **kwargs)
            self._handoff.put(key, cache)
        else:
            out = generate.greedy_decode_fused_grouped(*args, **kwargs)
        return out, m

    def decode_completion(self, generated_ids: np.ndarray) -> str:
        """Token ids -> text, stopping at the first EOS (HF generate parity —
        the fixed-length jitted decode keeps emitting after EOS; those tokens
        must not leak into response text or the confidence-integer parse)."""
        trimmed = tok.trim_at_eos(np.asarray(generated_ids).tolist(), self.eos_id)
        with self._tok_lock:
            return self.tokenizer.decode(
                trimmed, skip_special_tokens=True).strip()

    def _pad_batch(self, prompts: Sequence[str],
                   pretokenized: Optional[Sequence[Sequence[int]]] = None
                   ) -> Tuple[jax.Array, jax.Array]:
        """Tokenize + left-pad into the smallest fitting bucket."""
        if pretokenized is not None:
            ids_list = list(pretokenized)
        else:
            with self._tok_lock:
                ids_list = [self.tokenizer(p).input_ids for p in prompts]
        bucket = tok.pick_bucket([len(i) for i in ids_list], self.buckets)
        toks_arr, mask = tok.left_pad_ids(ids_list, bucket,
                                          tok.pad_token_id(self.tokenizer))
        return jnp.asarray(toks_arr), jnp.asarray(mask)

    def _sample_from_ids(self, toks: jax.Array, mask: jax.Array,
                         key: jax.Array, temperature: float,
                         max_new_tokens: Optional[int]) -> List[str]:
        return self._sample_from_ids_raw(toks, mask, key, temperature,
                                         max_new_tokens)[0]

    def _sample_from_ids_raw(self, toks: jax.Array, mask: jax.Array,
                             key: jax.Array, temperature: float,
                             max_new_tokens: Optional[int]
                             ) -> Tuple[List[str], np.ndarray]:
        """(decoded texts, raw generated ids) — callers that must know
        whether the reply finished inside the budget (EOS emitted) need the
        ids, not just the EOS-trimmed text."""
        gen = generate.sample_decode(
            self.params, self.cfg, toks, mask, key, temperature=temperature,
            max_new_tokens=(self.rt.max_new_tokens if max_new_tokens is None
                            else max_new_tokens),
            prefill_fn=self._prefill_fn,
            # HF/API-parity EOS stop: a finished row emits EOS fill (so
            # the finished-inside-budget signal this method documents is
            # preserved) and an all-done batch skips the remaining
            # forwards; unfinished rows are bit-identical to the
            # unstopped sampler.
            eos_id=(None if self.eos_id is None
                    else jnp.int32(self.eos_id)))
        gen = np.asarray(jax.device_get(gen))
        return ([self.decode_completion(gen[j])
                 for j in range(gen.shape[0])], gen)

    def sample_completions(self, prompts: Sequence[str], key: jax.Array,
                           temperature: float = 1.0,
                           max_new_tokens: Optional[int] = None) -> List[str]:
        """One temperature-sampled completion per prompt (single jitted
        call; same bucketing as the greedy paths)."""
        toks, mask = self._pad_batch(prompts)
        return self._sample_from_ids(toks, mask, key, temperature,
                                     max_new_tokens)

    def sample_completions_with_ids(
            self, prompts: Sequence[str], key: jax.Array,
            temperature: float = 1.0,
            max_new_tokens: Optional[int] = None
    ) -> Tuple[List[str], np.ndarray]:
        toks, mask = self._pad_batch(prompts)
        return self._sample_from_ids_raw(toks, mask, key, temperature,
                                         max_new_tokens)

    # -- public API ---------------------------------------------------------

    def score_prompts_sampled(
        self, prompts: Sequence[str],
        target_texts: Sequence[Tuple[str, str]],
        n_runs: int = 10, key: Optional[jax.Array] = None,
        temperature: float = 1.0,
        max_new_tokens: Optional[int] = None,
    ) -> List[SampledScore]:
        """Reasoning-model scoring: n sampled runs per prompt, answer-count
        averaging (VERDICT r1 #7; perturb_prompts.py:412-446 locally).

        ``key`` may be per-prompt keys shaped (B, 2): each prompt then owns
        its PRNG stream, so results do not depend on batch composition (the
        sweep keys rows by grid-cell identity -> resume-deterministic).

        The reference's reasoning models expose no logprobs, so it samples
        each binary prompt REASONING_MODEL_RUNS times (API default
        temperature) and sets Token_i_Prob = (runs whose text contains
        target_i) / n_runs, if/elif order — a text containing both targets
        (e.g. "Not Covered" contains "Covered") counts toward token 1 only;
        the stored response is the most common run text. Runs loop outside
        jit on purpose: vmapping the decode over runs would multiply the KV
        cache by n_runs (a 7B batch-32 cache is ~4.5 GB — x10 cannot fit
        HBM); each run reuses the same compiled sample_decode executable.
        """
        if key is None:
            key = jax.random.PRNGKey(0)
        per_row = generate.is_per_row_keys(key)  # per-prompt streams
        all_runs: List[List[str]] = [[] for _ in prompts]
        # Tokenize/pad ONCE; only the PRNG key varies across runs.
        toks, mask = self._pad_batch(prompts)
        for run in range(n_runs):
            if per_row:
                run_key = jax.vmap(
                    lambda k: jax.random.fold_in(k, run))(key)
            else:
                run_key = jax.random.fold_in(key, run)
            texts = self._sample_from_ids(
                toks, mask, run_key, temperature, max_new_tokens)
            for j, t in enumerate(texts):
                all_runs[j].append(t.strip())

        out: List[SampledScore] = []
        for j, prompt in enumerate(prompts):
            t1, t2 = target_texts[j]
            p1, p2, most_common = score.count_averaged_responses(
                all_runs[j], t1, t2)
            out.append(SampledScore(
                prompt=prompt,
                response=most_common,
                all_responses=list(all_runs[j]),
                token_1_prob=p1,
                token_2_prob=p2,
                odds_ratio=(p1 / p2) if p2 > 0 else float("inf"),
            ))
        return out

    def score_prompts(self, prompts: Sequence[str]) -> List[PromptScore]:
        """Score every prompt; one jitted call per full batch."""
        order = np.argsort([len(p) for p in prompts], kind="stable")
        rows: List[Optional[PromptScore]] = [None] * len(prompts)
        B = self.rt.batch_size
        for start in range(0, len(order), B):
            idx = order[start:start + B]
            batch_prompts = [prompts[i] for i in idx]
            rows_out = self._score_batch(batch_prompts)
            for i, r in zip(idx, rows_out):
                rows[i] = r
        return rows  # type: ignore[return-value]

    def _score_batch(self, batch_prompts: List[str]) -> List[PromptScore]:
        n = len(batch_prompts)
        B = self.rt.batch_size
        # Tail bucket: pad to the next power of two, not the full B (at most
        # one extra compile; stops re-scoring the last prompt B-n times).
        bsz = B if n == B else _tail_batch(n, B)
        padded_prompts = batch_prompts + [batch_prompts[-1]] * (bsz - n)

        if self.encoder_decoder:
            gen, step_logits = self.decode_prompts(padded_prompts)
            res = score.readout_from_step_logits(
                step_logits, gen, jnp.int32(self.yes_id),
                jnp.int32(self.no_id), scan_positions=self.rt.scan_positions)
        else:
            yes_ids = np.full((bsz,), self.yes_id, np.int32)
            no_ids = np.full((bsz,), self.no_id, np.int32)
            fused = self.decode_fused(padded_prompts, yes_ids, no_ids)
            res = score.readout_from_fused(
                fused, jnp.asarray(yes_ids), jnp.asarray(no_ids),
                scan_positions=self.rt.scan_positions)

        res = jax.device_get(res)
        out = []
        for j in range(n):
            out.append(PromptScore(
                prompt=batch_prompts[j],
                completion=self.decode_completion(res.generated[j]),
                yes_prob=float(res.yes_prob[j]),
                no_prob=float(res.no_prob[j]),
                yes_logprob=float(res.yes_logprob[j]),
                no_logprob=float(res.no_logprob[j]),
                odds_ratio=float(res.odds_ratio[j]),
                relative_prob=float(res.relative_prob[j]),
                position_found=int(res.position_found[j]),
                yes_no_found=bool(res.yes_no_found[j]),
            ))
        return out
