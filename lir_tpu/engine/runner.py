"""Batched scoring engine: the TPU replacement for both reference backends.

Where the reference loops prompts one at a time through
``model.generate(output_scores=True)`` (compare_base_vs_instruct.py:458-492)
or ships them to the OpenAI Batch API (perturb_prompts.py:551-726), this
engine packs ragged prompts into fixed-shape left-padded batches, runs ONE
jitted greedy-decode-with-capture per batch (sharded over the device mesh),
and applies the C13 readout vectorized over the batch.

Static-shape discipline: prompts are bucketed by token length and the batch
axis padded to ``batch_size``, so XLA compiles once per (bucket, batch_size)
pair and every subsequent batch reuses the cache.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import CascadeConfig, GovernorConfig, RuntimeConfig, SpecConfig
from ..guard.watchdog import DispatchWatchdog
from ..models import decoder, paged, quant
from ..utils.profiling import (CascadeStats, CompileStats, FaultStats,
                               GuardStats, KernelStats, PrefixCacheStats,
                               SpecStats, cascade_decode_bytes_saved,
                               cascade_prefill_flops_saved)
from . import (compile_plan, generate, hbm, prefix_tree,
               scheduler as scheduler_mod, score, spec as spec_mod,
               tokens as tok)


class PiggybackIneligible(RuntimeError):
    """A dispatch can't ride the piggyback chain (layout fallback, memory
    headroom, learned-position ceiling) — the caller dispatches it through
    the plain path instead. Deliberate control flow, never an error."""


def _tail_batch(n: int, cap: int) -> int:
    """Smallest power of two >= n, capped at the configured batch size."""
    b = 1
    while b < n:
        b *= 2
    return min(b, cap)


# Cross-dispatch donation chain for the dense dispatch caches. The class
# moved to models/paged.py so all three KV ownership schemes — the page
# pool, the radix index, and the dispatch-scratch donation chain — live
# under the one allocator module; this alias keeps the historical name.
_CacheHandoff = paged.CacheHandoff


@dataclasses.dataclass
class _PrefixPlan:
    """One dispatch's radix-cache resume decision (engine-internal).

    ``window`` is the remainder-window edge the paged executable will
    run (each row recomputes its last ``window`` real prefix tokens and
    gathers everything earlier from the page pool), or None when nothing
    useful is cached — the dispatch then runs the plain unpaged prefill
    (whose executable already exists) and only INSERTS pages afterward.
    ``matches`` hold the dispatch's page pins; every plan MUST pass
    through ScoringEngine._finish_prefix_resume, which inserts the new
    pages and releases the pins."""

    bucket: int
    prefix_ids: List[Sequence[int]]
    matches: List[Any]
    n_real: int
    window: Optional[int] = None
    w0: int = 0
    slot_src: Optional[np.ndarray] = None
    rem: Optional[np.ndarray] = None
    rem_mask: Optional[np.ndarray] = None


@dataclasses.dataclass
class PromptScore:
    """One prompt's raw measurement. Sweep drivers wrap this into
    data/schemas.py records (which add model identity and D1/D2 semantics)."""

    prompt: str
    completion: str
    yes_prob: float
    no_prob: float
    yes_logprob: float
    no_logprob: float
    odds_ratio: float
    relative_prob: float
    position_found: int
    yes_no_found: bool


@dataclasses.dataclass
class SampledScore:
    """n-run count-averaged measurement (reasoning-model mode,
    perturb_prompts.py:412-446): probabilities are answer-count fractions,
    not logit softmaxes."""

    prompt: str
    response: str               # most common run text
    all_responses: List[str]
    token_1_prob: float
    token_2_prob: float
    odds_ratio: float


class ScoringEngine:
    """Holds (params, cfg, tokenizer) and the jitted decode path.

    ``encoder_decoder=True`` routes through the T5 branch (reference routing
    rule compare_instruct_models.py:471-475).
    """

    def __init__(self, params: Any, cfg: Any, tokenizer: Any,
                 runtime: Optional[RuntimeConfig] = None,
                 encoder_decoder: bool = False,
                 yes_text: str = "Yes", no_text: str = "No",
                 seq_mesh: Any = None, seq_impl: str = "ring",
                 spec_config: Optional[SpecConfig] = None,
                 governor: Optional["hbm.HbmGovernor"] = None,
                 governor_config: Optional[GovernorConfig] = None,
                 cascade_config: Optional[CascadeConfig] = None):
        self.params = params
        self.cfg = cfg
        self.tokenizer = tokenizer
        self.rt = runtime or RuntimeConfig()
        # Speculative scoring decode (engine/spec.py): drafting policy,
        # per-dispatch SpecOut readouts pending their deferred host
        # fold, the optional fleet draft model (set_spec_draft), and
        # the fault hook a wrapped plan uses to corrupt drafts
        # (faults/plan.wrap_engine).
        self.spec_cfg = spec_config or SpecConfig()
        self.spec_stats = SpecStats()
        # Shared-prefix cascade prefill (ops/cascade_prefill): eligibility
        # policy + the dedup counters bench.py's "cascade" key reads.
        self.cascade_cfg = cascade_config or CascadeConfig()
        self.cascade_stats = CascadeStats()
        self._spec_draft = None
        self._spec_pending: List[Any] = []
        self.spec_fault_plan = None
        self.encoder_decoder = encoder_decoder
        # Fused decode kernels are a RUNTIME choice surfaced through the
        # static model config (the decode executables specialize on it):
        # --no-fused-decode restores the dense decode lowering exactly,
        # and the manifest key shifts with the cfg so a registry or
        # warmed compile cache can never serve the other mode's
        # executables.
        if (not encoder_decoder
                and getattr(cfg, "fused_decode", None) is not None
                and cfg.fused_decode != self.rt.fused_decode):
            self.cfg = cfg = dataclasses.replace(
                cfg, fused_decode=self.rt.fused_decode)
        # Cascade decode + fused-suffix cascade prefill follow the same
        # discipline: runtime choices mirrored into the static model
        # config, so --no-cascade-decode / --no-cascade-fused-suffix
        # re-key every affected executable and the manifest can never
        # serve the other mode's lowering.
        if (not encoder_decoder
                and getattr(cfg, "cascade_decode", None) is not None
                and cfg.cascade_decode != self.rt.cascade_decode):
            self.cfg = cfg = dataclasses.replace(
                cfg, cascade_decode=self.rt.cascade_decode)
        if (not encoder_decoder
                and getattr(cfg, "cascade_fused_suffix", None) is not None
                and cfg.cascade_fused_suffix != self.rt.cascade_fused_suffix):
            self.cfg = cfg = dataclasses.replace(
                cfg, cascade_fused_suffix=self.rt.cascade_fused_suffix)
        # Sequence-parallel prefill (long-context path): with a mesh whose
        # `seq` axis > 1, the quadratic prompt phase runs seq-sharded
        # through ring/Ulysses attention (parallel/seq_forward) and hands
        # the KV cache back unsharded for ordinary dense decode. Built ONCE
        # here so the jitted decode fns cache on a stable static callable.
        self._prefill_fn = None
        if seq_mesh is not None and not encoder_decoder:
            from ..parallel.seq_forward import prefill_seq_parallel

            def _seq_prefill(p, c, t, m, T, *, _mesh=seq_mesh,
                             _impl=seq_impl):
                return prefill_seq_parallel(p, c, t, m, T, mesh=_mesh,
                                            impl=_impl)

            self._prefill_fn = _seq_prefill
        self.yes_id, self.no_id = tok.yes_no_ids(
            tokenizer, encoder_decoder=encoder_decoder,
            yes_text=yes_text, no_text=no_text)
        self.eos_id = getattr(tokenizer, "eos_token_id", None)
        # The pipelined sweep tokenizes bucket N+1 on the main thread while
        # its writer thread decodes bucket N's completions. HF fast (Rust)
        # tokenizers are NOT safe under concurrent encode/decode (encode
        # takes a write borrow for truncation/padding state -> intermittent
        # "Already borrowed" RuntimeError), so every tokenizer touch goes
        # through this lock. Contention is negligible: encode/decode are
        # each ~ms per bucket vs ~1.5 s of device work.
        self._tok_lock = threading.Lock()
        # Length buckets. With the ragged scheduler: a ~sqrt(2) ladder
        # (tokens.bucket_ladder) so short prompts prefill short shapes —
        # each edge compiles once and the scheduler keeps dispatches
        # bucket-pure. Legacy mode keeps the powers-of-two set whose
        # per-batch pick_bucket pads every mixed-length batch to its
        # longest row (the bench's single-bucket baseline).
        if self.rt.ragged_scheduler:
            self.buckets = list(tok.bucket_ladder(self.rt.max_seq_len))
        else:
            self.buckets = [b for b in (64, 128, 256, 512, 1024)
                            if b <= self.rt.max_seq_len] or [self.rt.max_seq_len]
        if getattr(cfg, "pos_embedding", None) == "learned":
            # A bucket + generation budget past the learned-position table
            # would read beyond pos_embed (gpt2/opt tables are exactly
            # max_seq_len rows): trim such buckets so a ~1000-token prompt
            # fails loudly into a smaller bucket's truncation semantics
            # instead of decoding at clipped positions.
            limit = cfg.max_seq_len - self.rt.max_new_tokens
            fitting = [b for b in self.buckets if b <= limit]
            if not fitting:
                raise ValueError(
                    f"{cfg.name}: no length bucket fits the learned-"
                    f"position table ({cfg.max_seq_len} rows) minus the "
                    f"generation budget ({self.rt.max_new_tokens}) — "
                    f"reduce max_new_tokens or max_seq_len")
            self.buckets = fitting
        self._digit_table: Optional[Tuple[np.ndarray, np.ndarray]] = None
        self._digit_stop_mask: Any = False  # False = not resolved yet
        self._eos_stop_mask: Optional[jax.Array] = None
        # Cross-dispatch KV-cache buffer reuse (donation) + the last
        # sweep's scheduler counters (profiling.OccupancyStats) — set by
        # sweep.run_perturbation_sweep, read by bench.py.
        self._handoff = _CacheHandoff()
        self.occupancy = None
        # In-flight piggyback chain (chunked prefill/decode piggybacking):
        # the parked dispatch whose decode scans ride the next same-shape
        # dispatch's prefill call (generate.PiggybackCarry + the statics
        # needed to drain it). One chain at a time by construction — the
        # sweep drains before switching shapes.
        self._piggy: Optional[dict] = None
        # Per-phase kernel accounting + piggyback counters
        # (profiling.KernelStats; bench.py fills the phase rows).
        self.kernel_stats = KernelStats()
        # Unified HBM governor (engine/hbm.py): one ledger every HBM
        # consumer registers with, the pressure-driven degradation
        # ladder, and reclaim-and-retry OOM routing. With no configured
        # budget and no device memory stats (CPU) the ladder never
        # engages — behavior is identical to pre-governor. Built BEFORE
        # the prefix cache so the pool reservation lands in the ledger.
        if governor is not None:
            self.governor: Optional[hbm.HbmGovernor] = governor
        else:
            self.governor = hbm.HbmGovernor(governor_config)
        # Ledger keys are namespaced by model so engines sharing one
        # fleet governor never collide (and the fleet can hand params
        # accounting over to the weight cache — release_params_ledger).
        self._ledger_key = f"params:{getattr(cfg, 'name', 'model')}"
        if self.governor is not None and params is not None:
            try:
                self.governor.register(self._ledger_key,
                                       quant.param_bytes(params))
            except Exception:  # noqa: BLE001 — ledger accounting must
                # never block engine construction (exotic test params)
                pass
            self.governor.set_action(
                "evict_pages",
                engage=lambda: self._evict_cold_pages())
            self.governor.set_action(
                "no_piggyback",
                engage=lambda: self._drop_handoff_scratch())
        # Cross-request radix prefix cache (engine/prefix_tree.py) over
        # the paged KV allocator (models/paged.py): a dispatch resumes
        # each row's prefix from the deepest cached radix node and pays
        # prefill only for the unshared remainder — across requests,
        # batches, and sweeps. Built by enable_prefix_cache() (the serve
        # layer turns it on by default, ServeConfig.prefix_cache;
        # offline sweeps opt in via RuntimeConfig.prefix_cache).
        self.prefix_cache: Optional[prefix_tree.RadixPrefixCache] = None
        self.prefix_stats = PrefixCacheStats()
        if self.rt.prefix_cache:
            self.enable_prefix_cache()
        # Compile plan (engine/compile_plan.py): the sweep precompiles its
        # planned shapes into this registry; the decode entry points below
        # consult it and fall back to lazy jit on any miss. Stats record
        # per-shape compile seconds + registry/persistent-cache hit rates.
        self.compile_stats = CompileStats()
        self.exec_registry = None
        # Failure-path accounting (lir_tpu/faults): the sweep's dispatch
        # recovery and any wrapping FaultPlan count into this.
        self.fault_stats = FaultStats()
        # Guard layer (lir_tpu/guard): the dispatch watchdog (stall
        # detection priced by scheduler.bucket_cost, calibrated against
        # this engine's own dispatch rate) and the counters it shares
        # with the numerics guard and the multihost liveness barrier.
        self.guard_stats = GuardStats()
        # Speculating engines price dispatches at the spec decode floor,
        # so their watchdog seeds with the wider UNFUSED/SPEC headroom
        # (a zero-accept dispatch degenerating to sequential cost must
        # never trip a spec-calibrated deadline — scheduler.
        # watchdog_seed_headroom).
        # A cascade engine additionally multiplies in the cascade/dense
        # prefill spread: deadlines calibrate on trunk-discounted
        # dispatches, and an ineligible dispatch legitimately falls back
        # to the full dense prefill.
        self.watchdog = DispatchWatchdog(
            multiple=self.rt.watchdog_multiple,
            floor_s=self.rt.watchdog_floor_s, stats=self.guard_stats,
            seed_headroom=scheduler_mod.watchdog_seed_headroom(
                self.rt.spec_decode and self.rt.spec_k >= 2,
                cascade=self.cascade_supported()))
        self._seq_mesh_note = (
            None if seq_mesh is None
            else (repr(getattr(seq_mesh, "shape", seq_mesh)), seq_impl))
        self._manifest_key: Optional[str] = None

    def fresh_handoff(self) -> None:
        """Reset the cross-dispatch KV-cache donation chain. Call at the
        start of every dispatch stream (a sweep, a serving session): the
        first dispatch of each bucket then always runs the scratchless
        jit signature and later ones the donated-cache signature — the
        same two executables a warmup over the same shapes compiles, so
        steady-state timing never hits a fresh compile mid-stream."""
        self._handoff = _CacheHandoff()
        if getattr(self, "governor", None) is not None:
            self.governor.unregister(
                f"handoff:{getattr(self.cfg, 'name', 'model')}")

    def release_params_ledger(self) -> None:
        """The fleet weight cache now owns this engine's param bytes —
        drop the engine-level ledger entry so a shared governor never
        double-counts them under both ``params:<model>`` and the
        cache's ``weights`` entry (engine/fleet.py calls this when a
        model moves under cache ownership)."""
        if self.governor is not None:
            self.governor.unregister(self._ledger_key)

    def _drop_handoff_scratch(self) -> bool:
        """Governor no_piggyback rung: beyond refusing new chains, give
        back the donation-chain scratch buffer the handoff retains
        between dispatches — real HBM freed NOW (the next dispatch
        simply runs the scratchless executable signature, which every
        bucket already compiled as its first dispatch). True when a
        parked buffer was actually released."""
        had = self._handoff.pending
        self.fresh_handoff()
        return had

    def _evict_cold_pages(self) -> bool:
        """Governor evict_pages rung: drop the coldest radix pages
        (tree-driven LRU — models/paged refcounts keep in-flight pages
        unevictable). Returns True when any page was actually freed.
        With a tier store attached (:meth:`attach_tiers`) the rung
        DEMOTES instead: the coldest leaves export to the host tier
        before their pages leave HBM, and plain eviction remains the
        fallback when nothing was demotable."""
        if self.prefix_cache is None:
            return False
        store = getattr(self, "_tier_store", None)
        if store is not None and store.demote(self):
            return True
        n = self.prefix_cache.evict(
            self.governor.cfg.evict_pages_per_step
            if self.governor is not None else paged.DEFAULT_PAGE_SIZE)
        return n > 0

    def attach_tiers(self, store) -> None:
        """Point the ``evict_pages`` reclaim rung at a
        serve/tiers.TieredPageStore: HBM pressure then demotes the
        coldest radix leaves down the host/disk ladder (reversible —
        a later promote re-enters through the paged-warm import path
        bitwise) instead of deleting them. The rung's engage callback
        is unchanged — demotion frees the same HBM pages eviction
        would, so the governor's reclaim accounting holds."""
        self._tier_store = store

    def _note_handoff(self, cache: Any) -> None:
        """Ledger the donation-chain scratch cache the engine keeps
        live between dispatches (shape metadata only — no device
        sync). One entry: the chain holds at most one parked cache."""
        if self.governor is None or cache is None:
            return
        nbytes = 0
        for leaf in jax.tree.leaves(cache):
            size = getattr(leaf, "size", None)
            dtype = getattr(leaf, "dtype", None)
            if size is None or dtype is None:
                continue
            # .size/.itemsize are static shape METADATA (host ints on
            # an async jax array) — no device round-trip happens here.
            nbytes += int(size) * int(jnp.dtype(dtype).itemsize)  # lint: allow(host-sync)
        self.governor.register(
            f"handoff:{getattr(self.cfg, 'name', 'model')}", nbytes)

    def enable_prefix_cache(self) -> None:
        """Build the paged KV pool + radix index (idempotent). The pool
        leaves materialize immediately at their full configured size
        (rt.prefix_cache_pages x rt.prefix_page_size token positions,
        models/paged.kv_page_bytes each) so serving never allocates HBM
        mid-traffic; disable by sizing prefix_cache_pages < 2.
        Sequence-parallel engines keep the unpaged path (the paged
        window extension is a dense chunked prefill — resharding it
        through ring/Ulysses attention is not worth R tokens)."""
        if (self.prefix_cache is not None or self.encoder_decoder
                or self._prefill_fn is not None):
            return
        if self.rt.prefix_cache_pages < 2:
            return
        pool = paged.KVPagePool(self.rt.prefix_cache_pages,
                                self.rt.prefix_page_size)
        pool.ensure(self._cache_aval())
        self.prefix_cache = prefix_tree.RadixPrefixCache(
            pool, stats=self.prefix_stats)
        if self.governor is not None:
            # The pool materializes at full size up front — the ledger
            # carries the whole reservation, not current occupancy.
            self.governor.register(
                f"kv_pages:{getattr(self.cfg, 'name', 'model')}",
                pool.nbytes)

    # -- speculative decode (engine/spec.py) --------------------------------

    def spec_supported(self) -> bool:
        """Engine-level gate for speculative decode: on by config with a
        verify window of at least 2, plain decoder engines only (T5 and
        seq-parallel prefills keep their own paths). Per-dispatch
        eligibility (layout fallbacks, fleet-draft x paged exclusion)
        is decided where the dispatch forms."""
        return (self.rt.spec_decode and self.rt.spec_k >= 2
                and not self.encoder_decoder
                and self._prefill_fn is None)

    def set_spec_draft(self, params: Any, cfg: Any, name: str = "") -> None:
        """Arm fleet-model drafting: the small model's (params, cfg)
        draft for this engine's verifier. The caller owns the weights'
        lifetime — the fleet layer acquires them through the PR-10
        WeightCache around every dispatch window so drafting can never
        evict the verifier mid-dispatch. Same tokenizer/vocab as the
        verifier is the caller's contract (enforced here by vocab)."""
        if cfg.vocab_size != self.cfg.vocab_size:
            raise ValueError(
                f"spec draft model {name or cfg.name!r} vocab "
                f"{cfg.vocab_size} != verifier vocab "
                f"{self.cfg.vocab_size} — draft and verifier must share "
                f"a tokenizer")
        self._spec_draft = (params, cfg, name)
        if self.governor is not None:
            try:
                self.governor.register(
                    f"spec_draft:{name or cfg.name}",
                    quant.param_bytes(params))
            except Exception:  # noqa: BLE001 — accounting only
                pass

    def clear_spec_draft(self) -> None:
        if self._spec_draft is not None and self.governor is not None:
            _, dcfg, dname = self._spec_draft
            self.governor.unregister(f"spec_draft:{dname or dcfg.name}")
        self._spec_draft = None

    def spec_record(self, bucket: int,
                    prompt_ids: Sequence[Sequence[int]], gen_rows: Any,
                    n_real: Optional[int] = None) -> int:
        """Record observed completions into the radix tree's token
        history (prompt-lookup drafting warms itself — spec.py)."""
        return spec_mod.record_tails(
            self, bucket, prompt_ids, gen_rows,
            len(prompt_ids) if n_real is None else n_real,
            max_tails=self.spec_cfg.tree_tails_per_node)

    def spec_flush(self) -> None:
        """Fold pending device-side SpecOut counters into spec_stats
        (deferred off the dispatch path — spec.flush_pending)."""
        spec_mod.flush_pending(self)

    # -- shared-prefix cascade prefill (ops/cascade_prefill) ----------------

    def cascade_supported(self) -> bool:
        """Engine-level gate for cascade prefill: on by config, plain
        decoder engines only (T5 and seq-parallel prefills keep their
        own paths), float KV cache only (the cascade extension writes
        float k/v into the broadcast trunk cache — int8 KV engines keep
        the dense path), and only where the prefix-leg Pallas kernel
        runs: the TPU backend, or CPU under the interpreter when
        decoder.CASCADE_INTERPRET_ON_CPU is armed (tier-1 and the
        cascade smoke; production CPU stays dense). Per-dispatch
        eligibility (trunk length, row count) is
        :meth:`cascade_trunk_for`'s."""
        if not (self.rt.cascade_prefill and not self.encoder_decoder
                and self._prefill_fn is None
                and not getattr(self.cfg, "kv_cache_int8", False)):
            return False
        return (jax.default_backend() == "tpu"
                or decoder.CASCADE_INTERPRET_ON_CPU)

    def cascade_trunk_for(self, prefix_ids: Sequence[Sequence[int]],
                          n_real: Optional[int] = None,
                          bucket: Optional[int] = None) -> int:
        """The dispatch's shared-trunk extent, or 0 when the dispatch
        should run dense: the longest common token prefix across EVERY
        row's shared prefix (pad rows repeat a real row, so the
        all-rows LCP equals the real-rows LCP — and the broadcast-trunk
        cache layout requires the trunk to lead every batch row),
        snapped DOWN to the CascadeConfig.trunk_quantum grid (the trunk
        extent is a static compiled shape — compile_plan keys
        executables on it — so a few unshared tail tokens ride the
        per-row remainder instead of minting a new executable), floored
        at min_trunk, and kept strictly inside the bucket (a
        trunk == bucket dispatch would leave a zero-width remainder).
        ``n_real`` gates the min_rows dedup check — padding repeats
        dedup for free but buy nothing."""
        if not self.cascade_supported() or not prefix_ids:
            return 0
        return self._lcp_trunk(prefix_ids, n_real, bucket)

    def _lcp_trunk(self, prefix_ids: Sequence[Sequence[int]],
                   n_real: Optional[int], bucket: Optional[int]) -> int:
        """The quantized shared-trunk extent both cascade phases key on:
        all-rows LCP, snapped DOWN to the trunk_quantum grid, clamped
        strictly inside the bucket, floored at min_trunk; 0 when the
        dispatch is too small (min_rows) or the trunk too short."""
        cc = self.cascade_cfg
        rows_real = len(prefix_ids) if n_real is None else n_real
        if rows_real < max(cc.min_rows, 2):
            return 0
        q = max(int(cc.trunk_quantum), 1)
        trunk = (tok.common_prefix_len(prefix_ids) // q) * q
        if bucket is not None and trunk >= bucket:
            trunk = ((bucket - 1) // q) * q
        if trunk < max(int(cc.min_trunk), q):
            return 0
        return trunk

    # -- cascade decode (ops/flash_decode trunk-aware splits) ---------------

    def cascade_decode_supported(self) -> bool:
        """Engine-level gate for cascade DECODE: on by config, plain
        decoder engines only, float KV only, and only where the fused
        decode kernels run at all (cfg.fused_decode on the TPU backend,
        or CPU under the interpreter when
        decoder.FUSED_DECODE_INTERPRET_ON_CPU is armed) — the
        trunk-aware split dedup lives inside flash_decode/flash_decode_mq,
        so without the fused kernels there is nothing to dedup. The
        decoder gates once more on cfg.cascade_decode (belt and braces:
        --no-cascade-decode zeroes the trunk here AND flips the static
        cfg, so stale executables can never serve the other mode)."""
        if not (self.rt.cascade_decode and not self.encoder_decoder
                and getattr(self.cfg, "fused_decode", True)
                and not getattr(self.cfg, "kv_cache_int8", False)):
            return False
        return (jax.default_backend() == "tpu"
                or decoder.FUSED_DECODE_INTERPRET_ON_CPU)

    def decode_trunk_for(self, prefix_ids: Sequence[Sequence[int]],
                         n_real: Optional[int] = None,
                         bucket: Optional[int] = None) -> int:
        """The dispatch's shared-trunk extent for DECODE-phase dedup, or
        0 for the flat kernels: same LCP/quantum/bucket discipline as
        :meth:`cascade_trunk_for` (the trunk slots lead every row of the
        right-padded cache either way), but gated on the decode-side
        support check — a dispatch can cascade its decode steps even
        when the prefill ran dense (e.g. paged-warm prefixes), and vice
        versa. The extent is a static compiled shape: compile_plan keys
        decode executables on it."""
        if not self.cascade_decode_supported() or not prefix_ids:
            return 0
        return self._lcp_trunk(prefix_ids, n_real, bucket)

    def _note_cascade_decode(self, dtrunk: int, rows: int, bucket: int,
                             ba: int, bb: int, new_tokens: int,
                             conf_tokens: int) -> None:
        """Fold one trunk-aware decode dispatch into the cascade
        counters: the analytic HBM bytes the trunk dedup did NOT stream
        (trunk K/V tiles load once per decode step instead of once per
        row — profiling.cascade_decode_bytes_saved), over both format
        branches' full decode budgets."""
        if not dtrunk or rows <= 1:
            return
        t0 = bucket + max(ba + new_tokens, bb + conf_tokens)
        self.cascade_stats.count("cascade_decode_dispatches")
        self.cascade_stats.count(
            "trunk_bytes_deduped",
            int(cascade_decode_bytes_saved(
                self.cfg, rows, dtrunk, t0, new_tokens + conf_tokens)))

    def _cache_aval(self):
        """ShapeDtypeStruct tree of this engine's decode cache (leaf
        structure + dtypes — bf16 vs int8 payload+scale — exactly as
        prefill produces them), the authoritative template the page
        pool materializes from. Tracing only; no device work."""
        tok_aval = jax.ShapeDtypeStruct((1, 8), jnp.int32)
        _, cache, _ = jax.eval_shape(
            lambda p, t, m: decoder.prefill(p, self.cfg, t, m, 8),
            self.params, tok_aval, tok_aval)
        return cache

    # -- cross-request prefix resume (engine/prefix_tree over models/paged) --

    def _plan_prefix_resume(self, bucket: int,
                            prefix_ids: List[Sequence[int]],
                            n_real: int) -> "_PrefixPlan":
        """Pin the deepest cached prefix of every row and decide the
        dispatch's remainder window (the exact-layout scheme —
        generate._paged_prefix): window = the smallest planned edge
        covering every row's uncached tail, anchored at the dispatch's
        longest real row; rows recompute the window's slice of their
        prefix and gather the rest from the pool at the very slots the
        right-padded prefill would use, so results stay bitwise
        identical to the unpaged path. No coverable window means the
        cache holds nothing useful — the plan degrades to the unpaged
        prefill (still inserting pages afterward, which is how the
        cache warms up in the first place)."""
        tree = self.prefix_cache
        ps = tree.page_size
        matches = [tree.lookup(bucket, ids, record=(r < n_real))
                   for r, ids in enumerate(prefix_ids)]
        plan = _PrefixPlan(bucket=bucket, prefix_ids=list(prefix_ids),
                           matches=matches, n_real=n_real)
        for r in range(n_real):
            self.prefix_stats.count("prefill_tokens_total",
                                    len(prefix_ids[r]))
        # The canonical layout is RIGHT-padded (slot = token position),
        # so the recompute window is anchored at the dispatch's LONGEST
        # REAL ROW: slots [w0, w0 + window) with w0 = max_n - window (a
        # traced scalar into the paged executable, so the anchor moves
        # per dispatch without retracing). Every row's uncached tail
        # must start at or after w0 — the window covers the WORST row
        # (a fully-paged row needs none) — and anchoring at max_n
        # instead of the bucket edge means rows shorter than the bucket
        # never recompute pad slots.
        max_n = max(len(ids) for ids in prefix_ids)
        needed = max(max((max_n - m.tokens
                          for ids, m in zip(prefix_ids, matches)
                          if m.tokens < len(ids)), default=1), 1)
        window = paged.pick_window(needed, bucket, ps)
        if window is None:
            return plan                      # cold: unpaged prefill
        w0 = max(max_n - window, 0)
        B = len(prefix_ids)
        slot_src = np.zeros((B, bucket), np.int32)
        rem_ids = []
        for r, (ids, m) in enumerate(zip(prefix_ids, matches)):
            n = len(ids)
            keep = min(m.tokens, w0, n)      # tokens resumed from pages
            for t in range(keep):
                page = m.pages[t // ps]
                slot_src[r, t] = page * ps + t % ps
            rem_ids.append(list(ids[w0:]))   # recompute [w0, n)
            if r < n_real:
                self.prefix_stats.count("hit_tokens", keep)
        rem, rem_mask = tok.right_pad_ids(rem_ids, window,
                                          tok.pad_token_id(self.tokenizer))
        plan.window = window
        plan.w0 = w0
        plan.slot_src = slot_src
        plan.rem = rem
        plan.rem_mask = rem_mask
        return plan

    def _finish_prefix_resume(self, plan: "_PrefixPlan", cache,
                              row_map: Optional[Sequence[int]] = None
                              ) -> None:
        """Insert every full, not-yet-cached prefix page of the dispatch
        into the pool from the FINAL cache (prefix slots survive both
        suffix branches untouched), then drop the dispatch's page pins.
        ``row_map`` maps plan rows to cache rows (the grouped path's
        final cache holds member rows; any member of a group carries the
        group's prefix slots). Newly inserted pages are pinned until the
        scatter lands so a tight pool can never evict-and-reallocate a
        page between its tree insert and its data write."""
        tree = self.prefix_cache
        ps = tree.page_size
        writes = []
        fresh: List[int] = []
        for r, ids in enumerate(plan.prefix_ids):
            start, new_pages = tree.plan_insert(plan.bucket, ids)
            if not new_pages:
                continue
            tree.pool.incref(new_pages)
            fresh.extend(new_pages)
            crow = r if row_map is None else row_map[r]
            # Canonical right-padded layout: slot == token position, so
            # page k's data sits at cache slots [start + k*ps, ...).
            for j, pg in enumerate(new_pages):
                writes.append((pg, crow, start + j * ps))
        tree.pool.scatter(cache, writes)
        tree.pool.decref(fresh)
        for m in plan.matches:
            tree.release(m)

    def _abort_prefix_resume(self, plan: "_PrefixPlan") -> None:
        """Dispatch failed: drop the plan's page pins without inserting
        (there is no final cache to read pages from)."""
        for m in plan.matches:
            self.prefix_cache.release(m)

    def prefill_insert(self, bucket: int,
                       prefix_ids: List[Sequence[int]]) -> int:
        """PREFILL-ONLY dispatch (disaggregated serving — serve/migrate
        .py): compute the rows' prefix KV at the ``bucket`` extent and
        insert every full page into the pool + radix tree, decoding
        NOTHING. The prefill-role replica's unit of work: the pages it
        produces are bitwise the pages a full scoring dispatch of the
        same bucket would have inserted (generate.prefill_cache +
        the same canonical right-padded layout), so a decode replica
        that imports them resumes identically to a colocated run.

        Rows already fully page-covered are skipped (a repeat prefix
        costs nothing); callers pad the row list to a stable batch
        (serve/batcher.ContinuousBatcher.prefill) the same way score
        dispatches pad, so prefill and scoring prefills share XLA
        programs per (bucket, batch) shape. Runs on the owning
        dispatch thread (the tree's single-threaded contract). Returns
        the page-aligned tokens covered for the FIRST row (the
        migration chain's request row)."""
        tree = self.prefix_cache
        assert tree is not None, \
            "prefill_insert needs the prefix cache enabled"
        ps = tree.page_size
        rows = [list(ids)[:bucket] for ids in prefix_ids]
        aligned0 = (len(rows[0]) // ps) * ps
        todo = [ids for ids in rows
                if tree.match_len(bucket, ids) < (len(ids) // ps) * ps]
        if todo:
            pad_id = tok.pad_token_id(self.tokenizer)
            toks_arr, mask = tok.right_pad_ids(todo, bucket, pad_id)
            cache = generate.prefill_cache(
                self.params, self.cfg, jnp.asarray(toks_arr),
                jnp.asarray(mask), prefill_fn=self._prefill_fn)
            writes: List[Tuple[int, int, int]] = []
            fresh: List[int] = []
            for r, ids in enumerate(todo):
                start, new_pages = tree.plan_insert(bucket, ids)
                if not new_pages:
                    continue
                # Pin fresh pages until the scatter lands (the same
                # evict-and-reallocate guard _finish_prefix_resume
                # takes on a tight pool).
                tree.pool.incref(new_pages)
                fresh.extend(new_pages)
                for j, pg in enumerate(new_pages):
                    writes.append((pg, r, start + j * ps))
            tree.pool.scatter(cache, writes)
            tree.pool.decref(fresh)
        return min(tree.match_len(bucket, rows[0]), aligned0)

    def _prefix_plan_or_none(self, bucket: int,
                             prefix_ids: List[Sequence[int]],
                             n_real: Optional[int], total: int,
                             use_prefix_cache: Optional[bool]
                             ) -> Optional["_PrefixPlan"]:
        """Gate + build the prefix plan for one dispatch. None when the
        cache is absent or the caller opted out (``use_prefix_cache``
        False; None means 'use it iff enabled on this engine')."""
        on = (use_prefix_cache if use_prefix_cache is not None
              else self.prefix_cache is not None)
        if not on or self.prefix_cache is None:
            return None
        return self._plan_prefix_resume(
            bucket, prefix_ids, total if n_real is None else n_real)

    def degrade_to_lazy(self) -> None:
        """Degradation-ladder step one (lir_tpu/faults): drop the AOT
        registry so subsequent dispatches fall back to lazy jit — a
        fresh trace excludes a corrupt precompiled executable from the
        fault hypothesis — and reset the donation chain, whose scratch
        buffer a failed dispatch may have consumed or left in an
        undefined state. Both rebuild themselves on demand; the cost is
        one re-trace per shape, paid only after a real failure."""
        self.exec_registry = None
        self.fresh_handoff()

    @property
    def cache_manifest_key(self) -> str:
        """Cache key covering model config, runtime knobs, quant mode,
        mesh, and the bucket ladder (utils/compile_cache.manifest_key) —
        the namespace under which this engine's executables are planned,
        registered, and recorded in the on-disk manifest. Two engines
        differing in ANY of those inputs get different keys, so a
        registry or warmed cache can never serve a stale configuration."""
        if self._manifest_key is None:
            import jax as _jax

            from ..utils import compile_cache

            # Params fingerprint: shapes/dtypes/shardings (never values —
            # executables bind avals only, so same-shape engines with
            # different weights may share executables; differently
            # sharded or dtyped params may not).
            leaves = _jax.tree.leaves(self.params)
            params_fp = [(tuple(getattr(l, "shape", ())),
                          str(getattr(l, "dtype", type(l).__name__)),
                          str(getattr(l, "sharding", None)))
                         for l in leaves]
            self._manifest_key = compile_cache.manifest_key(
                self.cfg, self.rt, buckets=self.buckets,
                quant=compile_cache.quant_mode(self.params),
                mesh={"devices": _jax.device_count(),
                      "platform": _jax.default_backend(),
                      "seq_mesh": self._seq_mesh_note,
                      "params": params_fp})
        return self._manifest_key

    @property
    def digit_stop_mask(self) -> Optional[jax.Array]:
        """(V,) int32 surface-class device array for the confidence early
        stop (tokens.digit_stop_classes), or None when this tokenizer can't
        provide per-token strings (or has no EOS to signal the stop with) —
        callers then decode the full budget."""
        if self._digit_stop_mask is False:
            mask = None
            if self.eos_id is not None:
                with self._tok_lock:
                    m = tok.digit_stop_classes(self.tokenizer,
                                               self.cfg.vocab_size)
                if m is not None:
                    mask = jnp.asarray(m)
            self._digit_stop_mask = mask
        return self._digit_stop_mask

    @property
    def eos_stop_mask(self) -> Optional[jax.Array]:
        """(V,) all-transparent class table (tokens.eos_only_stop_classes)
        arming a pure all-rows-emitted-EOS stop on the sweep's binary
        branch. Gated on :attr:`digit_stop_mask` being available — the
        same real-tokenizer-with-EOS condition — so content-free
        tokenizers (FakeTokenizer) stay fully stop-free on BOTH branches
        and the bench's stop-OFF comparison keeps its meaning."""
        if self.digit_stop_mask is None:
            return None
        if self._eos_stop_mask is None:
            self._eos_stop_mask = jnp.asarray(
                tok.eos_only_stop_classes(self.cfg.vocab_size))
        return self._eos_stop_mask

    @property
    def digit_table(self) -> Tuple[np.ndarray, np.ndarray]:
        """(token ids, values) of single-token integers 0..100, resolved
        once per tokenizer (feeds the weighted-confidence readout)."""
        if self._digit_table is None:
            with self._tok_lock:
                if self._digit_table is None:
                    self._digit_table = tok.integer_token_table(self.tokenizer)
        return self._digit_table

    # -- building blocks ----------------------------------------------------

    def decode_prompts(self, prompts: Sequence[str]
                       ) -> Tuple[jax.Array, jax.Array]:
        """Tokenize once, left-pad into the smallest fitting bucket, run one
        jitted greedy decode. Returns (generated (B, T_new) int32,
        step_logits (B, T_new, V) fp32)."""
        toks, mask = self._pad_batch(prompts)
        if self.encoder_decoder:
            return generate.t5_greedy_decode(
                self.params, self.cfg, toks, mask,
                max_new_tokens=self.rt.max_new_tokens)
        return generate.greedy_decode(
            self.params, self.cfg, toks, mask,
            max_new_tokens=self.rt.max_new_tokens,
            prefill_fn=self._prefill_fn)

    def decode_fused(self, prompts: Sequence[str], yes_ids: np.ndarray,
                     no_ids: np.ndarray, with_digits: bool = False,
                     max_new_tokens: Optional[int] = None,
                     pretokenized: Optional[Sequence[Sequence[int]]] = None,
                     early_stop: bool = False, eos_stop: bool = False):
        """The production scoring path: one jitted decode with the C13/D6
        readouts fused into the scan (no (B, T, V) logit stack). Decoder-only
        models only; T5 keeps the capture path (tiny vocab stacks).

        ``max_new_tokens`` overrides the runtime default (the perturbation
        sweep passes its short per-cell budget, config.RuntimeConfig).
        ``pretokenized`` skips tokenization when the caller already holds
        the token ids (the shared-prefix fallback path). ``early_stop``
        enables the confidence digit early stop (generate._fused_tail);
        ``eos_stop`` the pure all-rows-emitted-EOS stop instead
        (:attr:`eos_stop_mask` — the sweep's binary branch). Both are
        gated on tokenizer support and only valid for calls whose
        downstream readout is position-0 (+ first-integer parse for the
        digit variant)."""
        assert not self.encoder_decoder
        assert not (early_stop and eos_stop), "pick one stop rule"
        toks, mask = self._pad_batch(prompts, pretokenized)
        if with_digits:
            digit_ids, digit_vals = self.digit_table
        else:
            digit_ids = np.zeros((0,), np.int32)
            digit_vals = np.zeros((0,), np.float32)
        stop_mask = (self.digit_stop_mask if early_stop
                     else self.eos_stop_mask if eos_stop else None)
        return generate.greedy_decode_fused(
            self.params, self.cfg, toks, mask,
            jnp.asarray(yes_ids, jnp.int32), jnp.asarray(no_ids, jnp.int32),
            jnp.asarray(digit_ids), jnp.asarray(digit_vals),
            max_new_tokens=(self.rt.max_new_tokens if max_new_tokens is None
                            else max_new_tokens),
            prefill_fn=self._prefill_fn, stop_mask=stop_mask,
            eos_id=(None if stop_mask is None
                    else jnp.int32(self.eos_id)))

    def decode_fused_shared(self, binary_prompts: Sequence[str],
                            confidence_prompts: Sequence[str],
                            yes_ids: np.ndarray, no_ids: np.ndarray,
                            new_tokens: int, conf_tokens: int,
                            early_stop: bool = False,
                            pretokenized_a: Optional[Sequence[Sequence[int]]] = None,
                            pretokenized_b: Optional[Sequence[Sequence[int]]] = None,
                            bucket: Optional[int] = None,
                            sfx_buckets_ab: Optional[Tuple[int, int]] = None,
                            reuse_cache: bool = False,
                            use_prefix_cache: Optional[bool] = None,
                            n_real: Optional[int] = None):
        """Score BOTH sweep formats with ONE shared-prefix prefill.

        Each grid cell's binary and confidence prompts share the long
        rephrased legal text and differ only in the short trailing format
        instruction. Tokenize both, split every row at the longest common
        TOKEN prefix (tokenizer-agnostic — see tokens.shared_prefix_len),
        left-pad the prefixes into the standard bucket and right-pad each
        format's suffix into a small power-of-two bucket, then run
        generate.greedy_decode_fused_shared: one prefill + two chunked
        suffix extensions instead of two full prefills. Returns
        (binary FusedDecodeOut, confidence FusedDecodeOut).

        The ragged scheduler passes ``pretokenized_a/b`` (cells were
        tokenized once at planning time), an explicit prefix ``bucket``
        and per-bucket ``sfx_buckets_ab`` (shape stability across a
        bucket queue), and ``reuse_cache=True`` to thread the KV cache
        buffer through the dispatch chain via donation (_CacheHandoff).
        The fallback guards below still apply and win over the overrides.

        With the cross-request prefix cache enabled (``use_prefix_cache``
        True, or None on an engine whose :attr:`prefix_cache` is built),
        a ``reuse_cache`` dispatch resumes every row's shared prefix from
        the deepest cached radix node: cached pages gather from the page
        pool into the exact slots the left-padded prefill would fill and
        only the per-row remainder window is recomputed
        (generate.greedy_decode_fused_shared_paged) — results BITWISE
        identical to the unpaged path, prefill FLOPs paid only for the
        unshared tail. Fresh full pages insert back into the pool after
        the dispatch, so reuse spans requests, batches, and sweeps.
        ``n_real`` bounds the rows counted in PrefixCacheStats (callers
        pad dispatches by repeating the last row).
        """
        assert not self.encoder_decoder
        if pretokenized_a is not None:
            bin_ids = [list(i) for i in pretokenized_a]
            conf_ids = [list(i) for i in pretokenized_b]
        else:
            with self._tok_lock:
                bin_ids = [self.tokenizer(p).input_ids
                           for p in binary_prompts]
                conf_ids = [self.tokenizer(p).input_ids
                            for p in confidence_prompts]
        lcp = [tok.shared_prefix_len(a, b)
               for a, b in zip(bin_ids, conf_ids)]
        pad_id = tok.pad_token_id(self.tokenizer)
        sfx_buckets = (8, 16, 32, 64, 128, 256)
        sfx_a_ids = [a[n:] for a, n in zip(bin_ids, lcp)]
        sfx_b_ids = [b[n:] for b, n in zip(conf_ids, lcp)]
        max_sfx = max(len(s) for s in sfx_a_ids + sfx_b_ids)
        max_total = max(len(r) for r in bin_ids + conf_ids)
        if bucket is None or bucket < max(max(n, 1) for n in lcp):
            bucket = tok.pick_bucket([max(n, 1) for n in lcp], self.buckets)
        if sfx_buckets_ab is not None:
            ba, bb = sfx_buckets_ab
            ba = max(ba, tok.pick_bucket(
                [len(s) for s in sfx_a_ids], sfx_buckets))
            bb = max(bb, tok.pick_bucket(
                [len(s) for s in sfx_b_ids], sfx_buckets))
        else:
            ba = tok.pick_bucket([len(s) for s in sfx_a_ids], sfx_buckets)
            bb = tok.pick_bucket([len(s) for s in sfx_b_ids], sfx_buckets)
        fallback_reason = None
        if max_sfx > max(sfx_buckets):
            # A suffix longer than the largest bucket would be silently
            # right-truncated — dropping the very instruction the readout
            # depends on. Prompt pairs that diverge this early share too
            # little to be worth a shared prefill anyway.
            fallback_reason = (
                f"a prompt pair diverges {max_sfx} tokens before its end "
                f"(> {max(sfx_buckets)} suffix bucket)")
        elif max_total > max(self.buckets):
            # An over-long TOTAL prompt: the plain path left-truncates the
            # whole prompt into the largest bucket, while the shared path
            # would retain prefix-bucket + suffix-bucket tokens — more
            # context, an unpinned scoring divergence between the two paths
            # (ADVICE r3 #2). The plain path owns over-long semantics.
            fallback_reason = (
                f"a prompt ({max_total} tokens) exceeds the largest "
                f"bucket ({max(self.buckets)})")
        elif (getattr(self.cfg, "pos_embedding", None) == "learned"
              and bucket + max(ba + new_tokens, bb + conf_tokens)
              > self.cfg.max_seq_len):
            # The suffix extension appends past the prefix bucket, so decode
            # positions can reach the shared-decode cache length
            # bucket + max(ba+new, bb+conf) (generate.py T0) — beyond the
            # plain-path limit the constructor's bucket trim enforces. A
            # learned-position table would be read out of range (ADVICE r3
            # #1); the plain path's trimmed buckets stay in range.
            fallback_reason = (
                f"prefix bucket {bucket} + suffix/new-token budget "
                f"{max(ba + new_tokens, bb + conf_tokens)} would overrun "
                f"the {self.cfg.max_seq_len}-row learned-position table")
        if fallback_reason is not None:
            from ..utils.logging import get_logger

            get_logger(__name__).info(
                "shared-prefix fallback: %s — scoring this whole bucket "
                "with two full prefills", fallback_reason)
            fused = self.decode_fused(binary_prompts, yes_ids, no_ids,
                                      max_new_tokens=new_tokens,
                                      pretokenized=bin_ids,
                                      eos_stop=early_stop)
            cfused = self.decode_fused(confidence_prompts, yes_ids, no_ids,
                                       with_digits=True,
                                       max_new_tokens=conf_tokens,
                                       pretokenized=conf_ids,
                                       early_stop=early_stop)
            return fused, cfused
        # Prefix rows are RIGHT-padded — the canonical slot = position
        # layout: a token's cache slot is independent of its row's
        # length, so KV pages produced by any dispatch back any later
        # row sharing the prefix BITWISE (masked tail slots contribute
        # exact zeros either way; the plain decode_fused path keeps the
        # left-padded convention, and the shared-vs-plain comparison
        # was never bitwise). The suffix extensions read per-row
        # boundaries from the mask, so a gap of masked slots between a
        # short row's prefix end and the bucket edge is a no-op.
        prefix, prefix_mask = tok.right_pad_ids(
            [a[:n] for a, n in zip(bin_ids, lcp)], bucket, pad_id)
        sfx_a, sfx_a_mask = tok.right_pad_ids(sfx_a_ids, ba, pad_id)
        sfx_b, sfx_b_mask = tok.right_pad_ids(sfx_b_ids, bb, pad_id)
        digit_ids, digit_vals = self.digit_table
        stop_mask = self.digit_stop_mask if early_stop else None
        kwargs = dict(
            max_new_a=new_tokens, max_new_b=conf_tokens,
            prefill_fn=self._prefill_fn, stop_mask_b=stop_mask,
            stop_mask_a=(None if stop_mask is None else self.eos_stop_mask),
            eos_id=(None if stop_mask is None
                    else jnp.int32(self.eos_id)))
        if reuse_cache:
            prefix_rows = [a[:n] for a, n in zip(bin_ids, lcp)]
            # Shared-prefix cascade prefill: an eligible dispatch takes
            # precedence over speculation AND piggybacking (both
            # optimize around the very prefill the cascade removes —
            # the sweep excludes cascade-eligible dispatches from piggy
            # chains for the same reason). Ineligible-while-enabled
            # counts a dense fallback; the dense path runs verbatim.
            trunk = self.cascade_trunk_for(prefix_rows, n_real, bucket)
            if trunk:
                return self._dispatch_shared_cascade(
                    trunk, bucket, prefix_rows[0][:trunk], prefix,
                    prefix_mask, sfx_a, sfx_a_mask, sfx_b, sfx_b_mask,
                    yes_ids, no_ids, digit_ids, digit_vals, new_tokens,
                    conf_tokens, ba, bb, early_stop,
                    {k: kwargs[k] for k in
                     ("stop_mask_a", "stop_mask_b", "eos_id")},
                    use_prefix_cache, n_real)
            if self.cascade_supported():
                self.cascade_stats.count("dense_fallbacks")
            # Cascade DECODE without cascade prefill: a dispatch that
            # runs its prefill dense (cascade prefill off, ineligible,
            # or superseded by a paged-warm front) still shares its
            # trunk slots row-for-row, so every decode step's trunk
            # splits can read the trunk KV once per kv head instead of
            # once per row (ops/flash_decode trunk variants — bitwise
            # the flat kernels). The extent is a static compiled shape;
            # compile_plan keys the shared executables on it.
            dtrunk = self.decode_trunk_for(prefix_rows, n_real, bucket)
            plan = self._prefix_plan_or_none(
                bucket, prefix_rows, n_real,
                len(bin_ids), use_prefix_cache)
            # Speculative decode (engine/spec.py): draft each branch's
            # continuation and verify the window in one multi-query
            # forward. Results are bitwise the sequential executable's;
            # a fleet draft model can't ride the paged front (the paged
            # executable binds slot tables, not prefix tokens), so that
            # combination falls back to the sequential paged path.
            splan = spec_mod.build_plan(self, bin_ids, conf_ids, bucket,
                                        ba, bb, new_tokens, conf_tokens)
            if (splan is not None and self.governor is not None
                    and not self.governor.allows("spec")):
                # Governor no_spec rung: the sequential executable is
                # bitwise-identical, so shedding speculation is a pure
                # HBM reclaim (the spec cache runs spec_k extra slots
                # per window). Re-arms when pressure clears.
                splan = None
                self.spec_stats.count("fallbacks")
            paged_warm = plan is not None and plan.window is not None
            if splan is not None and paged_warm and splan.fleet:
                splan = None
                self.spec_stats.count("fallbacks")
            # Paged and unpaged dispatches of one shape return the same
            # cache aval, so they share one handoff key — the donation
            # chain runs unbroken across cold and warm dispatches. The
            # speculative cache is LONGER (spec_k slots per decode
            # window), so speculative dispatches chain on their own key.
            key = ("shared", bucket, len(bin_ids), ba, bb, new_tokens,
                   conf_tokens, early_stop,
                   None if splan is None else (splan.k, splan.fleet))
            scratch = self._handoff.take(key)
            stop_kwargs = {k: kwargs[k] for k in
                           ("stop_mask_a", "stop_mask_b", "eos_id")}
            if splan is not None:
                try:
                    out = self._dispatch_shared_spec(
                        splan, plan, paged_warm, bucket, prefix,
                        prefix_mask, sfx_a, sfx_a_mask, sfx_b, sfx_b_mask,
                        yes_ids, no_ids, digit_ids, digit_vals,
                        new_tokens, conf_tokens, stop_kwargs, scratch,
                        ba, bb, dtrunk)
                except BaseException:
                    if plan is not None:
                        self._abort_prefix_resume(plan)
                    raise
                fused, cfused, spec_a, spec_b, cache = out
                self._spec_pending.append((spec_a, spec_b))
                self.spec_stats.count("spec_dispatches")
                self.spec_stats.count(
                    "spec_rows", len(bin_ids) if n_real is None else n_real)
                self._handoff.put(key, cache)
                self._note_handoff(cache)
                if plan is not None:
                    self._finish_prefix_resume(plan, cache)
                self._note_cascade_decode(
                    dtrunk, len(bin_ids) if n_real is None else n_real,
                    bucket, ba, bb, new_tokens, conf_tokens)
                return fused, cfused
            try:
                if plan is not None and plan.window is not None:
                    dyn_args = (self.params, self.prefix_cache.pool.leaves,
                                jnp.asarray(plan.slot_src),
                                jnp.int32(plan.w0),
                                jnp.asarray(prefix_mask),
                                jnp.asarray(plan.rem),
                                jnp.asarray(plan.rem_mask),
                                jnp.asarray(sfx_a), jnp.asarray(sfx_a_mask),
                                jnp.asarray(sfx_b), jnp.asarray(sfx_b_mask),
                                jnp.asarray(yes_ids, jnp.int32),
                                jnp.asarray(no_ids, jnp.int32),
                                jnp.asarray(digit_ids),
                                jnp.asarray(digit_vals))
                    exe = None
                    if self.exec_registry is not None:
                        exe = self.exec_registry.get(
                            compile_plan.shared_paged_spec(
                                bucket, len(bin_ids), plan.window, ba, bb,
                                new_tokens, conf_tokens,
                                stops_armed=stop_mask is not None,
                                scratch=scratch is not None,
                                decode_trunk=dtrunk))
                    if exe is not None:
                        fused, cfused, cache = compile_plan.registry_call(
                            exe, dyn_args, stop_kwargs, scratch)
                    else:
                        fused, cfused, cache = (
                            generate.greedy_decode_fused_shared_paged(
                                dyn_args[0], self.cfg, *dyn_args[1:],
                                max_new_a=new_tokens, max_new_b=conf_tokens,
                                return_cache=True, scratch_cache=scratch,
                                decode_trunk=dtrunk, **stop_kwargs))
                else:
                    dyn_args = (self.params, jnp.asarray(prefix),
                                jnp.asarray(prefix_mask), jnp.asarray(sfx_a),
                                jnp.asarray(sfx_a_mask), jnp.asarray(sfx_b),
                                jnp.asarray(sfx_b_mask),
                                jnp.asarray(yes_ids, jnp.int32),
                                jnp.asarray(no_ids, jnp.int32),
                                jnp.asarray(digit_ids),
                                jnp.asarray(digit_vals))
                    exe = None
                    if self.exec_registry is not None:
                        exe = self.exec_registry.get(compile_plan.shared_spec(
                            bucket, len(bin_ids), ba, bb, new_tokens,
                            conf_tokens, stops_armed=stop_mask is not None,
                            scratch=scratch is not None,
                            decode_trunk=dtrunk))
                    if exe is not None:
                        fused, cfused, cache = compile_plan.registry_call(
                            exe, dyn_args, stop_kwargs, scratch)
                    else:
                        fused, cfused, cache = (
                            generate.greedy_decode_fused_shared(
                                dyn_args[0], self.cfg, *dyn_args[1:],
                                return_cache=True, scratch_cache=scratch,
                                decode_trunk=dtrunk, **kwargs))
            except BaseException:
                if plan is not None:
                    self._abort_prefix_resume(plan)
                raise
            self._handoff.put(key, cache)
            self._note_handoff(cache)
            if plan is not None:
                self._finish_prefix_resume(plan, cache)
            self._note_cascade_decode(
                dtrunk, len(bin_ids) if n_real is None else n_real,
                bucket, ba, bb, new_tokens, conf_tokens)
            return fused, cfused
        return generate.greedy_decode_fused_shared(
            self.params, self.cfg, jnp.asarray(prefix),
            jnp.asarray(prefix_mask), jnp.asarray(sfx_a),
            jnp.asarray(sfx_a_mask), jnp.asarray(sfx_b),
            jnp.asarray(sfx_b_mask),
            jnp.asarray(yes_ids, jnp.int32), jnp.asarray(no_ids, jnp.int32),
            jnp.asarray(digit_ids), jnp.asarray(digit_vals), **kwargs)

    def _dispatch_shared_spec(self, splan, plan, paged_warm: bool,
                              bucket: int, prefix, prefix_mask, sfx_a,
                              sfx_a_mask, sfx_b, sfx_b_mask, yes_ids,
                              no_ids, digit_ids, digit_vals,
                              new_tokens: int, conf_tokens: int,
                              stop_kwargs: dict, scratch, ba: int,
                              bb: int, dtrunk: int = 0):
        """One SPECULATIVE shared dispatch (registry executable when
        planned, lazy jit otherwise): the unpaged prefill front or the
        radix-paged resume front, then both branches' draft-and-verify
        tails. ``dtrunk`` > 0 runs every verify window's trunk splits
        trunk-aware (cascade decode — the verifier's multi-query
        flash_decode_mq_trunk; the fleet draft model stays flat, its
        drafts are quality-only). Returns (fused, cfused, SpecOut_a,
        SpecOut_b, cache)."""
        armed = stop_kwargs.get("eos_id") is not None
        spec_args = tuple(jnp.asarray(x) for x in splan.dyn_args())
        if paged_warm:
            dyn_args = (self.params, self.prefix_cache.pool.leaves,
                        jnp.asarray(plan.slot_src), jnp.int32(plan.w0),
                        jnp.asarray(prefix_mask), jnp.asarray(plan.rem),
                        jnp.asarray(plan.rem_mask),
                        jnp.asarray(sfx_a), jnp.asarray(sfx_a_mask),
                        jnp.asarray(sfx_b), jnp.asarray(sfx_b_mask),
                        jnp.asarray(yes_ids, jnp.int32),
                        jnp.asarray(no_ids, jnp.int32),
                        jnp.asarray(digit_ids),
                        jnp.asarray(digit_vals)) + spec_args
            exe = None
            if self.exec_registry is not None:
                exe = self.exec_registry.get(compile_plan.shared_paged_spec(
                    bucket, len(prefix_mask), plan.window, ba, bb,
                    new_tokens, conf_tokens, stops_armed=armed,
                    scratch=scratch is not None, spec_k=splan.k,
                    decode_trunk=dtrunk))
            if exe is not None:
                out = compile_plan.registry_call(exe, dyn_args,
                                                 stop_kwargs, scratch)
            else:
                out = generate.greedy_decode_fused_shared_paged_spec(
                    dyn_args[0], self.cfg, *dyn_args[1:],
                    max_new_a=new_tokens, max_new_b=conf_tokens,
                    spec_k=splan.k, ngram=splan.ngram, return_cache=True,
                    scratch_cache=scratch, decode_trunk=dtrunk,
                    **stop_kwargs)
        else:
            draft_params, draft_cfg = None, None
            if splan.fleet:
                draft_params, draft_cfg, _ = self._spec_draft
            dyn_args = (self.params, jnp.asarray(prefix),
                        jnp.asarray(prefix_mask), jnp.asarray(sfx_a),
                        jnp.asarray(sfx_a_mask), jnp.asarray(sfx_b),
                        jnp.asarray(sfx_b_mask),
                        jnp.asarray(yes_ids, jnp.int32),
                        jnp.asarray(no_ids, jnp.int32),
                        jnp.asarray(digit_ids),
                        jnp.asarray(digit_vals)) + spec_args
            exe = None
            if self.exec_registry is not None:
                exe = self.exec_registry.get(compile_plan.shared_spec(
                    bucket, len(prefix_mask), ba, bb, new_tokens,
                    conf_tokens, stops_armed=armed,
                    scratch=scratch is not None,
                    spec_k=splan.k, spec_draft=splan.fleet,
                    decode_trunk=dtrunk))
            if exe is not None:
                out = compile_plan.registry_call(
                    exe, dyn_args,
                    dict(stop_kwargs, draft_params=draft_params), scratch)
            else:
                out = generate.greedy_decode_fused_shared_spec(
                    dyn_args[0], self.cfg, *dyn_args[1:],
                    max_new_a=new_tokens, max_new_b=conf_tokens,
                    spec_k=splan.k, ngram=splan.ngram,
                    prefill_fn=self._prefill_fn,
                    draft_params=draft_params, draft_cfg=draft_cfg,
                    return_cache=True, scratch_cache=scratch,
                    decode_trunk=dtrunk, **stop_kwargs)
        return out

    def _dispatch_shared_cascade(self, trunk: int, bucket: int,
                                 trunk_ids: Sequence[int], prefix,
                                 prefix_mask, sfx_a, sfx_a_mask, sfx_b,
                                 sfx_b_mask, yes_ids, no_ids, digit_ids,
                                 digit_vals, new_tokens: int,
                                 conf_tokens: int, ba: int, bb: int,
                                 early_stop: bool, stop_kwargs: dict,
                                 use_prefix_cache, n_real: Optional[int]):
        """One CASCADE shared dispatch (registry executable when planned,
        lazy jit otherwise): the batch-1 trunk prefill — cold, or resumed
        warm from the radix page pool — then the per-row cascade
        remainder extension and both branches' fused tails
        (generate.greedy_decode_fused_shared_cascade[_paged]).

        The warm trunk lives in the TRUNK-extent radix namespace (pages
        are bitwise-reproducible only within one attention extent —
        prefix_tree's per-bucket rule — and the cascade trunk prefills
        at extent ``trunk``, not ``bucket``): a one-row plan over the
        trunk ids, whose pages the cold dispatch inserts from cache
        row 0's broadcast trunk slots, so the SECOND dispatch sharing a
        trunk gathers it at zero recompute. The cascade cache aval
        equals the dense shared path's, so both share one donation-chain
        key — the handoff runs unbroken across cascade and dense
        dispatches of a bucket queue."""
        B = len(prefix_mask)
        plan = self._prefix_plan_or_none(trunk, [list(trunk_ids)], 1, 1,
                                         use_prefix_cache)
        paged_warm = plan is not None and plan.window is not None
        key = ("shared", bucket, B, ba, bb, new_tokens, conf_tokens,
               early_stop, None)
        scratch = self._handoff.take(key)
        armed = stop_kwargs.get("eos_id") is not None
        int8 = bool(self.cascade_cfg.int8_qk)
        statics = dict(max_new_a=new_tokens, max_new_b=conf_tokens,
                       trunk_len=trunk, int8_qk=int8, return_cache=True)
        try:
            if paged_warm:
                trunk_mask = np.ones((1, trunk), np.int32)
                dyn_args = (self.params, self.prefix_cache.pool.leaves,
                            jnp.asarray(plan.slot_src), jnp.int32(plan.w0),
                            jnp.asarray(trunk_mask),
                            jnp.asarray(plan.rem),
                            jnp.asarray(plan.rem_mask),
                            jnp.asarray(prefix), jnp.asarray(prefix_mask),
                            jnp.asarray(sfx_a), jnp.asarray(sfx_a_mask),
                            jnp.asarray(sfx_b), jnp.asarray(sfx_b_mask),
                            jnp.asarray(yes_ids, jnp.int32),
                            jnp.asarray(no_ids, jnp.int32),
                            jnp.asarray(digit_ids),
                            jnp.asarray(digit_vals))
                exe = None
                if self.exec_registry is not None:
                    exe = self.exec_registry.get(
                        compile_plan.shared_cascade_paged_spec(
                            bucket, B, trunk, plan.window, ba, bb,
                            new_tokens, conf_tokens, stops_armed=armed,
                            scratch=scratch is not None, int8_qk=int8))
                if exe is not None:
                    fused, cfused, cache = compile_plan.registry_call(
                        exe, dyn_args, stop_kwargs, scratch)
                else:
                    fused, cfused, cache = (
                        generate.greedy_decode_fused_shared_cascade_paged(
                            dyn_args[0], self.cfg, *dyn_args[1:],
                            scratch_cache=scratch, **stop_kwargs,
                            **statics))
            else:
                dyn_args = (self.params, jnp.asarray(prefix),
                            jnp.asarray(prefix_mask), jnp.asarray(sfx_a),
                            jnp.asarray(sfx_a_mask), jnp.asarray(sfx_b),
                            jnp.asarray(sfx_b_mask),
                            jnp.asarray(yes_ids, jnp.int32),
                            jnp.asarray(no_ids, jnp.int32),
                            jnp.asarray(digit_ids),
                            jnp.asarray(digit_vals))
                exe = None
                if self.exec_registry is not None:
                    exe = self.exec_registry.get(
                        compile_plan.shared_cascade_spec(
                            bucket, B, trunk, ba, bb, new_tokens,
                            conf_tokens, stops_armed=armed,
                            scratch=scratch is not None, int8_qk=int8))
                if exe is not None:
                    fused, cfused, cache = compile_plan.registry_call(
                        exe, dyn_args, stop_kwargs, scratch)
                else:
                    fused, cfused, cache = (
                        generate.greedy_decode_fused_shared_cascade(
                            dyn_args[0], self.cfg, *dyn_args[1:],
                            scratch_cache=scratch, **stop_kwargs,
                            **statics))
        except BaseException:
            if plan is not None:
                self._abort_prefix_resume(plan)
            raise
        self._handoff.put(key, cache)
        self._note_handoff(cache)
        if plan is not None:
            # Cache row 0's trunk slots hold the broadcast trunk KV —
            # exactly the batch-1 trunk prefill's values — so the
            # standard insert path pages them into the trunk namespace.
            self._finish_prefix_resume(plan, cache)
        rows = B if n_real is None else n_real
        self.cascade_stats.count("cascade_dispatches")
        self.cascade_stats.count("trunk_rows_deduped", max(rows - 1, 0))
        self.cascade_stats.count(
            "prefix_flops_saved",
            int(cascade_prefill_flops_saved(self.cfg, rows, trunk)))
        # The cascade dispatch's decode scans ride the trunk-aware flash
        # kernels too (generate._cascade_branches passes the trunk
        # through) — count that side's dedup where the kernels actually
        # run (the decode gate, not the prefill one).
        if self.cascade_decode_supported():
            self._note_cascade_decode(trunk, rows, bucket, ba, bb,
                                      new_tokens, conf_tokens)
        return fused, cfused

    # -- chunked prefill/decode piggybacking --------------------------------

    def piggyback_supported(self) -> bool:
        """Engine-level gate for the piggyback chain: on by config, plain
        decoder engines only (T5 and seq-parallel prefills keep their own
        paths), unpaged dispatches only (the prefix-cache resume path owns
        warm traffic), and never on a fault-wrapped engine — wrap_engine
        shadows the plain entry points at the instance level, and the
        chain must not bypass the injected dispatch sites."""
        return (self.rt.piggyback_prefill
                and not self.encoder_decoder
                and self._prefill_fn is None
                and self.prefix_cache is None
                and "decode_fused_shared" not in self.__dict__)

    def _piggyback_fits(self, bsz: int, total_len: int) -> bool:
        """HBM headroom gate: a piggybacked pair keeps TWO dispatch caches
        live (the parked carry + the riding dispatch's own), where the
        sequential path holds one. With a governed budget the check is
        an admission against the governor's LEDGER (params, pool, pins
        and the parked carry all already counted); otherwise it falls
        back to the raw device bytes_limit. Backends without either
        (CPU) are governed by host RAM and always pass."""
        aval = self._cache_aval()  # built at batch 1, 8 slots
        per_row_slot = sum(
            leaf.size * jnp.dtype(leaf.dtype).itemsize
            for leaf in jax.tree.leaves(aval)) / 8
        cache_bytes = per_row_slot * bsz * total_len
        if self.governor is not None:
            if not self.governor.allows("piggyback"):
                return False
            headroom = self.governor.headroom()
            if headroom is not None:
                # The ledger already carries the parked carry under
                # "handoff"; the riding dispatch's own cache (plus
                # fragmentation slack) must fit what is left.
                return 1.2 * cache_bytes < headroom
        try:
            stats = jax.devices()[0].memory_stats() or {}
            limit = stats.get("bytes_limit")
        except Exception:  # noqa: BLE001 — no stats, no gate
            limit = None
        if not limit:
            return True
        return (quant.param_bytes(self.params) + 2.2 * cache_bytes
                < 0.92 * limit)

    def decode_fused_shared_piggy(
            self, pretokenized_a: Sequence[Sequence[int]],
            pretokenized_b: Sequence[Sequence[int]],
            new_tokens: int, conf_tokens: int, early_stop: bool,
            bucket: int, sfx_buckets_ab: Tuple[int, int],
            prev_yes: Optional[np.ndarray] = None,
            prev_no: Optional[np.ndarray] = None):
        """Submit one shared dispatch into the piggyback chain.

        First call of a chain runs the dispatch's prefill + suffix
        extensions and PARKS its decode scans (returns None); every later
        call fuses the parked dispatch's decode scans into its own
        prefill program (ONE device call) and returns the parked
        dispatch's (binary, confidence) outputs, scored against
        ``prev_yes``/``prev_no`` — the target ids of the PARKED batch.
        Shapes/budgets must match the parked dispatch exactly (the sweep
        only chains same-shape dispatches; asserted here). Raises
        :class:`PiggybackIneligible` when this dispatch needs the plain
        path (layout fallback, learned-position ceiling at the piggyback
        cache length, or no memory headroom for two live caches)."""
        assert not self.encoder_decoder
        bin_ids = [list(i) for i in pretokenized_a]
        conf_ids = [list(i) for i in pretokenized_b]
        lcp = [tok.shared_prefix_len(a, b)
               for a, b in zip(bin_ids, conf_ids)]
        pad_id = tok.pad_token_id(self.tokenizer)
        sfx_a_ids = [a[n:] for a, n in zip(bin_ids, lcp)]
        sfx_b_ids = [b[n:] for b, n in zip(conf_ids, lcp)]
        max_sfx = max(len(s) for s in sfx_a_ids + sfx_b_ids)
        max_total = max(len(r) for r in bin_ids + conf_ids)
        sfx_buckets = scheduler_mod.SUFFIX_BUCKETS
        ba, bb = sfx_buckets_ab
        ba = max(ba, tok.pick_bucket([len(s) for s in sfx_a_ids],
                                     sfx_buckets))
        bb = max(bb, tok.pick_bucket([len(s) for s in sfx_b_ids],
                                     sfx_buckets))
        total_len = bucket + ba + new_tokens + bb + conf_tokens
        if (max_sfx > max(sfx_buckets)
                or max_total > max(self.buckets)
                or bucket < max(max(n, 1) for n in lcp)):
            raise PiggybackIneligible("shared-prefix layout fallback")
        if (getattr(self.cfg, "pos_embedding", None) == "learned"
                and total_len > self.cfg.max_seq_len):
            # The piggyback cache is LONGER than the sequential one
            # (disjoint branch regions), so its learned-position ceiling
            # binds earlier than the plain path's.
            raise PiggybackIneligible("learned-position table overrun")
        if (self.governor is not None
                and not self.governor.allows("piggyback")):
            # Governor no_piggyback rung engaged: the chain's second
            # live cache is the cheapest reversible HBM to give back.
            # The sweep keeps asking per dispatch, so chaining resumes
            # the moment the rung re-arms.
            raise PiggybackIneligible(
                "memory governor: piggyback disabled under pressure")
        if not self._piggyback_fits(len(bin_ids), total_len):
            raise PiggybackIneligible("no HBM headroom for two caches")

        prefix, prefix_mask = tok.right_pad_ids(
            [a[:n] for a, n in zip(bin_ids, lcp)], bucket, pad_id)
        sfx_a, sfx_a_mask = tok.right_pad_ids(sfx_a_ids, ba, pad_id)
        sfx_b, sfx_b_mask = tok.right_pad_ids(sfx_b_ids, bb, pad_id)
        stop_mask = self.digit_stop_mask if early_stop else None
        armed = stop_mask is not None
        key = (bucket, len(bin_ids), ba, bb, new_tokens, conf_tokens,
               armed)
        dispatch_args = (jnp.asarray(prefix), jnp.asarray(prefix_mask),
                         jnp.asarray(sfx_a), jnp.asarray(sfx_a_mask),
                         jnp.asarray(sfx_b), jnp.asarray(sfx_b_mask))
        if self._piggy is None:
            exe = None
            if self.exec_registry is not None:
                exe = self.exec_registry.get(compile_plan.piggy_prefill_spec(
                    bucket, len(bin_ids), ba, bb, new_tokens, conf_tokens))
            if exe is not None:
                carry = exe(self.params, *dispatch_args)
            else:
                carry = generate.shared_piggyback_prefill(
                    self.params, self.cfg, *dispatch_args,
                    max_new_a=new_tokens, max_new_b=conf_tokens)
            self._piggy = dict(key=key, carry=carry,
                               slot0_a=bucket + ba,
                               slot0_b=bucket + ba + new_tokens + bb,
                               new_tokens=new_tokens,
                               conf_tokens=conf_tokens, armed=armed)
            self.kernel_stats.count("chains_opened")
            return None
        assert self._piggy["key"] == key, (
            "piggyback chain shape mismatch — drain before switching "
            f"shapes ({self._piggy['key']} vs {key})")
        carry = self._piggy["carry"]
        stop_kwargs = self._piggy_stop_kwargs()
        digit_ids, digit_vals = self.digit_table
        exe = None
        if self.exec_registry is not None:
            exe = self.exec_registry.get(compile_plan.piggy_step_spec(
                bucket, len(bin_ids), ba, bb, new_tokens, conf_tokens,
                stops_armed=armed))
        dyn = (self.params, carry) + dispatch_args + (
            jnp.asarray(prev_yes, jnp.int32), jnp.asarray(prev_no, jnp.int32),
            jnp.asarray(digit_ids), jnp.asarray(digit_vals))
        if exe is not None:
            out_a, out_b, new_carry = exe(*dyn, **stop_kwargs)
        else:
            out_a, out_b, new_carry = generate.shared_piggyback_step(
                dyn[0], self.cfg, *dyn[1:], max_new_a=new_tokens,
                max_new_b=conf_tokens, **stop_kwargs)
        self._piggy["carry"] = new_carry
        self.kernel_stats.count("piggybacked_steps")
        return out_a, out_b

    def _piggy_stop_kwargs(self) -> dict:
        if not self._piggy["armed"]:
            return dict(stop_mask_a=None, stop_mask_b=None, eos_id=None)
        return dict(stop_mask_a=self.eos_stop_mask,
                    stop_mask_b=self.digit_stop_mask,
                    eos_id=jnp.int32(self.eos_id))

    def piggy_pending(self) -> bool:
        return self._piggy is not None

    def piggy_drain(self, prev_yes: np.ndarray, prev_no: np.ndarray):
        """Close the chain: run the parked dispatch's decode scans alone
        and return its (binary, confidence) outputs."""
        st = self._piggy
        assert st is not None, "no piggyback chain to drain"
        digit_ids, digit_vals = self.digit_table
        key = st["key"]
        exe = None
        if self.exec_registry is not None:
            exe = self.exec_registry.get(compile_plan.piggy_drain_spec(
                key[0], key[1], key[2], key[3], st["new_tokens"],
                st["conf_tokens"], stops_armed=st["armed"]))
        dyn = (self.params, st["carry"],
               jnp.asarray(prev_yes, jnp.int32),
               jnp.asarray(prev_no, jnp.int32),
               jnp.asarray(digit_ids), jnp.asarray(digit_vals))
        stop_kwargs = self._piggy_stop_kwargs()
        self._piggy = None
        self.kernel_stats.count("chains_drained")
        if exe is not None:
            return exe(*dyn, **stop_kwargs)
        return generate.shared_piggyback_drain(
            dyn[0], self.cfg, *dyn[1:], slot0_a=st["slot0_a"],
            slot0_b=st["slot0_b"], max_new_a=st["new_tokens"],
            max_new_b=st["conf_tokens"], **stop_kwargs)

    def piggy_abort(self) -> None:
        """Drop the chain (a failed piggyback call): the parked dispatch's
        carry may have been consumed by donation — the caller re-runs both
        dispatches through the plain path, which recomputes from scratch."""
        if self._piggy is not None:
            self.kernel_stats.count("chain_fallbacks")
        self._piggy = None

    def decode_fused_grouped(self, groups, yes_ids: np.ndarray,
                             no_ids: np.ndarray, new_tokens: int,
                             conf_tokens: int, early_stop: bool,
                             bucket: int, sfx_bucket: int,
                             reuse_cache: bool = False,
                             use_prefix_cache: Optional[bool] = None):
        """Cross-cell prefix reuse: score every member prompt of
        ``groups`` (scheduler.PrefixGroup-shaped: ``.items`` with
        ``.bin_ids``/``.conf_ids``, shared ``.plen``) with ONE prefill per
        group. Member rows are laid out [bin, conf] per cell, cells in
        group order; ``yes_ids``/``no_ids`` are per-CELL in that order.

        Returns (FusedDecodeOut over the padded member batch, real member
        row count) — callers slice even rows for the binary readout and
        odd rows for the confidence readout. Both formats run one shared
        decode budget max(new_tokens, conf_tokens); with ``early_stop``
        the binary rows take the EOS-only stop table and the confidence
        rows the digit stop (per-row selection, generate._fused_tail), so
        the extra binary steps retire the moment the row answers.
        """
        assert not self.encoder_decoder
        pad_id = tok.pad_token_id(self.tokenizer)
        prefix_ids, sfx_ids, group_idx, cell_rows = [], [], [], 0
        for g in groups:
            gi = len(prefix_ids)
            prefix_ids.append(list(g.items[0].bin_ids[:g.plen]))
            for it in g.items:
                sfx_ids.append(list(it.bin_ids[g.plen:]))
                sfx_ids.append(list(it.conf_ids[g.plen:]))
                group_idx += [gi, gi]
                cell_rows += 1
        m = len(sfx_ids)
        g_pad = _tail_batch(len(prefix_ids), self.rt.batch_size)
        m_pad = _tail_batch(m, 2 * self.rt.batch_size)
        prefix_ids += [prefix_ids[-1]] * (g_pad - len(prefix_ids))
        sfx_ids += [sfx_ids[-1]] * (m_pad - m)
        group_idx += [group_idx[-1]] * (m_pad - m)
        if max(len(p) for p in prefix_ids) > bucket:
            raise ValueError("scheduler planned a group prefix longer than "
                             "its bucket")  # planning bug, never truncate
        if (getattr(self.cfg, "pos_embedding", None) == "learned"
                and bucket + sfx_bucket + max(new_tokens, conf_tokens)
                > self.cfg.max_seq_len):
            raise ValueError("scheduler planned a grouped dispatch past the "
                             "learned-position table")

        # RIGHT-padded group prefixes — the canonical slot = position
        # layout (see decode_fused_shared): group prefix KV pages are
        # then bitwise-valid for any later dispatch sharing the trunk.
        prefix, prefix_mask = tok.right_pad_ids(prefix_ids, bucket, pad_id)
        sfx, sfx_mask = tok.right_pad_ids(sfx_ids, sfx_bucket, pad_id)
        yes2 = np.repeat(np.asarray(yes_ids, np.int32), 2)
        no2 = np.repeat(np.asarray(no_ids, np.int32), 2)
        yes2 = np.concatenate([yes2, np.repeat(yes2[-1:], m_pad - m)])
        no2 = np.concatenate([no2, np.repeat(no2[-1:], m_pad - m)])
        digit_ids, digit_vals = self.digit_table
        stop_mask = self.digit_stop_mask if early_stop else None
        kwargs = dict(
            max_new=max(new_tokens, conf_tokens),
            prefill_fn=self._prefill_fn,
            stop_mask=(None if stop_mask is None else self.eos_stop_mask),
            stop_mask2=stop_mask,
            stop_sel=(None if stop_mask is None else
                      jnp.asarray(np.arange(m_pad) % 2 == 1)),
            eos_id=(None if stop_mask is None else jnp.int32(self.eos_id)))
        args = (self.params, self.cfg, jnp.asarray(prefix),
                jnp.asarray(prefix_mask), jnp.asarray(sfx),
                jnp.asarray(sfx_mask),
                jnp.asarray(np.asarray(group_idx, np.int32)),
                jnp.asarray(yes2), jnp.asarray(no2),
                jnp.asarray(digit_ids), jnp.asarray(digit_vals))
        if reuse_cache:
            # Plan rows are the PADDED prefix rows; the final cache holds
            # member rows, and any member of a group carries the group's
            # prefix slots — row_map points each prefix row at its
            # group's first member row for the page extraction.
            first_member = []
            acc = 0
            for g in groups:
                first_member.append(acc)
                acc += 2 * len(g.items)
            first_member += [first_member[-1]] * (g_pad - len(groups))
            plan = self._prefix_plan_or_none(
                bucket, prefix_ids, len(groups), g_pad, use_prefix_cache)
            key = ("grouped", bucket, g_pad, m_pad, sfx_bucket,
                   kwargs["max_new"], early_stop)
            scratch = self._handoff.take(key)
            stop_kwargs = {k: kwargs[k] for k in
                           ("stop_mask", "stop_mask2", "stop_sel",
                            "eos_id")}
            try:
                if plan is not None and plan.window is not None:
                    dyn_args = (self.params, self.prefix_cache.pool.leaves,
                                jnp.asarray(plan.slot_src),
                                jnp.int32(plan.w0),
                                jnp.asarray(prefix_mask),
                                jnp.asarray(plan.rem),
                                jnp.asarray(plan.rem_mask),
                                args[4], args[5], args[6], args[7],
                                args[8], args[9], args[10])
                    exe = None
                    if self.exec_registry is not None:
                        exe = self.exec_registry.get(
                            compile_plan.grouped_paged_spec(
                                bucket, g_pad, m_pad, plan.window,
                                sfx_bucket, kwargs["max_new"],
                                stops_armed=stop_mask is not None,
                                scratch=scratch is not None))
                    if exe is not None:
                        out, cache = compile_plan.registry_call(
                            exe, dyn_args, stop_kwargs, scratch)
                    else:
                        out, cache = (
                            generate.greedy_decode_fused_grouped_paged(
                                dyn_args[0], self.cfg, *dyn_args[1:],
                                max_new=kwargs["max_new"],
                                return_cache=True, scratch_cache=scratch,
                                **stop_kwargs))
                else:
                    exe = None
                    if self.exec_registry is not None:
                        exe = self.exec_registry.get(compile_plan.grouped_spec(
                            bucket, g_pad, m_pad, sfx_bucket,
                            kwargs["max_new"],
                            stops_armed=stop_mask is not None,
                            scratch=scratch is not None))
                    if exe is not None:
                        out, cache = compile_plan.registry_call(
                            exe, (args[0],) + args[2:], stop_kwargs, scratch)
                    else:
                        out, cache = generate.greedy_decode_fused_grouped(
                            *args, return_cache=True, scratch_cache=scratch,
                            **kwargs)
            except BaseException:
                if plan is not None:
                    self._abort_prefix_resume(plan)
                raise
            self._handoff.put(key, cache)
            self._note_handoff(cache)
            if plan is not None:
                self._finish_prefix_resume(plan, cache,
                                           row_map=first_member)
        else:
            out = generate.greedy_decode_fused_grouped(*args, **kwargs)
        return out, m

    def decode_completion(self, generated_ids: np.ndarray) -> str:
        """Token ids -> text, stopping at the first EOS (HF generate parity —
        the fixed-length jitted decode keeps emitting after EOS; those tokens
        must not leak into response text or the confidence-integer parse)."""
        trimmed = tok.trim_at_eos(np.asarray(generated_ids).tolist(), self.eos_id)
        with self._tok_lock:
            return self.tokenizer.decode(
                trimmed, skip_special_tokens=True).strip()

    def _pad_batch(self, prompts: Sequence[str],
                   pretokenized: Optional[Sequence[Sequence[int]]] = None
                   ) -> Tuple[jax.Array, jax.Array]:
        """Tokenize + left-pad into the smallest fitting bucket."""
        if pretokenized is not None:
            ids_list = list(pretokenized)
        else:
            with self._tok_lock:
                ids_list = [self.tokenizer(p).input_ids for p in prompts]
        bucket = tok.pick_bucket([len(i) for i in ids_list], self.buckets)
        toks_arr, mask = tok.left_pad_ids(ids_list, bucket,
                                          tok.pad_token_id(self.tokenizer))
        return jnp.asarray(toks_arr), jnp.asarray(mask)

    def _sample_from_ids(self, toks: jax.Array, mask: jax.Array,
                         key: jax.Array, temperature: float,
                         max_new_tokens: Optional[int]) -> List[str]:
        return self._sample_from_ids_raw(toks, mask, key, temperature,
                                         max_new_tokens)[0]

    def _sample_from_ids_raw(self, toks: jax.Array, mask: jax.Array,
                             key: jax.Array, temperature: float,
                             max_new_tokens: Optional[int]
                             ) -> Tuple[List[str], np.ndarray]:
        """(decoded texts, raw generated ids) — callers that must know
        whether the reply finished inside the budget (EOS emitted) need the
        ids, not just the EOS-trimmed text."""
        gen = generate.sample_decode(
            self.params, self.cfg, toks, mask, key, temperature=temperature,
            max_new_tokens=(self.rt.max_new_tokens if max_new_tokens is None
                            else max_new_tokens),
            prefill_fn=self._prefill_fn,
            # HF/API-parity EOS stop: a finished row emits EOS fill (so
            # the finished-inside-budget signal this method documents is
            # preserved) and an all-done batch skips the remaining
            # forwards; unfinished rows are bit-identical to the
            # unstopped sampler.
            eos_id=(None if self.eos_id is None
                    else jnp.int32(self.eos_id)))
        gen = np.asarray(jax.device_get(gen))
        return ([self.decode_completion(gen[j])
                 for j in range(gen.shape[0])], gen)

    def sample_completions(self, prompts: Sequence[str], key: jax.Array,
                           temperature: float = 1.0,
                           max_new_tokens: Optional[int] = None) -> List[str]:
        """One temperature-sampled completion per prompt (single jitted
        call; same bucketing as the greedy paths)."""
        toks, mask = self._pad_batch(prompts)
        return self._sample_from_ids(toks, mask, key, temperature,
                                     max_new_tokens)

    def sample_completions_with_ids(
            self, prompts: Sequence[str], key: jax.Array,
            temperature: float = 1.0,
            max_new_tokens: Optional[int] = None
    ) -> Tuple[List[str], np.ndarray]:
        toks, mask = self._pad_batch(prompts)
        return self._sample_from_ids_raw(toks, mask, key, temperature,
                                         max_new_tokens)

    # -- public API ---------------------------------------------------------

    def score_prompts_sampled(
        self, prompts: Sequence[str],
        target_texts: Sequence[Tuple[str, str]],
        n_runs: int = 10, key: Optional[jax.Array] = None,
        temperature: float = 1.0,
        max_new_tokens: Optional[int] = None,
    ) -> List[SampledScore]:
        """Reasoning-model scoring: n sampled runs per prompt, answer-count
        averaging (VERDICT r1 #7; perturb_prompts.py:412-446 locally).

        ``key`` may be per-prompt keys shaped (B, 2): each prompt then owns
        its PRNG stream, so results do not depend on batch composition (the
        sweep keys rows by grid-cell identity -> resume-deterministic).

        The reference's reasoning models expose no logprobs, so it samples
        each binary prompt REASONING_MODEL_RUNS times (API default
        temperature) and sets Token_i_Prob = (runs whose text contains
        target_i) / n_runs, if/elif order — a text containing both targets
        (e.g. "Not Covered" contains "Covered") counts toward token 1 only;
        the stored response is the most common run text. Runs loop outside
        jit on purpose: vmapping the decode over runs would multiply the KV
        cache by n_runs (a 7B batch-32 cache is ~4.5 GB — x10 cannot fit
        HBM); each run reuses the same compiled sample_decode executable.
        """
        if key is None:
            key = jax.random.PRNGKey(0)
        per_row = generate.is_per_row_keys(key)  # per-prompt streams
        all_runs: List[List[str]] = [[] for _ in prompts]
        # Tokenize/pad ONCE; only the PRNG key varies across runs.
        toks, mask = self._pad_batch(prompts)
        for run in range(n_runs):
            if per_row:
                run_key = jax.vmap(
                    lambda k: jax.random.fold_in(k, run))(key)
            else:
                run_key = jax.random.fold_in(key, run)
            texts = self._sample_from_ids(
                toks, mask, run_key, temperature, max_new_tokens)
            for j, t in enumerate(texts):
                all_runs[j].append(t.strip())

        out: List[SampledScore] = []
        for j, prompt in enumerate(prompts):
            t1, t2 = target_texts[j]
            p1, p2, most_common = score.count_averaged_responses(
                all_runs[j], t1, t2)
            out.append(SampledScore(
                prompt=prompt,
                response=most_common,
                all_responses=list(all_runs[j]),
                token_1_prob=p1,
                token_2_prob=p2,
                odds_ratio=(p1 / p2) if p2 > 0 else float("inf"),
            ))
        return out

    def score_prompts(self, prompts: Sequence[str]) -> List[PromptScore]:
        """Score every prompt; one jitted call per full batch."""
        order = np.argsort([len(p) for p in prompts], kind="stable")
        rows: List[Optional[PromptScore]] = [None] * len(prompts)
        B = self.rt.batch_size
        for start in range(0, len(order), B):
            idx = order[start:start + B]
            batch_prompts = [prompts[i] for i in idx]
            rows_out = self._score_batch(batch_prompts)
            for i, r in zip(idx, rows_out):
                rows[i] = r
        return rows  # type: ignore[return-value]

    def _score_batch(self, batch_prompts: List[str]) -> List[PromptScore]:
        n = len(batch_prompts)
        B = self.rt.batch_size
        # Tail bucket: pad to the next power of two, not the full B (at most
        # one extra compile; stops re-scoring the last prompt B-n times).
        bsz = B if n == B else _tail_batch(n, B)
        padded_prompts = batch_prompts + [batch_prompts[-1]] * (bsz - n)

        if self.encoder_decoder:
            gen, step_logits = self.decode_prompts(padded_prompts)
            res = score.readout_from_step_logits(
                step_logits, gen, jnp.int32(self.yes_id),
                jnp.int32(self.no_id), scan_positions=self.rt.scan_positions)
        else:
            yes_ids = np.full((bsz,), self.yes_id, np.int32)
            no_ids = np.full((bsz,), self.no_id, np.int32)
            fused = self.decode_fused(padded_prompts, yes_ids, no_ids)
            res = score.readout_from_fused(
                fused, jnp.asarray(yes_ids), jnp.asarray(no_ids),
                scan_positions=self.rt.scan_positions)

        res = jax.device_get(res)
        out = []
        for j in range(n):
            out.append(PromptScore(
                prompt=batch_prompts[j],
                completion=self.decode_completion(res.generated[j]),
                yes_prob=float(res.yes_prob[j]),
                no_prob=float(res.no_prob[j]),
                yes_logprob=float(res.yes_logprob[j]),
                no_logprob=float(res.no_logprob[j]),
                odds_ratio=float(res.odds_ratio[j]),
                relative_prob=float(res.relative_prob[j]),
                position_found=int(res.position_found[j]),
                yes_no_found=bool(res.yes_no_found[j]),
            ))
        return out
