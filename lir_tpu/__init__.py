"""lir_tpu — TPU-native framework for LLM interpretation-reliability studies.

A brand-new JAX/XLA/pjit framework with the capabilities of the reference
``thechoipolloi/llm-interpretation-replication`` codebase (replication code for
"Large Language Models Are Unreliable Legal Interpreters"):

- prompt-perturbation generation + scoring sweeps (reference:
  analysis/perturb_prompts.py) executed as batched, sharded forward passes on a
  TPU mesh instead of the OpenAI Batch API;
- yes/no token relative-probability measurement across open-weight model zoos
  (reference: analysis/compare_base_vs_instruct.py,
  analysis/compare_instruct_models.py) via jitted scan decoding;
- the full downstream statistical pipeline — Cohen's kappa, bootstrap CIs,
  truncated-normal MC fits, human-survey agreement — vectorized with jax.vmap
  (reference: analysis/analyze_*.py, survey_analysis/*).

Layout (see SURVEY.md section 7):
  config.py   — dataclass config, backend switch "api" | "tpu"
  data/       — canonical prompt/question assets + row schemas (the file API)
  models/     — pure-JAX transformer families + HF safetensors loaders
  ops/        — core numeric ops (attention, norms, rotary, sampling readouts)
  parallel/   — Mesh construction, NamedSharding rules, collectives helpers
  engine/     — scoring/generation/grid/runner: the sweep executor
  stats/      — vmapped statistics kernels (bootstrap, kappa, fits, agreement)
  analysis/   — drivers regenerating every reference analysis artifact
  survey/     — human-survey loading/exclusions/matching/consolidated analysis
  report/     — figures + LaTeX emitters
  backends/   — inference backends: local TPU (default) and optional remote API
  utils/      — manifest/resume, logging, io
"""

__version__ = "0.1.0"
