"""Cohen's kappa kernels — every kappa variant the reference computes.

The reference has four distinct kappa procedures, all loop-based:

1. within-prompt perturbation kappa via an O(n^2) Python pair loop
   (analyze_perturbation_results.py:1094-1188) — ~2000^2 pairs per prompt;
2. per-prompt mean pairwise kappa across models + bootstrap "self-kappa"
   (calculate_cohens_kappa.py:76-218);
3. pooled aggregate kappa across all models with a 1000-fold bootstrap CI
   (model_comparison_graph.py:549-672);
4. pairwise model-model kappa matrices (model_comparison_graph.py:495-547).

Here the pair loops collapse to closed forms — for a group of n binary
decisions with s ones, agreeing pairs = C(s,2) + C(n-s,2) and total pairs =
C(n,2) — so the 2000^2-pair loop becomes a couple of reductions, and the
bootstrap variants are vmapped over resample indices.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .core import resample_indices

KAPPA_BANDS = (
    (0.0, "Poor agreement (worse than chance)"),
    (0.2, "Slight agreement"),
    (0.4, "Fair agreement"),
    (0.6, "Moderate agreement"),
    (0.8, "Substantial agreement"),
)


def interpret_kappa(kappa: float) -> str:
    """Interpretation bands (analyze_perturbation_results.py:1173-1184,
    calculate_cohens_kappa.py:379-394)."""
    if np.isnan(kappa):
        return "Undefined (kappa is NaN)"
    for upper, label in KAPPA_BANDS:
        if kappa < upper:
            return label
    return "Almost perfect agreement"


def cohen_kappa(a: jnp.ndarray, b: jnp.ndarray, n_classes: int = 2) -> jnp.ndarray:
    """Cohen's kappa between two label vectors, sklearn-compatible.

    po = observed agreement; pe = sum_k p_a(k) * p_b(k). Returns NaN when
    pe == 1 (both raters constant and identical), matching
    ``sklearn.metrics.cohen_kappa_score``'s 0/0 behavior.
    """
    a = a.astype(jnp.int32)
    b = b.astype(jnp.int32)
    po = (a == b).mean()
    ks = jnp.arange(n_classes)
    pa = (a[None, :] == ks[:, None]).mean(axis=1)
    pb = (b[None, :] == ks[:, None]).mean(axis=1)
    pe = (pa * pb).sum()
    return jnp.where(pe < 1.0, (po - pe) / (1.0 - pe), jnp.nan)


def within_group_kappa(
    decisions: np.ndarray, group_ids: np.ndarray
) -> Dict[str, float]:
    """Within-prompt kappa, closed form.

    Parity: analyze_perturbation_results.py:1094-1188. Observed agreement is
    the fraction of agreeing same-group pairs (groups of size <= 1 excluded);
    expected agreement is p1^2 + p0^2 over *all* decisions; kappa is the usual
    ratio. `decisions` is 0/1; `group_ids` is any integer labeling.
    """
    decisions = np.asarray(decisions)
    group_ids = np.asarray(group_ids)
    if decisions.size == 0:
        return {
            "kappa": float("nan"),
            "observed_agreement": float("nan"),
            "expected_agreement": float("nan"),
        }

    uniq = np.unique(group_ids)
    agree_pairs = 0.0
    total_pairs = 0.0
    for g in uniq:
        d = decisions[group_ids == g]
        n = d.size
        if n <= 1:
            continue
        s = float(d.sum())
        agree_pairs += s * (s - 1) / 2 + (n - s) * (n - s - 1) / 2
        total_pairs += n * (n - 1) / 2

    observed = agree_pairs / total_pairs if total_pairs > 0 else 0.0
    p1 = float(decisions.mean())
    expected = p1 * p1 + (1 - p1) * (1 - p1)
    kappa = (observed - expected) / (1 - expected) if expected < 1 else 1.0
    return {
        "kappa": float(kappa),
        "observed_agreement": float(observed),
        "expected_agreement": float(expected),
    }


def pairwise_kappa_matrix(binary: np.ndarray) -> np.ndarray:
    """All-pairs kappa between columns of a (n_items, n_raters) binary matrix
    with possible NaN entries (only rows finite for both raters count).

    Parity: the model-pair kappa loop at model_comparison_graph.py:495-547.
    Returns a symmetric (n_raters, n_raters) matrix with NaN diagonal-free 1s.
    """
    binary = np.asarray(binary, dtype=float)
    n = binary.shape[1]
    out = np.full((n, n), np.nan)
    for i in range(n):
        out[i, i] = 1.0
        for j in range(i + 1, n):
            mask = np.isfinite(binary[:, i]) & np.isfinite(binary[:, j])
            if mask.sum() < 2:
                continue
            k = float(
                cohen_kappa(
                    jnp.asarray(binary[mask, i]), jnp.asarray(binary[mask, j])
                )
            )
            out[i, j] = out[j, i] = k
    return out


def _aggregate_kappa_boot(rates, flat, ri, fi):
    obs = rates[ri].mean()
    q1 = flat[fi].mean()
    ch = q1 * q1 + (1 - q1) * (1 - q1)
    return jnp.where(ch < 1, (obs - ch) / (1 - ch), jnp.nan)


_aggregate_kappa_boot_jit = jax.jit(
    jax.vmap(_aggregate_kappa_boot, in_axes=(None, None, 0, 0))
)

_self_kappa_boot_jit = jax.jit(
    jax.vmap(lambda d, i, j: cohen_kappa(d[i], d[j]), in_axes=(None, 0, 0))
)


def _agreement_rates(binary: jnp.ndarray) -> jnp.ndarray:
    """Per-row fraction of agreeing rater pairs, closed form.
    binary: (n_items, n_raters) in {0,1}."""
    n = binary.shape[1]
    s = binary.sum(axis=1)
    agree = s * (s - 1) / 2 + (n - s) * (n - s - 1) / 2
    total = n * (n - 1) / 2
    return agree / total


def _checked_indices(arr, n_boot: int, n: int) -> jax.Array:
    """Validate injected replay indices with hard errors: XLA gathers
    CLAMP out-of-range indices, so bad inputs would silently produce
    plausible-but-wrong bootstrap quantities."""
    a = np.asarray(arr, np.int32)
    if a.shape != (n_boot, n):
        raise ValueError(f"indices shape {a.shape} != ({n_boot}, {n})")
    if a.size and (a.min() < 0 or a.max() >= n):
        raise ValueError("indices out of range")
    return jnp.asarray(a)


def aggregate_kappa(
    binary: np.ndarray,
    key: jax.Array,
    n_boot: int = 1000,
    indices: Optional[Tuple[np.ndarray, np.ndarray]] = None,
) -> Dict[str, float]:
    """Pooled kappa across all raters with a bootstrap CI.

    Parity: calculate_aggregate_cohens_kappa (model_comparison_graph.py:
    549-672): observed = mean per-prompt pair-agreement rate; chance =
    p1^2 + p0^2 over the flattened matrix; bootstrap resamples the
    per-prompt agreement rates and the flattened values independently.
    ``indices`` (test-only) injects explicit (rate_idx, flat_idx) resample
    index arrays so the executed-reference differential can replay the
    reference's exact np.random stream (VERDICT r4 #6).
    """
    b = jnp.asarray(np.asarray(binary, dtype=np.float32))
    rates = _agreement_rates(b)
    flat = b.reshape(-1)

    observed = float(rates.mean())
    p1 = float(flat.mean())
    chance = p1 * p1 + (1 - p1) * (1 - p1)
    kappa = (observed - chance) / (1 - chance) if chance < 1 else 0.0

    if indices is not None:
        rate_idx = _checked_indices(indices[0], n_boot, rates.shape[0])
        flat_idx = _checked_indices(indices[1], n_boot, flat.shape[0])
    else:
        k1, k2 = jax.random.split(key)
        rate_idx = resample_indices(k1, n_boot, rates.shape[0])
        flat_idx = resample_indices(k2, n_boot, flat.shape[0])
    samples = np.asarray(_aggregate_kappa_boot_jit(rates, flat, rate_idx, flat_idx))
    samples = samples[np.isfinite(samples)]
    return {
        "aggregate_kappa": float(kappa),
        "observed_agreement": observed,
        "chance_agreement": float(chance),
        "kappa_ci_lower": float(np.percentile(samples, 2.5)) if samples.size else float("nan"),
        "kappa_ci_upper": float(np.percentile(samples, 97.5)) if samples.size else float("nan"),
        "n_prompts": int(binary.shape[0]),
        "n_models": int(binary.shape[1]),
        "p_class1": p1,
        "p_class0": 1 - p1,
    }


def self_kappa_bootstrap(
    decisions: np.ndarray,
    key: jax.Array,
    n_boot: int = 1000,
    indices: Optional[Tuple[np.ndarray, np.ndarray]] = None,
) -> Dict[str, float]:
    """Perturbation 'self-kappa': kappa between two independent bootstrap
    resamples of one decision vector, averaged over n_boot draws.

    Parity: calculate_cohens_kappa.py:185-216. NaN draws (constant identical
    resamples) are dropped, mirroring the reference's try/except skip.
    ``indices`` (test-only) injects explicit (idx1, idx2) arrays so the
    differential can replay the reference's per-prompt seed-42 interleaved
    idx1/idx2 stream (VERDICT r4 #6).
    """
    d = jnp.asarray(np.asarray(decisions, dtype=np.int32))
    n = d.shape[0]
    if indices is not None:
        idx1 = _checked_indices(indices[0], n_boot, n)
        idx2 = _checked_indices(indices[1], n_boot, n)
    else:
        k1, k2 = jax.random.split(key)
        idx1 = resample_indices(k1, n_boot, n)
        idx2 = resample_indices(k2, n_boot, n)
    samples = np.asarray(_self_kappa_boot_jit(d, idx1, idx2))
    samples = samples[np.isfinite(samples)]
    if samples.size == 0:
        return {"self_kappa": float("nan"), "self_kappa_std": float("nan"),
                "min_kappa": float("nan"), "max_kappa": float("nan")}
    return {
        "self_kappa": float(samples.mean()),
        "self_kappa_std": float(samples.std()),
        "min_kappa": float(samples.min()),
        "max_kappa": float(samples.max()),
    }


def combined_kappa(
    model_kappa: float,
    perturbation_kappa: float,
    key: jax.Array,
    model_kappa_std: float = 0.1,
    pert_kappa_std: float = 0.1,
    n_boot: int = 1000,
) -> Dict[str, float]:
    """Combine the two kappa sources as min(model_draw, perturbation_draw)
    over normal draws (calculate_cohens_kappa.py:328-371)."""
    k1, k2 = jax.random.split(key)
    m = model_kappa + model_kappa_std * jax.random.normal(k1, (n_boot,))
    p = perturbation_kappa + pert_kappa_std * jax.random.normal(k2, (n_boot,))
    combined = np.asarray(jnp.minimum(m, p))
    return {
        "mean_kappa": float(combined.mean()),
        "median_kappa": float(np.median(combined)),
        "lower_ci": float(np.percentile(combined, 2.5)),
        "upper_ci": float(np.percentile(combined, 97.5)),
    }


def per_prompt_mean_pairwise_kappa(
    decisions_by_model: np.ndarray,
) -> Dict[str, float]:
    """Mean pairwise kappa for one prompt's decision vector across models.

    Parity note: the reference calls ``cohen_kappa_score([x], [y])`` on
    single-element lists (calculate_cohens_kappa.py:124-127), which is
    degenerate — it yields NaN for every disagreeing pair and NaN/1 for
    agreeing ones. SURVEY.md §7 lists this as a defect to fix, not replicate:
    we report the fraction of agreeing pairs (the quantity the reference's
    degenerate code effectively measures) alongside the agreement percentage.
    """
    d = np.asarray(decisions_by_model, dtype=float)
    d = d[np.isfinite(d)]
    n = d.size
    if n < 2:
        return {"avg_pairwise_agreement": float("nan"), "n_models": int(n),
                "agree_percent": float("nan")}
    s = float(d.sum())
    agree = (s * (s - 1) / 2 + (n - s) * (n - s - 1) / 2) / (n * (n - 1) / 2)
    mean_dec = float(d.mean())
    return {
        "avg_pairwise_agreement": float(agree),
        "n_models": int(n),
        "agree_percent": mean_dec if mean_dec > 0.5 else 1 - mean_dec,
    }
