"""Vectorized statistics kernels — the L4 layer of the framework.

Every statistic the reference computes with Python loops over scipy/sklearn
is re-expressed here as a jittable/vmappable JAX kernel (bootstrap CIs,
kappa variants, pairwise agreement, correlation matrices, truncated-normal MC
fits), with scipy retained only for one-shot host-side hypothesis tests.
"""

from .bootstrap import (
    BootstrapResult,
    bootstrap_correlation,
    bootstrap_mean_ci,
    bootstrap_metric_matrix,
    mae,
    mape,
    normal_approx_mc_difference,
    permutation_test_difference,
    rmse,
    simulate_individuals,
)
from .core import (
    average_ranks,
    nan_filter,
    pearson,
    percentile_ci,
    resample_indices,
    spearman,
)
from .agreement import pairwise_agreement_stats, per_item_agreement
from .correlations import (
    bootstrap_correlation_matrix,
    cross_rater_mean_correlation,
    masked_pearson_matrix,
    masked_spearman_matrix,
)
from .fits import truncated_normal_mc_fit
from .kappa import (
    aggregate_kappa,
    cohen_kappa,
    combined_kappa,
    interpret_kappa,
    pairwise_kappa_matrix,
    per_prompt_mean_pairwise_kappa,
    self_kappa_bootstrap,
    within_group_kappa,
)
from .normality import (
    anderson_darling_pvalue,
    compare_distributions,
    normality_tests,
)
from .streaming import (
    HostAccum,
    accum_from_rows,
    assert_parity,
    merge_accums,
    slot_map_from_cells,
    summarize as summarize_accum,
)

__all__ = [
    "BootstrapResult",
    "HostAccum",
    "accum_from_rows",
    "assert_parity",
    "merge_accums",
    "slot_map_from_cells",
    "summarize_accum",
    "aggregate_kappa",
    "anderson_darling_pvalue",
    "average_ranks",
    "bootstrap_correlation",
    "bootstrap_correlation_matrix",
    "bootstrap_mean_ci",
    "bootstrap_metric_matrix",
    "cohen_kappa",
    "combined_kappa",
    "compare_distributions",
    "cross_rater_mean_correlation",
    "interpret_kappa",
    "mae",
    "mape",
    "masked_pearson_matrix",
    "masked_spearman_matrix",
    "nan_filter",
    "normal_approx_mc_difference",
    "normality_tests",
    "pairwise_agreement_stats",
    "pairwise_kappa_matrix",
    "pearson",
    "per_item_agreement",
    "per_prompt_mean_pairwise_kappa",
    "percentile_ci",
    "permutation_test_difference",
    "resample_indices",
    "rmse",
    "self_kappa_bootstrap",
    "simulate_individuals",
    "spearman",
    "truncated_normal_mc_fit",
    "within_group_kappa",
]
