"""Correlation-matrix kernels with prompt-resampled bootstrap.

Parity target: calculate_model_correlations (model_comparison_graph.py:
207-340) — 1000 bootstrap recomputations of the models x models correlation
matrix, each a pandas `.corr()` in a Python loop. Here the masked pairwise
Pearson matrix is a handful of matmuls (so NaN cells are handled like
pandas' pairwise-complete observations), and the bootstrap axis is one vmap.
"""

from __future__ import annotations

import warnings
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .core import resample_indices


def masked_pearson_matrix(x: jnp.ndarray) -> jnp.ndarray:
    """Pairwise-complete Pearson correlation between the columns of `x`
    (rows = items, cols = raters), NaN-aware — matches
    ``pandas.DataFrame.corr(method='pearson')``.

    All pair statistics come from cross-products of the masked matrix, so the
    whole (n_cols x n_cols) matrix is ~6 matmuls on the MXU instead of an
    O(n_cols^2) host loop.
    """
    m = jnp.isfinite(x)
    # Pearson is invariant to per-column affine rescaling; standardizing by
    # the column-wise finite mean/std first keeps the cross-product formula
    # well-conditioned. Matmuls run at "highest" precision: correlations are
    # statistics, not activations — bf16/tf32 passes are not acceptable here,
    # and these matrices are tiny.
    mf = m.astype(x.dtype)
    cnt = jnp.maximum(mf.sum(axis=0), 1.0)
    xz0 = jnp.where(m, x, 0.0)
    mean = xz0.sum(axis=0) / cnt
    var = (jnp.where(m, (x - mean) ** 2, 0.0)).sum(axis=0) / cnt
    std = jnp.sqrt(jnp.maximum(var, 1e-30))
    x = (x - mean) / std
    xz = jnp.where(m, x, 0.0)
    with jax.default_matmul_precision("highest"):
        n = mf.T @ mf                  # joint-observation counts
        sx = xz.T @ mf                 # sum of x_i over joint mask
        sxy = xz.T @ xz
        sxx = (xz * xz).T @ mf
    sy = sx.T
    syy = sxx.T
    cov = n * sxy - sx * sy
    var_x = n * sxx - sx * sx
    var_y = n * syy - sy * sy
    denom = jnp.sqrt(var_x * var_y)
    return jnp.where((denom > 0) & (n > 1), cov / denom, jnp.nan)


def _masked_ranks(v: jnp.ndarray, m: jnp.ndarray) -> jnp.ndarray:
    """Average ranks of `v` restricted to mask `m` (invalid positions get an
    arbitrary value; callers must mask them out again)."""
    vm = jnp.where(m, v, jnp.inf)
    lt = ((vm[:, None] > vm[None, :]) & m[None, :]).sum(axis=1)
    eq = ((vm[:, None] == vm[None, :]) & m[None, :]).sum(axis=1)
    return (lt + (eq + 1) / 2.0).astype(v.dtype)


def _masked_pearson_pair(xi, xj, m):
    mf = m.astype(xi.dtype)
    n = mf.sum()
    xi = jnp.where(m, xi, 0.0)
    xj = jnp.where(m, xj, 0.0)
    mx = xi.sum() / n
    my = xj.sum() / n
    dx = jnp.where(m, xi - mx, 0.0)
    dy = jnp.where(m, xj - my, 0.0)
    denom = jnp.sqrt((dx * dx).sum() * (dy * dy).sum())
    return jnp.where((denom > 0) & (n > 1), (dx * dy).sum() / denom, jnp.nan)


def _spearman_pair(xi, xj):
    m = jnp.isfinite(xi) & jnp.isfinite(xj)
    ri = _masked_ranks(xi, m)
    rj = _masked_ranks(xj, m)
    return _masked_pearson_pair(ri, rj, m)


@jax.jit
def masked_spearman_matrix(x: jnp.ndarray) -> jnp.ndarray:
    """Pairwise-complete Spearman, pandas-compatible: for every column pair,
    restrict to jointly finite rows, re-rank *within that subset*, then
    Pearson. (Ranking whole columns first diverges whenever columns have
    different NaN patterns — e.g. the D1 base/instruct pivot, an incomplete
    49x18 grid.) vmapped over all pairs; O(pairs * n^2) comparisons fuse into
    one kernel."""
    ncol = x.shape[1]
    ii, jj = jnp.triu_indices(ncol, k=1)
    vals = jax.vmap(lambda i, j: _spearman_pair(x[:, i], x[:, j]))(ii, jj)
    out = jnp.full((ncol, ncol), jnp.nan, dtype=x.dtype)
    out = out.at[ii, jj].set(vals)
    out = out.at[jj, ii].set(vals)
    diag_ok = jnp.isfinite(x).sum(axis=0) > 1
    return out.at[jnp.arange(ncol), jnp.arange(ncol)].set(
        jnp.where(diag_ok, 1.0, jnp.nan)
    )


_RESAMPLED_CORR_JIT = {
    "pearson": jax.jit(
        jax.vmap(lambda x, i: masked_pearson_matrix(x[i]), in_axes=(None, 0))
    ),
    "spearman": jax.jit(
        jax.vmap(lambda x, i: masked_spearman_matrix(x[i]), in_axes=(None, 0))
    ),
}


def _masked_corr_matrix_f64(x: np.ndarray, method: str) -> np.ndarray:
    """Pairwise-complete correlation matrix in float64 (pandas-compatible
    down to rank-tie handling); host numpy — this is the tiny deterministic
    point estimate, not the bootstrap hot path."""
    from scipy.stats import rankdata

    n = x.shape[1]
    out = np.full((n, n), np.nan)
    for i in range(n):
        for j in range(i, n):
            m = np.isfinite(x[:, i]) & np.isfinite(x[:, j])
            if int(m.sum()) < 2:
                continue
            xi, xj = x[m, i], x[m, j]
            if method == "spearman":
                xi, xj = rankdata(xi), rankdata(xj)
            dx, dy = xi - xi.mean(), xj - xj.mean()
            denom = np.sqrt((dx * dx).sum() * (dy * dy).sum())
            if denom > 0:
                out[i, j] = out[j, i] = float((dx * dy).sum() / denom)
    return out


def _pair_values(matrix: np.ndarray) -> np.ndarray:
    iu = np.triu_indices(matrix.shape[0], k=1)
    vals = matrix[iu]
    return vals[np.isfinite(vals)]


def bootstrap_correlation_matrix(
    pivot: np.ndarray,
    key: jax.Array,
    method: str = "pearson",
    n_bootstrap: int = 1000,
    confidence: float = 0.95,
    indices: Optional[np.ndarray] = None,
) -> Dict[str, object]:
    """Full parity with calculate_model_correlations: original pairwise
    correlations + bootstrap (prompts resampled with replacement) CIs for the
    mean/median/std of the pairwise-correlation distribution.

    `pivot` is (n_prompts, n_models), NaN allowed. ``indices`` (test-only)
    injects explicit (n_bootstrap, n_prompts) resample indices — the
    executed-reference differential replays the reference's exact
    np.random.seed(42) streams through the vmapped kernel, putting the
    bootstrap CIs under the ≤1% gate instead of a width-level tolerance
    (VERDICT r4 #6; model_comparison_graph.py:258-263).
    """
    x64 = np.asarray(pivot, dtype=np.float64)
    x = jnp.asarray(x64)

    # The deterministic point matrix is computed host-side in float64:
    # jnp downcasts to f32 (x64 off), and for Spearman an f32-collapsed tie
    # can flip ranks vs pandas' f64 path — the executed-reference diff
    # (tests/test_reference_differential.py) caught exactly that. The
    # bootstrap resamples stay on-device in f32 (CI-level quantities).
    original = _masked_corr_matrix_f64(x64, method)
    original_vals = _pair_values(original)

    if indices is not None:
        idx_np = np.asarray(indices, np.int32)
        # Hard errors, not asserts: XLA gathers CLAMP out-of-range indices,
        # so bad replay inputs would yield plausible-but-wrong CIs.
        if idx_np.shape != (n_bootstrap, x.shape[0]):
            raise ValueError(f"indices shape {idx_np.shape} != "
                             f"({n_bootstrap}, {x.shape[0]})")
        if idx_np.size and (idx_np.min() < 0 or idx_np.max() >= x.shape[0]):
            raise ValueError("indices out of range for pivot rows")
        idx = jnp.asarray(idx_np)
    else:
        idx = resample_indices(key, n_bootstrap, x.shape[0])
    boot_mats = np.asarray(_RESAMPLED_CORR_JIT[method](x, idx))

    iu = np.triu_indices(x.shape[1], k=1)
    boot_vals = boot_mats[:, iu[0], iu[1]]          # (n_boot, n_pairs)
    with np.errstate(invalid="ignore"), warnings.catch_warnings():
        # Resamples where no pair has joint coverage reduce to all-NaN rows;
        # they contribute NaN (dropped by ci()/agg()) rather than a warning.
        warnings.simplefilter("ignore", RuntimeWarning)
        means = np.nanmean(boot_vals, axis=1)
        medians = np.nanmedian(boot_vals, axis=1)
        stds = np.nanstd(boot_vals, axis=1)

    alpha = 1 - confidence
    lo_p, hi_p = 100 * alpha / 2, 100 * (1 - alpha / 2)

    def ci(samples):
        s = samples[np.isfinite(samples)]
        if s.size == 0:
            return (float("nan"), float("nan"))
        return (float(np.percentile(s, lo_p)), float(np.percentile(s, hi_p)))

    def agg(fn, vals):
        finite = vals[np.isfinite(vals)]
        return float(fn(finite)) if finite.size else float("nan")

    return {
        "mean_correlation": agg(np.mean, original_vals),
        "mean_ci": ci(means),
        "mean_se": agg(np.nanstd, means),
        "median_correlation": agg(np.median, original_vals),
        "median_ci": ci(medians),
        "median_se": agg(np.nanstd, medians),
        "std_correlation": agg(np.std, original_vals),
        "std_ci": ci(stds),
        "std_se": agg(np.nanstd, stds),
        "min_correlation": agg(np.min, original_vals),
        "max_correlation": agg(np.max, original_vals),
        "correlation_matrix": original,
        "correlation_values": original_vals,
        "n_bootstrap": n_bootstrap,
        "confidence_level": confidence,
    }


def cross_rater_mean_correlation(
    matrix: np.ndarray,
    min_items: int = 5,
) -> float:
    """Mean off-diagonal pairwise-complete correlation between raters
    (columns), requiring >= min_items joint observations per pair — the inner
    statistic of the cross-prompt rank-consistency analysis
    (survey_analysis_consolidated.py:352-594)."""
    x = np.asarray(matrix, dtype=np.float64)
    m = np.isfinite(x).astype(np.float64)
    counts = m.T @ m
    corr = np.asarray(masked_pearson_matrix(jnp.asarray(x)))
    iu = np.triu_indices(x.shape[1], k=1)
    vals = corr[iu]
    ok = np.isfinite(vals) & (counts[iu] >= min_items)
    return float(np.mean(vals[ok])) if ok.any() else float("nan")
