"""Finalize path for the streaming-statistics accumulator: grid -> CIs
without reloading results.csv.

The device sink (engine/stream_stats.py) scatters per-cell sufficient
values into a (P, R) lattice; this module reduces that lattice — in ONE
canonical prompt-major order, so moments are deterministic regardless
of dispatch/resume order — into exactly the quantities the host-side
``stats``/``analysis`` pipeline computes from the csv:

- per-prompt moments + 2.5/97.5 percentiles of the relative
  probability and weighted confidence (analysis/perturbation.py's
  prompt_summary_stats columns, float64, pandas ddof=1 std);
- within-prompt Cohen's kappa from the binarized decisions — computed
  through the SAME ``stats.kappa.within_group_kappa`` code path the
  csv pipeline runs, fed from the accumulator's integer contingency
  counts (n_g, s_g per prompt are sufficient), so the result is
  bitwise-identical, not merely close;
- seeded bootstrap CIs on the per-prompt means, resample indices drawn
  from the key recorded in the sweep manifest (fold_in per prompt), so
  streaming CIs reproduce across resume and across
  ``--no-streaming-stats`` re-runs.

The csv-reload path is kept for parity: :func:`accum_from_rows` builds
the identical lattice from a results frame + the grid's slot map, and
``make stats-smoke`` / tests/test_streaming_stats.py assert the two
agree (counts and kappa bitwise; moments and CIs within FLOAT_TOL —
the lattice stores float32 device values where the csv pipeline
recomputes relative probabilities in float64).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

import numpy as np

# Documented float tolerance between streaming (float32 lattice values,
# f32 on-device division) and the csv-reload pipeline (float64 recompute
# from the same stored readouts). Decisions/counts carry NO tolerance —
# they are integers and the yes>no rule is exactly equivalent to the
# float64 Relative_Prob > 0.5 rule (engine/stream_stats.py docstring).
FLOAT_TOL = 5e-5


@dataclasses.dataclass
class HostAccum:
    """Host copy of the device lattice (one device_get at checkpoint /
    fence / finalize cadence — never per row)."""

    filled: np.ndarray   # (P, R) int32 0/1
    rel: np.ndarray      # (P, R) float32, NaN when invalid
    conf: np.ndarray     # (P, R) float32, NaN when invalid
    dec: np.ndarray      # (P, R) int32 1/0/-1
    seed: int

    @property
    def rows_folded(self) -> int:
        return int(self.filled.sum())


def empty_accum(n_prompts: int, n_rephrase: int, seed: int) -> HostAccum:
    P, R = int(n_prompts), int(n_rephrase)
    return HostAccum(
        filled=np.zeros((P, R), np.int32),
        rel=np.full((P, R), np.nan, np.float32),
        conf=np.full((P, R), np.nan, np.float32),
        dec=np.full((P, R), -1, np.int32),
        seed=int(seed))


def merge_accums(accs: Sequence[HostAccum],
                 allow_identical_overlap: bool = False) -> HostAccum:
    """Union of shard lattices (the multihost fence merge). Slot-wise
    and order-free.

    Under STATIC host_shard partitioning each host folded its own
    shard's cells, so for every slot at most one shard has it filled —
    asserted, because a double-fill would mean two hosts scored one
    cell (the exact duplicate-work bug host_shard exists to prevent).

    Under LEASED shards (engine/lease.py) a stolen shard is re-scored
    by its new holder while the slow/recovered original holder may have
    folded part of it too — overlap is then EXPECTED, and correct
    exactly when both holders folded bitwise-identical values
    (deterministic greedy decode on config-identical engines makes
    re-done rows bitwise no-ops). ``allow_identical_overlap=True``
    admits that case and still HARD-FAILS on any overlapped slot whose
    values differ: divergent duplicates mean non-deterministic scoring,
    which must never merge silently."""
    assert accs, "merge_accums needs at least one accumulator"
    out = empty_accum(*accs[0].filled.shape, seed=accs[0].seed)
    for acc in accs:
        overlap = (out.filled > 0) & (acc.filled > 0)
        if overlap.any():
            if not allow_identical_overlap:
                raise ValueError(
                    f"accumulator merge overlap on {int(overlap.sum())} "
                    "cells — two hosts folded the same grid cell")
            same = (
                np.array_equal(out.rel[overlap], acc.rel[overlap],
                               equal_nan=True)
                and np.array_equal(out.conf[overlap], acc.conf[overlap],
                                   equal_nan=True)
                and np.array_equal(out.dec[overlap], acc.dec[overlap]))
            if not same:
                raise ValueError(
                    f"accumulator merge overlap on {int(overlap.sum())} "
                    "cells with DIVERGENT values — two holders scored "
                    "one cell differently (non-deterministic scoring); "
                    "refusing to merge")
        m = acc.filled > 0
        out.filled[m] = acc.filled[m]
        out.rel[m] = acc.rel[m]
        out.conf[m] = acc.conf[m]
        out.dec[m] = acc.dec[m]
    return out


# ---------------------------------------------------------------------------
# Contingency counts and kappa (exact, integer-derived)
# ---------------------------------------------------------------------------


def contingency(acc: HostAccum) -> Dict[str, np.ndarray]:
    """Per-prompt integer contingency/agreement counts — the kappa
    sufficient statistic. Bitwise comparable across streaming and
    csv-reload paths."""
    filled = acc.filled > 0
    valid = filled & (acc.dec >= 0)
    return {
        "n_folded": filled.sum(axis=1).astype(np.int64),
        "n_valid": valid.sum(axis=1).astype(np.int64),
        "n_yes": ((acc.dec == 1) & filled).sum(axis=1).astype(np.int64),
        "n_conf": (filled & np.isfinite(acc.conf)).sum(axis=1)
                  .astype(np.int64),
    }


def group_counts(group_ids: np.ndarray, decisions: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """(n_g, s_g) per group from flat (group, decision) vectors — the
    serve ring's path into :func:`kappa_from_counts`."""
    group_ids = np.asarray(group_ids)
    decisions = np.asarray(decisions)
    uniq = np.unique(group_ids) if group_ids.size else np.empty(0, int)
    n_g = np.asarray([(group_ids == g).sum() for g in uniq], np.int64)
    s_g = np.asarray([decisions[group_ids == g].sum() for g in uniq],
                     np.int64)
    return n_g, s_g


def kappa_from_counts(n_g: np.ndarray, s_g: np.ndarray
                      ) -> Dict[str, float]:
    """Within-group kappa from per-group (n, s) counts, routed through
    the SAME stats.kappa.within_group_kappa code the csv pipeline calls
    — the counts are sufficient (the closed form only consumes per-group
    sums), and reusing the exact function makes streaming-vs-reload
    kappa bitwise-identical, not tolerance-close."""
    from .kappa import within_group_kappa

    n_g = np.asarray(n_g, np.int64)
    s_g = np.asarray(s_g, np.int64)
    decisions: List[int] = []
    groups: List[int] = []
    for g, (n, s) in enumerate(zip(n_g, s_g)):
        decisions.extend([1] * int(s) + [0] * int(n - s))
        groups.extend([g] * int(n))
    return within_group_kappa(np.asarray(decisions, int),
                              np.asarray(groups, int))


def kappa(acc: HostAccum) -> Dict[str, float]:
    """The D6 within-prompt kappa (analysis/perturbation.py's
    perturbation_kappa) straight from the accumulator."""
    c = contingency(acc)
    return kappa_from_counts(c["n_valid"], c["n_yes"])


# ---------------------------------------------------------------------------
# Moments / percentiles / bootstrap CIs (canonical-order reductions)
# ---------------------------------------------------------------------------


def prompt_values(acc: HostAccum, field: str, p: int) -> np.ndarray:
    """One prompt's valid values in canonical slot order (float64)."""
    arr = getattr(acc, field)[p].astype(np.float64)
    mask = (acc.filled[p] > 0) & np.isfinite(arr)
    return arr[mask]


def _moments(values: np.ndarray) -> Dict[str, float]:
    """prompt_summary_stats' numeric columns: mean, pandas-style ddof=1
    std, min/max, 2.5/97.5 percentiles, interval width (float64)."""
    if values.size == 0:
        return {k: float("nan") for k in
                ("n", "mean", "std", "min", "max", "p2_5", "p97_5",
                 "ci95_width")} | {"n": 0}
    lo, hi = np.percentile(values, [2.5, 97.5])
    return {
        "n": int(values.size),
        "mean": float(values.mean()),
        "std": float(values.std(ddof=1)) if values.size > 1
               else float("nan"),
        "min": float(values.min()),
        "max": float(values.max()),
        "p2_5": float(lo),
        "p97_5": float(hi),
        "ci95_width": float(hi - lo),
    }


def bootstrap_mean_ci_seeded(values: np.ndarray, seed: int,
                             prompt_idx: int, n_boot: int,
                             confidence: float = 0.95,
                             salt: int = 0) -> Dict[str, float]:
    """Percentile bootstrap CI on the mean, resample indices drawn from
    fold_in(PRNGKey(seed), prompt_idx [, salt]) — the key recorded in
    the sweep manifest, so the SAME values in the SAME canonical order
    give the SAME CI on every run, resumed or not."""
    import jax

    from .bootstrap import _resampled_means_jit
    from .core import percentile_ci, resample_indices

    if values.size == 0 or n_boot <= 0:
        return {"ci_lower": float("nan"), "ci_upper": float("nan"),
                "standard_error": float("nan")}
    key = jax.random.fold_in(jax.random.PRNGKey(int(seed)),
                             int(prompt_idx))
    if salt:
        key = jax.random.fold_in(key, int(salt))
    idx = resample_indices(key, int(n_boot), int(values.size))
    samples = np.asarray(_resampled_means_jit(
        np.asarray(values, np.float64), idx))
    lo, hi = percentile_ci(samples, confidence)
    return {"ci_lower": float(lo), "ci_upper": float(hi),
            "standard_error": float(np.nanstd(samples))}


_CONF_SALT = 10_000  # confidence bootstrap keys never collide with rel's


def summarize(acc: HostAccum, n_boot: int = 1000,
              confidence: float = 0.95) -> Dict[str, object]:
    """The full finalize: per-prompt moments/percentiles/bootstrap CIs
    for relative probability and weighted confidence, the within-prompt
    kappa, and the integer contingency counts. ``n_boot=0`` skips the
    bootstrap (cheap live mid-run estimates)."""
    counts = contingency(acc)
    per_prompt: List[Dict[str, object]] = []
    for p in range(acc.filled.shape[0]):
        rel = prompt_values(acc, "rel", p)
        conf = prompt_values(acc, "conf", p)
        entry: Dict[str, object] = {
            "prompt_idx": p,
            "n_folded": int(counts["n_folded"][p]),
            "n_valid": int(counts["n_valid"][p]),
            "n_yes": int(counts["n_yes"][p]),
            "n_no": int(counts["n_valid"][p] - counts["n_yes"][p]),
            "relative_prob": _moments(rel),
            "weighted_confidence": _moments(conf),
        }
        if n_boot > 0:
            entry["relative_prob"].update(bootstrap_mean_ci_seeded(
                rel, acc.seed, p, n_boot, confidence))
            entry["weighted_confidence"].update(bootstrap_mean_ci_seeded(
                conf, acc.seed, p, n_boot, confidence,
                salt=_CONF_SALT))
        per_prompt.append(entry)
    return {
        "rows_folded": acc.rows_folded,
        "seed": int(acc.seed),
        "n_boot": int(n_boot),
        "per_prompt": per_prompt,
        "kappa": kappa(acc),
    }


# ---------------------------------------------------------------------------
# csv-reload parity path (kept alongside streaming, per the ROADMAP)
# ---------------------------------------------------------------------------


def slot_map_from_cells(cells: Iterable) -> Dict[Tuple[str, str],
                                                 Tuple[int, int]]:
    """(original_main, rephrased_main) -> (prompt_idx, rephrase_idx)
    from the sweep's own grid cells — how a results frame maps back
    onto lattice slots."""
    return {(c.original_main, c.rephrased_main):
            (c.prompt_idx, c.rephrase_idx) for c in cells}


def accum_from_rows(df, slot_map: Mapping[Tuple[str, str],
                                          Tuple[int, int]],
                    n_prompts: int, n_rephrase: int,
                    seed: int) -> HostAccum:
    """Rebuild the lattice from a D6 results frame (the csv-reload
    parity path): relative probability recomputed in float64 exactly as
    analysis/perturbation.add_relative_prob does, decision as
    Relative_Prob > 0.5, quarantined rows (null token probs) invalid.
    With the manifest-recorded ``seed`` this reproduces the streaming
    CIs from a ``--no-streaming-stats`` re-run's artifact."""
    acc = empty_accum(n_prompts, n_rephrase, seed)
    t1 = df["Token_1_Prob"].to_numpy(dtype=np.float64)
    t2 = df["Token_2_Prob"].to_numpy(dtype=np.float64)
    wc = (df["Weighted Confidence"].to_numpy(dtype=np.float64)
          if "Weighted Confidence" in df.columns
          else np.full(len(df), np.nan))
    orig = df["Original Main Part"].tolist()
    reph = df["Rephrased Main Part"].tolist()
    for i in range(len(df)):
        slot = slot_map.get((orig[i], reph[i]))
        if slot is None:
            continue
        p, r = slot
        acc.filled[p, r] = 1
        total = t1[i] + t2[i]
        if np.isfinite(total) and total > 0:
            rel = t1[i] / total
            acc.rel[p, r] = np.float32(rel)
            acc.dec[p, r] = 1 if rel > 0.5 else 0
        if np.isfinite(wc[i]):
            acc.conf[p, r] = np.float32(wc[i])
    return acc


def assert_parity(streamed: Dict[str, object],
                  reloaded: Dict[str, object],
                  tol: float = FLOAT_TOL) -> None:
    """The acceptance gate: counts and kappa bitwise, moments and CIs
    within the documented float tolerance. Raises AssertionError with
    the first divergence."""
    assert streamed["rows_folded"] == reloaded["rows_folded"], (
        streamed["rows_folded"], reloaded["rows_folded"])
    ks, kr = streamed["kappa"], reloaded["kappa"]
    for k in ("kappa", "observed_agreement", "expected_agreement"):
        a, b = ks[k], kr[k]
        assert (np.isnan(a) and np.isnan(b)) or a == b, (k, a, b)
    for es, er in zip(streamed["per_prompt"], reloaded["per_prompt"]):
        for k in ("n_folded", "n_valid", "n_yes", "n_no"):
            assert es[k] == er[k], (k, es[k], er[k])
        for field in ("relative_prob", "weighted_confidence"):
            ms, mr = es[field], er[field]
            assert ms["n"] == mr["n"], (field, ms["n"], mr["n"])
            for k in ("mean", "std", "min", "max", "p2_5", "p97_5",
                      "ci_lower", "ci_upper"):
                if k not in ms and k not in mr:
                    continue
                a, b = ms.get(k, float("nan")), mr.get(k, float("nan"))
                if np.isnan(a) and np.isnan(b):
                    continue
                assert abs(a - b) <= tol, (
                    f"prompt {es['prompt_idx']} {field}.{k}: "
                    f"{a} vs {b} (tol {tol})")
