"""Per-item pairwise agreement kernels.

Parity targets: calculate_per_item_agreement_humans /
calculate_per_item_agreement_llms (survey_analysis_consolidated.py:234-350).
The reference loops over all O(n^2) respondent pairs per question in Python
(~507^2 pairs x 55 questions); here the pairwise |difference| matrix is one
broadcast subtraction and the pair statistics are reductions over its upper
triangle.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from .bootstrap import bootstrap_mean_ci


def pairwise_agreement_stats(values: np.ndarray, scale: float) -> Dict[str, float]:
    """Mean/std of pairwise agreement 1 - |a-b|/scale over all unordered
    pairs of `values` (scale=100 for human sliders, 1 for LLM probabilities).
    """
    v = jnp.asarray(np.asarray(values, dtype=np.float64))
    n = int(v.shape[0])
    diffs = jnp.abs(v[:, None] - v[None, :]) / scale
    agreement = 1.0 - diffs
    iu = jnp.triu_indices(n, k=1)
    pair_vals = agreement[iu]
    return {
        "mean_agreement": float(pair_vals.mean()),
        "std_agreement": float(pair_vals.std()),
        "n_pairs": n * (n - 1) // 2,
        "response_variance": float(jnp.var(v)),
    }


def per_item_agreement(
    responses_by_item: Dict[str, np.ndarray],
    scale: float,
    key: jax.Array,
    n_boot: int = 1000,
    count_key: str = "n_responses",
) -> Dict[str, object]:
    """Per-item pairwise agreement + bootstrap CI on the across-item mean.

    `responses_by_item` maps item id -> 1-D array of responses (already
    NaN-filtered). Items with < 2 responses are skipped, as in the reference.
    """
    per_item: Dict[str, Dict[str, float]] = {}
    means = []
    for item, vals in responses_by_item.items():
        vals = np.asarray(vals, dtype=float)
        vals = vals[np.isfinite(vals)]
        if vals.size < 2:
            continue
        stats = pairwise_agreement_stats(vals, scale)
        stats[count_key] = int(vals.size)
        per_item[item] = stats
        means.append(stats["mean_agreement"])

    if not means:
        return {
            "per_item": per_item,
            "overall_mean": 0.0,
            "overall_std": 0.0,
            "n_items": 0,
            "overall_mean_ci_lower": 0.0,
            "overall_mean_ci_upper": 0.0,
        }

    ci = bootstrap_mean_ci(np.asarray(means), key, n_boot=n_boot)
    return {
        "per_item": per_item,
        "overall_mean": float(np.mean(means)),
        "overall_std": float(np.std(means)),
        "n_items": len(means),
        "overall_mean_ci_lower": ci.ci_lower,
        "overall_mean_ci_upper": ci.ci_upper,
    }
