"""Vectorized statistic primitives shared by every stats kernel.

These are pure-JAX building blocks: Pearson/Spearman correlation, average
ranks with tie handling, and resample-index generation. The reference computes
each of these with scipy inside Python loops (e.g.
survey_analysis/survey_analysis_consolidated.py:162-200); here they are
shape-static jittable functions designed to be `vmap`ed over bootstrap
resamples so the whole CI computation is one XLA program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pearson(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Pearson r along the last axis. Broadcasts over leading axes."""
    xm = x - x.mean(axis=-1, keepdims=True)
    ym = y - y.mean(axis=-1, keepdims=True)
    cov = (xm * ym).sum(axis=-1)
    denom = jnp.sqrt((xm * xm).sum(axis=-1) * (ym * ym).sum(axis=-1))
    return jnp.where(denom > 0, cov / denom, jnp.nan)


def average_ranks(x: jnp.ndarray) -> jnp.ndarray:
    """Ranks (1-based) with ties assigned their average rank, along the last
    axis — matches ``scipy.stats.rankdata(method='average')``.

    Uses an O(n^2) pairwise comparison, which XLA turns into one fused
    broadcast kernel; for the corpus sizes here (50 questions, ~500
    respondents) this is faster than sort-based tie bookkeeping and has no
    data-dependent shapes.
    """
    lt = (x[..., :, None] > x[..., None, :]).sum(axis=-1)
    eq = (x[..., :, None] == x[..., None, :]).sum(axis=-1)
    return lt + (eq + 1) / 2.0


def spearman(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Spearman rho along the last axis (Pearson of average ranks)."""
    return pearson(average_ranks(x), average_ranks(y))


def resample_indices(key: jax.Array, n_boot: int, n: int) -> jnp.ndarray:
    """(n_boot, n) matrix of with-replacement resample indices."""
    return jax.random.randint(key, (n_boot, n), 0, n)


def percentile_ci(samples: jnp.ndarray, confidence: float = 0.95):
    """Percentile CI along the last axis; returns (lower, upper)."""
    alpha = (1.0 - confidence) / 2.0
    lower = jnp.nanpercentile(samples, 100.0 * alpha, axis=-1)
    upper = jnp.nanpercentile(samples, 100.0 * (1.0 - alpha), axis=-1)
    return lower, upper


def nan_filter(x, *others):
    """Host-side helper: keep positions finite in every array (the reference
    filters NaN/inf before every statistic, SURVEY.md §4)."""
    import numpy as np

    arrs = [np.asarray(a, dtype=float) for a in (x, *others)]
    mask = np.ones(arrs[0].shape[0], dtype=bool)
    for a in arrs:
        mask &= np.isfinite(a)
    out = tuple(a[mask] for a in arrs)
    return out if others else out[0]
