"""Normality tests (KS vs fitted normal + Anderson-Darling).

Parity target: conduct_normality_tests (analyze_perturbation_results.py:
21-110). These are one-shot host-side tests per (model, prompt, column) —
not hot — so they wrap scipy directly; the value of this module is the exact
output schema and the reference's banded AD p-value approximation (scipy has
no AD p-value; SURVEY.md §7 notes the approximation is kept and documented).
"""

from __future__ import annotations

import warnings
from typing import Dict

import numpy as np
from scipy import stats as scipy_stats


def anderson_darling_pvalue(statistic: float, critical_values: np.ndarray) -> float:
    """Banded p-value approximation from the AD critical values
    (analyze_perturbation_results.py:82-94). `critical_values` is scipy's
    5-vector for significance levels [15%, 10%, 5%, 2.5%, 1%]."""
    if statistic > 10:
        return 0.0001
    if statistic > critical_values[4]:
        return 0.005
    if statistic > critical_values[3]:
        return 0.015
    if statistic > critical_values[2]:
        return 0.035
    if statistic > critical_values[1]:
        return 0.075
    return 0.15


def normality_tests(
    values: np.ndarray, prompt_idx: int = 0
) -> Dict[str, object]:
    """KS test vs a fitted normal + Anderson-Darling, reference schema."""
    values = np.asarray(values, dtype=np.float64)
    values = values[np.isfinite(values)]

    empty = {
        "Prompt": prompt_idx + 1,
        "Distribution Mean": float("nan"),
        "Distribution Std Dev": float("nan"),
        "KS Statistic": float("nan"),
        "KS p-value": float("nan"),
        "KS Normal (p>0.05)": False,
        "AD Statistic": float("nan"),
        "AD p-value": float("nan"),
        "AD Critical Value (5%)": float("nan"),
        "AD Normal (stat<crit)": False,
    }
    if values.size == 0:
        return empty
    if values.size < 3:
        empty["Distribution Mean"] = float(values.mean())
        if values.size > 1:
            empty["Distribution Std Dev"] = float(values.std())
        return empty

    mu, sigma = scipy_stats.norm.fit(values)
    ks_stat, ks_p = scipy_stats.kstest(values, "norm", args=(mu, sigma))
    with warnings.catch_warnings():
        # scipy >= 1.17 deprecates the critical-value result shape; we use
        # exactly that shape (statistic + critical values) to reproduce the
        # reference's hand-rolled p approximation, so keep it and silence
        # the migration warning.
        warnings.simplefilter("ignore", FutureWarning)
        ad = scipy_stats.anderson(values, "norm")
    ad_p = anderson_darling_pvalue(float(ad.statistic), np.asarray(ad.critical_values))

    return {
        "Prompt": prompt_idx + 1,
        "Distribution Mean": float(mu),
        "Distribution Std Dev": float(sigma),
        "KS Statistic": float(ks_stat),
        "KS p-value": float(ks_p),
        "KS Normal (p>0.05)": bool(ks_p > 0.05),
        "AD Statistic": float(ad.statistic),
        "AD p-value": float(ad_p),
        "AD Critical Value (5%)": float(ad.critical_values[2]),
        "AD Normal (stat<crit)": bool(ad.statistic < ad.critical_values[2]),
    }


def compare_distributions(a: np.ndarray, b: np.ndarray) -> Dict[str, float]:
    """Distribution-comparison battery: Mann-Whitney U, two-sample KS,
    Welch t-test, Cohen's d (calculate_correlation_pvalues.py:138-204)."""
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    a, b = a[np.isfinite(a)], b[np.isfinite(b)]
    u_stat, u_p = scipy_stats.mannwhitneyu(a, b, alternative="two-sided")
    ks_stat, ks_p = scipy_stats.ks_2samp(a, b)
    t_stat, t_p = scipy_stats.ttest_ind(a, b, equal_var=False)
    pooled = np.sqrt(
        ((a.size - 1) * a.var(ddof=1) + (b.size - 1) * b.var(ddof=1))
        / (a.size + b.size - 2)
    )
    d = float((a.mean() - b.mean()) / pooled) if pooled > 0 else float("nan")
    return {
        "mannwhitney_u": float(u_stat),
        "mannwhitney_p": float(u_p),
        "ks_statistic": float(ks_stat),
        "ks_p": float(ks_p),
        "t_statistic": float(t_stat),
        "t_p": float(t_p),
        "cohens_d": d,
        "n_a": int(a.size),
        "n_b": int(b.size),
        "mean_a": float(a.mean()),
        "mean_b": float(b.mean()),
    }
