"""Bootstrap confidence intervals as single vmapped XLA programs.

The reference runs every bootstrap as a Python for-loop over scipy calls —
1,000 to 10,000 iterations each (survey_analysis_consolidated.py:162-200,
bootstrap_confidence_intervals.py:101-239, analyze_llm_agreement_simple_
bootstrap.py:90-149). Here one `jax.vmap` over a (n_boot, n) index matrix
computes all resamples in a single fused kernel; the resample axis can further
be sharded over the `data` mesh axis by the caller.

Determinism: every function takes an explicit `jax.random` key (threaded
PRNG replaces the reference's global numpy seed-42; SURVEY.md §7 hard part 6).
Results are reproducible bit-for-bit for a fixed key and backend.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
from scipy import stats as scipy_stats

from .core import pearson, percentile_ci, resample_indices, spearman


@dataclasses.dataclass
class BootstrapResult:
    """Point estimate + percentile CI, mirroring the dict returned by
    survey_analysis_consolidated.py:192-200 (minus the raw distribution,
    available via `samples`)."""

    estimate: float
    p_value: float
    ci_lower: float
    ci_upper: float
    standard_error: float
    samples: np.ndarray

    def as_dict(self) -> Dict[str, float]:
        return {
            "correlation": self.estimate,
            "p_value": self.p_value,
            "ci_lower": self.ci_lower,
            "ci_upper": self.ci_upper,
            "standard_error": self.standard_error,
        }


def _bootstrap_stat(
    stat: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    x: jnp.ndarray,
    y: jnp.ndarray,
    key: jax.Array,
    n_boot: int,
) -> jnp.ndarray:
    n = x.shape[0]
    idx = resample_indices(key, n_boot, n)
    return jax.vmap(lambda i: stat(x[i], y[i]))(idx)


_bootstrap_pearson_jit = jax.jit(
    lambda x, y, key, n_boot: _bootstrap_stat(pearson, x, y, key, n_boot),
    static_argnames=("n_boot",),
)
_bootstrap_spearman_jit = jax.jit(
    lambda x, y, key, n_boot: _bootstrap_stat(spearman, x, y, key, n_boot),
    static_argnames=("n_boot",),
)
_resampled_means_jit = jax.jit(jax.vmap(lambda v, i: v[i].mean(), in_axes=(None, 0)))


@functools.cache
def _jitted_metric_bootstrap(metric_fn, n_boot: int):
    """One compiled program per (metric function, resample count) — jit's
    cache is keyed on the function object, so building a fresh lambda per
    call would recompile every time."""
    return jax.jit(
        lambda a, b, k: jax.vmap(lambda i: metric_fn(a[i], b[i]))(
            resample_indices(k, n_boot, a.shape[0])
        )
    )


_permutation_diffs_jit = jax.jit(
    jax.vmap(
        lambda k, pooled, n_a: (
            lambda perm: perm[:n_a].mean() - perm[n_a:].mean()
        )(jax.random.permutation(k, pooled)),
        in_axes=(0, None, None),
    ),
    static_argnames=("n_a",),
)


def bootstrap_correlation(
    x,
    y,
    key: jax.Array,
    n_boot: int = 1000,
    confidence: float = 0.95,
    method: str = "pearson",
) -> BootstrapResult:
    """Correlation + percentile bootstrap CI + SE.

    Parity target: calculate_pearson_with_bootstrap
    (survey_analysis_consolidated.py:162-200). The point estimate and p-value
    use scipy (exact match); the resampling distribution is computed on device.
    """
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    if method == "pearson":
        est, p = scipy_stats.pearsonr(x, y)
        samples = _bootstrap_pearson_jit(
            jnp.asarray(x), jnp.asarray(y), key, n_boot
        )
    elif method == "spearman":
        est, p = scipy_stats.spearmanr(x, y)
        samples = _bootstrap_spearman_jit(
            jnp.asarray(x), jnp.asarray(y), key, n_boot
        )
    else:
        raise ValueError(f"unknown method {method!r}")
    samples = np.asarray(samples)
    lo, hi = percentile_ci(jnp.asarray(samples), confidence)
    return BootstrapResult(
        estimate=float(est),
        p_value=float(p),
        ci_lower=float(lo),
        ci_upper=float(hi),
        standard_error=float(np.nanstd(samples)),
        samples=samples,
    )


def bootstrap_mean_ci(
    values,
    key: jax.Array,
    n_boot: int = 1000,
    confidence: float = 0.95,
) -> BootstrapResult:
    """Bootstrap CI for a mean (used for per-item agreement means,
    survey_analysis_consolidated.py:268-286, and metric CIs in
    analyze_llm_agreement_simple_bootstrap.py)."""
    v = jnp.asarray(np.asarray(values, dtype=np.float64))
    idx = resample_indices(key, n_boot, v.shape[0])
    samples = np.asarray(_resampled_means_jit(v, idx))
    lo, hi = percentile_ci(jnp.asarray(samples), confidence)
    return BootstrapResult(
        estimate=float(np.mean(np.asarray(values, dtype=np.float64))),
        p_value=float("nan"),
        ci_lower=float(lo),
        ci_upper=float(hi),
        standard_error=float(np.nanstd(samples)),
        samples=samples,
    )


def bootstrap_metric_matrix(
    metric_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    x,
    y,
    key: jax.Array,
    n_boot: int = 1000,
) -> np.ndarray:
    """Generic paired-resample bootstrap of an arbitrary jittable metric
    (MAE/RMSE/Pearson...). Returns the raw sample distribution so callers can
    build whatever summary the reference emits."""
    xj, yj = jnp.asarray(np.asarray(x, float)), jnp.asarray(np.asarray(y, float))
    return np.asarray(_jitted_metric_bootstrap(metric_fn, n_boot)(xj, yj, key))


# Jittable agreement metrics (analyze_llm_human_agreement.py:94-148) for use
# with bootstrap_metric_matrix.
def mae(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    return jnp.abs(x - y).mean(axis=-1)


def rmse(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    return jnp.sqrt(((x - y) ** 2).mean(axis=-1))


def mape(x: jnp.ndarray, y: jnp.ndarray, eps: float = 1e-10) -> jnp.ndarray:
    """Mean absolute percentage error vs x (human) as the denominator."""
    return (jnp.abs((x - y) / jnp.where(jnp.abs(x) < eps, eps, x))).mean(axis=-1) * 100.0


def permutation_test_difference(
    a,
    b,
    key: jax.Array,
    n_perm: int = 10_000,
) -> Dict[str, float]:
    """Two-sided permutation test for mean(a) - mean(b) by random relabeling.

    Parity target: the base-vs-instruct permutation p-value at
    analyze_llm_agreement_simple_bootstrap.py:312-347. Vectorized: one
    (n_perm, n_a+n_b) permutation tensor, one fused reduction.
    """
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    pooled = jnp.asarray(np.concatenate([a, b]))
    n_a = a.shape[0]
    observed = float(a.mean() - b.mean())
    keys = jax.random.split(key, n_perm)
    diffs = np.asarray(_permutation_diffs_jit(keys, pooled, n_a))
    p = float(np.mean(np.abs(diffs) >= abs(observed)))
    return {
        "observed_difference": observed,
        "p_value": p,
        "n_permutations": n_perm,
    }


def normal_approx_mc_difference(
    mean_a: float,
    std_a: float,
    mean_b: float,
    std_b: float,
    key: jax.Array,
    n_draws: int = 10_000,
) -> Dict[str, float]:
    """Monte-Carlo difference distribution from two normal approximations.

    Parity target: analyze_model_family_differences.py:169-230 — draw both
    metrics from N(mean, std), form the difference, report percentile CI and a
    two-tailed p-value for difference != 0.
    """
    k1, k2 = jax.random.split(key)
    draws_a = mean_a + std_a * jax.random.normal(k1, (n_draws,))
    draws_b = mean_b + std_b * jax.random.normal(k2, (n_draws,))
    diff = np.asarray(draws_a - draws_b)
    p_pos = float(np.mean(diff > 0))
    # Two-tailed p from the MC sign proportion, as the reference computes it.
    p_two = float(2 * min(p_pos, 1 - p_pos))
    return {
        "difference_mean": float(np.mean(diff)),
        "ci_lower": float(np.percentile(diff, 2.5)),
        "ci_upper": float(np.percentile(diff, 97.5)),
        "p_value": p_two,
    }


def simulate_individuals(
    means,
    stds,
    key: jax.Array,
    n_individuals: int,
) -> jnp.ndarray:
    """Simulate individual humans from per-question (mean, std):
    clip(N(mu, sigma), 0, 1) — bootstrap_confidence_intervals.py:86-89.

    Returns (n_individuals, n_questions).
    """
    means = jnp.asarray(np.asarray(means, float))
    stds = jnp.asarray(np.asarray(stds, float))
    draws = means[None, :] + stds[None, :] * jax.random.normal(
        key, (n_individuals, means.shape[0])
    )
    return jnp.clip(draws, 0.0, 1.0)
