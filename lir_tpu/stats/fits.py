"""Zero/one-inflated truncated-normal Monte-Carlo fitter.

Parity target: conduct_truncated_normal_test
(analyze_perturbation_results.py:113-337) — the reference's hottest loop:
<=30 Python iterations each drawing 100,000 numpy normals, clipping to [0,1],
and moment-matching with damping 0.5 / tolerance 1e-4. Here the whole fit is
one `lax.while_loop` whose body draws its samples on device, so the full
(models x prompts x 2 columns) sweep can additionally be vmapped.

The goodness-of-fit readout (two-sample KS + Anderson k-sample) stays on
scipy: those are one-shot host-side tests on the final sample, not hot.
"""

from __future__ import annotations

import warnings

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from scipy import stats as scipy_stats

EPSILON = 1e-6  # zero/one tolerance, analyze_perturbation_results.py:151


def _simulate(key: jax.Array, mu, sigma, n: int) -> jnp.ndarray:
    return jnp.clip(mu + sigma * jax.random.normal(key, (n,)), 0.0, 1.0)


def _fit_loop(
    key: jax.Array,
    target_mean: jnp.ndarray,
    target_std: jnp.ndarray,
    n_simulations: int,
    max_iterations: int,
    tol: float,
    damping: float,
):
    """Iterative moment matching as a while_loop; returns (mu, sigma, iters)."""

    def cond(state):
        _, _, i, converged = state
        return (~converged) & (i < max_iterations)

    def body(state):
        mu, sigma, i, _ = state
        sim = _simulate(jax.random.fold_in(key, i), mu, sigma, n_simulations)
        sim_mean, sim_std = sim.mean(), sim.std()
        converged = (jnp.abs(sim_mean - target_mean) < tol) & (
            jnp.abs(sim_std - target_std) < tol
        )
        # Multiplicative adjustment with damping, plus a direct additive mean
        # shift when the mean is off by > 1e-3 (reference :216-243).
        mean_adj = 1 + damping * (
            jnp.where(sim_mean > 0, target_mean / sim_mean, 1.0) - 1
        )
        std_adj = 1 + damping * (
            jnp.where(sim_std > 0, target_std / sim_std, 1.0) - 1
        )
        new_mu = mu * mean_adj
        new_sigma = sigma * std_adj
        new_mu = new_mu + jnp.where(
            jnp.abs(sim_mean - target_mean) > 0.001,
            damping * (target_mean - sim_mean),
            0.0,
        )
        new_mu = jnp.where(converged, mu, new_mu)
        new_sigma = jnp.where(converged, sigma, new_sigma)
        return (new_mu, new_sigma, i + 1, converged)

    mu, sigma, iters, _ = jax.lax.while_loop(
        cond, body, (target_mean, target_std, jnp.int32(0), jnp.bool_(False))
    )
    return mu, sigma, iters


_fit_loop_jit = jax.jit(
    _fit_loop, static_argnames=("n_simulations", "max_iterations")
)


def truncated_normal_mc_fit(
    values: np.ndarray,
    key: jax.Array,
    n_simulations: int = 100_000,
    max_iterations: int = 30,
    tol: float = 1e-4,
    damping: float = 0.5,
    prompt_idx: int = 0,
    column_name: str = "",
) -> Tuple[Dict[str, object], np.ndarray]:
    """Fit clip(N(mu, sigma), 0, 1) to `values` by MC moment matching and test
    the fit. Returns (results dict in the reference's schema, final sample).
    """
    values = np.asarray(values, dtype=np.float64)
    values = values[np.isfinite(values)]

    base = {
        "Prompt": prompt_idx + 1,
        "Column": column_name,
        "Model Type": "Truncated Normal with Zero/One Inflation",
    }
    failure_nans = {
        "KS Statistic": float("nan"),
        "KS p-value": float("nan"),
        "AD Statistic": float("nan"),
        "AD p-value": float("nan"),
        "Interior Mean": float("nan"),
        "Interior Std Dev": float("nan"),
        "Model Adequate (KS p>0.05)": False,
        "Model Adequate (AD p>0.05)": False,
        "Model Adequate (Combined)": False,
    }
    if values.size == 0:
        return {
            **base,
            "Model Fit": "Failed - No finite values",
            "Zero Proportion": float("nan"),
            "One Proportion": float("nan"),
            **failure_nans,
        }, np.array([])

    zero_prop = float(np.mean(values < EPSILON))
    one_prop = float(np.mean(values > 1 - EPSILON))
    interior = values[(values >= EPSILON) & (values <= 1 - EPSILON)]
    if interior.size == 0:
        return {
            **base,
            "Model Fit": "Failed - All values are 0 or 1",
            "Zero Proportion": zero_prop,
            "One Proportion": one_prop,
            **failure_nans,
        }, np.array([])

    target_mean = float(values.mean())
    target_std = float(values.std())

    fit_key, sim_key = jax.random.split(key)
    mu, sigma, iters = _fit_loop_jit(
        fit_key,
        jnp.asarray(target_mean, jnp.float32),
        jnp.asarray(target_std, jnp.float32),
        n_simulations,
        max_iterations,
        tol,
        damping,
    )
    mu, sigma = float(mu), float(sigma)
    sample = np.asarray(_simulate(sim_key, mu, sigma, n_simulations), dtype=np.float64)
    sim_mean, sim_std = float(sample.mean()), float(sample.std())

    mean_err = abs(sim_mean - target_mean) / target_mean if target_mean else abs(sim_mean)
    std_err = abs(sim_std - target_std) / target_std if target_std else abs(sim_std)

    # Fallback: direct scipy truncnorm sampling when MC accuracy is poor
    # (reference :259-290) — kept verbatim in spirit, scipy is fine here.
    if mean_err > 0.01 or std_err > 0.01:
        a, b = (0 - mu) / sigma, (1 - mu) / sigma
        alt = scipy_stats.truncnorm.rvs(
            a, b, loc=mu, scale=sigma, size=n_simulations,
            random_state=np.random.default_rng(42),
        )
        alt_mean_err = abs(alt.mean() - target_mean) / target_mean if target_mean else abs(alt.mean())
        alt_std_err = abs(alt.std() - target_std) / target_std if target_std else abs(alt.std())
        if alt_mean_err < mean_err and alt_std_err < std_err:
            sample = alt
            sim_mean, sim_std = float(alt.mean()), float(alt.std())
            mean_err, std_err = alt_mean_err, alt_std_err

    ks_stat, ks_p = scipy_stats.ks_2samp(values, sample)
    try:
        with warnings.catch_warnings():
            # midrank-deprecation and p-value-capped/floored notices are
            # informational; the statistic is what the artifact records.
            warnings.simplefilter("ignore", UserWarning)
            ad = scipy_stats.anderson_ksamp([values, sample])
        ad_stat, ad_p = float(ad.statistic), float(ad.pvalue)
        ad_ok = ad_p > 0.05
    except Exception:
        ad_stat, ad_p, ad_ok = float("nan"), float("nan"), False

    results = {
        **base,
        "Underlying Normal Mean": mu,
        "Underlying Normal Std Dev": sigma,
        "Observed Mean": target_mean,
        "Observed Std Dev": target_std,
        "Simulated Mean": sim_mean,
        "Simulated Std Dev": sim_std,
        "Mean Relative Error": float(mean_err),
        "Std Relative Error": float(std_err),
        "Zero Proportion": zero_prop,
        "One Proportion": one_prop,
        "Interior Mean": float(interior.mean()),
        "Interior Std Dev": float(interior.std()),
        "Iterations": int(iters),
        "KS Statistic": float(ks_stat),
        "KS p-value": float(ks_p),
        "AD Statistic": ad_stat,
        "AD p-value": ad_p,
        "Model Adequate (KS p>0.05)": bool(ks_p > 0.05),
        "Model Adequate (AD p>0.05)": bool(ad_ok),
        "Model Adequate (Combined)": bool(ks_p > 0.05) and bool(ad_ok),
    }
    return results, sample
