"""Inter-model correlation and agreement suite over the D2 CSV (C30).

Parity target: analysis/model_comparison_graph.py:33-781 — reference-model
difference plot (Baichuan anchor with fallback), prompt-resampled bootstrap
(1000x) of the model-model Pearson/Spearman correlation matrices with
percentile CIs for mean/median/std, lower-triangle heatmap with abbreviated
names, pairwise model kappas, and the pooled aggregate kappa with bootstrap
CI. Filters opt-iml and Mistral rows as the reference does (:724-726).

The 1000-iteration correlation-matrix bootstrap (a pandas .corr() per
iteration in the reference, :207-340) runs as one vmapped masked-Pearson
kernel (stats.correlations.bootstrap_correlation_matrix).
"""

from __future__ import annotations

import argparse
from pathlib import Path
from typing import Dict, List

import jax
import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402
import seaborn as sns  # noqa: E402

from ..stats.correlations import bootstrap_correlation_matrix  # noqa: E402
from ..stats.kappa import aggregate_kappa, pairwise_kappa_matrix  # noqa: E402
from ..utils.logging import get_logger  # noqa: E402

log = get_logger(__name__)

FILTERED_MODEL_PATTERNS = ("opt-iml-1.3b", "mistral")  # reference :724-726


def filter_models(df: pd.DataFrame) -> pd.DataFrame:
    out = df
    out = out[~out["model"].str.contains("opt-iml-1.3b")]
    out = out[~out["model"].str.contains("mistral", case=False)]
    return out


def abbreviated_model_name(model_name: str) -> str:
    """Short display name (get_abbreviated_model_name, :342-387)."""
    name = model_name.split("/")[-1]
    return name[:18] + ".." if len(name) > 20 else name


def prompt_model_pivot(df: pd.DataFrame) -> pd.DataFrame:
    return df.pivot_table(index="prompt", columns="model", values="relative_prob")


def reference_model_differences(
    df: pd.DataFrame, rng: np.random.Generator
) -> Dict[str, object]:
    """Per-model differences in relative_prob vs the Baichuan anchor
    (random fallback when absent, :59-79)."""
    models = df["model"].unique()
    anchors = [m for m in models if "baichuan" in m.lower()]
    if anchors:
        reference_model = anchors[0]
    else:
        prompts = df["prompt"].unique()
        valid = [
            m
            for m in models
            if df[df["model"] == m]["relative_prob"].notna().sum() >= len(prompts)
        ]
        if not valid:
            counts = df.groupby("model")["relative_prob"].count()
            valid = [counts.idxmax()]
        reference_model = valid[int(rng.integers(len(valid)))]

    pivot = prompt_model_pivot(df)
    ref = pivot[reference_model]
    diffs: Dict[str, np.ndarray] = {}
    for model in models:
        if model == reference_model:
            continue
        d = (pivot[model] - ref).dropna().to_numpy()
        if d.size:
            diffs[model] = d
    return {"reference_model": reference_model, "differences": diffs}


def plot_reference_differences(
    result: Dict[str, object], output_path: Path, rng: np.random.Generator
) -> None:
    """Violin + jitter + CI per model vs the anchor (:83-205)."""
    diffs: Dict[str, np.ndarray] = result["differences"]
    if not diffs:
        return
    colors = plt.cm.tab10(np.linspace(0, 1, 10))
    fig, ax = plt.subplots(figsize=(14, 10))
    legend_elements = []
    for idx, (model, vals) in enumerate(diffs.items()):
        color = colors[idx % len(colors)]
        parts = ax.violinplot([vals], [idx], widths=0.6, showmeans=False,
                              showmedians=False, showextrema=False)
        for pc in parts["bodies"]:
            pc.set_facecolor(color)
            pc.set_edgecolor("none")
            pc.set_alpha(0.3)
        ax.scatter(rng.normal(idx, 0.08, size=vals.size), vals, alpha=0.7,
                   s=50, color=color)
        if vals.size > 1:
            lo, hi = np.percentile(vals, [2.5, 97.5])
            ax.plot([idx, idx], [lo, hi], color="black", linewidth=2, zorder=4)
            for y in (lo, hi):
                ax.plot([idx - 0.1, idx + 0.1], [y, y], color="black",
                        linewidth=2, zorder=4)
        ax.scatter(idx, vals.mean(), color="black", s=100, zorder=5)
        legend_elements.append(
            plt.Line2D([0], [0], marker="s", color="w", markerfacecolor=color,
                       markersize=10, label=model.split("/")[-1])
        )
    ax.scatter(len(diffs), 0, color="black", s=100, marker="*")
    legend_elements.append(
        plt.Line2D([0], [0], marker="*", color="black", markersize=10,
                   label=f"Reference: {result['reference_model'].split('/')[-1]}")
    )
    ax.axhline(0, color="gray", linestyle="--", alpha=0.7)
    ax.set_xticks(range(len(diffs)))
    ax.set_xticklabels([""] * len(diffs))
    ax.set_xlabel("Model")
    ax.set_ylabel("Difference in Relative Probability\nfrom Reference Model")
    ax.legend(handles=legend_elements, loc="upper center",
              bbox_to_anchor=(0.5, -0.15), ncol=3)
    fig.tight_layout()
    fig.subplots_adjust(bottom=0.3)
    output_path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(output_path, dpi=150, bbox_inches="tight")
    plt.close(fig)


def plot_correlation_matrix(
    corr_matrix: np.ndarray, model_names: List[str], output_path: Path
) -> None:
    """Lower-triangle heatmap with abbreviated names (:389-433)."""
    mask = np.triu(np.ones_like(corr_matrix, dtype=bool))
    labels = [abbreviated_model_name(m) for m in model_names]
    fig = plt.figure(figsize=(12, 10))
    sns.heatmap(
        corr_matrix, mask=mask, cmap="RdBu_r", center=0, vmin=-1, vmax=1,
        annot=True, fmt=".2f", annot_kws={"size": 8},
        xticklabels=labels, yticklabels=labels,
        cbar_kws={"label": "Correlation"},
    )
    plt.xticks(rotation=45, ha="right")
    plt.tight_layout()
    output_path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(output_path, dpi=150, bbox_inches="tight")
    plt.close(fig)


def plot_correlation_distribution(
    values: np.ndarray,
    output_path: Path,
    correlation_type: str,
    mean_ci,
    median_ci,
) -> None:
    """Histogram of pairwise correlations with CI markers (:435-493)."""
    fig, ax = plt.subplots(figsize=(10, 6))
    ax.hist(values, bins=20, edgecolor="black", alpha=0.7)
    ax.axvline(values.mean(), color="red", linestyle="--",
               label=f"Mean: {values.mean():.3f} "
                     f"[{mean_ci[0]:.3f}, {mean_ci[1]:.3f}]")
    ax.axvline(np.median(values), color="green", linestyle="--",
               label=f"Median: {np.median(values):.3f} "
                     f"[{median_ci[0]:.3f}, {median_ci[1]:.3f}]")
    ax.set_xlabel(f"{correlation_type.capitalize()} correlation")
    ax.set_ylabel("Frequency")
    ax.set_title(f"Pairwise model {correlation_type} correlations")
    ax.legend()
    fig.tight_layout()
    output_path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(output_path, dpi=150, bbox_inches="tight")
    plt.close(fig)


def plot_kappa_distribution(kappas: np.ndarray, output_path: Path) -> None:
    """Histogram of pairwise model kappas (:674-708)."""
    kappas = kappas[np.isfinite(kappas)]
    if kappas.size == 0:
        return
    fig, ax = plt.subplots(figsize=(10, 6))
    ax.hist(kappas, bins=20, edgecolor="black", alpha=0.7)
    ax.axvline(kappas.mean(), color="red", linestyle="--",
               label=f"Mean: {kappas.mean():.3f}")
    ax.set_xlabel("Cohen's Kappa")
    ax.set_ylabel("Frequency")
    ax.legend()
    fig.tight_layout()
    output_path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(output_path, dpi=150, bbox_inches="tight")
    plt.close(fig)


def run_model_graph_analysis(
    instruct_csv: Path,
    out_dir: Path,
    seed: int = 42,
    n_bootstrap: int = 1000,
    make_figures: bool = True,
) -> Dict[str, object]:
    """Full C30 pipeline (__main__, :710-781)."""
    out_dir = Path(out_dir)
    figures_dir = out_dir / "figures"
    out_dir.mkdir(parents=True, exist_ok=True)
    key = jax.random.PRNGKey(seed)
    rng = np.random.default_rng(seed)

    df = filter_models(pd.read_csv(instruct_csv))
    log.info(
        "Model graph analysis: %d rows, %d models after filtering",
        len(df), df["model"].nunique(),
    )
    pivot = prompt_model_pivot(df)
    model_names = list(pivot.columns)

    ref_diffs = reference_model_differences(df, rng)
    if make_figures:
        plot_reference_differences(
            ref_diffs, figures_dir / "model_comparison_plot.png", rng
        )

    correlations: Dict[str, Dict[str, object]] = {}
    for corr_type in ("pearson", "spearman"):
        key, sub = jax.random.split(key)
        stats = bootstrap_correlation_matrix(
            pivot.to_numpy(dtype=float), sub, method=corr_type,
            n_bootstrap=n_bootstrap,
        )
        correlations[corr_type] = stats
        pd.DataFrame(
            stats["correlation_matrix"], index=model_names, columns=model_names
        ).to_csv(out_dir / f"model_{corr_type}_correlation_matrix.csv")
        if make_figures:
            plot_correlation_matrix(
                stats["correlation_matrix"], model_names,
                figures_dir / f"model_{corr_type}_correlation_matrix.png",
            )
            plot_correlation_distribution(
                stats["correlation_values"],
                figures_dir / f"model_{corr_type}_correlation_distribution.png",
                corr_type, stats["mean_ci"], stats["median_ci"],
            )

    binary = (pivot.to_numpy(dtype=float) > 0.5).astype(float)
    binary[~np.isfinite(pivot.to_numpy(dtype=float))] = np.nan
    kappa_matrix = pairwise_kappa_matrix(binary)
    pd.DataFrame(kappa_matrix, index=model_names, columns=model_names).to_csv(
        out_dir / "model_pairwise_kappa_matrix.csv"
    )
    iu = np.triu_indices(len(model_names), k=1)
    if make_figures:
        plot_kappa_distribution(
            kappa_matrix[iu], figures_dir / "model_kappa_distribution.png"
        )

    # Aggregate kappa over prompts answered by every model; fall back to
    # >= 2 models per prompt, as the reference does (:567-571).
    complete = pivot.dropna()
    if len(complete) < 2:
        complete = pivot.dropna(thresh=2)
    key, sub = jax.random.split(key)
    agg = aggregate_kappa(
        (complete.to_numpy(dtype=float) > 0.5).astype(np.float32), sub,
        n_boot=n_bootstrap,
    )
    pd.DataFrame([agg]).to_csv(out_dir / "aggregate_kappa_results.csv", index=False)

    summary = {
        "reference_model": ref_diffs["reference_model"],
        "correlations": {
            k: {kk: vv for kk, vv in v.items()
                if kk not in ("correlation_matrix", "correlation_values")}
            for k, v in correlations.items()
        },
        "aggregate_kappa": agg,
    }
    return {
        "pivot": pivot,
        "reference_differences": ref_diffs,
        "correlations": correlations,
        "pairwise_kappa_matrix": kappa_matrix,
        "aggregate_kappa": agg,
        "summary": summary,
    }


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--instruct", type=Path, required=True,
                        help="D2 instruct_model_comparison_results.csv")
    parser.add_argument("--out", type=Path, default=Path("results/model_graph"))
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--no-figures", action="store_true")
    args = parser.parse_args()
    run_model_graph_analysis(
        args.instruct, args.out, seed=args.seed,
        make_figures=not args.no_figures,
    )


if __name__ == "__main__":
    main()
