"""Statistical analysis drivers (reference: analysis/analyze_*.py,
calculate_cohens_kappa.py, model_comparison_graph.py — C20-C30).

Each driver consumes a §2.4 data artifact and reproduces the reference's
CSV/LaTeX/figure outputs, with the hot statistics routed through the
vectorized kernels in lir_tpu.stats.
"""

from .perturbation import (
    add_relative_prob,
    analyze_all_models,
    analyze_model,
    assert_compliance,
    check_confidence_compliance,
    check_output_compliance,
    expected_compliance_tokens,
    parse_logprob_content,
    perturbation_kappa,
    prompt_summary_stats,
)
from .base_vs_instruct import (
    family_differences,
    process_model_pair,
    run_base_vs_instruct_analysis,
)
from .kappa_combined import (
    combine_kappas,
    kappa_latex_table,
    match_legal_prompts,
    prepare_model_data,
    prepare_perturbation_data,
    run_kappa_analysis,
)
from .model_graph import (
    abbreviated_model_name,
    filter_models,
    prompt_model_pivot,
    reference_model_differences,
    run_model_graph_analysis,
)
