"""Base-vs-instruct delta analysis over the D1 CSV (C28).

Parity target: analysis/analyze_results_base_versus_instruct.py:1-268 —
pair base/instruct rows per family on prompt, drop rows where any of the four
probabilities is zero, recompute relative probabilities, report per-family
Pearson r and the instruct-minus-base difference distribution (mean, std,
2.5/97.5 percentiles), and emit the bar/violin/heatmap figures plus three
CSVs. The Mistral family is dropped as in the reference (:34); hard-coded
G:/ paths become arguments.
"""

from __future__ import annotations

import argparse
from pathlib import Path
from typing import Dict, List

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402
import seaborn as sns  # noqa: E402
from scipy import stats as scipy_stats  # noqa: E402

from ..utils.logging import get_logger  # noqa: E402

log = get_logger(__name__)

DROPPED_FAMILIES = ("mistral",)  # reference :34


def process_model_pair(
    df: pd.DataFrame, base_model: str, instruct_model: str
) -> pd.DataFrame:
    """Merge one family's base/instruct rows on prompt, keep rows where all
    four probabilities are positive, and add rel_prob columns (:38-58)."""
    base = df[df["model"] == base_model]
    instruct = df[df["model"] == instruct_model]
    paired = pd.merge(base, instruct, on="prompt", suffixes=("_base", "_instruct"))
    valid = (
        (paired["yes_prob_base"] > 0)
        & (paired["no_prob_base"] > 0)
        & (paired["yes_prob_instruct"] > 0)
        & (paired["no_prob_instruct"] > 0)
    )
    paired["rel_prob_base"] = paired["yes_prob_base"] / (
        paired["yes_prob_base"] + paired["no_prob_base"]
    )
    paired["rel_prob_instruct"] = paired["yes_prob_instruct"] / (
        paired["yes_prob_instruct"] + paired["no_prob_instruct"]
    )
    return paired[valid]


def family_differences(df: pd.DataFrame) -> Dict[str, object]:
    """Per-family paired analysis: correlation + difference distribution.

    Returns {"statistics": rows, "prompt_differences": long frame}.
    """
    df = df[~df["model_family"].isin(DROPPED_FAMILIES)]
    stats_rows: List[Dict[str, object]] = []
    long_rows: List[Dict[str, object]] = []

    for family in df["model_family"].unique():
        fam = df[df["model_family"] == family]
        base_models = fam.loc[fam["base_or_instruct"] == "base", "model"]
        instruct_models = fam.loc[fam["base_or_instruct"] == "instruct", "model"]
        if base_models.empty or instruct_models.empty:
            log.info("Family %s lacks a base or instruct model; skipped", family)
            continue
        paired = process_model_pair(
            df, base_models.iloc[0], instruct_models.iloc[0]
        )
        if len(paired) == 0:
            log.info("Family %s has no valid pairs after zero filtering", family)
            continue

        corr, p = scipy_stats.pearsonr(
            paired["rel_prob_base"], paired["rel_prob_instruct"]
        )
        diff = (paired["rel_prob_instruct"] - paired["rel_prob_base"]).to_numpy()
        lo, hi = np.percentile(diff, [2.5, 97.5])
        stats_rows.append(
            {
                "Model_Family": family,
                "Mean": float(diff.mean()),
                "Std_Dev": float(diff.std()),
                "Lower_CI_95": float(lo),
                "Upper_CI_95": float(hi),
                "CI_Width": float(hi - lo),
                "Num_Samples": int(diff.size),
                "Correlation": float(corr),
                "Correlation_p": float(p),
            }
        )
        for prompt, d in zip(paired["prompt"], diff):
            long_rows.append(
                {"Difference": float(d), "Prompt": prompt, "Model Family": family}
            )

    return {
        "statistics": pd.DataFrame(stats_rows),
        "prompt_differences": pd.DataFrame(long_rows),
    }


def _bar_plot(stats_df: pd.DataFrame, path: Path) -> None:
    fig, ax = plt.subplots(figsize=(15, 8))
    ax.bar(stats_df["Model_Family"], stats_df["Mean"])
    ax.set_xticks(range(len(stats_df)))
    ax.set_xticklabels(stats_df["Model_Family"], rotation=45, ha="right")
    ax.set_title("Average Difference in Relative Probability\n(Instruct - Base)")
    ax.set_ylabel("Difference in Relative Probability")
    fig.tight_layout()
    fig.savefig(path, dpi=150)
    plt.close(fig)


def _violin_plot(
    long_df: pd.DataFrame, path: Path, rng: np.random.Generator
) -> None:
    families = long_df["Model Family"].unique()
    colors = plt.cm.tab10(np.linspace(0, 1, len(families)))
    fig, ax = plt.subplots(figsize=(15, 10))
    for idx, family in enumerate(families):
        vals = long_df.loc[long_df["Model Family"] == family, "Difference"].to_numpy()
        lo, hi = np.percentile(vals, [2.5, 97.5])
        parts = ax.violinplot([vals], [idx + 1], widths=0.3, showmeans=False,
                              showmedians=False, showextrema=False)
        for pc in parts["bodies"]:
            pc.set_facecolor(colors[idx])
            pc.set_edgecolor("none")
            pc.set_alpha(0.3)
        ax.scatter(rng.normal(idx + 1, 0.08, size=vals.size), vals,
                   alpha=0.4, s=30, color=colors[idx])
        ax.scatter(idx + 1, vals.mean(), color="black", s=80, zorder=5)
        ax.plot([idx + 1, idx + 1], [lo, hi], color="black", linewidth=2, zorder=4)
        for y in (lo, hi):
            ax.plot([idx + 0.9, idx + 1.1], [y, y], color="black", linewidth=2,
                    zorder=4)
    ax.axhline(0, color="gray", linestyle="--", alpha=0.7)
    ax.set_xticks(range(1, len(families) + 1))
    ax.set_xticklabels(families, rotation=45, ha="right")
    ax.set_ylabel("Relative Probability Difference (Instruct - Base)")
    ax.legend(
        handles=[
            plt.Line2D([0], [0], marker="o", color="w",
                       markerfacecolor=colors[i], markersize=10, label=f)
            for i, f in enumerate(families)
        ],
        loc="best",
    )
    fig.tight_layout()
    fig.savefig(path, dpi=150, bbox_inches="tight")
    plt.close(fig)


def _heatmap(pivot: pd.DataFrame, path: Path) -> None:
    fig = plt.figure(figsize=(18, max(4.0, len(pivot) * 0.4)))
    sns.heatmap(pivot, center=0, cmap="RdBu_r", fmt=".2f")
    fig.tight_layout()
    fig.savefig(path, dpi=150)
    plt.close(fig)


def run_base_vs_instruct_analysis(
    results_csv: Path,
    out_dir: Path,
    make_figures: bool = True,
    seed: int = 42,
) -> Dict[str, object]:
    """Full C28: analysis + figures + the three CSV artifacts."""
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    df = pd.read_csv(results_csv)
    res = family_differences(df)
    stats_df: pd.DataFrame = res["statistics"]
    long_df: pd.DataFrame = res["prompt_differences"]

    stats_df.to_csv(out_dir / "model_rel_prob_statistics.csv", index=False)
    long_df.to_csv(out_dir / "prompt_rel_prob_differences.csv", index=False)
    pivot = long_df.pivot_table(
        index="Prompt", columns="Model Family", values="Difference",
        aggfunc="mean",
    )
    pivot.to_csv(out_dir / "prompt_rel_prob_heatmap_data.csv")

    if make_figures and len(stats_df):
        rng = np.random.default_rng(seed)
        _bar_plot(stats_df, out_dir / "rel_prob_differences.png")
        _violin_plot(long_df, out_dir / "prompt_rel_prob_differences.png", rng)
        _heatmap(pivot, out_dir / "prompt_rel_prob_heatmap.png")

    return {**res, "heatmap": pivot}


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--results", type=Path, required=True,
                        help="D1 model_comparison_results.csv")
    parser.add_argument("--out", type=Path, default=Path("results/base_vs_instruct"))
    parser.add_argument("--no-figures", action="store_true")
    args = parser.parse_args()
    run_base_vs_instruct_analysis(
        args.results, args.out, make_figures=not args.no_figures
    )


if __name__ == "__main__":
    main()
