"""Per-model perturbation-distribution analysis (C20-C27).

Parity target: analysis/analyze_perturbation_results.py — for each model in
the D6 results workbook: relative probabilities, per-prompt summary stats
with 2.5/97.5 percentiles, normality tests (KS + Anderson-Darling),
truncated-normal Monte-Carlo fits, within-prompt Cohen's kappa, instruction
and confidence compliance audits, QQ/histogram/violin figures, and LaTeX
appendix tables. Artifact names match the reference exactly:

  summary_statistics.csv, normality_test_results.csv,
  truncated_normal_test_results.csv, cohens_kappa_results.csv,
  output_compliance_results.csv, confidence_compliance_results.csv,
  prompt_perturbation_tables.tex, prompt_perturbation_standalone.tex,
  compliance_summary.tex, confidence_compliance_summary.tex, figures/*.png

TPU-native redesign: the O(n^2) same-prompt kappa pair loop (:1127-1139) is
closed-form (stats.kappa.within_group_kappa); the 30x100k-sample MC fit
(:193-243) is a lax.while_loop kernel (stats.fits); QQ bootstrap bands are a
vmapped sort. Fixed hard-coded personal paths (:1965,2005) become arguments.

Compliance checks double as pipeline assertions (SURVEY.md §4): call
``assert_compliance`` to gate a sweep on minimum compliance rates instead of
only reporting them.
"""

from __future__ import annotations

import argparse
import ast
import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
import pandas as pd

from ..data.prompts import LEGAL_PROMPTS, LegalPrompt
from ..data.schemas import read_results_frame
from ..report import figures
from ..report.latex import (
    compliance_latex_table,
    confidence_compliance_latex_table,
    perturbation_latex_table,
    standalone_latex_document,
)
from ..stats.fits import truncated_normal_mc_fit
from ..stats.kappa import interpret_kappa, within_group_kappa
from ..stats.normality import normality_tests
from ..utils.logging import get_logger

log = get_logger(__name__)

MIN_ROWS_FOR_ANALYSIS = 100  # reference :1724


def add_relative_prob(df: pd.DataFrame) -> pd.DataFrame:
    """Relative_Prob = Token_1/(Token_1+Token_2), NaN on zero mass
    (:1738-1746)."""
    df = df.copy()
    total = df["Token_1_Prob"] + df["Token_2_Prob"]
    with np.errstate(invalid="ignore", divide="ignore"):
        df["Relative_Prob"] = np.where(
            total > 0, df["Token_1_Prob"] / total, np.nan
        )
    return df


# ---------------------------------------------------------------------------
# Per-prompt summary statistics (:1789-1845)
# ---------------------------------------------------------------------------


def prompt_summary_stats(
    prompt_data: pd.DataFrame, prompt_idx: int, token_options: Sequence[str]
) -> Dict[str, object]:
    first_token, second_token = token_options[0], token_options[1]
    finite = prompt_data[np.isfinite(prompt_data["Relative_Prob"])]
    if len(finite) > 0:
        rp = finite["Relative_Prob"]
        lo, hi = np.percentile(rp, [2.5, 97.5])
        stats = {
            "Prompt Number": prompt_idx + 1,
            "First Token": first_token,
            "Second Token": second_token,
            f'Mean Relative Probability of "{first_token}"': rp.mean(),
            "Std Dev": rp.std(),
            "Min": rp.min(),
            "Max": rp.max(),
            "2.5th Percentile": lo,
            "97.5th Percentile": hi,
            "95% Interval Width": hi - lo,
        }
    else:
        stats = {
            "Prompt Number": prompt_idx + 1,
            "First Token": first_token,
            "Second Token": second_token,
            f'Mean Relative Probability of "{first_token}"': np.nan,
            "Std Dev": np.nan,
            "Min": np.nan,
            "Max": np.nan,
            "2.5th Percentile": np.nan,
            "97.5th Percentile": np.nan,
            "95% Interval Width": np.nan,
        }

    has_conf = (
        "Weighted Confidence" in prompt_data.columns
        and not prompt_data["Weighted Confidence"].isna().all()
    )
    if has_conf:
        conf = prompt_data.dropna(subset=["Weighted Confidence"])[
            "Weighted Confidence"
        ]
        if len(conf) > 0:
            clo, chi = np.percentile(conf, [2.5, 97.5])
            stats.update(
                {
                    f'Mean Weighted Confidence for "{first_token}"': conf.mean(),
                    "Confidence Std Dev": conf.std(),
                    "Confidence Min": conf.min(),
                    "Confidence Max": conf.max(),
                    "Confidence 2.5th Percentile": clo,
                    "Confidence 97.5th Percentile": chi,
                    "Confidence 95% Interval Width": chi - clo,
                }
            )
    return stats


# ---------------------------------------------------------------------------
# Within-prompt Cohen's kappa (C24, :1094-1188)
# ---------------------------------------------------------------------------


def perturbation_kappa(df: pd.DataFrame) -> Tuple[float, float, float]:
    """Binarize Relative_Prob > 0.5 and compute the same-prompt-pairs kappa
    via the closed-form kernel."""
    finite = df[np.isfinite(df["Relative_Prob"])]
    if len(finite) == 0:
        return float("nan"), float("nan"), float("nan")
    decisions = (finite["Relative_Prob"] > 0.5).to_numpy(dtype=int)
    groups = pd.factorize(finite["Original Main Part"])[0]
    res = within_group_kappa(decisions, groups)
    return res["kappa"], res["observed_agreement"], res["expected_agreement"]


# ---------------------------------------------------------------------------
# Compliance audits (C25/C26, :1191-1675)
# ---------------------------------------------------------------------------

# Expected-token tables per canonical prompt (:1207-1248). Derived from the
# prompt assets: first tokens are the target tokens; accepted full responses
# cover the casing variants the reference allows. The reference additionally
# accepts two truncated variants for prompt 4 (:1236-1237) that cannot be
# derived from the instruction text.
EXTRA_FULL_RESPONSES: Dict[int, Dict[str, List[str]]] = {
    3: {
        "Monthly": ["Monthly Installment Payment"],
        "Payment": ["Payment Upon"],
    },
}


def expected_compliance_tokens(
    prompt: LegalPrompt, prompt_idx: Optional[int] = None
) -> Dict[str, object]:
    t1, t2 = prompt.target_tokens
    # The reference's expected-token table (:1207-1248) lists first tokens
    # in the RESPONSE FORMAT's presentation order ('First, Ultimate'), not
    # the readout's token_1/token_2 order ('Ultimate, First') — order the
    # report identically (membership semantics are unaffected).
    order = (t1, t2)
    fmt = prompt.response_format
    pos = {t: fmt.find(f"'{t}") for t in order}
    if all(p >= 0 for p in pos.values()):
        order = tuple(sorted(order, key=lambda t: pos[t]))
    full: Dict[str, List[str]] = {}
    for token in (t1, t2):
        # Reconstruct the allowed answer phrases from the response format:
        # every quoted alternative in the instruction that starts with the
        # token, plus lower-cased tail variants.
        phrases = []
        fmt = prompt.response_format
        for part in fmt.split("'")[1::2]:  # quoted alternatives
            if part.startswith(token):
                phrases.append(part)
                if " " in part:
                    head, tail = part.split(" ", 1)
                    phrases.append(f"{head} {tail.lower()}")
        if prompt_idx is not None:
            phrases.extend(EXTRA_FULL_RESPONSES.get(prompt_idx, {}).get(token, []))
        full[token] = phrases or [token]
    return {"first_tokens": list(order), "full_responses": full}


def _load_payload(raw):
    """Parse a stored Log Probabilities value (json -> ast fallback,
    :1301-1322); None when unparseable."""
    if not isinstance(raw, str):
        return raw
    try:
        return json.loads(raw)
    except (json.JSONDecodeError, ValueError):
        try:
            return ast.literal_eval(raw)
        except (ValueError, SyntaxError):
            return None


def parse_logprob_content(raw) -> Optional[Tuple[str, str]]:
    """(first token, full response) from a stored Log Probabilities value
    (json -> ast fallback, :1301-1322)."""
    obj = _load_payload(raw)
    if not isinstance(obj, dict) or "content" not in obj or not obj["content"]:
        return None
    tokens = [t.get("token", "") for t in obj["content"]]
    return tokens[0], "".join(tokens).strip()


def _is_local_logprob_map(obj) -> bool:
    """True for the LOCAL sweep's 'Log Probabilities' payload (already
    parsed by _load_payload): a flat {token_id: logprob} top-20 map whose
    keys are all integer strings (data/schemas.py D6 writer). The
    reference's API payloads are content-style dicts, and reference-style
    word-keyed maps stay False — so reference data keeps the executed
    reference's skip semantics (pinned by test_reference_differential)
    while locally produced workbooks get classified instead of silently
    skipped."""
    return (isinstance(obj, dict) and bool(obj)
            and "content" not in obj
            and all(isinstance(k, str) and k.lstrip("-").isdigit()
                    for k in obj))


def check_output_compliance(
    df: pd.DataFrame,
    prompts: Sequence[LegalPrompt],
) -> pd.DataFrame:
    """First-token and conditional full-response compliance per prompt
    (:1191-1451)."""
    results = []
    for idx, original_prompt in enumerate(df["Original Main Part"].unique()):
        if idx >= len(prompts):
            break
        expected = expected_compliance_tokens(prompts[idx], idx)
        pdata = df[df["Original Main Part"] == original_prompt]
        valid = pdata[np.isfinite(pdata["Relative_Prob"])]
        total = len(valid)
        if total == 0:
            continue

        first_ok = first_bad = sub_ok = sub_bad = 0
        responses = (valid["Model Response"]
                     if "Model Response" in valid.columns
                     else pd.Series([None] * total, index=valid.index))
        for raw, resp in zip(valid["Log Probabilities"], responses):
            payload = _load_payload(raw)
            parsed = parse_logprob_content(payload)
            if parsed is None:
                # LOCAL-format rows (top-20 id map) carry the decoded text
                # in 'Model Response': classify from it — first word plays
                # the reference's whole-word first token. API/reference
                # rows with unparseable payloads keep the reference's skip
                # behavior (:1313-1326).
                if (_is_local_logprob_map(payload) and isinstance(resp, str)
                        and resp.strip()):
                    full_response = resp.strip()
                    first_token = full_response.split()[0]
                else:
                    continue
            else:
                first_token, full_response = parsed

            # Longest-matching expected token wins, so a target that is a
            # string prefix of another (none today, but format wording can
            # change) cannot steal the other's bucket by iteration order
            # (ADVICE r4).
            matched = None
            for exp in expected["first_tokens"]:
                if first_token == exp or first_token.startswith(exp):
                    if matched is None or len(exp) > len(matched):
                        matched = exp
            if matched is None:
                first_bad += 1
                continue
            first_ok += 1

            norm_resp = full_response.replace(" ", "")
            is_full = False
            for exp_full in expected["full_responses"].get(matched, []):
                norm_exp = exp_full.replace(" ", "")
                if (
                    full_response == exp_full
                    or norm_resp == norm_exp
                    or norm_resp.startswith(norm_exp)
                ):
                    is_full = True
                    break
            if is_full:
                sub_ok += 1
            else:
                sub_bad += 1

        row: Dict[str, object] = {
            "Prompt": idx + 1,
            "Expected_First_Tokens": ", ".join(expected["first_tokens"]),
            "Total_Samples": total,
            "First_Token_Compliant": first_ok,
            "First_Token_Non_Compliant": first_bad,
            "First_Token_Compliance_Rate": first_ok / total * 100,
            "First_Token_Non_Compliance_Rate": first_bad / total * 100,
        }
        if first_ok > 0:
            row.update(
                {
                    "Conditional_Subsequent_Compliant": sub_ok,
                    "Conditional_Subsequent_Non_Compliant": sub_bad,
                    "Conditional_Subsequent_Compliance_Rate": sub_ok / first_ok * 100,
                    "Conditional_Subsequent_Non_Compliance_Rate": sub_bad
                    / first_ok
                    * 100,
                }
            )
        results.append(row)
    return pd.DataFrame(results)


def check_confidence_compliance(
    df: pd.DataFrame, prompts: Sequence[LegalPrompt]
) -> pd.DataFrame:
    """Integer-in-[0,100] confidence compliance per prompt (:1501-1675)."""
    if "Model Confidence Response" not in df.columns:
        return pd.DataFrame()
    results = []
    for idx, original_prompt in enumerate(df["Original Main Part"].unique()):
        if idx >= len(prompts):
            break
        pdata = df[df["Original Main Part"] == original_prompt]
        valid = pdata[pdata["Model Confidence Response"].notna()]
        total = len(valid)
        if total == 0:
            continue

        compliant = 0
        kinds = {"float": 0, "text": 0, "out_of_range": 0, "other": 0}
        for raw in valid["Model Confidence Response"]:
            s = str(raw).strip()
            try:
                v = int(s)
                if 0 <= v <= 100:
                    compliant += 1
                else:
                    kinds["out_of_range"] += 1
            except ValueError:
                try:
                    float(s)
                    kinds["float"] += 1
                except ValueError:
                    if any(c.isalpha() for c in s):
                        kinds["text"] += 1
                    else:
                        kinds["other"] += 1
        non_compliant = total - compliant
        results.append(
            {
                "Prompt": idx + 1,
                "Total_Confidence_Samples": total,
                "Confidence_Compliant": compliant,
                "Confidence_Non_Compliant": non_compliant,
                "Confidence_Compliance_Rate": compliant / total * 100,
                "Confidence_Non_Compliance_Rate": non_compliant / total * 100,
                "Float_Errors": kinds["float"],
                "Text_Errors": kinds["text"],
                "Out_Of_Range_Errors": kinds["out_of_range"],
                "Other_Errors": kinds["other"],
            }
        )
    return pd.DataFrame(results)


def assert_compliance(
    compliance_df: pd.DataFrame,
    min_first_token_rate: float = 50.0,
) -> None:
    """Turn the compliance report into a pipeline assertion (SURVEY.md §4:
    'compliance checks become assertions, not just reports')."""
    if compliance_df.empty:
        return
    overall = (
        compliance_df["First_Token_Compliant"].sum()
        / compliance_df["Total_Samples"].sum()
        * 100
    )
    if overall < min_first_token_rate:
        raise AssertionError(
            f"First-token compliance {overall:.1f}% below the "
            f"{min_first_token_rate:.1f}% gate — measurement likely invalid "
            "(wrong target tokens or prompt formatting)."
        )


# ---------------------------------------------------------------------------
# Per-model orchestration (:1719-1960)
# ---------------------------------------------------------------------------


def analyze_model(
    df: pd.DataFrame,
    model_name: str,
    output_dir: Path,
    prompts: Sequence[LegalPrompt] = LEGAL_PROMPTS,
    key: Optional[jax.Array] = None,
    n_simulations: int = 100_000,
    make_figures: bool = True,
) -> Dict[str, object]:
    """Full single-model analysis; writes every reference artifact into
    `output_dir` and returns the result frames."""
    output_dir = Path(output_dir)
    output_dir.mkdir(parents=True, exist_ok=True)
    figures_dir = output_dir / "figures"
    key = key if key is not None else jax.random.PRNGKey(42)

    if len(df) < MIN_ROWS_FOR_ANALYSIS:
        log.warning(
            "Only %d rows for %s; skipping detailed analysis", len(df), model_name
        )
        summary = pd.DataFrame(
            [
                {
                    "Model": model_name,
                    "Total Rows": len(df),
                    "Status": "Insufficient data for analysis",
                }
            ]
        )
        summary.to_csv(output_dir / "summary_statistics.csv", index=False)
        return {"summary": summary, "status": "insufficient_data"}

    df = add_relative_prob(df)
    non_finite = int((~np.isfinite(df["Relative_Prob"])).sum())
    if non_finite:
        log.warning(
            "%d non-finite relative probabilities for %s", non_finite, model_name
        )

    unique_prompts = df["Original Main Part"].unique()
    summary_rows, normality_rows, truncated_rows, tables = [], [], [], []
    rng = np.random.default_rng(42)

    for idx, original_prompt in enumerate(unique_prompts):
        pdata = df[df["Original Main Part"] == original_prompt]
        token_options = (
            prompts[idx].target_tokens if idx < len(prompts) else ("A", "B")
        )

        if make_figures:
            figures.probability_histogram(pdata, idx, token_options, figures_dir)
            figures.confidence_histogram(pdata, idx, token_options, figures_dir)

        tables.append(
            perturbation_latex_table(
                pdata, idx,
                prompts[idx].main if idx < len(prompts) else original_prompt,
                token_options, rng,
            )
        )
        summary_rows.append(prompt_summary_stats(pdata, idx, token_options))

        rp = pdata["Relative_Prob"].to_numpy(dtype=float)
        nres = normality_tests(rp, prompt_idx=idx)
        nres["Column"] = "Relative_Prob"
        normality_rows.append(nres)

        if make_figures:
            key, sub = jax.random.split(key)
            figures.qq_plot(pdata, "Relative_Prob", idx, token_options,
                            figures_dir, sub)

        key, sub = jax.random.split(key)
        tres, sample = truncated_normal_mc_fit(
            rp, sub, n_simulations=n_simulations, prompt_idx=idx,
            column_name="Relative_Prob",
        )
        truncated_rows.append(tres)
        if make_figures and sample.size:
            figures.truncated_model_plot(
                pdata, "Relative_Prob", idx, token_options, sample,
                figures_dir, tres["KS Statistic"],
            )

        has_conf = (
            "Weighted Confidence" in pdata.columns
            and not pdata["Weighted Confidence"].isna().all()
        )
        if has_conf:
            conf_data = pdata.dropna(subset=["Weighted Confidence"])
            conf = conf_data["Weighted Confidence"].to_numpy(dtype=float)
            cres = normality_tests(conf, prompt_idx=idx)
            cres["Column"] = "Weighted_Confidence"
            normality_rows.append(cres)
            if make_figures:
                key, sub = jax.random.split(key)
                figures.qq_plot(conf_data, "Weighted Confidence", idx,
                                token_options, figures_dir, sub)

            # Rescale 0-100 confidence to [0,1] for the truncated fit, then
            # report on the original scale (:1880-1900).
            scale = 100.0 if conf.max() > 1 else 1.0
            key, sub = jax.random.split(key)
            ctres, csample = truncated_normal_mc_fit(
                conf / scale, sub, n_simulations=n_simulations,
                prompt_idx=idx, column_name="Weighted Confidence",
            )
            if csample.size:
                csample = csample * scale
                for field in (
                    "Underlying Normal Mean", "Underlying Normal Std Dev",
                    "Observed Mean", "Observed Std Dev", "Simulated Mean",
                    "Simulated Std Dev", "Interior Mean", "Interior Std Dev",
                ):
                    if field in ctres and np.isfinite(ctres[field]):
                        ctres[field] *= scale
            truncated_rows.append(ctres)
            if make_figures and csample.size:
                figures.truncated_model_plot(
                    conf_data, "Weighted Confidence", idx, token_options,
                    csample, figures_dir, ctres["KS Statistic"],
                )

    # LaTeX artifacts.
    (output_dir / "prompt_perturbation_tables.tex").write_text(
        "\n".join(tables), encoding="utf-8"
    )
    (output_dir / "prompt_perturbation_standalone.tex").write_text(
        standalone_latex_document(tables), encoding="utf-8"
    )

    summary_df = pd.DataFrame(summary_rows)
    summary_df.to_csv(output_dir / "summary_statistics.csv", index=False)

    if make_figures:
        figures.combined_visualization(df, prompts, output_dir, rng)
        figures.combined_confidence_visualization(df, prompts, output_dir, rng)

    normality_df = pd.DataFrame(normality_rows)
    normality_df.to_csv(output_dir / "normality_test_results.csv", index=False)
    truncated_df = pd.DataFrame(truncated_rows)
    truncated_df.to_csv(
        output_dir / "truncated_normal_test_results.csv", index=False
    )

    kappa, observed, expected = perturbation_kappa(df)
    kappa_df = pd.DataFrame(
        [
            {
                "Model": model_name,
                "Cohen's Kappa": kappa,
                "Observed Agreement": observed,
                "Expected Agreement": expected,
            }
        ]
    )
    kappa_df.to_csv(output_dir / "cohens_kappa_results.csv", index=False)
    log.info(
        "%s: kappa=%.4f (%s)", model_name, kappa, interpret_kappa(kappa)
    )

    compliance_df = check_output_compliance(df, prompts)
    if len(compliance_df):
        compliance_df.to_csv(
            output_dir / "output_compliance_results.csv", index=False
        )
        (output_dir / "compliance_summary.tex").write_text(
            compliance_latex_table(compliance_df), encoding="utf-8"
        )
    confidence_df = check_confidence_compliance(df, prompts)
    if len(confidence_df):
        confidence_df.to_csv(
            output_dir / "confidence_compliance_results.csv", index=False
        )
        (output_dir / "confidence_compliance_summary.tex").write_text(
            confidence_compliance_latex_table(confidence_df), encoding="utf-8"
        )

    return {
        "summary": summary_df,
        "normality": normality_df,
        "truncated": truncated_df,
        "kappa": kappa_df,
        "compliance": compliance_df,
        "confidence_compliance": confidence_df,
        "status": "ok",
    }


def analyze_all_models(
    results_path: Path,
    output_root: Path,
    prompts: Sequence[LegalPrompt] = LEGAL_PROMPTS,
    seed: int = 42,
    n_simulations: int = 100_000,
    make_figures: bool = True,
) -> Dict[str, Dict[str, object]]:
    """The reference's __main__ loop (:1963-2026): one output directory per
    model (dots/dashes replaced), no hard-coded personal paths."""
    df = read_results_frame(Path(results_path))
    key = jax.random.PRNGKey(seed)
    out: Dict[str, Dict[str, object]] = {}
    if "Model" in df.columns:
        for model_name in df["Model"].unique():
            key, sub = jax.random.split(key)
            safe = model_name.replace(".", "_").replace("-", "_")
            out[model_name] = analyze_model(
                df[df["Model"] == model_name].copy(), model_name,
                Path(output_root) / safe, prompts, sub,
                n_simulations=n_simulations, make_figures=make_figures,
            )
    else:
        out["Single Model"] = analyze_model(
            df, "Single Model", Path(output_root), prompts, key,
            n_simulations=n_simulations, make_figures=make_figures,
        )
    return out


def main() -> None:
    parser = argparse.ArgumentParser(
        description="Per-model perturbation analysis (C20-C27 parity)."
    )
    parser.add_argument("--results", type=Path, required=True,
                        help="D6 results workbook (xlsx or csv)")
    parser.add_argument("--out", type=Path, default=Path("results/perturbation"))
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--n-simulations", type=int, default=100_000)
    parser.add_argument("--no-figures", action="store_true")
    args = parser.parse_args()
    analyze_all_models(
        args.results, args.out, seed=args.seed,
        n_simulations=args.n_simulations, make_figures=not args.no_figures,
    )


if __name__ == "__main__":
    main()
