"""Two-source Cohen's kappa combiner (C29).

Parity target: analysis/calculate_cohens_kappa.py:20-675 — per-prompt
inter-model agreement from the D2 CSV, per-prompt perturbation "self-kappa"
from the D6 workbook (1000 bootstrap pairs of binarized decisions), keyword
matching of the 5 legal prompts across the two datasets, min-of-normal-draws
combination with bootstrap CI, interpretation bands, bar/scatter/distribution
figures, LaTeX table, and the four CSV artifacts.

Defect fixed, not replicated (SURVEY.md §7): the reference computes
per-prompt model agreement with ``cohen_kappa_score([x], [y])`` on
single-element lists (:124-127), a degenerate statistic (NaN for every
disagreeing pair). We report the pairwise agreement fraction that loop
actually measures (stats.kappa.per_prompt_mean_pairwise_kappa) and use it as
the model-variation agreement input.

All bootstrap loops run as vmapped kernels (stats.kappa.self_kappa_bootstrap,
stats.kappa.combined_kappa).
"""

from __future__ import annotations

import argparse
from pathlib import Path
from typing import Dict, List, Sequence, Tuple

import jax
import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402
import seaborn as sns  # noqa: E402

from ..data.schemas import read_results_frame  # noqa: E402
from ..stats.kappa import (  # noqa: E402
    combined_kappa,
    interpret_kappa,
    per_prompt_mean_pairwise_kappa,
    self_kappa_bootstrap,
)
from ..utils.logging import get_logger  # noqa: E402

log = get_logger(__name__)

# Keyword table matching the 5 legal prompts across datasets (:230-241).
LEGAL_PROMPT_KEYWORDS: Dict[str, List[str]] = {
    "Insurance Policy Water Damage Exclusion": [
        "water damage", "levee", "flood", "insurance policy",
    ],
    "Prenuptial Agreement Petition Filing Date": [
        "prenuptial", "petition", "dissolution", "marriage", "filing",
    ],
    "Contract Term Affiliate Interpretation": [
        "contract", "affiliate", "royalty", "1961", "company",
    ],
    "Construction Payment Terms Interpretation": [
        "contractor", "usual manner", "payment", "foundry", "construction",
    ],
    "Insurance Policy Burglary Coverage": [
        "insurance", "felonious", "burglary", "theft", "visible marks",
    ],
}


def prepare_model_data(df: pd.DataFrame) -> pd.DataFrame:
    """Per-prompt inter-model agreement from the D2 CSV (:76-145)."""
    df = df.copy()
    df["binary_decision"] = (df["relative_prob"] > 0.5).astype(int)
    rows = []
    for prompt, group in df.groupby("prompt"):
        if group["model"].nunique() < 2:
            continue
        stats = per_prompt_mean_pairwise_kappa(
            group["binary_decision"].to_numpy()
        )
        rows.append(
            {
                "prompt": prompt,
                "avg_pairwise_kappa": stats["avg_pairwise_agreement"],
                "n_models": stats["n_models"],
                "agree_percent": stats["agree_percent"],
            }
        )
    return pd.DataFrame(rows)


def prepare_perturbation_data(
    df: pd.DataFrame, key: jax.Array, n_bootstrap: int = 1000
) -> pd.DataFrame:
    """Per-prompt perturbation self-kappa from the D6 workbook (:147-218)."""
    df = df.copy()
    if "Total_Prob" not in df.columns:
        df["Total_Prob"] = df["Token_1_Prob"] + df["Token_2_Prob"]
    if "Relative_Prob" not in df.columns:
        with np.errstate(invalid="ignore", divide="ignore"):
            df["Relative_Prob"] = df["Token_1_Prob"] / df["Total_Prob"]
    df["binary_decision"] = (df["Relative_Prob"] > 0.5).astype(int)

    rows = []
    for prompt, group in df.groupby("Original Main Part"):
        decisions = group["binary_decision"].to_numpy()
        mean_dec = float(decisions.mean())
        key, sub = jax.random.split(key)
        boot = self_kappa_bootstrap(decisions, sub, n_boot=n_bootstrap)
        rows.append(
            {
                "prompt": prompt,
                "n_variations": int(decisions.size),
                "agree_percent": mean_dec if mean_dec > 0.5 else 1 - mean_dec,
                **boot,
            }
        )
    return pd.DataFrame(rows)


def _keyword_match(
    df: pd.DataFrame, columns: Sequence[str]
) -> Dict[str, pd.Series]:
    """title -> first row whose prompt text contains any keyword (:247-311)."""
    out: Dict[str, pd.Series] = {}
    for title, keywords in LEGAL_PROMPT_KEYWORDS.items():
        for col in columns:
            if title in out or col not in df.columns:
                continue
            for keyword in keywords:
                matches = df[
                    df[col].str.contains(keyword, case=False, regex=False, na=False)
                ]
                if not matches.empty:
                    out[title] = matches.iloc[0]
                    break
            if title in out:
                break
    return out


def match_legal_prompts(
    model_kappa_df: pd.DataFrame, pert_kappa_df: pd.DataFrame
) -> Tuple[pd.DataFrame, pd.DataFrame]:
    """Keyword-match the 5 legal prompts in both prepared frames (:220-326).

    The model-comparison CSV holds the 50 word-meaning questions, so in the
    canonical data it matches few/none of the legal keywords — preserved
    behavior; the combiner then runs on whatever titles match in both.
    """
    model_rows = []
    for title, row in _keyword_match(model_kappa_df, ["prompt"]).items():
        model_rows.append(
            {
                "title": title,
                "prompt": row["prompt"],
                "avg_pairwise_kappa": row["avg_pairwise_kappa"],
                "n_models": row["n_models"],
                "agree_percent": row["agree_percent"],
                "source": "model_comparison",
            }
        )
    pert_rows = []
    for title, row in _keyword_match(pert_kappa_df, ["prompt"]).items():
        pert_rows.append(
            {
                "title": title,
                "prompt": row["prompt"],
                "self_kappa": row["self_kappa"],
                "n_variations": row["n_variations"],
                "agree_percent": row["agree_percent"],
                "source": "perturbation",
            }
        )
    return pd.DataFrame(model_rows), pd.DataFrame(pert_rows)


def combine_kappas(
    model_legal_df: pd.DataFrame,
    pert_legal_df: pd.DataFrame,
    key: jax.Array,
    n_bootstrap: int = 1000,
) -> Dict[str, Dict[str, object]]:
    """Min-of-draws combination per matched title (:566-600)."""
    out: Dict[str, Dict[str, object]] = {}
    for title in model_legal_df["title"].unique():
        mdata = model_legal_df[model_legal_df["title"] == title]
        pdata = pert_legal_df[pert_legal_df["title"] == title]
        if mdata.empty or pdata.empty:
            continue
        m_kappa = float(mdata["avg_pairwise_kappa"].mean())
        m_std = float(mdata["avg_pairwise_kappa"].std()) if len(mdata) > 1 else 0.1
        p_kappa = float(pdata["self_kappa"].mean())
        p_std = float(pdata["self_kappa"].std()) if len(pdata) > 1 else 0.1
        key, sub = jax.random.split(key)
        combined = combined_kappa(
            m_kappa, p_kappa, sub, m_std, p_std, n_boot=n_bootstrap
        )
        out[title] = {
            "model_kappa": m_kappa,
            "model_kappa_std": m_std,
            "model_interpretation": interpret_kappa(m_kappa),
            "perturbation_kappa": p_kappa,
            "perturbation_kappa_std": p_std,
            "perturbation_interpretation": interpret_kappa(p_kappa),
            "combined": combined,
            "combined_interpretation": interpret_kappa(combined["mean_kappa"]),
        }
    return out


def combined_results_frame(
    combined: Dict[str, Dict[str, object]]
) -> pd.DataFrame:
    rows = []
    for title, res in combined.items():
        rows.append(
            {
                "Prompt": title,
                "Model Kappa": res["model_kappa"],
                "Model Kappa Std": res["model_kappa_std"],
                "Model Interpretation": res["model_interpretation"],
                "Perturbation Kappa": res["perturbation_kappa"],
                "Perturbation Kappa Std": res["perturbation_kappa_std"],
                "Perturbation Interpretation": res["perturbation_interpretation"],
                "Combined Mean Kappa": res["combined"]["mean_kappa"],
                "Combined Median Kappa": res["combined"]["median_kappa"],
                "Combined Lower CI": res["combined"]["lower_ci"],
                "Combined Upper CI": res["combined"]["upper_ci"],
                "Combined Interpretation": res["combined_interpretation"],
            }
        )
    return pd.DataFrame(rows)


def kappa_latex_table(combined_df: pd.DataFrame) -> str:
    """LaTeX summary (:630-655)."""
    lines = [
        "\\begin{table}[htbp]",
        "\\centering",
        "\\caption{Cohen's Kappa Analysis of Model Variation vs. Prompt "
        "Perturbation}",
        "\\label{tab:kappa_analysis}",
        "\\begin{tabular}{lccccc}",
        "\\hline",
        "Prompt & Model $\\kappa$ & Perturbation $\\kappa$ & Combined "
        "$\\kappa$ & 95\\% CI & Interpretation \\\\ ",
        "\\hline",
    ]
    for _, row in combined_df.iterrows():
        short = " ".join(row["Prompt"].split()[-2:])
        ci = f"[{row['Combined Lower CI']:.3f}, {row['Combined Upper CI']:.3f}]"
        lines.append(
            f"{short} & {row['Model Kappa']:.3f} & "
            f"{row['Perturbation Kappa']:.3f} & "
            f"{row['Combined Mean Kappa']:.3f} & {ci} & "
            f"{row['Combined Interpretation']} \\\\ "
        )
    lines += ["\\hline", "\\end{tabular}", "\\end{table}", ""]
    return "\n".join(lines)


def _plots(
    combined: Dict[str, Dict[str, object]],
    out_dir: Path,
    key: jax.Array,
    n_bootstrap: int = 1000,
) -> None:
    """Bar + scatter + per-title distribution figures (:396-513)."""
    titles = list(combined.keys())
    if not titles:
        return
    model_k = [combined[t]["model_kappa"] for t in titles]
    pert_k = [combined[t]["perturbation_kappa"] for t in titles]
    comb_k = [combined[t]["combined"]["mean_kappa"] for t in titles]

    x = np.arange(len(titles))
    width = 0.25
    fig, ax = plt.subplots(figsize=(14, 8))
    ax.bar(x - width, model_k, width, label="Model Variation Kappa")
    ax.bar(x, pert_k, width, label="Perturbation Kappa")
    ax.bar(x + width, comb_k, width, label="Combined Kappa")
    ax.set_ylabel("Cohen's Kappa Value")
    ax.set_title("Comparison of Kappa Values by Source of Variation")
    ax.set_xticks(x)
    ax.set_xticklabels(
        [" ".join(t.split()[-2:]) for t in titles], rotation=45, ha="right"
    )
    for level in (0, 0.2, 0.4, 0.6, 0.8):
        ax.axhline(level, color="gray", linestyle="--", alpha=0.5)
    ax.legend()
    fig.tight_layout()
    fig.savefig(out_dir / "kappa_comparison_bar.png", dpi=150,
                bbox_inches="tight")
    plt.close(fig)

    # Per-title bootstrap distribution (regenerate draws for the histogram —
    # combined_kappa returns summary stats, the figure needs the samples).
    for title in titles:
        res = combined[title]
        key, k1, k2 = jax.random.split(key, 3)
        m = res["model_kappa"] + res["model_kappa_std"] * np.asarray(
            jax.random.normal(k1, (n_bootstrap,))
        )
        p = res["perturbation_kappa"] + res["perturbation_kappa_std"] * np.asarray(
            jax.random.normal(k2, (n_bootstrap,))
        )
        samples = np.minimum(m, p)
        fig, ax = plt.subplots(figsize=(10, 6))
        sns.histplot(samples, kde=True, ax=ax)
        ax.axvline(res["combined"]["mean_kappa"], color="r", linestyle="--",
                   label=f"Mean: {res['combined']['mean_kappa']:.3f}")
        ax.axvline(res["combined"]["lower_ci"], color="g", linestyle=":",
                   label=f"2.5th percentile: {res['combined']['lower_ci']:.3f}")
        ax.axvline(res["combined"]["upper_ci"], color="g", linestyle=":",
                   label=f"97.5th percentile: {res['combined']['upper_ci']:.3f}")
        ax.set_xlabel("Cohen's Kappa Value")
        ax.set_ylabel("Frequency")
        ax.set_title(f"Bootstrap Distribution of Combined Kappa: {title}")
        ax.legend()
        fig.tight_layout()
        short = "_".join(title.split()[-2:]).lower()
        fig.savefig(out_dir / f"kappa_distribution_{short}.png", dpi=150,
                    bbox_inches="tight")
        plt.close(fig)

    fig, ax = plt.subplots(figsize=(10, 8))
    ax.scatter(model_k, pert_k, s=100, alpha=0.7)
    lo = min(min(model_k), min(pert_k))
    hi = max(max(model_k), max(pert_k))
    ax.plot([lo, hi], [lo, hi], "k--", alpha=0.5)
    for i, t in enumerate(titles):
        ax.annotate(" ".join(t.split()[-2:]), (model_k[i], pert_k[i]),
                    fontsize=12, xytext=(5, 5), textcoords="offset points")
    ax.set_xlabel("Model Variation Kappa")
    ax.set_ylabel("Perturbation Kappa")
    ax.set_title("Model Variation vs. Prompt Perturbation Kappa")
    ax.grid(True, alpha=0.3)
    for val in (0.2, 0.4, 0.6, 0.8):
        ax.axhline(val, color="gray", linestyle="--", alpha=0.2)
        ax.axvline(val, color="gray", linestyle="--", alpha=0.2)
    fig.tight_layout()
    fig.savefig(out_dir / "kappa_scatter.png", dpi=150, bbox_inches="tight")
    plt.close(fig)


def run_kappa_analysis(
    instruct_csv: Path,
    perturbation_results: Path,
    out_dir: Path,
    seed: int = 42,
    n_bootstrap: int = 1000,
    make_figures: bool = True,
) -> Dict[str, object]:
    """Full C29 pipeline; artifact names match the reference (:560-658)."""
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    key = jax.random.PRNGKey(seed)
    k1, k2, k3 = jax.random.split(key, 3)

    model_df = pd.read_csv(instruct_csv)
    pert_df = read_results_frame(Path(perturbation_results))

    model_kappa_df = prepare_model_data(model_df)
    pert_kappa_df = prepare_perturbation_data(pert_df, k1, n_bootstrap)
    model_kappa_df.to_csv(out_dir / "model_kappa_metrics.csv", index=False)
    pert_kappa_df.to_csv(out_dir / "perturbation_kappa_metrics.csv", index=False)

    model_legal_df, pert_legal_df = match_legal_prompts(
        model_kappa_df, pert_kappa_df
    )
    model_legal_df.to_csv(out_dir / "model_legal_kappas.csv", index=False)
    pert_legal_df.to_csv(out_dir / "perturbation_legal_kappas.csv", index=False)

    combined: Dict[str, Dict[str, object]] = {}
    if not model_legal_df.empty and not pert_legal_df.empty:
        combined = combine_kappas(model_legal_df, pert_legal_df, k2, n_bootstrap)
    else:
        log.info(
            "No matched legal prompts across datasets (%d model, %d "
            "perturbation) — combined kappa skipped",
            len(model_legal_df), len(pert_legal_df),
        )

    combined_df = combined_results_frame(combined)
    combined_df.to_csv(out_dir / "combined_kappa_results.csv", index=False)
    (out_dir / "kappa_analysis_table.tex").write_text(
        kappa_latex_table(combined_df)
    )
    if make_figures and combined:
        _plots(combined, out_dir, k3, n_bootstrap)

    return {
        "model_kappa": model_kappa_df,
        "perturbation_kappa": pert_kappa_df,
        "combined": combined,
        "combined_frame": combined_df,
    }


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--instruct", type=Path, required=True,
                        help="D2 instruct_model_comparison_results.csv")
    parser.add_argument("--perturbation", type=Path, required=True,
                        help="D6 perturbation results workbook")
    parser.add_argument("--out", type=Path, default=Path("results/kappa"))
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--no-figures", action="store_true")
    args = parser.parse_args()
    run_kappa_analysis(
        args.instruct, args.perturbation, args.out, seed=args.seed,
        make_figures=not args.no_figures,
    )


if __name__ == "__main__":
    main()
