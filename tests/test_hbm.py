"""Unified HBM governor tests (engine/hbm.py + the wiring around it).

Pins the contracts the memory-governance tentpole rides on:

- the ledger: register/update/unregister, pressure math, admission
  counters, and the gauges landing in the MetricsRegistry snapshot
  next to device_memory_stats;
- the degradation ladder: rungs engage only under SUSTAINED pressure,
  release with hysteresis in reverse order, and the flag rungs map to
  allows()/batch_cap()/should_shed() exactly;
- the seeded ``hbm_squeeze`` fault kind: budget shrinks at the
  scheduled tick, auto-restores, and the ladder walks down AND back up
  (rung_downs == rung_ups after the squeeze clears);
- OOM routing: a device OOM in the sweep path reclaims and retries
  once (run completes, rows intact), a persistent OOM raises
  HbmExhausted with the ledger arithmetic; a serve-path OOM never
  advances the circuit breaker (capacity != device death) and
  quarantines only the irreducible dispatch;
- fleet boot validation: a weight-cache budget smaller than the
  largest configured model fails construction with the sizing
  arithmetic instead of surfacing as WeightCacheOOM mid-sweep;
- WeightCache refcounts under concurrency: threaded acquire/release/
  evict stress holding the never-negative invariant and pinned/
  in-flight unevictability under contention;
- router placement: the replica pressure gauge penalizes squeezed
  replicas.
"""

import threading

import pytest

import jax

from lir_tpu import faults
from lir_tpu.backends.fake import FakeTokenizer
from lir_tpu.config import (GovernorConfig, RetryConfig, RouterConfig,
                            RuntimeConfig, ServeConfig)
from lir_tpu.engine import hbm
from lir_tpu.engine.fleet import ModelFleet
from lir_tpu.engine.runner import ScoringEngine
from lir_tpu.models import decoder, weights
from lir_tpu.models.registry import ModelConfig
from lir_tpu.serve import ScoringServer, ServeRequest
from lir_tpu.utils.profiling import MemStats

MB = 1 << 20


def _gov(budget_mb=100, engage=0.9, hyst=0.15, sustain=1, enabled=True):
    return hbm.HbmGovernor(
        GovernorConfig(enabled=enabled, engage_pressure=engage,
                       hysteresis=hyst, sustain_ticks=sustain),
        budget_bytes=budget_mb * MB)


def _tiny_cfg(name="hbm-test"):
    return ModelConfig(name=name, vocab_size=FakeTokenizer.VOCAB,
                       hidden_size=32, n_layers=1, n_heads=2,
                       intermediate_size=64, max_seq_len=256)


def _tiny_engine(name="hbm-test", seed=3, batch_size=4, **rt_kw):
    cfg = _tiny_cfg(name)
    return ScoringEngine(
        decoder.init_params(cfg, jax.random.PRNGKey(seed)), cfg,
        FakeTokenizer(),
        RuntimeConfig(batch_size=batch_size, max_seq_len=256, **rt_kw))


# ---------------------------------------------------------------------------
# ledger + pressure
# ---------------------------------------------------------------------------


def test_ledger_register_update_unregister():
    g = _gov(budget_mb=100)
    g.register("a", 30 * MB)
    g.register("b", 20 * MB)
    assert g.ledger_bytes == 50 * MB
    assert g.pressure() == pytest.approx(0.5)
    g.update("a", 10 * MB)          # replace, not accumulate
    assert g.ledger_bytes == 30 * MB
    g.unregister("b")
    assert g.ledger() == {"a": 10 * MB}
    assert g.headroom() == 90 * MB


def test_admit_counts_and_respects_budget():
    g = _gov(budget_mb=100)
    g.register("a", 60 * MB)
    assert g.admit("b", 30 * MB)            # 90 <= 100
    assert not g.admit("b", 50 * MB)        # 110 > 100
    assert g.admit("a", 90 * MB)            # replacing a: 90 <= 100
    assert g.stats.admits == 2
    assert g.stats.denials == 1


def test_unbounded_governor_is_inert():
    g = hbm.HbmGovernor(GovernorConfig(), budget_bytes=None)
    g.register("a", 10 ** 12)
    assert g.pressure() == 0.0
    assert g.headroom() is None
    for _ in range(20):
        g.tick()
    assert g.level == 0                     # nothing to press against


# ---------------------------------------------------------------------------
# the ladder
# ---------------------------------------------------------------------------


def test_ladder_requires_sustained_pressure():
    g = _gov(budget_mb=100, sustain=3)
    g.register("a", 95 * MB)                # pressure 0.95 > 0.9
    g.tick()
    g.tick()
    assert g.level == 0                     # 2 ticks < sustain 3
    g.tick()
    assert g.level == 1                     # third consecutive engages
    assert g.stats.rung_downs == {"evict_weights": 1}


def test_ladder_walks_down_in_order_and_back_up_in_reverse():
    g = _gov(budget_mb=100, sustain=1)
    g.register("a", 95 * MB)
    for _ in range(len(hbm.RUNGS)):
        g.tick()
    assert g.level == len(hbm.RUNGS)
    assert g.engaged_rungs() == list(hbm.RUNGS)
    assert not g.allows("piggyback")
    assert not g.allows("spec")
    assert g.batch_cap(32) == 16
    assert g.should_shed()
    g.update("a", 10 * MB)                  # pressure clears
    for _ in range(len(hbm.RUNGS)):
        g.tick()
    assert g.level == 0
    assert g.allows("piggyback") and g.allows("spec")
    assert g.batch_cap(32) == 32
    assert not g.should_shed()
    # every rung shows BOTH transitions — full reversibility
    for rung in hbm.RUNGS:
        assert g.stats.rung_downs.get(rung) == 1, rung
        assert g.stats.rung_ups.get(rung) == 1, rung


def test_hysteresis_band_is_quiet():
    g = _gov(budget_mb=100, engage=0.9, hyst=0.15, sustain=1)
    g.register("a", 95 * MB)
    g.tick()
    assert g.level == 1
    # 0.80 sits inside (0.75, 0.9): neither engages nor releases.
    g.update("a", 80 * MB)
    for _ in range(5):
        g.tick()
    assert g.level == 1
    g.update("a", 70 * MB)                  # 0.70 < 0.75 releases
    g.tick()
    assert g.level == 0


def test_rung_actions_fire_and_report_freed():
    g = _gov(budget_mb=100, sustain=1)
    calls = []
    g.set_action("evict_weights", engage=lambda: calls.append("w") or True)
    g.register("a", 95 * MB)
    g.tick()
    assert calls == ["w"]


# ---------------------------------------------------------------------------
# squeeze (the hbm_squeeze fault kind)
# ---------------------------------------------------------------------------


def test_squeeze_shrinks_and_auto_restores():
    g = _gov(budget_mb=100, sustain=1)
    g.register("a", 50 * MB)                # pressure 0.5 — calm
    g.squeeze(0.25, calls=4)                # budget -> 25 MB: pressure 2
    assert g.stats.squeezes == 1
    for _ in range(4):
        g.tick()
    assert g.level > 0                      # ladder walked down
    down_at_peak = dict(g.stats.rung_downs)
    for _ in range(len(hbm.RUNGS) + 2):
        g.tick()                            # squeeze expired: walk up
    assert g.level == 0
    assert g.budget_bytes == 100 * MB
    assert g.stats.rung_ups == down_at_peak  # fully reversible


def test_wrap_governor_fires_at_the_seeded_tick():
    g = _gov(budget_mb=100, sustain=1)
    g.register("a", 50 * MB)
    plan = faults.FaultPlan(seed=1, schedules={
        "hbm": faults.SiteSchedule.hbm_squeeze_at(2, frac=0.2, calls=3)})
    faults.wrap_governor(g, plan)
    g.tick()
    g.tick()
    assert g.stats.squeezes == 0            # calls 0 and 1: no squeeze
    g.tick()                                # call 2 fires
    assert g.stats.squeezes == 1
    assert plan.injected("hbm") == 1
    assert g.budget_bytes == 20 * MB


# ---------------------------------------------------------------------------
# OOM routing
# ---------------------------------------------------------------------------


def _oom():
    return RuntimeError("RESOURCE_EXHAUSTED: out of memory allocating")


def test_handle_oom_force_engages_reclaim_rungs():
    g = _gov(budget_mb=100, sustain=10)     # sustain high: ticks alone
    freed = []                              # would never engage
    g.set_action("evict_weights", engage=lambda: freed.append(1) or True)
    assert g.handle_oom("sweep") is True
    assert g.engaged_rungs() == list(hbm.RECLAIM_RUNGS)
    assert freed == [1]
    assert g.stats.oom_reclaims == 1
    assert g.stats.oom_events == {"sweep": 1}
    # a second OOM with everything already engaged frees nothing
    assert g.handle_oom("sweep") is False
    assert g.stats.oom_exhausted == 1


def test_sweep_oom_reclaims_and_retries_once():
    from lir_tpu.engine.sweep import _dispatch_with_recovery

    engine = _tiny_engine()
    engine.governor = _gov(budget_mb=100)
    engine.governor.set_action("evict_weights", engage=lambda: True)
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] == 1:
            raise _oom()
        return "scored"

    assert _dispatch_with_recovery(engine, flaky) == "scored"
    assert state["n"] == 2                  # exactly one retry
    assert engine.governor.stats.oom_reclaims == 1


def test_sweep_persistent_oom_raises_hbm_exhausted_with_arithmetic():
    from lir_tpu.engine.sweep import _dispatch_with_recovery

    engine = _tiny_engine()
    engine.governor = _gov(budget_mb=100)
    engine.governor.register("kv_pages:x", 40 * MB)
    engine.governor.set_action("evict_weights", engage=lambda: True)

    def always_oom():
        raise _oom()

    with pytest.raises(hbm.HbmExhausted) as ei:
        _dispatch_with_recovery(engine, always_oom)
    msg = str(ei.value)
    assert "ledger" in msg and "kv_pages:x" in msg and "budget" in msg


def test_sweep_oom_without_reclaim_reraises_raw():
    from lir_tpu.engine.sweep import _dispatch_with_recovery

    engine = _tiny_engine()
    engine.governor = hbm.HbmGovernor(GovernorConfig(enabled=False))

    def always_oom():
        raise _oom()

    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        _dispatch_with_recovery(engine, always_oom)


def _serve_cfg():
    return ServeConfig(
        queue_depth=64, classes=(("smoke", 600.0),),
        default_class="smoke", linger_s=0.0,
        max_consecutive_failures=2,
        retry=RetryConfig(max_retries=2, initial_delay=0.001,
                          max_delay=0.002, full_jitter=True,
                          max_elapsed=0.5))


def _request(i, rid=None):
    body = f"clause {i} covers wind damage under policy {i * 7}"
    return ServeRequest(
        binary_prompt=f"{body} Answer Yes or No .",
        confidence_prompt=f"{body} Give a number from 0 to 100 .",
        klass="smoke", request_id=rid or str(i))


def test_serve_oom_reclaim_retry_bypasses_breaker():
    engine = _tiny_engine()
    engine.governor = _gov(budget_mb=100)
    engine.governor.set_action("evict_weights", engage=lambda: True)
    server = ScoringServer(engine, "hbm-serve", _serve_cfg())
    real_score = server.batcher.score
    state = {"n": 0}

    def oom_once(bucket, rows):
        state["n"] += 1
        if state["n"] == 1:
            raise _oom()
        return real_score(bucket, rows)

    server.batcher.score = oom_once
    server.start()
    try:
        res = [server.submit(_request(i)).result(timeout=60)
               for i in range(2)]
    finally:
        server.stop()
    assert all(r.status == "ok" for r in res)
    assert state["n"] >= 2                   # reclaim retry ran
    assert engine.governor.stats.oom_reclaims == 1
    assert server.breaker.consecutive_failures == 0
    assert server.healthy


def test_serve_persistent_oom_quarantines_dispatch_not_breaker():
    engine = _tiny_engine()
    engine.governor = _gov(budget_mb=100)
    # nothing reclaimable: no evict action, flag rungs free no bytes
    server = ScoringServer(engine, "hbm-serve", _serve_cfg())
    state = {"n": 0}

    def always_oom(bucket, rows):
        state["n"] += 1
        raise _oom()

    real_score = server.batcher.score
    server.batcher.score = always_oom
    server.start()
    try:
        res = server.submit(_request(1)).result(timeout=60)
        assert res.status == "error"
        assert "ledger" in res.note          # the arithmetic, not a trace
        # capacity never advances the breaker — the server stays
        # healthy and serves the next request once memory "returns"
        assert server.breaker.consecutive_failures == 0
        assert server.healthy
        server.batcher.score = real_score
        ok = server.submit(_request(2)).result(timeout=60)
        assert ok.status == "ok"
    finally:
        server.stop()
    # the OOM skipped the generic retry loop: ONE attempt before the
    # governor's single reclaim-retry path took over
    assert state["n"] <= 2
    assert engine.governor.stats.oom_events.get("serve") == 1


def test_serve_shed_rung_resolves_shed():
    engine = _tiny_engine()
    engine.governor = _gov(budget_mb=100, sustain=1)
    engine.governor.register("big", 95 * MB)
    for _ in range(len(hbm.RUNGS)):
        engine.governor.tick()               # walk to the shed rung
    server = ScoringServer(engine, "hbm-serve", _serve_cfg())
    res = server.submit(_request(1)).result(timeout=5)
    assert res.status == "shed"
    assert "memory pressure" in res.note
    engine.governor.update("big", 5 * MB)
    for _ in range(len(hbm.RUNGS) + 1):
        engine.governor.tick()               # rungs re-arm
    server.start()
    try:
        ok = server.submit(_request(2)).result(timeout=60)
        assert ok.status == "ok"
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# metrics + engine wiring
# ---------------------------------------------------------------------------


def test_governor_gauges_in_metrics_snapshot():
    engine = _tiny_engine()
    server = ScoringServer(engine, "hbm-serve", _serve_cfg())
    snap = server.metrics.snapshot(device_memory=False)
    assert "mem" in snap["sources"]
    fields = snap["sources"]["mem"]["fields"]
    assert fields["ledger_bytes"] > 0        # params registered
    assert set(fields) >= {"pressure", "rung", "rung_downs", "rung_ups"}
    assert snap["sources"]["mem"]["type"] == "MemStats"


def test_engine_registers_params_and_pool_in_ledger():
    engine = _tiny_engine(prefix_cache=True, prefix_cache_pages=16)
    ledger = engine.governor.ledger()
    assert any(k.startswith("params:") for k in ledger)
    assert any(k.startswith("kv_pages:") and v > 0
               for k, v in ledger.items())


def test_mem_stats_schema_matches_dataclass():
    import dataclasses

    from lir_tpu.observe.registry import STATS_SCHEMA

    fields = {f.name for f in dataclasses.fields(MemStats)
              if not f.name.startswith("_")}
    assert fields == set(STATS_SCHEMA["MemStats"])


# ---------------------------------------------------------------------------
# fleet boot validation (satellite: budget < largest model fails loud)
# ---------------------------------------------------------------------------


def test_fleet_boot_rejects_budget_below_largest_model():
    engine = _tiny_engine("m0", seed=5)
    nbytes = weights.tree_bytes(engine.params)
    fleet = ModelFleet(cache_budget_bytes=nbytes // 2)
    with pytest.raises(ValueError) as ei:
        fleet.add_model("m0", engine=engine)
    msg = str(ei.value)
    assert "m0" in msg and "GiB" in msg and "headroom" in msg
    assert "weight-cache-gb" in msg


def test_fleet_boot_accepts_fitting_budget():
    engine = _tiny_engine("m0", seed=5)
    nbytes = weights.tree_bytes(engine.params)
    fleet = ModelFleet(cache_budget_bytes=2 * nbytes)
    fleet.add_model("m0", engine=engine)     # no raise
    assert fleet.resident("m0")


def test_attach_governor_revalidates_and_mirrors_weights():
    engine = _tiny_engine("m0", seed=5)
    nbytes = weights.tree_bytes(engine.params)
    fleet = ModelFleet(cache_budget_bytes=2 * nbytes)
    fleet.add_model("m0", engine=engine)
    gov = _gov(budget_mb=1000)
    fleet.attach_governor(gov)
    assert gov.ledger().get("weights") == fleet.cache.resident_bytes
    # evict_weights rung action drops the (idle) model
    assert fleet.evict_idle() is True
    assert not fleet.resident("m0")
    assert gov.ledger().get("weights") == 0


# ---------------------------------------------------------------------------
# WeightCache refcounts under concurrency (satellite: stress test)
# ---------------------------------------------------------------------------


def test_weight_cache_refcounts_threaded_stress():
    """Threaded acquire/release against a concurrent evictor: refcounts
    can never go negative (WeightCache asserts — any violation raises
    into the worker and fails the test), an in-flight or pinned model
    is never evicted mid-acquire, and the cache ends balanced."""
    cache = weights.WeightCache(budget_bytes=None)
    n_models = 4
    for i in range(n_models):
        cache.insert(f"m{i}", params={"w": i}, nbytes=MB)
    cache.pin("m0")
    errors = []
    stop = threading.Event()

    def worker(wid):
        try:
            for k in range(300):
                mid = f"m{(wid + k) % n_models}"
                try:
                    params = cache.acquire(mid)
                except KeyError:
                    continue        # evicted between choice and acquire
                assert params is not None
                # the model CANNOT be evicted while we hold it
                assert mid in cache, f"{mid} evicted while referenced"
                cache.release(mid)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)
            stop.set()

    def evictor():
        try:
            while not stop.is_set():
                evicted = cache.evict_idle()
                if evicted is not None:
                    assert evicted != "m0", "pinned model evicted"
                    # reinsert so workers keep finding work
                    cache.insert(evicted, params={"w": evicted},
                                 nbytes=MB)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(4)]
    ev = threading.Thread(target=evictor)
    for t in threads:
        t.start()
    ev.start()
    for t in threads:
        t.join(timeout=60)
    stop.set()
    ev.join(timeout=60)
    assert not errors, errors
    for i in range(n_models):
        assert cache.refcount(f"m{i}") == 0, "unbalanced refcount"
    assert "m0" in cache                     # pinned survived the storm

def test_weight_cache_release_below_zero_crashes():
    cache = weights.WeightCache()
    cache.insert("m", params={"w": 1}, nbytes=MB)
    cache.acquire("m")
    cache.release("m")
    with pytest.raises(AssertionError, match="negative"):
        cache.release("m")


# ---------------------------------------------------------------------------
# router pressure signal
# ---------------------------------------------------------------------------


def test_router_placement_penalizes_pressure():
    from lir_tpu.serve.router import ReplicaRouter

    class _Stub:
        def __init__(self, pressure):
            self.hbm_pressure = pressure
            self.queue_depth = 0
            self.stats = None

        def oldest_wait(self, now):
            return 0.0

        def submit(self, request):
            raise AssertionError("placement test never dispatches")

    calm, squeezed = _Stub(0.0), _Stub(2.0)
    router = ReplicaRouter(
        [("calm", calm), ("squeezed", squeezed)],
        config=RouterConfig(pressure_weight=6.0, cache_entries=0))
    # with equal depth, the squeezed replica must lose every pick
    for _ in range(6):
        h = router._pick("", exclude=set())
        assert h.replica_id == "calm"
    summary = router.stats_summary()
    assert summary["replicas"]["squeezed"]["hbm_pressure"] == 2.0
