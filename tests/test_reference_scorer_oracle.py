"""C13 oracle = the reference's EXECUTED scorer (VERDICT r4 #1).

tools/reference_scorer_oracle.py staged compare_base_vs_instruct.py /
compare_instruct_models.py with mechanical patches only, imported the
reference's own `get_yes_no_logprobs` (compare_base_vs_instruct.py:185-305,
compare_instruct_models.py:171-293), and ran it on CPU torch against the
deterministic tiny checkpoints from tools/tiny_checkpoints.py — including
the programmed-chain GPT-2 that forces top-2 matches at positions 0/2/5,
as runner-up at 3, and never (pos-0 fallback), and a bos-prepending
tokenizer that executes the reference's special-token grab (:244-247).
Every captured field lives in tests/golden/reference_executed.json
["scorer_oracle"]. These tests rebuild the IDENTICAL checkpoints, score
the identical prompts with lir_tpu's production engine
(factory.load_engine -> engine/score.py), and diff row-by-row. The scan
rule's semantics are therefore pinned against executed reference code, not
a reimplementation.
"""

import json
from pathlib import Path

import numpy as np
import pytest

from lir_tpu.config import RuntimeConfig
from lir_tpu.models.factory import load_engine

pytestmark = pytest.mark.slow  # heavy lane: see tests/conftest.py

GOLDEN_PATH = Path(__file__).parent / "golden" / "reference_executed.json"

PROB_ABS = 2e-3     # CPU f32 torch vs XLA logit-level agreement
REL = 0.01          # the BASELINE ≤1% gate for derived readouts


@pytest.fixture(scope="module")
def golden():
    if not GOLDEN_PATH.exists():
        pytest.skip("run tools/reference_differential.py first")
    data = json.loads(GOLDEN_PATH.read_text())
    if "scorer_oracle" not in data:
        pytest.skip("run tools/reference_scorer_oracle.py first")
    return data["scorer_oracle"]


@pytest.fixture(scope="module")
def ckpt_root(tmp_path_factory):
    return tmp_path_factory.mktemp("oracle_ckpts")


def _engine(path, max_new=50):
    # max_seq_len 256: the formatted few-shot prompts are ~134 tokens and
    # buckets are powers of two — 128 would silently left-truncate.
    return load_engine(path, RuntimeConfig(batch_size=4,
                                           max_new_tokens=max_new,
                                           max_seq_len=256))


def _diff_case(row, ref, *, check_completion=False):
    """Row-by-row diff of one engine PromptScore against one executed
    reference result dict."""
    assert row.position_found == ref["position_found"], (
        row.prompt, row.position_found, ref["position_found"])
    assert row.yes_no_found == ref["yes_no_found"]
    assert abs(row.yes_prob - ref["yes_prob"]) < PROB_ABS
    assert abs(row.no_prob - ref["no_prob"]) < PROB_ABS
    # Derived readouts under the 1% gate wherever they are numerically
    # meaningful. Below ~1e-6 masses the engine's 1e-10 softmax epsilon
    # and the reference's raw ratio diverge by construction (documented in
    # engine/score.py); the raw probabilities above already pin those.
    if "odds_ratio" in ref and ref["no_prob"] > 1e-6:
        assert abs(row.odds_ratio - ref["odds_ratio"]) <= (
            REL * max(abs(ref["odds_ratio"]), 1e-9))
    denom = ref["yes_prob"] + ref["no_prob"]
    if "relative_prob" in ref and denom > 1e-6:
        assert abs(row.relative_prob - ref["relative_prob"]) <= (
            REL * max(abs(ref["relative_prob"]), 1e-9))
    if check_completion:
        assert row.completion.strip() == ref["completion"].strip()


def _run_group(golden, ckpt_root, key, builder, *,
               check_completion=False, max_new=50):
    group = golden[key]
    path = ckpt_root / key
    built = builder(path)
    engine = _engine(path, max_new=max_new)
    # Target-id resolution must agree with what the EXECUTED reference
    # resolved (it never adds specials for these tokenizers).
    assert engine.yes_id == group["yes_id"]
    assert engine.no_id == group["no_id"]
    prompts = [c["prompt"] for c in group["cases"]]
    rows = engine.score_prompts(prompts)
    for row, case in zip(rows, group["cases"]):
        # Both reference variants ran; their scan rules are identical, so
        # diff against each (cbvi carries odds_ratio, cim relative_prob).
        _diff_case(row, case["ref_cbvi"], check_completion=check_completion)
        _diff_case(row, case["ref_cim"], check_completion=check_completion)
    return built, engine, rows


def test_bpe_gpt2_matches_executed_reference(golden, ckpt_root):
    from tiny_checkpoints import build_bpe_gpt2
    _run_group(golden, ckpt_root, "bpe-gpt2", build_bpe_gpt2)


def test_sp_llama_matches_executed_reference(golden, ckpt_root):
    from tiny_checkpoints import build_sp_llama
    _run_group(golden, ckpt_root, "sp-llama", build_sp_llama)


def test_sp_t5_matches_executed_reference(golden, ckpt_root):
    """The enc-dec branch (compare_base_vs_instruct.py:188-237): ids from
    tokenizer("Yes"), scores scanned from decoder steps."""
    from tiny_checkpoints import build_sp_t5
    _run_group(golden, ckpt_root, "sp-t5", build_sp_t5, max_new=12)


def test_chain_gpt2_pins_scan_positions(golden, ckpt_root):
    """The programmed-chain checkpoint forces every scan outcome the rule
    can produce: found at 0 (immediate), 2 and 5 (after preamble), found
    as the top-2 RUNNER-UP at 3, and never found -> position-0 fallback
    (compare_base_vs_instruct.py:280-285). Completions compare exactly —
    +10/+5 margins leave no framework tie-break slack."""
    from tiny_checkpoints import build_chain_gpt2
    group = golden["chain-gpt2"]
    # The capture asserted the reference hit the designed outcomes; pin
    # them here too so the golden can't drift.
    designed = {k: tuple(v) for k, v in group["designed"].items()}
    for case in group["cases"]:
        want = designed[case["key"]]
        assert (case["ref_cbvi"]["position_found"],
                case["ref_cbvi"]["yes_no_found"]) == want
    _, _, rows = _run_group(golden, ckpt_root, "chain-gpt2",
                            lambda p: build_chain_gpt2(p)[:3],
                            check_completion=True)
    # The never-found case must have scanned ALL 10 positions without a
    # match on our side as well (fallback, not an early find).
    never = [r for r, c in zip(rows, group["cases"]) if c["key"] == "never"]
    assert never[0].yes_no_found is False
    assert never[0].position_found == 0


@pytest.mark.parametrize("key,never", [("chain-t5-pos2", False),
                                       ("chain-t5-never", True)])
def test_chain_t5_pins_encdec_scan_positions(golden, ckpt_root, key, never):
    """The enc-dec branch at NON-fallback positions: the chain T5's
    zeroed cross-attention makes its decoder output a designed constant,
    so the executed reference finds Yes in the top-2 at position 2 (or
    never -> position-0 fallback) and our T5 capture path must land on
    the identical outcome, completion included."""
    from tiny_checkpoints import build_chain_t5
    group = golden[key]
    assert [group["cases"][0]["ref_cbvi"]["position_found"],
            group["cases"][0]["ref_cbvi"]["yes_no_found"]] == group["designed"]
    _run_group(golden, ckpt_root, key,
               lambda p: build_chain_t5(p, never=never)[:3],
               check_completion=not never, max_new=12)


def test_bos_tokenizer_quirk_executed_and_fixed(golden, ckpt_root):
    """EXECUTED reference fact (not a reading of its source): with a
    bos-prepending tokenizer (real LlamaTokenizer encode semantics), the
    reference's `tokenizer(" Yes").input_ids[0]` (:244-247) resolves BOTH
    targets to <s>, so yes_prob == no_prob and relative_prob degenerates
    to exactly 0.5 for every prompt. lir_tpu resolves targets with
    add_special_tokens=False (engine/tokens.first_token_id) — fixed, not
    replicated (PARITY.md "Reference defects")."""
    from tiny_checkpoints import build_sp_llama
    group = golden["sp-llama-bos"]
    assert group["yes_id"] == group["bos_id"]
    assert group["no_id"] == group["bos_id"]
    ref = group["cases"][0]["ref_cim"]
    assert ref["relative_prob"] == 0.5
    assert ref["yes_prob"] == ref["no_prob"]

    path = ckpt_root / "sp-llama-bos"
    build_sp_llama(path, add_bos=True)
    engine = _engine(path)
    # Our resolution lands on the metaspace pieces, never the special.
    assert engine.yes_id != group["bos_id"]
    assert engine.no_id != group["bos_id"]
    assert engine.yes_id == golden["sp-llama"]["yes_id"]
    row = engine.score_prompts([group["cases"][0]["prompt"]])[0]
    assert np.isfinite(row.relative_prob)
    # The engine keeps a real signal where the reference's is constant.
    assert row.yes_prob != row.no_prob
