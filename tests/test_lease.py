"""Shard-lease semantics tests (engine/lease.py + the leased sweep).

Pins the work-stealing tentpole's contracts:

- claim / renew / expire / steal ordering over a shared lease log,
  including double-claim refusal while a foreign lease is live;
- a torn trailing ``__meta__`` lease line (the kill-mid-append
  artifact) is tolerated on resume and truncated by the next append;
- a stolen shard's re-folded rows are BITWISE no-ops on the streaming
  lattice, and the identical-overlap-tolerant merge reproduces an
  uninterrupted run's accumulator exactly (divergent overlap still
  hard-fails);
- the leased sweep driver produces the same rows and the same
  accumulator as a static run, across a mid-sweep kill + resume.
"""

import jax
import numpy as np
import pytest

from lir_tpu import faults
from lir_tpu.backends.fake import FakeTokenizer
from lir_tpu.config import RuntimeConfig
from lir_tpu.engine import lease as lease_mod
from lir_tpu.engine import stream_stats as stream_mod
from lir_tpu.stats import streaming
from lir_tpu.utils.profiling import LeaseStats


def _mgr(path, holder, ttl=10.0, t0=0.0):
    now = {"t": t0}
    m = lease_mod.LeaseManager(path, holder, ttl_s=ttl,
                               clock=lambda: now["t"],
                               stats=LeaseStats())
    return m, now


# ---------------------------------------------------------------------------
# Claim / renew / expire / steal over one shared log
# ---------------------------------------------------------------------------

def test_claim_renew_expire_steal_ordering(tmp_path):
    log = tmp_path / "sweep.leases.jsonl"
    a, a_now = _mgr(log, "hostA", ttl=10.0)
    b, b_now = _mgr(log, "hostB", ttl=10.0)

    # A claims shard 0; B's claim is refused while the lease is live.
    assert a.claim(0)
    assert not b.claim(0)
    assert b.stats.refused == 1

    # A renews at t=8 -> expiry moves to 18; B still refused at t=12.
    a_now["t"] = 8.0
    assert a.renew(0)
    b_now["t"] = 12.0
    assert not b.claim(0)

    # Expiry passes with no renewal (A died): B observes the expired
    # lease but a plain claim still refuses — stealing is explicit.
    b_now["t"] = 19.0
    assert not b.claim(0, steal=False)
    assert b.stats.expired_seen >= 1
    assert b.claim(0, steal=True)
    assert b.stats.steals == 1
    rec = b.record(0)
    assert rec["holder"] == "hostB" and rec["seq"] >= 2

    # A comes back and renews: the lease is LOST (B holds it live) —
    # A must abandon the shard, not keep scoring it blind.
    a_now["t"] = 19.5
    assert not a.renew(0)
    assert a.stats.lost == 1
    assert 0 not in a.held

    # B finishes: done-records are terminal for everyone.
    b.mark_done(0)
    a_now["t"] = 100.0
    assert not a.claim(0, steal=True)
    assert b.is_done(0) and a.is_done(0)


def test_own_reclaim_after_resume_is_not_a_steal(tmp_path):
    log = tmp_path / "l.jsonl"
    a, a_now = _mgr(log, "hostA", ttl=10.0)
    assert a.claim(0)
    # The same holder resumes (fresh manager, same identity): its own
    # live lease re-claims without a steal.
    a2, now2 = _mgr(log, "hostA", ttl=10.0, t0=5.0)
    assert a2.claim(0)
    assert a2.stats.steals == 0 and a2.stats.claims == 1


def test_all_done_and_claim_loop(tmp_path):
    log = tmp_path / "l.jsonl"
    a, _ = _mgr(log, "hostA")
    shards = [["c0", "c1"], ["c2"], ["c3", "c4"]]
    seen = []
    for sid, cells in a.claim_loop(shards):
        seen.append((sid, list(cells)))
        a.mark_done(sid)
    assert sorted(s for s, _ in seen) == [0, 1, 2]
    assert a.all_done()
    assert a.stats.shards_done == 3


def test_steal_expired_skips_live_and_done(tmp_path):
    log = tmp_path / "l.jsonl"
    a, a_now = _mgr(log, "hostA", ttl=10.0)
    b, b_now = _mgr(log, "hostB", ttl=10.0)
    shards = [["c0"], ["c1"], ["c2"]]
    a.register_shards(3)
    b.register_shards(3)
    assert a.claim(0) and a.claim(1)
    a.mark_done(0)
    # shard 1 live (held by A), shard 2 unclaimed: B's steal pass takes
    # shard 2 first, then nothing (1 is live, 0 done).
    got = b.steal_expired(shards)
    assert got is not None and got[0] == 2
    b.mark_done(2)
    assert b.steal_expired(shards) is None
    assert not b.all_done()
    # A's lease on shard 1 expires -> B steals it within one TTL.
    b_now["t"] = 11.0
    got = b.steal_expired(shards)
    assert got is not None and got[0] == 1
    b.mark_done(1)
    assert b.all_done()


def test_torn_trailing_lease_line_tolerated_on_resume(tmp_path):
    log = tmp_path / "l.jsonl"
    a, _ = _mgr(log, "hostA")
    assert a.claim(0)
    a.mark_done(0)
    # Kill mid-append: a torn, newline-free __meta__ fragment tails the
    # log — exactly what SweepManifest's crash mode leaves behind.
    faults.tear_jsonl_tail(log, fragment='{"__meta__": {"lease:1": {"ho')
    b, _ = _mgr(log, "hostB")
    assert b.is_done(0)          # intact records survive
    assert b.record(1) is None   # the torn record reads as absent
    assert b.claim(1)            # ... and the next append truncates it
    # The log stays parseable end-to-end after the truncating append.
    c, _ = _mgr(log, "hostC")
    assert c.record(1)["holder"] == "hostB"


def test_renew_on_flush_via_attach_manifest(tmp_path):
    from lir_tpu.utils.manifest import SweepManifest

    log = tmp_path / "l.jsonl"
    a, a_now = _mgr(log, "hostA", ttl=10.0)
    assert a.claim(0)
    man = SweepManifest(tmp_path / "m.jsonl", ("k",))
    a.attach_manifest(man)
    a_now["t"] = 9.0
    man.mark_done_many([{"k": "row1"}])   # a flush IS a heartbeat
    rec = a.record(0)
    assert rec["expiry"] == pytest.approx(19.0)
    assert a.stats.renews == 1


# ---------------------------------------------------------------------------
# Stolen-shard re-folds: bitwise no-ops on the lattice
# ---------------------------------------------------------------------------

class _Cell:
    def __init__(self, p, r):
        self.prompt_idx, self.rephrase_idx = p, r


def _fold_cells(sink, cells, seed=0):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    # Deterministic per-cell values keyed by slot — what a re-score of
    # the same cells on a config-identical engine produces.
    for c in cells:
        v = (c.prompt_idx * 31 + c.rephrase_idx * 7) % 97 / 97.0
        yes = np.float32(0.1 + 0.8 * v)
        sink.fold(jnp.asarray([yes]), jnp.asarray([1 - yes],
                                                  jnp.float32),
                  jnp.asarray([100 * v], jnp.float32),
                  jnp.zeros((1, 1), jnp.float32), [c], topk=1)
    del rng


def test_stolen_shard_refold_is_bitwise_noop():
    cells = [_Cell(0, r) for r in range(6)]
    sink = stream_mod.StreamSink(1, 6, seed=3)
    _fold_cells(sink, cells)
    once = sink.snapshot()
    _fold_cells(sink, cells)      # the steal re-scores the whole shard
    twice = sink.snapshot()
    assert np.array_equal(once.filled, twice.filled)
    assert np.array_equal(once.rel, twice.rel, equal_nan=True)
    assert np.array_equal(once.conf, twice.conf, equal_nan=True)
    assert np.array_equal(once.dec, twice.dec)


def test_identical_overlap_merge_matches_uninterrupted_run():
    # Uninterrupted run: one holder folds everything.
    full = stream_mod.StreamSink(1, 8, seed=5)
    _fold_cells(full, [_Cell(0, r) for r in range(8)])
    want = full.snapshot()

    # Leased run: host A folded rows 0-4 then died mid-shard (rows 0-2
    # were its shard, 3-4 the start of shard 2); host B steals shard 2
    # and re-scores ALL of it (3-5) plus its own shard (6-7).
    a = stream_mod.StreamSink(1, 8, seed=5)
    _fold_cells(a, [_Cell(0, r) for r in range(5)])
    b = stream_mod.StreamSink(1, 8, seed=5)
    _fold_cells(b, [_Cell(0, r) for r in range(3, 8)])

    with pytest.raises(ValueError):
        streaming.merge_accums([a.snapshot(), b.snapshot()])
    merged = streaming.merge_accums(
        [a.snapshot(), b.snapshot()], allow_identical_overlap=True)
    assert np.array_equal(merged.filled, want.filled)
    assert np.array_equal(merged.rel, want.rel, equal_nan=True)
    assert np.array_equal(merged.conf, want.conf, equal_nan=True)
    assert np.array_equal(merged.dec, want.dec)


def test_divergent_overlap_refuses_even_when_allowed():
    a = stream_mod.StreamSink(1, 4, seed=5)
    _fold_cells(a, [_Cell(0, r) for r in range(3)])
    b = stream_mod.StreamSink(1, 4, seed=5)
    _fold_cells(b, [_Cell(0, r) for r in range(2, 4)])
    acc_b = b.snapshot()
    rel = np.array(acc_b.rel)     # snapshots are read-only buffers
    rel[0, 2] += 0.25             # a non-deterministic "re-score"
    acc_b = streaming.HostAccum(filled=acc_b.filled, rel=rel,
                                conf=acc_b.conf, dec=acc_b.dec,
                                seed=acc_b.seed)
    with pytest.raises(ValueError, match="DIVERGENT"):
        streaming.merge_accums([a.snapshot(), acc_b],
                               allow_identical_overlap=True)


# ---------------------------------------------------------------------------
# The leased sweep driver: rows + accumulator == a static run
# ---------------------------------------------------------------------------

N_CELLS = 10
BATCH = 4


def _make_engine(lease=False, seed=11, **rt_kw):
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    cfg = ModelConfig(name="lease-t", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=1, n_heads=2,
                      intermediate_size=64, max_seq_len=256)
    params = decoder.init_params(cfg, jax.random.PRNGKey(seed))
    rt = RuntimeConfig(batch_size=BATCH, max_seq_len=256,
                       piggyback_prefill=False, lease_shards=lease,
                       lease_ttl_s=30.0, lease_cells_per_shard=3,
                       **rt_kw)
    return ScoringEngine(params, cfg, FakeTokenizer(), rt)


def _grid(n_cells, seed=21):
    from lir_tpu.data.prompts import LegalPrompt

    rng = np.random.default_rng(seed)
    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement peril deductible").split()

    def text(n):
        return " ".join(rng.choice(words) for _ in range(n)) + " ?"

    lp = (LegalPrompt(main=text(10), response_format="Answer Yes or No .",
                      target_tokens=("Yes", "No"),
                      confidence_format="Give a number from 0 to 100 ."),)
    return lp, ([text(10 if i % 2 else 20) for i in range(n_cells - 1)],)


def _accum(path):
    return stream_mod.load_accum(path.with_suffix(stream_mod.ACCUM_SUFFIX))


def _assert_accums_equal(a, b):
    assert a is not None and b is not None
    assert np.array_equal(a.filled, b.filled)
    assert np.array_equal(a.rel, b.rel, equal_nan=True)
    assert np.array_equal(a.conf, b.conf, equal_nan=True)
    assert np.array_equal(a.dec, b.dec)


def test_leased_sweep_matches_static_run_bitwise(tmp_path):
    from lir_tpu.engine.sweep import run_perturbation_sweep

    lp, perts = _grid(N_CELLS)
    static = run_perturbation_sweep(
        _make_engine(), "lease", lp, perts, tmp_path / "static.csv",
        checkpoint_every=4)
    leased = run_perturbation_sweep(
        _make_engine(lease=True), "lease", lp, perts,
        tmp_path / "leased.csv", checkpoint_every=4)
    assert len(leased) == len(static) == N_CELLS
    by_key = {r.rephrased_main: (r.token_1_prob, r.token_2_prob,
                                 r.confidence_value,
                                 r.weighted_confidence)
              for r in static}
    for r in leased:
        assert (r.token_1_prob, r.token_2_prob, r.confidence_value,
                r.weighted_confidence) == by_key[r.rephrased_main]
    _assert_accums_equal(_accum(tmp_path / "static.csv"),
                         _accum(tmp_path / "leased.csv"))
    # The lease log exists and records the full claim/done history.
    log = (tmp_path / "leased.csv").with_suffix(lease_mod.LEASE_SUFFIX)
    check, _ = _mgr(log, "checker", t0=1e12)
    n_shards = -(-N_CELLS // 3)
    assert all(check.is_done(s) for s in range(n_shards))


def test_leased_sweep_kill_resume_accumulator_bitwise(tmp_path):
    """A leased sweep killed mid-run (rows folded but shards unfinished)
    resumes — re-claiming its own leases — and converges on the static
    run's accumulator EXACTLY (the acceptance gate for the elastic
    bench's offline half)."""
    from lir_tpu.engine.sweep import run_perturbation_sweep

    lp, perts = _grid(N_CELLS)
    run_perturbation_sweep(_make_engine(), "lease", lp, perts,
                           tmp_path / "static.csv", checkpoint_every=4)

    engine = _make_engine(lease=True)
    plan = faults.FaultPlan(seed=9, schedules={
        "dispatch": faults.SiteSchedule.kill_at(1)})
    faults.wrap_engine(engine, plan)
    out = tmp_path / "leased.csv"
    with pytest.raises(faults.InjectedPreemption):
        run_perturbation_sweep(engine, "lease", lp, perts, out,
                               checkpoint_every=4)
    # Resume (same holder identity: its own live leases re-claim).
    leased = run_perturbation_sweep(_make_engine(lease=True), "lease",
                                    lp, perts, out, checkpoint_every=4)
    keys = [r.rephrased_main for r in leased]
    assert len(set(keys)) == len(keys)
    _assert_accums_equal(_accum(tmp_path / "static.csv"), _accum(out))
