"""Streaming statistics: the device-resident accumulator sink
(engine/stream_stats.py) vs the host-side csv-reload pipeline.

The tentpole contract (ISSUE 9 / ROADMAP item 4), pinned on CPU:

- streaming moments/kappa/contingency counts equal the host-side
  ``stats``/``analysis`` results computed from the SAME rows — counts
  and kappa bitwise, moments/CIs within stats.streaming.FLOAT_TOL;
- the multihost fence merge over a fake 8-host shard split equals the
  single-host fold bitwise;
- a killed-and-resumed sweep yields accumulators bitwise-identical to
  an uninterrupted one, and the manifest-recorded bootstrap key makes
  CIs reproducible across resume and across --no-streaming-stats
  re-runs analyzed from the row artifact;
- the serve sink folds once per content address: SIGTERM checkpoint /
  resume / re-submitted (deadline-cancelled) rows never double-count.
"""

from __future__ import annotations

import json
from pathlib import Path

import jax
import numpy as np
import pytest

from lir_tpu.backends.fake import FakeTokenizer
from lir_tpu.config import RuntimeConfig, ServeConfig
from lir_tpu.data import schemas
from lir_tpu.data.prompts import LegalPrompt
from lir_tpu.engine import grid as grid_mod
from lir_tpu.engine import stream_stats as stream_mod
from lir_tpu.engine.runner import ScoringEngine
from lir_tpu.engine.sweep import run_perturbation_sweep
from lir_tpu.models import decoder
from lir_tpu.models.registry import ModelConfig
from lir_tpu.stats import streaming as st

N_CELLS = 12
BATCH = 4
N_REPH = N_CELLS  # one prompt: rephrase slots 0..N_CELLS-1


def _cfg():
    return ModelConfig(name="stream-test", vocab_size=FakeTokenizer.VOCAB,
                       hidden_size=32, n_layers=1, n_heads=2,
                       intermediate_size=64, max_seq_len=256)


@pytest.fixture(scope="module")
def params():
    return decoder.init_params(_cfg(), jax.random.PRNGKey(11))


def _engine(params, **rt_kw):
    rt_kw.setdefault("batch_size", BATCH)
    rt_kw.setdefault("max_seq_len", 256)
    # Plain dispatch path: chaos/bitwise comparisons must not depend on
    # the piggyback chain's fault-wrap gating.
    rt_kw.setdefault("piggyback_prefill", False)
    rt_kw.setdefault("aot_precompile", False)
    return ScoringEngine(params, _cfg(), FakeTokenizer(),
                         RuntimeConfig(**rt_kw))


def _grid(n_cells=N_CELLS, seed=21):
    rng = np.random.default_rng(seed)
    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement peril deductible").split()

    def text(n):
        return " ".join(rng.choice(words) for _ in range(n)) + " ?"

    lp = (LegalPrompt(main=text(10),
                      response_format="Answer Yes or No .",
                      target_tokens=("Yes", "No"),
                      confidence_format="Give a number from 0 to 100 ."),)
    perts = ([text(10 if i % 2 else 24) for i in range(n_cells - 1)],)
    return lp, perts


def _sweep(engine, tmp_path, name="r.csv", **kw):
    lp, perts = _grid()
    rows = run_perturbation_sweep(engine, "sm", lp, perts,
                                  tmp_path / name, **kw)
    return rows, engine.stream_sink


def _slot_map():
    lp, perts = _grid()
    return st.slot_map_from_cells(grid_mod.build_grid("sm", lp, perts))


# ---------------------------------------------------------------------------
# Parity: streaming == csv-reload on the same rows
# ---------------------------------------------------------------------------


def test_streaming_matches_csv_reload(params, tmp_path):
    rows, sink = _sweep(_engine(params), tmp_path)
    assert len(rows) == N_CELLS
    acc = sink.snapshot()
    assert acc.rows_folded == N_CELLS
    streamed = st.summarize(acc, n_boot=200)

    df = schemas.read_results_frame(tmp_path / "r.csv")
    reload_acc = st.accum_from_rows(df, _slot_map(), 1, N_REPH, acc.seed)
    reloaded = st.summarize(reload_acc, n_boot=200)

    # counts + kappa bitwise, moments/CIs within FLOAT_TOL
    st.assert_parity(streamed, reloaded)
    # decisions themselves are bitwise (yes>no == f64 rel>0.5)
    assert np.array_equal(acc.dec, reload_acc.dec)
    assert np.array_equal(acc.filled, reload_acc.filled)


def test_streaming_kappa_matches_analysis_pipeline(params, tmp_path):
    """The accumulator kappa runs through the SAME within_group_kappa
    the analysis layer calls on the dataframe — identical floats."""
    from lir_tpu.analysis.perturbation import (add_relative_prob,
                                               perturbation_kappa)

    rows, sink = _sweep(_engine(params), tmp_path)
    k_stream = st.kappa(sink.snapshot())
    df = add_relative_prob(schemas.read_results_frame(tmp_path / "r.csv"))
    k_host, obs, exp = perturbation_kappa(df)

    def eq(a, b):
        return (np.isnan(a) and np.isnan(b)) or a == b

    assert eq(k_stream["kappa"], k_host)
    assert eq(k_stream["observed_agreement"], obs)
    assert eq(k_stream["expected_agreement"], exp)


def test_quarantined_rows_excluded_identically(params, tmp_path):
    """An injected-NaN row is NaN'd by the device predicate exactly as
    the host numerics guard quarantines it: counts still bitwise."""
    from lir_tpu import faults

    engine = _engine(params)
    plan = faults.FaultPlan(seed=23, schedules={
        "dispatch": faults.SiteSchedule.nan_at(0, rows=(1,))},
        stats=engine.fault_stats)
    faults.wrap_engine(engine, plan)
    rows, sink = _sweep(engine, tmp_path)
    acc = sink.snapshot()
    assert acc.rows_folded == N_CELLS
    # exactly one cell invalid on the streaming side...
    counts = st.contingency(acc)
    assert int(counts["n_valid"].sum()) == N_CELLS - 1
    # ...and the csv-reload side agrees bitwise (the quarantined row's
    # measurement fields are nulled in the artifact).
    df = schemas.read_results_frame(tmp_path / "r.csv")
    reload_acc = st.accum_from_rows(df, _slot_map(), 1, N_REPH, acc.seed)
    st.assert_parity(st.summarize(acc, n_boot=50),
                     st.summarize(reload_acc, n_boot=50))


def test_moments_match_summary_statistics(params, tmp_path):
    """Per-prompt moments line up with the analysis layer's
    prompt_summary_stats columns within FLOAT_TOL."""
    from lir_tpu.analysis.perturbation import (add_relative_prob,
                                               prompt_summary_stats)

    rows, sink = _sweep(_engine(params), tmp_path)
    streamed = st.summarize(sink.snapshot(), n_boot=0)
    df = add_relative_prob(schemas.read_results_frame(tmp_path / "r.csv"))
    host = prompt_summary_stats(df, 0, ("Yes", "No"))
    m = streamed["per_prompt"][0]["relative_prob"]
    assert abs(m["mean"]
               - host['Mean Relative Probability of "Yes"']) <= st.FLOAT_TOL
    assert abs(m["std"] - host["Std Dev"]) <= st.FLOAT_TOL
    assert abs(m["p2_5"] - host["2.5th Percentile"]) <= st.FLOAT_TOL
    assert abs(m["p97_5"] - host["97.5th Percentile"]) <= st.FLOAT_TOL


# ---------------------------------------------------------------------------
# Multihost fence merge == single-host fold
# ---------------------------------------------------------------------------


def test_shard_merge_equals_single_host_fold(params, tmp_path):
    """Fold the grid as 8 disjoint host shards (the fake 8-host split
    host_shard performs) and union at the fence: bitwise equal to one
    host folding everything."""
    from lir_tpu.parallel import multihost

    rows, sink = _sweep(_engine(params), tmp_path)
    full = sink.snapshot()

    lp, perts = _grid()
    cells = grid_mod.build_grid("sm", lp, perts)
    shards = []
    for h in range(8):
        shard_cells = multihost.host_shard(cells, process_index=h,
                                           process_count=8)
        acc = st.empty_accum(1, N_REPH, full.seed)
        for c in shard_cells:
            p, r = c.prompt_idx, c.rephrase_idx
            acc.filled[p, r] = full.filled[p, r]
            acc.rel[p, r] = full.rel[p, r]
            acc.conf[p, r] = full.conf[p, r]
            acc.dec[p, r] = full.dec[p, r]
        shards.append(acc)
    merged = st.merge_accums(shards)
    assert np.array_equal(merged.filled, full.filled)
    assert np.array_equal(merged.rel, full.rel, equal_nan=True)
    assert np.array_equal(merged.conf, full.conf, equal_nan=True)
    assert np.array_equal(merged.dec, full.dec)
    # merge refuses overlapping shards (two hosts scoring one cell)
    with pytest.raises(ValueError):
        st.merge_accums([full, shards[0]])
    # gather_stacked is the identity stack on a single process
    stacked = multihost.gather_stacked(full.rel)
    assert stacked.shape == (1,) + full.rel.shape


# ---------------------------------------------------------------------------
# Resume: bitwise accumulators + reproducible CIs
# ---------------------------------------------------------------------------


def test_kill_resume_accumulator_bitwise(params, tmp_path):
    from lir_tpu import faults

    e_clean = _engine(params)
    _sweep(e_clean, tmp_path, name="clean.csv", checkpoint_every=4)
    acc_clean = stream_mod.load_accum(
        (tmp_path / "clean.csv").with_suffix(stream_mod.ACCUM_SUFFIX))

    e_kill = _engine(params)
    plan = faults.FaultPlan(seed=5, schedules={
        "dispatch": faults.SiteSchedule.kill_at(1)},
        stats=e_kill.fault_stats)
    faults.wrap_engine(e_kill, plan)
    with pytest.raises(faults.InjectedPreemption):
        _sweep(e_kill, tmp_path, name="killed.csv", checkpoint_every=4)
    # the partial accumulator was flushed on the kill path
    partial = stream_mod.load_accum(
        (tmp_path / "killed.csv").with_suffix(stream_mod.ACCUM_SUFFIX))
    assert partial is not None and 0 < partial.rows_folded < N_CELLS

    _sweep(_engine(params), tmp_path, name="killed.csv",
           checkpoint_every=4)
    acc_resumed = stream_mod.load_accum(
        (tmp_path / "killed.csv").with_suffix(stream_mod.ACCUM_SUFFIX))
    assert acc_resumed.rows_folded == N_CELLS
    assert np.array_equal(acc_clean.filled, acc_resumed.filled)
    assert np.array_equal(acc_clean.rel, acc_resumed.rel, equal_nan=True)
    assert np.array_equal(acc_clean.conf, acc_resumed.conf,
                          equal_nan=True)
    assert np.array_equal(acc_clean.dec, acc_resumed.dec)
    assert acc_clean.seed == acc_resumed.seed


def test_stream_seed_recorded_and_cis_reproducible(params, tmp_path):
    """The bootstrap key rides the manifest: a --no-streaming-stats
    re-run analyzed from the row artifact with the recorded key yields
    the same CIs (within float tolerance of the f32 lattice)."""
    from lir_tpu.utils.manifest import SweepManifest

    rows, sink = _sweep(_engine(params), tmp_path, seed=1234)
    m = SweepManifest((tmp_path / "r.csv").with_suffix(".manifest.jsonl"),
                      grid_mod.RESUME_KEY_FIELDS)
    assert m.meta.get("stream_seed") == 1234
    streamed = st.summarize(sink.snapshot(), n_boot=200)

    # "--no-streaming-stats re-run": same grid swept with the sink off,
    # analysis from the artifact + recorded key.
    e2 = _engine(params, streaming_stats=False)
    rows2, sink2 = _sweep(e2, tmp_path, name="off.csv", seed=1234)
    assert sink2 is None
    df = schemas.read_results_frame(tmp_path / "off.csv")
    replay = st.summarize(
        st.accum_from_rows(df, _slot_map(), 1, N_REPH,
                           m.meta["stream_seed"]), n_boot=200)
    st.assert_parity(streamed, replay)


def test_streaming_only_mode_no_rows(params, tmp_path):
    """row_artifact=False: zero rows materialized, rows folded == grid,
    bytes-avoided counter moves, resume runs off manifest + accum."""
    engine = _engine(params, row_artifact=False)
    rows, sink = _sweep(engine, tmp_path)
    assert rows == []
    assert not (tmp_path / "r.csv").exists()
    assert sink.stats.rows_folded == N_CELLS
    assert sink.stats.host_bytes_avoided > 0
    assert sink.snapshot().rows_folded == N_CELLS
    # resume: nothing pending, accumulator intact
    rows2, _ = _sweep(_engine(params, row_artifact=False), tmp_path)
    acc = stream_mod.load_accum(
        (tmp_path / "r.csv").with_suffix(stream_mod.ACCUM_SUFFIX))
    assert acc.rows_folded == N_CELLS


def test_accum_checkpoint_roundtrip(tmp_path):
    acc = st.empty_accum(2, 3, seed=7)
    acc.filled[0, 1] = 1
    acc.rel[0, 1] = np.float32(0.25)
    acc.dec[0, 1] = 0
    stream_mod.save_accum(acc, tmp_path / "a.accum.npz")
    back = stream_mod.load_accum(tmp_path / "a.accum.npz")
    assert back.seed == 7
    assert np.array_equal(back.filled, acc.filled)
    assert np.array_equal(back.rel, acc.rel, equal_nan=True)
    # unreadable file degrades to None, never raises
    (tmp_path / "torn.accum.npz").write_bytes(b"not-an-npz")
    assert stream_mod.load_accum(tmp_path / "torn.accum.npz") is None


def test_fold_mesh_sharded_inputs(params):
    """Mesh engines hand the sink NamedSharding-committed readouts: the
    accumulator must replicate onto that mesh on first fold (and bypass
    the single-device AOT registry) instead of raising an incompatible-
    devices error — the bug the 8-device dryrun surfaced."""
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devices = np.array(jax.devices()).reshape(2, 4)
    mesh = Mesh(devices, ("data", "model"))
    repl = NamedSharding(mesh, PartitionSpec())
    sink = stream_mod.StreamSink(1, 4, seed=0,
                                 registry_get=lambda *a: (_ for _ in ()
                                                          ).throw(
                                     AssertionError("registry must be "
                                                    "bypassed on mesh")))

    class C:
        prompt_idx = 0
        rephrase_idx = 1

    put = lambda x: jax.device_put(jnp.asarray(x, jnp.float32), repl)  # noqa: E731
    sink.fold(put([0.6, 0.0]), put([0.2, 0.0]), put([40.0, 0.0]),
              put(np.full((2, 20), -1.0)), [C()], topk=20)
    assert sink.registry_get is None          # AOT path disabled on mesh
    acc = sink.snapshot()
    assert acc.rows_folded == 1 and acc.dec[0, 1] == 1
    assert abs(acc.rel[0, 1] - 0.75) < 1e-6


def test_fold_padding_rows_dropped_and_idempotent():
    import jax.numpy as jnp

    sink = stream_mod.StreamSink(1, 4, seed=0)

    class C:
        prompt_idx = 0
        rephrase_idx = 2

    yes = jnp.asarray([0.8, 999.0], jnp.float32)   # row 1 is padding
    no = jnp.asarray([0.1, 999.0], jnp.float32)
    wc = jnp.asarray([50.0, -5.0], jnp.float32)
    lp = jnp.full((2, 20), -1.0, jnp.float32)
    sink.fold(yes, no, wc, lp, [C()], topk=20)
    acc = sink.snapshot()
    assert acc.rows_folded == 1
    assert acc.filled[0, 2] == 1 and acc.dec[0, 2] == 1
    # refold: bitwise no-op
    sink.fold(yes, no, wc, lp, [C()], topk=20)
    acc2 = sink.snapshot()
    assert np.array_equal(acc.rel, acc2.rel, equal_nan=True)


# ---------------------------------------------------------------------------
# Serve: live endpoint + no double-count across checkpoint/resume
# ---------------------------------------------------------------------------


def _serve_cfg(**kw):
    kw.setdefault("queue_depth", 64)
    kw.setdefault("classes", (("t", 600.0),))
    kw.setdefault("default_class", "t")
    kw.setdefault("linger_s", 0.005)
    kw.setdefault("prefix_cache", False)
    kw.setdefault("stream_window", 64)
    return ServeConfig(**kw)


def _request(i, deadline_s=None):
    from lir_tpu.serve import ServeRequest

    return ServeRequest(
        binary_prompt=f"claim {i} flood damage ? Answer Yes or No .",
        confidence_prompt=(f"claim {i} flood damage ? Give a number "
                           "from 0 to 100 ."),
        targets=("Yes", "No"), klass="t", deadline_s=deadline_s,
        request_id=f"r{i}")


def test_serve_live_stats_endpoint(params):
    from lir_tpu.serve import ScoringServer

    server = ScoringServer(_engine(params), "sm", _serve_cfg()).start()
    try:
        futs = [server.submit(_request(i)) for i in range(8)]
        for f in futs:
            assert f.result(timeout=300).status == "ok"
        live = server.stream_summary()
        assert live["rows_folded"] == 8
        g = live["per_group"]["0"]
        assert g["targets"] == ["Yes", "No"] and g["n_valid"] == 8
        assert 0.0 <= g["mean_relative_prob"] <= 1.0
        assert "kappa" in live
        # json-serializable end to end (the cli endpoint prints it)
        json.dumps(live)
        # dedup re-ask: answered from cache, folded once
        server.submit(_request(3)).result(timeout=60)
        assert server.stream_summary()["rows_folded"] == 8
    finally:
        server.stop()


def test_serve_checkpoint_resume_never_double_counts(params, tmp_path):
    """The bugfix pin: SIGTERM checkpoint flushes the partial sink; a
    resumed server restores the folded-key set, so rows cancelled
    in-flight (or re-submitted after resume) fold at most once."""
    from lir_tpu.serve import ScoringServer

    server = ScoringServer(_engine(params), "sm", _serve_cfg()).start()
    for i in range(6):
        assert server.submit(_request(i)).result(timeout=300).status == "ok"
    # one row expires before dispatch: resolves partial, never folds
    dead = server.submit(_request(6, deadline_s=-1.0))
    assert dead.result(timeout=60).status == "deadline_exceeded"
    assert server.stream_summary()["rows_folded"] == 6

    ck = tmp_path / "state.json"
    server.shutdown_checkpoint(ck)
    payload = json.loads(ck.read_text())
    assert payload["stream"]["head"] == 6      # partial accum flushed

    resumed = ScoringServer(_engine(params), "sm", _serve_cfg())
    resumed.resume_from_checkpoint(ck)
    resumed.start()
    try:
        assert resumed.stream_summary()["rows_folded"] == 6
        # the cancelled row re-submitted post-resume folds ONCE...
        assert resumed.submit(_request(6)).result(timeout=300).status == "ok"
        assert resumed.stream_summary()["rows_folded"] == 7
        # ...and an already-counted row from before the checkpoint
        # (fresh server, empty dedup cache -> scored again) does NOT.
        assert resumed.submit(_request(2)).result(timeout=300).status == "ok"
        assert resumed.stream_summary()["rows_folded"] == 7
    finally:
        resumed.stop()


def test_serve_stream_disabled(params):
    from lir_tpu.serve import ScoringServer

    server = ScoringServer(_engine(params, streaming_stats=False), "sm",
                           _serve_cfg())
    assert server.stream is None and server.stream_summary() == {}
    server2 = ScoringServer(_engine(params), "sm",
                            _serve_cfg(stream_window=0))
    assert server2.stream is None


# ---------------------------------------------------------------------------
# Survey layer: finalize consuming the accumulator directly
# ---------------------------------------------------------------------------


def test_survey_estimates_from_accum(params, tmp_path):
    from lir_tpu.survey.human_llm import llm_prompt_estimates_from_accum

    rows, sink = _sweep(_engine(params), tmp_path)
    est = llm_prompt_estimates_from_accum(sink.snapshot(), n_boot=100)
    assert set(est) == {0}
    e = est[0]
    assert 0.0 <= e["estimate"] <= 1.0
    assert e["ci_lower"] <= e["estimate"] <= e["ci_upper"]
    assert e["n"] == N_CELLS
    # reproducible from the same accumulator + recorded key
    est2 = llm_prompt_estimates_from_accum(sink.snapshot(), n_boot=100)
    assert est == est2
