"""Pallas flash attention vs reference softmax attention (interpret mode on
CPU; the same kernel runs compiled on the real chip)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lir_tpu.ops import flash_attention
from lir_tpu.parallel import reference_attention

pytestmark = pytest.mark.slow  # heavy lane: see tests/conftest.py


def _qkv(B=2, S=256, H=4, hd=64, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(size=(B, S, H, hd)), dtype) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [True, False])
def test_matches_reference(causal):
    q, k, v = _qkv()
    expected = reference_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=2e-5)


def test_multi_block_tiling():
    q, k, v = _qkv(S=512, seed=2)
    expected = reference_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, block_q=128, block_k=64, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=2e-5)


def test_short_sequence_block_clamp():
    q, k, v = _qkv(S=32, seed=3)
    out = flash_attention(q, k, v, interpret=True)  # blocks clamp to 32
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(reference_attention(q, k, v)), atol=2e-5)


def test_indivisible_seq_rejected():
    q, k, v = _qkv(S=100)
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)


def test_masked_attention_matches_reference():
    """key_mask semantics: masked keys excluded for every padding pattern."""
    import jax.numpy as jnp

    q, k, v = _qkv(S=128)
    mask = np.ones((2, 128), np.int32)
    mask[0, :30] = 0    # left padding
    mask[1, 100:] = 0   # right padding

    out = flash_attention(q, k, v, causal=True,
                          key_mask=jnp.asarray(mask), block_q=64, block_k=64,
                          interpret=True)
    # Dense reference with the same key-mask + causal semantics.
    S = 128
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = np.einsum("bqhd,bkhd->bhqk", np.asarray(q), np.asarray(k)) * scale
    allowed = (np.tril(np.ones((S, S), bool))[None, None]
               & (mask[:, None, None, :] > 0))
    s = np.where(allowed, s, -np.inf)
    with np.errstate(invalid="ignore", over="ignore"):
        p = np.exp(s - s.max(-1, keepdims=True))
        p = np.where(np.isfinite(s), p, 0.0)
        denom = p.sum(-1, keepdims=True)
        p = np.where(denom > 0, p / np.maximum(denom, 1e-30), 0.0)
    expected = np.einsum("bhqk,bkhd->bqhd", p, np.asarray(v))
    valid_q = allowed.any(-1)[:, 0]  # queries with at least one valid key
    np.testing.assert_allclose(
        np.asarray(out)[valid_q], expected[valid_q], atol=2e-5
    )


def test_decoder_flash_routing_matches_dense():
    """A flash-enabled decoder forward matches the dense path on real token
    positions, for both left- and right-padded rows."""
    import dataclasses
    import importlib

    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    cfg = ModelConfig(name="flash-test", vocab_size=256, hidden_size=64,
                      n_layers=2, n_heads=4, n_kv_heads=4,
                      intermediate_size=128, max_seq_len=256)
    params = decoder.init_params(cfg, jax.random.PRNGKey(0))

    rng = np.random.default_rng(0)
    S = 128
    toks = jnp.asarray(rng.integers(3, 256, (2, S)), jnp.int32)
    mask = np.ones((2, S), np.int32)
    mask[0, :17] = 0    # left padding on row 0
    mask[1, 120:] = 0   # right padding on row 1
    mask = jnp.asarray(mask)

    dense = decoder.forward(params, cfg, toks, mask)
    cfg_flash = dataclasses.replace(cfg, use_flash_attention=True)
    # The decoder's interpreter hook engages the flash route on CPU (the
    # backend gate otherwise keeps CPU dense, which would make this test
    # compare dense against itself).
    try:
        decoder.FLASH_INTERPRET_ON_CPU = True
        flash = decoder.forward(params, cfg_flash, toks, mask)
    finally:
        decoder.FLASH_INTERPRET_ON_CPU = False

    # Compare only real-token positions (pad rows are garbage on both
    # paths, by design).
    real = np.asarray(mask, bool)
    np.testing.assert_allclose(
        np.asarray(flash)[real], np.asarray(dense)[real], atol=3e-4
    )


def test_alibi_matches_dense_bias():
    """ALiBi in-kernel (VERDICT r1 #4: bloom can now use flash) vs the dense
    path's additive bias (decoder._causal_bias) — left-padded batch."""
    import math

    from lir_tpu.models.decoder import alibi_slopes

    B, S, H, hd = 2, 128, 4, 32
    q, k, v = _qkv(B=B, S=S, H=H, hd=hd, seed=7)
    mask = np.ones((B, S), np.int32)
    mask[0, :17] = 0  # left padding
    kpos = np.maximum(np.cumsum(mask, axis=1) - 1, 0)
    slopes = np.asarray(alibi_slopes(H))

    # Dense reference: softmax(qk/sqrt(d) + causal/key-mask bias + alibi) v,
    # causality on mask-aware positions (decoder._causal_bias semantics).
    scores = np.einsum("bshd,bthd->bhst", np.asarray(q), np.asarray(k))
    scores = scores / math.sqrt(hd)
    allowed = (kpos[:, None, :] <= kpos[:, :, None]) & (mask[:, None, :] > 0)
    # positional causality for the pad region mirrors the kernel's index rule
    idx = np.arange(S)
    allowed &= idx[None, None, :] <= idx[None, :, None]
    bias = np.where(allowed[:, None, :, :], 0.0, -1e30)
    bias = bias + slopes[None, :, None, None] * kpos[:, None, None, :]
    probs = np.exp(scores + bias - (scores + bias).max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    expected = np.einsum("bhst,bthd->bshd", probs, np.asarray(v))

    out = flash_attention(
        q, k, v, causal=True, key_mask=jnp.asarray(mask),
        alibi_slopes=jnp.asarray(slopes), key_positions=jnp.asarray(kpos),
        block_q=64, block_k=64, interpret=True)
    valid_q = mask.astype(bool)
    np.testing.assert_allclose(np.asarray(out)[valid_q],
                               expected[valid_q], atol=2e-5)


def test_alibi_requires_positions():
    q, k, v = _qkv(S=64, seed=8)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, alibi_slopes=jnp.ones((4,)), interpret=True)


def test_7b_presets_default_dense():
    """Presets run DENSE prefill by default — a measured decision, not an
    omission: on v5e, dense beats the flash kernel ~8% at every batch/seq
    that fits one chip (SCALE.md "flash vs dense", 2026-07-30). The kernel
    stays available behind the flag for long-S / large-HBM regimes."""
    from lir_tpu.models import registry

    for mk in (registry.llama2_7b, registry.mistral_7b, registry.qwen_7b,
               registry.baichuan2_7b, registry.falcon_7b, registry.bloom_7b1):
        assert not mk().use_flash_attention, mk().name
        # The flag itself must keep working per preset.
        import dataclasses
        assert dataclasses.replace(
            mk(), use_flash_attention=True).use_flash_attention


def test_decoder_alibi_flash_routing_matches_dense():
    """Decoder-level ALiBi wiring (slopes + mask-aware positions into the
    kernel) vs the dense additive-bias path, on a tiny bloom config with a
    left-padded batch."""
    import dataclasses

    from lir_tpu.models import decoder, registry

    cfg = registry.tiny("bloom")
    params = decoder.init_params(cfg, jax.random.PRNGKey(3))
    rng = np.random.default_rng(5)
    S = 128
    toks = jnp.asarray(rng.integers(3, cfg.vocab_size, (2, S)), jnp.int32)
    mask = np.ones((2, S), np.int32)
    mask[0, :9] = 0
    mask = jnp.asarray(mask)

    dense = decoder.forward(params, cfg, toks, mask)
    cfg_flash = dataclasses.replace(cfg, use_flash_attention=True)
    try:
        decoder.FLASH_INTERPRET_ON_CPU = True
        flash = decoder.forward(params, cfg_flash, toks, mask)
    finally:
        decoder.FLASH_INTERPRET_ON_CPU = False

    real = np.asarray(mask, bool)
    np.testing.assert_allclose(
        np.asarray(flash)[real], np.asarray(dense)[real], atol=3e-4)
