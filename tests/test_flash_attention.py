"""Pallas flash attention vs reference softmax attention (interpret mode on
CPU; the same kernel runs compiled on the real chip)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lir_tpu.ops import flash_attention
from lir_tpu.parallel import reference_attention


def _qkv(B=2, S=256, H=4, hd=64, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(size=(B, S, H, hd)), dtype) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [True, False])
def test_matches_reference(causal):
    q, k, v = _qkv()
    expected = reference_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=2e-5)


def test_multi_block_tiling():
    q, k, v = _qkv(S=512, seed=2)
    expected = reference_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, block_q=128, block_k=64, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=2e-5)


def test_short_sequence_block_clamp():
    q, k, v = _qkv(S=32, seed=3)
    out = flash_attention(q, k, v, interpret=True)  # blocks clamp to 32
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(reference_attention(q, k, v)), atol=2e-5)


def test_indivisible_seq_rejected():
    q, k, v = _qkv(S=100)
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
