"""Pallas flash attention vs reference softmax attention (interpret mode on
CPU; the same kernel runs compiled on the real chip)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lir_tpu.ops import flash_attention
from lir_tpu.parallel import reference_attention


def _qkv(B=2, S=256, H=4, hd=64, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(size=(B, S, H, hd)), dtype) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [True, False])
def test_matches_reference(causal):
    q, k, v = _qkv()
    expected = reference_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=2e-5)


def test_multi_block_tiling():
    q, k, v = _qkv(S=512, seed=2)
    expected = reference_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, block_q=128, block_k=64, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=2e-5)


def test_short_sequence_block_clamp():
    q, k, v = _qkv(S=32, seed=3)
    out = flash_attention(q, k, v, interpret=True)  # blocks clamp to 32
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(reference_attention(q, k, v)), atol=2e-5)


def test_indivisible_seq_rejected():
    q, k, v = _qkv(S=100)
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)


def test_decoder_flash_routing_matches_dense():
    """A flash-enabled decoder forward (left-padded batch) matches the dense
    path on the real token positions."""
    import dataclasses

    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    cfg = ModelConfig(name="flash-test", vocab_size=256, hidden_size=64,
                      n_layers=2, n_heads=4, n_kv_heads=4,
                      intermediate_size=128, max_seq_len=256)
    params = decoder.init_params(cfg, jax.random.PRNGKey(0))

    rng = np.random.default_rng(0)
    S = 128
    toks = jnp.asarray(rng.integers(3, 256, (2, S)), jnp.int32)
    mask = np.ones((2, S), np.int32)
    mask[0, :17] = 0  # left padding on row 0
    mask = jnp.asarray(mask)

    dense = decoder.forward(params, cfg, toks, mask)
    cfg_flash = dataclasses.replace(cfg, use_flash_attention=True)
    # Interpret mode so the kernel runs on CPU under the test harness.
    # (The package re-exports the function under the module's name, so
    # resolve the module itself for monkeypatching.)
    import importlib

    fa = importlib.import_module("lir_tpu.ops.flash_attention")
    orig = fa.flash_attention

    def interp(*args, **kwargs):
        kwargs["interpret"] = True
        return orig(*args, **kwargs)

    fa_flash = fa.flash_attention
    try:
        fa.flash_attention = interp
        import lir_tpu.models.decoder as dec
        flash = dec.forward(params, cfg_flash, toks, mask)
    finally:
        fa.flash_attention = fa_flash

    # Compare only real-token positions (pad rows are garbage on both
    # paths, by design).
    real = np.asarray(mask, bool)
    np.testing.assert_allclose(
        np.asarray(flash)[real], np.asarray(dense)[real], atol=3e-4
    )
