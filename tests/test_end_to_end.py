"""End-to-end pipeline: on-pod rephrasing -> perturbation sweep (with a
mid-run kill + resume) -> perturbation analysis artifacts — the complete
reference workflow (perturb_prompts.py + analyze_perturbation_results.py)
run hermetically on the tiny model + fake tokenizer."""

import jax
import numpy as np
import pandas as pd
import pytest
import torch

from lir_tpu.analysis.perturbation import analyze_model
from lir_tpu.backends.fake import FakeTokenizer
from lir_tpu.config import RuntimeConfig
from lir_tpu.data import schemas
from lir_tpu.data.prompts import LEGAL_PROMPTS
from lir_tpu.engine.rephrase import (
    load_or_generate_perturbations,
    rephraser_from_engine,
)
from lir_tpu.engine.runner import ScoringEngine
from lir_tpu.engine.sweep import run_perturbation_sweep
from lir_tpu.models.loader import config_from_hf, convert_decoder
from lir_tpu.utils.manifest import SweepManifest

pytestmark = pytest.mark.slow  # heavy lane: see tests/conftest.py


@pytest.fixture(scope="module")
def engine():
    import transformers as tf

    torch.manual_seed(0)
    hf = tf.LlamaForCausalLM(tf.LlamaConfig(
        vocab_size=FakeTokenizer.VOCAB, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=4, intermediate_size=128,
        max_position_embeddings=512, tie_word_embeddings=False)).eval()
    cfg, fam = config_from_hf(hf.config)
    params = convert_decoder(hf.state_dict(), cfg, fam)
    return ScoringEngine(
        params, cfg, FakeTokenizer(),
        RuntimeConfig(batch_size=8, max_new_tokens=6, max_seq_len=256),
    )


@pytest.fixture(scope="module")
def prompts():
    return LEGAL_PROMPTS[:2]


def test_full_pipeline(engine, prompts, tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("e2e")

    # Stage 1: on-pod rephrasing with the sampling decoder. The tiny random
    # model emits gibberish tokens; the parser still yields per-session
    # strings, which is all the downstream grid needs.
    cache = tmp_path / "perturbations.json"
    entries = load_or_generate_perturbations(
        cache, prompts, rephraser_from_engine(engine, max_new_tokens=8),
        jax.random.PRNGKey(0), sessions_per_prompt=2,
        rephrasings_per_session=2,
    )
    assert cache.exists()
    perturbations = [reph[:3] if reph else ["fallback variant"]
                     for _, reph in entries]

    # Stage 2: perturbation sweep -> D6 rows.
    results_path = tmp_path / "results.xlsx"
    rows = run_perturbation_sweep(
        engine, "tiny/model", prompts, perturbations, results_path,
        checkpoint_every=3,
    )
    n_cells = sum(1 + len(p) for p in perturbations)
    assert len(rows) == n_cells

    actual_path = schemas.resolve_results_path(results_path)
    df = schemas.read_results_frame(actual_path)
    assert list(df.columns) == list(schemas.PERTURBATION_COLUMNS)
    assert len(df) == n_cells
    assert np.isfinite(df["Token_1_Prob"]).all()
    # Weighted confidence exists when integer tokens exist in the vocab; the
    # fake tokenizer hashes digits to ids, so E[v] is defined.
    assert df["Weighted Confidence"].notna().all()

    # Stage 3: resume — nothing left to do.
    manifest = SweepManifest(
        actual_path.with_suffix(".manifest.jsonl"),
        ("model", "original_main", "rephrased_main"),
    )
    rows2 = run_perturbation_sweep(
        engine, "tiny/model", prompts, perturbations, results_path,
        manifest=manifest,
    )
    assert rows2 == []
    df_after = schemas.read_results_frame(actual_path)
    assert len(df_after) == n_cells  # no duplicate rows

    # Stage 4: a fresh model sweeps into the same artifact (append).
    rows3 = run_perturbation_sweep(
        engine, "tiny/model-2", prompts, perturbations, results_path,
    )
    assert len(rows3) == n_cells
    df_both = schemas.read_results_frame(actual_path)
    assert set(df_both["Model"]) == {"tiny/model", "tiny/model-2"}

    # Stage 5: the perturbation analysis runs on the swept artifact. The
    # sweep is far below the 100-row reference gate, so lower it by
    # concatenating the frame to itself.
    big = pd.concat([df_both] * 20, ignore_index=True)
    out = tmp_path / "analysis"
    res = analyze_model(
        big[big["Model"] == "tiny/model"], "tiny/model", out,
        prompts=prompts, n_simulations=1000, make_figures=False,
    )
    assert res["status"] == "ok"
    summary = pd.read_csv(out / "summary_statistics.csv")
    assert len(summary) == 2
    kappa = pd.read_csv(out / "cohens_kappa_results.csv")
    assert -1 <= kappa["Cohen's Kappa"].iloc[0] <= 1
