"""Reliability observatory + unified telemetry spine (lir_tpu/observe
+ engine/stream_stats.WindowedStreamSink + lint/metricsdrift).

Pins the ISSUE-11 contracts:

- the windowed accumulator lattice preserves EVERY single-window
  property per window: a single-window fold is bitwise the plain
  StreamSink, re-folds are idempotent, kill → checkpoint → resume →
  re-fold converges bitwise on the uninterrupted run, disjoint-shard
  window merges are order-free unions with overlap a hard error;
- the sentinel scheduler: clean windows raise zero alerts, a seeded
  fault-plan NaN injection on one model raises EXACTLY one alert
  carrying the drifted window's identity and the injected model,
  weight-cache residency changes force a sweep, per-window kappa is
  bitwise the analysis layer's within_group_kappa;
- the metrics registry: the snapshot JSON round-trips, STATS_SCHEMA
  covers every public field of every *Stats dataclass (the runtime
  mirror of the metrics-drift lint pass), and both servers expose a
  populated registry;
- tracing: spans record into the ring, export is valid Chrome
  trace-event JSON, and without a recorder spans are no-ops;
- the metrics-drift lint pass: seeded violations fire, the clean twin
  is silent.
"""

from __future__ import annotations

import json
import threading
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lir_tpu.backends.fake import FakeTokenizer
from lir_tpu.config import ObserveConfig, RuntimeConfig, ServeConfig
from lir_tpu.engine import stream_stats as stream_mod
from lir_tpu.engine.fleet import ModelFleet
from lir_tpu.engine.runner import ScoringEngine
from lir_tpu.faults.plan import FaultPlan, SiteSchedule
from lir_tpu.models import decoder, weights
from lir_tpu.models.registry import ModelConfig
from lir_tpu.observe import drift as drift_mod
from lir_tpu.observe import registry as reg_mod
from lir_tpu.observe import tracing
from lir_tpu.observe.sentinel import SentinelScheduler
from lir_tpu.serve import FleetScoringServer, ScoringServer, ServeRequest
from lir_tpu.stats import streaming
from lir_tpu.stats.kappa import within_group_kappa

FIXTURES = Path(__file__).parent / "lint_fixtures"

P, R = 3, 8     # lattice rows/cols for the windowed-sink tests


class _Cell:
    def __init__(self, p, r):
        self.prompt_idx = p
        self.rephrase_idx = r


def _readouts(rng, n):
    yes = rng.uniform(0.0, 0.6, n).astype(np.float32)
    no = rng.uniform(0.0, 0.4, n).astype(np.float32)
    wc = rng.uniform(0.0, 100.0, n).astype(np.float32)
    lp = -rng.uniform(0.1, 5.0, (n, 4)).astype(np.float32)
    return (jnp.asarray(yes), jnp.asarray(no), jnp.asarray(wc),
            jnp.asarray(lp))


def _dispatches(seed=3):
    """Deterministic fold batches covering the (P, R) grid."""
    rng = np.random.default_rng(seed)
    cells = [_Cell(p, r) for p in range(P) for r in range(R)]
    out = []
    for start in range(0, len(cells), 4):
        batch = cells[start:start + 4]
        out.append((batch, _readouts(rng, len(batch))))
    return out


def _accum_equal(a, b):
    np.testing.assert_array_equal(a.filled, b.filled)
    np.testing.assert_array_equal(a.rel, b.rel)
    np.testing.assert_array_equal(a.conf, b.conf)
    np.testing.assert_array_equal(a.dec, b.dec)


# ---------------------------------------------------------------------------
# WindowedStreamSink: the time axis preserves the lattice contracts
# ---------------------------------------------------------------------------


class TestWindowedSink:
    def test_single_window_bitwise_vs_plain_sink(self):
        plain = stream_mod.StreamSink(P, R, seed=7)
        windowed = stream_mod.WindowedStreamSink(P, R, seed=7)
        for batch, (yes, no, wc, lp) in _dispatches():
            plain.fold(yes, no, wc, lp, batch, topk=4)
            windowed.fold(0, yes, no, wc, lp, batch, topk=4)
        _accum_equal(plain.snapshot(), windowed.snapshot(0))

    def test_refold_is_idempotent_per_window(self):
        w = stream_mod.WindowedStreamSink(P, R)
        disp = _dispatches()
        for batch, arrs in disp:
            w.fold(5, *arrs, batch, topk=4)
        before = w.snapshot(5)
        for batch, arrs in disp[:2]:        # re-fold a prefix
            w.fold(5, *arrs, batch, topk=4)
        _accum_equal(before, w.snapshot(5))

    def test_checkpoint_resume_rejoins_uninterrupted_bitwise(self, tmp_path):
        disp = _dispatches()
        # Uninterrupted: everything folds across two windows.
        full = stream_mod.WindowedStreamSink(P, R)
        for i, (batch, arrs) in enumerate(disp):
            full.fold(i % 2, *arrs, batch, topk=4)
        # Killed: fold half, checkpoint, resume in a NEW sink, re-fold
        # the tail (overlapping one dispatch — idempotence absorbs it).
        a = stream_mod.WindowedStreamSink(P, R)
        for i, (batch, arrs) in enumerate(disp[:3]):
            a.fold(i % 2, *arrs, batch, topk=4)
        a.checkpoint(tmp_path)
        b = stream_mod.WindowedStreamSink(P, R)
        assert sorted(b.load(tmp_path)) == sorted(a.window_ids())
        for i, (batch, arrs) in enumerate(disp):
            if i >= 2:                      # one-dispatch overlap
                b.fold(i % 2, *arrs, batch, topk=4)
        for wid in full.window_ids():
            _accum_equal(full.snapshot(wid), b.snapshot(wid))

    def test_merge_window_union_and_overlap_error(self):
        disp = _dispatches()
        a = stream_mod.WindowedStreamSink(P, R)
        b = stream_mod.WindowedStreamSink(P, R)
        for batch, arrs in disp[:3]:
            a.fold(0, *arrs, batch, topk=4)
        for batch, arrs in disp[3:]:
            b.fold(0, *arrs, batch, topk=4)
        merged = stream_mod.WindowedStreamSink(P, R)
        merged.merge_window(0, a.snapshot(0))
        merged.merge_window(0, b.snapshot(0))
        full = stream_mod.WindowedStreamSink(P, R)
        for batch, arrs in disp:
            full.fold(0, *arrs, batch, topk=4)
        _accum_equal(full.snapshot(0), merged.snapshot(0))
        with pytest.raises(ValueError, match="overlap"):
            merged.merge_window(0, a.snapshot(0))

    def test_max_windows_drops_oldest(self):
        dropped = []
        w = stream_mod.WindowedStreamSink(
            P, R, max_windows=2, on_evict=dropped.append)
        batch, arrs = _dispatches()[0]
        for wid in (1, 2, 3):
            w.fold(wid, *arrs, batch, topk=4)
        assert w.window_ids() == [2, 3]
        assert dropped == [1]


# ---------------------------------------------------------------------------
# Tracing
# ---------------------------------------------------------------------------


class TestTracing:
    def _with_recorder(self, capacity=100):
        rec = tracing.TraceRecorder(capacity=capacity)
        prev = tracing.set_recorder(rec)
        return rec, prev

    def test_span_records_and_export_is_valid_chrome_json(self, tmp_path):
        rec, prev = self._with_recorder()
        try:
            with tracing.span("serve/dispatch", bucket=64, rows=3):
                with tracing.span("serve/readout"):
                    pass
            tracing.add_span("serve/queue_wait", 1.0, 2.5,
                             request_id="r1")
        finally:
            tracing.set_recorder(prev)
        assert len(rec) == 3
        out_path = tmp_path / "trace.json"
        doc = rec.export_chrome(out_path)
        reloaded = json.loads(out_path.read_text())
        assert reloaded == json.loads(json.dumps(doc))
        events = [e for e in reloaded["traceEvents"] if e["ph"] == "X"]
        assert {e["name"] for e in events} == {
            "serve/dispatch", "serve/readout", "serve/queue_wait"}
        for e in events:
            assert isinstance(e["ts"], (int, float))
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
            assert e["pid"] == 1 and e["tid"] >= 1
        qw = next(e for e in events if e["name"] == "serve/queue_wait")
        assert qw["args"]["request_id"] == "r1"
        assert abs(qw["dur"] - 1.5e6) < 1.0
        meta = [e for e in reloaded["traceEvents"] if e["ph"] == "M"]
        assert meta and all(e["name"] == "thread_name" for e in meta)

    def test_noop_without_recorder(self):
        assert tracing.get_recorder() is None
        with tracing.span("sweep/dispatch", rows=1):
            pass
        tracing.add_span("serve/queue_wait", 0.0, 1.0)
        assert tracing.get_recorder() is None

    def test_ring_bounds_and_counts_drops(self):
        rec, prev = self._with_recorder(capacity=4)
        try:
            for i in range(7):
                tracing.add_span(f"s{i}", 0.0, 1.0)
        finally:
            tracing.set_recorder(prev)
        assert len(rec) == 4 and rec.dropped == 3
        assert [e["name"] for e in rec.events()] == ["s3", "s4", "s5",
                                                     "s6"]
        assert rec.summary()["dropped"] == 3

    def test_spans_from_threads_get_distinct_tids(self):
        rec, prev = self._with_recorder()
        try:
            tracing.add_span("main-span", 0.0, 1.0)
            t = threading.Thread(
                target=lambda: tracing.add_span("worker-span", 0.0, 1.0),
                name="obs-worker")
            t.start()
            t.join()
        finally:
            tracing.set_recorder(prev)
        doc = rec.export_chrome()
        tids = {e["name"]: e["tid"] for e in doc["traceEvents"]
                if e["ph"] == "X"}
        assert tids["main-span"] != tids["worker-span"]


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------


class TestMetricsRegistry:
    def test_schema_covers_every_public_stats_field(self):
        """Runtime mirror of the metrics-drift lint pass."""
        import dataclasses

        from lir_tpu.utils import profiling

        stats_classes = [
            obj for name, obj in vars(profiling).items()
            if isinstance(obj, type) and name.endswith("Stats")
            and dataclasses.is_dataclass(obj)]
        assert stats_classes, "profiling lost its *Stats classes?"
        for cls in stats_classes:
            declared = reg_mod.STATS_SCHEMA.get(cls.__name__)
            assert declared is not None, cls.__name__
            public = {f.name for f in dataclasses.fields(cls)
                      if not f.name.startswith("_")}
            assert public <= set(declared), (
                cls.__name__, public - set(declared))
            assert set(declared) <= public, (
                "stale schema entries", cls.__name__,
                set(declared) - public)

    def test_snapshot_roundtrips_with_live_stats(self):
        from lir_tpu.utils.profiling import FleetStats, ServeStats

        reg = reg_mod.MetricsRegistry()
        sv, fl = ServeStats(), FleetStats()
        sv.count("submitted", 3)
        sv.record_latency(0.5)
        fl.count("swap_s_hidden", 1.25)
        reg.register("serve", sv)
        reg.register("fleet", fl)
        reg.counter("sentinel_sweeps", 2)
        reg.gauge("observatory_window", 7)
        snap = reg.snapshot(device_memory=True)
        assert json.loads(json.dumps(snap)) == snap
        assert snap["sources"]["serve"]["fields"]["submitted"] == 3
        assert snap["sources"]["serve"]["summary"]["submitted"] == 3
        assert snap["sources"]["fleet"]["fields"]["swap_s_hidden"] == 1.25
        assert snap["counters"]["sentinel_sweeps"] == 2
        assert snap["gauges"]["observatory_window"] == 7
        assert "device_memory" in snap

    def test_nan_gauges_sanitize_to_none(self):
        reg = reg_mod.MetricsRegistry()
        reg.gauge("bad", float("nan"))
        snap = reg.snapshot(device_memory=False)
        assert snap["gauges"]["bad"] is None
        json.dumps(snap, allow_nan=False)   # strict JSON survives


# ---------------------------------------------------------------------------
# metrics-drift lint pass
# ---------------------------------------------------------------------------


class TestMetricsDriftLint:
    def _findings(self, sub):
        from lir_tpu.lint.core import load_project, run_passes

        return run_passes(load_project(FIXTURES / "metricsdrift" / sub),
                          only=["metrics-drift"])

    def test_bad_fixture_fires_all_three_ways(self):
        fs = self._findings("bad")
        msgs = [f.message for f in fs]
        assert any("'misses' is missing" in m for m in msgs), msgs
        assert any("'OrphanStats' has no" in m for m in msgs), msgs
        assert any("stale schema entry" in m for m in msgs), msgs
        assert len(fs) == 3
        # Private fields owe nothing to the endpoint.
        assert not any("_private" in m for m in msgs)

    def test_ok_fixture_is_clean(self):
        assert self._findings("ok") == []


# ---------------------------------------------------------------------------
# The observatory: fleet + sentinel scheduler + drift
# ---------------------------------------------------------------------------

W = 100.0     # window seconds in the scheduler tests


def _tiny_cfg(name):
    return ModelConfig(name=name, vocab_size=FakeTokenizer.VOCAB,
                       hidden_size=32, n_layers=1, n_heads=2,
                       intermediate_size=64, max_seq_len=256)


def _tiny_engine(name, seed):
    return ScoringEngine(
        decoder.init_params(_tiny_cfg(name), jax.random.PRNGKey(seed)),
        _tiny_cfg(name), FakeTokenizer(),
        RuntimeConfig(batch_size=4, max_seq_len=256))


SENTINELS = [
    ServeRequest(binary_prompt=f"{q} Answer Yes or No.",
                 confidence_prompt=f"{q} Give a confidence 0-100.",
                 request_id=f"s{i}")
    for i, q in enumerate(["Is a cat an animal",
                           "Is rain considered weather"])]


@pytest.fixture()
def fleet_server():
    fleet = ModelFleet.from_engines(
        [(f"m{i}", _tiny_engine(f"m{i}", i)) for i in range(2)])
    server = FleetScoringServer(fleet,
                                ServeConfig(linger_s=0.005)).start()
    yield server
    server.stop()
    fleet.shutdown()


def _scheduler(server, **cfg_kw):
    now = {"t": W}
    cfg_kw.setdefault("sentinel_interval_s", 1.0)
    cfg_kw.setdefault("sentinel_window_s", W)
    cfg_kw.setdefault("drift_min_windows", 2)
    sched = SentinelScheduler(server, SENTINELS,
                              cfg=ObserveConfig(**cfg_kw),
                              clock=lambda: now["t"])
    server.attach_observatory(sched)
    return sched, now


class TestObservatory:
    def test_clean_windows_no_alerts_kappa_bitwise(self, fleet_server):
        sched, now = _scheduler(fleet_server)
        for w in (1, 2, 3):
            now["t"] = w * W + 1.0
            rec = sched.tick()
            assert rec is not None and rec["window"] == w
        now["t"] = 4 * W + 1.0
        sched.finalize_closed()
        obs = sched.summary()
        assert len(obs["windows"]) == 3
        assert obs["alerts"] == []
        # Deterministic greedy decode: identical clean windows.
        kappas = [w["kappa"]["kappa"] for w in obs["windows"]]
        assert kappas[0] == kappas[1] == kappas[2]
        # Per-window kappa bitwise vs the analysis layer on the same
        # contingency counts.
        for w in obs["windows"]:
            decisions, groups = [], []
            for g, (n, s) in enumerate(zip(w["counts"]["n_g"],
                                           w["counts"]["s_g"])):
                decisions += [1] * s + [0] * (n - s)
                groups += [g] * n
            ref = within_group_kappa(np.asarray(decisions, int),
                                     np.asarray(groups, int))
            assert w["kappa"]["kappa"] == ref["kappa"]
            assert (w["kappa"]["observed_agreement"]
                    == ref["observed_agreement"])

    def test_nan_injection_exactly_one_alert_right_window(
            self, fleet_server):
        sched, now = _scheduler(fleet_server)
        for w in (1, 2):
            now["t"] = w * W + 1.0
            assert sched.tick() is not None
        # Fault-plan NaN on model m0's dispatches during window 3: the
        # numerics guard quarantines its rows, decisions go invalid.
        plan = FaultPlan(seed=3, schedules={
            "dispatch": SiteSchedule(rate=1.0, kind="nan",
                                     nan_rows=(0, 1, 2, 3))})
        victim = fleet_server.batcher.batchers["m0"]
        orig = victim.score
        victim.score = plan.wrap("dispatch", victim.score)
        try:
            now["t"] = 3 * W + 1.0
            assert sched.tick() is not None
        finally:
            victim.score = orig
        now["t"] = 4 * W + 1.0
        sched.finalize_closed()
        obs = sched.summary()
        assert len(obs["alerts"]) == 1
        alert = obs["alerts"][0]
        assert alert["window"] == 3
        assert any(m["metric"] == "valid_frac" and m["model"] == "m0"
                   for m in alert["metrics"])
        assert obs["windows"][2]["drifted"] is True
        assert not obs["windows"][0].get("drifted")
        assert not obs["windows"][1].get("drifted")
        assert obs["windows"][2]["per_model"]["m0"]["valid_frac"] == 0.0
        assert plan.injected("dispatch") > 0

    def test_weight_cache_change_forces_sweep(self, fleet_server):
        sched, now = _scheduler(fleet_server)
        now["t"] = W + 1.0
        assert sched.tick() is not None
        assert sched.tick() is None        # interval not elapsed
        # A residency change (listener set by the scheduler) forces the
        # next tick to sweep regardless of the interval.
        fleet_server.fleet.cache._notify("evict", "m0")
        rec = sched.tick()
        assert rec is not None and rec["slot"] == 1

    def test_breaker_open_pauses_sweeps_and_rescores_on_recovery(
            self, fleet_server):
        """The elastic-router satellite: while the server's fronting
        breaker is OPEN (a replica failing over), sentinel sweeps pause
        — a capacity loss must not alert as model drift — and the
        first tick after recovery re-scores IMMEDIATELY, interval or
        not."""
        from lir_tpu.faults import CircuitBreaker

        sched, now = _scheduler(fleet_server)
        now["t"] = W + 1.0
        assert sched.tick() is not None
        # The router assigns its replica breaker onto the server; here
        # we drive one directly with a fake clock.
        t = {"b": 0.0}
        breaker = CircuitBreaker(failure_threshold=1, cooldown_s=5.0,
                                 clock=lambda: t["b"])
        fleet_server.breaker = breaker
        try:
            breaker.trip()
            now["t"] = W + 3.0           # interval elapsed: due...
            assert sched.tick() is None  # ...but paused (breaker open)
            now["t"] = W + 5.0
            assert sched.tick() is None
            assert (sched.summary()["sweeps_skipped_breaker_open"]
                    >= 2)
            n_before = sched.summary()["sweeps"]
            # Recovery: cooldown elapses (half-open admits traffic) —
            # the very next tick re-scores even though the last
            # ATTEMPTED sweep was recent.
            t["b"] = 6.0
            rec = sched.tick()
            assert rec is not None
            assert sched.summary()["sweeps"] == n_before + 1
        finally:
            fleet_server.breaker = None

    def test_window_capacity_skips_loudly(self, fleet_server):
        sched, now = _scheduler(fleet_server, max_sweeps_per_window=1)
        now["t"] = W + 1.0
        assert sched.tick() is not None
        sched.force()
        assert sched.tick() is None        # window full: skipped
        assert sched.summary()["sweeps_skipped_window_full"] == 1

    def test_stats_summary_and_metrics_endpoint(self, fleet_server):
        sched, now = _scheduler(fleet_server)
        now["t"] = W + 1.0
        sched.tick()
        now["t"] = 2 * W + 1.0
        sched.tick()
        sched.finalize_closed()
        out = fleet_server.stats_summary()
        assert "serve" in out and "fleet" in out
        assert len(out["observatory"]["windows"]) == 1
        snap = fleet_server.metrics.snapshot()
        assert snap["counters"]["sentinel_sweeps"] == 2
        assert snap["sources"]["serve"]["fields"]["completed"] > 0
        assert "model:m0:guard" in snap["sources"]
        assert json.loads(json.dumps(snap)) == snap

    def test_drift_detect_excludes_drifted_baseline(self):
        """A drifted window must not normalize into the baseline."""
        def entry(wid, kappa, drifted=False):
            e = {"window": wid,
                 "kappa": {"kappa": kappa},
                 "per_model": {}}
            if drifted:
                e["drifted"] = True
            return e

        history = [entry(1, 0.8), entry(2, 0.8),
                   entry(3, 0.0, drifted=True)]
        alert = drift_mod.detect_drift(history, entry(4, 0.0),
                                       sigma=3.0, min_baseline=2)
        assert alert is not None and alert["window"] == 4
        assert alert["n_baseline_windows"] == 2


# ---------------------------------------------------------------------------
# Single-model server metrics + weight-cache listener unit coverage
# ---------------------------------------------------------------------------


class TestServerTelemetry:
    def test_scoring_server_registry_sources(self):
        engine = _tiny_engine("solo", 0)
        server = ScoringServer(engine, "solo",
                               ServeConfig(linger_s=0.005)).start()
        try:
            fut = server.submit(ServeRequest(
                binary_prompt="Is a cat an animal Answer Yes or No.",
                confidence_prompt="Is a cat an animal Confidence 0-100.",
                request_id="q1"))
            assert fut.result(30.0).status == "ok"
        finally:
            server.stop()
        snap = server.metrics.snapshot()
        for name in ("serve", "serve_faults", "guard", "compile",
                     "faults"):
            assert name in snap["sources"], name
        assert snap["sources"]["serve"]["fields"]["completed"] == 1
        assert snap["sources"]["guard"]["summary"]["checked"] == {
            "serve": 1}

    def test_weight_cache_listener_fires_on_insert_and_evict(self):
        events = []
        p = decoder.init_params(_tiny_cfg("a"), jax.random.PRNGKey(0))
        nb = weights.tree_bytes(p)
        wc = weights.WeightCache(budget_bytes=nb + nb // 2)
        wc.add_listener(lambda ev, mid: events.append((ev, mid)))
        wc.insert("a", p, nb)
        wc.insert("b", decoder.init_params(_tiny_cfg("b"),
                                           jax.random.PRNGKey(1)), nb)
        assert ("insert", "a") in events
        assert ("evict", "a") in events
        assert ("insert", "b") in events
