"""Pipeline parallelism: GPipe-style stage execution over the 'pipe' mesh
axis must reproduce the dense forward exactly (parallel/pipeline.py).
Runs on the 8 virtual CPU devices (conftest.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lir_tpu.models import decoder
from lir_tpu.models.registry import tiny
from lir_tpu.parallel import pipeline

pytestmark = pytest.mark.slow  # heavy lane: see tests/conftest.py


@pytest.mark.parametrize("family,n_stages,n_micro", [
    ("llama", 2, 4),    # rotary + RMSNorm + gated MLP
    ("llama", 4, 2),    # deeper pipe than microbatches (bubble-heavy)
    ("bloom", 2, 2),    # ALiBi + embedding LayerNorm
    ("gpt2", 2, 4),     # learned positions + tied embeddings
])
def test_pipelined_forward_matches_dense(family, n_stages, n_micro):
    cfg = tiny(family)
    # tiny() has 2 layers; deepen so every stage holds >= 1 layer.
    import dataclasses
    cfg = dataclasses.replace(cfg, n_layers=4)
    params = decoder.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(5)
    B, S = 8, 12
    toks = rng.integers(3, cfg.vocab_size, (B, S)).astype(np.int32)
    mask = np.ones((B, S), np.int32)
    # Left padding on some rows: position bookkeeping must survive PP.
    toks[1, :4] = 0
    mask[1, :4] = 0
    toks[5, :2] = 0
    mask[5, :2] = 0

    dense = decoder.forward(params, cfg, jnp.asarray(toks), jnp.asarray(mask))

    mesh = pipeline.build_pipe_mesh(n_stages)
    placed = pipeline.shard_params_pipelined(params, cfg, mesh)
    # Layer stacks really split across stages.
    wq = placed["layers"]["wq"]
    assert wq.sharding.shard_shape(wq.shape)[0] == cfg.n_layers // n_stages
    out = pipeline.forward_pipelined(placed, cfg, jnp.asarray(toks),
                                     jnp.asarray(mask), mesh=mesh,
                                     n_micro=n_micro)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               atol=1e-4, rtol=1e-4)


def test_pipelined_validation_errors():
    import dataclasses
    cfg = dataclasses.replace(tiny("llama"), n_layers=4)
    params = decoder.init_params(cfg, jax.random.PRNGKey(0))
    mesh = pipeline.build_pipe_mesh(2)
    placed = pipeline.shard_params_pipelined(params, cfg, mesh)
    toks = jnp.zeros((6, 8), jnp.int32)
    with pytest.raises(ValueError, match="microbatches"):
        pipeline.forward_pipelined(placed, cfg, toks, mesh=mesh, n_micro=4)
    cfg3 = dataclasses.replace(cfg, n_layers=3)
    with pytest.raises(ValueError, match="pipeline stages"):
        pipeline.shard_params_pipelined(
            decoder.init_params(cfg3, jax.random.PRNGKey(0)), cfg3, mesh)


def test_pipelined_scoring_readout_matches():
    """The capture scoring path (C13 readout over full logits) through the
    pipelined forward equals the dense path — PP is usable for scoring
    prefill, not just raw logits."""
    import dataclasses
    from lir_tpu.engine import score

    cfg = dataclasses.replace(tiny("llama"), n_layers=4)
    params = decoder.init_params(cfg, jax.random.PRNGKey(1))
    rng = np.random.default_rng(7)
    toks = jnp.asarray(rng.integers(3, cfg.vocab_size, (4, 10)), jnp.int32)
    mask = jnp.ones_like(toks)
    mesh = pipeline.build_pipe_mesh(4)
    placed = pipeline.shard_params_pipelined(params, cfg, mesh)
    logits_pp = pipeline.forward_pipelined(placed, cfg, toks, mask,
                                           mesh=mesh, n_micro=2)
    logits_dense = decoder.forward(params, cfg, toks, mask)
    # Last-position softmax (what a scoring readout consumes).
    p_pp = jax.nn.softmax(logits_pp[:, -1], axis=-1)
    p_dn = jax.nn.softmax(logits_dense[:, -1], axis=-1)
    np.testing.assert_allclose(np.asarray(p_pp), np.asarray(p_dn),
                               atol=1e-5)


def test_pipelined_forward_int8_quant_tree():
    """QuantTensor layer stacks shard their leading (layer) axis across
    stages like dense ones (payload + per-channel scales both lead with
    L); pipelined int8 forward equals the unsharded int8 forward."""
    import dataclasses

    from lir_tpu.models import quant

    cfg = dataclasses.replace(tiny("llama"), n_layers=4)
    params = quant.quantize_decoder_params(
        decoder.init_params(cfg, jax.random.PRNGKey(2)))
    rng = np.random.default_rng(9)
    toks = jnp.asarray(rng.integers(3, cfg.vocab_size, (4, 8)), jnp.int32)
    mask = jnp.ones_like(toks)
    dense = decoder.forward(params, cfg, toks, mask)

    mesh = pipeline.build_pipe_mesh(2)
    placed = pipeline.shard_params_pipelined(params, cfg, mesh)
    wq = placed["layers"]["wq"]
    assert wq.q.sharding.shard_shape(wq.q.shape)[0] == cfg.n_layers // 2
    out = pipeline.forward_pipelined(placed, cfg, toks, mask, mesh=mesh,
                                     n_micro=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               atol=1e-4, rtol=1e-4)


def test_pipelined_forward_jits_and_single_row_microbatches():
    """forward_pipelined composes with an outer jax.jit (the engine would
    call it from jitted scoring code) and survives Bm=1 microbatches."""
    import dataclasses
    import functools

    cfg = dataclasses.replace(tiny("llama"), n_layers=4)
    params = decoder.init_params(cfg, jax.random.PRNGKey(3))
    rng = np.random.default_rng(11)
    toks = jnp.asarray(rng.integers(3, cfg.vocab_size, (4, 6)), jnp.int32)
    mask = jnp.ones_like(toks)
    mesh = pipeline.build_pipe_mesh(2)
    placed = pipeline.shard_params_pipelined(params, cfg, mesh)

    f = jax.jit(functools.partial(pipeline.forward_pipelined, cfg=cfg,
                                  mesh=mesh, n_micro=4))   # Bm = 1
    out = f(placed, tokens=toks, attn_mask=mask)
    dense = decoder.forward(params, cfg, toks, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               atol=1e-4, rtol=1e-4)
