"""Shared-prefix cascade prefill (ops/cascade_prefill + the cascade
dispatch path): the Hydragen-style prefix/suffix split behind the 36%
MFU plateau fix.

Parity contracts pinned here:
- ops/lse.merge_partials is BITWISE the inline log-sum-exp combine it
  was lifted out of flash_decode's kernels (the refactor changed no op);
- cascade_attention == dense softmax over trunk + window keys at every
  ladder trunk extent (including non-power-of-two trunks), under GQA /
  MQA, ALiBi, masked (pad) remainder rows, and fully-masked rows that
  defer entirely to the prefix leg — Pallas interpreter on CPU, the
  same kernel that runs compiled on the chip;
- the in-kernel int8 QK^T prefix leg == the dequantized reference built
  from models/quant.dynamic_quant's own rule;
- the cold cascade shared dispatch is argmax-identical (ints exact,
  floats to tolerance — the PR-7 bar) to the dense shared path, and the
  paged-warm trunk resume is BITWISE the unpaged cold cascade;
- scheduler pricing: bucket_cost's cascade discount and the watchdog's
  cascade seed spread, with defaults byte-identical to the old model;
- CascadeStats mirrors STATS_SCHEMA (the metrics-drift contract).
"""

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lir_tpu.engine import generate
from lir_tpu.models import decoder, quant
from lir_tpu.models.registry import ModelConfig
from lir_tpu.ops.cascade_prefill import (DEFAULT_BLOCK_N, cascade_attention,
                                         pick_block_n)
from lir_tpu.ops.lse import merge_partials


def _tiny_cfg(**kw) -> ModelConfig:
    base = dict(name="cascade-tiny", vocab_size=128, hidden_size=32,
                n_layers=2, n_heads=4, n_kv_heads=2, intermediate_size=64,
                max_seq_len=512)
    base.update(kw)
    return ModelConfig(**base)


# ---------------------------------------------------------------------------
# Satellite 1: the lifted log-sum-exp partial merge
# ---------------------------------------------------------------------------

def _inline_merge_reference(o_p, m_p, l_p, axis):
    """The EXACT op sequence flash_decode._decode_kernel carried inline
    before the helper was lifted — kept verbatim here so any drift in
    merge_partials (a reorder, a different epsilon, a dtype change)
    breaks this test bitwise."""
    m = m_p.max(axis=axis)
    w = jnp.where(jnp.isfinite(m_p),
                  jnp.exp(m_p - jnp.expand_dims(m, axis)), 0.0)
    l = (w * l_p).sum(axis=axis)
    o = (w[..., None] * o_p).sum(axis=axis)
    return o / jnp.maximum(l, 1e-30)[..., None]


class TestMergePartials:
    @pytest.mark.parametrize("seed", [0, 1])
    def test_bitwise_equals_pre_refactor_inline(self, seed):
        """Flash-decode-shaped partials: (B, H, splits, ...) with axis=2,
        including all-masked splits (m = -inf, l = 0)."""
        rng = np.random.default_rng(seed)
        B, H, S, hd = 3, 4, 5, 16
        o_p = jnp.asarray(rng.normal(size=(B, H, S, hd)), jnp.float32)
        m_p = np.asarray(rng.normal(size=(B, H, S)), np.float32)
        l_p = np.abs(rng.normal(size=(B, H, S))).astype(np.float32) + 0.1
        m_p[0, :, 2] = -np.inf        # an empty split
        l_p[0, :, 2] = 0.0
        m_p[1, 0, :] = -np.inf        # a fully-empty query row
        l_p[1, 0, :] = 0.0
        got = merge_partials(o_p, jnp.asarray(m_p), jnp.asarray(l_p), axis=2)
        exp = _inline_merge_reference(o_p, jnp.asarray(m_p),
                                      jnp.asarray(l_p), axis=2)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(exp))

    def test_cascade_shaped_axis(self):
        """The cascade merge stacks two legs on axis=2 of a 5D/4D pair —
        same helper, same bitwise contract."""
        rng = np.random.default_rng(2)
        B, K, R, G, hd = 2, 2, 3, 2, 8
        o_p = jnp.asarray(rng.normal(size=(B, K, 2, R, G, hd)), jnp.float32)
        m_p = jnp.asarray(rng.normal(size=(B, K, 2, R, G)), jnp.float32)
        l_p = jnp.asarray(np.abs(rng.normal(size=(B, K, 2, R, G))) + 0.1,
                          jnp.float32)
        got = merge_partials(o_p, m_p, l_p, axis=2)
        exp = _inline_merge_reference(o_p, m_p, l_p, axis=2)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(exp))

    def test_flash_decode_output_unchanged(self):
        """The refactored flash_decode still matches the dense decode
        reference (the kernel's merge now routes through the helper —
        the same contract tests/test_kernels.py pins per extent)."""
        from lir_tpu.ops import flash_decode

        rng = np.random.default_rng(3)
        B, H, K, hd, T = 3, 4, 2, 16, 128
        G = H // K
        q = jnp.asarray(rng.normal(size=(B, H, hd)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(K, T, B, hd)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(K, T, B, hd)), jnp.float32)
        mask = np.zeros((B, T), np.int32)
        mask[0, :40], mask[1, 10:90], mask[2, :] = 1, 1, 1
        key_pos = np.maximum(np.cumsum(mask, -1) - 1, 0)
        q_pos = np.asarray([mask[r].sum() - 1 for r in range(B)], np.int32)
        qg = q.reshape(B, 1, K, G, hd)
        scores = (jnp.einsum("bskgd,ktbd->bkgst", qg, k)
                  .reshape(B, H, 1, T).astype(jnp.float32)
                  / math.sqrt(hd))
        allowed = ((key_pos[:, None, :] <= q_pos[:, None, None])
                   & (mask[:, None, :] > 0))
        bias = jnp.where(jnp.asarray(allowed), 0.0,
                         jnp.float32(-1e9))[:, None, :, :]
        probs = jax.nn.softmax(scores + bias, axis=-1)
        exp = jnp.einsum("bkgst,ktbd->bskgd",
                         probs.reshape(B, K, G, 1, T), v).reshape(B, H, hd)
        got = flash_decode(q, k, v, jnp.asarray(q_pos), jnp.asarray(mask),
                           jnp.asarray(key_pos), interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   atol=2e-5)


# ---------------------------------------------------------------------------
# The cascade kernel vs a dense full-softmax reference
# ---------------------------------------------------------------------------

def _dense_cascade_reference(q, sfx_k, sfx_v, trunk_k, trunk_v, sfx_mask,
                             q_pos, slopes=None):
    """Plain softmax over trunk ++ window keys per row: trunk slot t is
    position t and always valid; window keys carry the row's mask and
    the causal key-pos <= query-pos rule (keys ARE the queries' slots);
    ALiBi biases by key position (decoder._causal_bias convention)."""
    B, R, H, hd = q.shape
    K, Tt = trunk_k.shape[0], trunk_k.shape[1]
    G = H // K
    tk = jnp.broadcast_to(trunk_k[None], (B, K, Tt, hd))
    k_all = jnp.concatenate([tk, sfx_k.transpose(0, 2, 1, 3)], axis=2)
    v_all = jnp.concatenate(
        [jnp.broadcast_to(trunk_v[None], (B, K, Tt, hd)),
         sfx_v.transpose(0, 2, 1, 3)], axis=2)
    key_pos = jnp.concatenate(
        [jnp.broadcast_to(jnp.arange(Tt, dtype=jnp.float32)[None], (B, Tt)),
         q_pos.astype(jnp.float32)], axis=1)                   # (B, Tt+R)
    key_ok = jnp.concatenate(
        [jnp.ones((B, Tt), bool), sfx_mask > 0], axis=1)
    qg = (q.reshape(B, R, K, G, hd).astype(jnp.float32)
          / math.sqrt(hd))
    s = jnp.einsum("brkgd,bktd->bkrgt", qg, k_all.astype(jnp.float32))
    if slopes is not None:
        sl = jnp.asarray(slopes, jnp.float32).reshape(K, G)
        s = s + (sl[None, :, None, :, None]
                 * key_pos[:, None, None, None, :])
    allowed = (key_ok[:, None, :]
               & (key_pos[:, None, :] <= q_pos.astype(jnp.float32)[:, :, None]))
    s = jnp.where(allowed[:, None, :, None, :], s, -jnp.inf)
    m = s.max(axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    p = p / jnp.maximum(p.sum(axis=-1, keepdims=True), 1e-30)
    o = jnp.einsum("bkrgt,bktd->bkrgd", p, v_all.astype(jnp.float32))
    return o.transpose(0, 2, 1, 3, 4).reshape(B, R, H, hd)


class TestCascadeKernel:
    def _case(self, Tt, R=8, seed=0, B=2, H=4, K=2, hd=16):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.normal(size=(B, R, H, hd)), jnp.float32)
        sk = jnp.asarray(rng.normal(size=(B, R, K, hd)), jnp.float32)
        sv = jnp.asarray(rng.normal(size=(B, R, K, hd)), jnp.float32)
        tk = jnp.asarray(rng.normal(size=(K, Tt, hd)), jnp.float32)
        tv = jnp.asarray(rng.normal(size=(K, Tt, hd)), jnp.float32)
        mask = np.ones((B, R), np.int32)
        mask[0, R // 2:] = 0           # right-padded remainder row
        if B > 2:
            mask[2, :] = 0             # whole prefix IS the trunk
        q_pos = Tt + np.maximum(np.cumsum(mask, -1) - 1, 0)
        return q, sk, sv, tk, tv, jnp.asarray(mask), jnp.asarray(q_pos)

    @pytest.mark.parametrize("Tt", [16, 48, 64, 100, 128])
    def test_matches_dense_per_trunk_extent(self, Tt):
        """Every ladder trunk extent, including the non-power-of-two
        ones (100 is not 8-aligned on the key axis — the whole-trunk
        block must still lower in interpret mode)."""
        case = self._case(Tt)
        exp = _dense_cascade_reference(*case)
        got = cascade_attention(*case, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   atol=2e-5)

    def test_fully_masked_row_defers_to_prefix_leg(self):
        """A row whose whole prefix is the trunk has an all-masked
        remainder window: the suffix leg contributes m=-inf/l=0 and the
        merged output is pure trunk attention (finite everywhere)."""
        case = self._case(32, B=3, seed=1)
        got = cascade_attention(*case, interpret=True)
        exp = _dense_cascade_reference(*case)
        assert np.isfinite(np.asarray(got)).all()
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   atol=2e-5)

    def test_mqa_grouping(self):
        case = self._case(64, seed=2, H=4, K=1)
        exp = _dense_cascade_reference(*case)
        got = cascade_attention(*case, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   atol=2e-5)

    def test_alibi_slopes(self):
        q, sk, sv, tk, tv, mask, q_pos = self._case(48, seed=3, H=4, K=4)
        slopes = decoder.alibi_slopes(4)
        exp = _dense_cascade_reference(q, sk, sv, tk, tv, mask, q_pos,
                                       slopes=slopes)
        got = cascade_attention(q, sk, sv, tk, tv, mask, q_pos,
                                alibi_slopes=slopes, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   atol=2e-5)

    def test_int8_qk_matches_dequant_reference(self):
        """The in-kernel int8 prefix leg == a reference whose trunk
        scores are computed from models/quant.dynamic_quant's OWN
        dequantized q/k (s8 x s8 accumulation is exact below 2^24, so
        only the score scales round)."""
        q, sk, sv, tk, tv, mask, q_pos = self._case(64, seed=4)
        B, R, H, hd = q.shape
        K, Tt = tk.shape[0], tk.shape[1]
        G = H // K
        qf = (q.reshape(B, R, K, G, hd).transpose(2, 0, 1, 3, 4)
              .reshape(K, B * R * G, hd))
        deq_q, deq_k = [], []
        for h in range(K):
            qq, qs = quant.dynamic_quant(qf[h])
            kq, ks = quant.dynamic_quant(tk[h])
            deq_q.append(qq.astype(jnp.float32) * qs[:, None])
            deq_k.append(kq.astype(jnp.float32) * ks[:, None])
        dq = (jnp.stack(deq_q).reshape(K, B, R, G, hd)
              .transpose(1, 2, 0, 3, 4).reshape(B, R, H, hd))
        dk = jnp.stack(deq_k)
        exp = _dense_cascade_reference(dq, sk, sv, dk, tv, mask, q_pos)
        # ... except the suffix leg must use the UNquantized q — rebuild
        # the reference by merging the int8 trunk leg with the fp32
        # suffix leg via the same exact-split identity.
        from lir_tpu.ops.cascade_prefill import (_prefix_partials,
                                                 _suffix_partials)
        o_t, m_t, l_t = _prefix_partials(dq, dk, tv, None, False,
                                         DEFAULT_BLOCK_N, True)
        o_s, m_s, l_s = _suffix_partials(q, sk, sv, mask, q_pos, None)
        exp = merge_partials(jnp.stack([o_t, o_s], axis=2),
                             jnp.stack([m_t, m_s], axis=2),
                             jnp.stack([l_t, l_s], axis=2), axis=2)
        exp = (exp.transpose(0, 2, 1, 3, 4).reshape(B, R, H, hd))
        got = cascade_attention(q, sk, sv, tk, tv, mask, q_pos,
                                int8_qk=True, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   rtol=1e-5, atol=1e-5)

    def test_pick_block_n(self):
        assert pick_block_n(1000) == DEFAULT_BLOCK_N
        assert pick_block_n(128) == 128
        assert pick_block_n(60) == 64       # sublane-rounded small N
        assert pick_block_n(3) == 8

    def test_block_tail_padding(self):
        """N not a block multiple: pad rows compute garbage partials
        that are sliced off — output equals the dense reference."""
        case = self._case(32, R=5, B=3, seed=5)   # N = 3*5*2 = 30
        exp = _dense_cascade_reference(*case)
        got = cascade_attention(*case, block_n=16, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   atol=2e-5)


# ---------------------------------------------------------------------------
# The cascade shared dispatch vs the dense shared path (generate level)
# ---------------------------------------------------------------------------

def _assert_fused_out_close(a, b, atol=1e-5):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        x, y = np.asarray(x), np.asarray(y)
        if np.issubdtype(x.dtype, np.floating):
            np.testing.assert_allclose(x, y, atol=atol)
        else:
            np.testing.assert_array_equal(x, y)


def _shared_trunk_dispatch(seed, B=3, S=48, trunk=32, SA=4, SB=8, V=128):
    """Shared-trunk inputs: every row's prefix leads with the same
    ``trunk`` tokens (right-padded canonical layout), tails differ."""
    rng = np.random.default_rng(seed)
    prefix = rng.integers(3, V, (B, S)).astype(np.int32)
    prefix[:, :trunk] = prefix[0, :trunk]
    pm = np.ones((B, S), np.int32)
    pm[0, S - 6:] = 0                      # a short row (still > trunk)
    sa = jnp.asarray(rng.integers(3, V, (B, SA)), jnp.int32)
    sam = np.ones((B, SA), np.int32)
    sam[min(1, B - 1), 2:] = 0
    sb = jnp.asarray(rng.integers(3, V, (B, SB)), jnp.int32)
    sbm = np.ones((B, SB), np.int32)
    sbm[B - 1, 5:] = 0
    return (jnp.asarray(prefix), jnp.asarray(pm), sa, jnp.asarray(sam),
            sb, jnp.asarray(sbm))


class TestCascadeSharedDecode:
    def _readout(self, B=3):
        yes = jnp.asarray([5, 6, 7][:B], jnp.int32)
        no = jnp.asarray([9, 10, 11][:B], jnp.int32)
        d_ids = jnp.arange(10, 30, dtype=jnp.int32)
        d_vals = jnp.arange(0.0, 20.0, dtype=jnp.float32)
        return yes, no, d_ids, d_vals

    def test_cold_cascade_argmax_identical_to_dense(self):
        """The PR-7 parity bar: ints (generated tokens, top-2/top-k ids)
        exact, interior floats to tolerance, vs the dense shared path."""
        cfg = _tiny_cfg()
        params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                     dtype=jnp.float32)
        d = _shared_trunk_dispatch(1)
        ro = self._readout()
        na, nb = 3, 5
        dense = generate.greedy_decode_fused_shared(
            params, cfg, *d, *ro, max_new_a=na, max_new_b=nb)
        casc = generate.greedy_decode_fused_shared_cascade(
            params, cfg, *d, *ro, max_new_a=na, max_new_b=nb,
            trunk_len=32)
        _assert_fused_out_close(dense, casc, atol=5e-5)

    def test_nonquantum_trunk_and_tiny_rows(self):
        """A non-power-of-two trunk extent through the full dispatch."""
        cfg = _tiny_cfg(name="cascade-tiny-48")
        params = decoder.init_params(cfg, jax.random.PRNGKey(1),
                                     dtype=jnp.float32)
        d = _shared_trunk_dispatch(2, B=2, S=64, trunk=48)
        ro = self._readout(B=2)
        dense = generate.greedy_decode_fused_shared(
            params, cfg, *d, *ro, max_new_a=2, max_new_b=3)
        casc = generate.greedy_decode_fused_shared_cascade(
            params, cfg, *d, *ro, max_new_a=2, max_new_b=3, trunk_len=48)
        _assert_fused_out_close(dense, casc, atol=5e-5)

    def test_early_stop_parity(self):
        """Armed stop masks ride the cascade tail exactly as the dense
        branch code (the tail IS the dense path's own branch code)."""
        cfg = _tiny_cfg(name="cascade-tiny-stop")
        params = decoder.init_params(cfg, jax.random.PRNGKey(2),
                                     dtype=jnp.float32)
        d = _shared_trunk_dispatch(3)
        yes, no, d_ids, d_vals = self._readout()
        stop = jnp.zeros((128,), jnp.int32).at[jnp.arange(10, 30)].set(1)
        eos = jnp.int32(2)
        kw = dict(max_new_a=3, max_new_b=5, stop_mask_b=stop,
                  stop_mask_a=jnp.zeros((128,), jnp.int32), eos_id=eos)
        dense = generate.greedy_decode_fused_shared(
            params, cfg, *d, yes, no, d_ids, d_vals, **kw)
        casc = generate.greedy_decode_fused_shared_cascade(
            params, cfg, *d, yes, no, d_ids, d_vals, trunk_len=32, **kw)
        _assert_fused_out_close(dense, casc, atol=5e-5)

    def test_int8_qk_argmax_identical(self):
        """int8 QK^T on the trunk leg: argmax fields exact vs the fp32
        cascade, interior floats tolerance-bound (the PR-7 int8 bar)."""
        cfg = _tiny_cfg(name="cascade-tiny-i8")
        params = decoder.init_params(cfg, jax.random.PRNGKey(3),
                                     dtype=jnp.float32)
        d = _shared_trunk_dispatch(4)
        ro = self._readout()
        f32 = generate.greedy_decode_fused_shared_cascade(
            params, cfg, *d, *ro, max_new_a=3, max_new_b=5, trunk_len=32)
        i8 = generate.greedy_decode_fused_shared_cascade(
            params, cfg, *d, *ro, max_new_a=3, max_new_b=5, trunk_len=32,
            int8_qk=True)
        for x, y in zip(jax.tree.leaves(f32[0]) + jax.tree.leaves(f32[1]),
                        jax.tree.leaves(i8[0]) + jax.tree.leaves(i8[1])):
            x, y = np.asarray(x), np.asarray(y)
            if np.issubdtype(x.dtype, np.floating):
                np.testing.assert_allclose(x, y, atol=0.05)
            else:
                np.testing.assert_array_equal(x, y)


# ---------------------------------------------------------------------------
# Engine routing: eligibility, dense fallback, paged-warm bitwise
# ---------------------------------------------------------------------------

@pytest.fixture()
def cascade_interpret():
    """Arm the tier-1 interpret hook (mirrors fused_decode_interpret)."""
    old = decoder.CASCADE_INTERPRET_ON_CPU
    decoder.CASCADE_INTERPRET_ON_CPU = True
    yield
    decoder.CASCADE_INTERPRET_ON_CPU = old


def _fake_engine(rt=None, cfg_kw=None, **eng_kw):
    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.engine.runner import ScoringEngine

    cfg = _tiny_cfg(vocab_size=FakeTokenizer.VOCAB, **(cfg_kw or {}))
    params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                 dtype=jnp.float32)
    rt = rt or RuntimeConfig(batch_size=4)
    return ScoringEngine(params, cfg, FakeTokenizer(), rt, **eng_kw)


def _trunk_rows(B=4, trunk=32, tail=8, seed=0):
    rng = np.random.default_rng(seed)
    head = [int(x) for x in rng.integers(3, 200, trunk)]
    rows = [head + [int(x) for x in rng.integers(3, 200, tail - (r % 3))]
            for r in range(B)]
    return rows


class TestEngineRouting:
    def test_gates(self, cascade_interpret):
        from lir_tpu.config import RuntimeConfig

        eng = _fake_engine()
        assert eng.cascade_supported()
        off = _fake_engine(rt=RuntimeConfig(batch_size=4,
                                            cascade_prefill=False))
        assert not off.cascade_supported()
        assert off.cascade_trunk_for(_trunk_rows(), 4, 64) == 0

    def test_gate_needs_interpret_hook_on_cpu(self):
        eng = _fake_engine()
        assert not eng.cascade_supported()     # hook not armed, CPU

    def test_trunk_derivation(self, cascade_interpret):
        eng = _fake_engine()
        rows = _trunk_rows(trunk=39)           # LCP 39 -> snaps to 32
        assert eng.cascade_trunk_for(rows, 4, 64) == 32
        assert eng.cascade_trunk_for(rows, 1, 64) == 0      # min_rows
        short = _trunk_rows(trunk=20)          # below min_trunk
        assert eng.cascade_trunk_for(short, 4, 64) == 0
        # trunk must stay strictly inside the bucket
        ident = [list(range(3, 67))] * 4
        t = eng.cascade_trunk_for(ident, 4, 64)
        assert 0 < t < 64 and t % 16 == 0

    def test_dispatch_matches_dense_and_counts(self, cascade_interpret):
        from lir_tpu.config import RuntimeConfig

        rows = _trunk_rows()
        conf = [r + [7, 8] for r in rows]
        bins = [r + [5, 6] for r in rows]
        t1 = np.asarray([5] * 4, np.int32)
        t2 = np.asarray([9] * 4, np.int32)

        def dispatch(eng):
            return eng.decode_fused_shared(
                [""] * 4, [""] * 4, t1, t2, new_tokens=3, conf_tokens=4,
                pretokenized_a=bins, pretokenized_b=conf, bucket=64,
                sfx_buckets_ab=(8, 8), reuse_cache=True, n_real=4)

        on = _fake_engine()
        f_on = dispatch(on)
        assert on.cascade_stats.cascade_dispatches == 1
        assert on.cascade_stats.trunk_rows_deduped == 3
        assert on.cascade_stats.prefix_flops_saved > 0
        off = _fake_engine(rt=RuntimeConfig(batch_size=4,
                                            cascade_prefill=False))
        f_off = dispatch(off)
        assert off.cascade_stats.cascade_dispatches == 0
        for a, b in zip(f_on, f_off):
            _assert_fused_out_close(a, b, atol=5e-5)

    def test_ineligible_dispatch_counts_dense_fallback(self,
                                                       cascade_interpret):
        eng = _fake_engine()
        rows = [[int(x) for x in np.random.default_rng(r).integers(
            3, 200, 40)] for r in range(4)]    # no shared trunk
        t = np.asarray([5] * 4, np.int32)
        eng.decode_fused_shared(
            [""] * 4, [""] * 4, t, t, new_tokens=2, conf_tokens=2,
            pretokenized_a=[r + [5] for r in rows],
            pretokenized_b=[r + [7] for r in rows], bucket=64,
            sfx_buckets_ab=(8, 8), reuse_cache=True, n_real=4)
        assert eng.cascade_stats.cascade_dispatches == 0
        assert eng.cascade_stats.dense_fallbacks == 1

    def test_paged_warm_trunk_bitwise_equals_cold(self, cascade_interpret):
        """Dispatch twice with the same shared trunk on a prefix-cached
        engine: the second gathers the trunk from the radix page pool
        and its payloads are BITWISE the cold dispatch's."""
        from lir_tpu.config import RuntimeConfig

        eng = _fake_engine(rt=RuntimeConfig(batch_size=4,
                                            prefix_cache=True))
        assert eng.prefix_cache is not None
        rows = _trunk_rows(trunk=64, seed=7)
        bins = [r + [5, 6] for r in rows]
        conf = [r + [7, 8] for r in rows]
        t1 = np.asarray([5] * 4, np.int32)
        t2 = np.asarray([9] * 4, np.int32)

        def dispatch():
            return eng.decode_fused_shared(
                [""] * 4, [""] * 4, t1, t2, new_tokens=3, conf_tokens=4,
                pretokenized_a=bins, pretokenized_b=conf, bucket=128,
                sfx_buckets_ab=(8, 8), reuse_cache=True, n_real=4)

        cold = dispatch()
        assert eng.cascade_stats.cascade_dispatches == 1
        warm = dispatch()
        assert eng.cascade_stats.cascade_dispatches == 2
        assert eng.prefix_stats.hits >= 1
        for a, b in zip(cold, warm):
            for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
                np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# Satellite 2: scheduler pricing + watchdog seed spread
# ---------------------------------------------------------------------------

class TestSchedulerCascade:
    def test_bucket_cost_defaults_byte_identical(self):
        from lir_tpu.engine import scheduler as sched

        base = sched.bucket_cost(4, 64, 4, 12)
        assert base == 4 * 64 + sched.decode_floor(4, 4, 12)
        assert sched.bucket_cost(4, 64, 4, 12, cascade=False,
                                 trunk_tokens=48) == base
        assert sched.bucket_cost(4, 64, 4, 12, trunk_tokens=48) == base

    def test_bucket_cost_cascade_discount(self):
        from lir_tpu.engine import scheduler as sched

        base = sched.bucket_cost(4, 64, 4, 12)
        disc = sched.bucket_cost(4, 64, 4, 12, cascade=True,
                                 trunk_tokens=32)
        # slots - 1 = 3 trunk prefills deduped
        assert disc == base - 3 * 32
        # the discount composes with cached tokens and clamps at zero
        floor = sched.decode_floor(4, 4, 12)
        assert sched.bucket_cost(4, 64, 4, 12, cached_tokens=4 * 64,
                                 cascade=True, trunk_tokens=64) == floor

    def test_watchdog_seed_cascade_spread(self):
        from lir_tpu.engine import scheduler as sched

        base = sched.watchdog_seed_headroom()
        assert sched.watchdog_seed_headroom(cascade=False) == base
        assert sched.watchdog_seed_headroom(cascade=True) == (
            base * sched.CASCADE_PREFILL_SPREAD)
        # composes with the speculative spread
        spec = sched.watchdog_seed_headroom(spec_decode=True)
        assert sched.watchdog_seed_headroom(
            spec_decode=True, cascade=True) == (
            spec * sched.CASCADE_PREFILL_SPREAD)
        assert sched.CASCADE_PREFILL_SPREAD > 1.0


# ---------------------------------------------------------------------------
# Satellite 3 tail: stats schema mirror + flops analytic
# ---------------------------------------------------------------------------

class TestCascadeStats:
    def test_schema_mirror(self):
        from lir_tpu.observe import registry as reg_mod
        from lir_tpu.utils.profiling import CascadeStats

        declared = set(reg_mod.STATS_SCHEMA["CascadeStats"])
        public = {f.name for f in dataclasses.fields(CascadeStats)
                  if not f.name.startswith("_")}
        assert declared == public

    def test_summary_and_registry(self, cascade_interpret):
        from lir_tpu.observe.registry import engine_registry
        from lir_tpu.utils.profiling import CascadeStats

        s = CascadeStats()
        s.count("cascade_dispatches", 3)
        s.count("dense_fallbacks")
        out = s.summary()
        assert out["cascade_frac"] == 0.75
        eng = _fake_engine()
        reg = engine_registry(eng)
        assert "cascade" in reg.snapshot()["sources"]

    def test_flops_saved_analytic(self):
        from lir_tpu.utils.profiling import cascade_prefill_flops_saved

        cfg = _tiny_cfg(name="cascade-flops")
        assert cascade_prefill_flops_saved(cfg, 1, 64) == 0.0
        assert cascade_prefill_flops_saved(cfg, 4, 0) == 0.0
        saved = cascade_prefill_flops_saved(cfg, 4, 64)
        assert saved > 0
        # 3 deduped rows, linear in (rows - 1)
        assert cascade_prefill_flops_saved(cfg, 7, 64) == 2 * saved
